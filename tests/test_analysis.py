"""shmemlint: static semaphore-protocol analysis (ISSUE 2 acceptance).

The properties pinned here:

* every registered kernel family lints CLEAN on an 8-rank abstract mesh
  (and the analyzer is shape/size-generic: a 3-rank mesh too);
* the seeded broken kernels each produce their expected rule ID with
  rank + site diagnostics — including the ``test_races.py`` caveat (a
  deliberately missing wait the dynamic race detector has MISSED under
  ``dma_execution_mode="on_wait"``): :func:`fixtures.missing_wait` is
  that bug and SL001 flags it statically, forever, on any jax;
* the CLI (``python -m triton_distributed_tpu.analysis.lint``) walks
  the registry and exits nonzero exactly when errors exist.

Everything here is static — no interpreter, no devices, no mesh: these
tests run identically on the 2-vCPU CI runner and a TPU host.
"""

import json

import numpy as np
import pytest

pytestmark = [pytest.mark.analysis, pytest.mark.fast]

from triton_distributed_tpu.analysis import (
    dataflow,
    events,
    fixtures,
    mosaic_compat,
)
from triton_distributed_tpu.analysis.checks import simulate
from triton_distributed_tpu.analysis.findings import (
    RULES,
    SCHEMA_VERSION,
    Severity,
)
from triton_distributed_tpu.analysis.lint import (
    _cross_family_checks,
    analyze_family,
    analyze_spec,
    lint_all,
    lint_family,
    main as lint_main,
)
from triton_distributed_tpu.kernels.registry import families


def _rules(findings):
    return sorted({f.rule for f in findings})


def _analyze_fixture(fx, n=8, site="fixture"):
    spec, in_shapes = fx()
    return analyze_spec(spec, in_shapes(n), n, kernel_name=fx.__name__,
                        site=site)


# ------------------------------------------------------------ registry clean

class TestRegistryClean:
    def test_all_registered_families_lint_clean_mesh8(self):
        """ISSUE acceptance: the full registry on --mesh 8 — protocol
        (SL001-007), delivery contracts (SL008) and wire rails
        (SL009/SL010) — no findings."""
        findings = lint_all(n=8)
        assert findings == [], [f.format() for f in findings]

    def test_all_registered_families_lint_clean_mesh4(self):
        """ISSUE acceptance: same at --mesh 4."""
        findings = lint_all(n=4)
        assert findings == [], [f.format() for f in findings]

    def test_registry_clean_on_odd_mesh(self):
        findings = lint_all(n=3)
        assert findings == [], [f.format() for f in findings]

    def test_every_family_produces_cross_rank_traffic(self):
        """A vacuously-clean analyzer is worthless: every family's
        symbolic execution must record real cross-rank events (puts to
        a different rank and/or remote signals) on every rank —
        except `local`-contract families (the ragged serving kernel),
        which must instead record real LOCAL DMA traffic."""
        for name, fam in families().items():
            rec, _ = analyze_family(fam, 4)
            is_local = (
                fam.contract is not None
                and getattr(fam.contract, "kind", None) == "local"
            )
            for r in range(4):
                if is_local:
                    local = [
                        e for e in rec.traces[r]
                        if isinstance(e, events.PutEvent) and e.local
                    ]
                    assert local, f"{name}: rank {r} recorded no DMAs"
                    continue
                cross = [
                    e for e in rec.traces[r]
                    if (isinstance(e, events.PutEvent) and e.dst_rank != r)
                    or (isinstance(e, events.SignalEvent) and e.target != r)
                ]
                assert cross, f"{name}: rank {r} recorded no remote traffic"

    def test_replay_completes_and_balances(self):
        """The replay simulation itself: the ring allgather completes
        with every semaphore exactly drained."""
        rec, _ = analyze_family(families()["allgather.ring_1d"], 4)
        sim = simulate(rec)
        assert sim.completed
        for k, total in sim.delivered.items():
            assert sim.consumed.get(k, 0) == total, k


# --------------------------------------------------------- seeded fixtures

class TestSeededFixtures:
    def test_missing_wait_flagged_with_rank_and_site(self):
        """The test_races.py caveat, covered forever: the deliberately
        removed wait the dynamic detector missed is SL001 here, naming
        the semaphore, the ranks and the site."""
        rec, findings = _analyze_fixture(fixtures.missing_wait)
        assert "SL001" in _rules(findings)
        f = next(f for f in findings if f.rule == "SL001")
        assert f.severity == Severity.ERROR
        assert f.site == "fixture"
        assert len(f.ranks) > 0
        assert f.sem
        # the unordered landing is also caught as a buffer hazard
        assert "SL004" in _rules(findings)

    def test_credit_imbalance_flagged(self):
        """Signal-1/wait-2 off-by-one → SL002 on every rank, with the
        available-vs-required credit arithmetic in the message."""
        rec, findings = _analyze_fixture(fixtures.credit_imbalance)
        sl2 = [f for f in findings if f.rule == "SL002"]
        assert sl2, _rules(findings)
        assert {r for f in sl2 for r in f.ranks} == set(range(8))
        assert "only 1 are available" in sl2[0].message

    def test_deadlock_cycle_flagged_with_full_chain(self):
        rec, findings = _analyze_fixture(fixtures.deadlock)
        f = next(f for f in findings if f.rule == "SL003")
        assert set(f.ranks) == set(range(8))
        for r in range(8):
            assert f"rank {r}" in f.message

    def test_duplicate_collective_id_flagged(self):
        (sa, ia), (sb, ib) = fixtures.duplicate_collective_id()
        ra, _ = analyze_spec(sa, ia(8), 8, kernel_name="dup_a",
                             site="site_a")
        rb, _ = analyze_spec(sb, ib(8), 8, kernel_name="dup_b",
                             site="site_b")
        findings = _cross_family_checks([ra, rb])
        assert _rules(findings) == ["SL005"]
        assert "45" in findings[0].message

    def test_same_site_engines_may_share_collective_id(self):
        """Engine variants of one op entry share its default id by
        design — no false positive."""
        fams = families()
        recs = [
            analyze_family(fams[n], 4)[0]
            for n in ("allgather.ring_1d", "allgather.ll_small")
        ]
        assert _cross_family_checks(recs) == []

    def test_barrier_sequence_mismatch_flagged(self):
        rec, findings = _analyze_fixture(fixtures.barrier_mismatch)
        f = next(f for f in findings if f.rule == "SL005")
        assert set(f.ranks) == set(range(1, 8))

    def test_undrained_dma_flagged(self):
        rec, findings = _analyze_fixture(fixtures.undrained_dma)
        assert _rules(findings) == ["SL007"]
        assert all("send_sem" in f.sem for f in findings)

    def test_vmem_overcommit_flagged(self):
        rec, findings = _analyze_fixture(fixtures.vmem_overcommit)
        f = next(f for f in findings if f.rule == "SL006")
        assert "big_ref" in f.message


# ---------------------------------------------------- dataflow provenance

def _analyze_df_fixture(fx, n=8):
    spec, in_shapes, contract = fx()
    return analyze_spec(
        spec, in_shapes(n), n, kernel_name=fx.__name__, site="fixture",
        contract=contract,
    )


class TestDataflowProvenance:
    """The symbolic payload-provenance engine itself — guard against a
    vacuously-clean pass."""

    def test_gather_provenance_single_marker_per_source(self):
        """The ring AG's workspace must end with each source's marker on
        exactly its slab, on every rank (not all-zeros, not mixed)."""
        from triton_distributed_tpu.analysis.checks import simulate

        rec, _ = analyze_family(families()["allgather.ring_1d"], 4)
        sim = simulate(rec)
        st = dataflow._State(rec)
        st.seed_inputs()
        dataflow._replay(rec, sim, st)
        for rank in range(4):
            c = st.get(rank, "out_ref")["contrib"]
            for s in range(4):
                slab = c[s * 8:(s + 1) * 8]
                assert (slab == np.int64(1) << (4 * s)).all(), (rank, s)

    def test_reduce_provenance_full_fold_mask(self):
        """gemm_rs's output: every element exactly one contribution per
        rank (the 0x1111 nibble mask at n=4)."""
        from triton_distributed_tpu.analysis.checks import simulate

        rec, _ = analyze_family(families()["gemm_rs.fused"], 4)
        sim = simulate(rec)
        st = dataflow._State(rec)
        st.seed_inputs()
        dataflow._replay(rec, sim, st)
        for rank in range(4):
            assert (st.get(rank, "out_hbm")["contrib"] == 0x1111).all()

    def test_wire_families_record_quant_dequant_events(self):
        """The wire hooks feed the evaluator: AG-side rings record
        dequants, RS-side rings record per-hop quantize + fused
        dequant-accumulate."""
        rec, _ = analyze_family(families()["ag_gemm.fused_fp8w"], 4)
        deq = [e for e in rec.events(events.DequantEvent)]
        assert deq and all(e.add_region is None for e in deq)
        rec, _ = analyze_family(families()["gemm_rs.fused_fp8w"], 4)
        assert any(True for _ in rec.events(events.QuantEvent))
        assert all(
            e.add_region is not None
            for e in rec.events(events.DequantEvent)
        )

    def test_wire_dst_ends_dequantized_never_quantized(self):
        """No registry family may leave raw wire bytes in its contract
        destination (the SL008 wire leg, asserted on the state)."""
        from triton_distributed_tpu.analysis.checks import simulate

        for name in ("ag_gemm.fused_fp8w", "reduce_scatter.ring_fp8w"):
            fam = families()[name]
            rec, _ = analyze_family(fam, 4)
            sim = simulate(rec)
            st = dataflow._State(rec)
            st.seed_inputs()
            dataflow._replay(rec, sim, st)
            dst = dataflow._resolve_dst(rec, fam.contract.dst)
            for rank in range(4):
                wire = st.get(rank, dst)["wire"]
                assert not (wire == dataflow.QUANTIZED).any(), (name, rank)
                assert (wire == dataflow.DEQUANTIZED).any(), (name, rank)


class TestSeededDataflowFixtures:
    """Each data-correctness rule pinned by a deliberately broken kernel
    that is PROTOCOL-CLEAN — the whole point: every semaphore balances
    and SL001-SL007 stay silent, yet the delivered bytes are wrong."""

    def test_skipped_chunk_is_sl008_only(self):
        rec, findings = _analyze_df_fixture(fixtures.skipped_chunk)
        assert _rules(findings) == ["SL008"], [f.format() for f in findings]
        f = next(f for f in findings if "never delivered" in f.message)
        assert f.severity == Severity.ERROR
        assert f.site == "fixture"
        assert len(f.ranks) >= 1
        # every rank is missing a chunk
        assert {fd.ranks[0] for fd in findings
                if "of source rank" in fd.message} == set(range(8))

    def test_dup_chunk_reports_duplicate_and_loss(self):
        rec, findings = _analyze_df_fixture(fixtures.dup_chunk)
        assert _rules(findings) == ["SL008"], [f.format() for f in findings]
        msgs = " | ".join(f.message for f in findings)
        assert "duplicated" in msgs
        assert "never delivered" in msgs
        # the duplicate names both the holder and source rank 0
        f = next(f for f in findings if "duplicated" in f.message)
        assert 0 in f.ranks

    def test_scale_on_payload_sem_is_sl009(self):
        rec, findings = _analyze_df_fixture(fixtures.scale_on_payload_sem)
        assert _rules(findings) == ["SL009"], [f.format() for f in findings]
        f = findings[0]
        assert "payload rail's semaphore" in f.message
        assert f.sem and "recv_sem" in f.sem
        assert len(f.ranks) == 2

    def test_stale_scale_is_sl010(self):
        rec, findings = _analyze_df_fixture(fixtures.stale_scale)
        assert _rules(findings) == ["SL010"], [f.format() for f in findings]
        f = findings[0]
        assert "scale group" in f.message
        assert f.site == "fixture"
        assert len(f.ranks) == 1

    def test_scale_fold_omitted_is_sl009(self):
        """The int8→MXU consumer bug (round 8): rails correctly paired,
        semaphores balanced, but the epilogue never folds the scale —
        the s8×s8 product is stored unrescaled. SL009 with rank + site."""
        rec, findings = _analyze_df_fixture(fixtures.scale_fold_omitted)
        assert _rules(findings) == ["SL009"], [f.format() for f in findings]
        f = findings[0]
        assert "NO scale folded" in f.message
        assert f.site == "fixture"
        assert len(f.ranks) == 1
        # every rank consumes unrescaled — one finding each
        assert {fd.ranks[0] for fd in findings} == set(range(8))

    def test_serialized_ring_is_sl011_with_projection(self):
        """The hop-critical-path feed-in (ROADMAP PR-4 follow-on): a
        protocol-clean, delivery-complete gather whose deepest chain
        rides n hops instead of n-1 — flagged with the perf model's
        projected wall-clock regression in the message."""
        rec, findings = _analyze_df_fixture(fixtures.serialized_ring)
        assert _rules(findings) == ["SL011"], [f.format() for f in findings]
        f = findings[0]
        assert "8 remote hops" in f.message and "ring-optimal <= 7" in f.message
        assert "ms critical path" in f.message
        assert f.site == "fixture"

    def test_epilogue_consume_families_flow(self):
        """The int8→MXU registry families record epilogue DequantEvents
        (q + scale regions, no dst copy) and their contract destination
        — the WIRE workspace itself — ends fully consumed: every
        arrival flipped to DEQUANTIZED by the epilogue fold, never raw."""
        from triton_distributed_tpu.analysis.checks import simulate

        for name in ("ag_gemm.fused_int8mxw",
                     "moe_tp.ag_group_gemm_int8mxw"):
            fam = families()[name]
            rec, findings = analyze_family(fam, 4)
            assert findings == [], [f.format() for f in findings]
            eps = [e for e in rec.events(events.DequantEvent) if e.epilogue]
            assert eps and all(e.s_region is not None for e in eps), name
            sim = simulate(rec)
            st = dataflow._State(rec)
            st.seed_inputs()
            dataflow._replay(rec, sim, st)
            dst = dataflow._resolve_dst(rec, fam.contract.dst)
            for rank in range(4):
                wire = st.get(rank, dst)["wire"]
                assert not (wire == dataflow.QUANTIZED).any(), (name, rank)
                assert (wire == dataflow.DEQUANTIZED).any(), (name, rank)

    def test_hop_histogram_ring_depth(self):
        """The per-element hop counters behind SL011: a clean 4-rank AG
        ring tops out at exactly n-1 = 3 hops."""
        from triton_distributed_tpu.analysis.checks import simulate

        fam = families()["allgather.ring_1d"]
        rec, _ = analyze_family(fam, 4)
        sim = simulate(rec)
        st = dataflow._State(rec)
        st.seed_inputs()
        dataflow._replay(rec, sim, st)
        hist = dataflow.hop_histogram(
            rec, st, dataflow._resolve_dst(rec, fam.contract.dst)
        )
        assert max(hist) == 3
        assert dataflow._check_hop_depth(rec, st, fam.contract) == []

    def test_contract_on_unknown_ref_is_loud(self):
        spec, in_shapes, _ = fixtures.skipped_chunk()
        with pytest.raises(KeyError, match="no_such_buffer"):
            analyze_spec(
                spec, in_shapes(4), 4, kernel_name="fx", site="fixture",
                contract=dataflow.DeliveryContract(
                    kind="gather", dst="no_such_buffer"
                ),
            )


# ------------------------------------------------------ mosaic pre-flight

class TestMosaicCompat:
    def test_registry_preflight_clean(self):
        """ISSUE acceptance: every family passes MC001-MC003 — scanned
        under the hardware build config, or refusing cleanly under the
        pinned-fp8 wire contract (the contract fires before Mosaic
        would)."""
        findings, report = mosaic_compat.preflight_all(n=4)
        assert findings == [], [f.format() for f in findings]
        assert set(report["scanned"]) | set(report["refused"]) == set(
            families()
        )
        # the fp8-pinned wire twins are exactly the clean refusals
        assert all("fp8w" in name for name in report["refused"])
        assert report["refused"], "no family exercised the wire contract"

    def test_preflight_is_seconds_fast(self):
        """The pre-flight must stay tier-1-cheap (< 60 s is the
        acceptance bound; warm it runs in single-digit seconds)."""
        import time

        t0 = time.time()
        mosaic_compat.preflight_all(n=4, kernels=["allgather"])
        assert time.time() - t0 < 60

    def test_f8_cast_fixture_flagged(self):
        spec, in_shapes = fixtures.f8_inkernel_cast()
        f = mosaic_compat.preflight_spec(
            spec, in_shapes(4), 4, kernel_name="fx_f8", site="fixture"
        )
        assert _rules(f) == ["MC001"]
        assert "16-bit to 32-bit" in f[0].message

    def test_scalar_shape_cast_fixture_flagged(self):
        spec, in_shapes = fixtures.scalar_shape_cast()
        f = mosaic_compat.preflight_spec(
            spec, in_shapes(4), 4, kernel_name="fx_sc", site="fixture"
        )
        assert _rules(f) == ["MC002"]

    def test_subbyte_broadcast_fixture_flagged(self):
        spec, in_shapes = fixtures.subbyte_broadcast()
        f = mosaic_compat.preflight_spec(
            spec, in_shapes(4), 4, kernel_name="fx_sb", site="fixture"
        )
        assert _rules(f) == ["MC003"]

    def test_dynamic_gather_fixture_flagged(self):
        """MC006: jnp.take over a TRACED index vector — the anc[par]
        index chase the ragged kernel's static ancestor-bitmask unroll
        exists to avoid — is denied; the registry preflight above
        proves the real kernels never produce it."""
        spec, in_shapes = fixtures.dynamic_gather()
        f = mosaic_compat.preflight_spec(
            spec, in_shapes(4), 4, kernel_name="fx_dg", site="fixture"
        )
        assert _rules(f) == ["MC006"]
        assert "traced indices" in f[0].message

    def test_sublane_dynamic_slice_fixture_flagged(self):
        """MC007 (the nightly-slow-run signature promoted to a static
        rule): lax.dynamic_slice with a TRACED start index on the
        sublane (second-minor) dim — this Mosaic only folds constant
        sublane offsets, so the 8-minute AOT refusal becomes a
        2-second lint finding."""
        spec, in_shapes = fixtures.sublane_dynamic_slice()
        f = mosaic_compat.preflight_spec(
            spec, in_shapes(4), 4, kernel_name="fx_sds", site="fixture"
        )
        assert _rules(f) == ["MC007"]
        assert "sublane" in f[0].message

    def test_fp8_wire_family_flags_mc001_when_forced(self, monkeypatch):
        """The KNOWN f8-cast construct, on a real registry family: with
        the toolchain override asserting in-kernel f8 support, the fp8
        wire twin builds — and the pre-flight still flags the extf cast
        this Mosaic rejects (the finding the 8-minute AOT suite would
        otherwise be the first to see)."""
        monkeypatch.setenv("TDTPU_WIRE_FP8_INKERNEL", "1")
        status, f = mosaic_compat.preflight_family(
            families()["ag_gemm.fused_fp8w"], 4
        )
        assert status == "scanned"
        assert "MC001" in _rules(f)

    def test_clean_kernels_not_flagged(self):
        """int8 widening and the (1, 128) scale-row idiom must NOT trip
        the scan — the non-wire and int8-capable families are clean."""
        status, f = mosaic_compat.preflight_family(
            families()["gemm_rs.fused"], 4
        )
        assert status == "scanned" and f == []

    def test_mosaic_cli(self):
        assert mosaic_compat.main(
            ["--mesh", "4", "--kernel", "allgather.ring_1d"]
        ) == 0


# ------------------------------------------------------------------ the CLI

class TestCLI:
    def test_cli_clean_registry_exits_zero(self, capsys):
        assert lint_main(["--mesh", "4"]) == 0
        err = capsys.readouterr().err
        assert "0 error(s)" in err

    def test_cli_kernel_filter_and_json(self, capsys):
        assert lint_main(["--mesh", "4", "--kernel", "allgather",
                          "--json"]) == 0

    def test_cli_json_schema_version_and_rule_counts(self, capsys):
        """Satellite contract: --json emits a schema_version header and
        a per-rule-count summary (machine-readable, all rules present
        with zeros)."""
        assert lint_main(["--mesh", "4", "--kernel", "allgather.ring_1d",
                          "--json"]) == 0
        lines = [json.loads(l) for l in
                 capsys.readouterr().out.strip().splitlines()]
        assert lines[0]["schema_version"] == SCHEMA_VERSION
        assert "allgather.ring_1d" in lines[0]["families"]
        assert set(lines[-1]["rule_counts"]) == set(RULES)
        assert lines[-1]["errors"] == 0

    def test_cli_mosaic_flag(self, capsys):
        assert lint_main(["--mesh", "4", "--kernel", "allgather.ring_1d",
                          "--mosaic"]) == 0
        assert "mosaic-compat" in capsys.readouterr().err

    def test_cli_rejects_trivial_mesh(self):
        with pytest.raises(SystemExit):
            lint_main(["--mesh", "1"])

    def test_cli_list(self, capsys):
        assert lint_main(["--list"]) == 0
        out = capsys.readouterr().out
        for name in families():
            assert name in out

    def test_allow_demotes_severity(self):
        spec, in_shapes = fixtures.vmem_overcommit()
        _, findings = analyze_spec(spec, in_shapes(4), 4,
                                   kernel_name="fx", site="fixture")
        from triton_distributed_tpu.analysis.lint import _apply_allow

        demoted = _apply_allow(findings, {"SL006"})
        assert all(f.severity == Severity.INFO for f in demoted
                   if f.rule == "SL006")


# --------------------------------------------------------------- event model

class TestEventModel:
    def test_rule_catalog_is_stable(self):
        """Rule ids are load-bearing (docs, suppressions, this file):
        removing or renumbering one is a breaking change."""
        assert set(RULES) == {
            "SL001", "SL002", "SL003", "SL004", "SL005", "SL006", "SL007",
            "SL008", "SL009", "SL010", "SL011", "SL012", "SL013",
            "MC001", "MC002", "MC003", "MC004", "MC005", "MC006",
            "MC007",
            "SV001", "SV002", "SV003", "SV004", "SV005", "SV006",
            "SV007",
        }

    def test_ring_trace_targets_right_neighbor(self):
        rec, _ = analyze_family(families()["allgather.ring_1d"], 4)
        for r in range(4):
            puts = [e for e in rec.traces[r]
                    if isinstance(e, events.PutEvent) and not e.local]
            assert puts and all(p.dst_rank == (r + 1) % 4 for p in puts)

    def test_region_overlap_semantics(self):
        a = events.Region("buf", (0, 0), (8, 128))
        b = events.Region("buf", (7, 0), (9, 128))
        c = events.Region("buf", (8, 0), (16, 128))
        d = events.Region("other", (0, 0), (8, 128))
        assert a.overlaps(b) and b.overlaps(c)
        assert not a.overlaps(c) and not a.overlaps(d)

    def test_lint_family_by_name(self):
        assert lint_family("gemm_rs.fused", n=4) == []
        with pytest.raises(KeyError):
            lint_family("no_such_kernel")


# --------------------------------------------------- quantized-wire bytes

#: bytes per element of each ring buffer the wire kernels ship, keyed by
#: the kernel parameter name the Region's root ref carries (the base
#: families move f32 lint payloads; the _fp8w twins move 1-byte slabs
#: plus f32 scale planes).
_REF_ITEMSIZE = {
    "x_hbm": 4, "ag_hbm": 4, "a_hbm": 4, "w0": 4, "w1": 4,
    "xs_hbm": 4, "y_hbm": 4,
    "xq_hbm": 1, "agq_hbm": 1, "wq0": 1, "wq1": 1, "xsc_hbm": 4,
    "xs_ref": 4, "xq_ref": 1, "x_ref": 4, "out_ref": 4,
    "outq_ref": 1, "outs_ref": 4, "qbuf_ref": 1, "sbuf_ref": 4,
    "ws0": 4, "ws1": 4, "ags_hbm": 4, "acc_ref": 4,
}


def _remote_put_bytes(rec, rank=0):
    """Total bytes rank ``rank`` RDMAs to peers in one symbolic run."""
    total = 0
    for e in rec.traces[rank]:
        if isinstance(e, events.PutEvent) and not e.local:
            r = e.src_region
            elems = 1
            for lo, hi in zip(r.lo, r.hi):
                elems *= hi - lo
            total += elems * _REF_ITEMSIZE[r.ref]
    return total


class TestWirePayloadBytes:
    """ISSUE 3 acceptance: shmemlint symbolically models the COMPRESSED
    payload byte counts — the _fp8w twins' recorded RDMA traffic is the
    lang.wire layout (1-byte payload + per-chunk f32 scale plane), not
    the raw-slab byte count, and the scale rail's semaphore protocol is
    part of the replayed trace."""

    @pytest.mark.parametrize(
        "base,wire", [
            ("ag_gemm.fused", "ag_gemm.fused_fp8w"),
            ("gemm_rs.fused", "gemm_rs.fused_fp8w"),
            ("moe_tp.ag_group_gemm", "moe_tp.ag_group_gemm_fp8w"),
            ("moe_tp.reduce_rs", "moe_tp.reduce_rs_fp8w"),
        ],
    )
    def test_wire_variant_ships_fewer_bytes(self, base, wire):
        fams = families()
        rec_b, f_b = analyze_family(fams[base], 4)
        rec_w, f_w = analyze_family(fams[wire], 4)
        assert f_b == [] and f_w == [], (
            [f.format() for f in f_b + f_w]
        )
        b_bytes = _remote_put_bytes(rec_b)
        w_bytes = _remote_put_bytes(rec_w)
        # lint payloads are f32 → the 1-byte wire + scale planes must
        # come in well under half (the bf16 acceptance ratio is 1.8×;
        # on f32 lint slabs the same layout gives ≥ 2×)
        assert w_bytes * 2 <= b_bytes, (base, b_bytes, wire, w_bytes)

    @pytest.mark.parametrize(
        "wire,rows,cols", [
            # standalone rings carry PER-ROW scale planes at wider lint
            # columns (their entries gate on cols·itemsize > cols+512)
            ("allgather.ring_1d_fp8w", 8, 2048),
            ("reduce_scatter.ring_fp8w", 8, 2048),
        ],
    )
    def test_standalone_wire_under_raw_bytes(self, wire, rows, cols):
        rec, f = analyze_family(families()[wire], 4)
        assert f == [], [x.format() for x in f]
        w_bytes = _remote_put_bytes(rec)
        raw = 3 * rows * cols * 4          # n-1 = 3 hops of the f32 slab
        expect = 3 * (rows * cols + rows * 128 * 4)   # 1-byte + scales
        assert w_bytes == expect
        assert w_bytes * 2 <= raw

    def test_rs_stream_wire_under_raw_bytes(self):
        """The HBM-streaming RS wire (round 8): per-hop quantized ring
        slabs + per-chunk scale planes, well under half the raw f32
        ring traffic the base streaming family ships."""
        rec_b, f_b = analyze_family(families()["reduce_scatter.stream"], 4)
        rec_w, f_w = analyze_family(
            families()["reduce_scatter.stream_int8w"], 4
        )
        assert f_b == [] and f_w == [], (
            [x.format() for x in f_b + f_w]
        )
        w = _remote_put_bytes(rec_w)
        # lint geometry differs (128 vs 2048 cols) — compare per-element
        b_per = _remote_put_bytes(rec_b) / (3 * 8 * 128)
        w_per = w / (3 * 8 * 2048)
        assert w_per * 2 <= b_per, (b_per, w_per)

    def test_int8_mxu_wire_ships_compressed_and_never_dequantizes(self):
        """The dequant-free consumer's traffic is the int8 wire layout
        (identical rails to the dequant twin) — the difference is all on
        the consume side, checked by the epilogue-event tests above."""
        rec_b, _ = analyze_family(families()["ag_gemm.fused"], 4)
        rec_w, f_w = analyze_family(families()["ag_gemm.fused_int8mxw"], 4)
        assert f_w == [], [x.format() for x in f_w]
        assert _remote_put_bytes(rec_w) * 2 <= _remote_put_bytes(rec_b)

    def test_ag_gemm_wire_bytes_match_the_layout_exactly(self):
        from triton_distributed_tpu.lang import wire as wirelib

        rec, _ = analyze_family(families()["ag_gemm.fused_fp8w"], 4)
        fmt = wirelib.make_wire_format("fp8", 16)
        # n-1 = 3 forwards of one 16×128 slab + its scale plane
        assert _remote_put_bytes(rec) == 3 * fmt.slab_bytes(16, 128)

    def test_wire_ring_has_a_scale_rail(self):
        """Every payload RDMA is paired with a scale-plane RDMA (the
        protocol shmemlint replays covers both rails)."""
        rec, _ = analyze_family(families()["ag_gemm.fused_fp8w"], 4)
        puts = [
            e for e in rec.traces[0]
            if isinstance(e, events.PutEvent) and not e.local
        ]
        payload = [p for p in puts if p.src_region.ref in ("xq_hbm", "agq_hbm")]
        scales = [p for p in puts if p.src_region.ref in ("xs_hbm", "ags_hbm")]
        assert len(payload) == len(scales) == 3


# ------------------------------------------------- KV-ship family

class TestKVShipFamily:
    """The `kv_ship.pages` family (ISSUE 7): the disaggregated-serving
    page transport — a PAIRWISE permute contract (src_only pins the
    role topology) with dual payload/scale DMA rails, and its two
    seeded fixtures."""

    def test_family_lints_clean_both_meshes(self):
        for n in (4, 8):
            findings = lint_family("kv_ship.pages", n=n)
            assert findings == [], [f.format() for f in findings]

    def test_family_is_preflighted(self):
        status, f = mosaic_compat.preflight_family(
            families()["kv_ship.pages"], 8
        )
        assert status == "scanned" and f == []

    def test_pages_land_from_exactly_the_partner(self):
        """Provenance: every rank's landing buffer holds its partner
        rank's marker on every element — nobody else's, no holes, and
        the landed bytes end DEQUANTIZED (installed with their scale
        planes), never raw."""
        from triton_distributed_tpu.analysis.checks import simulate

        fam = families()["kv_ship.pages"]
        rec, findings = analyze_family(fam, 4)
        assert findings == [], [f.format() for f in findings]
        sim = simulate(rec)
        st = dataflow._State(rec)
        st.seed_inputs()
        dataflow._replay(rec, sim, st)
        for rank in range(4):
            s = st.get(rank, "dst_q")
            partner = (rank - 2) % 4
            marker = np.int64(1) << (4 * partner)
            assert (s["contrib"] == marker).all(), rank
            assert not (s["wire"] == dataflow.QUANTIZED).any(), rank
        # the install edges are consume-with-scale epilogue events
        eps = [e for e in rec.events(events.DequantEvent) if e.epilogue]
        assert eps and all(e.s_region is not None for e in eps)

    def test_skipped_page_fixture_is_sl008(self):
        rec, findings = _analyze_df_fixture(fixtures.kv_ship_skipped_page)
        assert _rules(findings) == ["SL008"], [f.format() for f in findings]
        msgs = " | ".join(f.message for f in findings)
        assert "chunk missing" in msgs and "hole" in msgs
        assert all(f.severity == Severity.ERROR for f in findings)
        # every rank is short exactly its partner's page
        short = {f.ranks[0] for f in findings if "of source rank" in f.message}
        assert short == set(range(8))

    def test_unpaired_scale_fixture_is_sl009(self):
        rec, findings = _analyze_df_fixture(fixtures.kv_ship_unpaired_scale)
        assert _rules(findings) == ["SL009"], [f.format() for f in findings]
        msgs = " | ".join(f.message for f in findings)
        assert "no paired scale-plane RDMA" in msgs
        assert "NO scale folded" in msgs

    def test_src_only_flags_stray_sources(self):
        """The src_only extension itself: a delivery from OUTSIDE the
        declared sender set is flagged even when its byte count looks
        plausible — want is 0 for non-partners."""
        from triton_distributed_tpu.analysis.dataflow import (
            DeliveryContract,
        )

        spec, in_shapes, _ = fixtures.skipped_chunk()
        _, findings = analyze_spec(
            spec, in_shapes(4), 4, kernel_name="stray", site="fixture",
            contract=DeliveryContract(
                kind="gather", dst="out_ref",
                src_only=lambda rank, n: {rank},   # only own writes legal
            ),
        )
        dup = [f for f in findings if "duplicated" in f.message]
        assert dup, [f.format() for f in findings]


# ----------------------------------------------- ragged serving family

class TestRaggedFamily:
    """The `flash_decode.ragged_paged` family (ISSUE 6): a LOCAL
    grid kernel analyzed per grid point, its `local` delivery contract,
    and the MC005 lane-reshape deny rule its packing exists to avoid."""

    def test_family_lints_clean_both_meshes(self):
        for n in (4, 8):
            findings = lint_family("flash_decode.ragged_paged", n=n)
            assert findings == [], [f.format() for f in findings]

    def test_family_is_preflighted(self):
        from triton_distributed_tpu.analysis import mosaic_compat

        status, f = mosaic_compat.preflight_family(
            families()["flash_decode.ragged_paged"], 4
        )
        assert status == "scanned" and f == []

    def test_grid_walk_runs_every_row(self):
        """The symbolic evaluator executes one kernel run PER GRID
        POINT: both rows' out spans carry write events (a single-
        invocation evaluation would leave row 1's span untouched and
        the contract pass blind to it)."""
        rec, _ = analyze_family(families()["flash_decode.ragged_paged"], 4)
        writes = [
            e.dst_region for e in rec.traces[0]
            if isinstance(e, events.PutEvent) and e.local
            and e.dst_region.ref == "ref10"
        ]
        starts = sorted(r.lo[1] for r in writes)
        assert starts == [0, 8]            # one out-DMA per packed row

    def test_tree_sibling_fixture_is_sl008(self):
        """Seeded masked-coverage true-positive: a TREE row whose
        ancestry bitmask smuggles a SIBLING-branch bit (anc not closed
        under the parent pointers) — balanced semaphores, full byte
        coverage; only the contract's topology facet can reject it."""
        spec, in_shapes, contract, init = fixtures.ragged_tree_sibling()
        _, findings = analyze_spec(
            spec, in_shapes(4), 4, kernel_name="ragged_tree_sibling",
            site="fixture", contract=contract, init=init,
        )
        sib = [f for f in findings if f.rule == "SL008"]
        assert sib, [f.format() for f in findings]
        assert all("sibling" in f.message for f in sib)
        assert all(f.severity == Severity.ERROR for f in sib)

    def test_topo_meta_inferred_both_meshes(self):
        """The masked-coverage facet is INFERRED, not just declared:
        contract inference detects the topology operand from the
        scalar-prefetch profile at mesh 4 AND 8, agrees with the
        declared facet (no SL012), and carries the width."""
        from triton_distributed_tpu.analysis import contract_infer

        for n in (4, 8):
            res = contract_infer.infer_family(
                families()["flash_decode.ragged_paged"], n)
            assert res.findings == [], [f.format() for f in res.findings]
            assert res.contract.topo == {
                "ref": 4, "kv_lens": 1, "q_lens": 2, "width": 8}

    def test_ragged_hole_fixture_is_sl008(self):
        spec, in_shapes, contract = fixtures.ragged_hole()
        _, findings = analyze_spec(
            spec, in_shapes(4), 4, kernel_name="ragged_hole",
            site="fixture", contract=contract,
        )
        holes = [f for f in findings if f.rule == "SL008"]
        assert holes and all("hole" in f.message for f in holes)
        assert all(f.severity == Severity.ERROR for f in holes)

    def test_lane_reshape_fixture_is_mc005(self):
        from triton_distributed_tpu.analysis import mosaic_compat

        spec, in_shapes = fixtures.lane_reshape()
        f = mosaic_compat.preflight_spec(
            spec, in_shapes(8), 8, kernel_name="fixture_lane_reshape"
        )
        assert [x.rule for x in f] == ["MC005"]
        assert "lane" in f[0].message

    def test_unit_collapse_reshape_not_flagged(self):
        """The supported reshape form — unit dims dropped, lane dim
        kept — must pass MC005 (the existing kernels' idiom)."""
        from triton_distributed_tpu.analysis import mosaic_compat
        from triton_distributed_tpu.analysis.fixtures import _spec

        def kernel(x_ref, out_ref):
            import jax.numpy as jnp

            out_ref[...] = jnp.reshape(x_ref[...], (8, 128))  # (1,8,128)

        f = mosaic_compat.preflight_spec(
            _spec(kernel, "fixture_unit_collapse",
                  out_shapes=[((8, 128), np.dtype(np.float32))]),
            [((1, 8, 128), np.dtype(np.float32))], 8,
            kernel_name="unit_collapse",
        )
        assert [x.rule for x in f] == []


# -------------------------------------------- grid-schedule mutations

class TestGridScheduleMutations:
    """The PR-15 grid-schedule legality gate, pinned through its
    mutation fixtures: each is the REAL production builder under a
    mutated :class:`GridSchedule`, and each must land on its exact rule
    ID — the shapes of wrongness the grid enumerator's oracle exists to
    reject (a gate that cannot reject is not a gate)."""

    def test_overwide_block_q_is_sl008(self):
        """block_q=32 past the 16-token parking cap: the q-window and
        out-DMA overrun the zero-slack gate buffer — OOB + coverage
        SL008, nothing else (the protocol pass is blind to it)."""
        rec, findings = _analyze_df_fixture(
            fixtures.grid_ragged_overwide_block)
        assert _rules(findings) == ["SL008"], [f.format() for f in findings]
        assert all(f.severity == Severity.ERROR for f in findings)

    def test_coalesced_drop_rail_is_sl009(self):
        """coalesce=2 ticks shipping payload-only: every page lands at
        its slot but no scale plane accompanies it and the install has
        no fold — exactly SL009 (contract=None keeps the permute pass's
        SL008 for the missing scale deliveries out of the pin)."""
        rec, findings = _analyze_df_fixture(
            fixtures.grid_kv_ship_dropped_scale)
        assert _rules(findings) == ["SL009"], [f.format() for f in findings]
        msgs = " | ".join(f.message for f in findings)
        assert "scale" in msgs

    def test_gemm_rs_shared_rail_is_sl009(self):
        """rail='shared' on the int8-MXU fused GEMM-RS: scale arrivals
        signal the payload's recv semaphore — credits balance, only the
        rail-pairing replay can reject it."""
        rec, findings = _analyze_df_fixture(
            fixtures.grid_gemm_rs_shared_rail)
        assert _rules(findings) == ["SL009"], [f.format() for f in findings]

    def test_grid_families_lint_clean_default(self):
        """The other half of the oracle pin: the DEFAULT grid schedule
        gates clean for all three families at mesh 4 AND 8 (the
        candidate production actually runs must never be rejected)."""
        from triton_distributed_tpu.tune.schedule import (
            GRID_DEFAULT,
            check_schedule,
            grid_families,
        )

        for fam in grid_families():
            for n in (4, 8):
                findings = check_schedule(fam, GRID_DEFAULT, n)
                assert findings == [], (
                    fam, n, [f.format() for f in findings])


# -------------------------------------- CP + grad-ring train families

class TestCPTrainFamilies:
    """The training subsystem's lint families (ISSUE 14): the
    context-parallel attention rings (``cp.ring_attention`` KV
    rotation, ``cp.ulysses`` a2a) and the quantized gradient ring
    (``grad_ring.stream_int8w``), plus their seeded schedule-mutation
    fixtures."""

    FAMILIES = (
        "cp.ring_attention", "cp.ulysses", "grad_ring.stream_int8w",
    )

    def test_families_lint_clean_both_meshes(self):
        for name in self.FAMILIES:
            for n in (4, 8):
                findings = lint_family(name, n=n)
                assert findings == [], (name, [f.format() for f in findings])

    def test_families_are_preflighted(self):
        for name in self.FAMILIES:
            status, f = mosaic_compat.preflight_family(families()[name], 8)
            assert status == "scanned" and f == [], (name, f)

    def test_families_have_degradation_targets(self):
        from triton_distributed_tpu.kernels.registry import (
            missing_degradation_targets,
        )

        missing = {f.name for f in missing_degradation_targets()}
        assert not (missing & set(self.FAMILIES))

    def test_skipped_block_fixture_is_sl008(self):
        rec, findings = _analyze_df_fixture(fixtures.cp_ring_skipped_block)
        assert _rules(findings) == ["SL008"], [f.format() for f in findings]
        assert all(f.severity == Severity.ERROR for f in findings)

    def test_unpaired_scale_fixture_is_sl009(self):
        rec, findings = _analyze_df_fixture(fixtures.grad_ring_unpaired_scale)
        assert _rules(findings) == ["SL009"], [f.format() for f in findings]


# ------------------------------------------------- contract inference (17)

def _infer_fixture(fx, n=8):
    """Run one 4-tuple contract fixture (spec, in_shapes, declared,
    degrades_to) through the inference diff."""
    from triton_distributed_tpu.analysis import abstract, contract_infer

    spec, in_shapes, declared, twin = fx()
    rec = abstract.run_symbolic(
        spec, in_shapes(n), n, kernel_name=fx.__name__, site="fixture")
    return rec, contract_infer.infer_spec(
        rec, degrades_to=twin, declared=declared)


class TestContractInference:
    """ISSUE 17 tentpole: SL008 obligations derived from the XLA twin
    + replay provenance, hand-written contracts demoted to assertions.
    """

    def test_registry_complete_targets_and_contracts(self):
        """Satellite: every registered family resolves its degrades_to
        dotted path AND carries a declared-or-inferred delivery
        contract — the `bench.py --lint` silent-gap check, promoted to
        tier-1."""
        from triton_distributed_tpu.analysis import contract_infer
        from triton_distributed_tpu.kernels.registry import (
            resolve_degradation_target,
        )

        for name, fam in sorted(families().items()):
            assert fam.degrades_to, f"{name}: no degradation target"
            assert resolve_degradation_target(fam.degrades_to) is not None
            contract = fam.contract
            if contract is None:
                contract = contract_infer.infer_family(fam, 4).contract
            assert contract is not None, (
                f"{name}: neither a declared nor an inferable contract")

    @pytest.mark.parametrize("n", [4, 8])
    def test_inferred_agrees_with_declared_whole_registry(self, n):
        """Acceptance: inferred contracts agree with declared ones for
        ALL registered families at mesh 4 and 8 — no silent allow. Any
        SL012/SL013 here is either a real contract bug or twin drift;
        fix the declaration (or the kernel), don't relax this test."""
        findings = lint_all(n=n, infer_contracts=True)
        assert findings == [], [f.format() for f in findings]

    def test_twins_actually_execute(self):
        """The verdicts above must come from EXECUTED twins (conftest
        provides 8 host devices), not the static class table — a tabled
        profile can't measure payloads."""
        from triton_distributed_tpu.analysis import contract_infer

        for name in ("allgather.ring_1d", "reduce_scatter.ring",
                     "all_to_all.dense", "kv_ship.pages",
                     "flash_decode.ragged_paged", "moe_tp.reduce_rs",
                     "grad_ring.stream_int8w", "cp.ring_attention"):
            res = contract_infer.infer_family(families()[name], 4)
            assert res.profile.executed, (name, res.profile.detail)

    def test_sl012_on_declared_gather_that_reduces(self):
        """Seeded true-positive: the REAL reduce-scatter ring declared
        `kind='gather'`. The twin delivers class 'fold'; the kind-class
        diff names the declaration as the bug."""
        _, res = _infer_fixture(fixtures.contract_declares_gather_actually_reduces)
        assert "SL012" in _rules(res.findings), (
            [f.format() for f in res.findings])
        f = next(f for f in res.findings if f.rule == "SL012")
        assert "class 'fold'" in f.message and "gather" in f.message
        assert f.severity == Severity.ERROR

    def test_sl012_on_overdeclared_payload(self):
        """Seeded true-positive: the REAL AG ring declaring twice the
        per-source payload the kernel lands. Kind and dst are right —
        only the measured modal payload can catch it."""
        _, res = _infer_fixture(fixtures.contract_overdeclared_payload)
        rules = [f.rule for f in res.findings]
        assert rules == ["SL012"], [f.format() for f in res.findings]
        assert "over-declares" in res.findings[0].message
        assert "2048" in res.findings[0].message
        assert "1024" in res.findings[0].message

    def test_sl013_on_undeclared_contract_and_sl008_still_bites(self):
        """Acceptance: a family with contract=None draws SL013, AND the
        inferred contract keeps SL008 live — the skipped-chunk schedule
        mutation (a real AG ring one source short) is still caught with
        no declaration anywhere in sight."""
        from triton_distributed_tpu.analysis import (
            abstract,
            checks,
            contract_infer,
        )

        spec, in_shapes, _declared = fixtures.schedule_skipped_chunk()
        rec = abstract.run_symbolic(
            spec, in_shapes(8), 8, kernel_name="fx_skip", site="fixture")
        res = contract_infer.infer_spec(
            rec, degrades_to="jax.lax.all_gather", declared=None)
        assert _rules(res.findings) == ["SL013"]
        assert res.findings[0].severity == Severity.WARNING
        # the twin pins src_only=None (all sources) — the kernel's own
        # skip cannot launder itself into the inferred topology
        assert res.contract is not None and res.contract.src_only is None
        findings = checks.check_family(
            rec, contract=None, fallback_contract=res.contract)
        assert "SL008" in _rules(findings), [f.format() for f in findings]
        assert any("chunk missing" in f.message for f in findings
                   if f.rule == "SL008")

    def test_sl013_clean_family_passes_sl008_via_inferred(self):
        """The SL013 path on a CORRECT kernel: stripping a clean
        family's declaration yields exactly the warning — the inferred
        contract runs SL008 and it passes."""
        import dataclasses

        fam = dataclasses.replace(
            families()["allgather.ring_1d"], contract=None)
        _, findings = analyze_family(fam, 4, infer_contracts=True)
        assert _rules(findings) == ["SL013"], (
            [f.format() for f in findings])

    def test_inference_is_opt_in(self):
        """Without infer_contracts, a contract=None family draws no
        SL013 and no SL008 — exactly the pre-existing silent gap this
        subsystem exists to surface (pinned so the default path stays
        byte-identical for downstream consumers)."""
        import dataclasses

        fam = dataclasses.replace(
            families()["allgather.ring_1d"], contract=None)
        _, findings = analyze_family(fam, 4)
        assert findings == []

    def test_strict_registration_gate(self):
        """TDTPU_LINT_STRICT=1 re-verifies declared contracts at
        registration (memoized one-shot) — the current registry must
        pass it."""
        import os
        from triton_distributed_tpu.kernels import registry

        old = os.environ.get("TDTPU_LINT_STRICT")
        saved = registry._STRICT_VERIFIED
        registry._STRICT_VERIFIED = None
        os.environ["TDTPU_LINT_STRICT"] = "1"
        try:
            fams = registry.families()
            assert len(fams) >= 27
            assert registry._STRICT_VERIFIED is True
        finally:
            registry._STRICT_VERIFIED = saved
            if old is None:
                os.environ.pop("TDTPU_LINT_STRICT", None)
            else:
                os.environ["TDTPU_LINT_STRICT"] = old

    def test_cli_infer_contracts_flag(self, capsys):
        assert lint_main(["--mesh", "4", "--kernel", "allgather.ring_1d",
                          "--infer-contracts"]) == 0
        assert "0 error(s)" in capsys.readouterr().err


# ------------------------------------------------------- docs coverage (17)

class TestLintDocs:
    def test_every_emitted_code_is_documented(self):
        """Satellite: grep every finding code emitted anywhere under
        analysis/ (plus the full RULES catalog) and fail on any code
        docs/LINT.md does not carry a table row for."""
        import pathlib
        import re

        repo = pathlib.Path(__file__).resolve().parents[1]
        analysis_dir = (repo / "triton_distributed_tpu" / "analysis")
        emitted = set(RULES)
        pat = re.compile(r'["\'](SL\d{3}|MC\d{3}|SV\d{3})["\']')
        for py in analysis_dir.glob("*.py"):
            emitted |= set(pat.findall(py.read_text()))
        doc = (repo / "docs" / "LINT.md").read_text()
        documented = {
            m.group(1)
            for m in re.finditer(r"^\|\s*(SL\d{3}|MC\d{3}|SV\d{3})\s*\|",
                                 doc, re.MULTILINE)
        }
        undocumented = emitted - documented
        assert not undocumented, (
            f"finding codes emitted in analysis/ but missing a "
            f"docs/LINT.md table row: {sorted(undocumented)}")
        # and the table must not document codes the catalog disowns
        phantom = documented - set(RULES)
        assert not phantom, (
            f"docs/LINT.md documents codes not in the RULES catalog: "
            f"{sorted(phantom)}")
