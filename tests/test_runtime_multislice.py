"""Multi-slice mesh construction + model presets.

≡ the reference's CommScope intra/inter-node split
(DistributedAttrDefs.td:45-53) — on TPU the split is ICI vs DCN; single
slice must degenerate cleanly (nnodes==1 specialization, SURVEY.md §4).
"""

import jax
import pytest
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.models import Transformer, presets
from triton_distributed_tpu.runtime import (
    create_hybrid_mesh,
    is_dcn_axis,
    num_slices,
)

#: tier-1 fast subset (ci/fast.sh): mesh construction, no kernels
pytestmark = pytest.mark.fast


class TestHybridMesh:
    def test_single_slice_degenerates(self):
        assert num_slices() == 1
        mesh = create_hybrid_mesh((2, 4))
        assert mesh.axis_names == ("dcn", "dp", "tp")
        assert mesh.shape["dcn"] == 1
        assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4

    def test_no_axis_is_dcn_on_host(self):
        mesh = create_hybrid_mesh((2, 4))
        for ax in mesh.axis_names:
            assert not is_dcn_axis(mesh, ax)

    def test_model_trains_on_hybrid_mesh(self):
        """The flagship model runs unchanged on a hybrid mesh, using the
        DCN axis as (degenerate) extra data parallelism."""
        mesh = create_hybrid_mesh((2, 4))
        cfg = presets.tiny(presets.mixtral_8x7b())
        model = Transformer(cfg, mesh, "tp", ("dcn", "dp"))
        params = jax.tree.map(
            lambda p, s: jax.device_put(p, s),
            model.init(jax.random.PRNGKey(0)), model.shardings(),
        )
        toks = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128),
            NamedSharding(mesh, P(("dcn", "dp"))),
        )
        l1, params = model.train_step(params, toks, toks)
        l2, _ = model.train_step(params, toks, toks)
        assert np.isfinite(float(l1)) and float(l2) < float(l1)


class TestPresets:
    def test_families_construct(self):
        for fn in (presets.llama_7b, presets.llama_70b,
                   presets.mixtral_8x7b, presets.deepseek_moe_16b):
            cfg = fn()
            assert cfg.hidden > 0 and cfg.qkv_dim > 0

    def test_tiny_preserves_topology(self):
        big = presets.mixtral_8x7b()
        small = presets.tiny(big)
        assert small.moe == big.moe == "ep"
        assert small.moe_layers == (0, 1)
        assert small.hidden == 128

    def test_overrides(self):
        cfg = presets.llama_7b(n_layers=2, attn="ring")
        assert cfg.n_layers == 2 and cfg.attn == "ring"
