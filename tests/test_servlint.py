"""ISSUE-19 servlint suite: bounded model checking of the serving/
fleet protocol through the production :class:`ProtocolOps` seam.

Three pins:

* **production ops are clean** — the exhaustive bounded exploration
  (2 replicas × 3 requests × ≤8 pages per engine, all interleavings of
  route/admit/step/spec/evict/preempt/ship/commit/transport-fail/
  drain/death) visits ≥1000 states with zero findings;
* **every seeded fixture is a true positive** — each mutated-ops
  fixture is caught by EXACTLY its rule, with the minimal repro
  interleaving printed in the finding (BFS ⇒ shortest counterexample);
* **the CLI contract** — ``lint --serving`` exits 0 on production ops
  and 2 on every fixture, ``--json`` speaks SCHEMA_VERSION 3 with SV
  rule counts, and ``--allow SV00x`` demotes uniformly with SL/MC.

Sim-free and device-free: the model drives host bookkeeping only.
"""

import json

import pytest

from triton_distributed_tpu.analysis import servlint
from triton_distributed_tpu.analysis.findings import SCHEMA_VERSION
from triton_distributed_tpu.analysis.lint import main as lint_main
from triton_distributed_tpu.serving.protocol import ProtocolOps

pytestmark = pytest.mark.fast


class TestProductionOpsClean:
    def test_bounded_exploration_is_clean(self):
        findings, stats = servlint.lint_serving(max_states=2000)
        assert findings == []
        assert stats["states"] >= 1000
        assert stats["transitions"] > stats["states"]

    def test_explicit_ops_instance(self):
        findings, _ = servlint.lint_serving(ProtocolOps(),
                                            max_states=500)
        assert findings == []


class TestFixturesAreTruePositives:
    @pytest.mark.parametrize("rule", sorted(servlint.FIXTURES))
    def test_fixture_caught_by_exactly_its_rule(self, rule):
        findings, stats = servlint.lint_serving(fixture=rule,
                                                max_states=20_000)
        # faceted keys (e.g. SV001cp) seed their base rule
        want = servlint.FIXTURES[rule].seeds_rule
        assert [f.rule for f in findings] == [want], (
            f"fixture {rule} produced {[f.rule for f in findings]} "
            f"after {stats['states']} states")
        # the finding carries its minimal repro interleaving (BFS
        # order ⇒ no shorter counterexample exists in the model)
        assert "repro:" in findings[0].message

    def test_fixture_rule_ids_cover_catalog(self):
        assert sorted(servlint.FIXTURES) == [
            "SV001", "SV001cp", "SV002", "SV003", "SV004", "SV005",
            "SV006", "SV007"]
        for rule, cls in servlint.FIXTURES.items():
            # a fixture key is its seeded rule plus an optional facet
            # suffix (SV001cp seeds SV001 over a cp=2-sharded pool)
            assert rule.startswith(cls.seeds_rule)
            assert issubclass(cls, ProtocolOps)

    def test_cp_production_ops_clean(self):
        # the cp facet's clean half: sharded pool, production verbs
        findings, stats = servlint.lint_serving(
            servlint.CpProtocolOps(), max_states=2000)
        assert findings == []
        assert stats["states"] >= 1000

    def test_unknown_fixture_refused(self):
        with pytest.raises(ValueError, match="unknown servlint"):
            servlint.lint_serving(fixture="SV999")


class TestServingCli:
    def test_production_exits_zero(self, capsys):
        assert lint_main(["--serving", "--serving-states", "800"]) == 0
        err = capsys.readouterr().err
        assert "servlint:" in err and "0 error(s)" in err

    def test_capped_run_labels_itself_honestly(self, capsys):
        """A truncated exploration must SAY it was truncated — the
        nightly's exhaustive claim rests on this label telling the
        truth (ci/nightly.sh asserts the inverse, "exhaustive")."""
        assert lint_main(["--serving", "--serving-states", "500"]) == 0
        err = capsys.readouterr().err
        assert "(state-capped)" in err
        assert "(exhaustive)" not in err

    def test_fixture_exits_two(self, capsys):
        assert lint_main(["--serving-fixture", "SV004"]) == 2
        out = capsys.readouterr().out
        assert "SV004" in out and "repro:" in out

    def test_json_schema_and_sv_rule_counts(self, capsys):
        assert lint_main(["--serving-fixture", "SV001", "--json"]) == 2
        lines = [json.loads(l) for l in
                 capsys.readouterr().out.strip().splitlines()]
        header, findings, summary = lines[0], lines[1:-1], lines[-1]
        assert header["schema_version"] == SCHEMA_VERSION == 3
        assert header["mode"] == "serving"
        assert header["fixture"] == "SV001"
        assert header["states"] > 0
        assert [f["rule"] for f in findings] == ["SV001"]
        assert findings[0]["slug"] == "serving-page-leak"
        assert summary["rule_counts"]["SV001"] == 1
        assert summary["errors"] == 1
        # SL/MC/SV share one rule_counts namespace (uniform schema)
        assert "SL001" in summary["rule_counts"]
        assert "MC007" in summary["rule_counts"]

    def test_allow_sv_rule_demotes_uniformly(self, capsys):
        assert lint_main(["--serving-fixture", "SV002",
                          "--allow", "SV002"]) == 0
        out = capsys.readouterr().out
        # still printed, demoted to info — the SL/MC --allow contract
        assert "SV002 info" in out


class TestExhaustiveNightly:
    """The ci/nightly.sh gate in-process: ``--serving-states 0`` lifts
    the cap and the BFS walks the ENTIRE reachable graph — tractable
    because ``_World.key()`` canonicalizes page ids (shard-preserving
    relabeling symmetry). Slow-marked: ~1 min of pure-python BFS."""

    @pytest.mark.slow
    def test_uncapped_production_exploration_is_exhaustive(self):
        findings, stats = servlint.lint_serving(max_states=0)
        assert findings == []
        assert stats["complete"] is True
        assert stats["states"] > 20_000

    @pytest.mark.slow
    def test_uncapped_cp_exploration_is_exhaustive(self):
        findings, stats = servlint.lint_serving(
            servlint.CpProtocolOps(), max_states=0)
        assert findings == []
        assert stats["complete"] is True
        assert stats["states"] > 20_000
