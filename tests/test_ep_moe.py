"""EP MoE op tests: forward vs dense reference, gradients through the
differentiable transport.

Mirrors test_ep_moe_inference.py / test_ep_a2a.py
(python/triton_dist/test/nvidia/); the dense per-expert einsum plays the
torch reference, and — beyond the reference's scope — the op must be
trainable end-to-end on the XLA transport.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.ops import create_ep_moe_context, ep_moe

N, E, TOPK, H, F, MTOK = 8, 16, 2, 128, 256, 16


def _data(dtype=jnp.float32):
    x = jax.random.normal(jax.random.PRNGKey(0), (N * MTOK, H), dtype)
    logits = jax.random.normal(jax.random.PRNGKey(1), (N * MTOK, E))
    w_up = jax.random.normal(jax.random.PRNGKey(2), (E, H, F), dtype) * 0.05
    w_down = jax.random.normal(jax.random.PRNGKey(3), (E, F, H), dtype) * 0.05
    return x, logits, w_up, w_down


def _dense_ref(x, logits, w_up, w_down, activation="silu"):
    from conftest import dense_moe_ref

    return dense_moe_ref(x, logits, w_up, w_down, TOPK, activation)


def _put(mesh, *arrays):
    sh = NamedSharding(mesh, P("x"))
    return tuple(jax.device_put(a, sh) for a in arrays)


@pytest.mark.parametrize("transport", ["xla", "pallas", "fused"])
@pytest.mark.parametrize("use_pallas_gemm", [True, False])
def test_forward_vs_dense(mesh8, transport, use_pallas_gemm):
    x, logits, w_up, w_down = _data()
    ref = _dense_ref(x, logits, w_up, w_down)
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK, hidden=H,
        dtype=jnp.float32, transport=transport, block_m=8,
        use_pallas_gemm=use_pallas_gemm,
    )
    out = ep_moe(*_put(mesh8, x, logits, w_up, w_down), ctx)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_fused_quant_vs_dense(mesh8):
    """Fused window-DMA transport with the fp8 in-row scale lane (the
    reference's headline WITH_SCALE dispatch) vs the dense reference."""
    x, logits, w_up, w_down = _data()
    ref = _dense_ref(x, logits, w_up, w_down)
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK, hidden=H,
        dtype=jnp.float32, transport="fused", quant="fp8", block_m=8,
        use_pallas_gemm=False,
    )
    out = ep_moe(*_put(mesh8, x, logits, w_up, w_down), ctx)
    err = np.abs(np.asarray(out) - np.asarray(ref))
    assert np.max(err) < 0.08 * np.abs(np.asarray(ref)).max()


@pytest.mark.parametrize("use_pallas_gemm", [True, False])
def test_weight_quantized_experts_vs_dense(mesh8, use_pallas_gemm):
    """Weight-only-quantized expert dicts through ep_moe (the serving
    decode weight path): Pallas consumes them in the grouped-GEMM
    epilogue, the XLA twin widens — both must track the full-precision
    dense reference within int8 per-channel error."""
    x, logits, w_up, w_down = _data()
    ref = _dense_ref(x, logits, w_up, w_down)
    from triton_distributed_tpu.kernels.group_gemm import (
        quantize_grouped_weights,
    )

    qu, su = quantize_grouped_weights(w_up, "int8")
    qd, sd = quantize_grouped_weights(w_down, "int8")
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK, hidden=H,
        dtype=jnp.float32, transport="fused", block_m=8,
        use_pallas_gemm=use_pallas_gemm,
    )
    xs, logitss = _put(mesh8, x, logits)
    esh = NamedSharding(mesh8, P("x"))
    wq_up = {"q": jax.device_put(qu, esh), "scale": jax.device_put(su, esh)}
    wq_down = {"q": jax.device_put(qd, esh), "scale": jax.device_put(sd, esh)}
    out = ep_moe(xs, logitss, wq_up, wq_down, ctx)
    err = np.abs(np.asarray(out) - np.asarray(ref))
    assert np.max(err) < 0.05 * np.abs(np.asarray(ref)).max()


class TestChunkedWire:
    """The r4 transport contract: wire bytes scale with TRUE counts
    (+ ≤1 chunk slack/peer), not with the worst-case window (≡ the
    reference shipping exact per-expert ranges,
    low_latency_all_to_all.py:62-90). Pure accounting over send_plan —
    the same numbers the kernel's traced chunk loops execute."""

    def _ctx(self, mesh, max_m=MTOK * TOPK, chunk_m=None, quant=None):
        from triton_distributed_tpu.kernels import moe_all_to_all as ma

        return ma.create_all_to_all_context(
            mesh, "x", max_m=max_m, hidden=H, experts_per_rank=E // N,
            dtype=jnp.float32, quant=quant, chunk_m=chunk_m,
        )

    def test_wire_rows_track_counts(self, mesh8):
        from triton_distributed_tpu.kernels import moe_dispatch as md

        ctx = self._ctx(mesh8)
        ck = md.chunk_rows(ctx)
        rng = np.random.default_rng(0)
        # uniform-ish routing: true counts ~ max_m/n per peer
        assign = np.sort(rng.integers(0, E, MTOK * TOPK)).astype(np.int32)
        splits = jnp.asarray(
            np.bincount(assign, minlength=E).astype(np.int32)
        )
        counts, _, _, sendk = md.send_plan(ctx, splits)
        wire = np.asarray(md.wire_rows(ctx, splits))
        true = np.asarray(counts)
        # per-peer: within one chunk of the true count
        assert (wire >= true).all()
        assert (wire - true < ck).all()
        # and nowhere near the old worst-case window (slot_pad rows/peer)
        assert wire.sum() < 2 * true.sum() + N * ck
        assert md.slot_pad(ctx) * N >= 4 * wire.sum()  # the r3 regime

    def test_wire_rows_skewed(self, mesh8):
        """All tokens to one expert: one peer gets everything, the rest
        get ZERO wire rows (the r3 window shipped max_pad to each)."""
        from triton_distributed_tpu.kernels import moe_dispatch as md

        ctx = self._ctx(mesh8)
        splits = jnp.zeros((E,), jnp.int32).at[3].set(MTOK * TOPK)
        wire = np.asarray(md.wire_rows(ctx, splits))
        owner = 3 // (E // N)
        assert wire[owner] >= MTOK * TOPK
        assert (np.delete(wire, owner) == 0).all()

    def test_combine_leg_rows_match_dispatch(self, mesh8):
        """The combine leg returns exactly the chunk ranges the dispatch
        shipped (retk == sendk seen from the two ends)."""
        from triton_distributed_tpu.kernels import moe_all_to_all as ma
        from triton_distributed_tpu.kernels import moe_dispatch as md

        ctx = self._ctx(mesh8)
        rng = np.random.default_rng(1)
        assign = np.sort(rng.integers(0, E, MTOK * TOPK)).astype(np.int32)
        splits = jnp.asarray(np.bincount(assign, minlength=E).astype(np.int32))
        _, _, _, sendk = md.send_plan(ctx, splits)
        # receiver side: counts arrive as the meta splits; retk from rspl
        spl2d = np.asarray(splits).reshape(N, E // N)
        rcnt = spl2d.sum(axis=1)
        retk = -(-rcnt // md.chunk_rows(ctx))
        np.testing.assert_array_equal(np.asarray(sendk), retk)
        del ma

    def test_checksum_injection(self, mesh8):
        """Corrupted meta head must fail LOUDLY (NaN poison) under
        debug_checksum, and only then (VERDICT r3 weak #4)."""
        from triton_distributed_tpu.config import config
        from triton_distributed_tpu.kernels import moe_dispatch as md

        ctx = self._ctx(mesh8, quant="fp8")
        rng = np.random.default_rng(2)
        assign = np.sort(rng.integers(0, E, MTOK * TOPK)).astype(np.int32)
        splits = jnp.asarray(np.bincount(assign, minlength=E).astype(np.int32))
        counts, offs, offs_al, sendk = md.send_plan(ctx, splits)
        scales = jnp.ones((md.m_cap(ctx),), jnp.float32)
        meta = md.meta_payload(ctx, splits, scales, offs_al, sendk)
        toks = jnp.ones(
            (ctx.n * md.slot_pad(ctx), ctx.hidden), ctx.wire_dtype
        )
        flat = meta.reshape(ctx.n * md.meta_rows(ctx), md.META_W)

        # intact meta, check on: no poison
        old = config.debug_checksum
        try:
            config.debug_checksum = True
            out, _ = md.recv_view(ctx, toks, flat)
            assert not np.isnan(np.asarray(out)).any()
            # corrupt one count word of slot 2
            bad = flat.at[2 * md.meta_rows(ctx), 0].add(1)
            out_bad, _ = md.recv_view(ctx, toks, bad)
            outn = np.asarray(out_bad)
            assert np.isnan(outn[2]).all(), "corruption must poison slot 2"
            assert not np.isnan(np.delete(outn, 2, axis=0)).any()
            config.debug_checksum = False
            out_off, _ = md.recv_view(ctx, toks, bad)
            assert not np.isnan(np.asarray(out_off)).any(), (
                "check off: legacy silent-masking behavior"
            )
        finally:
            config.debug_checksum = old


class TestFusedLL:
    """Barrier-free fused transport: persistent workspaces + parity
    carry (VERDICT r3 missing #2). Multi-call sequences roll the parity;
    a fully-jitted loop threads the state as a carry."""

    def _ctx(self, mesh, **kw):
        kw.setdefault("use_pallas_gemm", False)
        return create_ep_moe_context(
            mesh, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK,
            hidden=H, dtype=jnp.float32, transport="fused", block_m=8, **kw,
        )

    def test_multi_call_parity_roll(self, mesh8):
        from triton_distributed_tpu.ops import create_ep_moe_state

        ctx = self._ctx(mesh8)
        state = create_ep_moe_state(ctx)
        for i in range(3):
            x = jax.random.normal(
                jax.random.PRNGKey(10 + i), (N * MTOK, H), jnp.float32
            )
            logits = jax.random.normal(
                jax.random.PRNGKey(20 + i), (N * MTOK, E)
            )
            _, _, w_up, w_down = _data()
            ref = _dense_ref(x, logits, w_up, w_down)
            out, state = ep_moe(
                *_put(mesh8, x, logits, w_up, w_down), ctx, state=state
            )
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
            )
            assert int(np.asarray(state.parity)[0]) == (i + 1) % 2

    def test_jitted_loop_carries_state(self, mesh8):
        """The functional-carry requirement: a jitted multi-step loop
        rolls the parity across steps with no host round-trip (what the
        LL allgather could not do, allgather.py:403-408)."""
        from triton_distributed_tpu.ops import create_ep_moe_state
        from triton_distributed_tpu.ops.moe import _build_ep_moe
        from triton_distributed_tpu.config import interp_key

        ctx = self._ctx(mesh8)
        state = create_ep_moe_state(ctx)
        x, logits, w_up, w_down = _data()
        ref = _dense_ref(x, logits, w_up, w_down)
        xg, lg, wu, wd = _put(mesh8, x, logits, w_up, w_down)
        fn = _build_ep_moe(ctx, interp_key(), state.instance)

        @jax.jit
        def two_steps(x, logits, wu, wd, ws):
            out1, ws = fn(x, logits, wu, wd, ws)
            out2, ws = fn(x, logits, wu, wd, ws)
            return out1, out2, ws

        out1, out2, ws = two_steps(xg, lg, wu, wd, state.as_dict())
        np.testing.assert_allclose(
            np.asarray(out1), np.asarray(ref), atol=1e-5, rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(out2), np.asarray(ref), atol=1e-5, rtol=1e-5
        )
        assert int(np.asarray(ws["parity"])[0]) == 0  # rolled 0→1→0

    def test_quantized_ll(self, mesh8):
        from triton_distributed_tpu.ops import create_ep_moe_state

        ctx = self._ctx(mesh8, quant="fp8")
        state = create_ep_moe_state(ctx)
        x, logits, w_up, w_down = _data()
        ref = _dense_ref(x, logits, w_up, w_down)
        out, state = ep_moe(
            *_put(mesh8, x, logits, w_up, w_down), ctx, state=state
        )
        err = np.abs(np.asarray(out) - np.asarray(ref))
        assert np.max(err) < 0.08 * np.abs(np.asarray(ref)).max()

    def test_state_requires_fused(self, mesh8):
        from triton_distributed_tpu.ops import create_ep_moe_state

        ctx = create_ep_moe_context(
            mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK,
            hidden=H, dtype=jnp.float32, transport="xla",
        )
        with pytest.raises(ValueError, match="fused"):
            create_ep_moe_state(ctx)


def test_fused_rejects_hierarchical():
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dcn", "ep"))
    with pytest.raises(ValueError, match="flat"):
        create_ep_moe_context(
            mesh, "ep", dcn_axis="dcn", num_experts=E, topk=TOPK,
            max_m=MTOK * TOPK, hidden=H, transport="fused",
        )


def test_grads_match_dense(mesh8):
    """Training path: grads through routing, dispatch a2a, grouped MLP,
    combine a2a must equal the dense MoE's grads."""
    x, logits, w_up, w_down = _data()
    y_tgt = jax.random.normal(jax.random.PRNGKey(4), (N * MTOK, H))
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK, hidden=H,
        dtype=jnp.float32, transport="xla", block_m=8, use_pallas_gemm=False,
    )

    def loss_ep(params, x, logits):
        out = ep_moe(x, logits, params["up"], params["down"], ctx)
        return jnp.mean((out - y_tgt) ** 2)

    def loss_dense(params, x, logits):
        out = _dense_ref(x, logits, params["up"], params["down"])
        return jnp.mean((out - y_tgt) ** 2)

    xg, lg, wu, wd = _put(mesh8, x, logits, w_up, w_down)
    g_ep = jax.grad(loss_ep)({"up": wu, "down": wd}, xg, lg)
    g_ref = jax.grad(loss_dense)({"up": w_up, "down": w_down}, x, logits)
    for k in ("up", "down"):
        np.testing.assert_allclose(
            np.asarray(g_ep[k]), np.asarray(g_ref[k]), atol=1e-6, rtol=1e-4
        )
    gx = jax.grad(loss_ep, argnums=1)({"up": wu, "down": wd}, xg, lg)
    gx_ref = jax.grad(loss_dense, argnums=1)(
        {"up": w_up, "down": w_down}, x, logits
    )
    np.testing.assert_allclose(
        np.asarray(gx), np.asarray(gx_ref), atol=1e-6, rtol=1e-4
    )


def test_ep_moe_tuned_matches_and_caches(mesh8, tmp_path, monkeypatch):
    """Autotuned entry: same numerics as ep_moe, one bench pass, then
    cache hits (≡ wrapping kernels in contextual_autotune)."""
    monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
    from triton_distributed_tpu.ops import create_ep_moe_context, ep_moe_tuned
    from triton_distributed_tpu.ops import moe as moe_mod

    monkeypatch.setattr(moe_mod, "_EP_MOE_TUNERS", type(moe_mod._EP_MOE_TUNERS)())

    x, logits, w_up, w_down = _data()
    ref = _dense_ref(x, logits, w_up, w_down)
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK, hidden=H,
        dtype=jnp.float32, transport="xla", use_pallas_gemm=False,
    )
    args = _put(mesh8, x, logits, w_up, w_down)
    out = ep_moe_tuned(*args, ctx, candidates=(8, 16))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    out2 = ep_moe_tuned(*args, ctx, candidates=(8, 16))   # cache hit
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref), atol=1e-5)
    log = (tmp_path / "process-0.jsonl").read_text()
    assert log.count('"best"') == 1


class TestHierarchical:
    """DCN-aware hierarchical EP exchange: same-local-rank DCN rail leg +
    intra-slice ICI leg on a (dcn=2, ep=4) virtual mesh (VERDICT r1 #5;
    ≡ ep_a2a.py:36-150's node rotation with same-local-rank rail puts)."""

    @pytest.fixture(scope="class")
    def mesh_dcn(self):
        from jax.sharding import Mesh

        devs = np.asarray(jax.devices()).reshape(2, 4)
        return Mesh(devs, ("dcn", "ep"))

    def _hier_ctx(self, mesh, transport, **kw):
        return create_ep_moe_context(
            mesh, "ep", dcn_axis="dcn", num_experts=E, topk=TOPK,
            max_m=MTOK * TOPK, hidden=H, dtype=jnp.float32,
            transport=transport, block_m=8, **kw,
        )

    @pytest.mark.parametrize("transport", ["xla", "pallas"])
    def test_hier_forward_vs_dense(self, mesh_dcn, transport):
        x, logits, w_up, w_down = _data()
        ref = _dense_ref(x, logits, w_up, w_down)
        ctx = self._hier_ctx(mesh_dcn, transport)
        assert ctx.n == 8 and ctx.dcn == 2 and ctx.epl == 4
        sh_rows = NamedSharding(mesh_dcn, P(("dcn", "ep")))
        out = ep_moe(
            jax.device_put(x, sh_rows), jax.device_put(logits, sh_rows),
            jax.device_put(w_up, sh_rows), jax.device_put(w_down, sh_rows),
            ctx,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_hier_matches_flat(self, mesh8, mesh_dcn):
        """The hierarchical exchange must be numerically identical to the
        flat 8-rank exchange on the same data."""
        x, logits, w_up, w_down = _data()
        flat_ctx = create_ep_moe_context(
            mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK,
            hidden=H, dtype=jnp.float32, transport="xla", block_m=8,
        )
        flat = ep_moe(*_put(mesh8, x, logits, w_up, w_down), flat_ctx)
        ctx = self._hier_ctx(mesh_dcn, "xla")
        sh_rows = NamedSharding(mesh_dcn, P(("dcn", "ep")))
        hier = ep_moe(
            jax.device_put(x, sh_rows), jax.device_put(logits, sh_rows),
            jax.device_put(w_up, sh_rows), jax.device_put(w_down, sh_rows),
            ctx,
        )
        np.testing.assert_allclose(
            np.asarray(hier), np.asarray(flat), atol=1e-6, rtol=1e-6
        )

    def test_dcn_routing_guard(self, mesh_dcn, monkeypatch):
        """A pallas transport over an axis the topology classifies as DCN
        must be rejected unless routed hierarchically (is_dcn_axis)."""
        from triton_distributed_tpu.runtime import topology as topo

        real = topo.detect_topology

        def fake(mesh, axis=None):
            info = real(mesh, axis)
            if axis == "dcn":
                info.link_kind = topo.LinkKind.DCN
            return info

        monkeypatch.setattr(topo, "detect_topology", fake)
        import triton_distributed_tpu.runtime.multislice as ms

        monkeypatch.setattr(ms, "detect_topology", fake)
        # flat pallas EP straight over the DCN axis → rejected
        with pytest.raises(ValueError, match="crosses DCN"):
            create_ep_moe_context(
                mesh_dcn, "dcn", num_experts=E, topk=TOPK,
                max_m=MTOK * TOPK, hidden=H, transport="pallas",
            )
        # hierarchical with the axes swapped (ICI leg on the DCN axis) →
        # rejected too
        with pytest.raises(ValueError, match="itself crosses DCN"):
            create_ep_moe_context(
                mesh_dcn, "dcn", dcn_axis="ep", num_experts=E, topk=TOPK,
                max_m=MTOK * TOPK, hidden=H, transport="pallas",
            )
        # correctly declared hierarchy → accepted
        ctx = create_ep_moe_context(
            mesh_dcn, "ep", dcn_axis="dcn", num_experts=E, topk=TOPK,
            max_m=MTOK * TOPK, hidden=H, transport="pallas",
        )
        assert ctx.dcn == 2


class TestRailDedup:
    """The DCN rail ships each token ONCE per target slice (VERDICT r2
    #5; ≡ the reference's once-per-node put + intra-node scatter,
    ep_a2a.py:74-80): DCN payload scales with unique (token, slice)
    pairs, never with topk duplicates."""

    def test_rail_bytes_scale_with_unique_tokens(self, mesh8):
        """All topk experts of every token on ONE remote slice: the rail
        must carry exactly M unique rows for that slice — not M·topk —
        and the rail slot capacity itself is M rows per slice."""
        from triton_distributed_tpu.ops.moe import _rail_stage

        mesh_dcn = jax.sharding.Mesh(
            np.asarray(jax.devices()).reshape(2, 4), ("dcn", "ep")
        )
        ctx = create_ep_moe_context(
            mesh_dcn, "ep", dcn_axis="dcn", num_experts=E, topk=TOPK,
            max_m=MTOK * TOPK, hidden=H, dtype=jnp.float32,
        )
        m = MTOK
        x = jax.random.normal(jax.random.PRNGKey(0), (m, H))
        slice1 = E // 2  # experts [E/2, E) live on slice 1
        ids = jnp.stack(
            [jnp.full((m,), slice1, jnp.int32),
             jnp.full((m,), slice1 + 1, jnp.int32)], axis=1,
        )
        weights = jnp.full((m, TOPK), 0.5, jnp.float32)
        tok, ids_s, w_s, hit, u_counts = _rail_stage(ctx, x, ids, weights)
        # capacity: M rows per slice — independent of topk
        assert tok.shape == (2, m, H)
        # every token hits slice 1 exactly once despite topk=2 experts
        np.testing.assert_array_equal(np.asarray(u_counts), [0, m])
        np.testing.assert_array_equal(
            np.asarray(hit).sum(), m  # M unique pairs, not M·topk
        )

    def test_hier_dedup_matches_flat(self, mesh8):
        """The dedup'd hierarchical exchange must still equal the flat
        8-rank exchange on identical data (all transports)."""
        mesh_dcn = jax.sharding.Mesh(
            np.asarray(jax.devices()).reshape(2, 4), ("dcn", "ep")
        )
        x, logits, w_up, w_down = _data()
        flat_ctx = create_ep_moe_context(
            mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK,
            hidden=H, dtype=jnp.float32, transport="xla", block_m=8,
            use_pallas_gemm=False,
        )
        flat = ep_moe(*_put(mesh8, x, logits, w_up, w_down), flat_ctx)
        ctx = create_ep_moe_context(
            mesh_dcn, "ep", dcn_axis="dcn", num_experts=E, topk=TOPK,
            max_m=MTOK * TOPK, hidden=H, dtype=jnp.float32,
            transport="xla", block_m=8, use_pallas_gemm=False,
        )
        sh = NamedSharding(mesh_dcn, P(("dcn", "ep")))
        hier = ep_moe(
            *(jax.device_put(a, sh) for a in (x, logits, w_up, w_down)), ctx
        )
        np.testing.assert_allclose(
            np.asarray(hier), np.asarray(flat), atol=1e-5, rtol=1e-5
        )


class TestQuantizedTransport:
    """fp8/int8 wire format with in-slot per-token scales (VERDICT r1 #6;
    ≡ the reference's WITH_SCALE fp8 dispatch,
    low_latency_all_to_all.py:43-107)."""

    def _run(self, mesh8, quant, **kw):
        x, logits, w_up, w_down = _data()
        ctx = create_ep_moe_context(
            mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK,
            hidden=H, dtype=jnp.float32, transport="pallas", block_m=8,
            quant=quant, **kw,
        )
        return x, logits, w_up, w_down, ep_moe(
            *_put(mesh8, x, logits, w_up, w_down), ctx
        )

    @pytest.mark.parametrize("quant", ["fp8", "int8"])
    def test_quant_matches_full_precision(self, mesh8, quant):
        x, logits, w_up, w_down, out = self._run(mesh8, quant)
        ref = _dense_ref(x, logits, w_up, w_down)
        # quantization tolerance against the global output scale (per-
        # element relative error is meaningless at near-zero refs): two
        # quantized hops (dispatch + combine) of ~2^-3-step formats
        err = np.abs(np.asarray(out) - np.asarray(ref))
        scale = np.abs(np.asarray(ref)).max()
        assert np.max(err) < 0.08 * scale
        assert np.median(err) < 0.01 * scale

    def test_slot_geometry_carries_scales(self, mesh8):
        from triton_distributed_tpu.kernels import moe_all_to_all as ma

        ctx = create_ep_moe_context(
            mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK,
            hidden=H, dtype=jnp.float32, transport="pallas", quant="fp8",
        ).a2a
        assert ctx.wire_dtype == jnp.dtype(jnp.float8_e4m3fn)
        assert ctx.ints_per_row == H // 4
        assert ctx.scale_rows == -(-ctx.max_m // ctx.ints_per_row)
        assert ctx.slot_rows == ctx.max_m + ctx.scale_rows + ctx.splits_rows
        # round-trip: pack → unpack reproduces tokens within fp8 step
        toks = jax.random.normal(
            jax.random.PRNGKey(7), (ctx.n, ctx.max_m, H), jnp.float32
        )
        spl = jnp.full((ctx.n, ctx.experts_per_rank), 3, jnp.int32)
        slots = ma.pack_slots(ctx, toks, spl)
        back, bspl = ma.recv_tokens_view(
            ctx, slots.reshape(ctx.n * ctx.slot_rows, ctx.ints_per_row)
        )
        np.testing.assert_allclose(
            np.asarray(back), np.asarray(toks), atol=0.12, rtol=0.12
        )
        np.testing.assert_array_equal(np.asarray(bspl), np.asarray(spl))

    def test_quant_under_chaos(self, mesh8, monkeypatch):
        """Quantized dispatch+combine must stay correct with randomized
        comm delays widening race windows (the reference's
        for_correctness chaos testing, SURVEY.md §4)."""
        from triton_distributed_tpu.config import config as cfg

        # chaos_delay participates in _build_ep_moe's cache key via
        # interp_key(), so no manual cache_clear is needed here
        monkeypatch.setattr(cfg, "chaos_delay", True)
        x, logits, w_up, w_down, out = self._run(mesh8, "fp8")
        ref = _dense_ref(x, logits, w_up, w_down)
        err = np.abs(np.asarray(out) - np.asarray(ref))
        scale = np.abs(np.asarray(ref)).max()
        assert np.max(err) < 0.08 * scale

    def test_quant_requires_pallas(self, mesh8):
        with pytest.raises(ValueError, match="Pallas"):
            create_ep_moe_context(
                mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK,
                hidden=H, transport="xla", quant="fp8",
            )
