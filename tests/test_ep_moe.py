"""EP MoE op tests: forward vs dense reference, gradients through the
differentiable transport.

Mirrors test_ep_moe_inference.py / test_ep_a2a.py
(python/triton_dist/test/nvidia/); the dense per-expert einsum plays the
torch reference, and — beyond the reference's scope — the op must be
trainable end-to-end on the XLA transport.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.ops import create_ep_moe_context, ep_moe

N, E, TOPK, H, F, MTOK = 8, 16, 2, 128, 256, 16


def _data(dtype=jnp.float32):
    x = jax.random.normal(jax.random.PRNGKey(0), (N * MTOK, H), dtype)
    logits = jax.random.normal(jax.random.PRNGKey(1), (N * MTOK, E))
    w_up = jax.random.normal(jax.random.PRNGKey(2), (E, H, F), dtype) * 0.05
    w_down = jax.random.normal(jax.random.PRNGKey(3), (E, F, H), dtype) * 0.05
    return x, logits, w_up, w_down


def _dense_ref(x, logits, w_up, w_down, activation="silu"):
    from conftest import dense_moe_ref

    return dense_moe_ref(x, logits, w_up, w_down, TOPK, activation)


def _put(mesh, *arrays):
    sh = NamedSharding(mesh, P("x"))
    return tuple(jax.device_put(a, sh) for a in arrays)


@pytest.mark.parametrize("transport", ["xla", "pallas"])
@pytest.mark.parametrize("use_pallas_gemm", [True, False])
def test_forward_vs_dense(mesh8, transport, use_pallas_gemm):
    x, logits, w_up, w_down = _data()
    ref = _dense_ref(x, logits, w_up, w_down)
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK, hidden=H,
        dtype=jnp.float32, transport=transport, block_m=8,
        use_pallas_gemm=use_pallas_gemm,
    )
    out = ep_moe(*_put(mesh8, x, logits, w_up, w_down), ctx)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_grads_match_dense(mesh8):
    """Training path: grads through routing, dispatch a2a, grouped MLP,
    combine a2a must equal the dense MoE's grads."""
    x, logits, w_up, w_down = _data()
    y_tgt = jax.random.normal(jax.random.PRNGKey(4), (N * MTOK, H))
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK, hidden=H,
        dtype=jnp.float32, transport="xla", block_m=8, use_pallas_gemm=False,
    )

    def loss_ep(params, x, logits):
        out = ep_moe(x, logits, params["up"], params["down"], ctx)
        return jnp.mean((out - y_tgt) ** 2)

    def loss_dense(params, x, logits):
        out = _dense_ref(x, logits, params["up"], params["down"])
        return jnp.mean((out - y_tgt) ** 2)

    xg, lg, wu, wd = _put(mesh8, x, logits, w_up, w_down)
    g_ep = jax.grad(loss_ep)({"up": wu, "down": wd}, xg, lg)
    g_ref = jax.grad(loss_dense)({"up": w_up, "down": w_down}, x, logits)
    for k in ("up", "down"):
        np.testing.assert_allclose(
            np.asarray(g_ep[k]), np.asarray(g_ref[k]), atol=1e-6, rtol=1e-4
        )
    gx = jax.grad(loss_ep, argnums=1)({"up": wu, "down": wd}, xg, lg)
    gx_ref = jax.grad(loss_dense, argnums=1)(
        {"up": w_up, "down": w_down}, x, logits
    )
    np.testing.assert_allclose(
        np.asarray(gx), np.asarray(gx_ref), atol=1e-6, rtol=1e-4
    )


def test_ep_moe_tuned_matches_and_caches(mesh8, tmp_path, monkeypatch):
    """Autotuned entry: same numerics as ep_moe, one bench pass, then
    cache hits (≡ wrapping kernels in contextual_autotune)."""
    monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
    from triton_distributed_tpu.ops import create_ep_moe_context, ep_moe_tuned
    from triton_distributed_tpu.ops import moe as moe_mod

    monkeypatch.setattr(moe_mod, "_EP_MOE_TUNERS", type(moe_mod._EP_MOE_TUNERS)())

    x, logits, w_up, w_down = _data()
    ref = _dense_ref(x, logits, w_up, w_down)
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=E, topk=TOPK, max_m=MTOK * TOPK, hidden=H,
        dtype=jnp.float32, transport="xla", use_pallas_gemm=False,
    )
    args = _put(mesh8, x, logits, w_up, w_down)
    out = ep_moe_tuned(*args, ctx, candidates=(8, 16))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    out2 = ep_moe_tuned(*args, ctx, candidates=(8, 16))   # cache hit
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref), atol=1e-5)
    log = (tmp_path / "process-0.jsonl").read_text()
    assert log.count('"best"') == 1
