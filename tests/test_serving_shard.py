"""Shard-resident serving state across the prefill→decode boundary.

The serving contract on a dp×tp mesh (≡ the reference's SP decode
layer, whose per-rank KV shard keeps one placement for the life of the
session — sp_flash_decode_layer.py:45-184):

* ONE canonical cache placement (batch over dp, sequence over tp,
  ``Transformer.cache_sharding``) from ``init_cache`` through prefill
  into every decode step;
* the decode jits DONATE the caches and kv_lens, and the pinned
  output placements let XLA alias them — the per-step cache update is
  in place, not a cache-sized copy;
* the shardguard utilities turn a violation (the round-4 "[SPMD]
  Involuntary full rematerialization" compile-log failure mode) into
  a loud CI failure.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.runtime import (
    assert_args_aliased,
    assert_no_involuntary_resharding,
    find_involuntary_resharding,
    input_output_aliased_params,
)


def _model(mesh, kv_quant=None):
    cfg = TransformerConfig(
        vocab=128, n_layers=2, hidden=128, ffn=256,
        n_heads=8, n_kv_heads=4, head_dim=16,
        moe="ep", moe_layers=(1,), num_experts=8, topk=2,
        kv_quant=kv_quant,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    model = Transformer(cfg, mesh, "tp", ("dp",))
    params = jax.tree.map(
        lambda p, s: jax.device_put(p, s),
        model.init(jax.random.PRNGKey(0)), model.shardings(),
    )
    return model, params


def _assert_canonical(model, caches):
    sh = model.cache_sharding
    for leaf in jax.tree.leaves(caches):
        assert leaf.sharding.is_equivalent_to(sh, leaf.ndim), (
            f"cache leaf on {leaf.sharding} != canonical {sh}"
        )


class TestServingShardResidency:
    @pytest.mark.parametrize("kv_quant", [None, "int8"])
    def test_decode_no_reshard_and_aliased(self, mesh2x4, kv_quant):
        """Compile decode_step on the 2×4 dryrun mesh: (i) its cache
        input shardings equal prefill's output shardings (no
        involuntary reshard at the boundary), (ii) the compiled program
        aliases the cache (and lens) inputs to outputs — in-place
        update survived donation."""
        model, params = _model(mesh2x4, kv_quant)
        b = 4
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (b, 16), 0, 128),
            NamedSharding(mesh2x4, P("dp")),
        )
        caches = model.init_cache(b, 32)
        _assert_canonical(model, caches)       # init placement
        last, caches, lens = model._prefill_jit(params, caches, tokens)
        _assert_canonical(model, caches)       # prefill kept it

        first = jnp.argmax(last, axis=-1).astype(jnp.int32)
        args = (params, caches, lens, first)
        # lower from ABSTRACT args carrying the canonical placements —
        # lowering from the live arrays would echo their shardings back
        # and make the boundary check vacuous
        comp = model._decode_jit.lower(
            *model.decode_abstract_args(*args)
        ).compile()
        # (i) every argument (params included) arrives in the placement
        # the program compiled for — nothing is resharded per step
        assert find_involuntary_resharding(comp, args, min_bytes=0) == []
        # ... and the check is NON-vacuous: the same program must flag
        # caches living in a non-canonical placement
        bad_caches = jax.tree.map(
            lambda x: jax.device_put(
                np.asarray(x), NamedSharding(mesh2x4, P())
            ),
            caches,
        )
        assert find_involuntary_resharding(
            comp, (params, bad_caches, lens, first), min_bytes=0
        )
        # (ii) caches and kv_lens are input/output-aliased
        assert_args_aliased(comp, args, lambda a: a[1])
        assert_args_aliased(comp, args, lambda a: a[2])

        logits, caches2, lens2 = comp(*args)
        _assert_canonical(model, caches2)      # decode kept it too
        assert np.asarray(lens2).tolist() == [17] * b
        assert bool(jnp.isfinite(logits).all())

    def test_decode_matches_replicated_reference(self, mesh2x4):
        """The dp-sharded decode path must produce the same logits as
        the same model run with everything on one device mesh."""
        model, params = _model(mesh2x4)
        b = 4
        tokens = jax.random.randint(jax.random.PRNGKey(1), (b, 16), 0, 128)
        caches = model.init_cache(b, 32)
        last, caches, lens = model._prefill_jit(
            params, caches,
            jax.device_put(tokens, NamedSharding(mesh2x4, P("dp"))),
        )
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)
        logits, _, _ = model._decode_jit(params, caches, lens, first)

        mesh1 = jax.sharding.Mesh(
            np.asarray(jax.devices()[:1]).reshape(1, 1), ("dp", "tp")
        )
        model1, _ = _model(mesh1)
        params1 = jax.device_put(
            jax.tree.map(np.asarray, params),
            NamedSharding(mesh1, P()),
        )
        caches1 = model1.init_cache(b, 32)
        last1, caches1, lens1 = model1._prefill_jit(params1, caches1, tokens)
        logits1, _, _ = model1._decode_jit(
            params1, caches1, lens1,
            jnp.argmax(last1, axis=-1).astype(jnp.int32),
        )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(logits1), atol=2e-4, rtol=2e-4
        )

    def test_guard_trips_on_seeded_mismatch(self, mesh2x4):
        """A program compiled for one placement, fed an array living in
        another, must fail the guard loudly."""
        want = NamedSharding(mesh2x4, P("dp", None))
        have = NamedSharding(mesh2x4, P(None, "tp"))
        comp = jax.jit(lambda a: a * 2).lower(
            jax.ShapeDtypeStruct((64, 64), jnp.float32, sharding=want)
        ).compile()
        x = jax.device_put(jnp.zeros((64, 64), jnp.float32), have)
        bad = find_involuntary_resharding(comp, (x,), min_bytes=0)
        assert len(bad) == 1
        with pytest.raises(AssertionError, match="involuntary resharding"):
            assert_no_involuntary_resharding(comp, (x,), min_bytes=0)
        # the matching placement passes
        ok = jax.device_put(jnp.zeros((64, 64), jnp.float32), want)
        assert_no_involuntary_resharding(comp, (ok,), min_bytes=0)

    def test_alias_guard_trips_on_dropped_donation(self, mesh2x4):
        """A donated argument whose output placement diverges cannot be
        aliased — the guard must say so (instead of the program paying
        a silent copy per call)."""
        x = jax.device_put(
            jnp.zeros((64, 64), jnp.float32),
            NamedSharding(mesh2x4, P("dp", None)),
        )

        def resharded(a):
            return jax.lax.with_sharding_constraint(
                a + 1, NamedSharding(mesh2x4, P("tp", None))
            )

        comp = jax.jit(resharded, donate_argnums=(0,)).lower(x).compile()
        with pytest.raises(AssertionError, match="NOT input/output-aliased"):
            assert_args_aliased(comp, (x,), lambda a: a[0])

        def inplace(a):
            return jax.lax.with_sharding_constraint(
                a.at[0].set(1.0), NamedSharding(mesh2x4, P("dp", None))
            )

        comp2 = jax.jit(inplace, donate_argnums=(0,)).lower(x).compile()
        assert_args_aliased(comp2, (x,), lambda a: a[0])
        assert 0 in input_output_aliased_params(comp2)

    def test_decode_boundary_violation_raises(self, mesh2x4):
        """The ISSUE-1 negative path on the REAL decode program (not a
        synthetic lambda): caches living off the canonical placement
        must make ``assert_no_involuntary_resharding`` raise with the
        offending leaf paths in the message."""
        model, params = _model(mesh2x4)
        b = 4
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (b, 16), 0, 128),
            NamedSharding(mesh2x4, P("dp")),
        )
        caches = model.init_cache(b, 32)
        last, caches, lens = model._prefill_jit(params, caches, tokens)
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)
        args = (params, caches, lens, first)
        comp = model._decode_jit.lower(
            *model.decode_abstract_args(*args)
        ).compile()
        bad_caches = jax.tree.map(
            lambda x: jax.device_put(
                np.asarray(x), NamedSharding(mesh2x4, P())
            ),
            caches,
        )
        with pytest.raises(AssertionError, match="involuntary resharding"):
            assert_no_involuntary_resharding(
                comp, (params, bad_caches, lens, first), min_bytes=0
            )

    def test_reshard_guard_min_bytes_filters_small_leaves(self, mesh2x4):
        """Leaves below ``min_bytes`` are exempt: resharding a few KB per
        call is noise, and flagging it would make the guard uninhabitable
        for scalar step counters and lens vectors."""
        want = NamedSharding(mesh2x4, P("dp", None))
        have = NamedSharding(mesh2x4, P(None, "tp"))
        comp = jax.jit(lambda a: a * 2).lower(
            jax.ShapeDtypeStruct((8, 8), jnp.float32, sharding=want)
        ).compile()
        x = jax.device_put(jnp.zeros((8, 8), jnp.float32), have)
        # 256 bytes: flagged at min_bytes=0, exempt at the 1 MiB default
        assert find_involuntary_resharding(comp, (x,), min_bytes=0)
        assert_no_involuntary_resharding(comp, (x,))

    def test_guard_rejects_mismatched_arg_tree(self, mesh2x4):
        """Passing a different argument tree than the program was
        lowered with must be a loud ValueError, not a silent mispairing
        of leaves with parameter shardings."""
        sh = NamedSharding(mesh2x4, P())
        comp = jax.jit(lambda a, b: a + b).lower(
            jax.ShapeDtypeStruct((8,), jnp.float32, sharding=sh),
            jax.ShapeDtypeStruct((8,), jnp.float32, sharding=sh),
        ).compile()
        x = jax.device_put(jnp.zeros((8,), jnp.float32), sh)
        with pytest.raises(ValueError, match="does not match the compiled"):
            find_involuntary_resharding(comp, (x,), min_bytes=0)

    def test_leaf_range_rejects_foreign_selector(self):
        from triton_distributed_tpu.runtime.shardguard import leaf_range

        args = (jnp.zeros((4,)), jnp.zeros((8,)))
        assert leaf_range(args, lambda a: a[1]) == range(1, 2)
        with pytest.raises(ValueError, match="top-level args"):
            leaf_range(args, lambda a: "not an arg")

    def test_alias_guard_handles_dropped_unused_args(self, mesh2x4):
        """jit(keep_unused=False) drops unused argument leaves from the
        compiled signature — the guards must renumber through the kept
        set instead of false-failing (or false-passing) on the shift."""
        f = jax.jit(lambda a, b: b.at[0].set(1.0), donate_argnums=(1,))
        a = jnp.zeros((8,))
        b = jax.device_put(
            jnp.zeros((64,)), NamedSharding(mesh2x4, P())
        )
        comp = f.lower(a, b).compile()
        # b IS aliased even though it is HLO parameter 0 (a was dropped)
        assert_args_aliased(comp, (a, b), lambda t: t[1])
        # the dropped leaf itself reports as not-aliased
        with pytest.raises(AssertionError, match="NOT input/output"):
            assert_args_aliased(comp, (a, b), lambda t: t[0])
        # and the reshard guard still pairs the kept leaves correctly
        assert_no_involuntary_resharding(comp, (a, b), min_bytes=0)
