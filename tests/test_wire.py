"""Quantized-wire streaming rings (ISSUE 3): lang.wire layout, the
XLA-ring wire twins (byte-identical layout to the fused Pallas wire),
the standalone collectives' wire knobs, the perf-model/topology wire
auto-selection, and the collective-id rail ledger.

Accuracy tolerances are PINNED here (the acceptance contract):

* fp8 (e4m3) wire: one rounding per element ≤ 2^-3 relative → AG-side
  (quantize once) max error ≤ 6% of the output scale; RS-side (per-hop
  requant over n-1 hops) ≤ 15%.
* int8 wire with per-chunk scales: ≤ 2% AG-side / 4% RS-side on
  well-conditioned slabs; the worst-case OUTLIER slab test pins the
  known failure mode (one huge row inflates the chunk scale and
  flattens its neighbors) so the guidance in docs/PERF.md stays honest.

The fused Pallas wire engines themselves need the TPU-simulation
interpreter (skipped without it); their protocol is checked statically
for every jax by the registry families in test_analysis.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import requires_tpu_sim

from triton_distributed_tpu.lang import wire as wirelib

#: tier-1 fast subset (ci/fast.sh): XLA wire twins and layout math
pytestmark = pytest.mark.fast


def _rel_err(got, ref):
    ref = np.asarray(ref, np.float64)
    got = np.asarray(got, np.float64)
    scale = np.abs(ref).max() or 1.0
    return float(np.abs(got - ref).max() / scale)


# ------------------------------------------------------------- the layout

class TestWireFormat:
    def test_normalize(self):
        assert wirelib.normalize_wire(None) is None
        assert wirelib.normalize_wire("bf16") is None
        assert wirelib.normalize_wire("fp8") == "fp8"
        assert wirelib.normalize_wire("int8") == "int8"
        assert wirelib.normalize_wire("auto") == "auto"
        with pytest.raises(ValueError):
            wirelib.normalize_wire("fp4")

    def test_chunking_and_bytes(self):
        fmt = wirelib.make_wire_format("fp8", 128)
        assert fmt.chunk_rows == 64 and fmt.chunks(128) == 2
        # payload at 1 B/elem + one (128·4 B) scale row per chunk
        assert fmt.slab_bytes(128, 8192) == 128 * 8192 + 2 * 512
        # vs the bf16 wire: the acceptance ratio at ring-slab scale
        assert 128 * 8192 * 2 / fmt.slab_bytes(128, 8192) > 1.8

    def test_whole_slab_chunk_for_tiny_slabs(self):
        fmt = wirelib.make_wire_format("int8", 16)
        assert fmt.chunk_rows == 16 and fmt.chunks(16) == 1

    def test_wire_blockable_rejects_tiny_slabs(self):
        # an 8×32 slab: the 512 B scale row eats the compression → must
        # be rejected, not shipped larger than the bf16 wire
        assert not wirelib.wire_blockable(8, 32, "fp8", strict=False)
        assert wirelib.wire_blockable(64, 2048, "fp8", strict=False)

    @pytest.mark.parametrize("quant", ["fp8", "int8"])
    def test_roundtrip_tolerance(self, quant):
        fmt = wirelib.make_wire_format(quant, 128)
        x = jax.random.normal(jax.random.PRNGKey(0), (128, 1024), jnp.float32)
        q, s = wirelib.quantize_slab(x, fmt)
        assert q.dtype == fmt.wire_dtype
        assert s.shape == fmt.scale_shape(128)
        y = wirelib.dequantize_slab(q, s, fmt, jnp.float32)
        tol = 0.06 if quant == "fp8" else 0.02
        assert _rel_err(y, x) < tol

    def test_outlier_slab_worst_case(self):
        """One huge row per chunk inflates the shared scale: int8 must
        still round-trip the OUTLIER exactly-ish while its neighbors
        degrade gracefully (bounded by outlier/127 per element) — the
        documented worst case of per-chunk scales."""
        fmt = wirelib.make_wire_format("int8", 64)
        x = np.random.default_rng(1).normal(size=(64, 512)).astype(np.float32)
        x[0, :] *= 1000.0                       # the outlier row
        q, s = wirelib.quantize_slab(jnp.asarray(x), fmt)
        y = np.asarray(wirelib.dequantize_slab(q, s, fmt, jnp.float32))
        # outlier row: ~2 valid digits survive
        assert _rel_err(y[0], x[0]) < 0.01
        # neighbor rows: absolute error bounded by half a quantization
        # step of the inflated scale
        step = float(np.asarray(s)[0, 0])
        assert np.abs(y[1:] - x[1:]).max() <= 0.5 * step * 1.01
        # fp8 keeps per-element exponents: neighbors stay accurate even
        # under the inflated chunk scale
        fmt8 = wirelib.make_wire_format("fp8", 64)
        q8, s8 = wirelib.quantize_slab(jnp.asarray(x), fmt8)
        y8 = np.asarray(wirelib.dequantize_slab(q8, s8, fmt8, jnp.float32))
        assert _rel_err(y8[1:], x[1:]) < 0.06

    def test_quantize_matches_ring_wire_bytes_model(self):
        from triton_distributed_tpu.tune.perf_model import ring_wire_bytes

        fmt = wirelib.make_wire_format("fp8", 128)
        assert ring_wire_bytes(128, 8192, 2, "fp8", fmt.chunk_rows) == \
            fmt.slab_bytes(128, 8192)
        assert ring_wire_bytes(128, 8192, 2, None) == 128 * 8192 * 2


# ------------------------------------------------ XLA ring wire engines

class TestWireOverlapEngines:
    """fp8/int8-wire AG-GEMM and GEMM-RS vs their bf16-wire twins, at
    pinned tolerances (the XLA ring engines ship the same lang.wire
    bytes as the fused kernels and run on any backend)."""

    def _ab(self, m, k, n, seed):
        a = jax.random.normal(jax.random.PRNGKey(seed), (m, k), jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(seed + 1), (k, n), jnp.float32)
        return a, b

    @pytest.mark.parametrize("w,tol", [("fp8", 0.06), ("int8", 0.02)])
    def test_ag_gemm_wire_accuracy(self, mesh8, w, tol):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a, b = self._ab(64, 1024, 128, 1)
        ref = ag_gemm(a, b, mesh8, "x", method=AGGemmMethod.XLA_RING)
        got = ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.XLA_RING, wire_dtype=w
        )
        assert _rel_err(got, ref) < tol

    @pytest.mark.parametrize("w,tol", [("fp8", 0.15), ("int8", 0.04)])
    def test_gemm_rs_wire_accuracy(self, mesh8, w, tol):
        from triton_distributed_tpu.kernels.gemm_rs import (
            GemmRSMethod,
            gemm_rs,
        )

        a, b = self._ab(64, 1024, 256, 3)
        ref = gemm_rs(a, b, mesh8, "x", method=GemmRSMethod.XLA_RING)
        got = gemm_rs(
            a, b, mesh8, "x", method=GemmRSMethod.XLA_RING, wire_dtype=w
        )
        assert _rel_err(got, ref) < tol

    def test_bf16_wire_is_todays_numerics(self, mesh8):
        """wire_dtype=None and 'bf16' are the identical program."""
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a, b = self._ab(64, 1024, 128, 5)
        x = ag_gemm(a, b, mesh8, "x", method=AGGemmMethod.XLA_RING)
        y = ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.XLA_RING, wire_dtype="bf16"
        )
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_explicit_wire_on_ineligible_slab_raises(self, mesh8):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        # 32 cols: the scale plane eats the compression — a pinned wire
        # format is a contract, so this must raise, not silently demote
        a, b = self._ab(64, 32, 128, 7)
        with pytest.raises(ValueError, match="wire"):
            ag_gemm(
                a, b, mesh8, "x", method=AGGemmMethod.XLA_RING,
                wire_dtype="fp8",
            )

    def test_auto_wire_demotes_to_none_on_ineligible(self, mesh8):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            resolve_ag_gemm_wire,
        )

        a, b = self._ab(64, 32, 128, 9)
        assert resolve_ag_gemm_wire(
            mesh8, "x", a, b, method=AGGemmMethod.XLA_RING, wire_dtype="auto"
        ) is None

    def test_naive_engine_never_ships_a_wire(self, mesh8):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            resolve_ag_gemm_wire,
        )

        a, b = self._ab(64, 1024, 128, 11)
        assert resolve_ag_gemm_wire(
            mesh8, "x", a, b, method=AGGemmMethod.XLA_NAIVE,
            wire_dtype="fp8",
        ) is None

    def test_overlap_ctx_wire_forward_only(self, mesh8):
        """ops.overlap threads ctx.wire_dtype into the forward; the
        VJP still runs (backward duals ship the bf16 wire)."""
        from triton_distributed_tpu.kernels.ag_gemm import AGGemmMethod
        from triton_distributed_tpu.ops.overlap import (
            ag_gemm,
            create_ag_gemm_context,
        )

        ctx = create_ag_gemm_context(
            mesh8, "x", method=AGGemmMethod.XLA_RING, wire_dtype="fp8",
        )
        a, b = self._ab(64, 1024, 128, 13)
        out, grads = jax.value_and_grad(
            lambda a, b: jnp.sum(ag_gemm(a, b, ctx) ** 2), argnums=(0, 1)
        )(a, b)
        assert np.isfinite(float(out))
        assert all(np.isfinite(np.asarray(g)).all() for g in grads)


# ------------------------------------------------ int8→MXU consumer wire

class TestInt8MXU:
    """ISSUE 5 acceptance: the dequant-free 'int8-mxu' wire — identical
    int8 rails, consumed by an s8×s8→s32 matmul with the chunk·channel
    scales folded in the accumulator epilogue. Pinned here: tolerance
    against the dequant-then-matmul twin (incl. the outlier-slab worst
    case), knob plumbing, the jaxpr proof that no per-arrival dequant
    pass exists in the traced fused kernel, and the auto-selection
    contract (int8-mxu on the comm-bound wq=int8 config, bf16 on the
    north-star)."""

    def _ab(self, m, k, n, seed):
        a = jax.random.normal(jax.random.PRNGKey(seed), (m, k), jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(seed + 1), (k, n), jnp.float32)
        return a, b

    def test_normalize_and_payload(self):
        assert wirelib.normalize_wire("int8-mxu") == "int8-mxu"
        assert wirelib.wire_payload("int8-mxu") == "int8"
        assert wirelib.wire_payload("fp8") == "fp8"
        assert wirelib.wire_payload(None) is None

    def test_quantize_cols_roundtrip(self):
        b = jax.random.normal(jax.random.PRNGKey(3), (256, 128), jnp.float32)
        bq, bs = wirelib.quantize_cols(b)
        assert bq.dtype == jnp.int8 and bs.shape == (1, 128)
        assert _rel_err(bq.astype(jnp.float32) * bs, b) < 0.02

    def test_ag_gemm_int8_mxu_accuracy(self, mesh8):
        """Output within pinned tolerance of BOTH the exact result and
        the dequant-then-matmul twin on the same wire (the twin gap is
        pure per-channel weight-quant error, ≲1/127 per element)."""
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a, b = self._ab(64, 1024, 128, 21)
        ref = ag_gemm(a, b, mesh8, "x", method=AGGemmMethod.XLA_RING)
        mx = ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.XLA_RING,
            wire_dtype="int8-mxu",
        )
        twin = ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.XLA_RING,
            wire_dtype="int8",
        )
        assert _rel_err(mx, ref) < 0.04
        assert _rel_err(mx, np.asarray(twin)) < 0.03

    def test_outlier_slab_worst_case_vs_twin(self, mesh8):
        """One huge activation row inflates its chunk scale identically
        for both int8 consumers — the epilogue fold must not amplify
        the documented per-chunk-scale worst case beyond the twin's."""
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a = np.random.default_rng(5).normal(size=(64, 1024)).astype(np.float32)
        a[0, :] *= 1000.0                       # the outlier row
        a = jnp.asarray(a)
        b = jax.random.normal(jax.random.PRNGKey(6), (1024, 128), jnp.float32)
        mx = ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.XLA_RING,
            wire_dtype="int8-mxu",
        )
        twin = ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.XLA_RING,
            wire_dtype="int8",
        )
        assert np.isfinite(np.asarray(mx)).all()
        assert _rel_err(mx, np.asarray(twin)) < 0.03

    def test_explicit_on_ineligible_slab_raises(self, mesh8):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a, b = self._ab(64, 32, 128, 23)   # scale plane eats compression
        with pytest.raises(ValueError, match="wire"):
            ag_gemm(
                a, b, mesh8, "x", method=AGGemmMethod.XLA_RING,
                wire_dtype="int8-mxu",
            )

    def test_resolve_explicit_and_auto_wq(self, mesh8):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            resolve_ag_gemm_wire,
        )

        a, b = self._ab(64, 1024, 128, 25)
        assert resolve_ag_gemm_wire(
            mesh8, "x", a, b, method=AGGemmMethod.XLA_RING,
            wire_dtype="int8-mxu",
        ) == "int8-mxu"
        # auto + declared int8 weight intent on a comm-bound shard
        assert resolve_ag_gemm_wire(
            mesh8, "x", a, b, method=AGGemmMethod.XLA_RING,
            wire_dtype="auto", wq="int8",
        ) == "int8-mxu"
        # auto without the intent never silently picks int8 numerics
        assert resolve_ag_gemm_wire(
            mesh8, "x", a, b, method=AGGemmMethod.XLA_RING,
            wire_dtype="auto",
        ) in (None, "fp8")

    def test_toolchain_gate_demotes_auto_and_refuses_pinned(
        self, mesh8, monkeypatch
    ):
        """TDTPU_WIRE_INT8_MXU=0: auto+wq demotes to the
        dequant-then-matmul int8 wire on the fused engine (not a
        numerics-class switch — the caller declared int8); an explicit
        pinned 'int8-mxu' refuses with the canonical diagnostic."""
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            resolve_ag_gemm_wire,
        )

        monkeypatch.setenv("TDTPU_WIRE_INT8_MXU", "0")
        a, b = self._ab(64, 1024, 128, 27)
        assert resolve_ag_gemm_wire(
            mesh8, "x", a, b, method=AGGemmMethod.PALLAS_FUSED,
            wire_dtype="auto", wq="int8",
        ) == "int8"
        with pytest.raises(ValueError, match="in-kernel s8"):
            resolve_ag_gemm_wire(
                mesh8, "x", a, b, method=AGGemmMethod.PALLAS_FUSED,
                wire_dtype="int8-mxu",
            )

    def test_wire_tuner_mxu_candidates(self):
        from triton_distributed_tpu.tune.autotuner import wire_tuner

        t = wire_tuner("t", lambda *a, **k: None, mxu=True)
        assert {"wire_dtype": "int8-mxu"} in t.configs
        t2 = wire_tuner("t2", lambda *a, **k: None)
        assert {"wire_dtype": "int8-mxu"} not in t2.configs

    def test_perf_model_projects_the_win(self):
        """Acceptance: the perf model projects int8→MXU as a per-step
        win on the comm-bound bench config (skipped dequant pass + the
        s8×s8 MXU rate), and auto picks it exactly there."""
        from triton_distributed_tpu.tune.perf_model import (
            TPU_SPECS,
            auto_wire_dtype,
            dequant_pass_ms,
            int8_mxu_step_ratio,
        )

        spec = TPU_SPECS["v5e"]
        assert int8_mxu_step_ratio(128, 8192, 512, spec) > 1.0
        assert dequant_pass_ms(128, 8192, 2, spec) > 0.0
        assert auto_wire_dtype(
            128, 8192, 512, 2, spec=spec, consumer_wq="int8"
        ) == "int8-mxu"
        # the north-star prefill shard stays on the exact wire
        assert auto_wire_dtype(
            1024, 8192, 3584, 2, spec=spec, consumer_wq="int8"
        ) == "bf16"
        # no declared intent → fp8, as before
        assert auto_wire_dtype(128, 8192, 512, 2, spec=spec) == "fp8"

    def test_fused_kernel_jaxpr_has_no_dequant_pass(self):
        """THE acceptance assertion: the traced int8-mxu fused kernel
        contains an s8×s8→s32 dot and NO int8→float convert (the
        signature of a per-arrival dequant pass) — the wire provably
        ends at the MXU. The dequant twin is the positive control."""
        from triton_distributed_tpu.analysis import mosaic_compat
        from triton_distributed_tpu.kernels.registry import families

        kjs = mosaic_compat.trace_family_kernels(
            families()["ag_gemm.fused_int8mxw"], 4
        )
        assert kjs
        casts, s8_dots = [], 0
        for kj in kjs:
            casts += mosaic_compat.i8_to_float_casts(kj)
            for eqn in mosaic_compat._walk_jaxprs(kj):
                if eqn.primitive.name != "dot_general":
                    continue
                dts = [str(v.aval.dtype) for v in eqn.invars[:2]]
                if dts == ["int8", "int8"]:
                    s8_dots += 1
                    assert "int32" in str(eqn.outvars[0].aval.dtype)
        assert s8_dots >= 1
        assert casts == [], casts
        # positive control: the grouped int8-mxu family likewise
        kjs = mosaic_compat.trace_family_kernels(
            families()["moe_tp.ag_group_gemm_int8mxw"], 4
        )
        assert all(
            mosaic_compat.i8_to_float_casts(kj) == [] for kj in kjs
        )

    def test_mc004_flags_f32_accumulate_of_int8(self):
        """The deny-list leg: an s8 dot asking for a float accumulator
        is MC004 (what this Mosaic actually rejects)."""
        import jax as _jax
        from triton_distributed_tpu.analysis import mosaic_compat

        def bad(aq, bq):
            return jax.lax.dot_general(
                aq, bq, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        jaxpr = _jax.make_jaxpr(bad)(
            jnp.zeros((8, 128), jnp.int8), jnp.zeros((128, 64), jnp.int8)
        )
        f = mosaic_compat.scan_kernel_jaxpr(jaxpr.jaxpr, "fixture")
        assert [x.rule for x in f] == ["MC004"]

    def test_moe_tp_context_int8_mxu_builds(self, mesh8):
        """Knob plumbing: MoETPContext(wire_dtype='int8-mxu') reaches
        the grouped epilogue consumer's builder (the fused engines
        themselves need the TPU-sim interpreter; their protocol twin is
        the registry family)."""
        from triton_distributed_tpu.kernels.moe_tp_fused import (
            build_ag_group_gemm_call,
            pick_gg_blocks,
        )

        blocks = pick_gg_blocks(8, 16, 128, 128, 4)
        call = build_ag_group_gemm_call(
            8, ("x",), "x", 16, 128, 128, 2, blocks,
            jnp.dtype(jnp.float32), 13, wire="int8-mxu",
        )
        assert call is not None


# --------------------------------------------- standalone ring wire knobs

class TestStandaloneWire:
    def test_all_gather_wire_fp8(self, mesh8):
        from triton_distributed_tpu.kernels.allgather import all_gather

        x = jax.random.normal(jax.random.PRNGKey(0), (64, 1024), jnp.float32)
        got = all_gather(x, mesh8, "x", wire_dtype="fp8")
        assert got.shape == x.shape
        assert _rel_err(got, x) < 0.06

    def test_all_gather_wire_auto_small_stays_exact(self, mesh8):
        from triton_distributed_tpu.kernels.allgather import all_gather

        # 32 KiB shards sit under the auto threshold → bf16 wire, exact
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 1024), jnp.float32)
        got = all_gather(x, mesh8, "x", wire_dtype="auto")
        np.testing.assert_array_equal(np.asarray(got), np.asarray(x))

    def test_all_gather_explicit_wire_on_1d_raises(self, mesh8):
        from triton_distributed_tpu.kernels.allgather import all_gather

        with pytest.raises(ValueError, match="wire"):
            all_gather(jnp.zeros((64,)), mesh8, "x", wire_dtype="fp8")

    @pytest.mark.parametrize("w,tol", [("fp8", 0.15), ("int8", 0.04)])
    def test_reduce_scatter_wire(self, mesh8, w, tol):
        from triton_distributed_tpu.kernels.reduce_scatter import (
            reduce_scatter,
        )

        y = jax.random.normal(
            jax.random.PRNGKey(2), (8, 64, 1024), jnp.float32
        )
        ref = np.asarray(y).sum(0)
        got = reduce_scatter(y, mesh8, "x", stacked=True, wire_dtype=w)
        assert got.shape == ref.shape
        assert _rel_err(got, ref) < tol

    def test_reduce_scatter_bf16_wire_unchanged(self, mesh8):
        from triton_distributed_tpu.kernels.reduce_scatter import (
            reduce_scatter,
        )

        y = jax.random.normal(jax.random.PRNGKey(3), (8, 16, 64), jnp.float32)
        a = reduce_scatter(y, mesh8, "x", stacked=True)
        b = reduce_scatter(y, mesh8, "x", stacked=True, wire_dtype="bf16")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------- streaming-RS wire (round 8)

class TestStreamRSWire:
    """The last bf16 leg of the standalone RS family: rs_ring_stream's
    quantized wire. The Pallas streaming engine needs the TPU-sim
    interpreter (its protocol twin is the reduce_scatter.stream_int8w
    registry family in test_analysis.py); what runs on any backend here
    is the entry routing, the builder, and the byte-identical XLA-twin
    numerics."""

    def test_stream_wire_builder_constructs(self, mesh8):
        from triton_distributed_tpu.kernels.reduce_scatter import (
            _build_rs_stream_w,
        )

        fn = _build_rs_stream_w(
            mesh8, "x", 64, 2048, jnp.dtype(jnp.float32), True, 3,
            ("test", 0), "int8",
        )
        assert fn is not None

    def test_resolve_maps_int8_mxu_to_payload(self):
        from triton_distributed_tpu.kernels.reduce_scatter import (
            _resolve_rs_wire,
        )

        # a reduce ring has no MXU consumer: the epilogue wire carries
        # its int8 payload
        assert _resolve_rs_wire("int8-mxu", 64, 2048, 8, 4) == "int8"

    @pytest.mark.parametrize("w,tol", [("fp8", 0.15), ("int8", 0.04)])
    def test_streaming_scale_payload_accuracy(self, mesh8, w, tol):
        """A payload sized past the VMEM ring: off-TPU the entry
        degrades to the XLA twin carrying the same wire; the reduction
        stays within the pinned RS tolerances."""
        from triton_distributed_tpu.kernels.reduce_scatter import (
            reduce_scatter,
        )

        y = jax.random.normal(
            jax.random.PRNGKey(8), (8, 256, 2048), jnp.float32
        )
        ref = np.asarray(y).sum(0)
        got = reduce_scatter(y, mesh8, "x", stacked=True, wire_dtype=w)
        assert got.shape == ref.shape
        assert _rel_err(got, ref) < tol


# ------------------------------------------------ DCN rail wire (round 8)

class TestDCNRailWire:
    """The hierarchical engines' DCN rail legs — the slowest transport
    in the system — now ship the quantized payload + scale planes
    (runtime.multislice.dcn_wire_*). The rail machinery is
    link-agnostic, so the 2×4 CPU mesh exercises the exact multi-slice
    numerics."""

    def _ab(self, m, k, n, seed):
        a = jax.random.normal(jax.random.PRNGKey(seed), (m, k), jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(seed + 1), (k, n), jnp.float32)
        return a, b

    def test_hier_ag_gemm_rail_wire_accuracy(self, mesh2x4):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a, b = self._ab(64, 1024, 128, 31)
        ref = ag_gemm(
            a, b, mesh2x4, "tp", dcn_axis="dp",
            method=AGGemmMethod.XLA_RING,
        )
        got = ag_gemm(
            a, b, mesh2x4, "tp", dcn_axis="dp",
            method=AGGemmMethod.XLA_RING, wire_dtype="fp8",
        )
        assert _rel_err(got, np.asarray(ref)) < 0.08

    def test_hier_gemm_rs_rail_wire_accuracy(self, mesh2x4):
        from triton_distributed_tpu.kernels.gemm_rs import (
            GemmRSMethod,
            gemm_rs,
        )

        a, b = self._ab(64, 1024, 256, 33)
        ref = gemm_rs(
            a, b, mesh2x4, "tp", dcn_axis="dp",
            method=GemmRSMethod.XLA_RING,
        )
        got = gemm_rs(
            a, b, mesh2x4, "tp", dcn_axis="dp",
            method=GemmRSMethod.XLA_RING, wire_dtype="int8",
        )
        assert _rel_err(got, np.asarray(ref)) < 0.06

    def test_resolve_hier_returns_rail_payload(self, mesh2x4):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            resolve_ag_gemm_wire,
        )

        a, b = self._ab(64, 1024, 128, 35)
        # explicit wires resolve to the rail payload; int8-mxu demotes
        # to int8 (the rail dequantizes before any MXU)
        assert resolve_ag_gemm_wire(
            mesh2x4, "tp", a, b, method=AGGemmMethod.XLA_RING,
            wire_dtype="int8-mxu", dcn_axis="dp",
        ) == "int8"
        assert resolve_ag_gemm_wire(
            mesh2x4, "tp", a, b, method=AGGemmMethod.XLA_RING,
            wire_dtype="fp8", dcn_axis="dp",
        ) == "fp8"

    def test_auto_rail_wire_compresses_big_payloads_only(self, mesh2x4):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            resolve_ag_gemm_wire,
        )

        big_a, big_b = self._ab(512, 2048, 128, 37)
        assert resolve_ag_gemm_wire(
            mesh2x4, "tp", big_a, big_b, method=AGGemmMethod.XLA_RING,
            wire_dtype="auto", dcn_axis="dp",
        ) == "fp8"
        small_a, small_b = self._ab(64, 256, 128, 39)
        assert resolve_ag_gemm_wire(
            mesh2x4, "tp", small_a, small_b, method=AGGemmMethod.XLA_RING,
            wire_dtype="auto", dcn_axis="dp",
        ) is None

    def test_dcn_wire_reduce_scatter_helper(self, mesh8):
        """The shared rail body (also the gemm_rs degradation twin's
        ring): per-hop quantized ppermute reduce over any axis."""
        from jax.sharding import PartitionSpec as P

        from triton_distributed_tpu.runtime.multislice import (
            dcn_wire_reduce_scatter,
        )

        fmt = wirelib.make_wire_format("int8", 8)
        x = jax.random.normal(jax.random.PRNGKey(9), (64, 256), jnp.float32)

        fn = jax.shard_map(
            lambda s: dcn_wire_reduce_scatter(s, "x", 8, fmt),
            mesh=mesh8, in_specs=P(None), out_specs=P("x"),
            check_vma=False,
        )
        got = np.asarray(jax.jit(fn)(x))
        ref = np.asarray(x) * 8
        assert _rel_err(got, ref) < 0.04


# ------------------------------------------------------ wire auto-selection

class TestWireSelection:
    def test_perf_model_comm_bound_picks_fp8(self):
        from triton_distributed_tpu.tune.perf_model import (
            TPU_SPECS,
            auto_wire_dtype,
        )

        spec = TPU_SPECS["v5e"]
        # decode-side small-M small-N shard: the A-slab ring transfer
        # dwarfs the per-step matmul → compressed wire
        assert auto_wire_dtype(128, 8192, 512, 2, spec=spec) == "fp8"
        # the north-star prefill shard is flops-bound → raw wire
        assert auto_wire_dtype(1024, 8192, 3584, 2, spec=spec) == "bf16"

    def test_topology_standalone_threshold(self):
        from triton_distributed_tpu.runtime.topology import (
            auto_allgather_wire,
        )

        assert auto_allgather_wire(1 << 20) == "fp8"
        assert auto_allgather_wire(1 << 12) is None

    def test_engine_tuner_keys_include_wire(self, mesh8):
        """Persisted engine winners must be per-wire-format: the tuner
        name (the disk key namespace) carries the wire."""
        from triton_distributed_tpu.kernels.ag_gemm import _engine_tuner

        t_raw = _engine_tuner(mesh8, "x", (), jnp.dtype(jnp.float32), 5,
                              False, None, None)
        t_fp8 = _engine_tuner(mesh8, "x", (), jnp.dtype(jnp.float32), 5,
                              False, None, "fp8")
        assert t_raw.name != t_fp8.name and "wfp8" in t_fp8.name

    def test_wire_tuner_candidates(self):
        from triton_distributed_tpu.tune.autotuner import wire_tuner

        t = wire_tuner("t", lambda *a, **k: None)
        assert t.configs == [
            {"wire_dtype": "bf16"}, {"wire_dtype": "fp8"}
        ]


# ------------------------------------------------- collective-id rails

class TestCollectiveRails:
    def test_shipped_rails_match_the_historical_offsets(self):
        from triton_distributed_tpu.kernels.registry import (
            rail_collective_id,
            reserved_rails,
        )

        rails = reserved_rails()
        assert rails["ag_gemm.dcn_chunks"] == (64, 32)
        assert rails["gemm_rs.dcn_chunks"] == (96, 32)
        # the ledger arithmetic reproduces the old ad-hoc ids exactly
        assert rail_collective_id("ag_gemm.dcn_chunks", 5, 3) == 5 + 64 + 3
        assert rail_collective_id("gemm_rs.dcn_chunks", 6, 2) == 6 + 96 + 2
        assert rail_collective_id("gemm_rs.dcn_chunks", None, 0) is None

    def test_overlapping_reservation_raises(self):
        from triton_distributed_tpu.kernels import registry

        with pytest.raises(ValueError, match="overlaps"):
            registry.reserve_collective_rail("rogue.family", 90, 16)
        assert "rogue.family" not in registry.reserved_rails()

    def test_out_of_range_chunk_raises(self):
        from triton_distributed_tpu.kernels.registry import (
            rail_collective_id,
        )

        with pytest.raises(ValueError, match="reserved length"):
            rail_collective_id("ag_gemm.dcn_chunks", 5, 32)

    def test_re_reservation_same_range_is_idempotent(self):
        from triton_distributed_tpu.kernels import registry

        registry.reserve_collective_rail("ag_gemm.dcn_chunks", 64, 32)
        with pytest.raises(ValueError, match="re-reserved"):
            registry.reserve_collective_rail("ag_gemm.dcn_chunks", 64, 16)


# ---------------------------------------------- fused engines (TPU sim)

@requires_tpu_sim
class TestFusedWireEngines:
    """The fused Pallas wire rings, executed on the interpreter mesh
    (skipped on a jax without the TPU-simulation interpreter — the
    static protocol twin lives in test_analysis.py)."""

    @pytest.mark.parametrize("w,tol", [("fp8", 0.06), ("int8", 0.02)])
    def test_fused_ag_gemm_wire(self, mesh8, w, tol):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a = jax.random.normal(jax.random.PRNGKey(1), (64, 1024), jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(2), (1024, 128), jnp.float32)
        ref = np.asarray(jnp.dot(a, b))
        got = ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.PALLAS_FUSED, wire_dtype=w
        )
        assert _rel_err(got, ref) < tol

    @pytest.mark.parametrize("w,tol", [("fp8", 0.15), ("int8", 0.04)])
    def test_fused_gemm_rs_wire(self, mesh8, w, tol):
        from triton_distributed_tpu.kernels.gemm_rs import (
            GemmRSMethod,
            gemm_rs,
        )

        a = jax.random.normal(jax.random.PRNGKey(3), (64, 1024), jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(4), (1024, 256), jnp.float32)
        ref = np.asarray(jnp.dot(a, b))
        got = gemm_rs(
            a, b, mesh8, "x", method=GemmRSMethod.PALLAS_FUSED, wire_dtype=w
        )
        assert _rel_err(got, ref) < tol

    def test_fused_ring_ag_standalone_wire(self, mesh8):
        from triton_distributed_tpu.kernels.allgather import all_gather
        from triton_distributed_tpu.runtime import AllGatherMethod

        x = jax.random.normal(jax.random.PRNGKey(5), (64, 1024), jnp.float32)
        got = all_gather(
            x, mesh8, "x", method=AllGatherMethod.RING_1D, wire_dtype="fp8"
        )
        assert _rel_err(got, x) < 0.06


class TestWeightResidency:
    """Pre-quantized weight residency for the int8-mxu consumers
    (ROADMAP carried-forward, closed by PR 6): serving layers holding
    quantize_grouped_weights-style dicts pass the (bq, bs) pair
    through — NO per-call quantize_cols of B — and ineligible calls
    widen once and degrade cleanly."""

    def _ab(self):
        a = jax.random.normal(jax.random.PRNGKey(31), (512, 256),
                              jnp.bfloat16)
        b = jax.random.normal(jax.random.PRNGKey(32), (256, 512),
                              jnp.bfloat16)
        return a, b

    def test_resident_pair_matches_per_call_quantization(self, mesh8):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a, b = self._ab()
        ref = np.asarray(ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.XLA_RING,
            wire_dtype="int8-mxu",
        ), np.float32)
        bq, bs = wirelib.quantize_cols(b)
        got = np.asarray(ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.XLA_RING,
            b_quant=(bq, bs),
        ), np.float32)
        got_dict = np.asarray(ag_gemm(
            a, {"q": bq, "scale": bs[0]}, mesh8, "x",
            method=AGGemmMethod.XLA_RING,
        ), np.float32)
        np.testing.assert_array_equal(got, ref)
        np.testing.assert_array_equal(got_dict, ref)

    def test_resident_path_never_requantizes_b(self, mesh8, monkeypatch):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a, b = self._ab()
        bq, bs = wirelib.quantize_cols(b)
        calls = {"n": 0}
        orig = wirelib.quantize_cols

        def counting(x):
            calls["n"] += 1
            return orig(x)

        monkeypatch.setattr(wirelib, "quantize_cols", counting)
        ag_gemm(a, b, mesh8, "x", method=AGGemmMethod.XLA_RING,
                b_quant=(bq, bs))
        assert calls["n"] == 0

    def test_ineligible_call_widens_and_degrades(self):
        """1-device mesh: the resident pair cannot ride a wire — B is
        widened once and the plain dot runs, within weight-quant
        error of the dense result."""
        from jax.sharding import Mesh

        from triton_distributed_tpu.kernels.ag_gemm import ag_gemm

        mesh1 = Mesh(np.asarray(jax.devices()[:1]), ("x",))
        a, b = self._ab()
        bq, bs = wirelib.quantize_cols(b)
        ref = np.asarray(ag_gemm(a, b, mesh1, "x"), np.float32)
        got = np.asarray(
            ag_gemm(a, b, mesh1, "x", b_quant=(bq, bs)), np.float32
        )
        assert _rel_err(jnp.asarray(got), jnp.asarray(ref)) < 0.02
