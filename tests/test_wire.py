"""Quantized-wire streaming rings (ISSUE 3): lang.wire layout, the
XLA-ring wire twins (byte-identical layout to the fused Pallas wire),
the standalone collectives' wire knobs, the perf-model/topology wire
auto-selection, and the collective-id rail ledger.

Accuracy tolerances are PINNED here (the acceptance contract):

* fp8 (e4m3) wire: one rounding per element ≤ 2^-3 relative → AG-side
  (quantize once) max error ≤ 6% of the output scale; RS-side (per-hop
  requant over n-1 hops) ≤ 15%.
* int8 wire with per-chunk scales: ≤ 2% AG-side / 4% RS-side on
  well-conditioned slabs; the worst-case OUTLIER slab test pins the
  known failure mode (one huge row inflates the chunk scale and
  flattens its neighbors) so the guidance in docs/PERF.md stays honest.

The fused Pallas wire engines themselves need the TPU-simulation
interpreter (skipped without it); their protocol is checked statically
for every jax by the registry families in test_analysis.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import requires_tpu_sim

from triton_distributed_tpu.lang import wire as wirelib

#: tier-1 fast subset (ci/fast.sh): XLA wire twins and layout math
pytestmark = pytest.mark.fast


def _rel_err(got, ref):
    ref = np.asarray(ref, np.float64)
    got = np.asarray(got, np.float64)
    scale = np.abs(ref).max() or 1.0
    return float(np.abs(got - ref).max() / scale)


# ------------------------------------------------------------- the layout

class TestWireFormat:
    def test_normalize(self):
        assert wirelib.normalize_wire(None) is None
        assert wirelib.normalize_wire("bf16") is None
        assert wirelib.normalize_wire("fp8") == "fp8"
        assert wirelib.normalize_wire("int8") == "int8"
        assert wirelib.normalize_wire("auto") == "auto"
        with pytest.raises(ValueError):
            wirelib.normalize_wire("fp4")

    def test_chunking_and_bytes(self):
        fmt = wirelib.make_wire_format("fp8", 128)
        assert fmt.chunk_rows == 64 and fmt.chunks(128) == 2
        # payload at 1 B/elem + one (128·4 B) scale row per chunk
        assert fmt.slab_bytes(128, 8192) == 128 * 8192 + 2 * 512
        # vs the bf16 wire: the acceptance ratio at ring-slab scale
        assert 128 * 8192 * 2 / fmt.slab_bytes(128, 8192) > 1.8

    def test_whole_slab_chunk_for_tiny_slabs(self):
        fmt = wirelib.make_wire_format("int8", 16)
        assert fmt.chunk_rows == 16 and fmt.chunks(16) == 1

    def test_wire_blockable_rejects_tiny_slabs(self):
        # an 8×32 slab: the 512 B scale row eats the compression → must
        # be rejected, not shipped larger than the bf16 wire
        assert not wirelib.wire_blockable(8, 32, "fp8", strict=False)
        assert wirelib.wire_blockable(64, 2048, "fp8", strict=False)

    @pytest.mark.parametrize("quant", ["fp8", "int8"])
    def test_roundtrip_tolerance(self, quant):
        fmt = wirelib.make_wire_format(quant, 128)
        x = jax.random.normal(jax.random.PRNGKey(0), (128, 1024), jnp.float32)
        q, s = wirelib.quantize_slab(x, fmt)
        assert q.dtype == fmt.wire_dtype
        assert s.shape == fmt.scale_shape(128)
        y = wirelib.dequantize_slab(q, s, fmt, jnp.float32)
        tol = 0.06 if quant == "fp8" else 0.02
        assert _rel_err(y, x) < tol

    def test_outlier_slab_worst_case(self):
        """One huge row per chunk inflates the shared scale: int8 must
        still round-trip the OUTLIER exactly-ish while its neighbors
        degrade gracefully (bounded by outlier/127 per element) — the
        documented worst case of per-chunk scales."""
        fmt = wirelib.make_wire_format("int8", 64)
        x = np.random.default_rng(1).normal(size=(64, 512)).astype(np.float32)
        x[0, :] *= 1000.0                       # the outlier row
        q, s = wirelib.quantize_slab(jnp.asarray(x), fmt)
        y = np.asarray(wirelib.dequantize_slab(q, s, fmt, jnp.float32))
        # outlier row: ~2 valid digits survive
        assert _rel_err(y[0], x[0]) < 0.01
        # neighbor rows: absolute error bounded by half a quantization
        # step of the inflated scale
        step = float(np.asarray(s)[0, 0])
        assert np.abs(y[1:] - x[1:]).max() <= 0.5 * step * 1.01
        # fp8 keeps per-element exponents: neighbors stay accurate even
        # under the inflated chunk scale
        fmt8 = wirelib.make_wire_format("fp8", 64)
        q8, s8 = wirelib.quantize_slab(jnp.asarray(x), fmt8)
        y8 = np.asarray(wirelib.dequantize_slab(q8, s8, fmt8, jnp.float32))
        assert _rel_err(y8[1:], x[1:]) < 0.06

    def test_quantize_matches_ring_wire_bytes_model(self):
        from triton_distributed_tpu.tune.perf_model import ring_wire_bytes

        fmt = wirelib.make_wire_format("fp8", 128)
        assert ring_wire_bytes(128, 8192, 2, "fp8", fmt.chunk_rows) == \
            fmt.slab_bytes(128, 8192)
        assert ring_wire_bytes(128, 8192, 2, None) == 128 * 8192 * 2


# ------------------------------------------------ XLA ring wire engines

class TestWireOverlapEngines:
    """fp8/int8-wire AG-GEMM and GEMM-RS vs their bf16-wire twins, at
    pinned tolerances (the XLA ring engines ship the same lang.wire
    bytes as the fused kernels and run on any backend)."""

    def _ab(self, m, k, n, seed):
        a = jax.random.normal(jax.random.PRNGKey(seed), (m, k), jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(seed + 1), (k, n), jnp.float32)
        return a, b

    @pytest.mark.parametrize("w,tol", [("fp8", 0.06), ("int8", 0.02)])
    def test_ag_gemm_wire_accuracy(self, mesh8, w, tol):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a, b = self._ab(64, 1024, 128, 1)
        ref = ag_gemm(a, b, mesh8, "x", method=AGGemmMethod.XLA_RING)
        got = ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.XLA_RING, wire_dtype=w
        )
        assert _rel_err(got, ref) < tol

    @pytest.mark.parametrize("w,tol", [("fp8", 0.15), ("int8", 0.04)])
    def test_gemm_rs_wire_accuracy(self, mesh8, w, tol):
        from triton_distributed_tpu.kernels.gemm_rs import (
            GemmRSMethod,
            gemm_rs,
        )

        a, b = self._ab(64, 1024, 256, 3)
        ref = gemm_rs(a, b, mesh8, "x", method=GemmRSMethod.XLA_RING)
        got = gemm_rs(
            a, b, mesh8, "x", method=GemmRSMethod.XLA_RING, wire_dtype=w
        )
        assert _rel_err(got, ref) < tol

    def test_bf16_wire_is_todays_numerics(self, mesh8):
        """wire_dtype=None and 'bf16' are the identical program."""
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a, b = self._ab(64, 1024, 128, 5)
        x = ag_gemm(a, b, mesh8, "x", method=AGGemmMethod.XLA_RING)
        y = ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.XLA_RING, wire_dtype="bf16"
        )
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_explicit_wire_on_ineligible_slab_raises(self, mesh8):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        # 32 cols: the scale plane eats the compression — a pinned wire
        # format is a contract, so this must raise, not silently demote
        a, b = self._ab(64, 32, 128, 7)
        with pytest.raises(ValueError, match="wire"):
            ag_gemm(
                a, b, mesh8, "x", method=AGGemmMethod.XLA_RING,
                wire_dtype="fp8",
            )

    def test_auto_wire_demotes_to_none_on_ineligible(self, mesh8):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            resolve_ag_gemm_wire,
        )

        a, b = self._ab(64, 32, 128, 9)
        assert resolve_ag_gemm_wire(
            mesh8, "x", a, b, method=AGGemmMethod.XLA_RING, wire_dtype="auto"
        ) is None

    def test_naive_engine_never_ships_a_wire(self, mesh8):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            resolve_ag_gemm_wire,
        )

        a, b = self._ab(64, 1024, 128, 11)
        assert resolve_ag_gemm_wire(
            mesh8, "x", a, b, method=AGGemmMethod.XLA_NAIVE,
            wire_dtype="fp8",
        ) is None

    def test_overlap_ctx_wire_forward_only(self, mesh8):
        """ops.overlap threads ctx.wire_dtype into the forward; the
        VJP still runs (backward duals ship the bf16 wire)."""
        from triton_distributed_tpu.kernels.ag_gemm import AGGemmMethod
        from triton_distributed_tpu.ops.overlap import (
            ag_gemm,
            create_ag_gemm_context,
        )

        ctx = create_ag_gemm_context(
            mesh8, "x", method=AGGemmMethod.XLA_RING, wire_dtype="fp8",
        )
        a, b = self._ab(64, 1024, 128, 13)
        out, grads = jax.value_and_grad(
            lambda a, b: jnp.sum(ag_gemm(a, b, ctx) ** 2), argnums=(0, 1)
        )(a, b)
        assert np.isfinite(float(out))
        assert all(np.isfinite(np.asarray(g)).all() for g in grads)


# --------------------------------------------- standalone ring wire knobs

class TestStandaloneWire:
    def test_all_gather_wire_fp8(self, mesh8):
        from triton_distributed_tpu.kernels.allgather import all_gather

        x = jax.random.normal(jax.random.PRNGKey(0), (64, 1024), jnp.float32)
        got = all_gather(x, mesh8, "x", wire_dtype="fp8")
        assert got.shape == x.shape
        assert _rel_err(got, x) < 0.06

    def test_all_gather_wire_auto_small_stays_exact(self, mesh8):
        from triton_distributed_tpu.kernels.allgather import all_gather

        # 32 KiB shards sit under the auto threshold → bf16 wire, exact
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 1024), jnp.float32)
        got = all_gather(x, mesh8, "x", wire_dtype="auto")
        np.testing.assert_array_equal(np.asarray(got), np.asarray(x))

    def test_all_gather_explicit_wire_on_1d_raises(self, mesh8):
        from triton_distributed_tpu.kernels.allgather import all_gather

        with pytest.raises(ValueError, match="wire"):
            all_gather(jnp.zeros((64,)), mesh8, "x", wire_dtype="fp8")

    @pytest.mark.parametrize("w,tol", [("fp8", 0.15), ("int8", 0.04)])
    def test_reduce_scatter_wire(self, mesh8, w, tol):
        from triton_distributed_tpu.kernels.reduce_scatter import (
            reduce_scatter,
        )

        y = jax.random.normal(
            jax.random.PRNGKey(2), (8, 64, 1024), jnp.float32
        )
        ref = np.asarray(y).sum(0)
        got = reduce_scatter(y, mesh8, "x", stacked=True, wire_dtype=w)
        assert got.shape == ref.shape
        assert _rel_err(got, ref) < tol

    def test_reduce_scatter_bf16_wire_unchanged(self, mesh8):
        from triton_distributed_tpu.kernels.reduce_scatter import (
            reduce_scatter,
        )

        y = jax.random.normal(jax.random.PRNGKey(3), (8, 16, 64), jnp.float32)
        a = reduce_scatter(y, mesh8, "x", stacked=True)
        b = reduce_scatter(y, mesh8, "x", stacked=True, wire_dtype="bf16")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------ wire auto-selection

class TestWireSelection:
    def test_perf_model_comm_bound_picks_fp8(self):
        from triton_distributed_tpu.tune.perf_model import (
            TPU_SPECS,
            auto_wire_dtype,
        )

        spec = TPU_SPECS["v5e"]
        # decode-side small-M small-N shard: the A-slab ring transfer
        # dwarfs the per-step matmul → compressed wire
        assert auto_wire_dtype(128, 8192, 512, 2, spec=spec) == "fp8"
        # the north-star prefill shard is flops-bound → raw wire
        assert auto_wire_dtype(1024, 8192, 3584, 2, spec=spec) == "bf16"

    def test_topology_standalone_threshold(self):
        from triton_distributed_tpu.runtime.topology import (
            auto_allgather_wire,
        )

        assert auto_allgather_wire(1 << 20) == "fp8"
        assert auto_allgather_wire(1 << 12) is None

    def test_engine_tuner_keys_include_wire(self, mesh8):
        """Persisted engine winners must be per-wire-format: the tuner
        name (the disk key namespace) carries the wire."""
        from triton_distributed_tpu.kernels.ag_gemm import _engine_tuner

        t_raw = _engine_tuner(mesh8, "x", (), jnp.dtype(jnp.float32), 5,
                              False, None, None)
        t_fp8 = _engine_tuner(mesh8, "x", (), jnp.dtype(jnp.float32), 5,
                              False, None, "fp8")
        assert t_raw.name != t_fp8.name and "wfp8" in t_fp8.name

    def test_wire_tuner_candidates(self):
        from triton_distributed_tpu.tune.autotuner import wire_tuner

        t = wire_tuner("t", lambda *a, **k: None)
        assert t.configs == [
            {"wire_dtype": "bf16"}, {"wire_dtype": "fp8"}
        ]


# ------------------------------------------------- collective-id rails

class TestCollectiveRails:
    def test_shipped_rails_match_the_historical_offsets(self):
        from triton_distributed_tpu.kernels.registry import (
            rail_collective_id,
            reserved_rails,
        )

        rails = reserved_rails()
        assert rails["ag_gemm.dcn_chunks"] == (64, 32)
        assert rails["gemm_rs.dcn_chunks"] == (96, 32)
        # the ledger arithmetic reproduces the old ad-hoc ids exactly
        assert rail_collective_id("ag_gemm.dcn_chunks", 5, 3) == 5 + 64 + 3
        assert rail_collective_id("gemm_rs.dcn_chunks", 6, 2) == 6 + 96 + 2
        assert rail_collective_id("gemm_rs.dcn_chunks", None, 0) is None

    def test_overlapping_reservation_raises(self):
        from triton_distributed_tpu.kernels import registry

        with pytest.raises(ValueError, match="overlaps"):
            registry.reserve_collective_rail("rogue.family", 90, 16)
        assert "rogue.family" not in registry.reserved_rails()

    def test_out_of_range_chunk_raises(self):
        from triton_distributed_tpu.kernels.registry import (
            rail_collective_id,
        )

        with pytest.raises(ValueError, match="reserved length"):
            rail_collective_id("ag_gemm.dcn_chunks", 5, 32)

    def test_re_reservation_same_range_is_idempotent(self):
        from triton_distributed_tpu.kernels import registry

        registry.reserve_collective_rail("ag_gemm.dcn_chunks", 64, 32)
        with pytest.raises(ValueError, match="re-reserved"):
            registry.reserve_collective_rail("ag_gemm.dcn_chunks", 64, 16)


# ---------------------------------------------- fused engines (TPU sim)

@requires_tpu_sim
class TestFusedWireEngines:
    """The fused Pallas wire rings, executed on the interpreter mesh
    (skipped on a jax without the TPU-simulation interpreter — the
    static protocol twin lives in test_analysis.py)."""

    @pytest.mark.parametrize("w,tol", [("fp8", 0.06), ("int8", 0.02)])
    def test_fused_ag_gemm_wire(self, mesh8, w, tol):
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            ag_gemm,
        )

        a = jax.random.normal(jax.random.PRNGKey(1), (64, 1024), jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(2), (1024, 128), jnp.float32)
        ref = np.asarray(jnp.dot(a, b))
        got = ag_gemm(
            a, b, mesh8, "x", method=AGGemmMethod.PALLAS_FUSED, wire_dtype=w
        )
        assert _rel_err(got, ref) < tol

    @pytest.mark.parametrize("w,tol", [("fp8", 0.15), ("int8", 0.04)])
    def test_fused_gemm_rs_wire(self, mesh8, w, tol):
        from triton_distributed_tpu.kernels.gemm_rs import (
            GemmRSMethod,
            gemm_rs,
        )

        a = jax.random.normal(jax.random.PRNGKey(3), (64, 1024), jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(4), (1024, 256), jnp.float32)
        ref = np.asarray(jnp.dot(a, b))
        got = gemm_rs(
            a, b, mesh8, "x", method=GemmRSMethod.PALLAS_FUSED, wire_dtype=w
        )
        assert _rel_err(got, ref) < tol

    def test_fused_ring_ag_standalone_wire(self, mesh8):
        from triton_distributed_tpu.kernels.allgather import all_gather
        from triton_distributed_tpu.runtime import AllGatherMethod

        x = jax.random.normal(jax.random.PRNGKey(5), (64, 1024), jnp.float32)
        got = all_gather(
            x, mesh8, "x", method=AllGatherMethod.RING_1D, wire_dtype="fp8"
        )
        assert _rel_err(got, x) < 0.06
