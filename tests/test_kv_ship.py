"""Disaggregated prefill/decode serving: the KV-ship transport suite.

The ISSUE-7 satellite coverage, all sim-free (the transports under test
are XLA-side — the gather/scatter plumbing, the paired DCN ``ppermute``
rails, the device_put fallback — and the scheduling machinery is host
code; the Pallas ship kernel's correctness is pinned statically by the
``kv_ship.pages`` lint family in test_analysis.py):

* wire-layout round trip — int8 pages + per-row scale planes gathered,
  shipped and scattered BYTE-IDENTICALLY, across both the DCN rail and
  its XLA twin;
* in-flight-transfer vs eviction race — pages pinned by a mid-ship
  request are never eviction victims on either side;
* decode admission gating on SHIPPED pages (reserve → commit);
* 2×2 hybrid-mesh end-to-end token-exactness vs the colocated engine
  (int8 KV, tp=2 head sharding, evictions included);
* transport degradation onto ``tools.native.xla_kv_ship``;
* the perf model's `auto` placement refusal.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.serving import (
    DisaggregatedEngine,
    EngineConfig,
    Request,
    ServingEngine,
    poisson_trace,
)

pytestmark = pytest.mark.fast

CFG = dict(
    vocab=128, n_layers=2, hidden=64, ffn=128,
    n_heads=4, n_kv_heads=2, head_dim=16,
    dtype=jnp.float32, param_dtype=jnp.float32, kv_quant="int8",
)


def _mesh(devs, axes):
    return Mesh(np.asarray(devs), axes)


@pytest.fixture(scope="module")
def roles1():
    """One device per role + the 2×1 hybrid mesh."""
    devs = jax.devices()
    return (_mesh(devs[:1], ("tp",)), _mesh(devs[1:2], ("tp",)),
            Mesh(np.asarray(devs[:2]).reshape(2, 1), ("dcn", "tp")))


@pytest.fixture(scope="module")
def models1(roles1):
    mesh_p, mesh_d, _ = roles1
    mp = Transformer(TransformerConfig(**CFG), mesh_p, "tp", ())
    md = Transformer(TransformerConfig(**CFG), mesh_d, "tp", ())
    params = mp.init(jax.random.PRNGKey(0))
    pp = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                      mp.shardings())
    pd = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                      md.shardings())
    return mp, pp, md, pd


def _reference_tokens(model, params, req, cap=128):
    prompt = jnp.asarray(req.prompt)[None]
    caches = model.init_cache(1, cap)
    last, caches, lens = model.prefill(params, caches, prompt)
    tok = jnp.argmax(last, -1).astype(jnp.int32)
    out = [int(tok[0])]
    if req.max_new > 1:
        more, *_ = model.generate(params, caches, lens, tok,
                                  req.max_new - 1)
        out += [int(x) for x in np.asarray(more)[0]]
    return out


class TestWireLayout:
    """The payload IS the pool's quantized bytes: every transport must
    move it bit-exactly."""

    def test_gather_scatter_round_trip_byte_identical(self, models1):
        """Pages gathered from a populated pool and scattered into a
        fresh pool at different slots hold byte-identical int8 payload
        AND scale planes."""
        from triton_distributed_tpu.kernels.kv_ship import (
            gather_kv_pages,
            scatter_kv_pages,
        )

        mp, pp, *_ = models1
        src = mp.init_serving_state(2, 16, 8)
        # populate a pool deterministically and PARK the finished
        # request (on_complete=False) so its table survives completion
        eng2 = ServingEngine(
            mp, pp, EngineConfig(slots=2, token_budget=32, chunk=8,
                                 page=8, npages=16),
            on_complete=lambda r, s: False,   # park: keep pages resident
        )
        req2 = Request(rid=0, prompt=np.arange(20, dtype=np.int32),
                       max_new=1, arrival=0.0)
        eng2.run([req2], max_steps=40)
        pids = eng2.table[req2.slot, :eng2._pages_held(req2.cursor)]
        assert (pids >= 0).all()
        qpay, spay = jax.jit(gather_kv_pages)(
            eng2.state.layers, jnp.asarray(pids.astype(np.int32))
        )
        assert qpay.dtype == jnp.int8 and spay is not None
        dst_pids = jnp.asarray(
            np.arange(len(pids), dtype=np.int32)[::-1].copy()
        )
        new_layers = jax.jit(scatter_kv_pages)(
            src.layers, dst_pids, qpay, spay
        )
        for li, (kp, vp) in enumerate(eng2.state.layers):
            nkp, nvp = new_layers[li]
            for pool, npool in ((kp, nkp), (vp, nvp)):
                np.testing.assert_array_equal(
                    np.asarray(pool["q"])[pids],
                    np.asarray(npool["q"])[np.asarray(dst_pids)],
                )
                np.testing.assert_array_equal(
                    np.asarray(pool["scale"])[pids],
                    np.asarray(npool["scale"])[np.asarray(dst_pids)],
                )

    def test_dcn_rail_byte_identical_to_xla_twin(self):
        """The paired ppermute rails land the exact payload+scale bytes
        on the destination role — byte-identical to what the XLA twin
        (device_put) moves — on a 2×4 hybrid mesh."""
        from triton_distributed_tpu.runtime.multislice import kv_ship_rail
        from triton_distributed_tpu.tools.native import xla_kv_ship

        devs = jax.devices()
        hybrid = Mesh(np.asarray(devs).reshape(2, 4), ("dcn", "x"))
        rng = np.random.default_rng(3)
        q = rng.integers(-127, 127, (4, 6, 2, 8, 16)).astype(np.int8)
        s = rng.standard_normal((4, 6, 2, 8)).astype(np.float32)
        stk_q = np.stack([q, np.zeros_like(q)])
        stk_s = np.stack([s, np.zeros_like(s)])
        out_q, out_s = kv_ship_rail(hybrid, "dcn", True)(stk_q, stk_s)
        np.testing.assert_array_equal(np.asarray(out_q)[1], q)
        np.testing.assert_array_equal(np.asarray(out_s)[1], s)
        # the XLA twin moves the same bytes (trivially — device_put)
        tq, ts = xla_kv_ship((q, s), (None, None))
        np.testing.assert_array_equal(np.asarray(tq), q)
        np.testing.assert_array_equal(np.asarray(ts), s)
        # raw wire (unquantized pools): payload-only rail
        (out_raw,) = kv_ship_rail(hybrid, "dcn", False)(stk_q)
        np.testing.assert_array_equal(np.asarray(out_raw)[1], q)

    def test_ship_wire_bytes_matches_perf_model(self):
        from triton_distributed_tpu.kernels.kv_ship import ship_wire_bytes
        from triton_distributed_tpu.tune.perf_model import (
            TPU_SPECS,
            kv_ship_ms,
        )

        b = ship_wire_bytes(4, 8, 2, 16, 2, True)
        # 2 layers × K,V × 4 pages × (2·8·16 int8 + 2·8·4 scale)
        assert b == 2 * 2 * 4 * (2 * 8 * 16 + 2 * 8 * 4)
        spec = TPU_SPECS["v5e"]
        ms = kv_ship_ms(4, 8, 2, 16, 2, True, spec)
        assert ms == pytest.approx(b / (spec.dcn_gbps * 1e9) * 1e3)


class TestDisaggregatedEngine:
    def test_end_to_end_token_exact_vs_colocated(self, models1, roles1):
        """Single-tp roles on the hybrid wire: every request's token
        stream equals the colocated engine's on the same trace."""
        mp, pp, md, pd = models1
        _, _, hybrid = roles1
        ecfg = EngineConfig(slots=4, token_budget=48, chunk=16, page=8,
                            npages=32)
        trace_c = poisson_trace(7, 6, 1.0, 5, 30, 3, 6, 128)
        trace_d = poisson_trace(7, 6, 1.0, 5, 30, 3, 6, 128)
        col = ServingEngine(mp, pp, ecfg)
        col.run(trace_c, max_steps=400)
        eng = DisaggregatedEngine(
            mp, pp, md, pd, ecfg, hybrid_mesh=hybrid, dcn_axis="dcn",
            transport="dcn", ship_delay_steps=1,
        )
        stats = eng.run(trace_d, max_ticks=600)
        assert stats.completed == 6
        assert stats.ships > 0 and not stats.degraded_transport
        assert stats.wire_compression > 1.0   # int8+scales vs bf16 pages
        for a, b in zip(trace_c, trace_d):
            assert a.generated == b.generated, a.rid

    def test_tp2_head_sharded_with_evictions_token_exact(self):
        """The acceptance pin: 2×2 hybrid mesh (tp=2 head sharding per
        role), int8 KV, decode pool small enough to force mid-stream
        evictions — token streams equal the colocated engine's."""
        devs = jax.devices()
        if len(devs) < 4:
            pytest.skip("needs 4 devices")
        mesh_p = _mesh(devs[:2], ("tp",))
        mesh_d = _mesh(devs[2:4], ("tp",))
        hybrid = Mesh(np.asarray(devs[:4]).reshape(2, 2), ("dcn", "tp"))
        mp = Transformer(TransformerConfig(**CFG), mesh_p, "tp", ())
        md = Transformer(TransformerConfig(**CFG), mesh_d, "tp", ())
        params = mp.init(jax.random.PRNGKey(0))
        pp = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                          mp.shardings())
        pd = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                          md.shardings())
        # decode pool far smaller than the prefill pool: decode-side
        # recompute-evictions fire while later ships are in flight
        ecfg = EngineConfig(slots=4, token_budget=48, chunk=16, page=8,
                            npages=32)
        dcfg = EngineConfig(slots=4, token_budget=32, chunk=16, page=8,
                            npages=14)
        trace_c = poisson_trace(9, 6, 0.7, 8, 30, 3, 6, 128)
        trace_d = poisson_trace(9, 6, 0.7, 8, 30, 3, 6, 128)
        col = ServingEngine(mp, pp, ecfg)
        col.run(trace_c, max_steps=500)
        eng = DisaggregatedEngine(
            mp, pp, md, pd, ecfg, decode_cfg=dcfg, hybrid_mesh=hybrid,
            dcn_axis="dcn", transport="dcn", ship_delay_steps=2,
        )
        stats = eng.run(trace_d, max_ticks=800)
        assert stats.completed == 6
        assert stats.decode.evictions > 0, (
            "config failed to force a decode-side eviction"
        )
        for a, b in zip(trace_c, trace_d):
            assert a.generated == b.generated, a.rid

    def test_admission_gates_on_shipped_pages(self, models1, roles1):
        """Between a ship's launch and its commit the decode slot is
        reserved-but-parked: its pages are claimed, its row is never
        batched; the first decode batch containing it happens only
        after the transfer commits."""
        mp, pp, md, pd = models1
        _, _, hybrid = roles1
        ecfg = EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                            npages=16)
        eng = DisaggregatedEngine(
            mp, pp, md, pd, ecfg, hybrid_mesh=hybrid, dcn_axis="dcn",
            transport="dcn", ship_delay_steps=3,
        )
        req = Request(rid=0, prompt=np.arange(12, dtype=np.int32),
                      max_new=4, arrival=0.0)
        eng.submit_trace([req])
        saw_parked_with_pages = False
        while not eng.idle and eng.ticks < 100:
            eng.tick()
            if eng._inflight:
                r = eng._inflight[0]
                assert req.parked
                # pages already claimed (admission gated on the SHIP,
                # not on promises) ...
                held = eng.decode.table[r.dslot]
                assert (held[:len(r.dpids)] >= 0).all()
                # ... but the row is not schedulable: no decode batch
                # has carried it while the transfer is in flight
                assert sum(eng.decode.stats.step_generated) == 0
                saw_parked_with_pages = True
        assert saw_parked_with_pages
        assert sum(eng.decode.stats.step_generated) > 0
        assert req.done
        assert req.generated == _reference_tokens(mp, pp, req)

    def test_eviction_never_frees_pages_mid_ship(self, models1, roles1):
        """The race pin: while a transfer is in flight, neither role's
        eviction may pick the shipping request — its landing pages stay
        claimed and its table rows intact through the window."""
        mp, pp, md, pd = models1
        _, _, hybrid = roles1
        ecfg = EngineConfig(slots=3, token_budget=48, chunk=8, page=8,
                            npages=24)
        # decode pool with room for the ship but tight for decoders —
        # decode evictions fire during the in-flight windows
        dcfg = EngineConfig(slots=3, token_budget=24, chunk=8, page=8,
                            npages=10)
        eng = DisaggregatedEngine(
            mp, pp, md, pd, ecfg, decode_cfg=dcfg, hybrid_mesh=hybrid,
            dcn_axis="dcn", transport="dcn", ship_delay_steps=3,
        )
        trace = poisson_trace(5, 5, 0.5, 8, 22, 4, 7, 128)
        eng.submit_trace(trace)
        while not eng.idle and eng.ticks < 500:
            eng.tick()
            for r in eng._inflight:
                assert r.req.parked, "in-flight request lost its pin"
                table_row = eng.decode.table[r.dslot, :len(r.dpids)]
                assert list(table_row) == list(r.dpids), (
                    "eviction touched in-flight landing pages"
                )
                # the prefill-side source pages are still held too
                assert eng.prefill.slot_req[r.pslot] is r.req
        assert eng.stats.completed == 5
        for req in trace:
            assert req.generated == _reference_tokens(mp, pp, req), req.rid

    def test_parked_requests_are_never_eviction_victims(self, models1):
        mp, pp, *_ = models1
        eng = ServingEngine(
            mp, pp, EngineConfig(slots=2, token_budget=32, chunk=8,
                                 page=8, npages=16),
        )
        req = Request(rid=0, prompt=np.arange(9, dtype=np.int32),
                      max_new=2, arrival=0.0)
        eng._admit()   # no-op, just exercise the empty path
        eng.submit(req)
        eng._admit()
        req.parked = True
        assert eng._evict_one(set()) is False
        req.parked = False
        assert eng._evict_one(set()) is True

    def test_transport_degrades_to_xla_on_first_failure(
        self, models1, roles1, monkeypatch,
    ):
        """First DCN-wire failure flips the engine onto the
        device_put fallback (tools.native.xla_kv_ship) — results
        identical, stats record the degradation."""
        import triton_distributed_tpu.serving.engine as engine_mod

        mp, pp, md, pd = models1
        _, _, hybrid = roles1
        eng = DisaggregatedEngine(
            mp, pp, md, pd,
            EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                         npages=16),
            hybrid_mesh=hybrid, dcn_axis="dcn", transport="dcn",
        )

        def boom(self, qpay, spay):
            raise RuntimeError("injected wire failure")

        monkeypatch.setattr(
            engine_mod.DisaggregatedEngine, "_transport_dcn", boom
        )
        req = Request(rid=0, prompt=np.arange(11, dtype=np.int32),
                      max_new=3, arrival=0.0)
        stats = eng.run([req], max_ticks=100)
        assert stats.degraded_transport
        assert eng.transport == "xla"
        assert stats.completed == 1
        assert req.generated == _reference_tokens(mp, pp, req)

    def test_max_new_1_completes_on_the_prefill_side(self, models1,
                                                     roles1):
        """A 1-token request is DONE when prefill finishes — no ship,
        no decode-slot churn."""
        mp, pp, md, pd = models1
        _, _, hybrid = roles1
        eng = DisaggregatedEngine(
            mp, pp, md, pd,
            EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                         npages=16),
            hybrid_mesh=hybrid, dcn_axis="dcn",
        )
        req = Request(rid=0, prompt=np.arange(10, dtype=np.int32),
                      max_new=1, arrival=0.0)
        stats = eng.run([req], max_ticks=50)
        assert stats.completed == 1 and stats.ships == 0
        assert req.generated == _reference_tokens(mp, pp, req)

    def test_sampling_token_exact_across_topologies(self, models1,
                                                    roles1):
        """The satellite sampler is request-keyed: temperature/top-k
        streams are identical colocated vs disaggregated."""
        mp, pp, md, pd = models1
        _, _, hybrid = roles1
        ecfg = EngineConfig(slots=3, token_budget=48, chunk=16, page=8,
                            npages=24, temperature=0.8, top_k=12, seed=5)
        tc = poisson_trace(3, 4, 1.0, 5, 24, 3, 6, 128)
        td = poisson_trace(3, 4, 1.0, 5, 24, 3, 6, 128)
        ServingEngine(mp, pp, ecfg).run(tc, max_steps=300)
        DisaggregatedEngine(
            mp, pp, md, pd, ecfg, hybrid_mesh=hybrid, dcn_axis="dcn",
            transport="dcn", ship_delay_steps=1,
        ).run(td, max_ticks=500)
        assert [r.generated for r in tc] == [r.generated for r in td]
        assert all(len(r.generated) == r.max_new for r in tc)


class TestAutoPlacement:
    def test_perf_model_refuses_wire_dominated_traffic(self):
        from triton_distributed_tpu.tune.perf_model import (
            TPU_SPECS,
            refuse_disaggregation,
        )

        cfg = TransformerConfig(**CFG)
        spec = TPU_SPECS["v5e"]
        # long prompt, one decode step, fast decode: the ship cannot
        # hide — refused with the priced reason
        reason = refuse_disaggregation(
            cfg, 8,
            {"prompt_len": 4096, "max_new": 1, "decode_step_ms": 0.01},
            spec,
        )
        assert reason is not None and "kv_ship_ms" in reason
        # generous decode window: accepted
        assert refuse_disaggregation(
            cfg, 8,
            {"prompt_len": 64, "max_new": 256, "decode_step_ms": 5.0},
            spec,
        ) is None

    def test_engine_auto_placement_refusal_is_loud(self, models1,
                                                   roles1):
        mp, pp, md, pd = models1
        _, _, hybrid = roles1
        with pytest.raises(ValueError, match="refuses disaggregation"):
            DisaggregatedEngine(
                mp, pp, md, pd,
                EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                             npages=16),
                hybrid_mesh=hybrid, placement="auto",
                traffic={"prompt_len": 100_000, "max_new": 1,
                         "decode_step_ms": 1e-6},
            )
