"""Distributed flash-decode vs dense attention reference.

Mirrors the reference's test_decode_attn.py / test_sp_decode_attn.py:
local split-KV decode and the SP (sequence-parallel) path are both checked
against a plain masked-softmax attention computed in f64-ish f32.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.kernels.flash_decode import (
    combine_partials,
    gqa_fwd_batch_decode,
    gqa_fwd_batch_decode_xla,
    sp_gqa_fwd_batch_decode,
)
from triton_distributed_tpu.utils import assert_allclose


def _setup(batch=2, hq=8, hkv=2, d=128, s=512, seed=0, layout="bshd"):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (batch, hq, d), jnp.float32)
    shape = (batch, s, hkv, d) if layout == "bshd" else (batch, hkv, s, d)
    k = jax.random.normal(ks[1], shape, jnp.float32)
    v = jax.random.normal(ks[2], shape, jnp.float32)
    return q, k, v


@pytest.mark.parametrize("kv_layout", ["bshd", "bhsd"])
@pytest.mark.parametrize("kv_lens", [[512, 512], [300, 17], [512, 1]])
def test_local_decode_matches_xla(kv_lens, kv_layout):
    q, k, v = _setup(layout=kv_layout)
    lens = jnp.asarray(kv_lens, jnp.int32)
    out, lse = gqa_fwd_batch_decode(q, k, v, lens, block_k=128, kv_layout=kv_layout)
    out_ref, lse_ref = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout=kv_layout)
    assert_allclose(np.asarray(out), np.asarray(out_ref), atol=2e-5, rtol=2e-5)
    assert_allclose(np.asarray(lse), np.asarray(lse_ref), atol=2e-5, rtol=2e-5)


def test_local_decode_soft_cap():
    q, k, v = _setup(seed=3)
    lens = jnp.asarray([512, 211], jnp.int32)
    out, _ = gqa_fwd_batch_decode(
        q, k, v, lens, soft_cap=30.0, block_k=128, kv_layout="bshd"
    )
    out_ref, _ = gqa_fwd_batch_decode_xla(
        q, k, v, lens, soft_cap=30.0, kv_layout="bshd"
    )
    assert_allclose(np.asarray(out), np.asarray(out_ref), atol=2e-5, rtol=2e-5)


def test_combine_partials_is_exact_softmax_merge():
    """Splitting a sequence into R chunks and merging partials must equal
    attention over the whole sequence (the ring-attention invariant)."""
    q, k, v = _setup(batch=1, s=512, seed=1)
    lens = jnp.asarray([512], jnp.int32)
    whole, whole_lse = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bshd")

    outs, lses = [], []
    r = 4
    for i in range(r):
        ks = k[:, i * 128 : (i + 1) * 128]
        vs = v[:, i * 128 : (i + 1) * 128]
        o, l = gqa_fwd_batch_decode_xla(
            q, ks, vs, jnp.asarray([128], jnp.int32), kv_layout="bshd"
        )
        outs.append(o)
        lses.append(l)
    merged, merged_lse = combine_partials(jnp.stack(outs), jnp.stack(lses))
    assert_allclose(np.asarray(merged), np.asarray(whole), atol=2e-5, rtol=2e-5)
    assert_allclose(np.asarray(merged_lse), np.asarray(whole_lse), atol=2e-5, rtol=2e-5)


def test_combine_partials_empty_shard_contributes_zero():
    q, k, v = _setup(batch=1, s=128, seed=2)
    lens = jnp.asarray([128], jnp.int32)
    out, lse = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bshd")
    empty_out, empty_lse = gqa_fwd_batch_decode_xla(
        q, k, v, jnp.asarray([0], jnp.int32), kv_layout="bshd"
    )
    merged, _ = combine_partials(
        jnp.stack([out, empty_out]), jnp.stack([lse, empty_lse])
    )
    assert_allclose(np.asarray(merged), np.asarray(out), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("use_pallas", [True, False])
@pytest.mark.parametrize("global_len", [1024, 700, 130, 1])
def test_sp_decode_matches_dense(mesh8, use_pallas, global_len):
    """KV sharded over 8 devices; partial ranks (even fully-empty ranks at
    short kv_lens) must still merge to the dense answer
    (≡ test_sp_decode_attn.py)."""
    q, k, v = _setup(batch=2, s=1024, seed=4)
    lens = jnp.asarray([global_len, max(global_len // 2, 1)], jnp.int32)
    out = sp_gqa_fwd_batch_decode(
        q, k, v, lens, mesh8, "x", use_pallas=use_pallas, block_k=128,
        kv_layout="bshd",
    )
    out_ref, _ = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bshd")
    assert_allclose(np.asarray(out), np.asarray(out_ref), atol=3e-5, rtol=3e-5)


class TestInt8KV:
    """INT8 KV cache decode (TPU-first serving extension: half the KV
    bytes at rest and on the attention DMA stream; scales fold exactly
    into the softmax — see _decode_kernel_dyn's quant mode)."""

    def _q(self, batch=3, hq=16, hkv=4, d=128, s=256, seed=7):
        from triton_distributed_tpu.kernels.flash_decode import quantize_kv

        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (batch, hq, d), jnp.float32)
        k = jax.random.normal(ks[1], (batch, hkv, s, d), jnp.float32)
        v = jax.random.normal(ks[2], (batch, hkv, s, d), jnp.float32)
        kq, ksc = quantize_kv(k)
        vq, vsc = quantize_kv(v)
        return q, k, v, kq, ksc, vq, vsc

    def test_quantize_roundtrip_error_bound(self):
        _, k, _, kq, ksc, _, _ = self._q()
        widened = kq.astype(jnp.float32) * ksc[..., None]
        # per-row max-abs scaling: error ≤ scale/2 = amax/254 per elem
        amax = jnp.max(jnp.abs(k), axis=-1, keepdims=True)
        assert float(jnp.max(jnp.abs(widened - k) / (amax / 254.0 + 1e-9))) <= 1.001

    @pytest.mark.parametrize("kv_lens", [[256, 256, 256], [200, 37, 0], [1, 255, 128]])
    def test_kernel_matches_widened_xla(self, kv_lens):
        from triton_distributed_tpu.kernels.flash_decode import (
            gqa_fwd_batch_decode_q8,
            gqa_fwd_batch_decode_q8_xla,
        )

        q, _, _, kq, ksc, vq, vsc = self._q()
        lens = jnp.asarray(kv_lens, jnp.int32)
        out, lse = gqa_fwd_batch_decode_q8(q, kq, ksc, vq, vsc, lens)
        ref, lse_ref = gqa_fwd_batch_decode_q8_xla(q, kq, ksc, vq, vsc, lens)
        # kernel runs q/k/v in bf16 (the TPU compute dtype); the twin is f32
        assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2, rtol=2e-2)
        finite = np.isfinite(np.asarray(lse_ref))
        assert_allclose(
            np.asarray(lse)[finite], np.asarray(lse_ref)[finite], atol=2e-2
        )

    def test_quant_error_vs_full_precision(self):
        from triton_distributed_tpu.kernels.flash_decode import (
            gqa_fwd_batch_decode_q8,
        )

        q, k, v, kq, ksc, vq, vsc = self._q()
        lens = jnp.asarray([256, 200, 128], jnp.int32)
        out, _ = gqa_fwd_batch_decode_q8(q, kq, ksc, vq, vsc, lens)
        ref, _ = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bhsd")
        assert float(jnp.max(jnp.abs(out - ref))) < 0.05  # ~int8 noise

    def test_sp_q8_matches_dense(self, mesh8):
        from triton_distributed_tpu.kernels.flash_decode import (
            sp_gqa_fwd_batch_decode_q8,
        )

        q, k, v, kq, ksc, vq, vsc = self._q(s=1024)
        lens = jnp.asarray([900, 400, 64], jnp.int32)  # empty far shards
        out = sp_gqa_fwd_batch_decode_q8(q, kq, ksc, vq, vsc, lens, mesh8, "x")
        ref, _ = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bhsd")
        assert float(jnp.max(jnp.abs(out - ref))) < 0.05

    @pytest.mark.parametrize("lens", [(256, 256), (200, 37), (0, 1)])
    def test_paged_q8_matches_widened(self, lens):
        from triton_distributed_tpu.kernels.flash_decode import (
            paged_gqa_fwd_batch_decode_q8,
            paged_gqa_fwd_batch_decode_q8_xla,
            quantize_kv,
        )

        rng = np.random.default_rng(3)
        B, HQ, HKV, D, PAGE, PAGES = 2, 8, 2, 128, 64, 4
        npages = B * PAGES + 2
        kp = jnp.asarray(
            rng.standard_normal((npages, HKV, PAGE, D)), jnp.float32
        )
        vp = jnp.asarray(
            rng.standard_normal((npages, HKV, PAGE, D)), jnp.float32
        )
        kq, ks = quantize_kv(kp)
        vq, vs = quantize_kv(vp)
        table = jnp.asarray(
            rng.permutation(B * PAGES).reshape(B, PAGES).astype(np.int32)
        )
        q = jnp.asarray(rng.standard_normal((B, HQ, D)), jnp.float32)
        kv_lens = jnp.asarray(lens, jnp.int32)
        out, lse = paged_gqa_fwd_batch_decode_q8(
            q, kq, ks, vq, vs, kv_lens, table
        )
        ref, lse_ref = paged_gqa_fwd_batch_decode_q8_xla(
            q, kq, ks, vq, vs, kv_lens, table
        )
        assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2,
                        rtol=2e-2)
        finite = np.isfinite(np.asarray(lse_ref))
        assert_allclose(
            np.asarray(lse)[finite], np.asarray(lse_ref)[finite], atol=2e-2
        )

    @pytest.mark.parametrize("lens", [(256, 200), (129, 0)])
    def test_paged_q8_mh_aligned(self, lens):
        """ALIGNED geometry (page and D multiples of 128) takes the
        round-5 MULTIHEAD page walk (grid (B,), table-indexed manual
        DMAs, `_paged_kernel_dyn_mh`) — the serving-shape kernel; the
        smaller-page tests above exercise the widen fallback."""
        from triton_distributed_tpu.kernels.flash_decode import (
            paged_gqa_fwd_batch_decode_q8,
            paged_gqa_fwd_batch_decode_q8_xla,
            quantize_kv,
        )

        rng = np.random.default_rng(9)
        B, HQ, HKV, D, PAGE, PAGES = 2, 8, 2, 128, 128, 2
        npages = B * PAGES + 1
        kp = jnp.asarray(
            rng.standard_normal((npages, HKV, PAGE, D)), jnp.float32
        )
        vp = jnp.asarray(
            rng.standard_normal((npages, HKV, PAGE, D)), jnp.float32
        )
        kq, ks = quantize_kv(kp)
        vq, vs = quantize_kv(vp)
        table = jnp.asarray(
            rng.permutation(B * PAGES).reshape(B, PAGES).astype(np.int32)
        )
        q = jnp.asarray(rng.standard_normal((B, HQ, D)), jnp.float32)
        kv_lens = jnp.asarray(lens, jnp.int32)
        out, lse = paged_gqa_fwd_batch_decode_q8(
            q, kq, ks, vq, vs, kv_lens, table
        )
        ref, lse_ref = paged_gqa_fwd_batch_decode_q8_xla(
            q, kq, ks, vq, vs, kv_lens, table
        )
        assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2,
                        rtol=2e-2)
        finite = np.isfinite(np.asarray(lse_ref))
        assert_allclose(
            np.asarray(lse)[finite], np.asarray(lse_ref)[finite], atol=2e-2
        )

    def test_sp_paged_q8_matches_dense(self, mesh8):
        from triton_distributed_tpu.kernels.flash_decode import (
            sp_paged_gqa_fwd_batch_decode_q8,
            quantize_kv,
        )

        rng = np.random.default_rng(5)
        R, B, HQ, HKV, D, PAGE, PPS = 8, 2, 8, 2, 128, 32, 2
        s_total = R * PPS * PAGE                   # 512
        k = jnp.asarray(rng.standard_normal((B, HKV, s_total, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, HKV, s_total, D)), jnp.float32)
        # build per-rank pools: rank r's slice rows → its PPS·B pages
        kpages = k.reshape(B, HKV, R, PPS, PAGE, D)
        vpages = v.reshape(B, HKV, R, PPS, PAGE, D)
        # pool layout (R·npl, Hkv, page, D), npl = B·PPS local pages
        kp = kpages.transpose(2, 0, 3, 1, 4, 5).reshape(
            R * B * PPS, HKV, PAGE, D
        )
        vp = vpages.transpose(2, 0, 3, 1, 4, 5).reshape(
            R * B * PPS, HKV, PAGE, D
        )
        # local table: rank r, row b, slot j → local page b·PPS + j
        table = jnp.asarray(
            np.tile(
                (np.arange(B)[:, None] * PPS + np.arange(PPS)[None, :]),
                (R, 1, 1),
            ).astype(np.int32)
        )
        kq, ks = quantize_kv(kp)
        vq, vs = quantize_kv(vp)
        q = jnp.asarray(rng.standard_normal((B, HQ, D)), jnp.float32)
        lens = jnp.asarray([450, 97], jnp.int32)
        out = sp_paged_gqa_fwd_batch_decode_q8(
            q, kq, ks, vq, vs, lens, table, mesh8, "x"
        )
        ref, _ = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bhsd")
        assert float(jnp.max(jnp.abs(out - ref))) < 0.05

    def test_append_kv_q8(self):
        from triton_distributed_tpu.layers import append_kv
        from triton_distributed_tpu.kernels.flash_decode import quantize_kv

        rng = np.random.default_rng(0)
        B, H, S, D = 2, 2, 16, 128
        k0 = jnp.zeros((B, H, S, D), jnp.float32)
        kc = {"q": jnp.zeros((B, H, S, D), jnp.int8),
              "scale": jnp.ones((B, H, S), jnp.float32)}
        vc = {"q": kc["q"], "scale": kc["scale"]}
        lens = jnp.asarray([3, 9], jnp.int32)
        k_new = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
        v_new = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
        kc, vc, lens2 = append_kv(kc, vc, lens, k_new, v_new)
        assert list(np.asarray(lens2)) == [4, 10]
        widened = kc["q"].astype(jnp.float32) * kc["scale"][..., None]
        for b, l in enumerate([3, 9]):
            assert_allclose(
                np.asarray(widened[b, :, l]), np.asarray(k_new[b]),
                atol=2e-2, rtol=2e-2,
            )
            # untouched rows stay zero
            assert float(jnp.sum(jnp.abs(widened[b, :, l + 1:]))) == 0.0


def test_aot_twin_roundtrip(tmp_path):
    """The AOT library serializes the decode entry and reloads it with
    identical numerics (≡ the *_aot entries, flash_decode.py:1007-1160)."""
    from triton_distributed_tpu.kernels.flash_decode import (
        gqa_fwd_batch_decode,
        gqa_fwd_batch_decode_aot,
    )

    b, hq, hkv, d, s = 2, 8, 2, 128, 512
    q = jax.random.normal(jax.random.PRNGKey(0), (b, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d), jnp.float32)
    lens = jnp.array([400, 100], jnp.int32)

    lib = gqa_fwd_batch_decode_aot(block_k=128, kv_layout="bshd", cache_dir=tmp_path)
    path = lib.compile(q, k, v, lens)
    assert path.exists()
    # a fresh library finds the artifact on disk — no retrace
    lib2 = gqa_fwd_batch_decode_aot(block_k=128, kv_layout="bshd", cache_dir=tmp_path)
    out, lse = lib2(q, k, v, lens)
    assert lib2.stats == {"artifact_loads": 1, "jit_fallbacks": 0}
    # different hyperparameters must NOT reuse the artifact
    lib3 = gqa_fwd_batch_decode_aot(
        block_k=128, soft_cap=30.0, kv_layout="bshd", cache_dir=tmp_path
    )
    lib3(q, k, v, lens)
    assert lib3.stats["jit_fallbacks"] == 1
    ref, ref_lse = gqa_fwd_batch_decode(q, k, v, lens, block_k=128, kv_layout="bshd")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=1e-5)


class TestPagedDecode:
    """Paged KV decode (≡ the reference's block_table/page_size surface:
    gqa_fwd_batch_decode's (num_pages, page_size, Hkv, D) caches,
    flash_decode.py:763-846, and the SP layer's block_table forward,
    sp_flash_decode_layer.py:78-84)."""

    B, HQ, HKV, D, PAGE, PAGES = 2, 8, 2, 128, 64, 4

    def _pool(self, seed=0):
        """Random pool + per-row shuffled tables covering PAGES pages."""
        rng = np.random.default_rng(seed)
        npages = self.B * self.PAGES + 3          # a few never-used pages
        k_pool = jnp.asarray(
            rng.standard_normal((npages, self.HKV, self.PAGE, self.D)),
            jnp.float32,
        )
        v_pool = jnp.asarray(
            rng.standard_normal((npages, self.HKV, self.PAGE, self.D)),
            jnp.float32,
        )
        perm = rng.permutation(self.B * self.PAGES).reshape(
            self.B, self.PAGES
        ).astype(np.int32)
        q = jnp.asarray(
            rng.standard_normal((self.B, self.HQ, self.D)), jnp.float32
        )
        return q, k_pool, v_pool, jnp.asarray(perm)

    @pytest.mark.parametrize("lens", [(256, 256), (200, 37), (0, 1)])
    def test_paged_matches_dense_gather(self, lens):
        from triton_distributed_tpu.kernels.flash_decode import (
            paged_gqa_fwd_batch_decode,
            paged_gqa_fwd_batch_decode_xla,
        )

        q, kp, vp, table = self._pool()
        kv_lens = jnp.asarray(lens, jnp.int32)
        out, lse = paged_gqa_fwd_batch_decode(q, kp, vp, kv_lens, table)
        ref, lse_ref = paged_gqa_fwd_batch_decode_xla(
            q, kp, vp, kv_lens, table
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(lse), np.asarray(lse_ref), atol=2e-5, rtol=2e-5
        )

    def test_paged_matches_contiguous(self):
        """Scattering a contiguous bhsd cache into pages and decoding
        through the table must reproduce the contiguous kernel."""
        from triton_distributed_tpu.kernels.flash_decode import (
            gqa_fwd_batch_decode,
            paged_gqa_fwd_batch_decode,
        )

        q, kp, vp, table = self._pool(seed=3)
        s_len = self.PAGES * self.PAGE
        kv_lens = jnp.asarray([s_len, 150], jnp.int32)
        # contiguous view: gather each row's pages in table order
        kc = kp[table].transpose(0, 2, 1, 3, 4).reshape(
            self.B, self.HKV, s_len, self.D
        )
        vc = vp[table].transpose(0, 2, 1, 3, 4).reshape(
            self.B, self.HKV, s_len, self.D
        )
        out_p, lse_p = paged_gqa_fwd_batch_decode(q, kp, vp, kv_lens, table)
        out_c, lse_c = gqa_fwd_batch_decode(
            q, kc, vc, kv_lens, kv_layout="bhsd", block_k=self.PAGE
        )
        np.testing.assert_allclose(
            np.asarray(out_p), np.asarray(out_c), atol=2e-5, rtol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(lse_p), np.asarray(lse_c), atol=2e-5, rtol=2e-5
        )

    def test_sp_paged_layer(self, mesh8):
        """SP paged decode through the layer: 8 ranks × per-rank pools/
        tables vs the dense whole-sequence reference."""
        from triton_distributed_tpu.kernels.flash_decode import (
            gqa_fwd_batch_decode_xla,
        )
        from triton_distributed_tpu.layers import SpGQAFlashDecodeAttention

        rng = np.random.default_rng(7)
        R, B, HKV, HQ, D, PAGE, PPS = 8, 2, 2, 8, 128, 16, 2
        npl = B * PPS                         # pages per rank's pool
        k_pool = jnp.asarray(
            rng.standard_normal((R * npl, HKV, PAGE, D)), jnp.float32
        )
        v_pool = jnp.asarray(
            rng.standard_normal((R * npl, HKV, PAGE, D)), jnp.float32
        )
        table = jnp.asarray(
            np.stack([
                rng.permutation(npl).reshape(B, PPS) for _ in range(R)
            ]).astype(np.int32)
        )
        q = jnp.asarray(rng.standard_normal((B, HQ, D)), jnp.float32)
        lens = jnp.asarray([R * PPS * PAGE, 100], jnp.int32)

        layer = SpGQAFlashDecodeAttention(
            mesh8, "x", q_heads=HQ, kv_heads=HKV, head_dim=D,
            use_pallas=False,   # interpreter-friendly; pallas paged is
                                # covered by the single-device tests
        )
        out = layer(q, k_pool, v_pool, lens, block_table=table)

        # dense reference: assemble the global contiguous cache
        kparts, vparts = [], []
        for r in range(R):
            pool_k = np.asarray(k_pool[r * npl:(r + 1) * npl])
            pool_v = np.asarray(v_pool[r * npl:(r + 1) * npl])
            t = np.asarray(table[r])
            kparts.append(pool_k[t].transpose(0, 2, 1, 3, 4).reshape(
                B, HKV, PPS * PAGE, D))
            vparts.append(pool_v[t].transpose(0, 2, 1, 3, 4).reshape(
                B, HKV, PPS * PAGE, D))
        kc = jnp.asarray(np.concatenate(kparts, axis=2))
        vc = jnp.asarray(np.concatenate(vparts, axis=2))
        ref, _ = gqa_fwd_batch_decode_xla(q, kc, vc, lens, kv_layout="bhsd")
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_sp_paged_device_body(self, mesh8):
        """The exported per-device composition hook must equal the host
        entry (both use the shared _local_paged_shard_decode)."""
        import jax
        from jax.sharding import PartitionSpec as P

        from triton_distributed_tpu.kernels import (
            sp_paged_gqa_fwd_batch_decode,
            sp_paged_gqa_fwd_batch_decode_device,
        )

        rng = np.random.default_rng(11)
        R, B, HKV, HQ, D, PAGE, PPS = 8, 2, 2, 8, 128, 16, 2
        npl = B * PPS
        k_pool = jnp.asarray(
            rng.standard_normal((R * npl, HKV, PAGE, D)), jnp.float32
        )
        v_pool = jnp.asarray(
            rng.standard_normal((R * npl, HKV, PAGE, D)), jnp.float32
        )
        table = jnp.asarray(
            np.stack([
                rng.permutation(npl).reshape(B, PPS) for _ in range(R)
            ]).astype(np.int32)
        )
        q = jnp.asarray(rng.standard_normal((B, HQ, D)), jnp.float32)
        lens = jnp.asarray([150, 40], jnp.int32)

        ref = sp_paged_gqa_fwd_batch_decode(
            q, k_pool, v_pool, lens, table, mesh8, "x", use_pallas=False
        )

        def body(q, kp, vp, lens, table):
            return sp_paged_gqa_fwd_batch_decode_device(
                q, kp, vp, lens, table[0], "x", use_pallas=False
            )

        out = jax.jit(jax.shard_map(
            body, mesh=mesh8,
            in_specs=(P(), P("x"), P("x"), P(), P("x")), out_specs=P(),
            check_vma=False,
        ))(q, k_pool, v_pool, lens, table)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-6, rtol=1e-6
        )
