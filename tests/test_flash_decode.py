"""Distributed flash-decode vs dense attention reference.

Mirrors the reference's test_decode_attn.py / test_sp_decode_attn.py:
local split-KV decode and the SP (sequence-parallel) path are both checked
against a plain masked-softmax attention computed in f64-ish f32.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.kernels.flash_decode import (
    combine_partials,
    gqa_fwd_batch_decode,
    gqa_fwd_batch_decode_xla,
    sp_gqa_fwd_batch_decode,
)
from triton_distributed_tpu.utils import assert_allclose


def _setup(batch=2, hq=8, hkv=2, d=128, s=512, seed=0, layout="bshd"):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (batch, hq, d), jnp.float32)
    shape = (batch, s, hkv, d) if layout == "bshd" else (batch, hkv, s, d)
    k = jax.random.normal(ks[1], shape, jnp.float32)
    v = jax.random.normal(ks[2], shape, jnp.float32)
    return q, k, v


@pytest.mark.parametrize("kv_layout", ["bshd", "bhsd"])
@pytest.mark.parametrize("kv_lens", [[512, 512], [300, 17], [512, 1]])
def test_local_decode_matches_xla(kv_lens, kv_layout):
    q, k, v = _setup(layout=kv_layout)
    lens = jnp.asarray(kv_lens, jnp.int32)
    out, lse = gqa_fwd_batch_decode(q, k, v, lens, block_k=128, kv_layout=kv_layout)
    out_ref, lse_ref = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout=kv_layout)
    assert_allclose(np.asarray(out), np.asarray(out_ref), atol=2e-5, rtol=2e-5)
    assert_allclose(np.asarray(lse), np.asarray(lse_ref), atol=2e-5, rtol=2e-5)


def test_local_decode_soft_cap():
    q, k, v = _setup(seed=3)
    lens = jnp.asarray([512, 211], jnp.int32)
    out, _ = gqa_fwd_batch_decode(
        q, k, v, lens, soft_cap=30.0, block_k=128, kv_layout="bshd"
    )
    out_ref, _ = gqa_fwd_batch_decode_xla(
        q, k, v, lens, soft_cap=30.0, kv_layout="bshd"
    )
    assert_allclose(np.asarray(out), np.asarray(out_ref), atol=2e-5, rtol=2e-5)


def test_combine_partials_is_exact_softmax_merge():
    """Splitting a sequence into R chunks and merging partials must equal
    attention over the whole sequence (the ring-attention invariant)."""
    q, k, v = _setup(batch=1, s=512, seed=1)
    lens = jnp.asarray([512], jnp.int32)
    whole, whole_lse = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bshd")

    outs, lses = [], []
    r = 4
    for i in range(r):
        ks = k[:, i * 128 : (i + 1) * 128]
        vs = v[:, i * 128 : (i + 1) * 128]
        o, l = gqa_fwd_batch_decode_xla(
            q, ks, vs, jnp.asarray([128], jnp.int32), kv_layout="bshd"
        )
        outs.append(o)
        lses.append(l)
    merged, merged_lse = combine_partials(jnp.stack(outs), jnp.stack(lses))
    assert_allclose(np.asarray(merged), np.asarray(whole), atol=2e-5, rtol=2e-5)
    assert_allclose(np.asarray(merged_lse), np.asarray(whole_lse), atol=2e-5, rtol=2e-5)


def test_combine_partials_empty_shard_contributes_zero():
    q, k, v = _setup(batch=1, s=128, seed=2)
    lens = jnp.asarray([128], jnp.int32)
    out, lse = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bshd")
    empty_out, empty_lse = gqa_fwd_batch_decode_xla(
        q, k, v, jnp.asarray([0], jnp.int32), kv_layout="bshd"
    )
    merged, _ = combine_partials(
        jnp.stack([out, empty_out]), jnp.stack([lse, empty_lse])
    )
    assert_allclose(np.asarray(merged), np.asarray(out), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("use_pallas", [True, False])
@pytest.mark.parametrize("global_len", [1024, 700, 130, 1])
def test_sp_decode_matches_dense(mesh8, use_pallas, global_len):
    """KV sharded over 8 devices; partial ranks (even fully-empty ranks at
    short kv_lens) must still merge to the dense answer
    (≡ test_sp_decode_attn.py)."""
    q, k, v = _setup(batch=2, s=1024, seed=4)
    lens = jnp.asarray([global_len, max(global_len // 2, 1)], jnp.int32)
    out = sp_gqa_fwd_batch_decode(
        q, k, v, lens, mesh8, "x", use_pallas=use_pallas, block_k=128,
        kv_layout="bshd",
    )
    out_ref, _ = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bshd")
    assert_allclose(np.asarray(out), np.asarray(out_ref), atol=3e-5, rtol=3e-5)


def test_aot_twin_roundtrip(tmp_path):
    """The AOT library serializes the decode entry and reloads it with
    identical numerics (≡ the *_aot entries, flash_decode.py:1007-1160)."""
    from triton_distributed_tpu.kernels.flash_decode import (
        gqa_fwd_batch_decode,
        gqa_fwd_batch_decode_aot,
    )

    b, hq, hkv, d, s = 2, 8, 2, 128, 512
    q = jax.random.normal(jax.random.PRNGKey(0), (b, hq, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d), jnp.float32)
    lens = jnp.array([400, 100], jnp.int32)

    lib = gqa_fwd_batch_decode_aot(block_k=128, kv_layout="bshd", cache_dir=tmp_path)
    path = lib.compile(q, k, v, lens)
    assert path.exists()
    # a fresh library finds the artifact on disk — no retrace
    lib2 = gqa_fwd_batch_decode_aot(block_k=128, kv_layout="bshd", cache_dir=tmp_path)
    out, lse = lib2(q, k, v, lens)
    assert lib2.stats == {"artifact_loads": 1, "jit_fallbacks": 0}
    # different hyperparameters must NOT reuse the artifact
    lib3 = gqa_fwd_batch_decode_aot(
        block_k=128, soft_cap=30.0, kv_layout="bshd", cache_dir=tmp_path
    )
    lib3(q, k, v, lens)
    assert lib3.stats["jit_fallbacks"] == 1
    ref, ref_lse = gqa_fwd_batch_decode(q, k, v, lens, block_k=128, kv_layout="bshd")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=1e-5)
