"""Collective kernel tests vs jax.lax references.

≡ reference test_all_gather / test_fast_allgather / test_reduce_scatter /
test_all_to_all (python/triton_dist/test/nvidia/), with jax.lax collectives
playing the role of the torch/NCCL baseline (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels import (
    all_gather,
    all_to_all,
    all_to_all_xla,
    reduce_scatter,
    reduce_scatter_xla,
)
from triton_distributed_tpu.runtime import AllGatherMethod
from triton_distributed_tpu.utils import assert_allclose


def _rand(shape, dtype=jnp.float32, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype=dtype)


@pytest.mark.parametrize(
    "method",
    [
        AllGatherMethod.RING_1D,
        AllGatherMethod.RING_BIDIR,
        AllGatherMethod.LL_SMALL,
        AllGatherMethod.XLA_FALLBACK,
    ],
)
def test_all_gather_methods(mesh8, method):
    x = _rand((64, 256))
    y = all_gather(x, mesh8, "x", method=method)
    assert y.shape == x.shape
    assert_allclose(y, x)  # gathered = original global array, replicated


class TestLLPersist:
    """Barrier-free LL allgather over the persistent double-buffered
    workspace (VERDICT r2 #6; ≡ the reference's no-barrier LL protocol,
    low_latency_allgather.py:532-569). Correctness must hold across
    consecutive calls — the parity double-buffering and per-parity
    semaphore rows are the whole protocol."""

    def test_sequential_calls_roll_parity(self, mesh8):
        from triton_distributed_tpu.kernels.allgather import _PERSIST_STATES

        _PERSIST_STATES.clear()
        for i in range(5):          # odd+even parities, workspace reuse
            x = _rand((64, 256), seed=100 + i)
            y = all_gather(x, mesh8, "x", method=AllGatherMethod.LL_PERSIST)
            assert_allclose(y, x)

    def test_layer_entry_and_state_reuse(self, mesh8):
        from triton_distributed_tpu.kernels.allgather import (
            _PERSIST_STATES,
            PersistentLLAllGather,
        )
        from triton_distributed_tpu.layers import AllGatherLayer

        _PERSIST_STATES.clear()
        layer = AllGatherLayer(mesh8, "x")
        x = _rand((64, 128), seed=7)
        assert_allclose(layer.forward_ll_persist(x), x)
        assert_allclose(layer.forward_ll_persist(x), x)
        # one persistent context per configuration, reused across calls
        assert len(_PERSIST_STATES) == 1
        st = next(iter(_PERSIST_STATES.values()))
        assert isinstance(st, PersistentLLAllGather)
        assert st.call_idx == 2

    def test_chaos(self, mesh8, monkeypatch):
        """Randomized comm delays widen the skew window the protocol's
        double-buffering must absorb."""
        from triton_distributed_tpu.config import config as cfg
        from triton_distributed_tpu.kernels.allgather import _PERSIST_STATES

        _PERSIST_STATES.clear()
        monkeypatch.setattr(cfg, "chaos_delay", True)
        for i in range(3):
            x = _rand((64, 128), seed=200 + i)
            y = all_gather(x, mesh8, "x", method=AllGatherMethod.LL_PERSIST)
            assert_allclose(y, x)
        _PERSIST_STATES.clear()  # chaos builds must not leak


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_all_gather_dtypes(mesh8, dtype):
    x = _rand((64, 128), dtype)
    y = all_gather(x, mesh8, "x", method=AllGatherMethod.RING_1D)
    assert_allclose(
        np.asarray(y, np.float32), np.asarray(x, np.float32), atol=1e-2, rtol=1e-2
    )


def test_reduce_scatter_vs_xla(mesh8):
    # every device contributes a *different* full matrix: build by giving a
    # device-dependent input through sharding the stack dim
    x = _rand((64, 128))  # replicated input; per-device contribution identical
    y = reduce_scatter(x, mesh8, "x")
    y_ref = reduce_scatter_xla(x, mesh8, "x")
    assert y.shape == (64, 128)
    assert_allclose(y, y_ref, atol=1e-4, rtol=1e-4)
    # identical contributions → sum = 8 * shard
    assert_allclose(y, x * 8.0, atol=1e-4, rtol=1e-4)


def test_reduce_scatter_distinct_contributions(mesh8):
    """Device i contributes x[i]; output shard j must be sum_i x[i][rows_j]."""
    x = _rand((8, 64, 128))  # stacked: dim0 = device
    y = reduce_scatter(x, mesh8, "x", stacked=True)
    expected = np.sum(np.asarray(x), axis=0)  # (64, 128)
    assert y.shape == (64, 128)
    assert_allclose(y, expected, atol=1e-4, rtol=1e-4)
    y_ref = reduce_scatter_xla(x, mesh8, "x", stacked=True)
    assert_allclose(y, y_ref, atol=1e-4, rtol=1e-4)


def test_all_to_all_vs_xla(mesh8):
    x = _rand((64, 128))
    y = all_to_all(x, mesh8, "x")
    y_ref = all_to_all_xla(x, mesh8, "x")
    assert_allclose(y, y_ref)


def test_all_to_all_roundtrip(mesh8):
    x = _rand((64, 128))
    y = all_to_all(all_to_all(x, mesh8, "x"), mesh8, "x")
    assert_allclose(y, x)


def test_all_gather_rank1_bidir_demotes(mesh8):
    """Regression: RING_BIDIR splits dim 1 across the two ring directions,
    which is impossible on rank-1 inputs — the entry must demote to
    RING_1D instead of crashing at trace time."""
    x = jnp.arange(8 * 128, dtype=jnp.float32)
    y = all_gather(x, mesh8, "x", method=AllGatherMethod.RING_BIDIR)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))


def test_all_gather_multiaxis_mesh(mesh2x4):
    """Regression: collectives along the inner axis of a 2x4 ('dp','tp')
    mesh must translate axis-local peers to flat logical device ids —
    without pe_flat this deadlocks (RDMA crosses dp rows)."""
    x = _rand((32, 128))  # sharded over tp=4 → 8 rows/device
    y = all_gather(x, mesh2x4, "tp", method=AllGatherMethod.RING_1D)
    assert_allclose(y, x)
    y = all_gather(x, mesh2x4, "tp", method=AllGatherMethod.LL_SMALL)
    assert_allclose(y, x)


def test_reduce_scatter_multiaxis_mesh(mesh2x4):
    x = _rand((4, 32, 128))
    y = reduce_scatter(x, mesh2x4, "tp", stacked=True)
    expected = np.sum(np.asarray(x), axis=0)
    assert_allclose(y, expected, atol=1e-4, rtol=1e-4)


def test_reduce_scatter_streaming_engine(mesh8, monkeypatch):
    """Payloads over the VMEM budget take the HBM-streaming reduce ring
    (the VMEM ring would OOM at activation-scale shapes); same numerics."""
    from triton_distributed_tpu.config import config as cfg

    # force the streaming engine regardless of payload size
    monkeypatch.setattr(cfg, "fused_vmem_budget", 1)
    x = jax.random.normal(jax.random.PRNGKey(11), (8, 64, 48), jnp.float32)
    out = reduce_scatter(
        jax.device_put(x, NamedSharding(mesh8, P("x"))), mesh8, "x",
        stacked=True, collective_id=3,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x.sum(0)), atol=1e-5, rtol=1e-5
    )
    # non-stacked (replicated contributions)
    y = jax.random.normal(jax.random.PRNGKey(12), (64, 48), jnp.float32)
    out2 = reduce_scatter(y, mesh8, "x", collective_id=3)
    np.testing.assert_allclose(
        np.asarray(out2), np.asarray(y * 8), atol=1e-4, rtol=1e-5
    )
