"""Paged serving: decode_step / generate over page pools + block table.

The reference's block-table path is its DEFAULT decode entry
(flash_decode.py:763-846); round 5 wires the repo's paged int8 pools
into the model's serving loop — init_paged_cache / paginate_caches →
decode_step(block_table=...) with paged attention partials AND the
paged in-place append. These tests pin the paged path to the
contiguous path bit-for-bit on the same state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.models import Transformer, TransformerConfig

CFG = dict(
    vocab=128, n_layers=2, hidden=128, ffn=256,
    n_heads=8, n_kv_heads=4, head_dim=16,
    dtype=jnp.float32, param_dtype=jnp.float32,
)


def _model(mesh, kv_quant=None):
    cfg = TransformerConfig(
        **CFG, moe="ep", moe_layers=(1,), num_experts=8, topk=2,
        kv_quant=kv_quant,
    )
    model = Transformer(cfg, mesh, "tp", ())
    params = jax.tree.map(
        lambda p, s: jax.device_put(p, s),
        model.init(jax.random.PRNGKey(0)), model.shardings(),
    )
    return model, params


@pytest.fixture(scope="module")
def mesh_tp():
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), ("tp",))


class TestPagedServing:
    @pytest.mark.parametrize("kv_quant", [None, "int8"])
    def test_paged_decode_matches_contiguous(self, mesh_tp, kv_quant):
        """prefill → paginate_caches → paged decode_step must equal the
        contiguous decode_step on the same state, across two steps
        (the second step reads back what the paged APPEND wrote)."""
        model, params = _model(mesh_tp, kv_quant)
        b, smax, page = 4, 64, 4          # 8 ranks × 2 pages × 4 rows
        prompt = jax.random.randint(jax.random.PRNGKey(3), (b, 8), 0, 128)
        caches = model.init_cache(b, smax)
        last, caches, lens = model.prefill(params, caches, prompt)
        tok = jnp.argmax(last, axis=-1).astype(jnp.int32)

        pcaches, table = model.paginate_caches(caches, page=page)
        c_caches, c_lens, c_tok = caches, lens, tok
        p_caches, p_lens, p_tok = pcaches, lens, tok
        for _ in range(2):
            lg_c, c_caches, c_lens = model.decode_step(
                params, c_caches, c_lens, c_tok
            )
            lg_p, p_caches, p_lens = model.decode_step(
                params, p_caches, p_lens, p_tok, block_table=table
            )
            np.testing.assert_allclose(
                np.asarray(lg_p), np.asarray(lg_c), atol=1e-5, rtol=1e-5
            )
            c_tok = jnp.argmax(lg_c, axis=-1).astype(jnp.int32)
            p_tok = jnp.argmax(lg_p, axis=-1).astype(jnp.int32)
        assert np.asarray(p_lens).tolist() == np.asarray(c_lens).tolist()

    def test_init_paged_cache_generate(self, mesh_tp):
        """Zero-state paged serving: init_paged_cache + generate over
        the table matches contiguous generate from zero caches."""
        model, params = _model(mesh_tp)
        b, smax, page, steps = 2, 64, 4, 3
        first = jnp.array([5, 9], jnp.int32)
        toks_c, _, lens_c = model.generate(
            params, model.init_cache(b, smax),
            jnp.zeros((b,), jnp.int32), first, steps,
        )
        pcaches, table = model.init_paged_cache(b, smax, page=page)
        toks_p, _, lens_p = model.generate(
            params, pcaches, jnp.zeros((b,), jnp.int32), first, steps,
            block_table=table,
        )
        np.testing.assert_array_equal(np.asarray(toks_c), np.asarray(toks_p))
        assert np.asarray(lens_p).tolist() == [steps] * b

    def test_paged_capacity_contract(self, mesh_tp):
        model, params = _model(mesh_tp)
        with pytest.raises(ValueError, match="rank slices"):
            model.init_paged_cache(2, 60, page=4)   # 60 % (8·4) != 0
        pcaches, table = model.init_paged_cache(2, 64, page=4)
        with pytest.raises(AssertionError, match="capacity"):
            model.generate(
                params, pcaches, jnp.full((2,), 63, jnp.int32),
                jnp.zeros((2,), jnp.int32), 5, block_table=table,
            )


class TestDonatingRunner:
    def test_workspace_buffer_identity(self):
        """The bench's donate-and-thread runner must keep the SAME
        physical workspace buffers across invocations (the LL
        persistent-workspace contract, VERDICT r4 #8)."""
        import sys

        sys.path.insert(0, ".")
        from bench import _make_donating_runner

        x = jnp.ones((8,), jnp.float32)
        ws = jnp.zeros((128,), jnp.float32)

        def step(state, s):
            x, ws = state
            ws = ws + 1.0
            return (x, ws), s + jnp.sum(x) + ws[0]

        call = _make_donating_runner(step, (x, ws), 4, 1)
        d1, s1 = call(ws)
        p1 = d1.unsafe_buffer_pointer()
        d2, s2 = call(d1)
        p2 = d2.unsafe_buffer_pointer()
        assert p1 == p2, "workspace buffer was reallocated across invocations"
        # and the carry really threaded: 4 iters per call, ws grew by 8
        assert float(d2[0]) == 8.0
        assert s2 > s1
