"""ISSUE-20 long-context serving suite: context-parallel decode over
the paged KV pool with the cross-rank LSE-combine.

Four layers of the tentpole, pinned end to end:

* **kernel** — the TOPO_CP row kind: a cp rank's shard-local pool walk
  with the frontier shifted right by ``aux`` tokens, Pallas vs the XLA
  twin, and the shard decomposition (per-shard partials merged by
  ``combine_gqa_partials``) vs one full-length causal run;
* **engine** — a cp=2 :class:`ServingEngine` whose page need EXCEEDS
  one per-shard pool is admitted and produces token streams
  byte-identical to a single-pool oracle — under chunked prefill,
  eviction-under-pressure, int8 KV wire and a tp×cp mesh;
* **wire analysis** — the ``cp_decode.lse_combine`` family lints clean
  at mesh 4 and 8 including inferred contracts, and servlint's cp
  facet (sharded pool, production verbs) explores clean while the
  seeded wrong-shard-free fixture is caught by SV001;
* **fleet/pricing** — the router places long requests only on
  cp-capable replicas and refuses loudly with the perf-model-priced
  reason (``cp_decode_step_ms`` vs the flat single-slice walk).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from triton_distributed_tpu.analysis import servlint
from triton_distributed_tpu.analysis.lint import lint_family
from triton_distributed_tpu.kernels.flash_decode import (
    NEG_INF,
    combine_gqa_partials,
)
from triton_distributed_tpu.kernels.ragged_paged_attention import (
    cp_topology_row,
    pack_gqa_rows,
    ragged_paged_attention,
    ragged_paged_attention_xla,
    topo_width,
)
from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.serving.engine import (
    EngineConfig,
    Request,
    ServingEngine,
)
from triton_distributed_tpu.serving.fleet import ServingFleet
from triton_distributed_tpu.serving.state import CpPagePool
from triton_distributed_tpu.tune import perf_model as pm
from triton_distributed_tpu.tune.schedule import (
    GridSchedule,
    price_grid_schedule,
)

pytestmark = pytest.mark.fast

PAGE = 4


def _tcfg(kv_quant=None):
    return TransformerConfig(
        vocab=128, n_layers=2, hidden=64, ffn=128,
        n_heads=4, n_kv_heads=2, head_dim=16, kv_quant=kv_quant,
    )


def _mesh_tp_cp():
    devs = np.asarray(jax.devices()[:4]).reshape(2, 2)
    return Mesh(devs, ("x", "cpx"))


def _mesh_cp_only():
    devs = np.asarray(jax.devices()[:2]).reshape(1, 2)
    return Mesh(devs, ("x", "cpx"))


def _mesh_tp():
    return Mesh(np.asarray(jax.devices()[:2]), ("x",))


def _engine(mesh, cp_axis, npages, *, kv_quant=None, slots=2,
            budget=16, chunk=8):
    model = Transformer(_tcfg(kv_quant), mesh, tp_axis="x",
                        cp_axis=cp_axis)
    params = model.init(jax.random.PRNGKey(0))
    cfg = EngineConfig(slots=slots, token_budget=budget, chunk=chunk,
                       page=PAGE, npages=npages, max_steps=800,
                       temperature=0.0)
    return ServingEngine(model, params, cfg, use_pallas=False)


def _requests():
    """A long request needing 10 pages (> one 6-page shard pool but
    <= the 12-page cp=2 total) and a short fully-shard-resident one.
    The 30-token prompt prefills in four chunk=8 pieces."""
    rng = np.random.default_rng(0)
    return [
        Request(rid=0, prompt=rng.integers(1, 127, 30, np.int32),
                max_new=10, arrival=0),
        Request(rid=1, prompt=rng.integers(1, 127, 7, np.int32),
                max_new=6, arrival=0),
    ]


def _run(eng):
    done = {}
    eng.on_complete = lambda req, slot: done.setdefault(
        req.rid, list(req.generated)) or True
    eng.run(_requests())
    return done


def _assert_drained(pool):
    assert int(np.asarray(pool.refs).sum()) == 0
    assert len(pool.free) + len(pool._reclaim) == pool.npages


@pytest.fixture(scope="module")
def oracle_streams():
    """Single-pool (cp-free) oracle token streams for ``_requests``."""
    return _run(_engine(_mesh_tp(), None, 12))


class TestCpDecodeExactness:
    def test_long_request_exceeds_one_pool_token_exact(
            self, oracle_streams):
        eng = _engine(_mesh_tp_cp(), "cpx", 6)
        assert isinstance(eng.pool, CpPagePool)
        assert eng.pool.npages == 12          # 2 shards x 6
        done = _run(eng)
        assert done == oracle_streams
        # the long request really crossed a shard boundary
        assert -(-(30 + 10) // PAGE) == 10 > 6
        _assert_drained(eng.pool)

    def test_eviction_mid_decode_stays_exact(self, oracle_streams):
        """10 total pages against a 14-page working set: the scheduler
        must evict mid-decode and recompute — greedy streams stay
        byte-identical to the pressure-free oracle."""
        eng = _engine(_mesh_tp_cp(), "cpx", 5)
        done = _run(eng)
        assert eng.stats.evictions > 0
        assert done == oracle_streams
        _assert_drained(eng.pool)

    def test_int8_kv_exact(self):
        """int8 KV wire: page-local quantization is identical across
        pool layouts, so cp=2 still matches its int8 oracle exactly."""
        oracle = _run(_engine(_mesh_tp(), None, 12, kv_quant="int8"))
        eng = _engine(_mesh_tp_cp(), "cpx", 6, kv_quant="int8")
        done = _run(eng)
        assert done == oracle
        _assert_drained(eng.pool)

    def test_cp_without_tp_token_exact(self, oracle_streams):
        """A pure cp mesh (tp=1) — cp is orthogonal to head sharding."""
        eng = _engine(_mesh_cp_only(), "cpx", 6)
        assert eng.model.cp == 2 and eng.model.tp == 1
        assert _run(eng) == oracle_streams
        _assert_drained(eng.pool)

    def test_cp_rejects_prefix_share_and_speculation(self):
        model = Transformer(_tcfg(), _mesh_tp_cp(), tp_axis="x",
                            cp_axis="cpx")
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="context-parallel"):
            ServingEngine(
                model, params,
                EngineConfig(slots=2, token_budget=16, chunk=8,
                             page=PAGE, npages=6, prefix_cache=True,
                             prefix_share=True),
                use_pallas=False)


class TestCpKernelTopology:
    HKV, G, D, PPS = 2, 2, 32, 4
    KPAGE = 8

    def _pool(self, rng, npages):
        k = jnp.asarray(rng.standard_normal(
            (npages, self.HKV, self.KPAGE, self.D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal(
            (npages, self.HKV, self.KPAGE, self.D)), jnp.float32)
        return k, v

    def test_shard_decomposition_matches_full_causal(self):
        """kv=37 split as shard0=24 (shift 13) + shard1=13 (shift 0) +
        an empty shard: the LSE-combined per-shard partials equal one
        full-length causal decode, and the empty shard's lse is
        NEG_INF (zero combine weight)."""
        rng = np.random.default_rng(3)
        kpool, vpool = self._pool(rng, 8)
        kv = 37
        q = pack_gqa_rows(jnp.asarray(
            rng.standard_normal((8, self.HKV * self.G, self.D)),
            jnp.float32), self.HKV)
        width = topo_width(8)

        def run(kv_len, table, topo):
            return ragged_paged_attention_xla(
                q, kpool, vpool,
                jnp.asarray([kv_len], jnp.int32),
                jnp.asarray([1], jnp.int32),
                jnp.asarray([0], jnp.int32),
                jnp.asarray([table], jnp.int32),
                group=self.G, topologies=topo)

        full_t = [0, 1, 2, 3, 4]                    # 37 tokens, 5 pages
        out_ref, _ = run(kv, full_t, None)

        shards = [(24, [0, 1, 2, -1, -1], 13),      # covered: shift 13
                  (13, [3, 4, -1, -1, -1], 0),      # frontier: causal
                  (0, [0, -1, -1, -1, -1], 0)]      # past the data
        outs, lses = [], []
        for kv_len, table, shift in shards:
            topo = np.stack([cp_topology_row(shift, width)])
            o, l = run(kv_len, table, topo)
            outs.append(o)
            lses.append(l)
        assert bool((lses[2][:, :self.G] <= NEG_INF / 2).all())
        merged, _ = combine_gqa_partials(
            jnp.stack(outs), jnp.stack(lses))
        np.testing.assert_allclose(
            np.asarray(merged[:, :self.G]),
            np.asarray(out_ref[:, :self.G]), atol=2e-5, rtol=2e-5)

    def test_cp_rows_pallas_matches_xla(self):
        """The TOPO_CP mask inside the Pallas kernel (interpreted)
        against the dense twin: one fully-covered shard row (shift >=
        q_len) and one frontier row (shift 0) in a single launch."""
        rng = np.random.default_rng(4)
        kpool, vpool = self._pool(rng, 16)
        q = pack_gqa_rows(jnp.asarray(
            rng.standard_normal((16, self.HKV * self.G, self.D)),
            jnp.float32), self.HKV)
        width = topo_width(8)
        topo = np.stack([cp_topology_row(13, width),
                         cp_topology_row(0, width)])
        args = (
            q, kpool, vpool,
            jnp.asarray([24, 13], jnp.int32),    # kv_lens
            jnp.asarray([1, 1], jnp.int32),      # q_lens
            jnp.asarray([0, 8], jnp.int32),      # q_starts
            jnp.asarray([[0, 1, 2, -1], [3, 4, -1, -1]], jnp.int32),
        )
        out_p, lse_p = ragged_paged_attention(
            *args, group=self.G, topologies=topo, block_q=8)
        out_x, lse_x = ragged_paged_attention_xla(
            *args, group=self.G, topologies=topo)
        for r, start in ((0, 0), (1, 8)):
            s = slice(start * self.G, start * self.G + self.G)
            np.testing.assert_allclose(
                np.asarray(out_p[:, s]), np.asarray(out_x[:, s]),
                atol=2e-5, rtol=2e-5)
            np.testing.assert_allclose(
                np.asarray(lse_p[:, s]), np.asarray(lse_x[:, s]),
                atol=2e-5, rtol=2e-5)


class TestCpCombineWireAnalysis:
    @pytest.mark.parametrize("n", [4, 8])
    def test_family_lints_clean(self, n):
        assert lint_family("cp_decode.lse_combine", n) == []

    @pytest.mark.parametrize("n", [4, 8])
    def test_family_contracts_inferable(self, n):
        assert lint_family("cp_decode.lse_combine", n,
                           infer_contracts=True) == []

    def test_servlint_cp_facet(self):
        """The sharded-pool clean half explores clean; the seeded
        wrong-shard free is caught by SV001 with a minimal repro."""
        findings, _ = servlint.lint_serving(
            servlint.CpProtocolOps(), max_states=1500)
        assert findings == []
        findings, _ = servlint.lint_serving(fixture="SV001cp",
                                            max_states=4000)
        assert [f.rule for f in findings] == ["SV001"]
        assert "repro:" in findings[0].message


class TestLongContextPlacement:
    def _fleet(self, with_cp):
        replicas = [_engine(_mesh_tp(), None, 6)]
        if with_cp:
            replicas.append(_engine(_mesh_tp_cp(), "cpx", 6))
        return ServingFleet(replicas, seed=0)

    def test_long_request_lands_on_cp_replica(self):
        fleet = self._fleet(with_cp=True)
        for r in _requests():
            fleet.submit(r)
        stats = fleet.run(max_ticks=800)
        assert stats.completed == 2
        assert stats.long_context_refusals == []
        # the 10-page request can only have landed on replica 1
        assert stats.routed.get(1, 0) >= 1

    def test_refusal_priced_when_no_cp_replica(self):
        fleet = self._fleet(with_cp=False)
        reqs = _requests()
        for r in reqs:
            fleet.submit(r)
        stats = fleet.run(max_ticks=800)
        assert len(stats.long_context_refusals) == 1
        rid, reason = stats.long_context_refusals[0]
        assert rid == 0
        for token in ("cp=", "ms/step", "LSE-combine"):
            assert token in reason, reason
        long_req = reqs[0]
        assert long_req.done and long_req.refusal == reason
        # the short request still completed normally
        assert stats.records[1]["completion_tick"] is not None
        assert any(ev[0] == "long_context_refusal"
                   for ev in stats.events)

    def test_replica_fits_context(self):
        eng = _engine(_mesh_tp(), None, 6)
        fleet = ServingFleet([eng], seed=0)
        rep = fleet.replicas[0]
        assert rep.cp == 1
        ok, too_long = _requests()[1], _requests()[0]
        assert rep.fits_context(ok)
        assert not rep.fits_context(too_long)


class TestCpPerfModel:
    KW = dict(page=16, hkv=8, g=8, d=128, hidden=4096, n_layers=32)

    def test_cp1_degenerates_to_flat_walk(self):
        flat = pm.ragged_serving_step_ms([4096], [1], **self.KW)
        assert pm.cp_decode_step_ms(4096, cp=1, **self.KW) == flat

    def test_crossover_long_wins_short_pays_hop_tax(self):
        long, short = 512 * 1024, 128
        assert (pm.cp_decode_step_ms(long, cp=8, **self.KW)
                < pm.cp_decode_step_ms(long, cp=1, **self.KW))
        assert (pm.cp_decode_step_ms(short, cp=8, **self.KW)
                > pm.cp_decode_step_ms(short, cp=1, **self.KW))

    def test_refuse_long_context_contract(self):
        cfg = _tcfg()
        fits = pm.refuse_long_context(
            cfg, PAGE, 5, pool_pages=6, pages_per_seq=12)
        assert fits is None
        reason = pm.refuse_long_context(
            cfg, PAGE, 10, pool_pages=6, pages_per_seq=12)
        assert reason is not None
        for token in ("10 KV pages", "cp=2", "ms/step",
                      "LSE-combine", "cp-capable"):
            assert token in reason, reason
        # a request deeper than 2x the shard prices a deeper cp
        deep = pm.refuse_long_context(
            cfg, PAGE, 23, pool_pages=6, pages_per_seq=64)
        assert "cp=4" in deep


class TestChunkTrafficKey:
    def test_engine_grid_key_carries_chunk(self):
        eng = _engine(_mesh_tp(), None, 12, chunk=8)
        key = eng._grid_key
        assert len(key) == 9
        assert key[5] == PAGE and key[6] == 8

    def test_pricer_chunk_tail_pad_term(self):
        """The same geometry at a different prefill chunk prices
        differently under a pinned block_q — chunk 33 wastes a near-
        full 32-row block per prefill row, chunk 64 wastes none."""
        geom = (8, 128, 2, 4, 128, 16)
        sched = GridSchedule(block_q=32)
        base = price_grid_schedule(
            "flash_decode.ragged_paged", sched, shape=geom)
        aligned = price_grid_schedule(
            "flash_decode.ragged_paged", sched, shape=geom + (64,))
        ragged = price_grid_schedule(
            "flash_decode.ragged_paged", sched, shape=geom + (33,))
        assert aligned == base          # zero pad: term vanishes
        assert ragged > aligned         # 31 wasted q rows per prefill
