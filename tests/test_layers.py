"""Layer (L5) tests: AG layer, TP linears/MLP, MoE MLPs, SP decode layer.

Mirrors the reference's layer-level tests (test_sp_decode_attn.py,
test_ep_moe_inference.py, low_latency_allgather_layer usage) with
jax.lax/dense references (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu import layers, ops
from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.kernels.flash_decode import gqa_fwd_batch_decode_xla
from triton_distributed_tpu.runtime import AllGatherMethod
from triton_distributed_tpu.utils import assert_allclose


def _put(mesh, x, spec):
    return jax.device_put(x, NamedSharding(mesh, spec))


class TestAllGatherLayer:
    def test_all_variants_match(self, mesh8):
        x = jax.random.normal(jax.random.PRNGKey(0), (64, 128))
        layer = layers.AllGatherLayer(mesh8, "x")
        ref = np.asarray(layer.forward_xla(_put(mesh8, x, P("x"))))
        np.testing.assert_allclose(ref, np.asarray(x), rtol=1e-6)
        for fwd in (layer.forward_ring, layer.forward_ring_bidir, layer.forward_ll, layer):
            out = np.asarray(fwd(_put(mesh8, x, P("x"))))
            np.testing.assert_allclose(out, ref, rtol=1e-6)


class TestParallelMLP:
    def test_mlp_vs_dense(self, mesh8):
        m, h, f = 64, 128, 512
        ag_ctx = ops.create_ag_gemm_context(mesh8, "x")
        rs_ctx = ops.create_gemm_rs_context(mesh8, "x")
        mlp = layers.ParallelMLP(
            layers.ColumnParallelLinear(ag_ctx),
            layers.RowParallelLinear(rs_ctx),
        )
        params = mlp.init(jax.random.PRNGKey(0), h, f, jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(1), (m, h), jnp.float32)
        out = mlp(
            {
                "up": {"w": _put(mesh8, params["up"]["w"], P(None, "x"))},
                "down": {"w": _put(mesh8, params["down"]["w"], P("x", None))},
            },
            _put(mesh8, x, P("x")),
        )
        ref = jax.nn.gelu(x @ params["up"]["w"]) @ params["down"]["w"]
        assert_allclose(out, ref, atol=2e-4, rtol=2e-4)

    def test_mlp_trains(self, mesh8):
        """Gradients flow through both overlap ops."""
        m, h, f = 64, 128, 256
        ag_ctx = ops.create_ag_gemm_context(mesh8, "x")
        rs_ctx = ops.create_gemm_rs_context(mesh8, "x")
        mlp = layers.ParallelMLP(
            layers.ColumnParallelLinear(ag_ctx),
            layers.RowParallelLinear(rs_ctx),
        )
        params = mlp.init(jax.random.PRNGKey(0), h, f, jnp.float32)
        sharded = {
            "up": {"w": _put(mesh8, params["up"]["w"], P(None, "x"))},
            "down": {"w": _put(mesh8, params["down"]["w"], P("x", None))},
        }
        x = jax.random.normal(jax.random.PRNGKey(1), (m, h), jnp.float32)
        y = jax.random.normal(jax.random.PRNGKey(2), (m, h), jnp.float32)

        def loss(p, x):
            return jnp.mean((mlp(p, x) - y) ** 2)

        def loss_ref(p, x):
            return jnp.mean((jax.nn.gelu(x @ p["up"]["w"]) @ p["down"]["w"] - y) ** 2)

        g = jax.grad(loss)(sharded, _put(mesh8, x, P("x")))
        g_ref = jax.grad(loss_ref)(params, x)
        assert_allclose(g["up"]["w"], g_ref["up"]["w"], atol=1e-4, rtol=1e-3)
        assert_allclose(g["down"]["w"], g_ref["down"]["w"], atol=1e-4, rtol=1e-3)


class TestMoELayers:
    def test_ep_moe_mlp(self, mesh8):
        n, e, topk, h, f, mtok = 8, 16, 2, 128, 256, 16
        ctx = ops.create_ep_moe_context(
            mesh8, "x", num_experts=e, topk=topk, max_m=mtok * topk,
            hidden=h, dtype=jnp.float32, transport="pallas", block_m=8,
        )
        mlp = layers.EPMoEMLP(ctx)
        params = mlp.init(jax.random.PRNGKey(0), f, jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(1), (n * mtok, h), jnp.float32)

        out = mlp(
            {
                "router": params["router"],
                "up": _put(mesh8, params["up"], P("x")),
                "down": _put(mesh8, params["down"], P("x")),
            },
            _put(mesh8, x, P("x")),
        )
        logits = x @ params["router"]
        weights, ids = mu.select_experts(logits, topk)
        ref = jnp.zeros((n * mtok, h))
        for t in range(topk):
            hh = jax.nn.silu(jnp.einsum("mh,mhf->mf", x, params["up"][ids[:, t]]))
            ref += weights[:, t : t + 1] * jnp.einsum(
                "mf,mfh->mh", hh, params["down"][ids[:, t]]
            )
        assert_allclose(out, ref, atol=1e-4, rtol=1e-4)

    def test_moe_tp_mlp(self, mesh8):
        e, topk, m, h, f = 16, 2, 64, 128, 512
        ctx = ops.create_ag_group_gemm_context(
            mesh8, "x", num_experts=e, topk=topk, block_m=8, dtype=jnp.float32
        )
        mlp = layers.MoETPMLP(ctx)
        w_up = jax.random.normal(jax.random.PRNGKey(0), (e, h, f), jnp.float32) * 0.05
        w_down = jax.random.normal(jax.random.PRNGKey(1), (e, f, h), jnp.float32) * 0.05
        x = jax.random.normal(jax.random.PRNGKey(2), (m, h), jnp.float32)
        logits = jax.random.normal(jax.random.PRNGKey(3), (m, e))
        weights, ids = mu.select_experts(logits, topk)
        out = mlp(
            {
                "up": _put(mesh8, w_up, P(None, None, "x")),
                "down": _put(mesh8, w_down, P(None, "x")),
            },
            _put(mesh8, x, P("x")),
            ids, weights,
        )
        ref = jnp.zeros((m, h))
        for t in range(topk):
            hh = jax.nn.silu(jnp.einsum("mk,mkf->mf", x, w_up[ids[:, t]]))
            ref += weights[:, t : t + 1] * jnp.einsum(
                "mf,mfh->mh", hh, w_down[ids[:, t]]
            )
        assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_ep_a2a_layer_roundtrip(self, mesh8):
        """dispatch → identity → combine returns the sorted tokens."""
        from triton_distributed_tpu.kernels import moe_all_to_all as ma

        n, epr, hdim, max_m, m = 8, 2, 128, 16, 12
        e = n * epr
        a2a = ma.create_all_to_all_context(
            mesh8, "x", max_m=max_m, hidden=hdim,
            experts_per_rank=epr, dtype=jnp.float32,
        )
        layer = layers.EPAll2AllLayer(a2a)
        rng = np.random.default_rng(0)
        assign = np.sort(rng.integers(0, e, size=(n, m)), axis=1)
        splits = np.stack(
            [np.bincount(assign[d], minlength=e) for d in range(n)]
        ).astype(np.int32)
        toks = rng.standard_normal((n, m, hdim)).astype(np.float32)

        def body(t, s):
            recv, rs = layer.dispatch(t, s)
            return layer.combine(recv, s, m)

        fn = jax.jit(
            jax.shard_map(
                body, mesh=mesh8, in_specs=(P("x"), P("x")),
                out_specs=P("x"), check_vma=False,
            )
        )
        back = fn(
            _put(mesh8, jnp.asarray(toks).reshape(n * m, hdim), P("x")),
            _put(mesh8, jnp.asarray(splits).reshape(n * e), P("x")),
        )
        np.testing.assert_allclose(
            np.asarray(back).reshape(n, m, hdim), toks, rtol=1e-6
        )


class TestSpDecodeLayer:
    @pytest.mark.parametrize("kv_layout", ["bshd", "bhsd"])
    def test_vs_xla(self, mesh8, kv_layout):
        b, hq, hkv, d, s = 2, 8, 2, 128, 1024
        layer = layers.SpGQAFlashDecodeAttention(
            mesh8, "x", q_heads=hq, kv_heads=hkv, head_dim=d, block_k=128,
            kv_layout=kv_layout,
        )
        q = jax.random.normal(jax.random.PRNGKey(0), (b, hq, d), jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d), jnp.float32)
        v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d), jnp.float32)
        lens = jnp.array([900, 400], jnp.int32)
        ref, _ = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bshd")
        if kv_layout == "bhsd":
            k = k.transpose(0, 2, 1, 3)
            v = v.transpose(0, 2, 1, 3)
        out = layer(q, k, v, lens)
        assert_allclose(out, ref, atol=2e-2, rtol=2e-2)

    def test_uneven_block_k(self, mesh8):
        """SP cache slices need not divide block_k: a 384-capacity slice
        with the default block must round down, not assert (ADVICE r1)."""
        b, hq, hkv, d, s = 2, 8, 2, 128, 8 * 384
        layer = layers.SpGQAFlashDecodeAttention(
            mesh8, "x", q_heads=hq, kv_heads=hkv, head_dim=d,
            kv_layout="bhsd",
        )
        q = jax.random.normal(jax.random.PRNGKey(0), (b, hq, d), jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, hkv, s, d), jnp.float32)
        v = jax.random.normal(jax.random.PRNGKey(2), (b, hkv, s, d), jnp.float32)
        lens = jnp.array([1000, 500], jnp.int32)
        out = layer(q, k, v, lens)
        ref, _ = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bhsd")
        assert_allclose(out, ref, atol=2e-2, rtol=2e-2)

    @pytest.mark.parametrize("kv_layout", ["bshd", "bhsd"])
    def test_append_kv(self, kv_layout):
        b, s, hkv, d = 2, 8, 2, 128
        shape = (b, s, hkv, d) if kv_layout == "bshd" else (b, hkv, s, d)
        k = jnp.zeros(shape)
        v = jnp.zeros(shape)
        lens = jnp.array([3, 5], jnp.int32)
        kn = jnp.ones((b, hkv, d))
        k2, v2, lens2 = layers.append_kv(k, v, lens, kn, kn * 2, kv_layout=kv_layout)
        np.testing.assert_array_equal(np.asarray(lens2), [4, 6])
        if kv_layout == "bshd":
            at = lambda c, bi, si: c[bi, si]
        else:
            at = lambda c, bi, si: c[bi, :, si]
        assert float(at(k2, 0, 3).sum()) == hkv * d
        assert float(at(v2, 1, 5).sum()) == 2 * hkv * d
        assert float(at(k2, 0, 4).sum()) == 0

    def test_append_kv_int8_prequantized_is_bit_exact(self):
        """Threading the already-computed (q, scale) pairs into the int8
        append caches EXACTLY the ints the caller attended — the bf16
        round-trip re-quantization can differ by 1 LSB (ADVICE r5),
        which is what decode_step used to rely on not happening."""
        from triton_distributed_tpu.kernels.flash_decode import quantize_kv

        b, s, hkv, d = 2, 8, 2, 128
        kc = {
            "q": jnp.zeros((b, hkv, s, d), jnp.int8),
            "scale": jnp.zeros((b, hkv, s), jnp.float32),
        }
        vc = {
            "q": jnp.zeros((b, hkv, s, d), jnp.int8),
            "scale": jnp.zeros((b, hkv, s), jnp.float32),
        }
        lens = jnp.array([3, 5], jnp.int32)
        kn = jax.random.normal(jax.random.PRNGKey(3), (b, hkv, d), jnp.float32)
        vn = jax.random.normal(jax.random.PRNGKey(4), (b, hkv, d), jnp.float32)
        kq, ks = quantize_kv(kn)
        vq, vs = quantize_kv(vn)
        # the decode_step path: attend the DEQUANTIZED bf16 round-trip,
        # but append the original pairs
        kn_rt = (kq.astype(jnp.float32) * ks[..., None]).astype(jnp.bfloat16)
        vn_rt = (vq.astype(jnp.float32) * vs[..., None]).astype(jnp.bfloat16)
        k2, v2, _ = layers.append_kv(
            kc, vc, lens, kn_rt, vn_rt, kv_layout="bhsd",
            k_quant=(kq, ks), v_quant=(vq, vs),
        )
        np.testing.assert_array_equal(
            np.asarray(k2["q"][0, :, 3]), np.asarray(kq[0])
        )
        np.testing.assert_array_equal(
            np.asarray(k2["scale"][0, :, 3]), np.asarray(ks[0])
        )
        np.testing.assert_array_equal(
            np.asarray(v2["q"][1, :, 5]), np.asarray(vq[1])
        )
        # and the legacy path (no pairs) still works
        k3, v3, _ = layers.append_kv(
            kc, vc, lens, kn, vn, kv_layout="bhsd"
        )
        np.testing.assert_array_equal(
            np.asarray(k3["q"][0, :, 3]), np.asarray(kq[0])
        )
