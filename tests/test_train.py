"""The training subsystem (ISSUE 14): EF/SR quantized gradient rings,
the dp×tp×cp train step, and its ledger-driven wire degradation.

The reference repo trains on raw NCCL; the properties pinned here are
the ones this port's wire stack adds:

* the gradient ring's error feedback telescopes the LINK-AGGREGATE
  (stripe-summed) error — strictly below the no-EF control for > 1 hop
  and sublinear in hop count (per-element error is the unbiased SR
  noise floor either way; see train/grad_wire.py's module docstring),
* seeded stochastic rounding is bit-deterministic and rank-identical,
* the wire resolve contract is loud (pinned raises, auto demotes),
* the dp2×tp2×cp2 step tracks the single-device dense reference within
  a pinned tolerance on both the quantized ring and the psum twin,
* a chaos Stall on the grad ring trips the watchdog, demotes the step
  to the XLA twin through the HealthLedger, and probation re-promotes.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.fast

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from triton_distributed_tpu import train  # noqa: E402
from triton_distributed_tpu.train import grad_wire, step as stepmod  # noqa: E402


def _submesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("x",))


def _allreduce(mesh, n, wire, seed, ef=True):
    """Per-rank (rows, cols) partials → stacked per-rank sums
    (n·rows, cols): rank r's result slab at rows [r·rows, (r+1)·rows)."""
    fn = jax.shard_map(
        lambda x: grad_wire.grad_allreduce_device(
            x, "x", n=n, wire=wire, seed=seed, ef=ef),
        mesh=mesh, in_specs=P("x", None), out_specs=P("x", None),
        check_vma=False,
    )
    return jax.jit(fn)


def _reduce_scatter(mesh, n, wire, seed, ef):
    """Per-rank (n·srows, cols) partials → the reduced slab
    (n·srows, cols): stripe s is rank s's owned output."""
    fn = jax.shard_map(
        lambda x: grad_wire.ef_ring_reduce_scatter(
            x, "x", n=n, wire=wire, seed=seed, ef=ef),
        mesh=mesh, in_specs=P("x", None), out_specs=P("x", None),
        check_vma=False,
    )
    return jax.jit(fn)


def _partials(n, srows, cols, seed):
    """Per-rank partial slabs: rank r's (n·srows, cols) block of the
    returned (n·n·srows, cols) array."""
    rng = np.random.RandomState(seed)
    return rng.standard_normal((n * n * srows, cols)).astype(np.float32)


def _rs_errors(n, seed, ef, srows=8, cols=128):
    """(per-element |err| mean, link-aggregate |err| mean) of the
    quantized reduce-scatter vs the exact f32 reduction."""
    mesh = _submesh(n)
    x = _partials(n, srows, cols, seed)
    exact = x.reshape(n, n * srows, cols).sum(axis=0)
    out = np.asarray(
        _reduce_scatter(mesh, n, "int8", seed=seed + 7, ef=ef)(x))
    err = out - exact                           # (n·srows, cols)
    agg = err.reshape(n, srows, cols).sum(axis=0)   # stripe-summed
    return float(np.abs(err).mean()), float(np.abs(agg).mean())


# ------------------------------------------------- ring numerics + EF


class TestGradRing:
    def test_allreduce_matches_psum_and_is_rank_identical(self):
        n, rows, cols = 4, 16, 128
        mesh = _submesh(n)
        rng = np.random.RandomState(0)
        x = rng.standard_normal((n * rows, cols)).astype(np.float32)
        exact = x.reshape(n, rows, cols).sum(axis=0)
        out = np.asarray(_allreduce(mesh, n, "int8", seed=3)(x))
        blocks = out.reshape(n, rows, cols)
        # every rank consumed the same shipped bytes: bit-identical
        for r in range(1, n):
            assert (blocks[r] == blocks[0]).all(), r
        # per-element error bounded vs the exact reduction
        tol = 3e-2 * np.abs(exact).max()
        assert np.abs(blocks[0] - exact).max() < tol

    def test_wire_none_is_exact_psum(self):
        n, rows, cols = 4, 8, 128
        mesh = _submesh(n)
        x = np.random.RandomState(1).standard_normal(
            (n * rows, cols)).astype(np.float32)
        exact = x.reshape(n, rows, cols).sum(axis=0)
        out = np.asarray(_allreduce(mesh, n, None, seed=0)(x))
        np.testing.assert_allclose(
            out.reshape(n, rows, cols)[0], exact, rtol=1e-6, atol=1e-5)

    def test_same_seed_bit_identical_different_seed_not(self):
        n = 4
        mesh = _submesh(n)
        x = np.random.RandomState(2).standard_normal(
            (n * 16, 128)).astype(np.float32)
        a = np.asarray(_allreduce(mesh, n, "int8", seed=11)(x))
        b = np.asarray(_allreduce(mesh, n, "int8", seed=11)(x))
        c = np.asarray(_allreduce(mesh, n, "int8", seed=12)(x))
        assert (a == b).all()
        assert (a != c).any()

    @pytest.mark.parametrize("n", [4, 8])
    def test_ef_aggregate_error_below_no_ef_control(self, n):
        """The EF claim, measured on the metric EF actually bounds: the
        stripe-summed (link-aggregate) error. Per hop, EF folds the
        previous rounding's residual into the next message, so a rank's
        shipped total telescopes to ONE residual; the no-EF control
        accumulates n-1 independent roundings. (Per-element error is
        the unbiased SR noise floor either way — deliberately NOT the
        metric here.) Averaged over seeds for stability."""
        ef_aggs, ctl_aggs = [], []
        for seed in (0, 1, 2):
            _, agg_ef = _rs_errors(n, seed, ef=True)
            _, agg_ctl = _rs_errors(n, seed, ef=False)
            ef_aggs.append(agg_ef)
            ctl_aggs.append(agg_ctl)
        assert np.mean(ef_aggs) < np.mean(ctl_aggs), (ef_aggs, ctl_aggs)

    def test_ef_aggregate_error_sublinear_in_hops(self):
        """Hop growth: 3 hops (n=4) → 7 hops (n=8). With EF the
        aggregate error must grow SLOWER than the hop count; the no-EF
        control is free to grow at (or beyond) √hops."""
        ef4 = np.mean([_rs_errors(4, s, ef=True)[1] for s in (0, 1, 2)])
        ef8 = np.mean([_rs_errors(8, s, ef=True)[1] for s in (0, 1, 2)])
        assert ef8 / ef4 < 7.0 / 3.0, (ef4, ef8)


# ------------------------------------------------------ wire resolve


class TestResolveContract:
    def test_auto_demotes_silently(self):
        # 6 rows over an 8-ring: no legal chunking → exact wire
        assert grad_wire.resolve_grad_wire("auto", 6, 128, 8) is None

    def test_pinned_ineligible_raises(self):
        with pytest.raises(ValueError, match="pinned wire format"):
            grad_wire.resolve_grad_wire("int8", 6, 128, 8)

    def test_eligible_resolves(self):
        assert grad_wire.resolve_grad_wire("auto", 64, 128, 8) == "int8"
        assert grad_wire.resolve_grad_wire("fp8", 64, 128, 8) == "fp8"

    def test_bf16_and_none_are_exact(self):
        assert grad_wire.resolve_grad_wire(None, 64, 128, 8) is None
        assert grad_wire.resolve_grad_wire("bf16", 64, 128, 8) is None

    def test_trainer_pinned_config_refuses_at_init(self):
        # a vocab-1 model's slab is too small for an int8 ring over dp=8
        with pytest.raises(ValueError):
            grad_wire.resolve_grad_wire("int8", 2, 128, 8)


# ------------------------------------------------------- train step


def _reference_losses(cfg, batches):
    params = stepmod.init_params(cfg)
    opt = stepmod.init_opt_state(params)
    losses = []
    for tok, tgt in batches:
        params, opt, loss = train.train_step_reference(
            params, opt, tok, tgt, cfg)
        losses.append(float(loss))
    return losses


class TestTrainStep:
    STEPS = 4
    TOL = 0.05          # pinned |loss_dist - loss_ref| per step

    def _trainer_losses(self, cfg):
        tr = train.Trainer(cfg)
        batches = [tr.make_batch(k) for k in range(self.STEPS)]
        dist = [tr.step(tok, tgt)["loss"] for tok, tgt in batches]
        return tr, dist, _reference_losses(cfg, batches)

    def test_wire_step_tracks_reference(self):
        cfg = train.TrainConfig()          # dp2×tp2×cp2, int8 ring
        tr, dist, ref = self._trainer_losses(cfg)
        assert tr.wire == "int8"
        assert abs(dist[0] - ref[0]) < 1e-4     # identical initial params
        for d, r in zip(dist, ref):
            assert abs(d - r) < self.TOL, (dist, ref)
        # the wire actually halves the ring bytes
        assert tr.wire_report()["ratio"] > 1.9

    def test_psum_twin_tracks_reference(self):
        cfg = train.TrainConfig(wire_dtype=None)
        tr, dist, ref = self._trainer_losses(cfg)
        assert tr.wire is None
        for d, r in zip(dist, ref):
            assert abs(d - r) < self.TOL, (dist, ref)

    def test_ulysses_attention_step(self):
        cfg = train.TrainConfig(attn="ulysses")
        tr, dist, ref = self._trainer_losses(cfg)
        for d, r in zip(dist, ref):
            assert abs(d - r) < self.TOL, (dist, ref)

    def test_step_is_deterministic(self):
        cfg = train.TrainConfig()
        a = [r["loss"] for r in train.Trainer(cfg).run(3)]
        b = [r["loss"] for r in train.Trainer(cfg).run(3)]
        assert a == b


# ------------------------------------------------- chaos + probation


@pytest.mark.chaos
class TestGradRingDegradation:
    def test_stall_trips_degrades_and_reprobes(self):
        """The full degradation loop: a fault-plan Stall at site
        ``grad_ring`` wedges the wire step mid-run; the armed watchdog
        trips, names the site, and broadcasts ``site:grad_ring`` FATAL
        into the trainer's ledger; the next step demotes to the exact
        psum twin; clean degraded steps earn PROBATION; seeded probes
        re-promote the ring — and it STAYS promoted."""
        from triton_distributed_tpu.runtime import faults, watchdog
        from triton_distributed_tpu.runtime.faults import FaultPlan, Stall
        from triton_distributed_tpu.runtime.health import PeerState
        from triton_distributed_tpu.runtime.watchdog import WatchdogTimeout

        tr = train.Trainer(train.TrainConfig())
        assert tr.step()["wire"] == "int8"      # warm compile first

        plan = FaultPlan(seed=0, faults=(Stall(site="grad_ring", rank=0),))
        with faults.fault_plan(plan):
            with pytest.raises(WatchdogTimeout):
                with watchdog.collective_watchdog(deadline=0.2):
                    tr.step()
        assert tr.health.state("site:grad_ring") is PeerState.UNHEALTHY

        post = tr.step()
        assert post["wire"] is None and post["degraded"]

        reports = [tr.step() for _ in range(40)]
        assert any(r["probing"] for r in reports)
        assert tr.repromotions >= 1
        tail = tr.step()
        assert tail["wire"] == "int8" and not tail["degraded"]

    def test_probe_failure_falls_back_to_unhealthy(self):
        """A probe that raises drops the ring straight back to
        UNHEALTHY (no partial credit), and the step still completes on
        the twin."""
        from triton_distributed_tpu.runtime.health import PeerState

        tr = train.Trainer(train.TrainConfig())
        tr.step()
        tr.health.record("watchdog_trip", "site:grad_ring", fatal=True)
        assert tr.step()["wire"] is None        # demoted

        # walk to PROBATION, then sabotage exactly the probe step
        real_run = tr._run
        while not tr.health.probe_due("site:grad_ring", tr.step_count):
            tr.step()
            assert tr.health.state("site:grad_ring") is not None

        def boom(tokens, targets):
            if tr.use_wire:
                raise RuntimeError("injected ring failure")
            return real_run(tokens, targets)

        tr._run = boom
        r = tr.step()
        assert r["wire"] is None                # completed on the twin
        assert tr.health.state("site:grad_ring") is PeerState.UNHEALTHY
        tr._run = real_run


# --------------------------------------------------- overlap bwd wire


class TestOverlapBackwardWire:
    def test_ag_gemm_quantized_duals_track_exact(self, mesh8):
        from triton_distributed_tpu.ops import overlap

        a = np.random.RandomState(1).standard_normal(
            (64, 32)).astype(np.float32)
        b = np.random.RandomState(2).standard_normal(
            (32, 128)).astype(np.float32)

        def grads(ctx):
            f = lambda a_, b_: jnp.sum(overlap.ag_gemm(a_, b_, ctx) ** 2)
            da, db = jax.grad(f, argnums=(0, 1))(jnp.asarray(a),
                                                 jnp.asarray(b))
            return np.asarray(da), np.asarray(db)

        da0, db0 = grads(overlap.create_ag_gemm_context(mesh8, "x"))
        da8, db8 = grads(overlap.create_ag_gemm_context(
            mesh8, "x", bwd_wire_dtype="int8"))
        assert np.abs(da8 - da0).max() < 5e-2 * np.abs(da0).max()
        assert np.abs(db8 - db0).max() < 5e-2 * max(np.abs(db0).max(), 1.0)

    def test_gemm_rs_quantized_duals_track_exact(self, mesh8):
        from triton_distributed_tpu.ops import overlap

        a = np.random.RandomState(3).standard_normal(
            (64, 256)).astype(np.float32)
        b = np.random.RandomState(4).standard_normal(
            (256, 128)).astype(np.float32)

        def grads(ctx):
            f = lambda a_, b_: jnp.sum(overlap.gemm_rs(a_, b_, ctx) ** 2)
            da, db = jax.grad(f, argnums=(0, 1))(jnp.asarray(a),
                                                 jnp.asarray(b))
            return np.asarray(da), np.asarray(db)

        da0, db0 = grads(overlap.create_gemm_rs_context(mesh8, "x"))
        da8, db8 = grads(overlap.create_gemm_rs_context(
            mesh8, "x", bwd_wire_dtype="int8"))
        assert np.abs(da8 - da0).max() < 5e-2 * np.abs(da0).max()
        assert np.abs(db8 - db0).max() < 5e-2 * np.abs(db0).max()

    def test_pinned_bwd_wire_refuses_uncarryable_cotangent(self, mesh8):
        from triton_distributed_tpu.ops import overlap

        ctx = overlap.create_ag_gemm_context(
            mesh8, "x", bwd_wire_dtype="int8")
        g = jnp.ones((6, 32), jnp.float32)      # 6 rows % 8 ranks != 0
        with pytest.raises(ValueError, match="pinned wire format"):
            overlap._resolve_bwd(ctx, g, 32)

    def test_auto_bwd_wire_demotes_silently(self, mesh8):
        from triton_distributed_tpu.ops import overlap

        ctx = overlap.create_ag_gemm_context(
            mesh8, "x", bwd_wire_dtype="auto")
        g = jnp.ones((6, 32), jnp.float32)
        assert overlap._resolve_bwd(ctx, g, 32) is None
