"""Ragged paged-attention kernel: numerics vs the XLA reference twin.

The serving tentpole's kernel contract (ISSUE 6): ONE launch processes
mixed prefill-chunk and decode rows against per-request block tables —
per-row (kv_len, q_len) metadata, causal frontier masking, int8 pools
with exact in-softmax scale folds, and the packed GQA-rows layout.
These tests pin the kernel to :func:`ragged_paged_attention_xla` (an
independently written dense reference) and the reference itself to
plain dense causal attention.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.kernels.flash_decode import quantize_kv
from triton_distributed_tpu.kernels.ragged_paged_attention import (
    auto_block_q,
    causal_topologies,
    pack_gqa_rows,
    ragged_paged_attention,
    ragged_paged_attention_xla,
    topo_width,
    tree_topology_row,
    unpack_gqa_rows,
)

pytestmark = pytest.mark.fast

HKV, G, D, PAGE, PPS, NPAGES = 2, 2, 32, 8, 4, 16


def _pools(rng, quant):
    kc = jnp.asarray(
        rng.standard_normal((NPAGES, HKV, PAGE, D)), jnp.float32
    )
    vc = jnp.asarray(
        rng.standard_normal((NPAGES, HKV, PAGE, D)), jnp.float32
    )
    if not quant:
        return (kc, vc), {}
    kq, ks = quantize_kv(kc)
    vq, vs = quantize_kv(vc)
    return (kq, vq), dict(k_scale=ks, v_scale=vs)


def _mixed_batch(rng):
    """Three rows: steady decode, a mid-prompt chunk, a fresh prefill."""
    kv_lens = jnp.asarray([13, 21, 8], jnp.int32)   # incl. step tokens
    q_lens = jnp.asarray([1, 5, 8], jnp.int32)
    q_starts = jnp.asarray([0, 8, 16], jnp.int32)   # 8-aligned
    t = 32
    table = jnp.asarray(
        rng.permutation(NPAGES)[: 3 * PPS].reshape(3, PPS), jnp.int32
    )
    q = jnp.asarray(
        rng.standard_normal((t, HKV * G, D)), jnp.float32
    )
    return q, kv_lens, q_lens, q_starts, table


class TestRaggedKernel:
    @pytest.mark.parametrize("quant", [False, True])
    def test_matches_xla_twin_mixed_rows(self, quant):
        rng = np.random.default_rng(0)
        pools, scales = _pools(rng, quant)
        q, kv_lens, q_lens, q_starts, table = _mixed_batch(rng)
        qp = pack_gqa_rows(q, HKV)
        bq = auto_block_q(int(q_lens.max()), G)
        out, lse = ragged_paged_attention(
            qp, *pools, kv_lens, q_lens, q_starts, table, group=G,
            block_q=bq, **scales,
        )
        ref, rlse = ragged_paged_attention_xla(
            qp, *pools, kv_lens, q_lens, q_starts, table, group=G,
            **scales,
        )
        # int8 tolerance: the kernel widens to bf16 before the dot, the
        # twin to f32 — same bound as the paged q8 decode tests
        tol = 2e-2 if quant else 1e-5
        for r in range(3):
            s = int(q_starts[r]) * G
            w = int(q_lens[r]) * G
            np.testing.assert_allclose(
                np.asarray(out)[:, s:s + w], np.asarray(ref)[:, s:s + w],
                atol=tol, rtol=tol,
            )
            np.testing.assert_allclose(
                np.asarray(lse)[:, s:s + w],
                np.asarray(rlse)[:, s:s + w], atol=tol, rtol=tol,
            )

    def test_xla_twin_matches_dense_causal(self):
        """The reference itself, pinned: one fresh-prefill row equals
        plain dense causal attention over the gathered pages."""
        rng = np.random.default_rng(1)
        (kc, vc), _ = _pools(rng, False)
        L = 11
        kv_lens = jnp.asarray([L], jnp.int32)
        q_lens = jnp.asarray([L], jnp.int32)
        q_starts = jnp.asarray([0], jnp.int32)
        table = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
        t = 16
        q = jnp.asarray(rng.standard_normal((t, HKV * G, D)), jnp.float32)
        qp = pack_gqa_rows(q, HKV)
        out, _ = ragged_paged_attention_xla(
            qp, kc, vc, kv_lens, q_lens, q_starts, table, group=G
        )
        got = unpack_gqa_rows(out, HKV * G)[:L]          # (L, Hq, D)

        # dense causal reference over the contiguous first-4-pages view
        kcat = kc[table[0]].transpose(1, 0, 2, 3).reshape(HKV, -1, D)[:, :L]
        vcat = vc[table[0]].transpose(1, 0, 2, 3).reshape(HKV, -1, D)[:, :L]
        qg = q[:L].reshape(L, HKV, G, D)
        s = jnp.einsum("thgd,hsd->thgs", qg, kcat) / math.sqrt(D)
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        ref = jnp.einsum("thgs,hsd->thgd", p, vcat).reshape(L, HKV * G, D)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_decode_row_matches_paged_decode_kernel(self):
        """A decode-only ragged batch (every q_len == 1) must agree
        with the existing paged decode kernel on the same pools —
        the ragged kernel subsumes the decode rectangle."""
        from triton_distributed_tpu.kernels.flash_decode import (
            paged_gqa_fwd_batch_decode,
        )

        rng = np.random.default_rng(2)
        (kc, vc), _ = _pools(rng, False)
        b = 3
        kv_lens = jnp.asarray([9, 17, 25], jnp.int32)
        q_lens = jnp.ones((b,), jnp.int32)
        q_starts = jnp.asarray([0, 8, 16], jnp.int32)
        table = jnp.asarray(
            rng.permutation(NPAGES)[: b * PPS].reshape(b, PPS), jnp.int32
        )
        t = 32
        q = jnp.asarray(rng.standard_normal((t, HKV * G, D)), jnp.float32)
        qp = pack_gqa_rows(q, HKV)
        out, _ = ragged_paged_attention(
            qp, kc, vc, kv_lens, q_lens, q_starts, table, group=G,
            block_q=8,
        )
        got = unpack_gqa_rows(out, HKV * G)       # (T, Hq, D)
        q_dec = q[np.asarray(q_starts)]           # (b, Hq, D)
        ref, _ = paged_gqa_fwd_batch_decode(
            q_dec, kc, vc, kv_lens, table
        )
        np.testing.assert_allclose(
            np.asarray(got)[np.asarray(q_starts)], np.asarray(ref),
            atol=1e-5, rtol=1e-5,
        )

    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.standard_normal((10, HKV * G, D)), jnp.float32)
        assert np.array_equal(
            np.asarray(unpack_gqa_rows(pack_gqa_rows(q, HKV), HKV * G)),
            np.asarray(q),
        )

    def test_auto_block_q_ladder(self):
        assert auto_block_q(1, 7) == 8       # 8·7 = 56 ≡ 0 (mod 8)
        assert auto_block_q(1, 2) == 8
        assert auto_block_q(9, 2) == 16
        assert auto_block_q(16, 1) == 16
        for mx, g in ((1, 1), (3, 7), (100, 2)):
            b = auto_block_q(mx, g)
            assert b >= mx and (b * g) % 8 == 0

    def test_block_q_alignment_rejected(self):
        rng = np.random.default_rng(4)
        pools, _ = _pools(rng, False)
        q, kv_lens, q_lens, q_starts, table = _mixed_batch(rng)
        with pytest.raises(ValueError, match="sublane"):
            ragged_paged_attention(
                pack_gqa_rows(q, HKV), *pools, kv_lens, q_lens, q_starts,
                table, group=G, block_q=3,
            )

    @pytest.mark.parametrize("quant", [False, True])
    def test_all_causal_topologies_byte_identical(self, quant):
        """Acceptance: an all-CAUSAL topology operand changes NOTHING —
        valid spans byte-identical to the topology-less launch (the
        identity-operand contract; garbage spans excluded, per the
        packing contract)."""
        rng = np.random.default_rng(6)
        pools, scales = _pools(rng, quant)
        q, kv_lens, q_lens, q_starts, table = _mixed_batch(rng)
        qp = pack_gqa_rows(q, HKV)
        base, base_lse = ragged_paged_attention(
            qp, *pools, kv_lens, q_lens, q_starts, table, group=G,
            block_q=8, **scales,
        )
        topo = jnp.asarray(causal_topologies(3, topo_width(8)))
        got, got_lse = ragged_paged_attention(
            qp, *pools, kv_lens, q_lens, q_starts, table, group=G,
            block_q=8, topologies=topo, **scales,
        )
        for r in range(3):
            s = int(q_starts[r]) * G
            w = int(q_lens[r]) * G
            np.testing.assert_array_equal(
                np.asarray(base)[:, s:s + w], np.asarray(got)[:, s:s + w]
            )
            np.testing.assert_array_equal(
                np.asarray(base_lse)[:, s:s + w],
                np.asarray(got_lse)[:, s:s + w],
            )

    def _tree_batch(self, rng):
        """Row 0: a tree verify row — frontier + 5 draft nodes with a
        sibling fork (node 1 and node 2 both children of node 0).
        Row 1: a plain decode row (CAUSAL)."""
        parents = [-1, 0, 0, 2, 3]
        kv_lens = jnp.asarray([14, 21], jnp.int32)
        q_lens = jnp.asarray([6, 1], jnp.int32)
        q_starts = jnp.asarray([0, 8], jnp.int32)
        table = jnp.asarray(
            rng.permutation(NPAGES)[: 2 * PPS].reshape(2, PPS), jnp.int32
        )
        t = 16
        q = jnp.asarray(
            rng.standard_normal((t, HKV * G, D)), jnp.float32
        )
        w = topo_width(8)
        topo = causal_topologies(2, w)
        topo[0] = tree_topology_row(parents, w)
        return q, kv_lens, q_lens, q_starts, table, jnp.asarray(topo)

    @pytest.mark.parametrize("quant", [False, True])
    def test_tree_row_matches_xla_twin(self, quant):
        """Tentpole numerics: a TREE verify row (sibling fork) under the
        ancestor-bitmask mask agrees with the XLA twin given the same
        topology operand."""
        rng = np.random.default_rng(7)
        pools, scales = _pools(rng, quant)
        q, kv_lens, q_lens, q_starts, table, topo = self._tree_batch(rng)
        qp = pack_gqa_rows(q, HKV)
        out, lse = ragged_paged_attention(
            qp, *pools, kv_lens, q_lens, q_starts, table, group=G,
            block_q=8, topologies=topo, **scales,
        )
        ref, rlse = ragged_paged_attention_xla(
            qp, *pools, kv_lens, q_lens, q_starts, table, group=G,
            topologies=topo, **scales,
        )
        tol = 2e-2 if quant else 1e-5
        for r in range(2):
            s = int(q_starts[r]) * G
            w = int(q_lens[r]) * G
            np.testing.assert_allclose(
                np.asarray(out)[:, s:s + w], np.asarray(ref)[:, s:s + w],
                atol=tol, rtol=tol,
            )
            np.testing.assert_allclose(
                np.asarray(lse)[:, s:s + w],
                np.asarray(rlse)[:, s:s + w], atol=tol, rtol=tol,
            )

    def test_twin_tree_mask_matches_manual_dense(self):
        """The twin's TREE semantics, pinned independently: each q
        position attends the full committed prefix plus exactly the
        speculative positions its ancestor bitmask names — node 3 (a
        child of node 2) must NOT see sibling node 1's position."""
        rng = np.random.default_rng(8)
        (kc, vc), _ = _pools(rng, False)
        q, kv_lens, q_lens, q_starts, table, topo = self._tree_batch(rng)
        qp = pack_gqa_rows(q, HKV)
        out, _ = ragged_paged_attention_xla(
            qp, kc, vc, kv_lens, q_lens, q_starts, table, group=G,
            topologies=topo,
        )
        got = unpack_gqa_rows(out, HKV * G)
        L, nq = int(kv_lens[0]), int(q_lens[0])
        base = L - nq                        # committed prefix tokens
        anc = np.asarray(topo)[0, 2:2 + topo_width(8)]
        kcat = kc[table[0]].transpose(1, 0, 2, 3).reshape(HKV, -1, D)[:, :L]
        vcat = vc[table[0]].transpose(1, 0, 2, 3).reshape(HKV, -1, D)[:, :L]
        for t in range(nq):
            vis = np.zeros((L,), bool)
            vis[:base] = True
            for j in range(nq):
                if (int(anc[t]) >> j) & 1:
                    vis[base + j] = True
            if t >= 3:                       # deep chain excludes node 1
                assert not vis[base + 2]
            qt = np.asarray(q)[t].reshape(HKV, G, D)
            s = np.einsum(
                "hgd,hsd->hgs", qt, np.asarray(kcat)
            ) / math.sqrt(D)
            s = np.where(vis[None, None, :], s, -1e30)
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            ref = np.einsum(
                "hgs,hsd->hgd", p, np.asarray(vcat)
            ).reshape(HKV * G, D)
            np.testing.assert_allclose(
                np.asarray(got)[t], ref, atol=1e-5, rtol=1e-5
            )

    def test_qlen_zero_rows_skipped_under_topology(self):
        """Satellite: with the topology operand present the kernel
        takes the cross-row q-prefetch hop over q_len == 0 rows —
        active rows' valid spans must match the batch without the
        inactive row byte-for-byte."""
        rng = np.random.default_rng(9)
        pools, scales = _pools(rng, True)
        q, kv_lens, q_lens, q_starts, table = _mixed_batch(rng)
        qp = pack_gqa_rows(q, HKV)
        w = topo_width(8)
        a_out, _ = ragged_paged_attention(
            qp, *pools, kv_lens, q_lens, q_starts, table, group=G,
            block_q=8, topologies=jnp.asarray(causal_topologies(3, w)),
            **scales,
        )
        # inactive row INSIDE the batch (skip hop must cross it)
        kv4 = jnp.asarray([13, 0, 21, 8], jnp.int32)
        ql4 = jnp.asarray([1, 0, 5, 8], jnp.int32)
        qs4 = jnp.asarray([0, 24, 8, 16], jnp.int32)
        tb4 = jnp.concatenate(
            [table[:1], jnp.zeros((1, PPS), jnp.int32), table[1:]]
        )
        b_out, _ = ragged_paged_attention(
            qp, *pools, kv4, ql4, qs4, tb4, group=G, block_q=8,
            topologies=jnp.asarray(causal_topologies(4, w)), **scales,
        )
        for r in range(3):
            s = int(q_starts[r]) * G
            w_ = int(q_lens[r]) * G
            np.testing.assert_array_equal(
                np.asarray(a_out)[:, s:s + w_],
                np.asarray(b_out)[:, s:s + w_],
            )

    def test_inactive_rows_leave_valid_spans_intact(self):
        """q_len == 0 rows write garbage at THEIR q_start only — parked
        past every valid span, they must not perturb active rows (the
        engine's parking-zone contract; regression for the clobber bug
        the sequential out-DMA ordering self-heals)."""
        rng = np.random.default_rng(5)
        pools, scales = _pools(rng, True)
        q, kv_lens, q_lens, q_starts, table = _mixed_batch(rng)
        qp = pack_gqa_rows(q, HKV)
        a_out, _ = ragged_paged_attention(
            qp, *pools, kv_lens, q_lens, q_starts, table, group=G,
            block_q=8, **scales,
        )
        # add an inactive 4th row parked at token 24 (the slack zone)
        kv4 = jnp.concatenate([kv_lens, jnp.zeros((1,), jnp.int32)])
        ql4 = jnp.concatenate([q_lens, jnp.zeros((1,), jnp.int32)])
        qs4 = jnp.concatenate([q_starts, jnp.asarray([24], jnp.int32)])
        tb4 = jnp.concatenate([table, jnp.zeros((1, PPS), jnp.int32)])
        b_out, _ = ragged_paged_attention(
            qp, *pools, kv4, ql4, qs4, tb4, group=G, block_q=8, **scales,
        )
        for r in range(3):
            s = int(q_starts[r]) * G
            w = int(q_lens[r]) * G
            np.testing.assert_array_equal(
                np.asarray(a_out)[:, s:s + w],
                np.asarray(b_out)[:, s:s + w],
            )
