"""Context-parallel attention tests: ring + Ulysses, fwd and grads.

Beyond the reference's scope (its sequence parallelism is decode-only,
SURVEY.md §5): training-time CP must match dense causal attention in
both values and gradients, and slot into the transformer as a drop-in
attention mode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels.ring_attention import (
    dense_attention_reference,
    ring_attention,
    ulysses_attention,
)

B, S, D = 2, 128, 32


def _qkv(hq, hkv, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, hq, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, hkv, D), jnp.float32)
    return q, k, v


def _shard(mesh, *ts):
    sh = NamedSharding(mesh, P(None, "x"))
    return tuple(jax.device_put(t, sh) for t in ts)


@pytest.mark.parametrize("attn", [ring_attention, ulysses_attention])
@pytest.mark.parametrize("hq,hkv", [(8, 4), (8, 8), (16, 8)])
def test_forward_matches_dense(mesh8, attn, hq, hkv):
    q, k, v = _qkv(hq, hkv)
    ref = dense_attention_reference(q, k, v)
    out = attn(*_shard(mesh8, q, k, v), mesh8, "x")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


@pytest.mark.parametrize("attn", [ring_attention, ulysses_attention])
def test_non_causal(mesh8, attn):
    q, k, v = _qkv(8, 4)
    ref = dense_attention_reference(q, k, v, causal=False)
    out = attn(*_shard(mesh8, q, k, v), mesh8, "x", causal=False)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


@pytest.mark.parametrize("attn", [ring_attention, ulysses_attention])
def test_grads_match_dense(mesh8, attn):
    q, k, v = _qkv(8, 4)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(loss(dense_attention_reference), argnums=(0, 1, 2))(q, k, v)
    g = jax.grad(
        loss(lambda q, k, v: attn(q, k, v, mesh8, "x")), argnums=(0, 1, 2)
    )(*_shard(mesh8, q, k, v))
    for got, ref, name in zip(g, g_ref, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=1e-4, rtol=1e-4,
            err_msg=name,
        )


def test_model_attn_modes_agree(mesh2x4):
    """Same params → identical logits across tp/ring/ulysses attention;
    ring mode trains with decreasing loss."""
    from triton_distributed_tpu.models import Transformer, TransformerConfig

    base = dict(vocab=64, n_layers=1, hidden=64, ffn=128,
                n_heads=8, n_kv_heads=4, head_dim=8,
                dtype=jnp.float32, param_dtype=jnp.float32)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    tg = jax.device_put(toks, NamedSharding(mesh2x4, P("dp")))
    outs = {}
    ring_state = None
    for attn in ("tp", "ring", "ulysses"):
        m = Transformer(
            TransformerConfig(**base, attn=attn), mesh2x4, "tp", ("dp",)
        )
        params = jax.tree.map(
            lambda p, s: jax.device_put(p, s),
            m.init(jax.random.PRNGKey(0)), m.shardings(),
        )
        outs[attn] = np.asarray(m.forward(params, tg))
        if attn == "ring":
            ring_state = (m, params)
    for attn in ("ring", "ulysses"):
        np.testing.assert_allclose(outs[attn], outs["tp"], atol=2e-3)

    m, params = ring_state
    step = jax.jit(m.train_step)
    l1, params = step(params, tg, tg)
    l2, _ = step(params, tg, tg)
    assert float(l2) < float(l1)


def test_bad_attn_config_rejected():
    from triton_distributed_tpu.models import TransformerConfig

    with pytest.raises(ValueError, match="attn must be"):
        TransformerConfig(attn="flash")
    with pytest.raises(ValueError, match="moe must be"):
        TransformerConfig(moe="dense")
