"""Chaos-delay correctness runs (≡ the reference's ``for_correctness``
random comm-stream sleep, allgather.py:72-77: prove consumers truly wait
on signals by widening race windows)."""

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.config import config
from triton_distributed_tpu.kernels import all_gather, all_to_all, reduce_scatter
from triton_distributed_tpu.runtime import AllGatherMethod
from triton_distributed_tpu.utils import assert_allclose


@pytest.fixture()
def chaos():
    config.chaos_delay = True
    yield
    config.chaos_delay = False


def test_allgather_under_chaos(mesh8, chaos):
    x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
    for method in [AllGatherMethod.RING_1D, AllGatherMethod.RING_BIDIR,
                   AllGatherMethod.LL_SMALL]:
        y = all_gather(x, mesh8, "x", method=method)
        assert_allclose(y, x)


def test_reduce_scatter_under_chaos(mesh8, chaos):
    x = jnp.ones((8, 64, 128), jnp.float32) * jnp.arange(8).reshape(8, 1, 1)
    y = reduce_scatter(x, mesh8, "x", stacked=True)
    assert_allclose(y, np.full((64, 128), 28.0))


def test_all_to_all_under_chaos(mesh8, chaos):
    x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
    y = all_to_all(all_to_all(x, mesh8, "x"), mesh8, "x")
    assert_allclose(y, x)
