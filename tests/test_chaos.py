"""Chaos-delay correctness runs (≡ the reference's ``for_correctness``
random comm-stream sleep, allgather.py:72-77: prove consumers truly wait
on signals by widening race windows)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.config import config
from triton_distributed_tpu.kernels import (
    AGGemmMethod,
    GemmRSMethod,
    ag_gemm,
    all_gather,
    all_to_all,
    gemm_rs,
    reduce_scatter,
)
from triton_distributed_tpu.kernels.flash_decode import (
    gqa_fwd_batch_decode_xla,
    sp_gqa_fwd_batch_decode,
)
from triton_distributed_tpu.runtime import AllGatherMethod, Delay, FaultPlan, fault_plan
from triton_distributed_tpu.utils import assert_allclose

pytestmark = pytest.mark.chaos


@pytest.fixture()
def chaos():
    config.chaos_delay = True
    yield
    config.chaos_delay = False


def test_allgather_under_chaos(mesh8, chaos):
    x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
    for method in [AllGatherMethod.RING_1D, AllGatherMethod.RING_BIDIR,
                   AllGatherMethod.LL_SMALL]:
        y = all_gather(x, mesh8, "x", method=method)
        assert_allclose(y, x)


def test_reduce_scatter_under_chaos(mesh8, chaos):
    x = jnp.ones((8, 64, 128), jnp.float32) * jnp.arange(8).reshape(8, 1, 1)
    y = reduce_scatter(x, mesh8, "x", stacked=True)
    assert_allclose(y, np.full((64, 128), 28.0))


def test_all_to_all_under_chaos(mesh8, chaos):
    x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
    y = all_to_all(all_to_all(x, mesh8, "x"), mesh8, "x")
    assert_allclose(y, x)


def test_ag_gemm_under_chaos(mesh8, chaos):
    """Fused AG-GEMM under comm delays: the consumer GEMM must truly
    wait on the ring's signals for every slab it reads (the ``chaos=``
    leg of the builder cache key, previously untested)."""
    a = jax.random.normal(jax.random.PRNGKey(1), (64, 32), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(2), (32, 128), jnp.float32)
    c = ag_gemm(a, b, mesh8, "x", method=AGGemmMethod.PALLAS_FUSED)
    ref = jnp.dot(a, b, preferred_element_type=jnp.float32)
    assert_allclose(np.asarray(c, np.float32), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_gemm_rs_under_chaos(mesh8, chaos):
    """Fused GEMM-RS under comm delays: every reduced stripe must wait
    on its producer's signal before the scatter consumes it."""
    a = jax.random.normal(jax.random.PRNGKey(3), (64, 32), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(4), (32, 48), jnp.float32)
    c = gemm_rs(a, b, mesh8, "x", method=GemmRSMethod.PALLAS_FUSED)
    ref = jnp.dot(a, b, preferred_element_type=jnp.float32)
    assert_allclose(np.asarray(c, np.float32), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_ag_gemm_under_seeded_fault_plan(mesh8):
    """Same site through the fault engine instead of the global boolean:
    seeded per-(rank, step) delays on the ag_gemm ring stay bit-correct
    and replay identically (plan is in the builder cache key)."""
    a = jax.random.normal(jax.random.PRNGKey(1), (64, 32), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(2), (32, 128), jnp.float32)
    ref = np.asarray(jnp.dot(a, b, preferred_element_type=jnp.float32))
    plan = FaultPlan(seed=13, faults=(
        Delay(site="ag_gemm", cycles=60_000, jitter=0.8),
    ))
    runs = []
    for _ in range(2):
        with fault_plan(plan):
            runs.append(np.asarray(ag_gemm(
                a, b, mesh8, "x", method=AGGemmMethod.PALLAS_FUSED
            ), np.float32))
    assert_allclose(runs[0], ref, atol=1e-4, rtol=1e-4)
    np.testing.assert_array_equal(runs[0], runs[1])


def test_flash_decode_combine_under_chaos(mesh8, chaos):
    """SP flash-decode under chaos: the delay widens the slot-rotation
    window between KV prefetch issue and wait inside the local decode,
    and the cross-rank (out, lse) combine must still merge partial
    ranks to the dense answer."""
    B, Hq, Hkv, D, S = 2, 8, 2, 128, 1024
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(9), 3)
    q = jax.random.normal(kq, (B, Hq, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, Hkv, D), jnp.float32)
    lens = jnp.asarray([700, 130], jnp.int32)   # partial + near-empty ranks
    out = sp_gqa_fwd_batch_decode(
        q, k, v, lens, mesh8, "x", use_pallas=True, block_k=128,
        kv_layout="bshd",
    )
    out_ref, _ = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bshd")
    assert_allclose(np.asarray(out), np.asarray(out_ref), atol=3e-5, rtol=3e-5)


def test_moe_tp_ag_group_gemm_under_chaos(mesh8, chaos):
    """Fused AG⊕GroupGEMM under comm delays (VERDICT r5 #4): every
    grouped-GEMM pipeline must truly wait on its shard's ring arrival
    while the SMEM expert table steers its block fetches."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from triton_distributed_tpu.kernels import moe_utils as mu
    from triton_distributed_tpu.ops.moe_tp import (
        ag_group_gemm_fused,
        align_routing_sharded,
        create_ag_group_gemm_context,
    )

    E, TOPK, M, K, F = 16, 2, 64, 128, 256
    x = jax.random.normal(jax.random.PRNGKey(90), (M, K), jnp.float32)
    logits = jax.random.normal(jax.random.PRNGKey(91), (M, E))
    w_up = jax.random.normal(
        jax.random.PRNGKey(92), (E, K, F), jnp.float32) * 0.05
    _, ids = mu.select_experts(logits, TOPK)
    ctx = create_ag_group_gemm_context(
        mesh8, "x", num_experts=E, topk=TOPK, block_m=8, dtype=jnp.float32
    )
    routing = align_routing_sharded(ctx, ids)
    sh = lambda s: NamedSharding(mesh8, s)  # noqa: E731
    y = np.asarray(ag_group_gemm_fused(
        jax.device_put(x, sh(P("x"))), routing,
        jax.device_put(w_up, sh(P(None, None, "x"))), ctx,
    ))
    tp, m_s, cap_s = 8, M // 8, routing.cap_s
    for s in range(0, tp, 2):
        sti = np.asarray(routing.sti[s])
        ids_s = np.asarray(ids)[s * m_s:(s + 1) * m_s]
        xs = np.asarray(mu.gather_sorted(
            jnp.asarray(np.asarray(x)[s * m_s:(s + 1) * m_s]),
            jnp.asarray(sti), TOPK,
        ))
        flat = ids_s.reshape(-1)
        slab = y[s * cap_s:(s + 1) * cap_s]
        for r in range(0, cap_s, 13):
            if sti[r] < m_s * TOPK:
                expect = xs[r] @ np.asarray(w_up)[flat[sti[r]]]
                np.testing.assert_allclose(
                    slab[r], expect, atol=2e-5, rtol=2e-5
                )


def test_moe_tp_reduce_rs_under_chaos(mesh8, chaos):
    """Fused GroupGEMM⊕Reduce-RS under comm delays: the widened windows
    between a ring slot's rewrite and its ack must not let a partial be
    folded twice or a stale slab be consumed (the full overlapped MoE
    MLP must still match the dense reference)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from triton_distributed_tpu.kernels import moe_utils as mu
    from triton_distributed_tpu.ops.moe_tp import (
        create_ag_group_gemm_context,
        moe_tp_mlp_overlapped,
    )

    E, TOPK, M, K, F, H = 16, 2, 64, 128, 256, 128
    x = jax.random.normal(jax.random.PRNGKey(95), (M, K), jnp.float32)
    logits = jax.random.normal(jax.random.PRNGKey(96), (M, E))
    w_up = jax.random.normal(
        jax.random.PRNGKey(97), (E, K, F), jnp.float32) * 0.05
    w_down = jax.random.normal(
        jax.random.PRNGKey(98), (E, F, H), jnp.float32) * 0.05
    weights, ids = mu.select_experts(logits, TOPK)
    ctx = create_ag_group_gemm_context(
        mesh8, "x", num_experts=E, topk=TOPK, block_m=8, dtype=jnp.float32
    )
    sh = lambda s: NamedSharding(mesh8, s)  # noqa: E731
    out = moe_tp_mlp_overlapped(
        jax.device_put(x, sh(P("x"))),
        jax.device_put(ids, sh(P("x"))),
        jax.device_put(weights, sh(P("x"))),
        jax.device_put(w_up, sh(P(None, None, "x"))),
        jax.device_put(w_down, sh(P(None, "x"))), ctx,
    )
    ref = jnp.zeros((M, H))
    for t in range(TOPK):
        h = jax.nn.silu(jnp.einsum("mk,mkf->mf", x, w_up[ids[:, t]]))
        ref += weights[:, t: t + 1] * jnp.einsum(
            "mf,mfh->mh", h, w_down[ids[:, t]]
        )
    assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_moe_a2a_under_chaos(mesh8, chaos):
    """The packed-slot MoE transport must be race-free: counts and
    tokens land atomically per peer even with comm delays injected."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from triton_distributed_tpu.kernels import moe_all_to_all as ma

    from conftest import moe_splits_data

    n, epr, H, max_m, M = 8, 2, 128, 16, 12
    E = n * epr
    ctx = ma.create_all_to_all_context(
        mesh8, "x", max_m=max_m, hidden=H,
        experts_per_rank=epr, dtype=jnp.float32,
    )
    toks, splits = moe_splits_data(n, M, E, H, seed=3)
    sh = NamedSharding(mesh8, P("x"))
    stage = jax.jit(jax.shard_map(
        lambda t, s: ma.pack_slots(ctx, *ma.dispatch_stage(ctx, t, s)),
        mesh=mesh8, in_specs=(P("x"), P("x")), out_specs=P("x"),
        check_vma=False,
    ))
    send = stage(
        jax.device_put(jnp.asarray(toks).reshape(n * M, H), sh),
        jax.device_put(jnp.asarray(splits).reshape(n * E), sh),
    )
    recv = ma.fast_all_to_all(ctx, send)
    recv_ref = ma.fast_all_to_all(ctx, send, use_xla=True)
    np.testing.assert_array_equal(np.asarray(recv), np.asarray(recv_ref))


def test_ep_moe_under_chaos(mesh8, chaos):
    """Full EP MoE op under comm delays still matches the dense MoE."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from conftest import dense_moe_ref

    from triton_distributed_tpu.ops import create_ep_moe_context, ep_moe

    n, E, topk, H, F, Mtok = 8, 16, 2, 128, 256, 8
    x = jax.random.normal(jax.random.PRNGKey(0), (n * Mtok, H), jnp.float32)
    logits = jax.random.normal(jax.random.PRNGKey(1), (n * Mtok, E))
    w_up = jax.random.normal(jax.random.PRNGKey(2), (E, H, F), jnp.float32) * 0.05
    w_down = jax.random.normal(jax.random.PRNGKey(3), (E, F, H), jnp.float32) * 0.05
    ref = dense_moe_ref(x, logits, w_up, w_down, topk)
    sh = NamedSharding(mesh8, P("x"))
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=E, topk=topk, max_m=Mtok * topk, hidden=H,
        dtype=jnp.float32, transport="pallas", block_m=8,
    )
    out = ep_moe(
        jax.device_put(x, sh), jax.device_put(logits, sh),
        jax.device_put(w_up, sh), jax.device_put(w_down, sh), ctx,
    )
    assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_ep_moe_ll_under_chaos(mesh8, chaos):
    """Barrier-free fused transport under randomized comm delays: the
    widened race windows must not let a parity window be read early."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from conftest import dense_moe_ref

    from triton_distributed_tpu.ops import (
        create_ep_moe_context,
        create_ep_moe_state,
        ep_moe,
    )

    n, E, topk, H, F, Mtok = 8, 16, 2, 128, 256, 7
    sh = NamedSharding(mesh8, P("x"))
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=E, topk=topk, max_m=Mtok * topk, hidden=H,
        dtype=jnp.float32, transport="fused", block_m=8,
        use_pallas_gemm=False,
    )
    state = create_ep_moe_state(ctx)
    w_up = jax.random.normal(jax.random.PRNGKey(2), (E, H, F), jnp.float32) * 0.05
    w_down = jax.random.normal(jax.random.PRNGKey(3), (E, F, H), jnp.float32) * 0.05
    for i in range(2):
        x = jax.random.normal(jax.random.PRNGKey(50 + i), (n * Mtok, H),
                              jnp.float32)
        logits = jax.random.normal(jax.random.PRNGKey(60 + i), (n * Mtok, E))
        ref = dense_moe_ref(x, logits, w_up, w_down, topk)
        out, state = ep_moe(
            jax.device_put(x, sh), jax.device_put(logits, sh),
            jax.device_put(w_up, sh), jax.device_put(w_down, sh), ctx,
            state=state,
        )
        assert_allclose(out, ref, atol=1e-4, rtol=1e-4)
