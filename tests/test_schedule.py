"""Schedule-space search (tune.schedule + autotuner.search_ring_schedule).

Pins the tentpole's three contracts:

* DEFAULT BYTE-IDENTITY — threading ``schedule=None`` or the explicit
  canonical :data:`~triton_distributed_tpu.tune.schedule.DEFAULT`
  through every ring consumer produces the IDENTICAL symbolic trace
  (every DMA, semaphore op, write and dequant, on every rank). The
  refactor that made schedules data may not have moved a single byte of
  the default protocol.
* THE ORACLE GATES — every family's legal candidates replay clean
  through shmemlint + the Mosaic pre-flight, and the deliberately
  illegal mutations are rejected with stable rule IDs (SL008 for the
  skipped hop, SL009 for the scale-on-payload rail). A search whose
  oracle rejects nothing must fail loudly.
* WINNERS PERSIST — searched winners round-trip the flock'd store
  keyed by (family, shape, mesh, wire) and reload with ZERO search
  cost; explicit schedules outrank stored winners.
"""

from __future__ import annotations

import itertools
import json

import numpy as np
import pytest

import jax.numpy as jnp

from triton_distributed_tpu.analysis import fixtures, lint
from triton_distributed_tpu.lang.launch import captured_launch
from triton_distributed_tpu.tune import schedule as S

pytestmark = [pytest.mark.analysis, pytest.mark.fast]

_F32 = np.dtype(np.float32)
_I8 = np.dtype(np.int8)
_TOK = itertools.count()


def _tok():
    return ("test-schedule", next(_TOK))


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------------ IR

class TestScheduleIR:
    def test_default_roundtrip_and_identity(self):
        assert S.DEFAULT.is_default()
        assert S.RingSchedule.from_dict(S.DEFAULT.to_dict()) == S.DEFAULT
        mutated = S.RingSchedule(depth=3)
        assert not mutated.is_default()
        assert S.RingSchedule.from_dict(mutated.to_dict()) == mutated

    def test_enumerate_default_first_everywhere(self):
        for fam in S.searchable_families():
            cands = S.enumerate_schedules(fam)
            assert cands[0].is_default(), fam
            assert len(set(cands)) == len(cands), fam

    def test_mutations_are_off_menu(self):
        """A mutation is never inside the family's legal freedom set."""
        for fam in S.searchable_families():
            legal = set(S.enumerate_schedules(fam))
            for m in S.mutate(S.default_for(fam), fam):
                assert m not in legal, (fam, m)

    def test_grid_default_roundtrip_and_identity(self):
        assert S.GRID_DEFAULT.is_default()
        assert S.GridSchedule.from_dict(
            S.GRID_DEFAULT.to_dict()) == S.GRID_DEFAULT
        mutated = S.GridSchedule(n_bufs=3)
        assert not mutated.is_default()
        assert S.GridSchedule.from_dict(mutated.to_dict()) == mutated
        # the kind discriminator is a CLASS attr, never serialized —
        # duck-typed dispatch survives double-imported module paths
        assert "kind" not in S.GRID_DEFAULT.to_dict()
        assert S.GRID_DEFAULT.kind == "grid" and S.DEFAULT.kind == "ring"


# ------------------------------------------------- default byte-identity

def _trace(builder, launch, in_shapes, site):
    spec = captured_launch(launch)
    assert spec is not None, launch
    rec, findings = lint.analyze_spec(
        spec, in_shapes, 8, kernel_name=launch, site=site,
    )
    return [[repr(e) for e in tr] for tr in rec.traces], findings


def _build_ag_gemm(sched):
    from triton_distributed_tpu.kernels.ag_gemm import _build_fused

    _build_fused(
        lint.lint_mesh(8), "x", (), (16 * 8, 128), (128, 64 * 8),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 5, _tok(),
        return_gathered=True, wire="int8", schedule=sched,
    )
    return "ag_gemm_fused_int8w", [
        ((16, 128), _F32), ((16, 128), _I8), ((1, 128), _F32),
        ((128, 64), _F32),
    ], "ag_gemm"


def _build_gemm_rs(sched):
    from triton_distributed_tpu.kernels.gemm_rs import _build_fused

    _build_fused(
        lint.lint_mesh(8), "x", (), (16 * 8, 128 * 8), (128 * 8, 64),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 6, _tok(),
        wire="int8", schedule=sched,
    )
    return "gemm_rs_fused_int8w", [
        ((16 * 8, 128), _F32), ((128, 64), _F32),
    ], "gemm_rs"


def _build_ag_ring(sched):
    from triton_distributed_tpu.kernels.allgather import _build_all_gather
    from triton_distributed_tpu.runtime import AllGatherMethod

    _build_all_gather(
        lint.lint_mesh(8), "x", AllGatherMethod.RING_1D, (64, 2048),
        jnp.dtype(jnp.float32), 2, _tok(), wire="int8", schedule=sched,
    )
    return "ag_ring_1d_int8w", [
        ((8, 2048), _F32), ((8, 2048), _I8), ((8, 128), _F32),
    ], "allgather"


def _build_ag_bidir(sched):
    from triton_distributed_tpu.kernels.allgather import _build_all_gather
    from triton_distributed_tpu.runtime import AllGatherMethod

    _build_all_gather(
        lint.lint_mesh(8), "x", AllGatherMethod.RING_BIDIR, (64, 1024),
        jnp.dtype(jnp.float32), 2, _tok(), schedule=sched,
    )
    return "ag_ring_bidir", [((8, 1024), _F32)], "allgather"


def _build_rs_stream(sched):
    from triton_distributed_tpu.kernels.reduce_scatter import (
        _build_rs_stream_w,
    )

    _build_rs_stream_w(
        lint.lint_mesh(8), "x", 64, 2048, jnp.dtype(jnp.float32), False,
        3, _tok(), "int8", sched,
    )
    return "rs_ring_stream_int8w", [((64, 2048), _F32)], "reduce_scatter"


_CONSUMERS = {
    "ag_gemm": _build_ag_gemm,
    "gemm_rs": _build_gemm_rs,
    "allgather_ring": _build_ag_ring,
    "allgather_bidir": _build_ag_bidir,
    "reduce_scatter_stream": _build_rs_stream,
}


class TestDefaultByteIdentity:
    @pytest.mark.parametrize("name", sorted(_CONSUMERS))
    def test_none_and_default_trace_identically(self, name):
        """schedule=None (the un-refactored code path) and the explicit
        canonical DEFAULT must leave the SAME event trace on every rank
        — same DMAs, same semaphores, same writes, same order."""
        build = _CONSUMERS[name]
        launch, in_shapes, site = build(None)
        base, f0 = _trace(build, launch, in_shapes, site)
        launch, in_shapes, site = build(S.DEFAULT)
        dflt, f1 = _trace(build, launch, in_shapes, site)
        assert base == dflt, name
        assert not f0 and not f1, (name, _rules(f0), _rules(f1))

    def test_non_default_schedule_changes_the_trace(self):
        """The counter-pin: a genuinely different legal schedule must
        NOT trace identically (otherwise the identity test is vacuous
        and the kernels ignore their schedule)."""
        launch, in_shapes, site = _build_ag_ring(None)
        base, _ = _trace(_build_ag_ring, launch, in_shapes, site)
        launch, in_shapes, site = _build_ag_ring(
            S.RingSchedule(direction="rev")
        )
        rev, _ = _trace(_build_ag_ring, launch, in_shapes, site)
        assert base != rev
        launch, in_shapes, site = _build_rs_stream(S.RingSchedule(depth=3))
        d3, _ = _trace(_build_rs_stream, launch, in_shapes, site)
        launch, in_shapes, site = _build_rs_stream(None)
        d2, _ = _trace(_build_rs_stream, launch, in_shapes, site)
        assert d3 != d2


# --------------------------------------------------------------- oracle

class TestLegalityOracle:
    @pytest.mark.parametrize(
        "family",
        [f for f in S.searchable_families() if not S.is_grid_family(f)],
    )
    def test_every_legal_candidate_gates_clean(self, family):
        """Ring freedom sets are legal by construction — every
        enumerated candidate must gate clean."""
        for cand in S.enumerate_schedules(family):
            findings = S.check_schedule(family, cand, 8)
            assert not findings, (family, cand, _rules(findings))

    @pytest.mark.parametrize("family", sorted(S.grid_families()))
    def test_grid_freedom_products_prune_through_the_oracle(self, family):
        """Grid freedom PRODUCTS may contain illegal corners (the
        proposer proposes, the oracle disposes): the default always
        gates clean, every rejection carries rule IDs, and at least one
        non-default candidate survives — there is something to tune."""
        clean, rejected = [], []
        for cand in S.enumerate_schedules(family):
            findings = S.check_schedule(family, cand, 8)
            (rejected if findings else clean).append(
                (cand, _rules(findings)))
        assert clean and clean[0][0].is_default(), (family, rejected)
        assert any(not c.is_default() for c, _ in clean), family
        for cand, rules in rejected:
            assert rules, (family, cand)

    def test_skipped_hop_is_sl008(self):
        f = S.check_schedule(
            "allgather.ring_1d", S.RingSchedule(chunk_order="skip_last"), 8
        )
        assert "SL008" in _rules(f), _rules(f)

    def test_scale_on_payload_is_sl009(self):
        f = S.check_schedule(
            "reduce_scatter.stream", S.RingSchedule(scale_rail="payload"), 8
        )
        assert "SL009" in _rules(f), _rules(f)

    def test_search_smoke_rejects_and_picks(self):
        out = S.search_smoke("ag_gemm.fused", 8)
        assert out["legal"] >= 1
        rules = sorted({r for _, rs in out["rejected"] for r in rs})
        assert "SL008" in rules and "SL009" in rules
        assert out["pick"] is not None


class TestMutatedScheduleFixtures:
    """The mutations as seeded fixtures: built through the REAL
    production builders (not hand-written replicas), each pinned to
    exactly its rule."""

    def test_schedule_skipped_chunk_is_sl008_only(self):
        spec, in_shapes, contract = fixtures.schedule_skipped_chunk()
        _, findings = lint.analyze_spec(
            spec, in_shapes(8), 8, kernel_name="schedule_skipped_chunk",
            site="fixture", contract=contract,
        )
        assert _rules(findings) == ["SL008"], [f.format() for f in findings]

    def test_schedule_scale_on_payload_is_sl009_only(self):
        spec, in_shapes, contract = fixtures.schedule_scale_on_payload()
        _, findings = lint.analyze_spec(
            spec, in_shapes(8), 8, kernel_name="schedule_scale_on_payload",
            site="fixture", contract=contract,
        )
        assert _rules(findings) == ["SL009"], [f.format() for f in findings]


# ------------------------------------------------------------ perf model

class TestPricing:
    def test_epilogue_dequant_prices_below_eager_on_wire(self):
        eager = S.price_schedule(
            "ag_gemm.fused", S.DEFAULT, rows=128, cols=8192, n=8,
            wire="int8",
        )
        epi = S.price_schedule(
            "ag_gemm.fused", S.RingSchedule(dequant="epilogue"),
            rows=128, cols=8192, n=8, wire="int8",
        )
        assert epi < eager

    def test_bidir_even_split_is_cheapest(self):
        prices = {
            s8: S.price_schedule(
                "allgather.ring_bidir", S.RingSchedule(split8=s8),
                rows=64, cols=2048, n=8,
            )
            for s8 in (2, 3, 4, 5, 6)
        }
        assert min(prices, key=prices.get) == 4
        assert prices[2] == prices[6]      # symmetric skew, same path


# ---------------------------------------------------------- winner store

@pytest.fixture
def store_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
    S.load_schedule.cache_clear()
    yield tmp_path
    S.load_schedule.cache_clear()


class TestWinnerStore:
    def test_store_load_roundtrip(self, store_dir):
        win = S.RingSchedule(dequant="epilogue")
        key = S.store_schedule(
            "ag_gemm.fused", (1024, 8192), (8,), "int8", win,
            price_ms=1.0, default_ms=2.0,
        )
        assert S.load_schedule("ag_gemm.fused", (1024, 8192), (8,),
                               "int8") == win
        entry = S.stored_entries()[key]
        assert entry["family"] == "ag_gemm.fused"
        assert entry["price_ms"] == 1.0
        # a different key misses
        assert S.load_schedule("ag_gemm.fused", (1024, 4096), (8,),
                               "int8") is None

    def test_resolve_precedence(self, store_dir):
        stored = S.RingSchedule(direction="rev")
        S.store_schedule("allgather.ring_1d", (64, 2048), (8,), None,
                         stored)
        explicit = S.RingSchedule(chunk_order="skip_last")
        assert S.resolve_schedule(
            "allgather.ring_1d", (64, 2048), (8,), None, explicit
        ) == explicit
        assert S.resolve_schedule(
            "allgather.ring_1d", (64, 2048), (8,), None
        ) == stored
        assert S.resolve_schedule(
            "allgather.ring_1d", (9999, 1), (8,), None
        ) is None

    def test_corrupt_store_loads_as_empty(self, store_dir):
        p = store_dir / "schedules.json"
        p.write_text("{not json")
        S.load_schedule.cache_clear()
        assert S.load_schedule("ag_gemm.fused", (1, 1), (8,), None) is None
        assert S.stored_entries() == {}


class TestSearchMode:
    def test_search_persists_and_reloads_with_zero_cost(self, store_dir):
        from triton_distributed_tpu.tune.autotuner import (
            search_ring_schedule,
        )

        rep = search_ring_schedule(
            "ag_gemm.fused", rows=128, cols=8192, mesh_shape=(8,),
            wire="int8", shape=(1024, 8192), itemsize=2, dryrun=True,
        )
        assert not rep["cached"]
        assert rep["winner_ms"] <= rep["default_ms"] + 1e-9
        rules = sorted({r for _, rs in rep["rejected"] for r in rs})
        assert "SL008" in rules and "SL009" in rules
        # on disk, keyed by (family, shape, mesh, wire)
        data = json.loads((store_dir / "schedules.json").read_text())
        assert any("ag_gemm.fused" in k for k in data["entries"])
        # the second call never enumerates: zero candidates gated
        rep2 = search_ring_schedule(
            "ag_gemm.fused", rows=128, cols=8192, mesh_shape=(8,),
            wire="int8", shape=(1024, 8192), itemsize=2, dryrun=True,
        )
        assert rep2["cached"] and rep2["candidates"] == 0
        assert rep2["winner"] == rep["winner"]
        # and the op resolve path sees the winner
        assert S.resolve_schedule(
            "ag_gemm.fused", (1024, 8192), (8,), "int8"
        ) == S.RingSchedule.from_dict(rep["winner"])

    def test_search_refuses_a_dead_oracle(self, store_dir, monkeypatch):
        """An oracle that rejects nothing means the gate is unwired —
        the search must fail instead of silently caching winners."""
        from triton_distributed_tpu.tune.autotuner import (
            search_ring_schedule,
        )

        monkeypatch.setitem(S._MUTATIONS, "allgather.ring_bidir", ())
        with pytest.raises(RuntimeError, match="rejected nothing"):
            search_ring_schedule(
                "allgather.ring_bidir", rows=64, cols=1024,
                mesh_shape=(8,), dryrun=True,
            )


# ------------------------------------------------- grid-schedule suite

def _grid_trace(launch, in_shapes, site, init=None, contract=None):
    spec = captured_launch(launch)
    assert spec is not None, launch
    rec, findings = lint.analyze_spec(
        spec, in_shapes, 8, kernel_name=launch, site=site,
        contract=contract, init=init,
    )
    return [[repr(e) for e in tr] for tr in rec.traces], findings


def _build_ragged_grid(sched):
    from triton_distributed_tpu.kernels.ragged_paged_attention import (
        LINT_GEOM,
        build_grid_lint_kernel,
        build_lint_kernel,
        causal_topologies,
    )

    if sched is None:
        # the pre-refactor builder: the registry's LINT_GEOM entry
        build_lint_kernel(token=_tok())
        g = dict(LINT_GEOM, kv_lens=(12, 8), q_lens=(8, 8),
                 q_starts=(0, 8))
    else:
        g = build_grid_lint_kernel(token=_tok(), schedule=sched)
    topo = g.get("topo")
    if topo is None:
        topo = causal_topologies(g["r"], g["topo_w"])
    pool = (g["npages"], g["hkv"], g["page"], g["d"])
    shapes = [
        ((g["r"], g["pps"]), np.dtype(np.int32)),
        ((g["r"],), np.dtype(np.int32)),
        ((g["r"],), np.dtype(np.int32)),
        ((g["r"],), np.dtype(np.int32)),
        ((g["r"], 2 + 2 * g["topo_w"]), np.dtype(np.int32)),
        ((g["hkv"], g["t"] * g["g"], g["d"]), _F32),
        (pool, _I8), (pool, _I8),
        ((g["npages"], g["hkv"], 1, g["page"]), _F32),
        ((g["npages"], g["hkv"], 1, g["page"]), _F32),
    ]
    init = {
        0: np.arange(g["r"] * g["pps"], dtype=np.int32).reshape(
            g["r"], g["pps"]),
        1: np.asarray(g["kv_lens"], np.int32),
        2: np.asarray(g["q_lens"], np.int32),
        3: np.asarray(g["q_starts"], np.int32),
        4: np.asarray(topo, np.int32),
    }
    return "ragged_paged_attention_q8", shapes, "ragged_paged", init


def _build_kv_ship_grid(sched):
    from triton_distributed_tpu.kernels.kv_ship import (
        KV_SHIP_GEOM,
        build_lint_kernel,
        coalesced_landing_table,
    )

    g = KV_SHIP_GEOM
    build_lint_kernel(lint.lint_mesh(8), 8, token=_tok(), schedule=sched)
    rows = g["pages"] * g["rows"]
    shapes = [
        ((g["pages"],), np.dtype(np.int32)),
        ((rows, g["cols"]), _I8),
        ((rows, 128), _F32),
    ]
    co = 1 if sched is None else int(sched.coalesce)
    init = {0: np.asarray(
        coalesced_landing_table(g["pages"], co), np.int32)}
    return "kv_ship_pages", shapes, "kv_ship", init


def _build_gemm_rs_mx(sched):
    from triton_distributed_tpu.kernels.gemm_rs import _build_fused
    from triton_distributed_tpu.lang import wire as wirelib

    n = 8
    _build_fused(
        lint.lint_mesh(n), "x", (), (16 * n, 128 * n), (128 * n, 64),
        jnp.dtype(jnp.float32), jnp.dtype(jnp.float32), 6, _tok(),
        wire="int8-mxu", schedule=sched,
    )
    shapes = [((16 * n, 128), _I8), ((n, wirelib.SCALE_LANES), _F32),
              ((128, 64), _I8), ((1, 64), _F32)]
    return "gemm_rs_fused_int8mxw", shapes, "gemm_rs", None


_GRID_CONSUMERS = {
    "ragged_paged": _build_ragged_grid,
    "kv_ship": _build_kv_ship_grid,
    "gemm_rs_mx": _build_gemm_rs_mx,
}


class TestGridDefaultByteIdentity:
    @pytest.mark.parametrize("name", sorted(_GRID_CONSUMERS))
    def test_none_and_grid_default_trace_identically(self, name):
        """schedule=None (the baked-in protocol) and the explicit
        GRID_DEFAULT leave the SAME symbolic event trace on every rank
        for all three grid families — the GridSchedule refactor moved
        no bytes of the default kernels."""
        build = _GRID_CONSUMERS[name]
        launch, shapes, site, init = build(None)
        base, f0 = _grid_trace(launch, shapes, site, init=init)
        launch, shapes, site, init = build(S.GRID_DEFAULT)
        dflt, f1 = _grid_trace(launch, shapes, site, init=init)
        assert base == dflt, name
        assert not f0 and not f1, (name, _rules(f0), _rules(f1))

    def test_non_default_grid_schedule_changes_the_trace(self):
        """Counter-pin against a vacuous identity: a coalesced kv_ship
        and a deeper ragged page walk must each trace differently from
        the default (the builders genuinely consume their schedule)."""
        launch, shapes, site, init = _build_kv_ship_grid(None)
        base, _ = _grid_trace(launch, shapes, site, init=init)
        launch, shapes, site, init = _build_kv_ship_grid(
            S.GridSchedule(coalesce=2))
        co2, _ = _grid_trace(launch, shapes, site, init=init)
        assert base != co2
        launch, shapes, site, init = _build_ragged_grid(None)
        rbase, _ = _grid_trace(launch, shapes, site, init=init)
        launch, shapes, site, init = _build_ragged_grid(
            S.GridSchedule(n_bufs=3))
        nb3, _ = _grid_trace(launch, shapes, site, init=init)
        assert rbase != nb3


class TestGridSearchMode:
    def test_grid_search_persists_and_reloads_with_zero_cost(
            self, store_dir):
        from triton_distributed_tpu.tune.autotuner import (
            search_grid_schedule,
        )

        shape = S._GRID_SMOKE_SHAPES["kv_ship.pages"]
        rep = search_grid_schedule(
            "kv_ship.pages", shape=shape, mesh_shape=(8,), wire="int8",
            dryrun=True,
        )
        assert not rep["cached"]
        assert rep["winner_ms"] <= rep["default_ms"] + 1e-9
        rules = sorted({r for _, rs in rep["rejected"] for r in rs})
        assert "SL009" in rules
        data = json.loads((store_dir / "schedules.json").read_text())
        assert data["schema_version"] == 2
        key = S.schedule_key("kv_ship.pages", shape, (8,), "int8")
        assert data["entries"][key]["kind"] == "grid"
        # second search: cached, zero candidates gated
        rep2 = search_grid_schedule(
            "kv_ship.pages", shape=shape, mesh_shape=(8,), wire="int8",
            dryrun=True,
        )
        assert rep2["cached"] and rep2["candidates"] == 0
        assert rep2["winner"] == rep["winner"]
        # the op resolve path sees a GridSchedule winner
        got = S.resolve_schedule("kv_ship.pages", shape, (8,), "int8")
        assert got == S.GridSchedule.from_dict(rep["winner"])

    def test_grid_resolve_precedence(self, store_dir):
        """explicit > stored > default, with grid values."""
        shape = (4, 64, 2, 2, 16, 8)
        fam = "flash_decode.ragged_paged"
        stored = S.GridSchedule(n_bufs=3)
        S.store_schedule(fam, shape, (1,), None, stored)
        explicit = S.GridSchedule(block_q=16)
        assert S.resolve_schedule(fam, shape, (1,), None,
                                  explicit) == explicit
        assert S.resolve_schedule(fam, shape, (1,), None) == stored
        assert S.resolve_schedule(fam, (9, 9, 9, 9, 9, 9), (1,),
                                  None) is None

    def test_grid_search_refuses_a_dead_oracle(self, store_dir,
                                               monkeypatch):
        from triton_distributed_tpu.tune.autotuner import (
            search_grid_schedule,
        )

        monkeypatch.setitem(S._GRID_MUTATIONS, "kv_ship.pages", ())
        with pytest.raises(RuntimeError, match="rejected nothing"):
            search_grid_schedule(
                "kv_ship.pages",
                shape=S._GRID_SMOKE_SHAPES["kv_ship.pages"],
                mesh_shape=(8,), dryrun=True,
            )

    def test_grid_search_rejects_non_grid_family(self, store_dir):
        from triton_distributed_tpu.tune.autotuner import (
            search_grid_schedule,
        )

        with pytest.raises(ValueError, match="not a grid family"):
            search_grid_schedule(
                "allgather.ring_1d", shape=(64, 2048), mesh_shape=(8,),
            )


class TestStoreMigration:
    def test_v1_ring_store_migrates(self, store_dir):
        """A pre-grid v1 store ({"v": 1}) loads: its ring entries get
        kind='ring' stamped and resolve as RingSchedule values."""
        win = S.RingSchedule(dequant="epilogue")
        key = S.schedule_key("ag_gemm.fused", (1024, 8192), (8,), "int8")
        (store_dir / "schedules.json").write_text(json.dumps({
            "v": 1,
            "entries": {key: {"family": "ag_gemm.fused",
                              "schedule": win.to_dict(),
                              "price_ms": 1.0}},
        }))
        S.load_schedule.cache_clear()
        got = S.load_schedule("ag_gemm.fused", (1024, 8192), (8,),
                              "int8")
        assert got == win and got.kind == "ring"
        assert S.stored_entries()[key]["kind"] == "ring"

    def test_unknown_store_version_is_ignored(self, store_dir):
        (store_dir / "schedules.json").write_text(json.dumps({
            "schema_version": 99,
            "entries": {"k": {"family": "x", "schedule": {}}},
        }))
        S.load_schedule.cache_clear()
        assert S.stored_entries() == {}
        assert S.load_schedule("ag_gemm.fused", (1, 1), (8,),
                               None) is None

    def test_v2_rewrite_preserves_migrated_entries(self, store_dir):
        """Writing one new winner into a v1 store upgrades the file to
        schema_version 2 WITHOUT dropping the migrated ring entries."""
        ring_key = S.schedule_key("ag_gemm.fused", (1024, 8192), (8,),
                                  "int8")
        (store_dir / "schedules.json").write_text(json.dumps({
            "v": 1,
            "entries": {ring_key: {
                "family": "ag_gemm.fused",
                "schedule": S.RingSchedule(dequant="epilogue").to_dict(),
            }},
        }))
        S.load_schedule.cache_clear()
        S.store_schedule("kv_ship.pages", (16, 16, 2, 128, 4), (8,),
                         "int8", S.GridSchedule(coalesce=2))
        data = json.loads((store_dir / "schedules.json").read_text())
        assert data["schema_version"] == 2
        assert data["entries"][ring_key]["kind"] == "ring"
        got = S.load_schedule("kv_ship.pages", (16, 16, 2, 128, 4),
                              (8,), "int8")
        assert got == S.GridSchedule(coalesce=2)
