"""MoE TP overlap op tests: AG-GroupGEMM + GroupGEMM-Reduce-RS pipeline.

Mirrors test_ag_moe.py / test_moe_reduce_rs.py
(python/triton_dist/test/nvidia/); the dense per-expert einsum is the
torch-reference stand-in (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.ops.moe_tp import (
    ag_group_gemm,
    align_routing,
    create_ag_group_gemm_context,
    moe_reduce_rs,
)

E, TOPK, M, K, F, H = 16, 2, 64, 128, 512, 128


def _data():
    x = jax.random.normal(jax.random.PRNGKey(0), (M, K), jnp.float32)
    logits = jax.random.normal(jax.random.PRNGKey(1), (M, E))
    w_up = jax.random.normal(jax.random.PRNGKey(2), (E, K, F), jnp.float32) * 0.05
    w_down = jax.random.normal(jax.random.PRNGKey(3), (E, F, H), jnp.float32) * 0.05
    weights, ids = mu.select_experts(logits, TOPK)
    return x, w_up, w_down, weights, ids


def _dense_ref(x, w_up, w_down, weights, ids):
    ref = jnp.zeros((M, H))
    for t in range(TOPK):
        h = jax.nn.silu(jnp.einsum("mk,mkf->mf", x, w_up[ids[:, t]]))
        ref += weights[:, t : t + 1] * jnp.einsum(
            "mf,mfh->mh", h, w_down[ids[:, t]]
        )
    return ref


@pytest.mark.parametrize("use_pallas_gemm", [True, False])
def test_moe_tp_pipeline_vs_dense(mesh8, use_pallas_gemm):
    """ag_group_gemm → silu → moe_reduce_rs == dense MoE, with tokens
    row-sharded in, token rows reduce-scattered out."""
    x, w_up, w_down, weights, ids = _data()
    ctx = create_ag_group_gemm_context(
        mesh8, "x", num_experts=E, topk=TOPK, block_m=8,
        dtype=jnp.float32, use_pallas_gemm=use_pallas_gemm,
    )
    xg = jax.device_put(x, NamedSharding(mesh8, P("x")))
    wug = jax.device_put(w_up, NamedSharding(mesh8, P(None, None, "x")))
    wdg = jax.device_put(w_down, NamedSharding(mesh8, P(None, "x")))

    routing = align_routing(ctx, ids)
    y = ag_group_gemm(xg, routing, wug, ctx)
    assert y.shape[1] == F
    out = moe_reduce_rs(jax.nn.silu(y), routing, weights, wdg, ctx)
    ref = _dense_ref(x, w_up, w_down, weights, ids)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )
    assert out.dtype == ctx.dtype


def test_ag_group_gemm_sorted_layout(mesh8):
    """The sorted rows returned must equal gather_sorted(x) @ w[expert]."""
    x, w_up, _, _, ids = _data()
    ctx = create_ag_group_gemm_context(
        mesh8, "x", num_experts=E, topk=TOPK, block_m=8, dtype=jnp.float32
    )
    xg = jax.device_put(x, NamedSharding(mesh8, P("x")))
    wug = jax.device_put(w_up, NamedSharding(mesh8, P(None, None, "x")))
    routing = align_routing(ctx, ids)
    y = ag_group_gemm(xg, routing, wug, ctx)

    sti_ref, be, _ = mu.moe_align_block_size(ids, E, 8)
    np.testing.assert_array_equal(np.asarray(routing[0]), np.asarray(sti_ref))
    xs = mu.gather_sorted(x, sti_ref, TOPK)
    flat = np.asarray(ids).reshape(-1)
    y_np, sti_np = np.asarray(y), np.asarray(sti_ref)
    for r in range(0, sti_np.shape[0], 37):   # spot-check rows
        s = sti_np[r]
        if s < M * TOPK:
            expect = np.asarray(xs[r] @ w_up[flat[s]])
            np.testing.assert_allclose(y_np[r], expect, atol=2e-5, rtol=2e-5)


class TestOverlapped:
    """Single-kernel overlapped engines (kernels/moe_tp_fused.py) vs the
    dense reference and the composed pipeline (VERDICT r1 #4)."""

    def _ctx(self, mesh8, **kw):
        from triton_distributed_tpu.ops.moe_tp import (
            create_ag_group_gemm_context,
        )

        return create_ag_group_gemm_context(
            mesh8, "x", num_experts=E, topk=TOPK, block_m=8,
            dtype=jnp.float32, **kw,
        )

    def test_overlapped_mlp_vs_dense(self, mesh8):
        from triton_distributed_tpu.ops.moe_tp import moe_tp_mlp_overlapped

        x, w_up, w_down, weights, ids = _data()
        ctx = self._ctx(mesh8)
        xg = jax.device_put(x, NamedSharding(mesh8, P("x")))
        idsg = jax.device_put(ids, NamedSharding(mesh8, P("x")))
        wg = jax.device_put(weights, NamedSharding(mesh8, P("x")))
        wug = jax.device_put(w_up, NamedSharding(mesh8, P(None, None, "x")))
        wdg = jax.device_put(w_down, NamedSharding(mesh8, P(None, "x")))
        out = moe_tp_mlp_overlapped(xg, idsg, wg, wug, wdg, ctx)
        ref = _dense_ref(x, w_up, w_down, weights, ids)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_overlapped_matches_composed(self, mesh8):
        """Same inputs through both pipelines must agree tightly — the
        'fused replaces composed' contract."""
        from triton_distributed_tpu.ops.moe_tp import (
            ag_group_gemm,
            align_routing,
            moe_reduce_rs,
            moe_tp_mlp_overlapped,
        )

        x, w_up, w_down, weights, ids = _data()
        ctx = self._ctx(mesh8)
        xg = jax.device_put(x, NamedSharding(mesh8, P("x")))
        wug = jax.device_put(w_up, NamedSharding(mesh8, P(None, None, "x")))
        wdg = jax.device_put(w_down, NamedSharding(mesh8, P(None, "x")))
        routing = align_routing(ctx, ids)
        y = ag_group_gemm(xg, routing, wug, ctx)
        composed = moe_reduce_rs(jax.nn.silu(y), routing, weights, wdg, ctx)

        idsg = jax.device_put(ids, NamedSharding(mesh8, P("x")))
        wg = jax.device_put(weights, NamedSharding(mesh8, P("x")))
        fused = moe_tp_mlp_overlapped(xg, idsg, wg, wug, wdg, ctx)
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(composed), atol=1e-5, rtol=1e-5
        )

    def test_overlapped_sorted_layout(self, mesh8):
        """ag_group_gemm_fused returns per-shard sorted slabs: slab s ==
        grouped GEMM over shard s's locally sorted tokens."""
        from triton_distributed_tpu.ops.moe_tp import (
            ag_group_gemm_fused,
            align_routing_sharded,
        )

        x, w_up, _, _, ids = _data()
        ctx = self._ctx(mesh8)
        xg = jax.device_put(x, NamedSharding(mesh8, P("x")))
        wug = jax.device_put(w_up, NamedSharding(mesh8, P(None, None, "x")))
        routing = align_routing_sharded(ctx, ids)
        y = np.asarray(ag_group_gemm_fused(xg, routing, wug, ctx))
        tp = ctx.tp
        m_s = M // tp
        cap_s = routing.cap_s
        for s in range(0, tp, 3):
            ids_s = np.asarray(ids)[s * m_s:(s + 1) * m_s]
            x_s = np.asarray(x)[s * m_s:(s + 1) * m_s]
            sti = np.asarray(routing.sti[s])
            xs = np.asarray(mu.gather_sorted(jnp.asarray(x_s), jnp.asarray(sti), TOPK))
            flat = ids_s.reshape(-1)
            slab = y[s * cap_s:(s + 1) * cap_s]
            for r in range(0, cap_s, 29):
                if sti[r] < m_s * TOPK:
                    expect = xs[r] @ w_up[flat[sti[r]]]
                    np.testing.assert_allclose(
                        slab[r], expect, atol=2e-5, rtol=2e-5
                    )
