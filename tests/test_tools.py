"""Tools tests: AOT, native library, profiler merge.

Mirrors the reference's AOT path (compile_aot.py + triton_aot_runtime)
and group_profile merge (utils.py:282-502).
"""

import gzip
import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.tools import (
    AotLibrary,
    TokenDataset,
    aot_compile,
    aot_load,
    artifact_read,
    artifact_write,
    group_profile,
    merge_chrome_traces,
    moe_align_block_size_host,
)

#: tier-1 fast subset (ci/fast.sh): AOT metadata + profiler merge, no collectives
pytestmark = pytest.mark.fast


class TestAot:
    def test_roundtrip(self, tmp_path):
        def f(a, b):
            return a @ b + 1

        args = (jnp.ones((16, 32)), jnp.ones((32, 8)))
        p = aot_compile(f, args, name="mm", cache_dir=tmp_path)
        g = aot_load(p)
        np.testing.assert_allclose(np.asarray(g(*args)), np.asarray(f(*args)))

    def test_library_dispatch_and_disk_reload(self, tmp_path):
        def f(a):
            return a * 2

        lib = AotLibrary(f, name="dbl", cache_dir=tmp_path)
        lib.compile(jnp.ones((8, 8)))
        # a fresh library instance must find the artifact on disk
        lib2 = AotLibrary(f, name="dbl", cache_dir=tmp_path)
        out = lib2(jnp.ones((8, 8)))
        np.testing.assert_allclose(np.asarray(out), 2.0)
        # unseen shape falls back to jit
        out2 = lib2(jnp.ones((4, 4)))
        np.testing.assert_allclose(np.asarray(out2), 2.0)


class TestNative:
    def test_artifact_roundtrip(self, tmp_path):
        blob = bytes(range(256)) * 100
        path = str(tmp_path / "a.art")
        artifact_write(path, blob)
        assert artifact_read(path) == blob

    def test_artifact_corruption_detected(self, tmp_path):
        from triton_distributed_tpu.tools.native import native_lib

        if native_lib() is None:
            pytest.skip("native library unavailable")
        path = str(tmp_path / "a.art")
        artifact_write(path, b"payload-bytes-here")
        raw = bytearray(pathlib.Path(path).read_bytes())
        raw[20] ^= 0xFF                       # flip a payload byte
        pathlib.Path(path).write_bytes(raw)
        with pytest.raises(IOError):
            artifact_read(path)

    def test_artifact_truncation_detected_python_path(self, tmp_path, monkeypatch):
        """A framed artifact cut short must raise, not come back as
        garbage raw bytes misread as a legacy file (ADVICE r1)."""
        from triton_distributed_tpu.tools import native as nat

        path = str(tmp_path / "a.art")
        artifact_write(path, b"payload-bytes-here" * 10)
        raw = pathlib.Path(path).read_bytes()
        pathlib.Path(path).write_bytes(raw[: len(raw) // 2])
        monkeypatch.setattr(nat, "_lib_cache", [None])  # pure-python reader
        with pytest.raises(IOError):
            artifact_read(path)

    def test_artifact_corruption_detected_python_path(self, tmp_path, monkeypatch):
        from triton_distributed_tpu.tools import native as nat

        path = str(tmp_path / "a.art")
        artifact_write(path, b"payload-bytes-here" * 10)
        raw = bytearray(pathlib.Path(path).read_bytes())
        raw[20] ^= 0xFF
        pathlib.Path(path).write_bytes(bytes(raw))
        monkeypatch.setattr(nat, "_lib_cache", [None])
        with pytest.raises(IOError):
            artifact_read(path)

    def test_artifact_cross_environment(self, tmp_path, monkeypatch):
        """Native-written artifacts must be readable by the pure-python
        path and vice versa (same framed on-disk format)."""
        from triton_distributed_tpu.tools import native as nat

        blob = b"cross-env-payload" * 50
        p_native = str(tmp_path / "n.art")
        artifact_write(p_native, blob)
        # force the fallback reader
        monkeypatch.setattr(nat, "_lib_cache", [None])
        assert artifact_read(p_native) == blob
        p_py = str(tmp_path / "p.art")
        artifact_write(p_py, blob)              # python writer
        monkeypatch.setattr(nat, "_lib_cache", [])
        assert artifact_read(p_py) == blob      # native reader (if built)

    def test_moe_align_rejects_bad_ids(self):
        ids = np.array([[0, 16]], np.int32)     # 16 == num_experts
        with pytest.raises(ValueError, match="out of range"):
            moe_align_block_size_host(ids, 16, 8)

    def test_moe_align_matches_jax(self):
        from triton_distributed_tpu.kernels import moe_utils as mu

        ids = np.random.default_rng(0).integers(0, 16, (64, 2)).astype(np.int32)
        sti_n, be_n, spl_n = moe_align_block_size_host(ids, 16, 8)
        sti_j, be_j, spl_j = mu.moe_align_block_size(jnp.asarray(ids), 16, 8)
        np.testing.assert_array_equal(sti_n, np.asarray(sti_j))
        np.testing.assert_array_equal(be_n, np.asarray(be_j))
        np.testing.assert_array_equal(spl_n, np.asarray(spl_j))

    def test_token_dataset(self, tmp_path):
        toks = np.arange(5000, dtype=np.uint32)
        path = tmp_path / "toks.bin"
        toks.tofile(path)
        ds = TokenDataset(str(path))
        assert len(ds) == 5000
        b = ds.sample(4, 64, seed=7)
        assert b.shape == (4, 65)
        for row in b:                          # contiguous windows
            np.testing.assert_array_equal(
                row, np.arange(row[0], row[0] + 65, dtype=np.uint32)
            )
        np.testing.assert_array_equal(b, ds.sample(4, 64, seed=7))
        ds.close()


class TestProfile:
    def test_merge_remaps_pids(self, tmp_path):
        for i in range(2):
            sub = tmp_path / f"process-{i}" / "plugins" / "profile"
            sub.mkdir(parents=True)
            with gzip.open(sub / "host.trace.json.gz", "wt") as f:
                json.dump(
                    {"traceEvents": [{"pid": 1, "tid": 1, "name": f"op{i}"}]}, f
                )
        out = merge_chrome_traces(tmp_path)
        ev = json.load(gzip.open(out, "rt"))["traceEvents"]
        assert sorted(e["pid"] for e in ev) == [1, 100000001]

    def test_merge_empty_returns_none(self, tmp_path):
        assert merge_chrome_traces(tmp_path) is None

    def test_merge_refuses_partial_multiprocess(self, tmp_path, monkeypatch):
        """On a multi-process run, a merge that can only see the local
        host's traces must refuse loudly, not silently produce a
        partial timeline (VERDICT r3 weak #6)."""
        import jax

        sub = tmp_path / "process-0" / "plugins" / "profile"
        sub.mkdir(parents=True)
        with gzip.open(sub / "host.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": [{"pid": 1, "name": "op"}]}, f)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        with pytest.raises(RuntimeError, match="gather_traces"):
            merge_chrome_traces(tmp_path)

    def test_gather_traces_single_process_noop(self, tmp_path):
        from triton_distributed_tpu.tools import gather_traces

        assert gather_traces(tmp_path) == pathlib.Path(tmp_path)

    def test_group_profile_writes(self, tmp_path):
        with group_profile(tmp_path):
            jnp.dot(jnp.ones((32, 32)), jnp.ones((32, 32))).block_until_ready()
        assert list(pathlib.Path(tmp_path).rglob("*"))


class TestCheckpoint:
    def test_roundtrip_with_resharding(self, mesh8, tmp_path):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from triton_distributed_tpu.tools import (
            restore_checkpoint,
            save_checkpoint,
        )

        params = {
            "w": jax.device_put(
                jnp.arange(64.0).reshape(8, 8),
                NamedSharding(mesh8, P("x", None)),
            ),
            "b": jnp.zeros((4,)),
            "nested": [jnp.ones((2, 2)), jnp.full((3,), 7)],
        }
        path = tmp_path / "ckpt"
        save_checkpoint(path, params)
        # restore onto a DIFFERENT sharding for w
        like = dict(params)
        like["w"] = jax.device_put(
            jnp.zeros((8, 8)), NamedSharding(mesh8, P(None, "x"))
        )
        out = restore_checkpoint(path, like)
        np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(params["w"]))
        assert out["w"].sharding.spec == P(None, "x")
        np.testing.assert_array_equal(np.asarray(out["nested"][1]), 7)

    def test_shape_mismatch_raises(self, tmp_path):
        from triton_distributed_tpu.tools import (
            restore_checkpoint,
            save_checkpoint,
        )

        save_checkpoint(tmp_path / "c", {"w": jnp.zeros((4,))})
        with pytest.raises(ValueError, match="shape mismatch"):
            restore_checkpoint(tmp_path / "c", {"w": jnp.zeros((5,))})

    def test_manager_retention_and_latest(self, tmp_path):
        from triton_distributed_tpu.tools import CheckpointManager

        mgr = CheckpointManager(tmp_path, keep=2)
        assert mgr.latest_step() is None
        assert mgr.restore({"w": jnp.zeros((2,))}) is None
        for s in (1, 5, 9):
            mgr.save(s, {"w": jnp.full((2,), float(s))})
        assert mgr.latest_step() == 9
        assert sorted(p.name for p in tmp_path.iterdir()) == ["step_5", "step_9"]
        out = mgr.restore({"w": jnp.zeros((2,))})
        np.testing.assert_allclose(np.asarray(out["w"]), 9.0)
        out5 = mgr.restore({"w": jnp.zeros((2,))}, step=5)
        np.testing.assert_allclose(np.asarray(out5["w"]), 5.0)

    def test_structure_mismatch_raises(self, tmp_path):
        from triton_distributed_tpu.tools import (
            restore_checkpoint,
            save_checkpoint,
        )

        save_checkpoint(tmp_path / "c", {"a": jnp.zeros((4,)), "b": jnp.ones((4,))})
        with pytest.raises(ValueError, match="tree structure"):
            restore_checkpoint(
                tmp_path / "c", {"a": jnp.zeros((4,)), "c": jnp.ones((4,))}
            )


def test_compile_aot_cli_roundtrip(tmp_path):
    """The AOT CLI (≡ reference compile_aot.py + gen_aot_code.sh) builds
    artifacts a fresh library with the same hyperparameters loads without
    a jit fallback."""
    import jax
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels.flash_decode import (
        gqa_fwd_batch_decode_aot,
    )
    from triton_distributed_tpu.tools.compile_aot import main

    rc = main([
        "--cache-dir", str(tmp_path), "--batch", "2", "--q-heads", "8",
        "--kv-heads", "2", "--head-dim", "128", "--seq", "256",
        "--block-k", "128", "--dtype", "float32",
    ])
    assert rc == 0
    lib = gqa_fwd_batch_decode_aot(
        block_k=128, kv_layout="bhsd", cache_dir=tmp_path
    )
    q = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 128), jnp.float32)
    kv = jax.random.normal(jax.random.PRNGKey(1), (2, 2, 256, 128), jnp.float32)
    out, _ = lib(q, kv, kv, jnp.array([200, 50], jnp.int32))
    assert lib.stats == {"artifact_loads": 1, "jit_fallbacks": 0}
    assert out.shape == (2, 8, 128)


def test_generate_cli(capsys):
    """The serving CLI: prefill + SP decode generate on a tiny preset
    (the L7 surface a user drives; tutorial 13 is the library version)."""
    from triton_distributed_tpu.tools.generate import main

    main(["--preset", "tiny", "--batch", "2", "--prompt-len", "8",
          "--steps", "2"])
    out = capsys.readouterr().out
    assert "decode:" in out and "sample completion ids:" in out


def test_generate_cli_unknown_preset():
    import pytest

    from triton_distributed_tpu.tools.generate import main

    with pytest.raises(SystemExit, match="unknown preset"):
        main(["--preset", "nope"])
