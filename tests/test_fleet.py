"""ISSUE-11 fleet suite: the health- and cache-aware router over N
engine replicas and its ReplicaDeath failover discipline.

The tentpole under test is :mod:`triton_distributed_tpu.serving.fleet`:

* **scoring** — the admission score (prefix overlap × health factor /
  fleet-relative load) against hand-built expectations, and the
  affinity/spill rules (queue at the prefix home while its score beats
  the best replica with room; spill — and re-home — when it doesn't);
* **cache-aware routing** — a shared-prefix session trace lands more
  prefix-cache page hits under the scored router than under the
  round-robin baseline;
* **failover** — a :class:`ReplicaDeath` mid-trace drains the dead
  replica's requests back through the router onto survivors: zero lost
  requests, token streams byte-identical to the fault-free run
  (sampling is keyed ``(seed, rid, n_generated)``, so placement can
  never change tokens); both-replicas-dead is a loud refusal;
* **probation re-entry** — a revived replica earns PROBATION through
  clean ticks and re-enters rotation through seeded probe traffic,
  never a blind re-add;
* **determinism** — same fleet seed ⇒ identical placement, and the
  fleet seed folds into ``config.interp_key`` like the fault plan;
* **chaos sites** — the ``router_dispatch`` site and the XLA
  ``kv_ship`` fallback transport are heartbeated: a fault-plan Stall
  under an armed watchdog trips into the ledger instead of wedging.

All sim-free: the fleet/router layers are host code, the engines run
their CPU paths (XLA twins).
"""

import gc
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_distributed_tpu import config
from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.runtime import faults, health, watchdog
from triton_distributed_tpu.runtime.faults import (
    FaultPlan,
    ReplicaDeath,
    Stall,
    parse_plan,
)
from triton_distributed_tpu.runtime.health import HealthLedger, PeerState
from triton_distributed_tpu.runtime.watchdog import WatchdogTimeout
from triton_distributed_tpu.serving import (
    DisaggregatedEngine,
    EngineConfig,
    Request,
    ServingEngine,
)
from triton_distributed_tpu.serving.fleet import (
    FleetRouter,
    RouterConfig,
    ServingFleet,
)

#: tier-1 fast subset (ci/fast.sh): the fleet half of the robustness
#: story
pytestmark = pytest.mark.fast


@pytest.fixture(autouse=True)
def _isolated_ledgers():
    yield
    health.set_ledger(None)
    faults.set_fault_plan(None)
    watchdog.clear_trip()
    config.set_fleet_seed(None)
    gc.collect()


CFG = dict(
    vocab=128, n_layers=2, hidden=64, ffn=128,
    n_heads=4, n_kv_heads=2, head_dim=16,
    dtype=jnp.float32, param_dtype=jnp.float32, kv_quant="int8",
)

ECFG = dict(slots=4, token_budget=48, chunk=16, page=8, npages=32,
            prefix_cache=True, temperature=0.7, top_k=40, seed=11)


@pytest.fixture(scope="module")
def fleet_models():
    """Two replica models on their own 1-device meshes, same params."""
    devs = jax.devices()
    out = []
    params = None
    for k in range(2):
        mesh = Mesh(np.asarray(devs[k:k + 1]), ("tp",))
        model = Transformer(TransformerConfig(**CFG), mesh, "tp", ())
        if params is None:
            params = model.init(jax.random.PRNGKey(0))
        p = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                         model.shardings())
        out.append((model, p))
    return out


def _fast_ledger(seed=0):
    return HealthLedger(seed=seed, probation_after=1, promote_after=1,
                        probe_interval=2)


def _fleet(fleet_models, policy="scored", seed=1, ledger=None, **ecfg):
    kw = dict(ECFG, **ecfg)
    engines = [ServingEngine(m, p, EngineConfig(**kw), use_pallas=False)
               for m, p in fleet_models]
    return ServingFleet(engines, seed=seed,
                        router=RouterConfig(policy=policy),
                        health=ledger)


def _req(rid, arrival, session=None, plen=20, max_new=5, prefix=None):
    rng = np.random.default_rng(1000 + rid)
    prompt = rng.integers(0, CFG["vocab"], (plen,)).astype(np.int32)
    if prefix is not None:
        prompt = np.concatenate(
            [prefix, prompt[:6].astype(np.int32)])
    r = Request(rid=rid, prompt=prompt, max_new=max_new,
                arrival=arrival)
    if session is not None:
        r.session = session
    return r


def _trace(n=8, session_every=None, prefix=None, spread=1.0):
    out = []
    for i in range(n):
        sess = ("s" if session_every and i % session_every == 0
                else None)
        out.append(_req(i, arrival=i * spread, session=sess,
                        prefix=prefix if sess else None))
    return out


# ------------------------------------------------------------- scoring

class _StubReplica:
    def __init__(self, index, overlap=0, load=0.0, room=True):
        self.index = index
        self.peer = f"replica:{index}"
        self._overlap, self._load, self._room = overlap, load, room

    def overlap_pages(self, req):
        return self._overlap

    def load_ms(self):
        return self._load

    def can_accept(self, req):
        return self._room

    def fits_context(self, req):
        return True


class _StubLedger:
    def __init__(self, states=None):
        self._states = states or {}

    def state(self, peer):
        return self._states.get(peer, PeerState.HEALTHY)


class TestScoring:
    def test_score_matches_hand_formula(self):
        router = FleetRouter(seed=0)
        r = _StubReplica(0, overlap=4, load=2.0)
        # (1 + w_prefix*4) * hf / (1 + w_load * load/mean)
        assert router.score(r, None, PeerState.HEALTHY, 2.0) \
            == pytest.approx(5.0 / 2.0)
        assert router.score(r, None, PeerState.SUSPECT, 2.0) \
            == pytest.approx(5.0 / 4.0)
        assert router.score(r, None, PeerState.UNHEALTHY, 2.0) is None
        assert router.score(r, None, PeerState.PROBATION, 2.0) is None
        # no load anywhere -> pure prefix * health
        assert router.score(r, None, PeerState.HEALTHY, 0.0) \
            == pytest.approx(5.0)

    def test_route_picks_highest_score(self):
        router = FleetRouter(seed=0)
        cold = _StubReplica(0, overlap=0, load=1.0)
        warm = _StubReplica(1, overlap=5, load=1.0)
        chosen, spilled = router.route(
            _req(0, 0.0), [cold, warm], _StubLedger())
        assert chosen is warm and not spilled

    def test_route_excludes_condemned(self):
        router = FleetRouter(seed=0)
        sick = _StubReplica(0, overlap=9)
        ok = _StubReplica(1)
        led = _StubLedger({"replica:0": PeerState.UNHEALTHY})
        chosen, _ = router.route(_req(0, 0.0), [sick, ok], led)
        assert chosen is ok
        led = _StubLedger({"replica:0": PeerState.UNHEALTHY,
                           "replica:1": PeerState.PROBATION})
        with pytest.raises(RuntimeError, match="no survivor"):
            router.route(_req(0, 0.0), [sick, ok], led)

    def test_affinity_sticks_and_follows(self):
        router = FleetRouter(seed=0)
        a, b = _StubReplica(0), _StubReplica(1)
        req = _req(0, 0.0, session="s")
        router.affinity["s"] = 0
        chosen, spilled = router.route(req, [a, b], _StubLedger())
        assert chosen is a and not spilled
        assert router.affinity["s"] == 0

    def test_full_home_queues_while_score_justifies(self):
        """A full home with a resident prefix still wins: waiting where
        the pages live beats re-prefilling them elsewhere."""
        router = FleetRouter(seed=0)
        home = _StubReplica(0, overlap=10, load=1.0, room=False)
        other = _StubReplica(1, overlap=0, load=1.0, room=True)
        router.affinity["s"] = 0
        chosen, spilled = router.route(
            _req(0, 0.0, session="s"), [home, other], _StubLedger())
        assert chosen is home and not spilled

    def test_full_cold_home_spills_and_rehomes(self):
        router = FleetRouter(seed=0)
        home = _StubReplica(0, overlap=0, load=3.0, room=False)
        other = _StubReplica(1, overlap=0, load=1.0, room=True)
        router.affinity["s"] = 0
        chosen, spilled = router.route(
            _req(0, 0.0, session="s"), [home, other], _StubLedger())
        assert chosen is other and spilled
        assert router.affinity["s"] == 1   # affinity follows the spill


# ------------------------------------------------- cache-aware routing

class TestCacheAwareRouting:
    def test_prefix_routing_beats_round_robin(self, fleet_models):
        """A session's followers land where the leader's prefix pages
        are resident under the scored router; round-robin scatters them
        and pays the prefill once per replica."""
        rng = np.random.default_rng(7)
        prefix = rng.integers(0, CFG["vocab"], (80,)).astype(np.int32)

        def trace():
            # leader at 0, followers after its prefill completed,
            # poisson-ish fillers in between
            out = [_req(0, 0.0, session="s", prefix=prefix)]
            out += [_req(1 + j, 1.0 + j) for j in range(4)]
            out += [_req(5 + j, 8.0 + 1.5 * j, session="s",
                         prefix=prefix) for j in range(4)]
            return out

        scored = _fleet(fleet_models, "scored")
        scored.run(trace())
        rr = _fleet(fleet_models, "round_robin")
        rr.run(trace())
        assert scored.stats.lost_requests == 0
        assert rr.stats.lost_requests == 0
        assert scored.prefix_hits > rr.prefix_hits
        assert scored.goodput_tok_per_s > 0


# ------------------------------------------------------------ failover

class TestReplicaDeathFailover:
    def _session_trace(self):
        # session "s" pinned to replica 1 via the public affinity map,
        # so the step-4 death is guaranteed to catch in-flight work
        out = [_req(i, i * 0.7, session="s" if i % 2 else None,
                    max_new=6) for i in range(8)]
        return out

    def test_death_failover_token_exact(self, fleet_models):
        ref = _fleet(fleet_models, "scored")
        ref.router.affinity["s"] = 1
        ref.run(self._session_trace())
        assert ref.stats.lost_requests == 0
        ref_tokens = ref.token_streams()

        fleet = _fleet(fleet_models, "scored")
        fleet.router.affinity["s"] = 1
        plan = FaultPlan(seed=1,
                         faults=(ReplicaDeath(replica=1, step=4),))
        with faults.fault_plan(plan):
            stats = fleet.run(self._session_trace())
        assert stats.lost_requests == 0
        assert stats.completed == 8
        assert stats.deaths == [(1, 4)]
        assert stats.failover_requeued >= 1
        assert fleet.health.state("replica:1") is PeerState.UNHEALTHY
        assert fleet.rotation() == (0,)
        assert fleet.token_streams() == ref_tokens
        # run() restored the ambient fleet seed
        assert config.fleet_seed() is None

    def test_all_replicas_dead_refuses(self, fleet_models):
        fleet = _fleet(fleet_models)
        plan = FaultPlan(seed=1, faults=(
            ReplicaDeath(replica=0, step=2),
            ReplicaDeath(replica=1, step=2)))
        with faults.fault_plan(plan):
            with pytest.raises(RuntimeError, match="no survivor"):
                fleet.run(_trace())

    def test_probation_reentry_after_revive(self, fleet_models):
        """A revived replica re-enters rotation through the probation
        probe path: clean ticks earn PROBATION, a seeded probe carries
        real traffic, a clean probe earns HEALTHY — never a blind
        re-add."""
        fleet = _fleet(fleet_models, ledger=_fast_ledger())
        plan = FaultPlan(seed=1,
                         faults=(ReplicaDeath(replica=1, step=2),))
        with faults.fault_plan(plan):
            fleet.run(_trace())
        assert fleet.rotation() == (0,)

        m, p = fleet_models[1]
        fleet.revive(1, ServingEngine(m, p, EngineConfig(**ECFG),
                                      use_pallas=False))
        base = fleet.ticks
        second = [_req(100 + i, base + 1.0 + i, max_new=4)
                  for i in range(8)]
        fleet.run(second)
        assert fleet.stats.lost_requests == 0
        assert fleet.stats.probes >= 1
        assert fleet.health.state("replica:1") is PeerState.HEALTHY
        assert fleet.rotation() == (0, 1)
        assert fleet.stats.routed.get(1, 0) >= 1

    def test_revive_requires_dead(self, fleet_models):
        fleet = _fleet(fleet_models)
        with pytest.raises(ValueError, match="not dead"):
            fleet.revive(0)


# --------------------------------------------------------- determinism

class TestDeterminism:
    def _placements(self, fleet_models, seed):
        fleet = _fleet(fleet_models, seed=seed)
        placed = []
        orig = FleetRouter.route

        def spy(router, req, replicas, ledger):
            r, sp = orig(router, req, replicas, ledger)
            placed.append((req.rid, r.index, sp))
            return r, sp

        fleet.router.route = types.MethodType(spy, fleet.router)
        fleet.run(_trace(n=10, session_every=3))
        return placed, dict(fleet.stats.routed)

    def test_same_seed_identical_placement(self, fleet_models):
        p1, r1 = self._placements(fleet_models, seed=5)
        p2, r2 = self._placements(fleet_models, seed=5)
        assert p1 == p2
        assert r1 == r2

    def test_fleet_seed_in_interp_key(self):
        base = config.interp_key()
        config.set_fleet_seed(3)
        keyed = config.interp_key()
        assert keyed != base
        assert 3 in keyed
        config.set_fleet_seed(None)
        assert config.interp_key() == base

    def test_run_installs_fleet_seed(self, fleet_models):
        seen = {}
        fleet = _fleet(fleet_models, seed=9)
        orig_tick = fleet.tick

        def spy():
            seen["seed"] = config.fleet_seed()
            return orig_tick()

        fleet.tick = spy
        fleet.run(_trace(n=2))
        assert seen["seed"] == 9
        assert config.fleet_seed() is None

    def test_parse_plan_replica_death_roundtrip(self):
        plan = parse_plan("seed=2; ReplicaDeath(replica=1, step=8)")
        assert plan.seed == 2
        assert plan.faults == (ReplicaDeath(replica=1, step=8),)
        assert plan.dead_replicas(7) == ()
        assert plan.dead_replicas(8) == (1,)
        assert plan.dead_replicas() == (1,)


# --------------------------------------------------------- chaos sites

class TestChaosSites:
    def test_router_dispatch_stall_trips_watchdog(self, fleet_models):
        """A fault-plan Stall at the router_dispatch site wedges the
        WHOLE fleet's admission; an armed watchdog trips, names the
        site, releases the gate, and the trace still completes."""
        fleet = _fleet(fleet_models)
        plan = FaultPlan(seed=0,
                         faults=(Stall(site="router_dispatch", rank=0),))
        box = {}
        with faults.fault_plan(plan):
            with pytest.raises(WatchdogTimeout):
                with watchdog.collective_watchdog(deadline=0.2):
                    box["stats"] = fleet.run(_trace(n=4))
        assert box["stats"].lost_requests == 0
        assert fleet.health.state("site:router_dispatch") \
            is PeerState.UNHEALTHY

    def test_xla_kv_ship_fallback_is_heartbeated(self):
        """Satellite pin: the XLA collective-fallback KV ship transport
        runs under the kv_ship watchdog instrument — the LAST
        unheartbeated fallback entry point. A Stall there trips into
        the ledger instead of wedging the transfer."""
        from triton_distributed_tpu.tools import native

        led = HealthLedger(seed=0)
        payload = {"pages": np.ones((2, 4), np.int8)}
        plan = FaultPlan(seed=0, faults=(Stall(site="kv_ship", rank=0),))
        with faults.fault_plan(plan):
            with pytest.raises(WatchdogTimeout):
                with watchdog.collective_watchdog(deadline=0.2):
                    out = native.xla_kv_ship(
                        payload, {"pages": None})
                    # stall released by the trip; bytes still intact
                    assert np.array_equal(out["pages"],
                                          payload["pages"])
        assert led.state("site:kv_ship") is PeerState.UNHEALTHY


# -------------------------------------------- admission control (cap)

class TestAdmissionControl:
    """RouterConfig.queue_cap: a flooded trace is REJECTED with a
    priced retry-after once every routable replica's queue is at cap —
    `waiting` stops growing without bound, and nothing is lost (the
    harness re-enters rejected requests at their retry tick, standing
    in for a client honoring Retry-After)."""

    def _flooded_fleet(self, fleet_models, cap, slots=2):
        kw = dict(ECFG, slots=slots, npages=24)
        engines = [ServingEngine(m, p, EngineConfig(**kw),
                                 use_pallas=False)
                   for m, p in fleet_models]
        return ServingFleet(engines, seed=1,
                            router=RouterConfig(queue_cap=cap))

    def _flood(self, n):
        return [_req(i, arrival=0, plen=10, max_new=4)
                for i in range(n)]

    def test_flood_rejects_with_priced_retry_after(self, fleet_models):
        fleet = self._flooded_fleet(fleet_models, cap=2)
        stats = fleet.run(self._flood(14))
        assert stats.admission_rejections > 0
        assert stats.lost_requests == 0
        # the retry-after is PRICED (perf-model ms), never a blind 0
        assert len(stats.retry_after_ms) == stats.admission_rejections
        assert all(ms > 0 for ms in stats.retry_after_ms)
        # the cap held: no replica's queue ever exceeded cap + the
        # one-tick dispatch batch the cap is applied within
        assert all(r.queue_depth() == 0 for r in fleet.replicas)

    def test_cap_bounds_queue_depth_vs_uncapped(self, fleet_models):
        """The uncapped fleet buffers the whole flood in `waiting`; the
        capped fleet never queues deeper than cap at dispatch time."""
        kw = dict(ECFG, slots=2, npages=24)

        def depth_trace(router):
            engines = [ServingEngine(m, p, EngineConfig(**kw),
                                     use_pallas=False)
                       for m, p in fleet_models]
            fleet = ServingFleet(engines, seed=1, router=router)
            fleet.submit_trace(self._flood(14))
            depths = []
            for _ in range(200):
                if fleet.idle:
                    break
                fleet.tick()
                depths.append(max(r.queue_depth()
                                  for r in fleet.replicas))
            return fleet.stats, max(depths)

        un_stats, un_depth = depth_trace(RouterConfig())
        cap_stats, cap_depth = depth_trace(RouterConfig(queue_cap=2))
        assert un_stats.lost_requests == 0
        assert cap_stats.lost_requests == 0
        assert un_stats.admission_rejections == 0
        assert cap_stats.admission_rejections > 0
        assert cap_depth < un_depth, (cap_depth, un_depth)
        # dispatch admits into slots before queueing, so post-tick
        # depth stays bounded by the cap itself
        assert cap_depth <= 2

    def test_flood_with_replica_death_chaos(self, fleet_models):
        """Chaos pin: the cap keeps rejecting (on the survivor's queue
        alone) across a mid-flood ReplicaDeath, and the drain + retry
        paths compose — zero lost requests."""
        fleet = self._flooded_fleet(fleet_models, cap=2)
        plan = faults.parse_plan(
            "seed=1; ReplicaDeath(replica=1, step=3)")
        with faults.fault_plan(plan):
            stats = fleet.run(self._flood(12))
        assert stats.deaths == [(1, 3)]
        assert stats.admission_rejections > 0
        assert stats.lost_requests == 0
        assert stats.failover_requeued >= 0

    def test_zero_cap_refused(self, fleet_models):
        with pytest.raises(ValueError, match="queue_cap"):
            self._flooded_fleet(fleet_models, cap=0)


# ------------------------------------------- elastic fleet (ISSUE-13)

def _spare_factory(fleet_models, k=1, **ecfg):
    m, p = fleet_models[k]
    kw = dict(ECFG, **ecfg)
    return lambda: ServingEngine(m, p, EngineConfig(**kw),
                                 use_pallas=False)


class TestCarveReserve:
    def test_reserve_split_and_back_compat(self):
        from triton_distributed_tpu.runtime.topology import (
            carve_replica_meshes,
        )

        devs = jax.devices()
        active, spares = carve_replica_meshes(2, devs, reserve=1)
        assert len(active) == 2 and len(spares) == 1
        # reserve=0 keeps returning the pre-elastic flat list
        flat = carve_replica_meshes(2, devs)
        assert isinstance(flat, list) and len(flat) == 2
        with pytest.raises(ValueError, match="reserve"):
            carve_replica_meshes(2, devs, reserve=-1)


class _ScriptedScaler:
    """FleetAutoscaler with a scripted pressure signal — isolates the
    window/cooldown flap damping from the perf model."""

    def __init__(self, cfg, script):
        from triton_distributed_tpu.serving import FleetAutoscaler

        self.inner = FleetAutoscaler(cfg)
        self.inner.pressure = lambda fleet: bool(script.pop(0))

    def run(self, n):
        import types as _t

        decisions = []
        for t in range(n):
            fleet = _t.SimpleNamespace(ticks=t, _alive=lambda: [None])
            if self.inner.should_grow(fleet):
                decisions.append(t)
                self.inner.last_grow = t
                self.inner.pressured = 0
        return decisions


class TestAutoscaler:
    def test_window_and_cooldown_damping(self):
        from triton_distributed_tpu.serving import AutoscalerConfig

        cfg = AutoscalerConfig(slo_ms=1.0, window=2, cooldown=4)
        # pressure: sustained from t=1..9 with a one-tick dip at t=5
        script = [False, True, True, True, True, False,
                  True, True, True, True]
        grows = _ScriptedScaler(cfg, script).run(10)
        # first grow needs TWO consecutive pressured ticks (t=2); the
        # dip resets the window, then the second grow waits out BOTH
        # the rebuilt window (t=7) and the cooldown (7 - 2 >= 4)
        assert grows == [2, 7]

    def test_grow_spawns_probation_gated_replica(self, fleet_models):
        from triton_distributed_tpu.serving import AutoscalerConfig

        m0, p0 = fleet_models[0]
        engines = [ServingEngine(m0, p0, EngineConfig(**ECFG),
                                 use_pallas=False)]
        fleet = ServingFleet(
            engines, seed=1, router=RouterConfig(),
            health=_fast_ledger(),
            reserve=[_spare_factory(fleet_models)],
            autoscaler=AutoscalerConfig(slo_ms=0.0, window=2,
                                        cooldown=3, max_replicas=2))
        # staggered arrivals: the flood keeps arriving PAST the grow,
        # so the probe path has dispatch-time traffic to feed on
        trace = [_req(i, i * 0.5, plen=12, max_new=5)
                 for i in range(18)]
        stats = fleet.run(trace)
        assert stats.lost_requests == 0
        assert len(stats.grows) == 1          # max_replicas damped
        grown, at = stats.grows[0]
        assert grown == 1 and at >= 1         # window needed 2 ticks
        # the newcomer walked the PR-10 path: ledger entry, probes,
        # then real traffic — and ended HEALTHY in the rotation
        assert fleet.health.state("replica:1") is PeerState.HEALTHY
        assert stats.probes >= 1
        assert stats.routed.get(1, 0) >= 1
        assert 1 in fleet.rotation()
        kinds = [e[0] for e in stats.events]
        assert "grow" in kinds
        assert not fleet._reserve             # spare consumed

    def test_grow_without_reserve_refused(self, fleet_models):
        fleet = _fleet(fleet_models)
        with pytest.raises(ValueError, match="reserve"):
            fleet.grow()


class TestDrainMigration:
    def _pinned_trace(self, n_each=2, max_new=8):
        out = []
        for i in range(n_each):
            out.append(_req(i, 0.0, session="a", plen=20,
                            max_new=max_new))
        for i in range(n_each):
            out.append(_req(10 + i, 0.0, session="b", plen=20,
                            max_new=max_new))
        return out

    def _run_drained(self, fleet_models, drain_at=3, drain=1,
                     perf_spec=None, plan=None, death=None):
        fleet = _fleet(fleet_models, "scored")
        fleet.perf_spec = perf_spec
        fleet.router.affinity["a"] = 0
        fleet.router.affinity["b"] = 1
        fleet.submit_trace(self._pinned_trace())
        for t in range(400):
            if fleet.idle:
                break
            if t == drain_at:
                fleet.drain(drain)
            fleet.tick()
        return fleet

    def test_drain_migrates_pages_token_exact(self, fleet_models):
        ref = _fleet(fleet_models, "scored")
        ref.router.affinity["a"] = 0
        ref.router.affinity["b"] = 1
        ref.run(self._pinned_trace())
        assert ref.stats.lost_requests == 0

        fleet = self._run_drained(fleet_models)
        st = fleet.stats
        assert st.lost_requests == 0
        assert st.completed == 4
        # resident rows moved their committed pages over the wire —
        # and every shipped migration priced under the re-prefill
        assert st.migrations >= 1
        assert st.migrated_pages >= 1
        assert st.migration_wire_bytes > 0
        assert st.migrations_cheaper == st.migrations
        assert all(w < r for w, r in st.migration_priced)
        # the drained replica retired cleanly and left the rotation
        assert len(st.drains) == 1
        k, start, done = st.drains[0]
        assert k == 1 and start == 3 and done >= start
        assert fleet.rotation() == (0,)
        assert 1 in fleet._retired
        kinds = [e[0] for e in st.events]
        assert "drain_start" in kinds and "drain_done" in kinds
        assert "migrate" in kinds
        # placement changed, bytes did not
        assert fleet.token_streams() == ref.token_streams()

    def test_pricing_flip_refuses_migration(self, fleet_models):
        """A DCN priced absurdly slow flips migrate_vs_reprefill: the
        drain REFUSES the wire, rows finish in place, and the streams
        stay byte-identical — the degradation is time, never tokens."""
        from triton_distributed_tpu.tune.perf_model import TpuSpec

        slow = TpuSpec(name="torture-dcn", bf16_tflops=200.0,
                       hbm_gbps=800.0, ici_gbps=50.0, ici_links=4,
                       dcn_gbps=1e-12)
        ref = self._run_drained(fleet_models)
        fleet = self._run_drained(fleet_models, perf_spec=slow)
        st = fleet.stats
        assert st.migrations == 0
        assert st.migration_refusals >= 1
        assert st.lost_requests == 0
        assert st.completed == 4
        assert 1 in fleet._retired
        assert fleet.token_streams() == ref.token_streams()

    def test_drain_last_routable_refused(self, fleet_models):
        fleet = _fleet(fleet_models)
        fleet.drain(1)
        with pytest.raises(RuntimeError, match="last routable"):
            fleet.drain(0)
        with pytest.raises(ValueError, match="dead/retired"):
            fleet.drain(7)

    def test_event_log_replays_deterministically(self, fleet_models):
        logs = []
        for _ in range(2):
            fleet = self._run_drained(fleet_models)
            logs.append(list(fleet.stats.events))
        assert logs[0] == logs[1]


# -------------------------------------------------- chaos soak (soak)

class TestChaosSoak:
    """The ISSUE-13 composition pin: a flood past ``queue_cap`` × a
    ReplicaDeath DURING an active drain × a migration-transport Stall,
    all in one run — lost_requests stays 0 and every stream is
    byte-exact against the fault-free fleet. Robustness features must
    compose, not merely pass alone."""

    def _soak_trace(self):
        out = []
        for i in range(3):
            out.append(_req(i, 0.0, session="a", plen=20, max_new=10))
        for i in range(3):
            out.append(_req(10 + i, 0.0, session="b", plen=20,
                            max_new=10))
        # late fillers: they flood the lone survivor after the death
        out += [_req(20 + i, 6.0, plen=10, max_new=4)
                for i in range(6)]
        return out

    def _soak_fleet(self, fleet_models):
        kw = dict(ECFG)
        engines = [ServingEngine(m, p, EngineConfig(**kw),
                                 use_pallas=False)
                   for m, p in fleet_models]
        fleet = ServingFleet(engines, seed=1,
                             router=RouterConfig(queue_cap=2))
        fleet.router.affinity["a"] = 0
        fleet.router.affinity["b"] = 1
        return fleet

    def test_flood_death_mid_drain_migration_stall(self, fleet_models):
        ref = self._soak_fleet(fleet_models)
        ref.run(self._soak_trace())
        assert ref.stats.lost_requests == 0

        fleet = self._soak_fleet(fleet_models)
        plan = FaultPlan(seed=1, faults=(
            ReplicaDeath(replica=0, step=5),
            Stall(site="kv_migrate", rank=0)))
        fleet.submit_trace(self._soak_trace())
        with faults.fault_plan(plan):
            # warm ticks before the watchdog arms: admission + first
            # chunks compile here, so only the STALL can look stalled
            for t in range(2):
                fleet.tick()
            with pytest.raises(WatchdogTimeout):
                with watchdog.collective_watchdog(deadline=0.2):
                    for t in range(2, 400):
                        if fleet.idle:
                            break
                        if t == 3:
                            fleet.drain(0)
                        fleet.tick()
        st = fleet.stats
        assert st.lost_requests == 0
        assert st.completed == 12
        # all three chaos ingredients actually fired
        assert st.admission_rejections > 0          # the cap rejected
        assert st.migrations >= 1                   # stalled, then shipped
        assert st.deaths == [(0, 5)]                # died MID-drain
        death = next(e for e in st.events if e[0] == "death")
        assert "mid-drain" in death[3]
        assert fleet.health.state("site:kv_migrate") \
            is PeerState.UNHEALTHY
        # the interrupted drain never completes; the failover path
        # finished the job instead — with zero lost work
        assert st.drains == []
        assert not fleet._draining
        assert st.failover_requeued >= 1
        assert fleet.token_streams() == ref.token_streams()


# ----------------------------------------- ship-window chaos (ISSUE-19)

class TestShipReservationWindowChaos:
    """The servlint-discovered interleaving as a concrete chaos case:
    ``ReplicaDeath`` landing BETWEEN ``reserve_shipped`` and
    ``commit_shipped`` on a disaggregated replica — the destination
    slot+pages are reserved, the payload is in flight, and the replica
    dies before the commit fence. The reservation must roll back with
    the replica (its pool died with the slice) and every mid-ship
    request must re-route onto the survivor: 0 lost requests, 0 leaked
    pages."""

    def _trace(self, n=4, max_new=6):
        return [_req(i, 0.0, session="s", plen=20, max_new=max_new)
                for i in range(n)]

    def _fleet_with_disagg(self, fleet_models, ship_delay_steps=3):
        (m0, p0), (m1, p1) = fleet_models
        colo = ServingEngine(m0, p0, EngineConfig(**ECFG),
                             use_pallas=False)
        # same model for both roles: transport="xla" needs no hybrid
        # mesh, and the window under test is the host-side reservation
        disagg = DisaggregatedEngine(
            m1, p1, m1, p1, EngineConfig(**ECFG), transport="xla",
            ship_delay_steps=ship_delay_steps, use_pallas=False)
        fleet = ServingFleet([colo, disagg], seed=1,
                             router=RouterConfig(policy="scored"))
        fleet.router.affinity["s"] = 1
        return fleet

    def test_death_in_reservation_window(self, fleet_models):
        ref = self._fleet_with_disagg(fleet_models)
        ref.run(self._trace())
        assert ref.stats.lost_requests == 0
        ref_streams = ref.token_streams()

        fleet = self._fleet_with_disagg(fleet_models)
        fleet.submit_trace(self._trace())
        eng = fleet.replicas[1].engine
        armed = None
        for t in range(400):
            if fleet.idle:
                break
            if armed is None and eng._inflight:
                # reserve_shipped ran (decode slot+pages reserved,
                # req parked) and nothing has committed yet: arm the
                # death so the NEXT tick's death check — which runs
                # before any step could commit — kills the replica
                # inside the reservation window
                assert eng.stats.ships == 0
                armed = [r.req.rid for r in eng._inflight]
                faults.set_fault_plan(FaultPlan(
                    seed=1,
                    faults=(ReplicaDeath(replica=1, step=fleet.ticks),)))
            fleet.tick()
        assert armed, "no ship ever entered the reservation window"
        st = fleet.stats
        assert st.lost_requests == 0
        assert st.completed == 4
        assert [k for k, _ in st.deaths] == [1]
        assert st.failover_requeued >= len(armed)
        # the mid-ship payload never landed: the commit was rolled
        # back with the replica, not half-applied
        assert eng.stats.ships == 0
        # 0 leaked pages on the survivor: at idle every page is either
        # on the free list or parked in the reclaimable prefix cache,
        # and no refcount is live
        for role in fleet.replicas[0]._roles:
            assert int((np.asarray(role.pool.refs) > 0).sum()) == 0
            assert role.pool.available == role.pool.npages
        # placement changed (survivor finished the mid-ship rows),
        # bytes did not
        assert fleet.token_streams() == ref_streams


# ------------------------------------- drain-cancel on death (ISSUE-19)

class TestDrainCancelOnDeath:
    """servlint SV007 counterexample, regression-pinned: replica 0
    draining, replica 1 (the only other routable replica) dies — the
    backlog would wait forever on a fleet whose sole survivor admits no
    routed work. ``_kill`` now cancels the surviving drains (capacity
    loss outranks the drain intent)."""

    def test_death_of_last_routable_cancels_drain(self, fleet_models):
        from triton_distributed_tpu.tune.perf_model import TpuSpec

        # price the migration wire absurdly slow so the drain cannot
        # complete instantly — rows finish in place, holding the drain
        # open across the death tick
        slow = TpuSpec(name="slow-dcn", bf16_tflops=200.0,
                       hbm_gbps=800.0, ici_gbps=50.0, ici_links=4,
                       dcn_gbps=1e-12)

        def _trace():
            out = [_req(i, 0.0, session="a", plen=20, max_new=8)
                   for i in range(2)]
            out += [_req(10 + i, 0.0, session="b", plen=20, max_new=8)
                    for i in range(2)]
            out += [_req(20 + i, 4.0, plen=10, max_new=3)
                    for i in range(3)]
            return out

        ref = _fleet(fleet_models, "scored")
        ref.perf_spec = slow
        ref.router.affinity["a"] = 0
        ref.router.affinity["b"] = 1
        ref.run(_trace())
        assert ref.stats.lost_requests == 0

        fleet = _fleet(fleet_models, "scored")
        fleet.perf_spec = slow
        fleet.router.affinity["a"] = 0
        fleet.router.affinity["b"] = 1
        fleet.submit_trace(_trace())
        plan = FaultPlan(seed=1,
                         faults=(ReplicaDeath(replica=1, step=5),))
        with faults.fault_plan(plan):
            for t in range(400):
                if t == 3:
                    fleet.drain(0)
                if fleet.idle:
                    break
                fleet.tick()
        st = fleet.stats
        assert st.lost_requests == 0
        assert st.completed == 7
        assert st.deaths == [(1, 5)]
        # the drain was CANCELED, not completed: replica 0 is back in
        # rotation serving the backlog, never retired
        cancels = [e for e in st.events if e[0] == "drain_cancel"]
        assert cancels and cancels[0][1] == 0
        assert "death@1" in cancels[0][3]
        assert st.drains == []
        assert not fleet._draining
        assert 0 not in fleet._retired
        assert fleet.rotation() == (0,)
        # the backlog drained through the de-drained survivor with the
        # streams still byte-identical to the fault-free run
        assert fleet.token_streams() == ref.token_streams()


# --------------------------------- ProtocolOps seam pin (ISSUE-19)

class TestProtocolSeamTraceEquality:
    """The ProtocolOps refactor is behavior-preserving: this golden
    fleet trace (events + token streams) was captured BEFORE the
    serving verbs moved behind the seam. Same seed ⇒ byte-identical
    ``FleetStats.events`` and streams after it."""

    GOLDEN_EVENTS = [
        ("drain_start", 0, 6, "requeued=0"),
        ("migrate", 0, 6, "rid=5 pages=2 -> replica 1"),
        ("migrate", 0, 6, "rid=3 pages=3 -> replica 1"),
        ("migrate", 0, 6, "rid=4 pages=3 -> replica 1"),
        ("drain_done", 0, 6, "started@6"),
    ]
    GOLDEN_STREAMS = [
        (0, (19, 60, 73, 107)), (1, (54, 81, 32, 53)),
        (2, (123, 84, 51, 95)), (3, (121, 80, 80, 77)),
        (4, (20, 62, 113, 84)), (5, (19, 46, 26, 48)),
        (6, (31, 44, 73, 0)), (7, (70, 5, 51, 35)),
    ]

    def test_golden_fleet_trace_unchanged(self, fleet_models):
        fleet = _fleet(fleet_models, "scored", seed=3)
        fleet.submit_trace([_req(i, float(i), plen=20, max_new=4)
                            for i in range(8)])
        for t in range(60):
            if t == 6:
                fleet.drain(0)
            if fleet.idle:
                break
            fleet.tick()
        assert fleet.stats.lost_requests == 0
        assert list(fleet.stats.events) == self.GOLDEN_EVENTS
        streams = sorted((r, tuple(v))
                         for r, v in fleet.token_streams().items())
        assert streams == self.GOLDEN_STREAMS
