"""lang-layer tests: the minimum end-to-end distributed slices.

Equivalents of the reference's primitive tests: tutorial-01 notify/wait
producer-consumer (tutorials/01-distributed-notify-wait.py), ring put
(shmem/nvshmem_bind/pynvshmem/example/run_ring_put.py), barriers
(test/nvidia/test_common_ops.py).
"""

import jax
import pytest
import jax.numpy as jnp
import numpy as np
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import lang
from triton_distributed_tpu.utils import assert_allclose

#: tier-1 fast subset (ci/fast.sh): the minimal lang-layer slices
pytestmark = pytest.mark.fast


def test_ring_put(mesh8):
    """Each device puts its shard to its right neighbor (ring shift)."""

    def kernel(x_ref, out_ref, send_sem, recv_sem):
        me = lang.my_pe("x")
        n = lang.n_pes("x")
        dst = jax.lax.rem(me + 1, n)
        h = lang.putmem_nbi_block(out_ref, x_ref, send_sem, recv_sem, dst)
        lang.quiet(h)
        h.wait_recv()

    call = lang.shmem_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        in_specs=lang.vmem_specs(1),
        scratch_shapes=[pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(())],
    )
    f = lang.on_mesh(mesh8, in_specs=P("x"), out_specs=P("x"))(call)
    x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
    y = f(x)
    assert_allclose(y, jnp.roll(x, 8, axis=0))


def test_notify_wait_producer_consumer(mesh8):
    """Tutorial-01 equivalent: producer writes data into consumer's buffer
    then signals; the consumer spins on the signal before reading."""

    def kernel(x_ref, out_ref, scratch_ref, send_sem, recv_sem, flag):
        me = lang.my_pe("x")
        n = lang.n_pes("x")
        dst = jax.lax.rem(me + 1, n)
        # producer role: put payload into peer's scratch, then notify peer.
        h = lang.putmem_signal_nbi_block(scratch_ref, x_ref, send_sem, recv_sem, dst)
        lang.quiet(h)
        lang.signal_op(flag, 1, pe=dst)
        # consumer role: wait for notify, then for the payload, then consume.
        lang.signal_wait_until(flag, 1)
        h.wait_recv()
        out_ref[:] = scratch_ref[:] * 2.0

    call = lang.shmem_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        in_specs=lang.vmem_specs(1),
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.REGULAR,
        ],
    )
    f = lang.on_mesh(mesh8, in_specs=P("x"), out_specs=P("x"))(call)
    x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
    y = f(x)
    assert_allclose(y, jnp.roll(x, 8, axis=0) * 2.0)


def test_barrier_all(mesh8):
    """barrier_all: all devices synchronize without deadlock, twice in a
    row (the second round catches leftover un-consumed signals)."""

    def kernel(x_ref, out_ref):
        lang.barrier_all("x")
        out_ref[:] = x_ref[:] * 2.0
        lang.barrier_all("x")

    call = lang.shmem_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        in_specs=lang.vmem_specs(1),
        collective_id=1,
    )
    f = lang.on_mesh(mesh8, in_specs=P("x"), out_specs=P("x"))(call)
    x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
    y = f(x)
    assert_allclose(y, x * 2.0)


def test_signal_wait_ping_pong(mesh8):
    """Pure semaphore ping-pong (≡ test_notify.py / test_distributed_wait.py):
    even devices signal odd neighbors, odd wait then reply."""

    def kernel(x_ref, out_ref, flag):
        me = lang.my_pe("x")
        n = lang.n_pes("x")
        partner = jax.lax.rem(me + 1, n)  # even→right, odd wraps

        is_even = jax.lax.rem(me, 2) == 0

        def even_role(_):
            lang.signal_op(flag, 1, pe=partner)
            lang.signal_wait_until(flag, 1)
            return 0

        def odd_role(_):
            lang.signal_wait_until(flag, 1)
            prev = jax.lax.rem(me + n - 1, n)
            lang.signal_op(flag, 1, pe=prev)
            return 0

        jax.lax.cond(is_even, even_role, odd_role, 0)
        out_ref[:] = x_ref[:] + 1.0

    call = lang.shmem_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        in_specs=lang.vmem_specs(1),
        scratch_shapes=[pltpu.SemaphoreType.REGULAR],
    )
    f = lang.on_mesh(mesh8, in_specs=P("x"), out_specs=P("x"))(call)
    x = jnp.zeros((64, 128), jnp.float32)
    y = f(x)
    assert_allclose(y, x + 1.0)
