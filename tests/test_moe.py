"""MoE kernel tests: routing/alignment, grouped GEMM, EP AllToAll.

Mirrors test_all_to_all.py / test_ep_a2a.py / test_ag_moe.py
(python/triton_dist/test/nvidia/), with jax.lax collectives and dense
einsums playing the role of the torch/NCCL baselines (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels import group_gemm as gg
from triton_distributed_tpu.kernels import moe_all_to_all as ma
from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.utils import assert_allclose


def _routing(m, e, topk, seed=0):
    logits = jax.random.normal(jax.random.PRNGKey(seed), (m, e))
    return mu.select_experts(logits, topk)


class TestRouting:
    def test_select_experts_normalized(self):
        weights, ids = _routing(32, 8, 2)
        assert weights.shape == (32, 2) and ids.shape == (32, 2)
        np.testing.assert_allclose(np.asarray(weights.sum(-1)), 1.0, rtol=1e-5)

    def test_align_block_size_invariants(self):
        m, e, topk, bm = 64, 8, 2, 16
        _, ids = _routing(m, e, topk)
        sti, be, splits = mu.moe_align_block_size(ids, e, bm)
        sti, be, splits = map(np.asarray, (sti, be, splits))
        total = m * topk
        assert splits.sum() == total
        # every non-sentinel source index appears exactly once
        real = sti[sti < total]
        assert sorted(real.tolist()) == list(range(total))
        # each block's non-sentinel entries all route to the block's expert
        flat_ids = np.asarray(ids).reshape(-1)
        for b, exp in enumerate(be):
            blk = sti[b * bm : (b + 1) * bm]
            for s in blk[blk < total]:
                assert flat_ids[s] == exp

    def test_gather_scatter_roundtrip_identity_experts(self):
        """gather → (identity expert) → weighted scatter == input when
        weights sum to 1."""
        m, e, topk, bm, h = 32, 4, 2, 8, 128
        weights, ids = _routing(m, e, topk)
        x = jax.random.normal(jax.random.PRNGKey(1), (m, h))
        sti, _, _ = mu.moe_align_block_size(ids, e, bm)
        xs = mu.gather_sorted(x, sti, topk)
        out = mu.scatter_combine(xs, sti, weights, m)
        assert_allclose(out, x, atol=1e-5, rtol=1e-5)


class TestGroupedGemm:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_vs_ragged_dot(self, dtype):
        m, k, n, e, topk, bm = 64, 128, 256, 8, 2, 16
        _, ids = _routing(m, e, topk)
        sti, be, splits = mu.moe_align_block_size(ids, e, bm)
        cap = sti.shape[0]
        x = jax.random.normal(jax.random.PRNGKey(1), (m, k), dtype)
        w = jax.random.normal(jax.random.PRNGKey(2), (e, k, n), dtype) * 0.05
        xs = mu.gather_sorted(x, sti, topk)
        y = gg.grouped_matmul(xs, w, be, block_m=bm)
        y_ref = gg.grouped_matmul_xla(xs, w, gg.padded_splits(splits, bm, cap))
        tol = 1e-5 if dtype == jnp.float32 else 3e-2
        assert_allclose(y, y_ref, atol=tol, rtol=tol)

    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_weight_quantized_vs_dequantized(self, mode):
        """In-kernel epilogue dequant == widen-then-matmul: the scale is
        per out-channel, so folding it after the K reduction is exact —
        the two paths must agree to accumulation noise."""
        m, k, n, e, topk, bm = 64, 128, 256, 8, 2, 16
        _, ids = _routing(m, e, topk)
        sti, be, _ = mu.moe_align_block_size(ids, e, bm)
        x = jax.random.normal(jax.random.PRNGKey(1), (m, k), jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(2), (e, k, n)) * 0.05
        q, scale = gg.quantize_grouped_weights(w, mode)
        assert q.dtype.itemsize == 1 and scale.shape == (e, n)
        xs = mu.gather_sorted(x, sti, topk)
        y = gg.grouped_matmul(xs, q, be, w_scale=scale, block_m=bm)
        y_ref = gg.grouped_matmul(
            xs, gg.dequantize_grouped_weights(q, scale), be, block_m=bm
        )
        assert_allclose(y, y_ref, atol=3e-2, rtol=3e-2)

    def test_w8a8_vs_widened_exact_scales(self):
        """The s8×s8 path's rank-1 epilogue (x_scale[m]·w_scale[e, n])
        equals the widened f32 product of the SAME quantized operands
        (both scales are constant over the K reduction, so the fold is
        exact up to the out-dtype cast)."""
        m, k, n, e, topk, bm = 64, 128, 256, 8, 2, 16
        _, ids = _routing(m, e, topk)
        sti, be, _ = mu.moe_align_block_size(ids, e, bm)
        x = jax.random.normal(jax.random.PRNGKey(1), (m, k), jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(2), (e, k, n)) * 0.05
        wq, ws = gg.quantize_grouped_weights(w, "int8")
        xs = mu.gather_sorted(x, sti, topk)
        xq, xsc = gg.quantize_act_rows(xs)
        y = gg.grouped_matmul(
            xq, wq, be, w_scale=ws, x_scale=xsc, block_m=bm,
            out_dtype=jnp.float32,
        )
        xw = xq.astype(jnp.float32) * xsc
        y_ref = gg.grouped_matmul(
            xw, gg.dequantize_grouped_weights(wq, ws, jnp.float32), be,
            block_m=bm,
        )
        assert_allclose(y, y_ref, atol=1e-4, rtol=1e-4)

    def test_w8a8_error_vs_full_precision_bounded(self):
        """W8A8 (per-row act + per-channel weight int8) stays within
        serving tolerance of the full-precision product."""
        m, k, n, e, topk, bm = 64, 128, 128, 4, 2, 16
        _, ids = _routing(m, e, topk)
        sti, be, _ = mu.moe_align_block_size(ids, e, bm)
        x = jax.random.normal(jax.random.PRNGKey(1), (m, k), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(2), (e, k, n)) * 0.05
        wq, ws = gg.quantize_grouped_weights(w, "int8")
        xs = mu.gather_sorted(x, sti, topk)
        xq, xsc = gg.quantize_act_rows(xs)
        y = gg.grouped_matmul(
            xq, wq, be, w_scale=ws, x_scale=xsc, block_m=bm,
            out_dtype=jnp.float32,
        )
        y_full = gg.grouped_matmul(xs, w.astype(jnp.float32), be, block_m=bm)
        err = jnp.abs(y - y_full).max() / (jnp.abs(y_full).max() + 1e-9)
        assert float(err) < 0.03, float(err)

    def test_weight_quant_error_bounded(self):
        """int8 per-channel weight quant stays close to the full-
        precision product (the serving-accuracy contract)."""
        m, k, n, e, topk, bm = 64, 128, 128, 4, 2, 16
        _, ids = _routing(m, e, topk)
        sti, be, _ = mu.moe_align_block_size(ids, e, bm)
        x = jax.random.normal(jax.random.PRNGKey(1), (m, k), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(2), (e, k, n)) * 0.05
        q, scale = gg.quantize_grouped_weights(w, "int8")
        xs = mu.gather_sorted(x, sti, topk)
        y = gg.grouped_matmul(xs.astype(jnp.float32), q, be,
                              w_scale=scale, block_m=bm)
        y_full = gg.grouped_matmul(xs.astype(jnp.float32), w, be, block_m=bm)
        # per-channel int8: ~0.5% relative error on a K=128 reduction
        err = jnp.abs(y - y_full).max() / (jnp.abs(y_full).max() + 1e-9)
        assert float(err) < 0.02, float(err)

    def test_full_local_moe_vs_dense(self):
        """sorted grouped-GEMM MoE == dense per-expert einsum reference."""
        m, k, n, e, topk, bm = 32, 128, 128, 4, 2, 8
        weights, ids = _routing(m, e, topk)
        x = jax.random.normal(jax.random.PRNGKey(1), (m, k))
        w = jax.random.normal(jax.random.PRNGKey(2), (e, k, n)) * 0.05
        sti, be, _ = mu.moe_align_block_size(ids, e, bm)
        xs = mu.gather_sorted(x, sti, topk)
        y = gg.grouped_matmul(xs, w, be, block_m=bm)
        out = mu.scatter_combine(y, sti, weights, m)

        ref = jnp.zeros((m, n))
        for t in range(topk):
            ref += weights[:, t : t + 1] * jnp.einsum(
                "mk,mkn->mn", x, w[ids[:, t]]
            )
        assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


class TestMoEAllToAll:
    def _setup(self, mesh, n=8, epr=4, H=128, max_m=32, M=24, seed=0):
        E = n * epr
        ctx = ma.create_all_to_all_context(
            mesh, "x", max_m=max_m, hidden=H,
            experts_per_rank=epr, dtype=jnp.float32,
        )
        rng = np.random.default_rng(seed)
        assign = np.sort(rng.integers(0, E, size=(n, M)), axis=1)
        splits = np.stack(
            [np.bincount(assign[d], minlength=E) for d in range(n)]
        ).astype(np.int32)
        toks = rng.standard_normal((n, M, H)).astype(np.float32)
        sh = NamedSharding(mesh, P("x"))
        toks_g = jax.device_put(jnp.asarray(toks).reshape(n * M, H), sh)
        spl_g = jax.device_put(jnp.asarray(splits).reshape(n * E), sh)
        return ctx, toks, splits, toks_g, spl_g

    def _shard(self, mesh, fn, n_in, n_out):
        return jax.jit(
            jax.shard_map(
                fn, mesh=mesh,
                in_specs=tuple([P("x")] * n_in) if n_in > 1 else P("x"),
                out_specs=tuple([P("x")] * n_out) if n_out > 1 else P("x"),
                check_vma=False,
            )
        )

    @staticmethod
    def _stage_packed(ctx):
        return lambda t, s: ma.pack_slots(ctx, *ma.dispatch_stage(ctx, t, s))

    def test_transport_matches_xla(self, mesh8):
        ctx, _, _, toks_g, spl_g = self._setup(mesh8)
        stage = self._shard(mesh8, self._stage_packed(ctx), 2, 1)
        send = stage(toks_g, spl_g)
        recv = ma.fast_all_to_all(ctx, send)
        recv_ref = ma.fast_all_to_all(ctx, send, use_xla=True)
        np.testing.assert_array_equal(np.asarray(recv), np.asarray(recv_ref))

    def test_recv_splits(self, mesh8):
        n, epr = 8, 4
        ctx, _, splits, toks_g, spl_g = self._setup(mesh8, n=n, epr=epr)
        stage = self._shard(mesh8, self._stage_packed(ctx), 2, 1)
        view = self._shard(
            mesh8, lambda r: ma.recv_tokens_view(ctx, r)[1], 1, 1
        )
        rs = np.asarray(view(ma.fast_all_to_all(ctx, stage(toks_g, spl_g))))
        rs = rs.reshape(n, n, epr)
        for d in range(n):
            for s in range(n):
                np.testing.assert_array_equal(
                    rs[d, s], splits[s, d * epr : (d + 1) * epr]
                )

    def test_dispatch_combine_roundtrip(self, mesh8):
        n, M, H = 8, 24, 128
        ctx, toks, _, toks_g, spl_g = self._setup(mesh8, n=n, M=M, H=H)
        stage = self._shard(mesh8, self._stage_packed(ctx), 2, 1)
        comb_in = self._shard(
            mesh8,
            lambda r: ma.combine_stage(ctx, ma.recv_tokens_view(ctx, r)[0]),
            1, 1,
        )
        unstage = self._shard(
            mesh8,
            lambda c, s: ma.combine_unstage(
                ctx, ma.combine_unpack(ctx, c), s, M
            ),
            2, 1,
        )
        recv = ma.fast_all_to_all(ctx, stage(toks_g, spl_g))
        comb = ma.fast_all_to_all(ctx, comb_in(recv))
        back = np.asarray(unstage(comb, spl_g)).reshape(n, M, H)
        np.testing.assert_allclose(back, toks, rtol=1e-6)

    def test_overflow_truncates_to_zero_not_garbage(self, mesh8):
        """A peer total above max_m must come back as ZERO rows (dropped),
        never as duplicated slot data, and receiver splits must be
        clamped to what actually arrived."""
        n, epr, H, max_m, M = 8, 4, 128, 4, 24   # peers can get > 4 tokens
        ctx, toks, splits, toks_g, spl_g = self._setup(
            mesh8, n=n, epr=epr, H=H, max_m=max_m, M=M
        )
        stage = self._shard(mesh8, self._stage_packed(ctx), 2, 1)
        view = self._shard(
            mesh8, lambda r: ma.recv_tokens_view(ctx, r)[1], 1, 1
        )
        comb_in = self._shard(
            mesh8,
            lambda r: ma.combine_stage(ctx, ma.recv_tokens_view(ctx, r)[0]),
            1, 1,
        )
        unstage = self._shard(
            mesh8,
            lambda c, s: ma.combine_unstage(
                ctx, ma.combine_unpack(ctx, c), s, M
            ),
            2, 1,
        )
        recv = ma.fast_all_to_all(ctx, stage(toks_g, spl_g))
        rs = np.asarray(view(recv)).reshape(n, n, epr)
        # receiver splits never claim more than max_m per source
        assert rs.sum(axis=2).max() <= max_m
        comb = ma.fast_all_to_all(ctx, comb_in(recv))
        back = np.asarray(unstage(comb, spl_g)).reshape(n, M, H)
        counts = splits.reshape(n, n, epr).sum(axis=2)   # (dev, peer)
        offs = np.concatenate(
            [np.zeros((n, 1), np.int64), np.cumsum(counts, axis=1)[:, :-1]],
            axis=1,
        )
        for d in range(n):
            for t in range(M):
                j = np.searchsorted(np.cumsum(counts[d]), t, side="right")
                pos = t - offs[d, j]
                if pos < max_m:
                    np.testing.assert_allclose(back[d, t], toks[d, t], rtol=1e-6)
                else:
                    np.testing.assert_array_equal(back[d, t], 0.0)
