"""Continuous-batching serving engine: scheduler + serving-step tests.

The ISSUE-6 satellite suite: deterministic seeded Poisson traces,
admission blocking at pool exhaustion, eviction + re-admission resuming
from the exact cursor, chunked-prefill/decode interleave invariants —
and the end-to-end pin: every request served by the engine (under
contention, chunking and eviction) produces EXACTLY the tokens the
uncontended prefill+generate reference produces.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.serving import (
    EngineConfig,
    Request,
    ServingEngine,
    ServingState,
    poisson_trace,
)

pytestmark = pytest.mark.fast

CFG = dict(
    vocab=128, n_layers=2, hidden=64, ffn=128,
    n_heads=4, n_kv_heads=2, head_dim=16,
    dtype=jnp.float32, param_dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def mesh1():
    return Mesh(np.asarray(jax.devices()[:1]), ("tp",))


@pytest.fixture(scope="module")
def model_params(mesh1):
    model = Transformer(TransformerConfig(**CFG), mesh1, "tp", ())
    return model, model.init(jax.random.PRNGKey(0))


def _reference_tokens(model, params, req, cap=128):
    """Uncontended prefill + greedy generate for one request."""
    prompt = jnp.asarray(req.prompt)[None]
    caches = model.init_cache(1, cap)
    last, caches, lens = model.prefill(params, caches, prompt)
    tok = jnp.argmax(last, -1).astype(jnp.int32)
    out = [int(tok[0])]
    if req.max_new > 1:
        more, *_ = model.generate(params, caches, lens, tok,
                                  req.max_new - 1)
        out += [int(x) for x in np.asarray(more)[0]]
    return out


class TestServingEngine:
    def test_trace_is_deterministic(self, model_params):
        model, params = model_params
        outs = []
        for _ in range(2):
            eng = ServingEngine(
                model, params,
                EngineConfig(slots=4, token_budget=48, chunk=16,
                             page=8, npages=24),
            )
            trace = poisson_trace(9, 6, 1.0, 4, 24, 2, 5, 128)
            eng.run(trace, max_steps=300)
            outs.append([tuple(r.generated) for r in trace])
        assert outs[0] == outs[1]

    def test_matches_reference_under_contention(self, model_params):
        """Chunked prefill interleaved with other requests' decode —
        every request's tokens equal the uncontended reference."""
        model, params = model_params
        eng = ServingEngine(
            model, params,
            EngineConfig(slots=4, token_budget=48, chunk=16, page=8,
                         npages=32),
        )
        trace = poisson_trace(7, 6, 1.0, 5, 30, 3, 6, 128)
        stats = eng.run(trace, max_steps=400)
        assert stats.completed == 6
        for req in trace:
            assert req.generated == _reference_tokens(model, params, req), (
                req.rid
            )

    def test_admission_blocks_at_pool_exhaustion(self, model_params):
        """With pages for ~2 requests, a burst of 6 arrivals at t=0
        must NOT all be admitted at once — the queue drains as
        completions free pages, and everyone still completes."""
        model, params = model_params
        eng = ServingEngine(
            model, params,
            EngineConfig(slots=6, token_budget=64, chunk=16, page=8,
                         npages=6),                  # ~2 × 24-token seqs
        )
        trace = [
            Request(rid=i, prompt=(np.arange(16) + i).astype(np.int32)
                    % 128, max_new=3, arrival=0.0)
            for i in range(6)
        ]
        eng.submit_trace(trace)
        eng._admit()
        admitted0 = sum(r is not None for r in eng.slot_req)
        assert admitted0 <= 3                        # pool-gated, not slot-gated
        assert len(eng.waiting) == 6 - admitted0
        stats = eng.run(max_steps=400)
        assert stats.completed == 6

    def test_eviction_resumes_from_exact_cursor(self, model_params):
        """Force mid-decode eviction (pool far smaller than the load):
        the evicted request re-prefills prompt+generated and completes
        with EXACTLY the uncontended reference tokens."""
        model, params = model_params
        eng = ServingEngine(
            model, params,
            EngineConfig(slots=4, token_budget=48, chunk=16, page=8,
                         npages=12),
        )
        trace = poisson_trace(7, 8, 1.0, 5, 30, 3, 6, 128)
        stats = eng.run(trace, max_steps=600)
        assert stats.completed == 8
        assert stats.evictions > 0, "config failed to force an eviction"
        evicted = [r for r in trace if r.evictions]
        assert evicted
        for req in evicted:
            assert req.generated == _reference_tokens(model, params, req), (
                f"evicted rid {req.rid} diverged after re-admission"
            )

    def test_interleave_invariants(self, model_params):
        """Per-step accounting: packed tokens within budget, prefill
        rows advance by at most `chunk`, decode rows by exactly 1, and
        at least one step genuinely mixes prefill and decode rows."""
        model, params = model_params
        cfg = EngineConfig(slots=4, token_budget=48, chunk=8, page=8,
                           npages=32)
        eng = ServingEngine(model, params, cfg)
        # request 0 decodes from step ~2 while 1 and 2 still prefill
        trace = [
            Request(rid=0, prompt=np.arange(6, dtype=np.int32),
                    max_new=8, arrival=0.0),
            Request(rid=1, prompt=np.arange(30, dtype=np.int32) % 128,
                    max_new=2, arrival=1.0),
            Request(rid=2, prompt=np.arange(28, dtype=np.int32) % 128,
                    max_new=2, arrival=1.0),
        ]
        eng.submit_trace(trace)
        mixed_steps = 0
        cursors = {r.rid: 0 for r in trace}
        while not eng.idle and eng.step_count < 200:
            before = {
                r.rid: r.cursor for r in trace
            }
            rep = eng.step()
            assert rep["tokens"] <= cfg.token_budget
            decode_rows = prefill_rows = 0
            for r in trace:
                adv = r.cursor - before[r.rid]
                assert 0 <= adv <= cfg.chunk
                if adv == 1 and before[r.rid] >= len(r.prompt):
                    decode_rows += 1
                elif adv > 0 and before[r.rid] < len(r.prompt):
                    prefill_rows += 1
                    # prefill advances by the full chunk unless the
                    # prompt tail or budget ends it
                    assert adv == min(
                        cfg.chunk,
                        len(r.prompt) + len(r.generated) - before[r.rid],
                    ) or adv > 0
            if decode_rows and prefill_rows:
                mixed_steps += 1
            cursors.update({r.rid: r.cursor for r in trace})
        assert mixed_steps > 0, "trace never exercised a mixed batch"
        assert all(r.done for r in trace)

    def test_degrades_to_xla_twin_on_kernel_failure(self, model_params,
                                                    monkeypatch):
        """First Pallas failure flips the engine onto the XLA twin and
        the batch re-runs — results identical to a pallas-free run."""
        import triton_distributed_tpu.kernels.ragged_paged_attention as rpa

        model, params = model_params
        real = rpa.ragged_paged_attention

        calls = {"n": 0}

        def boom(*a, **k):
            calls["n"] += 1
            raise RuntimeError("injected kernel failure")

        monkeypatch.setattr(rpa, "ragged_paged_attention", boom)
        eng = ServingEngine(
            model, params,
            EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                         npages=16),
        )
        req = Request(rid=0, prompt=np.arange(9, dtype=np.int32),
                      max_new=3, arrival=0.0)
        stats = eng.run([req], max_steps=50)
        monkeypatch.setattr(rpa, "ragged_paged_attention", real)
        assert stats.degraded and calls["n"] >= 1
        assert eng.use_pallas is False
        assert req.generated == _reference_tokens(model, params, req)

    def test_serving_state_is_a_donatable_pytree(self, model_params):
        model, _ = model_params
        state = model.init_serving_state(slots=2, npages=8, page=8)
        assert isinstance(state, ServingState)
        leaves, tree = jax.tree.flatten(state)
        rebuilt = jax.tree.unflatten(tree, leaves)
        assert rebuilt.page == state.page
        assert rebuilt.slots == 2 and rebuilt.npages == 8
        assert state.capacity == state.pages_per_seq * 8

    def test_serving_rejects_unshardable_heads(self, mesh1):
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs the 8-device test mesh")
        mesh8 = Mesh(np.asarray(devs), ("tp",))
        model = Transformer(
            TransformerConfig(**{**CFG, "n_kv_heads": 2, "n_heads": 4}),
            mesh8, "tp", (),
        )
        with pytest.raises(ValueError, match="KV heads"):
            model.init_serving_state(slots=2, npages=8, page=8)


class TestPrefixCache:
    """The PR-6 follow-on: per-page refcounts + chain-hash page reuse
    (serving/state.PagePool) — shared prefixes and re-admitted evicted
    requests reattach resident pages instead of recomputing, pinned
    token-exact."""

    def test_shared_prefix_reuses_pages_token_exact(self, model_params):
        model, params = model_params
        shared = (np.arange(24, dtype=np.int32) * 3) % 128
        r1 = Request(rid=0, prompt=shared.copy(), max_new=3, arrival=0.0)
        r2 = Request(
            rid=1,
            prompt=np.concatenate([shared, np.asarray([9, 4], np.int32)]),
            max_new=3, arrival=6.0,       # admitted after r1's pages froze
        )
        eng = ServingEngine(
            model, params,
            EngineConfig(slots=4, token_budget=48, chunk=8, page=8,
                         npages=32, prefix_cache=True),
        )
        stats = eng.run([r1, r2], max_steps=300)
        assert stats.completed == 2
        assert stats.prefix_hits > 0, "shared prefix never reattached"
        for r in (r1, r2):
            assert r.generated == _reference_tokens(model, params, r), r.rid

    def test_evicted_request_reattaches_resident_pages(self, model_params):
        """Eviction decrements refcounts instead of freeing; the
        re-admitted request's recompute prefix reattaches the cached
        pages and still produces the exact reference tokens."""
        model, params = model_params
        eng = ServingEngine(
            model, params,
            EngineConfig(slots=4, token_budget=48, chunk=16, page=8,
                         npages=12, prefix_cache=True),
        )
        trace = poisson_trace(7, 8, 1.0, 5, 30, 3, 6, 128)
        stats = eng.run(trace, max_steps=600)
        assert stats.completed == 8
        assert stats.evictions > 0, "config failed to force an eviction"
        assert stats.prefix_hits > 0, "re-admission never reused a page"
        for req in trace:
            assert req.generated == _reference_tokens(model, params, req), (
                req.rid
            )

    def test_refcounted_release_keeps_shared_pages(self):
        from triton_distributed_tpu.serving.state import PagePool

        pool = PagePool(4, 8, prefix_cache=True)
        pg = pool.alloc()
        pool.register(pg, 1234)
        pool.retain(pg)                    # second holder
        pool.release(pg)                   # first lets go — still held
        assert pool.refs[pg] == 1
        assert pool.lookup(1234) == pg
        pool.release(pg)                   # last holder: parks in cache
        assert pool.refs[pg] == 0
        assert pool.lookup(1234) == pg     # resident, reattachable
        assert pool.available == 4         # and reclaimable under pressure
        # reclaim under pressure unregisters it
        got = {pool.alloc() for _ in range(4)}
        assert len(got) == 4
        assert pool.lookup(1234) is None
        assert pool.alloc() is None

    def test_prefix_cache_off_by_default(self, model_params):
        model, params = model_params
        eng = ServingEngine(
            model, params,
            EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                         npages=16),
        )
        assert eng.pool.prefix_cache is False


class TestSampling:
    """Engine-side temperature/top-k over the per-slot logits: draws
    are (seed, rid, n_generated)-keyed, so token streams are invariant
    to scheduling (chunking, contention, eviction replays)."""

    def test_greedy_default_unchanged(self, model_params):
        model, params = model_params
        req = Request(rid=0, prompt=np.arange(9, dtype=np.int32),
                      max_new=3, arrival=0.0)
        ServingEngine(
            model, params,
            EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                         npages=16),
        ).run([req], max_steps=50)
        assert req.generated == _reference_tokens(model, params, req)

    def test_sampled_stream_invariant_to_chunking(self, model_params):
        model, params = model_params
        outs = []
        for chunk in (4, 16):
            req = Request(rid=0, prompt=np.arange(12, dtype=np.int32),
                          max_new=6, arrival=0.0)
            ServingEngine(
                model, params,
                EngineConfig(slots=2, token_budget=32, chunk=chunk,
                             page=8, npages=16, temperature=0.8,
                             top_k=16, seed=3),
            ).run([req], max_steps=80)
            outs.append(req.generated)
        assert outs[0] == outs[1]
        assert len(outs[0]) == 6

    def test_top_k_truncates_support(self, model_params):
        """With top_k=1 the sampler IS greedy regardless of
        temperature."""
        model, params = model_params
        req_g = Request(rid=0, prompt=np.arange(10, dtype=np.int32),
                        max_new=4, arrival=0.0)
        req_s = Request(rid=0, prompt=np.arange(10, dtype=np.int32),
                        max_new=4, arrival=0.0)
        base = dict(slots=2, token_budget=32, chunk=8, page=8, npages=16)
        ServingEngine(
            model, params, EngineConfig(**base),
        ).run([req_g], max_steps=60)
        ServingEngine(
            model, params,
            EngineConfig(**base, temperature=2.5, top_k=1, seed=9),
        ).run([req_s], max_steps=60)
        assert req_g.generated == req_s.generated


class TestServingStepTP:
    def test_tp2_head_sharded_matches_reference(self):
        """tp=2: pools shard over the KV-head dim; the engine's tokens
        equal the single-request reference on the same mesh."""
        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs >= 2 devices")
        mesh2 = Mesh(np.asarray(devs[:2]), ("tp",))
        cfg = TransformerConfig(
            **CFG, moe="ep", moe_layers=(1,), num_experts=4, topk=2,
        )
        model = Transformer(cfg, mesh2, "tp", ())
        params = jax.tree.map(
            lambda p, s: jax.device_put(p, s),
            model.init(jax.random.PRNGKey(0)), model.shardings(),
        )
        eng = ServingEngine(
            model, params,
            EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                         npages=16),
        )
        # prompt length divisible by tp: the SP prefill REFERENCE pins
        # (B·S) % tp == 0 (the engine itself has no such constraint —
        # its packed width is the static token budget)
        req = Request(rid=0, prompt=(np.arange(10, dtype=np.int32) * 7)
                      % 128, max_new=3, arrival=0.0)
        stats = eng.run([req], max_steps=60)
        assert stats.completed == 1
        assert req.generated == _reference_tokens(model, params, req)

    def test_int8_kv_pools_match_reference(self):
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("tp",))
        cfg = TransformerConfig(**CFG, kv_quant="int8")
        model = Transformer(cfg, mesh, "tp", ())
        params = model.init(jax.random.PRNGKey(0))
        eng = ServingEngine(
            model, params,
            EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                         npages=16),
        )
        req = Request(rid=0, prompt=np.arange(10, dtype=np.int32),
                      max_new=3, arrival=0.0)
        eng.run([req], max_steps=50)
        assert req.generated == _reference_tokens(model, params, req)
