"""ISSUE-16 multi-tenant fleet suite: priority preemption,
deadline-aware routing, and brownout load-shedding.

The tentpole under test: every :class:`Request` carries a ``tenant`` +
priority tier (interactive / batch / background), and the stack
enforces it end to end —

* **deadline routing** — the router score gains a slack term
  (``slo_ms − modeled completion``); negative slack outranks prefix
  affinity, and retry-after prices by the request's OWN tier (only
  queued work at rank ≤ r is ahead of a tier-r retry);
* **priority preemption** — a higher-tier admission with no slot/page
  headroom evicts the lowest-tier resident through the recompute-
  eviction discipline: token-exact, cursor-resumable, zero pool-page
  leaks even mid-draft, with anti-starvation aging protecting both
  admission order AND residency;
* **brownout** — the fleet overload controller escalates through
  ``BROWNOUT_LEVELS`` in strict reverse-priority order (background
  shed first, batch squeezed then shed, interactive never) with
  hysteretic recovery;
* **fair share** — per-tenant page/token shares gate admission without
  head-of-line blocking, and the per-tenant stats surface
  goodput/p99/preemptions/sheds;
* **replay determinism** — tenant floods × ReplicaDeath × preemption
  produce byte-identical ``stats.events`` under the same seed (the
  PR-13 contract extended to preempt/shed/brownout events).

All sim-free: host-side scheduling over the engines' CPU (XLA) paths.
"""

import gc

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_distributed_tpu import config
from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.runtime import faults, health, watchdog
from triton_distributed_tpu.runtime.faults import FaultPlan, ReplicaDeath
from triton_distributed_tpu.runtime.health import PeerState
from triton_distributed_tpu.serving import (
    TIERS,
    BrownoutConfig,
    BrownoutController,
    EngineConfig,
    Request,
    ServingEngine,
    ServingFleet,
    SpeculativeEngine,
    TenantConfig,
    effective_rank,
    tier_rank,
)
from triton_distributed_tpu.serving.fleet import (
    BROWNOUT_LEVELS,
    FleetRouter,
    RouterConfig,
)

#: tier-1 fast subset (ci/fast.sh): the multi-tenant robustness story
pytestmark = pytest.mark.fast


@pytest.fixture(autouse=True)
def _isolated_ledgers():
    yield
    health.set_ledger(None)
    faults.set_fault_plan(None)
    watchdog.clear_trip()
    config.set_fleet_seed(None)
    gc.collect()


CFG = dict(
    vocab=128, n_layers=2, hidden=64, ffn=128,
    n_heads=4, n_kv_heads=2, head_dim=16,
    dtype=jnp.float32, param_dtype=jnp.float32,
)

ECFG = dict(slots=4, token_budget=48, chunk=16, page=8, npages=32,
            prefix_cache=True, temperature=0.7, top_k=40, seed=11)

TEN = {
    "iact": TenantConfig(priority="interactive", slo_ms=0.05),
    "bat": TenantConfig(priority="batch"),
    "bg": TenantConfig(priority="background"),
}


@pytest.fixture(scope="module")
def fleet_models():
    """Two replica models on their own 1-device meshes, same params."""
    devs = jax.devices()
    out = []
    params = None
    for k in range(2):
        mesh = Mesh(np.asarray(devs[k:k + 1] or devs[:1]), ("tp",))
        model = Transformer(TransformerConfig(**CFG), mesh, "tp", ())
        if params is None:
            params = model.init(jax.random.PRNGKey(0))
        p = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                         model.shardings())
        out.append((model, p))
    return out


def _req(rid, arrival, tenant=None, priority=None, session=None,
         plen=20, max_new=5):
    rng = np.random.default_rng(1000 + rid)
    prompt = rng.integers(0, CFG["vocab"], (plen,)).astype(np.int32)
    r = Request(rid=rid, prompt=prompt, max_new=max_new,
                arrival=arrival)
    if tenant is not None:
        r.tenant = tenant
    if priority is not None:
        r.priority = priority
    if session is not None:
        r.session = session
    return r


def _engine(fleet_models, cls=ServingEngine, tenants=None, k=0,
            **kw):
    m, p = fleet_models[k]
    ecfg = {key: kw.pop(key, val) for key, val in ECFG.items()}
    kw.setdefault("use_pallas", False)
    return cls(m, p, EngineConfig(**ecfg), tenants=tenants, **kw)


def _fleet(fleet_models, tenants=None, brownout=None, queue_cap=None,
           seed=1, **kw):
    engines = [ServingEngine(m, p, EngineConfig(**ECFG),
                             use_pallas=False)
               for m, p in fleet_models]
    return ServingFleet(engines, seed=seed,
                        router=RouterConfig(queue_cap=queue_cap),
                        tenants=tenants, brownout=brownout, **kw)


def _mixed_trace(n_iact=4, n_bat=16, n_bg=4):
    out, rid = [], 0
    for i in range(n_iact):
        out.append(_req(rid, i * 3.0, "iact")); rid += 1
    for i in range(n_bat):
        out.append(_req(rid, 1.0 + i * 0.2, "bat")); rid += 1
    for i in range(n_bg):
        out.append(_req(rid, i * 1.5, "bg")); rid += 1
    return out


def _assert_no_leaks(owner):
    """Zero held pages once every stream completed — on a fleet, over
    the ALIVE replicas (a dead replica's pool is abandoned wholesale
    with its requeued requests, not unwound)."""
    if hasattr(owner, "replicas"):
        roles = [role for r in owner._alive() for role in r._roles]
    else:
        roles = (owner,)
    for role in roles:
        assert role.pool.held_pages == 0, (
            f"page leak: {role.pool.held_pages} pages still held")


# ------------------------------------------------------------- tiers

class TestTenantConfig:
    def test_validation(self):
        with pytest.raises(ValueError, match="unknown priority"):
            TenantConfig(priority="platinum")
        with pytest.raises(ValueError, match="page_share"):
            TenantConfig(page_share=0.0)
        with pytest.raises(ValueError, match="page_share"):
            TenantConfig(page_share=1.5)
        with pytest.raises(ValueError, match="token_budget"):
            TenantConfig(token_budget=4)

    def test_tier_rank_order(self):
        assert [tier_rank(t) for t in TIERS] == [0, 1, 2]
        # unknown/unset ranks interactive: the single-tenant default
        # must schedule exactly like the pre-tenancy engine
        assert tier_rank(None) == 0
        assert tier_rank("whatever") == 0

    def test_effective_rank_ages_toward_zero(self):
        r = _req(0, arrival=10.0, priority="background")
        assert effective_rank(r, now=10.0, aging_ticks=4) == 2
        assert effective_rank(r, now=14.0, aging_ticks=4) == 1
        assert effective_rank(r, now=18.0, aging_ticks=4) == 0
        assert effective_rank(r, now=99.0, aging_ticks=4) == 0  # floor
        # aging disabled: the static rank, forever
        assert effective_rank(r, now=99.0, aging_ticks=0) == 2


# -------------------------------------------------- deadline routing

class _StubReplica:
    def __init__(self, index, overlap=0, load=0.0, room=True):
        self.index = index
        self.peer = f"replica:{index}"
        self._overlap, self._load, self._room = overlap, load, room

    def overlap_pages(self, req):
        return self._overlap

    def load_ms(self):
        return self._load

    def can_accept(self, req):
        return self._room

    def fits_context(self, req):
        return True


class _StubLedger:
    def __init__(self, states=None):
        self._states = states or {}

    def state(self, peer):
        return self._states.get(peer, PeerState.HEALTHY)


class TestDeadlineRouting:
    def test_score_negative_slack_divides_by_deficit(self):
        router = FleetRouter(seed=0)
        r = _StubReplica(0, overlap=4, load=2.0)
        base = router.score(r, None, PeerState.HEALTHY, 2.0)
        # positive slack: no penalty
        assert router.score(r, None, PeerState.HEALTHY, 2.0,
                            slack=3.0) == pytest.approx(base)
        # negative slack: / (1 + w_slack * deficit/mean)
        assert router.score(r, None, PeerState.HEALTHY, 2.0,
                            slack=-4.0) \
            == pytest.approx(base / (1.0 + 4.0 / 2.0))

    def test_slack_ms_none_without_finite_slo(self, fleet_models):
        fleet = _fleet(fleet_models, tenants=dict(TEN))
        rep = fleet.replicas[0]
        # no tenant entry / infinite SLO -> no deadline term
        assert fleet.router.slack_ms(rep, _req(0, 0.0)) is None
        assert fleet.router.slack_ms(rep, _req(0, 0.0, "bat")) is None
        s = fleet.router.slack_ms(rep, _req(0, 0.0, "iact"))
        assert s is not None and s < TEN["iact"].slo_ms

    def test_negative_slack_outranks_prefix_affinity(self):
        """The full home holds the prefix, but queueing there is
        modeled to miss the SLO while the other replica still makes
        it: the deadline wins and the request spills."""
        router = FleetRouter(seed=0)
        router.tenants = {"t": TenantConfig(slo_ms=1.0)}
        home = _StubReplica(0, overlap=10, load=1.0, room=False)
        other = _StubReplica(1, overlap=0, load=1.0, room=True)
        router.slack_ms = lambda r, req: (
            -5.0 if r.index == 0 else 2.0)
        router.affinity["s"] = 0
        req = _req(0, 0.0, tenant="t", session="s")
        chosen, spilled = router.route(req, [home, other],
                                       _StubLedger())
        assert chosen is other and spilled
        assert router.affinity["s"] == 1

    def test_positive_slack_keeps_prefix_affinity(self):
        router = FleetRouter(seed=0)
        router.tenants = {"t": TenantConfig(slo_ms=1.0)}
        home = _StubReplica(0, overlap=10, load=1.0, room=False)
        other = _StubReplica(1, overlap=0, load=1.0, room=True)
        router.slack_ms = lambda r, req: 2.0
        router.affinity["s"] = 0
        req = _req(0, 0.0, tenant="t", session="s")
        chosen, spilled = router.route(req, [home, other],
                                       _StubLedger())
        assert chosen is home and not spilled


# ---------------------------------------------- tier-priced retry

class TestTierRetryPricing:
    def _loaded_fleet(self, fleet_models, n_queued=6):
        fleet = _fleet(fleet_models, tenants=dict(TEN), queue_cap=2)
        for k, rep in enumerate(fleet.replicas):
            for i in range(n_queued):
                rep.admit_role.waiting.append(
                    _req(100 * (k + 1) + i, 0.0, "bat"))
        return fleet

    def test_retry_prices_by_own_tier(self, fleet_models):
        """A batch queue ahead is invisible to an interactive retry:
        tier-r admission sorts ahead of every lower tier, so the
        interactive price counts zero queued-ahead while the batch
        price pays the whole flood."""
        fleet = self._loaded_fleet(fleet_models)
        routable = fleet._routable()
        iact_ms, _ = fleet._priced_retry(_req(0, 0.0, "iact"),
                                         routable)
        bat_ms, _ = fleet._priced_retry(_req(1, 0.0, "bat"), routable)
        bg_ms, _ = fleet._priced_retry(_req(2, 0.0, "bg"), routable)
        assert iact_ms < bat_ms
        assert bat_ms == pytest.approx(bg_ms)  # nothing queued below batch

    def test_retry_prices_off_lightest_routable_not_probation(
            self, fleet_models):
        """The PROBATION replica's empty queue is the lightest — but
        it is unroutable (it only takes seeded probes), so the
        retry-after MUST price off the loaded HEALTHY replica: a
        retry-after the fleet cannot honor is worse than a long one."""
        fleet = _fleet(fleet_models, tenants=dict(TEN), queue_cap=2)
        fleet.health = _StubLedger({"replica:0": PeerState.PROBATION})
        # replica 0: PROBATION, empty queue. replica 1: HEALTHY, at cap
        for i in range(4):
            fleet.replicas[1].admit_role.waiting.append(
                _req(100 + i, 0.0, "bat"))
        routable = fleet._routable()
        assert [r.index for r in routable] == [1]
        probe = _req(0, 0.0, "bat")
        want_ms, _ = fleet._priced_retry(probe, [fleet.replicas[1]])
        assert fleet._reject_overload(probe)
        assert fleet.stats.admission_rejections == 1
        assert fleet.stats.retry_after_ms[-1] == pytest.approx(want_ms)
        # the un-routable empty replica would have priced ~a bare step:
        # strictly below what the real routable queue costs
        bare_ms, _ = fleet._priced_retry(_req(9, 0.0, "bat"),
                                         [fleet.replicas[0]])
        assert want_ms > bare_ms

    def test_single_tenant_pricing_unchanged(self, fleet_models):
        """With no tenants map every request is rank 0 and the tier
        filter passes the whole queue: the price equals the pre-tier
        ``replica_load_ms`` of the lightest routable replica."""
        fleet = _fleet(fleet_models, queue_cap=2)
        for i in range(3):
            fleet.replicas[0].admit_role.waiting.append(
                _req(100 + i, 0.0))
        light = min(fleet._routable(),
                    key=lambda r: (r.queue_depth(), r.load_ms(),
                                   r.index))
        ms, _ = fleet._priced_retry(_req(0, 0.0), fleet._routable())
        assert ms == pytest.approx(light.load_ms())


# ------------------------------------------------------ preemption

class TestPreemption:
    def _solo_streams(self, fleet_models, trace_fn):
        eng = _engine(fleet_models)
        t = trace_fn()
        eng.run(t, max_steps=800)
        return {r.rid: list(r.generated) for r in t}

    def test_interactive_preempts_lowest_tier(self, fleet_models):
        eng = _engine(fleet_models, tenants=dict(TEN))
        bgs = [_req(i, 0.0, "bg", max_new=8) for i in range(4)]
        for r in bgs:
            eng.submit(r)
        for _ in range(2):
            eng.step()
        assert all(r.slot is not None for r in bgs)
        hi = _req(10, 2.0, "iact", max_new=4)
        eng.submit(hi)
        eng.step()
        assert eng.stats.preemptions == 1
        assert eng.stats.tenant_preemptions == {"bg": 1}
        assert hi.slot is not None
        victim = next(r for r in bgs if r.slot is None and not r.done)
        assert victim.cursor == 0 and victim.evictions == 1
        # run out: everyone completes, no pages leak
        for _ in range(200):
            if eng.idle:
                break
            eng.step()
        assert all(r.done for r in bgs + [hi])
        _assert_no_leaks(eng)

    def test_equal_rank_victim_is_fewest_committed_pages(
            self, fleet_models):
        """ISSUE-17 fleet satellite (ROADMAP #2 follow-on): preemption-
        aware victim COST. At equal effective rank the resident with
        the FEWEST committed pages is evicted — eviction is recompute-
        priced, so the cheapest re-prefill goes first. The short-prompt
        row sits in slot 0 on purpose: the pre-cost tie-break (latest
        arrival, then highest slot) would have picked a long row and
        thrown away 3x the materialized KV."""
        eng = _engine(fleet_models, tenants=dict(TEN))
        short = _req(0, 0.0, "bg", plen=8, max_new=8)
        longs = [_req(i, 0.0, "bg", plen=24, max_new=8)
                 for i in range(1, 4)]
        for r in [short] + longs:
            eng.submit(r)
        for _ in range(3):
            eng.step()
        assert all(r.slot is not None for r in [short] + longs)
        pages = {r.rid: int((eng.table[r.slot] >= 0).sum())
                 for r in [short] + longs}
        assert pages[0] == min(pages.values())
        assert pages[0] < min(pages[r.rid] for r in longs)
        hi = _req(10, 2.0, "iact", max_new=4)
        eng.submit(hi)
        eng.step()
        assert eng.stats.preemptions == 1
        assert short.slot is None and short.cursor == 0
        assert all(r.slot is not None for r in longs)
        for _ in range(300):
            if eng.idle:
                break
            eng.step()
        assert all(r.done for r in [short, hi] + longs)
        _assert_no_leaks(eng)

    def test_preemption_token_exact(self, fleet_models):
        """Preempted streams are byte-identical to an unpreempted
        single-tenant run: sampling is keyed (seed, rid, n_generated),
        so the recompute-eviction resume cannot perturb a token."""
        def trace():
            out = [_req(i, 0.0, max_new=8) for i in range(4)]
            out.append(_req(10, 2.0, max_new=4))
            return out

        want = self._solo_streams(fleet_models, trace)
        eng = _engine(fleet_models, tenants=dict(TEN))
        t = [_req(i, 0.0, "bg", max_new=8) for i in range(4)]
        t.append(_req(10, 2.0, "iact", max_new=4))
        eng.run(t, max_steps=800)
        assert eng.stats.preemptions >= 1
        assert {r.rid: list(r.generated) for r in t} == want
        _assert_no_leaks(eng)

    def test_single_tenant_never_preempts(self, fleet_models):
        eng = _engine(fleet_models)
        t = [_req(i, 0.0, max_new=8) for i in range(4)]
        t.append(_req(10, 2.0, max_new=4))
        eng.run(t, max_steps=800)
        assert eng.stats.preemptions == 0

    def test_preempt_mid_draft_rolls_back_pages(self, fleet_models):
        """SpeculativeEngine: preemption lands while drafts are in
        flight — the victim's speculative pages roll back with the
        eviction, streams stay byte-identical to the PLAIN engine's
        (the rejection-sampling identity survives preemption), and the
        pool ends with zero held pages."""
        def trace():
            out = [_req(i, 0.0, max_new=8) for i in range(4)]
            out.append(_req(10, 3.0, max_new=4))
            return out

        want = self._solo_streams(fleet_models, trace)
        eng = _engine(fleet_models, cls=SpeculativeEngine,
                      tenants=dict(TEN), spec_k=4)
        t = [_req(i, 0.0, "bg", max_new=8) for i in range(4)]
        t.append(_req(10, 3.0, "iact", max_new=4))
        eng.run(t, max_steps=800)
        assert eng.stats.preemptions >= 1
        assert eng.stats.spec_rows > 0
        assert {r.rid: list(r.generated) for r in t} == want
        _assert_no_leaks(eng)

    def test_aging_prevents_background_starvation(self, fleet_models):
        """Sustained interactive flood vs one background request on a
        tiny engine. Without aging the background row is preempted or
        outsorted forever; with aging its effective rank reaches 0,
        where it can neither be outsorted NOR preempted — it completes
        while the flood is still arriving."""
        def run(aging_ticks):
            eng = _engine(fleet_models, tenants=dict(TEN), slots=2,
                          aging_ticks=aging_ticks)
            bg = _req(999, 0.0, "bg", max_new=4)
            eng.submit(bg)
            flood = [_req(i, i * 0.5, "iact", max_new=3)
                     for i in range(40)]
            for r in flood:
                eng.submit(r)
            done_at = None
            for s in range(120):
                eng.step()
                if bg.done and done_at is None:
                    done_at = s
            return bg, done_at, flood

        bg, done_at, flood = run(aging_ticks=4)
        last_arrival = max(r.arrival for r in flood)
        assert bg.done and done_at is not None
        assert done_at < last_arrival, (
            f"aged background finished at step {done_at}, after the "
            f"flood ended ({last_arrival}) — aging did not help")
        bg0, done0, _ = run(aging_ticks=0)
        assert done0 is None or done0 > done_at, (
            "disabling aging should starve the background request "
            "for longer")


# ------------------------------------------------------ fair share

class TestFairShare:
    def test_page_share_defers_without_blocking(self, fleet_models):
        tenants = {
            "bat": TenantConfig(priority="batch", page_share=0.25),
            "iact": TenantConfig(priority="interactive"),
        }
        eng = _engine(fleet_models, tenants=tenants)
        # two early batch residents fill the tenant's 8-page share
        # (24+12 tokens -> 4 pages each); the late pair must defer
        # until the early pair completes, while the late interactive
        # request sails through the free slots untouched
        t = [_req(i, 0.0, "bat", plen=24, max_new=12)
             for i in range(2)]
        t += [_req(2 + i, 4.0, "bat", plen=24, max_new=4)
              for i in range(2)]
        t.append(_req(10, 4.0, "iact", plen=24, max_new=4))
        eng.run(t, max_steps=800)
        assert eng.stats.fair_share_deferrals.get("bat", 0) > 0
        # deferred, not starved or lost — and no head-of-line block
        assert all(r.done for r in t)
        _assert_no_leaks(eng)

    def test_token_budget_caps_packed_rows(self, fleet_models):
        tenants = {"bat": TenantConfig(priority="batch",
                                       token_budget=16)}
        eng = _engine(fleet_models, tenants=tenants)
        t = [_req(i, 0.0, "bat", plen=24, max_new=4)
             for i in range(3)]
        eng.run(t, max_steps=800)
        assert eng.stats.fair_share_deferrals.get("bat", 0) > 0
        assert all(r.done for r in t)
        _assert_no_leaks(eng)


# -------------------------------------------------------- brownout

class TestBrownout:
    def test_level_ladder_sheds_reverse_priority(self):
        c = BrownoutController(BrownoutConfig(slo_ms=1.0))
        for level, (bg, bat) in enumerate(
                [(False, False), (True, False), (True, False),
                 (True, True)]):
            c.level = level
            assert c.sheds(tier_rank("background")) is bg
            assert c.sheds(tier_rank("batch")) is bat
            assert c.sheds(tier_rank("interactive")) is False
        c.level = 2
        assert c.squeezed == frozenset({"batch"})
        c.level = 1
        assert c.squeezed == frozenset()

    def test_hysteresis_window_and_cooldown(self, fleet_models):
        fleet = _fleet(fleet_models, tenants=dict(TEN),
                       brownout=BrownoutConfig(slo_ms=1.0, window=2,
                                               cooldown=3))
        c = fleet.brownout
        script = iter([True, True,            # escalate after 2
                       True,                  # 1 pressured (no move)
                       False, False, False,   # de-escalate after 3
                       False])
        c.pressure = lambda _fleet: next(script)
        c.observe(fleet)
        assert c.level == 0
        c.observe(fleet)
        assert c.level == 1                   # window hit
        c.observe(fleet)
        assert c.level == 1                   # needs window again
        for _ in range(3):
            c.observe(fleet)
        assert c.level == 0                   # cooldown hit
        trans = [e for e in fleet.stats.events if e[0] == "brownout"]
        assert [e[3] for e in trans] == [
            "normal->shed_background", "shed_background->normal"]

    def test_flood_sheds_in_strict_order_and_recovers(
            self, fleet_models):
        """A batch+background flood under a tight modeled SLO: the
        controller escalates, sheds land ONLY on background/batch with
        every background shed preceding the first batch shed, the
        squeeze clears on recovery, and zero requests are lost."""
        fleet = _fleet(fleet_models, tenants=dict(TEN), queue_cap=3,
                       brownout=BrownoutConfig(slo_ms=0.004, window=2,
                                               cooldown=3))
        st = fleet.run(_mixed_trace(n_bat=24, n_bg=6), max_ticks=800)
        assert st.lost_requests == 0
        shed_events = [e for e in st.events if e[0] == "shed"]
        assert shed_events, "flood never tripped the brownout"
        tiers = [e[3].split("tier=")[1].split()[0]
                 for e in shed_events]
        assert set(tiers) <= {"background", "batch"}
        assert "interactive" not in st.sheds
        if "batch" in tiers:
            assert "background" in tiers[:tiers.index("batch")]
        # recovered: back to normal, squeeze lifted everywhere
        assert fleet.brownout.level == 0
        for r in fleet._alive():
            for role in r._roles:
                assert role.throttled_tiers == frozenset()
        _assert_no_leaks(fleet)

    def test_interactive_p99_protected_under_flood(self, fleet_models):
        """The acceptance pin in miniature: interactive p99 TTFT under
        a batch flood (brownout armed) is no worse than without the
        flood."""
        base = _fleet(fleet_models, tenants=dict(TEN), queue_cap=3,
                      brownout=BrownoutConfig(slo_ms=0.004, window=2,
                                              cooldown=3))
        base.run(_mixed_trace(n_bat=0, n_bg=0), max_ticks=800)
        p99_free = base.stats.per_tenant()["iact"]["p99_ttft_ticks"]

        fleet = _fleet(fleet_models, tenants=dict(TEN), queue_cap=3,
                       brownout=BrownoutConfig(slo_ms=0.004, window=2,
                                               cooldown=3))
        st = fleet.run(_mixed_trace(n_bat=24, n_bg=6), max_ticks=800)
        assert st.lost_requests == 0
        p99_flood = fleet.per_tenant()["iact"]["p99_ttft_ticks"]
        assert p99_flood <= p99_free, (
            f"interactive p99 degraded under flood: "
            f"{p99_flood} > {p99_free}")


# ------------------------------------- drain × preemption interplay

class TestPreemptDuringDrain:
    def _trace(self):
        out = []
        for i in range(2):
            out.append(_req(i, 0.0, "bat", session="a", max_new=8))
        for i in range(2):
            out.append(_req(10 + i, 0.0, "bat", session="b",
                            max_new=8))
        # interactive burst while the drain migration is in flight
        out += [_req(20 + i, 4.0, "iact", max_new=4)
                for i in range(3)]
        return out

    def test_drain_migration_survives_preemption(self, fleet_models):
        """Drain replica 1 mid-run (its rows migrate to replica 0),
        then flood replica 0 with interactive admissions that preempt
        the migrated batch rows. The transactional reserve/land/commit
        handoff must stay intact: zero lost, token streams identical
        to the fault-free single-tenant fleet, no page leaks."""
        ref = _fleet(fleet_models)
        ref.router.affinity["a"] = 0
        ref.router.affinity["b"] = 1
        ref.run(self._trace())
        assert ref.stats.lost_requests == 0

        fleet = _fleet(fleet_models, tenants=dict(TEN))
        fleet.router.affinity["a"] = 0
        fleet.router.affinity["b"] = 1
        fleet.submit_trace(self._trace())
        for t in range(400):
            if fleet.idle:
                break
            if t == 3:
                fleet.drain(1)
            fleet.tick()
        st = fleet.stats
        assert st.lost_requests == 0
        assert st.migrations >= 1
        assert fleet.preemptions >= 1
        assert 1 in fleet._retired
        assert fleet.token_streams() == ref.token_streams()
        _assert_no_leaks(fleet)


# -------------------------------------------- maintenance retune

@pytest.fixture
def store_dir(tmp_path, monkeypatch):
    from triton_distributed_tpu.tune import schedule as S

    monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
    S.load_schedule.cache_clear()
    yield tmp_path
    S.load_schedule.cache_clear()


class TestMaintenanceRetune:
    def test_retune_fires_in_low_pressure_window(self, fleet_models,
                                                 store_dir):
        fleet = _fleet(fleet_models, retune_every=3)
        st = fleet.run(_mixed_trace(n_iact=3, n_bat=0, n_bg=0),
                       max_ticks=400)
        assert st.retunes, "no maintenance window found"
        assert any(e[0] == "retune" for e in st.events)
        tick, replica, n = st.retunes[0]
        assert tick % 3 == 0 and n >= 1

    def test_retune_suppressed_during_brownout(self, fleet_models,
                                               store_dir):
        fleet = _fleet(fleet_models, tenants=dict(TEN),
                       retune_every=3,
                       brownout=BrownoutConfig(slo_ms=1.0))
        fleet.run(_mixed_trace(n_iact=3, n_bat=0, n_bg=0),
                  max_ticks=400)
        before = len(fleet.stats.retunes)
        assert before > 0                  # normal level: retunes ran
        # force an overload level: the same low-pressure check must
        # now refuse the window
        fleet.brownout.level = 2
        fleet.ticks = 3 * fleet.retune_every
        fleet._maybe_retune()
        assert len(fleet.stats.retunes) == before
        fleet.brownout.level = 0
        fleet._maybe_retune()
        assert len(fleet.stats.retunes) == before + 1


# ------------------------------------------------ replay determinism

class TestReplayDeterminism:
    def _chaos_run(self, fleet_models):
        fleet = _fleet(fleet_models, tenants=dict(TEN), queue_cap=3,
                       brownout=BrownoutConfig(slo_ms=0.004, window=2,
                                               cooldown=3))
        plan = FaultPlan(seed=1,
                         faults=(ReplicaDeath(replica=1, step=8),))
        fleet.submit_trace(_mixed_trace(n_bat=16, n_bg=4))
        with faults.fault_plan(plan):
            for _ in range(600):
                if fleet.idle:
                    break
                fleet.tick()
        return fleet

    def test_flood_death_preemption_events_identical(
            self, fleet_models):
        """Tenant flood × ReplicaDeath × preemption/shed/brownout:
        same seed ⇒ byte-identical event logs (the PR-13 replay
        contract extended to the multi-tenant events), zero lost."""
        runs = [self._chaos_run(fleet_models) for _ in range(2)]
        for fleet in runs:
            assert fleet.stats.lost_requests == 0
            assert (1, 8) in fleet.stats.deaths
            _assert_no_leaks(fleet)
        assert runs[0].stats.events == runs[1].stats.events
        kinds = {e[0] for e in runs[0].stats.events}
        assert "death" in kinds
