"""Autotuner + perf-model tests.

Mirrors the reference's autotuner contract (autotuner.py:97-253):
thunk-level benching, failed-config skip, caching, consensus.
"""

import json
import pathlib

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.tune import (
    TPU_SPECS,
    contextual_autotune,
    detect_spec,
    estimate_all_gather_ms,
    estimate_all_to_all_ms,
    estimate_gemm_ms,
    estimate_reduce_scatter_ms,
    overlap_efficiency,
)


class TestAutotuner:
    def test_picks_and_caches(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
        bench_calls = []

        @contextual_autotune(configs=[{"s": 2.0}, {"s": 3.0}])
        def op(x, *, s):
            bench_calls.append(s)
            return x * s

        x = jnp.ones((4, 4))
        y1 = op(x)
        n_bench = len(bench_calls)
        assert n_bench >= 2                     # both configs benched
        y2 = op(x)                              # cache hit: exactly 1 call
        assert len(bench_calls) == n_bench + 1
        assert float(y1[0, 0]) == float(y2[0, 0])
        log = (tmp_path / "process-0.jsonl").read_text()
        assert "best" in log

    def test_failed_config_skipped(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))

        @contextual_autotune(configs=[{"ok": False}, {"ok": True}])
        def op(x, *, ok):
            if not ok:
                raise ValueError("broken config")
            return x + 1

        out = op(jnp.zeros((2,)))
        np.testing.assert_allclose(np.asarray(out), 1.0)

    def test_all_configs_failing_raises(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))

        @contextual_autotune(configs=[{"a": 1}, {"a": 2}])
        def op(x, *, a):
            raise ValueError("nope")

        with pytest.raises(RuntimeError, match="every config failed"):
            op(jnp.zeros((2,)))

    def test_distinct_shapes_tuned_separately(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
        seen = []

        @contextual_autotune(configs=[{"s": 1.0}])
        def op(x, *, s):
            seen.append(x.shape)
            return x

        op(jnp.ones((2, 2)))
        op(jnp.ones((4, 4)))
        log = (tmp_path / "process-0.jsonl").read_text().strip().splitlines()
        assert len([l for l in log if "best" in l]) == 2


class TestPairedBench:
    """VERDICT r3 #8: the paired (snake-order + within-round
    normalization) ranking must stay stable under a monotonic
    interference ramp that flips the naive independent ranking."""

    def test_paired_ranking_survives_drift(self, tmp_path, monkeypatch):
        import triton_distributed_tpu.tune.autotuner as at

        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
        # true costs: config B is 2% FASTER; background interference
        # ramps +5% per measurement window — larger than the real gap
        true_ms = {1: 1.00, 2: 0.98}
        step = [0]
        schedule = []

        def fake_perf(fn, warmup=0, iters=1):
            out = fn()       # the thunk returns its config's `a`
            a = int(out)
            ms = true_ms[a] * (1.0 + 0.05 * step[0])
            schedule.append((a, ms))
            step[0] += 1
            return out, ms

        monkeypatch.setattr(at, "perf_func", fake_perf)

        tuner = at.ContextualAutoTuner(
            lambda *, a: a, [{"a": 1}, {"a": 2}],
            name="paired", rounds=2, warmup=0, iters=1, log=False,
            persist=False,
        )
        best = tuner.pick()
        assert best == {"a": 2}, f"paired ranking picked {best}"

        # the same scripted measurements mislead the INDEPENDENT
        # (forward-order, median-of-absolute) ranking: A is measured
        # first in every round, so the ramp penalizes B systematically
        fwd = {1: [], 2: []}
        t = 0
        for _ in range(2):
            for a in (1, 2):
                fwd[a].append(true_ms[a] * (1.0 + 0.05 * t))
                t += 1
        assert np.median(fwd[1]) < np.median(fwd[2]), (
            "drift scenario no longer flips the independent ranking — "
            "strengthen the ramp"
        )


class TestWinnerValidation:
    """Persisted winners are TTL'd and re-validated against the recorded
    runner-up (VERDICT r2 #8): a noise-artifact winner heals instead of
    persisting forever."""

    @staticmethod
    def _sleep_op():
        import time as _t

        def op(x, *, d):
            _t.sleep(d)
            return x

        return op

    def test_stale_wrong_winner_recovers(self, tmp_path, monkeypatch):
        from triton_distributed_tpu.tune.autotuner import (
            ContextualAutoTuner,
            _shape_key,
        )

        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
        fast, slow = {"d": 0.0}, {"d": 0.05}
        tuner = ContextualAutoTuner(
            self._sleep_op(), [fast, slow], name="heal", warmup=0, iters=1,
        )
        x = jnp.ones((2,))
        key = ("heal", _shape_key((x,), {}))
        # inject the SLOW config as the persisted winner (a noisy sweep's
        # artifact), fast one recorded as runner-up
        tuner._disk_put(key, slow, fast)
        assert tuner.pick(x) == fast            # re-validated → re-tuned
        assert tuner._disk_get(key)["best"] == fast   # store healed

    def test_valid_winner_accepted_without_full_sweep(self, tmp_path, monkeypatch):
        from triton_distributed_tpu.tune.autotuner import (
            ContextualAutoTuner,
            _shape_key,
        )

        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
        fast, slow = {"d": 0.0}, {"d": 0.05}
        calls = []

        def op(x, *, d):
            calls.append(d)
            import time as _t

            _t.sleep(d)
            return x

        tuner = ContextualAutoTuner(op, [fast, slow], name="ok",
                                    warmup=0, iters=1)
        x = jnp.ones((2,))
        tuner._disk_put(("ok", _shape_key((x,), {})), fast, slow)
        assert tuner.pick(x) == fast
        # validation benched exactly winner+runner once each (no sweep,
        # which here would be indistinguishable by count — assert order:
        # best first, runner second, nothing else)
        assert calls == [0.0, 0.05]

    def test_ttl_expiry_rebenches(self, tmp_path, monkeypatch):
        from triton_distributed_tpu.tune.autotuner import (
            ContextualAutoTuner,
            _shape_key,
        )

        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
        tuner = ContextualAutoTuner(
            self._sleep_op(), [{"d": 0.0}, {"d": 0.02}], name="ttl",
            warmup=0, iters=1, ttl_s=0,
        )
        x = jnp.ones((2,))
        key = ("ttl", _shape_key((x,), {}))
        tuner._disk_put(key, {"d": 0.02}, {"d": 0.0})
        assert tuner._disk_get(key) is None     # ttl 0 → instantly stale
        assert tuner.pick(x) == {"d": 0.0}      # full re-bench found fast

    def test_legacy_v1_entry_rebenches(self, tmp_path, monkeypatch):
        import json as _json

        from triton_distributed_tpu.tune.autotuner import (
            ContextualAutoTuner,
            _shape_key,
        )

        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
        tuner = ContextualAutoTuner(
            self._sleep_op(), [{"d": 0.0}, {"d": 0.02}], name="v1",
            warmup=0, iters=1,
        )
        x = jnp.ones((2,))
        key = ("v1", _shape_key((x,), {}))
        # hand-write a pre-v2 store entry (bare config dict)
        (tmp_path / "cache.json").write_text(
            _json.dumps({repr(key): {"d": 0.02}})
        )
        assert tuner._disk_get(key) is None     # schema drift → miss
        assert tuner.pick(x) == {"d": 0.0}
        assert tuner._disk_get(key)["v"] == 2   # store upgraded


class TestPerfModel:
    def test_specs_and_detection(self):
        assert set(TPU_SPECS) == {"v4", "v5e", "v5p", "v6e"}
        spec = detect_spec()            # CPU test host → fallback, no crash
        assert spec.bf16_tflops > 0

    def test_estimates_scale_sanely(self):
        spec = TPU_SPECS["v5e"]
        small = estimate_gemm_ms(1024, 1024, 1024, spec)
        big = estimate_gemm_ms(8192, 8192, 8192, spec)
        assert big > small * 100        # cubic flops growth dominates
        ag = estimate_all_gather_ms(2**20, 8, spec)
        rs = estimate_reduce_scatter_ms(2**20, 8, spec)
        assert ag == rs > 0
        a2a = estimate_all_to_all_ms(2**20, 8, spec)
        assert 0 < a2a < ag             # torus bisection beats ring wire time
        assert overlap_efficiency(2.0, 1.0) == 1.0
        assert overlap_efficiency(1.0, 2.0) == 0.5

    def test_migrate_vs_reprefill_pricing(self):
        """The fleet's migration gate (ISSUE-13): shipping pages over a
        fast DCN beats recomputing the prefix; a slow DCN flips the
        verdict while the re-prefill side (DCN-independent) holds."""
        from triton_distributed_tpu.tune.perf_model import (
            TpuSpec,
            migrate_vs_reprefill_ms,
        )

        kw = dict(page=8, hkv=2, g=2, d=16, hidden=64, n_layers=2)
        fast = TpuSpec(name="fast-dcn", bf16_tflops=200.0,
                       hbm_gbps=800.0, ici_gbps=50.0, ici_links=4,
                       dcn_gbps=100.0)
        w, r = migrate_vs_reprefill_ms(4, spec=fast, **kw)
        assert 0 < w < r
        slow = TpuSpec(name="slow-dcn", bf16_tflops=200.0,
                       hbm_gbps=800.0, ici_gbps=50.0, ici_links=4,
                       dcn_gbps=1e-9)
        w2, r2 = migrate_vs_reprefill_ms(4, spec=slow, **kw)
        assert w2 > r2
        assert r2 == pytest.approx(r)
        # both sides grow with the prefix length
        w3, r3 = migrate_vs_reprefill_ms(8, spec=fast, **kw)
        assert w3 > w and r3 > r


class TestTunedEngineSelection:
    """method=None consults the measured tuner with a persistent on-disk
    cache (VERDICT r1 #7): miss → bench+store, hit → no bench."""

    def _env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TDTPU_AUTOTUNE", "1")
        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))

    def test_ag_gemm_tuned_and_disk_cached(self, mesh8, tmp_path, monkeypatch):
        import jax

        import importlib

        mod = importlib.import_module("triton_distributed_tpu.kernels.ag_gemm")
        from triton_distributed_tpu.tune.autotuner import ContextualAutoTuner

        self._env(tmp_path, monkeypatch)
        mod._engine_tuner.cache_clear()
        a = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
        b = jax.random.normal(jax.random.PRNGKey(1), (32, 128))
        ref = np.asarray(jnp.dot(a, b))
        out = mod.ag_gemm(a, b, mesh8, "x")            # miss → bench + store
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)
        store = json.loads((tmp_path / "cache.json").read_text())
        assert any("ag_gemm" in k for k in store)

        # fresh tuner (new process simulation): must hit the DISK cache —
        # a full sweep is forbidden. Winner re-validation (the cheap
        # 2-config re-bench) is pinned to "accept" here: on this noisy
        # time-shared host a legitimate rejection would trigger a full
        # sweep and flake the test; the validation logic itself is
        # covered deterministically by TestWinnerValidation.
        mod._engine_tuner.cache_clear()
        validated = []
        monkeypatch.setattr(
            ContextualAutoTuner, "_validate_entry",
            lambda self, entry, args, kwargs: (
                validated.append(entry), entry["best"]
            )[1],
        )
        monkeypatch.setattr(
            ContextualAutoTuner, "_bench",
            lambda self, *a, **k: (_ for _ in ()).throw(
                AssertionError("full sweep ran on a disk hit")
            ),
        )
        out2 = mod.ag_gemm(a, b, mesh8, "x")
        np.testing.assert_allclose(np.asarray(out2), ref, atol=1e-4, rtol=1e-4)
        assert validated, "disk entry never reached winner re-validation"

    def test_gemm_rs_and_all_gather_tuned(self, mesh8, tmp_path, monkeypatch):
        import jax

        import importlib

        agmod = importlib.import_module("triton_distributed_tpu.kernels.allgather")
        rsmod = importlib.import_module("triton_distributed_tpu.kernels.gemm_rs")

        self._env(tmp_path, monkeypatch)
        rsmod._engine_tuner.cache_clear()
        agmod._engine_tuner.cache_clear()
        a = jax.random.normal(jax.random.PRNGKey(2), (64, 32))
        b = jax.random.normal(jax.random.PRNGKey(3), (32, 48))
        out = rsmod.gemm_rs(a, b, mesh8, "x")
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(jnp.dot(a, b)), atol=1e-4, rtol=1e-4
        )
        x = jax.random.normal(jax.random.PRNGKey(4), (64, 16))
        full = agmod.all_gather(x, mesh8, "x")
        np.testing.assert_allclose(np.asarray(full), np.asarray(x), atol=0)
        store = json.loads((tmp_path / "cache.json").read_text())
        assert any("gemm_rs" in k for k in store)
        assert any("all_gather" in k for k in store)

    def test_heuristic_when_disabled(self, mesh8, tmp_path, monkeypatch):
        """TDTPU_AUTOTUNE=0 → static heuristics, no cache file."""
        import jax

        import importlib

        mod = importlib.import_module("triton_distributed_tpu.kernels.ag_gemm")
        monkeypatch.setenv("TDTPU_AUTOTUNE", "0")
        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
        mod._engine_tuner.cache_clear()
        a = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
        b = jax.random.normal(jax.random.PRNGKey(1), (32, 128))
        mod.ag_gemm(a, b, mesh8, "x")
        assert not (tmp_path / "cache.json").exists()
