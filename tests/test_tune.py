"""Autotuner + perf-model tests.

Mirrors the reference's autotuner contract (autotuner.py:97-253):
thunk-level benching, failed-config skip, caching, consensus.
"""

import json
import pathlib

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.tune import (
    TPU_SPECS,
    contextual_autotune,
    detect_spec,
    estimate_all_gather_ms,
    estimate_all_to_all_ms,
    estimate_gemm_ms,
    estimate_reduce_scatter_ms,
    overlap_efficiency,
)


class TestAutotuner:
    def test_picks_and_caches(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
        bench_calls = []

        @contextual_autotune(configs=[{"s": 2.0}, {"s": 3.0}])
        def op(x, *, s):
            bench_calls.append(s)
            return x * s

        x = jnp.ones((4, 4))
        y1 = op(x)
        n_bench = len(bench_calls)
        assert n_bench >= 2                     # both configs benched
        y2 = op(x)                              # cache hit: exactly 1 call
        assert len(bench_calls) == n_bench + 1
        assert float(y1[0, 0]) == float(y2[0, 0])
        log = (tmp_path / "process-0.jsonl").read_text()
        assert "best" in log

    def test_failed_config_skipped(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))

        @contextual_autotune(configs=[{"ok": False}, {"ok": True}])
        def op(x, *, ok):
            if not ok:
                raise ValueError("broken config")
            return x + 1

        out = op(jnp.zeros((2,)))
        np.testing.assert_allclose(np.asarray(out), 1.0)

    def test_all_configs_failing_raises(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))

        @contextual_autotune(configs=[{"a": 1}, {"a": 2}])
        def op(x, *, a):
            raise ValueError("nope")

        with pytest.raises(RuntimeError, match="every config failed"):
            op(jnp.zeros((2,)))

    def test_distinct_shapes_tuned_separately(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TDTPU_AUTOTUNE_LOG_DIR", str(tmp_path))
        seen = []

        @contextual_autotune(configs=[{"s": 1.0}])
        def op(x, *, s):
            seen.append(x.shape)
            return x

        op(jnp.ones((2, 2)))
        op(jnp.ones((4, 4)))
        log = (tmp_path / "process-0.jsonl").read_text().strip().splitlines()
        assert len([l for l in log if "best" in l]) == 2


class TestPerfModel:
    def test_specs_and_detection(self):
        assert set(TPU_SPECS) == {"v4", "v5e", "v5p", "v6e"}
        spec = detect_spec()            # CPU test host → fallback, no crash
        assert spec.bf16_tflops > 0

    def test_estimates_scale_sanely(self):
        spec = TPU_SPECS["v5e"]
        small = estimate_gemm_ms(1024, 1024, 1024, spec)
        big = estimate_gemm_ms(8192, 8192, 8192, spec)
        assert big > small * 100        # cubic flops growth dominates
        ag = estimate_all_gather_ms(2**20, 8, spec)
        rs = estimate_reduce_scatter_ms(2**20, 8, spec)
        assert ag == rs > 0
        a2a = estimate_all_to_all_ms(2**20, 8, spec)
        assert 0 < a2a < ag             # torus bisection beats ring wire time
        assert overlap_efficiency(2.0, 1.0) == 1.0
        assert overlap_efficiency(1.0, 2.0) == 0.5
