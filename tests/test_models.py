"""Flagship transformer tests: training (dense + MoE) and SP decode.

The reference has no model zoo; these tests pin the framework-level
contract — every projection through the overlap ops, trainable
end-to-end, and the SP flash-decode generation path numerically equal
to a dense incremental decode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.models import Transformer, TransformerConfig

CFG = dict(
    vocab=128, n_layers=2, hidden=128, ffn=256,
    n_heads=8, n_kv_heads=4, head_dim=16,
    dtype=jnp.float32, param_dtype=jnp.float32,
)


def _model(mesh, moe="none", dp=False):
    cfg = TransformerConfig(
        **CFG, moe=moe, moe_layers=(1,) if moe != "none" else (),
        num_experts=8, topk=2,
    )
    return Transformer(cfg, mesh, "tp", ("dp",) if dp else ())


def _sharded_params(model, key=0):
    params = model.init(jax.random.PRNGKey(key))
    return jax.tree.map(
        lambda p, s: jax.device_put(p, s), params, model.shardings()
    )


@pytest.fixture(scope="module")
def mesh_tp():
    devs = np.asarray(jax.devices())
    from jax.sharding import Mesh

    return Mesh(devs, ("tp",))


@pytest.fixture(scope="module")
def mesh_dp_tp():
    devs = np.asarray(jax.devices()).reshape(2, 4)
    from jax.sharding import Mesh

    return Mesh(devs, ("dp", "tp"))


class TestTraining:
    def test_dense_loss_decreases_dp_tp(self, mesh_dp_tp):
        model = _model(mesh_dp_tp, dp=True)
        params = _sharded_params(model)
        toks = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128),
            NamedSharding(mesh_dp_tp, P("dp")),
        )
        l1, params = model.train_step(params, toks, toks)
        l2, _ = model.train_step(params, toks, toks)
        assert np.isfinite(float(l1)) and float(l2) < float(l1)

    def test_moe_ep_loss_decreases(self, mesh_dp_tp):
        model = _model(mesh_dp_tp, moe="ep", dp=True)
        params = _sharded_params(model)
        toks = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128),
            NamedSharding(mesh_dp_tp, P("dp")),
        )
        l1, params = model.train_step(params, toks, toks)
        l2, _ = model.train_step(params, toks, toks)
        assert np.isfinite(float(l1)) and float(l2) < float(l1)


def _force_fused_ctx():
    """Monkeypatch body for Transformer._moe_ep_ctx: decode rides the
    fused transport even off-TPU (tiny interpreter-safe geometry),
    honoring the config's moe_wire_quant — shared by the LL-state and
    wire-quant decode tests."""
    from triton_distributed_tpu import ops

    def fused_ctx(self, m_local, inference=False, weights_quantized=None):
        c = self.config
        return ops.create_ep_moe_context(
            self.mesh, self.tp_axis, num_experts=c.num_experts,
            topk=c.topk, max_m=m_local * c.topk, hidden=c.hidden,
            dtype=c.dtype, transport="fused" if inference else "xla",
            use_pallas_gemm=False, block_m=8,
            quant=c.moe_wire_quant if inference else None,
            batch_axes=tuple(self.dp_axes),
        )

    return fused_ctx


class TestDecode:
    def test_decode_ll_state_matches_stateless(self, mesh_tp, monkeypatch):
        """decode_step with the barrier-free LL MoE state EXECUTES (not
        just compiles) and matches the stateless step bit-for-bit over
        consecutive parities. Off-TPU the model normally demotes decode
        to the XLA transport, so the fused context is forced here (tiny
        shapes, interpreter-safe)."""
        model = _model(mesh_tp, moe="ep")
        monkeypatch.setattr(Transformer, "_moe_ep_ctx", _force_fused_ctx())
        params = _sharded_params(model)
        b, smax = 8, 32
        caches = model.init_cache(b, smax)
        prompt = jax.random.randint(jax.random.PRNGKey(3), (b, 8), 0, 128)
        last, caches, lens = model.prefill(params, caches, prompt)
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)

        state = model.init_decode_state(b)
        assert state is not None and state[1] is not None  # MoE layer 1
        ref_caches, ref_lens, ref_tok = caches, lens, first
        ll_caches, ll_lens, ll_tok = caches, lens, first
        for step in range(3):
            ref_logits, ref_caches, ref_lens = model.decode_step(
                params, ref_caches, ref_lens, ref_tok
            )
            ll_logits, ll_caches, ll_lens, state = model.decode_step(
                params, ll_caches, ll_lens, ll_tok, state
            )
            np.testing.assert_allclose(
                np.asarray(ll_logits), np.asarray(ref_logits),
                atol=1e-5, rtol=1e-5,
            )
            ref_tok = jnp.argmax(ref_logits, axis=-1).astype(jnp.int32)
            ll_tok = jnp.argmax(ll_logits, axis=-1).astype(jnp.int32)
            assert int(np.asarray(state[1].parity)[0]) == (step + 1) % 2

    def test_decode_fused_ll_real_ctx_executes(self, mesh_tp):
        """The REAL ``_moe_ep_ctx`` path (no monkeypatch) under
        ``config.force_fused_transport`` runs 3 consecutive fused-LL
        decode steps on the 8-device interpreter mesh — chunked
        transport + donable functional state + append + SP attention
        composed in the production step — and matches the XLA-transport
        logits (VERDICT r4 #4)."""
        from triton_distributed_tpu.config import config as tcfg

        model = _model(mesh_tp, moe="ep")
        params = _sharded_params(model)
        b, smax = 8, 32
        prompt = jax.random.randint(jax.random.PRNGKey(3), (b, 8), 0, 128)
        caches = model.init_cache(b, smax)
        last, caches, lens = model.prefill(params, caches, prompt)
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)
        ref_c, ref_l, ref_t = caches, lens, first
        ll_c, ll_l, ll_t = caches, lens, first

        tcfg.force_fused_transport = True
        try:
            m_ll = _model(mesh_tp, moe="ep")   # fresh ctx/jit caches
            ctx = m_ll._moe_ep_ctx(1, inference=True)
            assert ctx.transport == "fused"
            state = m_ll.init_decode_state(b)
            assert state is not None and state[1] is not None
            for step in range(3):
                ref_lg, ref_c, ref_l = model.decode_step(
                    params, ref_c, ref_l, ref_t
                )
                ll_lg, ll_c, ll_l, state = m_ll.decode_step(
                    params, ll_c, ll_l, ll_t, state
                )
                np.testing.assert_allclose(
                    np.asarray(ll_lg), np.asarray(ref_lg),
                    atol=1e-5, rtol=1e-5,
                )
                ref_t = jnp.argmax(ref_lg, axis=-1).astype(jnp.int32)
                ll_t = jnp.argmax(ll_lg, axis=-1).astype(jnp.int32)
                assert int(np.asarray(state[1].parity)[0]) == (step + 1) % 2
        finally:
            tcfg.force_fused_transport = False

    # The three decode quant-consistency tests are ``slow``-marked
    # (round 7, the ROADMAP CI-budget item): each costs ~15 s of the
    # tier-1 budget on the 1-core host re-prefilling a full model twice
    # over the forced-fused transport. The numerics they pin sit behind
    # ``pytest -m slow tests/test_models.py`` (nightly and before any
    # quant-touching merge); tier-1 keeps the cheap LL-state and
    # transport-parity decode tests above.
    @pytest.mark.slow
    def test_decode_wire_quant_close_to_full_precision(self, mesh_tp,
                                                       monkeypatch):
        """moe_wire_quant='fp8': the decode MoE transport ships 1-byte
        tokens + per-token scales; logits must stay within quantization
        tolerance of the full-precision step."""
        cfg = TransformerConfig(
            **CFG, moe="ep", moe_layers=(1,), num_experts=8, topk=2,
            moe_wire_quant="fp8",
        )
        model = Transformer(cfg, mesh_tp, "tp", ())
        monkeypatch.setattr(Transformer, "_moe_ep_ctx", _force_fused_ctx())
        params = _sharded_params(model)
        b, smax = 8, 32
        caches = model.init_cache(b, smax)
        prompt = jax.random.randint(jax.random.PRNGKey(3), (b, 8), 0, 128)
        last, caches, lens = model.prefill(params, caches, prompt)
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)
        logits_q, _, _ = model.decode_step(params, caches, lens, first)

        # full-precision twin: same params/caches, no wire quant (the
        # class-level _moe_ep_ctx patch is already in effect and honors
        # each model's own moe_wire_quant)
        full = Transformer(
            TransformerConfig(**CFG, moe="ep", moe_layers=(1,),
                              num_experts=8, topk=2),
            mesh_tp, "tp", (),
        )
        logits_f, _, _ = full.decode_step(params, caches, lens, first)
        err = np.abs(np.asarray(logits_q) - np.asarray(logits_f))
        assert err.max() < 0.05 * np.abs(np.asarray(logits_f)).max()
        # the quantized wire must actually have engaged: identical
        # logits would mean the fp8 path silently regressed to a no-op
        assert err.max() > 0, "quantization did not perturb the logits"

        # the production combination: fp8 wire + the barrier-free LL
        # state (quant geometry sizes the persistent windows) — two
        # steps rolling the parity, matching the stateless quantized
        # step bit-for-bit
        state = model.init_decode_state(b)
        assert state is not None and state[1] is not None
        ll_caches, ll_lens, ll_tok = caches, lens, first
        q_caches, q_lens, q_tok = caches, lens, first
        for step in range(2):
            ll_logits, ll_caches, ll_lens, state = model.decode_step(
                params, ll_caches, ll_lens, ll_tok, state
            )
            q_logits, q_caches, q_lens = model.decode_step(
                params, q_caches, q_lens, q_tok
            )
            np.testing.assert_allclose(
                np.asarray(ll_logits), np.asarray(q_logits),
                atol=1e-5, rtol=1e-5,
            )
            ll_tok = jnp.argmax(ll_logits, axis=-1).astype(jnp.int32)
            q_tok = jnp.argmax(q_logits, axis=-1).astype(jnp.int32)

    @pytest.mark.slow
    def test_decode_weight_quant_close_to_full_precision(self, mesh_tp,
                                                         monkeypatch):
        """moe_weight_quant='int8': quantize_moe_weights replaces the EP
        expert matrices with {"q","scale"} dicts; decode (fused
        transport), prefill, and the training forward must all consume
        them, staying within per-channel-int8 tolerance of the
        full-precision model."""
        cfg = TransformerConfig(
            **CFG, moe="ep", moe_layers=(1,), num_experts=8, topk=2,
            moe_weight_quant="int8",
        )
        model = Transformer(cfg, mesh_tp, "tp", ())
        monkeypatch.setattr(Transformer, "_moe_ep_ctx", _force_fused_ctx())
        params = _sharded_params(model)
        b, smax = 8, 32
        caches = model.init_cache(b, smax)
        prompt = jax.random.randint(jax.random.PRNGKey(3), (b, 8), 0, 128)
        last, caches, lens = model.prefill(params, caches, prompt)
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)
        logits_f, _, _ = model.decode_step(params, caches, lens, first)

        qparams = model.quantize_moe_weights(params)
        blk = qparams["blocks"][1]
        assert isinstance(blk["moe_up"], dict)
        assert blk["moe_up"]["q"].dtype == jnp.int8
        # prefill with quantized weights (widens transparently)
        last_q, caches_q, lens_q = model.prefill(
            qparams, model.init_cache(b, smax), prompt
        )
        logits_q, _, _ = model.decode_step(qparams, caches_q, lens_q, first)
        err = np.abs(np.asarray(logits_q) - np.asarray(logits_f))
        assert err.max() < 0.05 * np.abs(np.asarray(logits_f)).max()
        assert err.max() > 0, "weight quant did not engage"
        # idempotent: already-quantized params pass through
        q2 = model.quantize_moe_weights(qparams)
        assert q2["blocks"][1]["moe_up"]["q"] is qparams["blocks"][1][
            "moe_up"]["q"]

    @pytest.mark.slow
    def test_decode_act_quant_close_to_w8a16(self, mesh_tp, monkeypatch):
        """moe_act_quant='int8' (W8A8): the decode expert GEMMs run the
        s8×s8 MXU path over per-row-quantized activations — logits stay
        within combined-int8 tolerance of the W8A16 path and the
        context actually engages (block_m 128, act_quant set)."""
        cfg16 = TransformerConfig(
            **CFG, moe="ep", moe_layers=(1,), num_experts=8, topk=2,
            moe_weight_quant="int8",
        )
        cfg8 = TransformerConfig(
            **CFG, moe="ep", moe_layers=(1,), num_experts=8, topk=2,
            moe_weight_quant="int8", moe_act_quant="int8",
        )
        m16 = Transformer(cfg16, mesh_tp, "tp", ())
        m8 = Transformer(cfg8, mesh_tp, "tp", ())

        # forced-fused ctx WITH the Pallas GEMM (W8A8 lives there);
        # honors the config's act_quant so m8 engages and m16 doesn't
        from triton_distributed_tpu import ops as _ops

        def fused_ctx(self, m_local, inference=False, weights_quantized=None):
            c = self.config
            return _ops.create_ep_moe_context(
                self.mesh, self.tp_axis, num_experts=c.num_experts,
                topk=c.topk, max_m=m_local * c.topk, hidden=c.hidden,
                dtype=c.dtype, transport="fused" if inference else "xla",
                use_pallas_gemm=True, block_m=8,
                quant=c.moe_wire_quant if inference else None,
                act_quant=c.moe_act_quant if inference else None,
                batch_axes=tuple(self.dp_axes),
            )

        monkeypatch.setattr(Transformer, "_moe_ep_ctx", fused_ctx)
        params = _sharded_params(m16)
        qp = m16.quantize_moe_weights(params)
        b, smax = 8, 32
        prompt = jax.random.randint(jax.random.PRNGKey(13), (b, 8), 0, 128)
        last, caches, lens = m16.prefill(qp, m16.init_cache(b, smax), prompt)
        tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
        lg16, _, _ = m16.decode_step(qp, caches, lens, tok)
        lg8, _, _ = m8.decode_step(qp, caches, lens, tok)
        err = np.abs(np.asarray(lg8) - np.asarray(lg16)).max()
        assert err < 0.06 * np.abs(np.asarray(lg16)).max()
        assert err > 0, "act quant did not engage"

    def test_decode_kv_quant_close_to_full_precision(self, mesh_tp):
        """kv_quant='int8': the decode caches hold int8 values +
        per-(b, h, s) f32 scales, prefill quantizes its K/V writes,
        append_kv quantizes each step's rows, and the SP attention
        consumes the dict caches — logits stay within int8-KV tolerance
        of the full-precision model over multiple steps."""
        cfg_f = TransformerConfig(**CFG)
        cfg_q = TransformerConfig(**CFG, kv_quant="int8")
        model_f = Transformer(cfg_f, mesh_tp, "tp", ())
        model_q = Transformer(cfg_q, mesh_tp, "tp", ())
        params = _sharded_params(model_f)
        b, smax = 4, 32
        prompt = jax.random.randint(jax.random.PRNGKey(5), (b, 10), 0, 128)

        caches_f = model_f.init_cache(b, smax)
        caches_q = model_q.init_cache(b, smax)
        assert isinstance(caches_q[0][0], dict)
        assert caches_q[0][0]["q"].dtype == jnp.int8
        last_f, caches_f, lens_f = model_f.prefill(params, caches_f, prompt)
        last_q, caches_q, lens_q = model_q.prefill(params, caches_q, prompt)
        scale = np.abs(np.asarray(last_f)).max()
        assert np.abs(np.asarray(last_q) - np.asarray(last_f)).max() < 0.05 * scale
        tok = jnp.argmax(last_f, axis=-1).astype(jnp.int32)
        for _ in range(3):
            lg_f, caches_f, lens_f = model_f.decode_step(
                params, caches_f, lens_f, tok
            )
            lg_q, caches_q, lens_q = model_q.decode_step(
                params, caches_q, lens_q, tok
            )
            err = np.abs(np.asarray(lg_q) - np.asarray(lg_f)).max()
            assert err < 0.05 * np.abs(np.asarray(lg_f)).max()
            assert err > 0, "kv quant did not engage"
            tok = jnp.argmax(lg_f, axis=-1).astype(jnp.int32)

    def test_decode_dense_weight_quant_close_to_full_precision(self, mesh_tp):
        """dense_weight_quant='int8': wqkv/wo/up/down/lm_head become
        {"q","scale"} dicts; decode rides the grouped-GEMM epilogue-
        dequant kernel (E=1) while prefill widens — both within
        per-out-channel-int8 tolerance of the full-precision model."""
        cfg = TransformerConfig(**CFG, dense_weight_quant="int8")
        model = Transformer(cfg, mesh_tp, "tp", ())
        params = _sharded_params(model)
        b, smax = 8, 32            # B=8 (8-multiple) → grouped-GEMM path
        prompt = jax.random.randint(jax.random.PRNGKey(9), (b, 8), 0, 128)
        last_f, caches_f, lens_f = model.prefill(
            params, model.init_cache(b, smax), prompt
        )
        tok = jnp.argmax(last_f, axis=-1).astype(jnp.int32)
        lg_f, _, _ = model.decode_step(params, caches_f, lens_f, tok)

        qp = model.quantize_dense_weights(params)
        assert isinstance(qp["lm_head"], dict)
        assert qp["blocks"][0]["wqkv"]["q"].dtype == jnp.int8
        last_q, caches_q, lens_q = model.prefill(
            qp, model.init_cache(b, smax), prompt
        )
        lg_q, _, _ = model.decode_step(qp, caches_q, lens_q, tok)
        for a, bq in ((last_f, last_q), (lg_f, lg_q)):
            err = np.abs(np.asarray(bq) - np.asarray(a)).max()
            assert err < 0.05 * np.abs(np.asarray(a)).max()
            assert err > 0, "dense weight quant did not engage"
        # B=64 (a block_m multiple) exercises the grouped-GEMM kernel
        # path of _dmm; same caches, quantized vs full-precision weights
        b2 = 64
        prompt2 = jax.random.randint(jax.random.PRNGKey(10), (b2, 4), 0, 128)
        _, caches2, lens2 = model.prefill(
            params, model.init_cache(b2, smax), prompt2
        )
        tok2 = jnp.zeros((b2,), jnp.int32)
        lg2_q, _, _ = model.decode_step(qp, caches2, lens2, tok2)
        lg2_f, _, _ = model.decode_step(params, caches2, lens2, tok2)
        assert lg2_q.dtype == lg2_f.dtype == jnp.float32
        err2 = np.abs(np.asarray(lg2_q) - np.asarray(lg2_f)).max()
        assert 0 < err2 < 0.05 * np.abs(np.asarray(lg2_f)).max()
        # W8A8 dense projections (dense_act_quant): same caches, logits
        # within combined-int8 tolerance; lm_head stays W8A16 (f32)
        cfg8 = TransformerConfig(
            **CFG, dense_weight_quant="int8", dense_act_quant="int8"
        )
        m8 = Transformer(cfg8, mesh_tp, "tp", ())
        lg8, _, _ = m8.decode_step(qp, caches2, lens2, tok2)
        assert lg8.dtype == jnp.float32
        err8 = np.abs(np.asarray(lg8) - np.asarray(lg2_f)).max()
        assert 0 < err8 < 0.06 * np.abs(np.asarray(lg2_f)).max()

        # B=6 (not an 8-multiple) exercises _dmm's widening fallback —
        # logits dtype and values must match the kernel path's contract
        b3 = 6
        _, caches3, lens3 = model.prefill(
            params, model.init_cache(b3, smax),
            jax.random.randint(jax.random.PRNGKey(11), (b3, 4), 0, 128),
        )
        tok3 = jnp.zeros((b3,), jnp.int32)
        lg3_q, _, _ = model.decode_step(qp, caches3, lens3, tok3)
        lg3_f, _, _ = model.decode_step(params, caches3, lens3, tok3)
        assert lg3_q.dtype == jnp.float32
        err3 = np.abs(np.asarray(lg3_q) - np.asarray(lg3_f)).max()
        assert 0 < err3 < 0.05 * np.abs(np.asarray(lg3_f)).max()

    def test_residency_gate_keys_on_actual_weights(self, mesh_tp):
        """A preset can default moe_weight_quant while the caller never
        ran quantize_moe_weights: the weight-residency VMEM gate must
        size from the REAL leaves (bf16), not the config's intent —
        sizing bf16 tiles at 1 B/elem would blow scoped VMEM at the
        first decode compile."""
        from triton_distributed_tpu.config import config, fused_vmem_budget

        cfg = TransformerConfig(
            vocab=128, n_layers=1, hidden=7168, ffn=2560, n_heads=8,
            n_kv_heads=4, head_dim=16, moe="ep", moe_layers=(0,),
            num_experts=8, topk=2, moe_weight_quant="int8",
        )
        budget = int(0.7 * fused_vmem_budget())
        if not (2 * cfg.hidden * cfg.ffn <= budget
                < 2 * cfg.hidden * cfg.ffn * 2):
            pytest.skip("vmem budget does not straddle this geometry")
        model = Transformer(cfg, mesh_tp, "tp", ())
        old = config.force_compile
        config.force_compile = True    # compiling_for_tpu() → True
        try:
            ctx_q = model._moe_ep_ctx(16, inference=True)
            ctx_raw = model._moe_ep_ctx(
                16, inference=True, weights_quantized=False
            )
        finally:
            config.force_compile = old
        assert ctx_q.gg_block_n is not None and ctx_q.block_m == 64
        assert ctx_raw.gg_block_n is None and ctx_raw.block_m == 256

    def test_sp_decode_matches_dense(self, mesh_tp):
        """generate() through the distributed flash-decode layer must
        match a dense incremental decode. Tokens are compared only where
        the dense argmax margin is decisive: the Pallas online-softmax +
        LSE combine reduces in a different order than dense softmax, so a
        near-tie may legitimately break the other way on another backend
        (ADVICE r1)."""
        model = _model(mesh_tp, moe="ep")
        params = _sharded_params(model)
        b, smax, steps = 2, 32, 3
        caches = model.init_cache(b, smax)
        lens = jnp.zeros((b,), jnp.int32)
        first = jnp.array([5, 9], jnp.int32)
        toks, _, lens2 = model.generate(params, caches, lens, first, steps)
        assert np.asarray(lens2).tolist() == [steps] * b

        ref, margins = self._dense_decode(
            model.config, params, first, b, smax, steps
        )
        # Compare each row only up to its first near-tie: after a
        # legitimately flipped argmax the two trajectories condition on
        # different prefixes, so later tokens are incomparable even
        # where the dense margin is decisive.
        nondecisive = np.asarray(margins) <= 1e-3
        first_bad = np.where(
            nondecisive.any(axis=1), nondecisive.argmax(axis=1), steps
        )
        assert (first_bad > 0).any(), "degenerate test: immediate near-ties"
        toks_np, ref_np = np.asarray(toks), np.asarray(ref)
        for i in range(b):
            np.testing.assert_array_equal(
                toks_np[i, : first_bad[i]], ref_np[i, : first_bad[i]]
            )

    def test_generate_scan_matches_generate(self, mesh_tp):
        """The on-device multi-step decode (ONE jitted lax.scan over
        steps) must produce the same tokens and lens as the per-step
        python-loop entry."""
        model = _model(mesh_tp, moe="ep")
        params = _sharded_params(model)
        b, smax, steps = 2, 32, 3
        first = jnp.array([5, 9], jnp.int32)
        toks_a, _, lens_a = model.generate(
            params, model.init_cache(b, smax),
            jnp.zeros((b,), jnp.int32), first, steps,
        )
        toks_b, _, lens_b = model.generate_scan(
            params, model.init_cache(b, smax),
            jnp.zeros((b,), jnp.int32), first, steps,
        )
        np.testing.assert_array_equal(np.asarray(toks_a), np.asarray(toks_b))
        assert np.asarray(lens_b).tolist() == [steps] * b

    def test_generate_scan_threads_ll_state(self, mesh_tp, monkeypatch):
        """generate_scan carries the barrier-free LL MoE state through
        the scan (the functional EPMoEState carry exists precisely for
        this) and matches the stateless scan's tokens; the state's
        parity must have rolled `steps` times."""
        model = _model(mesh_tp, moe="ep")
        monkeypatch.setattr(Transformer, "_moe_ep_ctx", _force_fused_ctx())
        params = _sharded_params(model)
        b, smax, steps = 8, 32, 2
        prompt = jax.random.randint(jax.random.PRNGKey(3), (b, 8), 0, 128)
        caches = model.init_cache(b, smax)
        last, caches, lens = model.prefill(params, caches, prompt)
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)

        state = model.init_decode_state(b)
        assert state is not None and state[1] is not None
        toks_ll, _, lens_ll, state = model.generate_scan(
            params, caches, lens, first, steps, moe_state=state
        )
        assert int(np.asarray(state[1].parity)[0]) == steps % 2

        caches_b = model.init_cache(b, smax)
        _, caches_b, lens_b = model.prefill(params, caches_b, prompt)
        toks_ref, _, _ = model.generate_scan(
            params, caches_b, lens_b, first, steps
        )
        np.testing.assert_array_equal(
            np.asarray(toks_ll), np.asarray(toks_ref)
        )

    @staticmethod
    def _dense_decode(c, params, last, b, smax, steps):
        params = jax.tree.map(jnp.asarray, jax.tree.map(np.asarray, params))
        ck = [jnp.zeros((b, smax, c.n_kv_heads, c.head_dim)) for _ in range(c.n_layers)]
        cv = [jnp.zeros((b, smax, c.n_kv_heads, c.head_dim)) for _ in range(c.n_layers)]
        lens = jnp.zeros((b,), jnp.int32)

        def rms(x, w):
            xf = x.astype(jnp.float32)
            return (
                xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + c.norm_eps)
            ).astype(x.dtype) * w

        outs, margins = [], []
        for _ in range(steps):
            x = params["embed"][last]
            for li, blk in enumerate(params["blocks"]):
                xn = rms(x, blk["norm_attn"])
                qkv = xn @ blk["wqkv"]
                q, k, v = jnp.split(qkv, [c.q_dim, c.q_dim + c.kv_dim], -1)
                q = q.reshape(b, c.n_heads, c.head_dim)
                k = k.reshape(b, c.n_kv_heads, c.head_dim)
                v = v.reshape(b, c.n_kv_heads, c.head_dim)
                rows = jnp.arange(b)
                ck[li] = ck[li].at[rows, lens].set(k)
                cv[li] = cv[li].at[rows, lens].set(v)
                g = c.n_heads // c.n_kv_heads
                qg = q.reshape(b, c.n_kv_heads, g, c.head_dim)
                s = jnp.einsum("bhgd,bshd->bhgs", qg, ck[li]) / (c.head_dim ** 0.5)
                mask = jnp.arange(smax)[None, None, None, :] < (lens + 1)[:, None, None, None]
                s = jnp.where(mask, s, -1e30)
                o = jnp.einsum(
                    "bhgs,bshd->bhgd", jax.nn.softmax(s, -1), cv[li]
                ).reshape(b, c.q_dim)
                x = x + o @ blk["wo"]
                xn = rms(x, blk["norm_mlp"])
                if "up" in blk:
                    x = x + jax.nn.silu(xn @ blk["up"]) @ blk["down"]
                else:
                    lr = xn @ blk["router"]
                    w, ids = mu.select_experts(lr, c.topk)
                    y = jnp.zeros_like(xn)
                    for t in range(c.topk):
                        hh = jax.nn.silu(
                            jnp.einsum("bh,bhf->bf", xn, blk["moe_up"][ids[:, t]])
                        )
                        y += w[:, t : t + 1] * jnp.einsum(
                            "bf,bfh->bh", hh, blk["moe_down"][ids[:, t]]
                        )
                    x = x + y
            lens = lens + 1
            x = rms(x, params["norm_f"])
            logits = x @ params["lm_head"]
            last = jnp.argmax(logits, -1).astype(jnp.int32)
            top2 = jax.lax.top_k(logits, 2)[0]
            margins.append(top2[:, 0] - top2[:, 1])
            outs.append(last)
        return jnp.stack(outs, 1), jnp.stack(margins, 1)


class TestRemat:
    def test_remat_matches_no_remat(self, mesh_dp_tp, monkeypatch):
        """jax.checkpoint must not change values or gradients. The
        interpreted Pallas engines carry io_callback effects that
        jax.checkpoint rejects, so this pins the XLA engines (what a
        remat run uses off-TPU; on hardware Mosaic kernels compose)."""
        from triton_distributed_tpu.config import config as tdtpu_config

        monkeypatch.setattr(tdtpu_config, "fused_vmem_budget", 0)
        toks = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128),
            NamedSharding(mesh_dp_tp, P("dp")),
        )
        losses, grads = {}, {}
        for remat in (False, True):
            cfg = TransformerConfig(**CFG, remat=remat)
            m = Transformer(cfg, mesh_dp_tp, "tp", ("dp",))
            params = jax.tree.map(
                lambda p, s: jax.device_put(p, s),
                m.init(jax.random.PRNGKey(0)), m.shardings(),
            )
            l, g = jax.value_and_grad(m.loss)(params, toks, toks)
            losses[remat], grads[remat] = float(l), g
        assert abs(losses[True] - losses[False]) < 1e-6
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
            ),
            grads[True], grads[False],
        )

    def test_remat_with_pallas_engines_rejected_off_tpu(self, mesh_dp_tp):
        cfg = TransformerConfig(**CFG, remat=True)
        m = Transformer(cfg, mesh_dp_tp, "tp", ("dp",))
        params = _sharded_params(m)
        toks = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128),
            NamedSharding(mesh_dp_tp, P("dp")),
        )
        with pytest.raises(ValueError, match="TDTPU_FUSED_VMEM_BUDGET"):
            m.forward(params, toks)


class TestPrefill:
    @pytest.mark.parametrize(
        "moe,attn", [("ep", "tp"), ("tp", "tp"), ("none", "ring")]
    )
    def test_prefill_matches_stepwise_decode(self, mesh_tp, moe, attn):
        """prefill(prompt) + generate must continue exactly like feeding
        the prompt through decode_step token by token (same caches, same
        lens) — the serving contract: one forward pass replaces S decode
        steps. moe='tp' exercises the overlapped inference engines;
        attn='ring' the CP prefill whose K/V arrive seq-sharded."""
        cfg = TransformerConfig(
            **CFG, attn=attn, moe=moe,
            moe_layers=(1,) if moe != "none" else (),
            num_experts=8, topk=2,
        )
        model = Transformer(cfg, mesh_tp, "tp", ())
        params = _sharded_params(model)
        b, smax, steps = 2, 32, 3
        prompt = jax.random.randint(jax.random.PRNGKey(3), (b, 16), 0, 128)

        # path A: one-shot prefill
        caches = model.init_cache(b, smax)
        last, caches, lens = model._prefill_jit(params, caches, prompt)

        # path B: feed the prompt one token at a time through decode_step
        caches_b = model.init_cache(b, smax)
        lens_b = jnp.zeros((b,), jnp.int32)
        logits = None
        for t in range(prompt.shape[1]):
            logits, caches_b, lens_b = model._decode_jit(
                params, caches_b, lens_b, prompt[:, t]
            )
        # the two paths compute attention with different reduction orders
        # (dense causal softmax vs flash-decode online softmax): logits
        # agree within tolerance...
        np.testing.assert_allclose(
            np.asarray(last), np.asarray(logits), atol=2e-3, rtol=2e-3
        )
        # ...and generation continues identically, compared STEPWISE with
        # a per-step margin gate well above the logit tolerance: a row
        # stops being compared at its first near-tie (the argmax may
        # legitimately flip there and the trajectories then diverge).
        la, lb = last, logits
        cmp = np.ones((b,), bool)
        for _ in range(steps):
            top2 = np.asarray(jax.lax.top_k(la, 2)[0])
            cmp &= (top2[:, 0] - top2[:, 1]) > 1e-2
            ta = jnp.argmax(la, axis=-1).astype(jnp.int32)
            tb = jnp.argmax(lb, axis=-1).astype(jnp.int32)
            assert cmp.any(), "degenerate test: all rows near-tied"
            np.testing.assert_array_equal(
                np.asarray(ta)[cmp], np.asarray(tb)[cmp]
            )
            la, caches, lens = model._decode_jit(params, caches, lens, ta)
            lb, caches_b, lens_b = model._decode_jit(
                params, caches_b, lens_b, tb
            )

    def test_ragged_prefill(self, mesh_tp):
        """Right-padded ragged prompts: each row's continuation state must
        equal prefilling that row's unpadded prompt alone."""
        model = _model(mesh_tp, moe="none")
        params = _sharded_params(model)
        b, smax = 2, 32
        full = jax.random.randint(jax.random.PRNGKey(5), (b, 16), 0, 128)
        lens = jnp.array([16, 8], jnp.int32)

        caches = model.init_cache(b, smax)
        last, caches, out_lens = model._prefill_jit(params, caches, full, lens)
        np.testing.assert_array_equal(np.asarray(out_lens), np.asarray(lens))

        # reference: prefill row 1's true (unpadded) prompt on its own
        # (length a multiple of tp — prefill shards B·S rows over tp)
        short = full[1:2, :8]
        c1 = model.init_cache(1, smax)
        last1, _, _ = model._prefill_jit(params, c1, short)
        np.testing.assert_allclose(
            np.asarray(last)[1], np.asarray(last1)[0], atol=2e-4, rtol=2e-4
        )
