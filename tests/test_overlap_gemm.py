"""AG-GEMM and GEMM-RS overlap kernels vs plain-JAX references.

≡ reference test_ag_gemm.py / test_gemm_rs.py
(python/triton_dist/test/nvidia/), with the jnp matmul + lax collective
playing the torch_ag_gemm / torch reference role (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.kernels import (
    AGGemmMethod,
    GemmRSMethod,
    ag_gemm,
    gemm_rs,
)
from triton_distributed_tpu.utils import assert_allclose


def _rand(shape, dtype=jnp.float32, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype=dtype)


def _ref_matmul(a, b):
    return np.asarray(
        jnp.dot(a, b, preferred_element_type=jnp.float32), dtype=np.float32
    )


@pytest.mark.parametrize(
    "method",
    [AGGemmMethod.PALLAS_FUSED, AGGemmMethod.XLA_RING, AGGemmMethod.XLA_NAIVE],
)
def test_ag_gemm_methods(mesh8, method):
    a = _rand((64, 32), seed=1)
    b = _rand((32, 128), seed=2)
    c = ag_gemm(a, b, mesh8, "x", method=method)
    assert c.shape == (64, 128)
    assert_allclose(np.asarray(c, np.float32), _ref_matmul(a, b), atol=1e-4, rtol=1e-4)


def test_ag_gemm_auto(mesh8):
    a = _rand((64, 32), seed=1)
    b = _rand((32, 128), seed=2)
    c = ag_gemm(a, b, mesh8, "x")
    assert_allclose(np.asarray(c, np.float32), _ref_matmul(a, b), atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("dtype", [jnp.bfloat16])
def test_ag_gemm_bf16(mesh8, dtype):
    a = _rand((64, 32), dtype, seed=1)
    b = _rand((32, 128), dtype, seed=2)
    c = ag_gemm(a, b, mesh8, "x", method=AGGemmMethod.PALLAS_FUSED)
    assert c.dtype == dtype
    assert_allclose(
        np.asarray(c, np.float32), _ref_matmul(a, b), atol=5e-2, rtol=5e-2
    )


def test_ag_gemm_multiaxis(mesh2x4):
    a = _rand((32, 32), seed=1)
    b = _rand((32, 128), seed=2)
    c = ag_gemm(a, b, mesh2x4, "tp", method=AGGemmMethod.PALLAS_FUSED)
    assert_allclose(np.asarray(c, np.float32), _ref_matmul(a, b), atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize(
    "method",
    [GemmRSMethod.PALLAS_FUSED, GemmRSMethod.XLA_RING, GemmRSMethod.XLA_NAIVE],
)
def test_gemm_rs_methods(mesh8, method):
    a = _rand((64, 32), seed=3)
    b = _rand((32, 48), seed=4)
    c = gemm_rs(a, b, mesh8, "x", method=method)
    assert c.shape == (64, 48)
    # every device computes a K-shard partial; reduce-scattered sum == full dot
    assert_allclose(np.asarray(c, np.float32), _ref_matmul(a, b), atol=1e-4, rtol=1e-4)


def test_gemm_rs_auto(mesh8):
    a = _rand((64, 32), seed=3)
    b = _rand((32, 48), seed=4)
    c = gemm_rs(a, b, mesh8, "x")
    assert_allclose(np.asarray(c, np.float32), _ref_matmul(a, b), atol=1e-4, rtol=1e-4)


def test_gemm_rs_multiaxis(mesh2x4):
    a = _rand((32, 32), seed=3)
    b = _rand((32, 48), seed=4)
    c = gemm_rs(a, b, mesh2x4, "tp", method=GemmRSMethod.PALLAS_FUSED)
    assert_allclose(np.asarray(c, np.float32), _ref_matmul(a, b), atol=1e-4, rtol=1e-4)


def test_tp_mlp_roundtrip(mesh8):
    """Column-parallel then row-parallel linear — the canonical TP MLP
    pattern the reference targets (AG-GEMM up-proj, GEMM-RS down-proj)."""
    x = _rand((64, 32), seed=5)
    w1 = _rand((32, 64), seed=6)
    w2 = _rand((64, 32), seed=7)
    h = ag_gemm(x, w1, mesh8, "x")          # (M, 64) sharded on cols
    y = gemm_rs(h, w2, mesh8, "x")          # (M, 32) sharded on rows
    ref = _ref_matmul(np.asarray(_ref_matmul(x, w1)), w2)
    assert_allclose(np.asarray(y, np.float32), ref, atol=1e-3, rtol=1e-3)


class TestHierarchical:
    """Hierarchical (multi-slice) fused engines (VERDICT r2 #4): the TP
    factor spans (axis, dcn_axis) axis-major; the fused Pallas ring runs
    intra-slice, the lax leg crosses the slice axis. ≡ the reference's
    inter-node AG-GEMM (allgather.py:291-375) and GEMM-RS
    (reduce_scatter.py:524-545)."""

    @pytest.fixture(scope="class")
    def mesh_tp_dcn(self):
        devs = np.asarray(jax.devices()).reshape(4, 2)
        return jax.sharding.Mesh(devs, ("tp", "dcn"))

    @pytest.mark.parametrize(
        "method",
        [AGGemmMethod.PALLAS_FUSED, AGGemmMethod.XLA_RING,
         AGGemmMethod.XLA_NAIVE, None],
    )
    def test_ag_gemm_hier(self, mesh_tp_dcn, method):
        a = _rand((64, 32), seed=11)
        b = _rand((32, 128), seed=12)
        c = ag_gemm(a, b, mesh_tp_dcn, "tp", method=method, dcn_axis="dcn")
        assert c.shape == (64, 128)
        assert_allclose(
            np.asarray(c, np.float32), _ref_matmul(a, b), atol=1e-4, rtol=1e-4
        )

    @pytest.mark.parametrize(
        "method",
        [GemmRSMethod.PALLAS_FUSED, GemmRSMethod.XLA_RING,
         GemmRSMethod.XLA_NAIVE, None],
    )
    def test_gemm_rs_hier(self, mesh_tp_dcn, method):
        a = _rand((64, 32), seed=13)
        b = _rand((32, 48), seed=14)
        c = gemm_rs(a, b, mesh_tp_dcn, "tp", method=method, dcn_axis="dcn")
        assert c.shape == (64, 48)
        assert_allclose(
            np.asarray(c, np.float32), _ref_matmul(a, b), atol=1e-4, rtol=1e-4
        )

    def test_ag_gemm_hier_return_gathered(self, mesh_tp_dcn):
        a = _rand((64, 32), seed=15)
        b = _rand((32, 128), seed=16)
        c, gathered = ag_gemm(
            a, b, mesh_tp_dcn, "tp", method=AGGemmMethod.PALLAS_FUSED,
            dcn_axis="dcn", return_gathered=True,
        )
        assert_allclose(np.asarray(c), _ref_matmul(a, b), atol=1e-4, rtol=1e-4)
        assert_allclose(np.asarray(gathered), np.asarray(a), atol=0, rtol=0)

    def test_hier_sharded_inputs_land_fused(self, mesh_tp_dcn):
        """Explicitly axis-major-sharded device inputs round-trip through
        the hierarchical fused engines (the realistic serving layout)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        a = jax.device_put(
            _rand((64, 32), seed=17),
            NamedSharding(mesh_tp_dcn, P(("tp", "dcn"), None)),
        )
        b = jax.device_put(
            _rand((32, 128), seed=18),
            NamedSharding(mesh_tp_dcn, P(None, ("tp", "dcn"))),
        )
        c = ag_gemm(
            a, b, mesh_tp_dcn, "tp", method=AGGemmMethod.PALLAS_FUSED,
            dcn_axis="dcn",
        )
        assert_allclose(
            np.asarray(c, np.float32), _ref_matmul(a, b), atol=1e-4, rtol=1e-4
        )


@pytest.mark.parametrize(
    "method", [AGGemmMethod.PALLAS_FUSED, AGGemmMethod.XLA_RING]
)
def test_ag_gemm_return_gathered(mesh8, method):
    """return_gathered=True hands back the gathered activations (free on
    the fused engine's workspace; a cached all_gather on XLA engines)."""
    a = _rand((64, 32), seed=7)
    b = _rand((32, 128), seed=8)
    c, gathered = ag_gemm(a, b, mesh8, "x", method=method, return_gathered=True)
    assert_allclose(np.asarray(c), _ref_matmul(a, b), atol=1e-4, rtol=1e-4)
    assert_allclose(np.asarray(gathered), np.asarray(a), atol=0, rtol=0)
