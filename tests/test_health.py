"""ISSUE-10 health-ledger suite: signal aggregation, mesh shrink,
serving failover and probation re-promotion.

The tentpole under test is :mod:`triton_distributed_tpu.runtime.health`
— one state machine fed by every failure signal the stack emits — and
the three action layers it drives:

* **signal aggregation** — fatal vs soft signals, flap damping (strikes
  survive a suspect-clear), deterministic seeded probe schedules (two
  replays of a trace probe at the same steps);
* **mesh shrink** — ``topology.replan_mesh`` maps the job onto the
  surviving n−1 (or surviving-slice) mesh, numerically identical to a
  hand-built mesh over the same devices, and feeds
  ``FaultPlan.unhealthy_peers`` automatically;
* **serving failover** — a :class:`SliceDeath` mid-trace re-queues the
  dead role's requests onto the survivor (exact-cursor re-prefill, the
  eviction recompute discipline), zero lost requests, token-exact; a
  transient kv_ship stall degrades the transport and probation probes
  re-promote it;
* **multi-slice watchdog aggregation** — per-slice trip summaries merge
  into one report naming the wedged slice, itself a ledger signal.

All sim-free: the ledger/topology layers are host code, the engines run
their CPU paths (the XLA twins and the interpreter kernels).
"""

import gc

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.runtime import faults, health, watchdog
from triton_distributed_tpu.runtime.faults import (
    FaultPlan,
    SliceDeath,
    Stall,
)
from triton_distributed_tpu.runtime.health import (
    FATAL_KINDS,
    HealthLedger,
    PeerState,
)
from triton_distributed_tpu.runtime.topology import replan_mesh
from triton_distributed_tpu.runtime.watchdog import (
    TripSummary,
    WatchdogTimeout,
    merge_trip_summaries,
    report_merged_trip,
)
from triton_distributed_tpu.serving import (
    DisaggregatedEngine,
    EngineConfig,
    Request,
    ServingEngine,
    poisson_trace,
)

#: tier-1 fast subset (ci/fast.sh): the health/failover half of the
#: robustness story
pytestmark = pytest.mark.fast


@pytest.fixture(autouse=True)
def _isolated_ledgers():
    """Ledgers register in a module-level WeakSet that the ops
    preflights consult — drop every ledger this test created so an
    UNHEALTHY verdict cannot leak into another test's preflight."""
    yield
    health.set_ledger(None)
    faults.set_fault_plan(None)
    watchdog.clear_trip()
    gc.collect()


# ----------------------------------------------------------- state machine


class TestLedgerStateMachine:
    def test_soft_signal_walks_through_suspect(self):
        led = HealthLedger(seed=0)
        assert led.state(3) is PeerState.HEALTHY
        assert led.record("transport_error", 3) is PeerState.SUSPECT
        assert led.record("transport_error", 3) is PeerState.UNHEALTHY

    @pytest.mark.parametrize("kind", sorted(FATAL_KINDS))
    def test_fatal_kinds_jump_straight_to_unhealthy(self, kind):
        led = HealthLedger(seed=0)
        assert led.record(kind, 1) is PeerState.UNHEALTHY

    def test_suspect_clears_but_strikes_persist(self):
        """Flap damping: a clean streak clears SUSPECT, but the strike
        count survives — the next failure condemns immediately instead
        of re-entering the suspect/clear livelock."""
        led = HealthLedger(seed=0, suspect_clears=2)
        led.record("transport_error", 5)
        assert led.observe_clean(5) is PeerState.SUSPECT
        assert led.observe_clean(5) is PeerState.HEALTHY
        assert led.record("transport_error", 5) is PeerState.UNHEALTHY

    def test_probation_and_probe_promotion(self):
        led = HealthLedger(seed=0, probation_after=2, promote_after=2,
                           probe_interval=3)
        led.record("watchdog_trip", 2)
        assert led.observe_clean(2) is PeerState.UNHEALTHY
        assert led.observe_clean(2) is PeerState.PROBATION
        # probes fire only in PROBATION, on the seeded schedule
        due = [s for s in range(12) if led.probe_due(2, s)]
        assert due and all(
            (s - due[0]) % 3 == 0 for s in due
        ), due
        assert led.probe_result(2, True) is PeerState.PROBATION
        assert led.probe_result(2, True) is PeerState.HEALTHY
        # promotion forgives strikes: one new soft failure is SUSPECT
        assert led.record("transport_error", 2) is PeerState.SUSPECT

    def test_probe_failure_drops_back_to_unhealthy(self):
        led = HealthLedger(seed=0, probation_after=1)
        led.record("slice_death", "slice:1")
        led.observe_clean("slice:1")
        assert led.state("slice:1") is PeerState.PROBATION
        assert led.probe_result("slice:1", False) is PeerState.UNHEALTHY
        assert not led.probe_due("slice:1", 0)

    def test_clean_observation_on_healthy_peer_is_identity(self):
        led = HealthLedger(seed=0)
        assert led.observe_clean("never-seen") is PeerState.HEALTHY
        assert "never-seen" not in led.peers()

    def test_unhealthy_queries_split_ranks_slices_and_sites(self):
        led = HealthLedger(seed=0)
        led.record("watchdog_trip", 3)
        led.record("watchdog_trip", 1)
        led.record("slice_death", "slice:1")
        led.record("kernel_error", "site:serving_step")
        assert led.unhealthy_peers() == (1, 3)
        assert led.unhealthy_slices() == (1,)
        snap = led.snapshot()
        assert snap["site:serving_step"]["state"] == "unhealthy"
        assert snap["3"]["last"] == "watchdog_trip"

    def test_to_fault_plan_fills_unhealthy_peers(self):
        led = HealthLedger(seed=7)
        led.record("watchdog_trip", 4)
        led.record("kernel_error", "site:serving_step")  # not a rank
        base = FaultPlan(seed=7, faults=(Stall(site="allgather", rank=1),),
                         unhealthy_peers=(2,))
        plan = led.to_fault_plan(base)
        assert plan.unhealthy_peers == (2, 4)
        assert plan.faults == base.faults  # faults preserved


class TestDeterminism:
    SIGNALS = [
        ("transport_error", "site:kv_ship", 1),
        ("watchdog_trip", 3, 4),
        ("transport_error", "site:kv_ship", 6),
        ("slice_death", "slice:1", 9),
    ]

    def _drive(self, led):
        for kind, peer, step in self.SIGNALS:
            led.record(kind, peer, step=step)
        for s in range(10, 16):
            led.observe_clean("site:kv_ship", step=s)

    def test_same_seed_same_story(self):
        """Two ledgers fed the identical signal sequence agree on every
        state, every snapshot field, and every probe step."""
        a, b = HealthLedger(seed=5), HealthLedger(seed=5)
        self._drive(a)
        self._drive(b)
        assert a.snapshot() == b.snapshot()
        sched_a = [s for s in range(40) if a.probe_due("site:kv_ship", s)]
        sched_b = [s for s in range(40) if b.probe_due("site:kv_ship", s)]
        assert sched_a == sched_b and sched_a

    def test_different_seed_different_probe_phase(self):
        """The probe phase is (seed, peer)-keyed: across a handful of
        peers two seeds cannot agree on every phase."""
        a, b = HealthLedger(seed=0), HealthLedger(seed=1)
        phases_a = [a._phase(p) for p in range(8)]
        phases_b = [b._phase(p) for p in range(8)]
        assert phases_a != phases_b

    def test_backoff_jitter_is_seeded(self):
        a, b = HealthLedger(seed=3), HealthLedger(seed=3)
        assert a.uniform("ship_backoff", 4, 1) == b.uniform(
            "ship_backoff", 4, 1)
        assert 0.0 <= a.uniform("x") < 1.0


# ------------------------------------------------------------- mesh shrink


class TestReplanMesh:
    def test_rank_removal_matches_handbuilt_mesh_numerically(self):
        """n−1 shrink: the replanned mesh runs a psum numerically equal
        to the same collective hand-built over the surviving devices —
        and the ledger's verdict rides along as the fault plan."""
        devs = jax.devices()
        assert len(devs) == 8
        mesh = Mesh(np.asarray(devs), ("x",))
        led = HealthLedger(seed=0)
        led.record("watchdog_trip", 3)
        rp = replan_mesh(mesh, led)
        assert rp.removed_ranks == (3,)
        assert rp.survivors == (0, 1, 2, 4, 5, 6, 7)
        assert rp.plan.unhealthy_peers == (3,)
        assert tuple(rp.mesh.devices.ravel()) == tuple(
            d for i, d in enumerate(devs) if i != 3)

        vals = np.arange(8.0, dtype=np.float32)
        surv_vals = vals[list(rp.survivors)]

        def total(x):
            return jax.lax.psum(x, "x")

        from jax.sharding import PartitionSpec as P

        out = jax.jit(jax.shard_map(
            total, mesh=rp.mesh, in_specs=P("x"), out_specs=P("x"),
        ))(jnp.asarray(surv_vals))
        twin = jax.jit(jax.shard_map(
            total, mesh=Mesh(np.asarray([devs[i] for i in rp.survivors]),
                             ("x",)),
            in_specs=P("x"), out_specs=P("x"),
        ))(jnp.asarray(surv_vals))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(twin))
        assert float(np.asarray(out)[0]) == surv_vals.sum()

    def test_slice_removal_on_hybrid_mesh(self):
        devs = jax.devices()
        hybrid = Mesh(np.asarray(devs).reshape(2, 4), ("dcn", "x"))
        led = HealthLedger(seed=0)
        led.record("slice_death", "slice:1")
        rp = replan_mesh(hybrid, led)
        assert rp.removed_slices == (1,)
        assert rp.removed_ranks == (4, 5, 6, 7)
        assert rp.survivors == (0, 1, 2, 3)
        assert rp.mesh.devices.shape == (1, 4)
        assert rp.mesh.axis_names == ("dcn", "x")

    def test_uncovered_rank_on_multiaxis_mesh_refuses(self):
        """A bad rank inside a surviving slice cannot be excised from a
        2-D mesh without leaving it ragged — replan refuses loudly."""
        devs = jax.devices()
        hybrid = Mesh(np.asarray(devs).reshape(2, 4), ("dcn", "x"))
        led = HealthLedger(seed=0)
        led.record("slice_death", "slice:1")
        led.record("watchdog_trip", 2)   # rank 2 lives in slice 0
        with pytest.raises(ValueError, match="containing slice"):
            replan_mesh(hybrid, led)
        led2 = HealthLedger(seed=0)
        led2.record("watchdog_trip", 5)  # rank 5 IS covered by slice 1
        led2.record("slice_death", "slice:1")
        rp = replan_mesh(hybrid, led2)
        assert rp.removed_ranks == (4, 5, 6, 7)

    def test_nothing_survives_refuses(self):
        devs = jax.devices()
        mesh = Mesh(np.asarray(devs[:2]).reshape(2, 1), ("dcn", "x"))
        led = HealthLedger(seed=0)
        led.record("slice_death", "slice:0")
        led.record("slice_death", "slice:1")
        with pytest.raises(ValueError, match="nothing survives"):
            replan_mesh(mesh, led)

    def test_preflight_refuses_on_live_unhealthy_ledger(self):
        """The ops preflight consults every live ledger: an UNHEALTHY
        collective rank anywhere refuses the fused path with a reason
        naming the re-plan escape hatch — no fault plan declared."""
        from triton_distributed_tpu.ops import (
            create_ag_gemm_context,
            preflight,
        )

        devs = jax.devices()
        mesh = Mesh(np.asarray(devs), ("x",))
        ctx = create_ag_gemm_context(mesh, "x")
        a = jnp.ones((64, 32), jnp.float32)
        b = jnp.ones((32, 128), jnp.float32)
        led = HealthLedger(seed=0)
        led.record("watchdog_trip", 2)
        reason = preflight(ctx, "ag_gemm", a, b)
        assert reason is not None and "health ledger" in reason
        assert "replan_mesh" in reason
        del led, reason
        gc.collect()
        assert not any(
            l.unhealthy_peers() for l in health.live_ledgers())


# ----------------------------------------------- multi-slice trip merging


class TestMultiSliceTripAggregation:
    def _summaries(self):
        clean = TripSummary(slice_index=0)
        waiting = TripSummary(
            slice_index=0, site="allgather", collective_id="('ag', 0)",
            n=4, entered=(0, 1, 2, 3), exited=(0, 1, 2, 3), gated=(),
            open_s=2.5,
        )
        wedged = TripSummary(
            slice_index=1, site="allgather", collective_id="('ag', 0)",
            n=4, entered=(0, 1, 2, 3), exited=(0, 1), gated=(2,),
            open_s=2.5,
        )
        return clean, waiting, wedged

    def test_merge_names_the_wedged_slice(self):
        clean, waiting, wedged = self._summaries()
        report, bad = merge_trip_summaries([clean, wedged])
        assert bad == (1,)
        assert "wedged slice [1]" in report and "slice 0: clean" in report

    def test_waiting_slice_is_not_wedged(self):
        """A slice whose ranks all exited (it tripped merely waiting on
        the wedged peer) is exonerated by the merge."""
        _, waiting, wedged = self._summaries()
        report, bad = merge_trip_summaries([waiting, wedged])
        assert bad == (1,)
        assert not waiting.wedged and wedged.wedged

    def test_report_merged_trip_feeds_the_ledger(self):
        led = HealthLedger(seed=0)
        clean, _, wedged = self._summaries()
        report = report_merged_trip([clean, wedged])
        assert "wedged slice [1]" in report
        assert led.unhealthy_slices() == (1,)
        assert led.state("slice:1") is PeerState.UNHEALTHY

    def test_summary_json_round_trip(self):
        _, _, wedged = self._summaries()
        back = TripSummary.from_json(wedged.to_json())
        assert back == wedged

    def test_exchange_is_identity_single_process(self):
        from triton_distributed_tpu.runtime.multislice import (
            exchange_trip_summaries,
        )

        _, _, wedged = self._summaries()
        assert exchange_trip_summaries(wedged) == [wedged]

    def test_host_instrument_trip_lands_in_ledger(self):
        """Satellite pin: a stalled kv_ship under an armed watchdog
        trips, and the trip report — parsed by every live ledger —
        condemns the ship site (n=1 host instrument: the site, not a
        mesh rank)."""
        from triton_distributed_tpu.lang.launch import maybe_instrument

        led = HealthLedger(seed=0)
        plan = FaultPlan(seed=0, faults=(Stall(site="kv_ship", rank=0),))
        with faults.fault_plan(plan):
            with pytest.raises(WatchdogTimeout):
                with watchdog.collective_watchdog(deadline=0.2):
                    fn = maybe_instrument(
                        lambda: 1, axis=None, site="kv_ship",
                        collective_id=("kv_ship", 0), n=1,
                    )
                    assert fn() == 1   # stall released by the trip
        assert led.state("site:kv_ship") is PeerState.UNHEALTHY
        assert led.unhealthy_peers() == ()   # host rank 0 is not a peer


# -------------------------------------------------------- serving engines

CFG = dict(
    vocab=128, n_layers=2, hidden=64, ffn=128,
    n_heads=4, n_kv_heads=2, head_dim=16,
    dtype=jnp.float32, param_dtype=jnp.float32, kv_quant="int8",
)


@pytest.fixture(scope="module")
def roles1():
    devs = jax.devices()
    return (Mesh(np.asarray(devs[:1]), ("tp",)),
            Mesh(np.asarray(devs[1:2]), ("tp",)),
            Mesh(np.asarray(devs[:2]).reshape(2, 1), ("dcn", "tp")))


@pytest.fixture(scope="module")
def models1(roles1):
    mesh_p, mesh_d, _ = roles1
    mp = Transformer(TransformerConfig(**CFG), mesh_p, "tp", ())
    md = Transformer(TransformerConfig(**CFG), mesh_d, "tp", ())
    params = mp.init(jax.random.PRNGKey(0))
    pp = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                      mp.shardings())
    pd = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                      md.shardings())
    return mp, pp, md, pd


def _fast_ledger(seed=0):
    """Tight thresholds so probation/promotion fit a short trace."""
    return HealthLedger(seed=seed, probation_after=1, promote_after=1,
                        probe_interval=2)


class TestKernelProbation:
    def test_single_failure_degrades_then_probe_repromotes(
            self, models1, monkeypatch):
        """One injected Pallas failure is FATAL (kernel_error): the
        engine rides the XLA twin, earns probation with clean steps,
        and a seeded probe re-promotes it to the fused path — tokens
        identical to an untouched run throughout."""
        import triton_distributed_tpu.kernels.ragged_paged_attention as rpa

        mp, pp, *_ = models1
        real = rpa.ragged_paged_attention
        calls = {"n": 0}

        def flaky(*a, **k):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected kernel failure")
            return real(*a, **k)

        monkeypatch.setattr(rpa, "ragged_paged_attention", flaky)
        eng = ServingEngine(
            mp, pp,
            EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                         npages=16),
            health=_fast_ledger(),
        )
        req = Request(rid=0, prompt=np.arange(9, dtype=np.int32),
                      max_new=12, arrival=0.0)
        stats = eng.run([req], max_steps=80)
        assert calls["n"] >= 2
        assert stats.repromotions >= 1
        assert eng.use_pallas and not stats.degraded
        assert eng.health.state(eng.health_peer) is PeerState.HEALTHY
        # token-exact across degrade + re-promotion
        ref = ServingEngine(
            mp, pp,
            EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                         npages=16),
        )
        ref_req = Request(rid=0, prompt=np.arange(9, dtype=np.int32),
                          max_new=12, arrival=0.0)
        ref.run([ref_req], max_steps=80)
        assert req.generated == ref_req.generated

    def test_always_failing_kernel_stays_demoted(self, models1,
                                                 monkeypatch):
        """Probes against a still-broken kernel FAIL back to UNHEALTHY:
        the engine never flaps onto a path that keeps breaking."""
        import triton_distributed_tpu.kernels.ragged_paged_attention as rpa

        mp, pp, *_ = models1
        calls = {"n": 0}

        def boom(*a, **k):
            calls["n"] += 1
            raise RuntimeError("still broken")

        monkeypatch.setattr(rpa, "ragged_paged_attention", boom)
        # shapes distinct from the re-promotion test above: the model's
        # step jit is cached per (width, block) and a cache hit would
        # replay the REAL kernel captured at an earlier trace
        eng = ServingEngine(
            mp, pp,
            EngineConfig(slots=2, token_budget=24, chunk=6, page=8,
                         npages=16),
            health=_fast_ledger(),
        )
        req = Request(rid=0, prompt=np.arange(11, dtype=np.int32),
                      max_new=8, arrival=0.0)
        stats = eng.run([req], max_steps=60)
        assert stats.degraded and not eng.use_pallas
        assert stats.repromotions == 0
        assert calls["n"] >= 2   # the probe retried the broken path
        assert all(r.done for r in [req])


class TestTransportRetries:
    def test_transient_dcn_failures_absorbed_by_retries(
            self, models1, roles1, monkeypatch):
        mp, pp, md, pd = models1
        _, _, hybrid = roles1
        monkeypatch.setenv("TDTPU_SHIP_RETRIES", "3")
        monkeypatch.setenv("TDTPU_SHIP_BACKOFF", "0.001")
        eng = DisaggregatedEngine(
            mp, pp, md, pd,
            EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                         npages=16),
            hybrid_mesh=hybrid, dcn_axis="dcn", transport="dcn",
            ship_delay_steps=1, health=_fast_ledger(),
        )
        calls = {"n": 0}

        def flaky(qpay, spay):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise RuntimeError("transient wire failure")
            return "landed"

        eng._transport_dcn = flaky
        assert eng._dcn_with_retries(None, None) == "landed"
        assert eng.stats.ship_retries == 2
        assert not eng.stats.degraded_transport

    def test_exhausted_retries_return_none(self, models1, roles1,
                                           monkeypatch):
        mp, pp, md, pd = models1
        _, _, hybrid = roles1
        monkeypatch.setenv("TDTPU_SHIP_RETRIES", "2")
        monkeypatch.setenv("TDTPU_SHIP_BACKOFF", "0.001")
        eng = DisaggregatedEngine(
            mp, pp, md, pd,
            EngineConfig(slots=2, token_budget=32, chunk=8, page=8,
                         npages=16),
            hybrid_mesh=hybrid, dcn_axis="dcn", transport="dcn",
            ship_delay_steps=1, health=_fast_ledger(),
        )

        def broken(qpay, spay):
            raise RuntimeError("wire down")

        eng._transport_dcn = broken
        assert eng._dcn_with_retries(None, None) is None
        assert eng.stats.ship_retries == 1   # attempts - 1


class TestServingFailover:
    ECFG = dict(slots=4, token_budget=48, chunk=16, page=8, npages=32)
    TRACE = dict(seed=9, n_requests=6, mean_interarrival=0.7,
                 len_lo=8, len_hi=30, max_new_lo=3, max_new_hi=6,
                 vocab=128)

    def _reference(self, models1):
        mp, pp, *_ = models1
        trace = poisson_trace(**self.TRACE)
        ServingEngine(mp, pp, EngineConfig(**self.ECFG)).run(
            trace, max_steps=500)
        return trace

    def _engine(self, models1, roles1, **kw):
        mp, pp, md, pd = models1
        _, _, hybrid = roles1
        return DisaggregatedEngine(
            mp, pp, md, pd, EngineConfig(**self.ECFG),
            hybrid_mesh=hybrid, dcn_axis="dcn", transport="dcn",
            ship_delay_steps=2, health=_fast_ledger(), **kw,
        )

    @pytest.mark.parametrize("dead_slice,role", [(1, "decode"),
                                                 (0, "prefill")])
    def test_slice_death_failover_token_exact(self, models1, roles1,
                                              dead_slice, role):
        """The acceptance pin: a role slice dies mid-trace; the
        survivor finishes the full Poisson trace — zero lost requests,
        token streams equal the fault-free colocated engine's."""
        ref = self._reference(models1)
        trace = poisson_trace(**self.TRACE)
        eng = self._engine(models1, roles1)
        plan = FaultPlan(
            seed=1, faults=(SliceDeath(slice=dead_slice, step=5),))
        with faults.fault_plan(plan):
            stats = eng.run(trace, max_ticks=800)
        assert stats.completed == self.TRACE["n_requests"]
        assert all(r.done for r in trace)
        fo = stats.failover
        assert fo is not None and fo["role"] == role
        assert fo["tick"] == 5 and fo["recovery_tick"] is not None
        assert eng.health.state(f"slice:{dead_slice}") \
            is PeerState.UNHEALTHY
        for a, b in zip(ref, trace):
            assert a.generated == b.generated, a.rid

    def test_decode_death_preserves_inflight_kv(self, models1, roles1):
        """Requests parked for (or inside) a ship when the decode slice
        dies keep their prefilled KV — it lives in the SURVIVOR's pool —
        so they resume decoding in place instead of re-prefilling."""
        trace = poisson_trace(**self.TRACE)
        eng = self._engine(models1, roles1)
        seen_inflight = {}

        real_check = eng._check_slice_deaths

        def spy():
            if eng._dead_role is None:
                seen_inflight["at_death"] = (
                    len(eng._inflight) + len(eng._ready))
            real_check()

        eng._check_slice_deaths = spy
        plan = FaultPlan(seed=1, faults=(SliceDeath(slice=1, step=4),))
        with faults.fault_plan(plan):
            stats = eng.run(trace, max_ticks=800)
        assert stats.completed == self.TRACE["n_requests"]
        # requeued counts only the re-prefill cohort; anything in a
        # ship at death decodes in place on the survivor
        assert stats.failover["requeued"] <= self.TRACE["n_requests"]
        assert stats.failover["re_prefill_tokens"] >= 0

    def test_transient_ship_stall_degrades_then_repromotes(
            self, models1, roles1):
        """Satellite 2+3 pin: a persistent kv_ship stall gate under an
        armed watchdog trips on the FIRST ship (releasing it), the
        transport degrades onto the XLA twin, and — the trip being
        stale for the rest of the arming — a probation probe re-promotes
        the DCN wire. Zero lost requests, final state un-degraded."""
        trace = poisson_trace(**self.TRACE)
        eng = self._engine(models1, roles1)
        plan = FaultPlan(seed=1, faults=(Stall(site="kv_ship", rank=0),))
        box = {}
        with faults.fault_plan(plan):
            with pytest.raises(WatchdogTimeout):
                with watchdog.collective_watchdog(deadline=0.3):
                    box["stats"] = eng.run(trace, max_ticks=800)
        stats = box["stats"]
        assert stats.completed == self.TRACE["n_requests"]
        assert stats.transport_repromotions >= 1
        assert eng.transport == "dcn"
        assert not stats.degraded_transport
        assert eng.health.state("site:kv_ship") is PeerState.HEALTHY

    def test_both_slices_dead_refuses(self, models1, roles1):
        eng = self._engine(models1, roles1)
        trace = poisson_trace(**self.TRACE)
        plan = FaultPlan(seed=1, faults=(SliceDeath(slice=0, step=2),
                                         SliceDeath(slice=1, step=2)))
        with faults.fault_plan(plan):
            with pytest.raises(RuntimeError, match="no survivor"):
                eng.run(trace, max_ticks=800)

    def test_placement_refuses_condemned_slice(self, models1):
        """The perf-model placement gate consults the ledger: a split
        topology cannot place a role on a condemned slice."""
        from triton_distributed_tpu.tune.perf_model import (
            refuse_disaggregation,
        )

        mp, *_ = models1
        led = HealthLedger(seed=0)
        led.record("slice_death", "slice:1")
        reason = refuse_disaggregation(
            mp.config, 8, {"prompt_len": 64, "max_new": 8}, None,
            ledger=led,
        )
        assert reason is not None and "condemned slice" in reason


# ----------------------------------------------------------------- lint


class TestDegradationDeclarations:
    def test_every_family_declares_a_resolvable_target(self):
        """bench --lint's gate, asserted directly: every registered
        kernel family names a degradation target and every target
        resolves to a real callable."""
        from triton_distributed_tpu.kernels.registry import (
            families,
            missing_degradation_targets,
        )

        fams = families().values()
        assert fams and all(f.degrades_to for f in fams)
        assert missing_degradation_targets() == ()
