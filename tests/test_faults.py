"""Robustness subsystem: fault-plan engine, collective watchdog,
graceful degradation, bootstrap retry.

The acceptance properties (ISSUE 1):

* a single-peer stall on the ring allgather is DETECTED by the watchdog
  within its deadline and raises with rank/collective_id/semaphore
  diagnostics — no hang;
* the same ``FaultPlan`` seed reproduces the identical injected fault
  sequence across two runs, and delay-injected collectives stay
  bit-correct;
* a forced preflight failure on ``ag_gemm`` demotes to the XLA-native
  path and returns numerically identical results.

Tests that need the Pallas TPU-simulation interpreter are split from
those that run anywhere (the watchdog, stall gates and degradation
layer are host-side and engine-agnostic — on a jax without the
simulator they are exercised through the instrumented XLA fallback
engines instead).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import HAS_TPU_SIM, requires_tpu_sim

from triton_distributed_tpu.runtime import (
    AllGatherMethod,
    Corrupt,
    Delay,
    FaultPlan,
    SignalFault,
    Stall,
    WatchdogTimeout,
    collective_watchdog,
    fault_plan,
)
from triton_distributed_tpu.runtime import faults, watchdog
from triton_distributed_tpu.utils import assert_allclose

#: tier-1 fast subset (ci/fast.sh): the fault-engine half of the robustness story
pytestmark = pytest.mark.fast


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """No plan/trip state may leak between tests (the trip record is
    deliberately sticky for the degradation probe)."""
    yield
    faults.set_fault_plan(None)
    watchdog.clear_trip()


# ------------------------------------------------------------- plan engine


class TestFaultPlan:
    def test_schedule_deterministic_under_seed(self):
        mk = lambda seed: FaultPlan(seed=seed, faults=(
            Delay(site="allgather", jitter=0.75, cycles=50_000),
            SignalFault(site="allgather", rank=2, kind="dup"),
            Corrupt(site="allgather", rank=5, word=7, value=9.0),
            Stall(site="allgather", rank=3),
        ))
        s1 = mk(7).schedule("allgather", n=8, steps=7)
        s2 = mk(7).schedule("allgather", n=8, steps=7)
        s3 = mk(8).schedule("allgather", n=8, steps=7)
        assert s1 == s2, "same seed must replay the identical schedule"
        assert s1 != s3, "a different seed must draw different delays"
        # structural faults are seed-independent but present
        kinds = {e[0] for e in s1}
        assert kinds == {"delay", "signal", "corrupt", "stall"}

    def test_site_and_rank_matching(self):
        plan = FaultPlan(seed=0, faults=(
            Delay(site="gemm_rs", rank=1, step=2, cycles=1000, jitter=0.0),
        ))
        assert plan.delay_cycles("gemm_rs", 2, 4) == (0, 1000, 0, 0)
        assert plan.delay_cycles("gemm_rs", 1, 4) == (0, 0, 0, 0)
        assert plan.delay_cycles("allgather", 2, 4) == (0, 0, 0, 0)
        assert plan.signal_factor("gemm_rs", 1) == 1  # no signal faults

    def test_signal_and_corrupt_queries(self):
        plan = FaultPlan(seed=0, faults=(
            SignalFault(site="*", rank=3, kind="drop"),
            Corrupt(site="all_to_all", rank=1, word=4, value=2.5),
        ))
        assert plan.signal_factor("reduce_scatter", 3) == 0
        assert plan.signal_factor("reduce_scatter", 2) == 1
        assert plan.corruption("all_to_all", 1) == (4, 2.5)
        assert plan.corruption("all_to_all", 2) is None

    def test_invalid_faults_rejected(self):
        with pytest.raises(TypeError):
            FaultPlan(faults=("not a fault",))
        with pytest.raises(ValueError):
            FaultPlan(faults=(SignalFault(kind="replay"),))

    def test_plan_participates_in_trace_cache_key(self):
        from triton_distributed_tpu.config import interp_key

        base = interp_key()
        with fault_plan(FaultPlan(seed=1)):
            armed = interp_key()
        assert armed != base, "activating a plan must invalidate builds"
        assert interp_key() == base, "deactivation must restore the key"

    def test_nested_plans_rejected(self):
        with fault_plan(FaultPlan(seed=1)):
            with pytest.raises(RuntimeError, match="already active"):
                with fault_plan(FaultPlan(seed=2)):
                    pass


# ------------------------------------------------------------- stall cap


class TestStallCap:
    def test_matrix_larger_than_cap_skips_excess(self, monkeypatch):
        """ISSUE satellite: a 5-stall matrix under
        ``max_concurrent_stalls=2`` holds at most 2 gates; the other 3
        stall_wait calls return immediately instead of parking worker
        threads (the 2-vCPU CI wedge the cap exists to prevent)."""
        monkeypatch.setenv("TDTPU_STALL_TIMEOUT", "20")
        plan = FaultPlan(
            seed=0,
            faults=tuple(Stall(site=f"cap{i}", rank=0) for i in range(5)),
            max_concurrent_stalls=2,
        )
        done: list = []

        def worker(i):
            faults.stall_wait(f"cap{i}", 0)
            done.append(i)

        with fault_plan(plan):
            threads = [
                threading.Thread(target=worker, args=(i,), daemon=True)
                for i in range(5)
            ]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 10
            while len(done) < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
            # 3 of 5 skipped promptly; exactly the cap's worth held
            assert len(done) == 3, f"over-cap stalls did not skip: {done}"
            assert faults.held_stalls() == 2
            faults.release_stalls()
            for t in threads:
                t.join(timeout=10)
            assert not any(t.is_alive() for t in threads)
        assert faults.held_stalls() == 0, "held count must drain to zero"

    def test_uncapped_plan_holds_all(self, monkeypatch):
        """Without a cap every matching stall parks (the pre-cap
        behaviour chaos tests rely on)."""
        monkeypatch.setenv("TDTPU_STALL_TIMEOUT", "20")
        plan = FaultPlan(
            seed=0,
            faults=tuple(Stall(site=f"unc{i}", rank=0) for i in range(3)),
        )
        with fault_plan(plan):
            threads = [
                threading.Thread(
                    target=faults.stall_wait, args=(f"unc{i}", 0),
                    daemon=True,
                )
                for i in range(3)
            ]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 10
            while faults.held_stalls() < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert faults.held_stalls() == 3
            faults.release_stalls()
            for t in threads:
                t.join(timeout=10)
        assert faults.held_stalls() == 0

    def test_cap_in_trace_key(self):
        """Changing the cap must invalidate cached kernel builds, same
        as any other plan field."""
        a = FaultPlan(seed=1, max_concurrent_stalls=2)
        b = FaultPlan(seed=1, max_concurrent_stalls=3)
        assert a.key() != b.key()


# ------------------------------------------------------------ plan replay


class TestParsePlan:
    """bench --faults replay: a nightly chaos line round-trips back
    into the plan that produced it."""

    def test_compact_format(self):
        plan = faults.parse_plan(
            "seed=7; Delay(site=allgather, rank=2, cycles=50000); "
            "Stall(site=ag_gemm, rank=3); max_concurrent_stalls=2"
        )
        assert plan == FaultPlan(
            seed=7,
            faults=(
                Delay(site="allgather", rank=2, cycles=50000),
                Stall(site="ag_gemm", rank=3),
            ),
            max_concurrent_stalls=2,
        )

    def test_json_format(self):
        plan = faults.parse_plan(
            '{"seed": 7, "faults": [{"kind": "Delay", "site": '
            '"allgather", "cycles": 50000}], "max_concurrent_stalls": 2}'
        )
        assert plan == FaultPlan(
            seed=7,
            faults=(Delay(site="allgather", cycles=50000),),
            max_concurrent_stalls=2,
        )

    def test_repr_roundtrip(self):
        """The compact format is the dataclass reprs joined by ';' —
        exactly what a nightly log line carries."""
        plan = FaultPlan(
            seed=11,
            faults=(
                SignalFault(site="allgather", rank=1, kind="drop"),
                Corrupt(site="gemm_rs", rank=2, word=3, value=5.0),
            ),
            max_concurrent_stalls=1,
        )
        line = "seed=11; " + "; ".join(
            repr(f) for f in plan.faults
        ) + "; max_concurrent_stalls=1"
        assert faults.parse_plan(line) == plan

    def test_unknown_fault_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults.parse_plan("Frob(site=allgather)")

    def test_garbage_segment_rejected(self):
        with pytest.raises(ValueError, match="cannot parse"):
            faults.parse_plan("seed=1; what even is this")


# ---------------------------------------------------------------- watchdog


def _ag_method():
    """The ring allgather when the simulator exists; the (equally
    instrumented) XLA fallback engine otherwise."""
    return (
        AllGatherMethod.RING_1D if HAS_TPU_SIM
        else AllGatherMethod.XLA_FALLBACK
    )


@pytest.mark.chaos
class TestWatchdog:
    def test_detects_single_peer_stall_and_raises(self, mesh8):
        """ISSUE acceptance: a stalled peer on the allgather is detected
        within the deadline and the raise carries rank, collective_id
        and semaphore expected-vs-observed diagnostics — the test
        completes (bounded) instead of wedging."""
        from triton_distributed_tpu.kernels import all_gather

        x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
        plan = FaultPlan(seed=0, faults=(Stall(site="allgather", rank=3),))
        t0 = time.monotonic()
        with fault_plan(plan):
            with pytest.raises(WatchdogTimeout) as exc:
                with collective_watchdog(deadline=1.5):
                    y = all_gather(
                        x, mesh8, "x", method=_ag_method(), collective_id=2
                    )
                    np.asarray(y)       # force completion inside the guard
        elapsed = time.monotonic() - t0
        msg = str(exc.value)
        assert "collective_id=2" in msg
        assert "rank" in msg and "[3]" in msg          # the stalled rank
        assert "semaphore" in msg and "expected 7" in msg
        assert "FaultPlan" in msg and "Stall" in msg   # active plan dumped
        assert elapsed < 30, f"watchdog did not bound the stall: {elapsed}s"
        # the trip is sticky for the degradation probe until cleared
        assert watchdog.last_trip() is not None

    def test_stall_released_run_completes_correctly(self, mesh8):
        """After the watchdog releases the stall gate, the collective
        itself completes with correct data (the stall delays, it does
        not corrupt)."""
        from triton_distributed_tpu.kernels import all_gather

        x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
        plan = FaultPlan(seed=0, faults=(Stall(site="allgather", rank=1),))
        got = {}
        with fault_plan(plan):
            try:
                with collective_watchdog(deadline=1.0):
                    got["y"] = np.asarray(all_gather(
                        x, mesh8, "x", method=_ag_method(), collective_id=2
                    ))
            except WatchdogTimeout:
                pass
        np.testing.assert_array_equal(got["y"], np.asarray(x))

    def test_clean_run_does_not_trip(self, mesh8):
        from triton_distributed_tpu.kernels import all_gather

        x = jnp.ones((64, 128), jnp.float32)
        with collective_watchdog(deadline=30.0):
            y = np.asarray(all_gather(x, mesh8, "x", method=_ag_method()))
        np.testing.assert_array_equal(y, np.ones((64, 128), np.float32))
        assert watchdog.last_trip() is None

    def test_double_arming_rejected(self):
        with collective_watchdog(deadline=30.0):
            with pytest.raises(RuntimeError, match="already armed"):
                with collective_watchdog(deadline=30.0):
                    pass

    def test_hostlevel_trip_without_any_engine(self):
        """Watchdog core without jax in the loop: heartbeats driven by
        hand, a stalled rank held on the plan gate from a worker thread.
        The monitor must trip, dump diagnostics and release the gate."""
        plan = FaultPlan(seed=0, faults=(Stall(site="unit", rank=2),))
        with fault_plan(plan):
            with pytest.raises(WatchdogTimeout) as exc:
                with collective_watchdog(deadline=0.3, poll=0.02):
                    for r in (0, 1):
                        watchdog._hb_enter("unit", 99, 4, r)
                        watchdog._hb_exit("unit", 99, 4, r, None)
                    t = threading.Thread(
                        target=watchdog._hb_enter, args=("unit", 99, 4, 2)
                    )
                    t.start()
                    t.join(timeout=20)
                    assert not t.is_alive(), "gate was never released"
        msg = str(exc.value)
        assert "'unit'" in msg and "collective_id=99" in msg
        assert "stalled at fault-plan entry gate" in msg and "[2]" in msg

    def test_stall_timeout_backstop_without_watchdog(self, monkeypatch):
        """A stall with NO watchdog armed must not wedge forever: the
        TDTPU_STALL_TIMEOUT backstop lets the rank proceed."""
        monkeypatch.setenv("TDTPU_STALL_TIMEOUT", "0.2")
        plan = FaultPlan(seed=0, faults=(Stall(site="unit2", rank=0),))
        t0 = time.monotonic()
        with fault_plan(plan):
            faults.stall_wait("unit2", 0)      # blocks ~0.2s, then returns
        assert 0.1 < time.monotonic() - t0 < 5.0


# ---------------------------------------------------- injection end-to-end


@pytest.mark.chaos
class TestInjectionEndToEnd:
    @requires_tpu_sim
    def test_delay_plan_bit_correct_and_deterministic(self, mesh8):
        """Seeded per-(rank, step) delays widen race windows without
        changing results, twice over (ISSUE acceptance: same seed →
        identical sequence; collectives stay bit-correct)."""
        from triton_distributed_tpu.kernels import all_gather

        x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
        plan = FaultPlan(seed=11, faults=(
            Delay(site="allgather", jitter=0.9, cycles=80_000),
        ))
        outs = []
        for _ in range(2):
            with fault_plan(plan):
                outs.append(np.asarray(all_gather(
                    x, mesh8, "x", method=AllGatherMethod.RING_1D
                )))
        np.testing.assert_array_equal(outs[0], np.asarray(x))
        np.testing.assert_array_equal(outs[0], outs[1])

    @requires_tpu_sim
    def test_corruption_deterministic_under_seed(self, mesh8):
        """A corruption fault visibly lands (the result differs from
        truth at the targeted shard) and is bit-identical across two
        runs of the same plan — injected faults replay exactly."""
        from triton_distributed_tpu.kernels import all_gather

        x = jnp.ones((64, 128), jnp.float32)
        plan = FaultPlan(seed=3, faults=(
            Corrupt(site="allgather", rank=3, word=5, value=123.0),
        ))
        runs = []
        for _ in range(2):
            with fault_plan(plan):
                runs.append(np.asarray(all_gather(
                    x, mesh8, "x", method=AllGatherMethod.LL_SMALL
                )))
        assert not np.array_equal(runs[0], np.ones((64, 128), np.float32)), \
            "corruption fault never landed"
        # rank 3's shard head word is the corrupted one
        assert runs[0][3 * 8, 5] == 123.0
        np.testing.assert_array_equal(runs[0], runs[1])


# ------------------------------------------------------------- degradation


class TestGracefulDegradation:
    def test_ag_gemm_demotes_on_unhealthy_peer(self, mesh8):
        """ISSUE acceptance: a forced preflight failure demotes ag_gemm
        to the XLA-native path with allclose-identical results."""
        from triton_distributed_tpu.ops import (
            ag_gemm, ag_gemm_safe, create_ag_gemm_context, preflight,
        )

        a = jax.random.normal(jax.random.PRNGKey(1), (64, 32), jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(2), (32, 128), jnp.float32)
        ctx = create_ag_gemm_context(mesh8, "x")
        healthy = np.asarray(ag_gemm(a, b, ctx), np.float32)

        plan = FaultPlan(seed=0, unhealthy_peers=(3,))
        with fault_plan(plan):
            reason = preflight(ctx, "ag_gemm", a, b)
            assert reason is not None and "unhealthy" in reason
            demoted = np.asarray(ag_gemm_safe(a, b, ctx), np.float32)
        assert_allclose(demoted, healthy, atol=1e-5, rtol=1e-5)
        # and the demotion is transient: plan cleared -> fused again
        assert preflight(ctx, "ag_gemm", a, b) is None or not HAS_TPU_SIM

    def test_gemm_rs_demotes_on_watchdog_trip(self, mesh8):
        from triton_distributed_tpu.ops import (
            create_gemm_rs_context, gemm_rs, gemm_rs_safe, preflight,
        )

        a = jax.random.normal(jax.random.PRNGKey(3), (64, 32), jnp.float32)
        b = jax.random.normal(jax.random.PRNGKey(4), (32, 128), jnp.float32)
        ctx = create_gemm_rs_context(mesh8, "x")
        healthy = np.asarray(gemm_rs(a, b, ctx), np.float32)

        watchdog._LAST_TRIP = "synthetic trip (test)"
        try:
            assert "watchdog" in preflight(ctx, "gemm_rs", a, b)
            tripped = np.asarray(gemm_rs_safe(a, b, ctx), np.float32)
        finally:
            watchdog.clear_trip()
        assert_allclose(tripped, healthy, atol=1e-5, rtol=1e-5)

    def test_ep_moe_transport_demotes_and_matches_dense(self, mesh8):
        """The fused MoE transport demotes to the XLA a2a under an
        unhealthy-peer plan and still matches the dense reference."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from conftest import dense_moe_ref
        from triton_distributed_tpu.ops import create_ep_moe_context, ep_moe
        from triton_distributed_tpu.ops.moe import _transport_degrade_reason

        n, E, topk, H, F, Mtok = 8, 16, 2, 128, 256, 8
        x = jax.random.normal(jax.random.PRNGKey(0), (n * Mtok, H), jnp.float32)
        logits = jax.random.normal(jax.random.PRNGKey(1), (n * Mtok, E))
        w_up = jax.random.normal(jax.random.PRNGKey(2), (E, H, F), jnp.float32) * 0.05
        w_down = jax.random.normal(jax.random.PRNGKey(3), (E, F, H), jnp.float32) * 0.05
        ref = dense_moe_ref(x, logits, w_up, w_down, topk)
        sh = NamedSharding(mesh8, P("x"))
        ctx = create_ep_moe_context(
            mesh8, "x", num_experts=E, topk=topk, max_m=Mtok * topk,
            hidden=H, dtype=jnp.float32, transport="fused", block_m=8,
        )
        plan = FaultPlan(seed=0, unhealthy_peers=(5,))
        with fault_plan(plan):
            assert "unhealthy" in _transport_degrade_reason(ctx)
            out = ep_moe(
                jax.device_put(x, sh), jax.device_put(logits, sh),
                jax.device_put(w_up, sh), jax.device_put(w_down, sh), ctx,
            )
        assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


# --------------------------------------------------------- bootstrap retry


class TestBootstrapRetry:
    def test_retries_then_succeeds(self):
        from triton_distributed_tpu.runtime.bootstrap import (
            _initialize_with_retry,
        )

        calls, sleeps = [], []

        def flaky(**kw):
            calls.append(kw)
            if len(calls) < 3:
                raise RuntimeError("connection refused")

        _initialize_with_retry(
            "coord:1234", 4, 1, retries=5, backoff=0.5, cap=8.0,
            sleep=sleeps.append, initialize=flaky,
        )
        assert len(calls) == 3
        assert calls[0] == dict(
            coordinator_address="coord:1234", num_processes=4, process_id=1
        )
        # exponential envelope with ±50% jitter: attempt k in
        # [0.5, 1.5] * base * 2^k
        assert len(sleeps) == 2
        for k, s in enumerate(sleeps):
            assert 0.5 * 0.5 * 2 ** k <= s <= 1.5 * 0.5 * 2 ** k

    def test_backoff_capped(self):
        from triton_distributed_tpu.runtime.bootstrap import (
            _initialize_with_retry,
        )

        sleeps = []

        def always_fail(**kw):
            raise RuntimeError("nope")

        with pytest.raises(RuntimeError):
            _initialize_with_retry(
                "c:1", 2, 0, retries=8, backoff=1.0, cap=2.0,
                sleep=sleeps.append, initialize=always_fail,
            )
        assert len(sleeps) == 7
        assert all(s <= 2.0 * 1.5 for s in sleeps)

    def test_terminal_error_names_coordinator(self):
        from triton_distributed_tpu.runtime.bootstrap import (
            _initialize_with_retry,
        )

        def always_fail(**kw):
            raise ConnectionError("rendezvous timed out")

        with pytest.raises(RuntimeError) as exc:
            _initialize_with_retry(
                "10.0.0.9:8476", 16, 3, retries=2, backoff=0.0, cap=0.0,
                sleep=lambda s: None, initialize=always_fail,
            )
        msg = str(exc.value)
        assert "10.0.0.9:8476" in msg
        assert "2 attempt(s)" in msg
        assert "num_processes=16" in msg and "process_id=3" in msg
        assert "rendezvous timed out" in msg
        assert isinstance(exc.value.__cause__, ConnectionError)


# ----------------------------------------------------------- legacy chaos


def test_legacy_chaos_delay_untouched_by_engine(monkeypatch):
    """Without an active plan, chaos_delay keeps the reference-style
    global-boolean behaviour (and stays a no-op when disabled)."""
    from triton_distributed_tpu.config import config
    from triton_distributed_tpu.utils.testing import chaos_delay

    monkeypatch.setattr(config, "chaos_delay", False)
    chaos_delay(site="allgather", step=0, me=None, n=8)  # host no-op
