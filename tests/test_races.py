"""Interpreter race-detector pass over the Pallas kernel families.

The reference's substitute for a race detector is chaos delays
(allgather.py:72-77, SURVEY.md §5); the TPU interpreter additionally has
a real shared-memory race detector (InterpretParams(detect_races=True)).
This module runs one representative kernel per family under it — a
missing semaphore wait that lets a DMA land over in-use data shows up
here as a detected race / wrong value.

Caveat recorded in .claude/skills/verify: the detector has NOT flagged a
deliberately-missing wait under dma_execution_mode="on_wait" in the
past, so this pass is defense-in-depth on top of the chaos suite, not
the sole evidence of race-freedom.

Shapes are intentionally unique to this module: pallas builds capture
InterpretParams at construction, and lru-cached builds from other test
modules were built with detect_races=False.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.config import config


@pytest.fixture(autouse=True)
def _races_on():
    config.detect_races = True
    yield
    config.detect_races = False


def _put(mesh, x, spec):
    return jax.device_put(x, NamedSharding(mesh, spec))


def test_allgather_families_race_free(mesh8):
    from triton_distributed_tpu.kernels.allgather import AllGatherMethod, all_gather

    x = jax.random.normal(jax.random.PRNGKey(0), (24, 40), jnp.float32)
    xs = _put(mesh8, x, P("x"))
    for method in (AllGatherMethod.RING_1D, AllGatherMethod.RING_BIDIR,
                   AllGatherMethod.LL_SMALL):
        out = all_gather(xs, mesh8, "x", method=method)
        np.testing.assert_allclose(np.asarray(out), x, atol=0)


def test_reduce_scatter_race_free(mesh8):
    from triton_distributed_tpu.kernels.reduce_scatter import reduce_scatter

    x = jax.random.normal(jax.random.PRNGKey(1), (8, 24, 40), jnp.float32)
    out = reduce_scatter(_put(mesh8, x, P("x")), mesh8, "x", stacked=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x.sum(0)), atol=1e-5, rtol=1e-5
    )


def test_fused_ag_gemm_race_free(mesh8):
    from triton_distributed_tpu.kernels.ag_gemm import AGGemmMethod, ag_gemm

    a = jax.random.normal(jax.random.PRNGKey(2), (40, 24), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(3), (24, 72), jnp.float32)
    out = ag_gemm(a, b, mesh8, "x", method=AGGemmMethod.PALLAS_FUSED)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a @ b), atol=1e-4, rtol=1e-4
    )


def test_fused_gemm_rs_race_free(mesh8):
    from triton_distributed_tpu.kernels.gemm_rs import GemmRSMethod, gemm_rs

    a = jax.random.normal(jax.random.PRNGKey(4), (40, 24), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(5), (24, 56), jnp.float32)
    out = gemm_rs(a, b, mesh8, "x", method=GemmRSMethod.PALLAS_FUSED)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a @ b), atol=1e-4, rtol=1e-4
    )


def test_dense_a2a_race_free(mesh8):
    from triton_distributed_tpu.kernels.all_to_all import all_to_all, all_to_all_xla

    x = jax.random.normal(jax.random.PRNGKey(6), (8 * 8 * 3, 40), jnp.float32)
    xs = _put(mesh8, x, P("x"))
    out = all_to_all(xs, mesh8, "x")
    ref = all_to_all_xla(xs, mesh8, "x")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0)


def test_ll_persist_race_free(mesh8):
    """The barrier-free protocol's whole safety story is ordering —
    run consecutive parities under the race detector."""
    from triton_distributed_tpu.kernels.allgather import (
        _PERSIST_STATES,
        AllGatherMethod,
        all_gather,
    )

    _PERSIST_STATES.clear()
    for i in range(3):
        x = jax.random.normal(jax.random.PRNGKey(10 + i), (24, 40), jnp.float32)
        xs = _put(mesh8, x, P("x"))
        out = all_gather(xs, mesh8, "x", method=AllGatherMethod.LL_PERSIST)
        np.testing.assert_allclose(np.asarray(out), x, atol=0)
    _PERSIST_STATES.clear()  # race-detector builds must not leak


def test_fused_moe_ll_race_free(mesh8):
    """Barrier-free chunked a2a: consecutive parities over the
    persistent workspaces under the race detector — the protocol's
    whole safety story is the parity-window/semaphore ordering."""
    from triton_distributed_tpu.ops import (
        create_ep_moe_context,
        create_ep_moe_state,
        ep_moe,
    )

    e, topk, m_per, h = 16, 2, 9, 128
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=e, topk=topk, max_m=m_per * topk, hidden=h,
        dtype=jnp.float32, transport="fused", block_m=8,
        use_pallas_gemm=False,
    )
    state = create_ep_moe_state(ctx)
    from conftest import dense_moe_ref

    for i in range(3):
        x = jax.random.normal(jax.random.PRNGKey(30 + i), (8 * m_per, h),
                              jnp.float32)
        logits = jax.random.normal(jax.random.PRNGKey(40 + i), (8 * m_per, e))
        w_up = jax.random.normal(jax.random.PRNGKey(24), (e, h, 64),
                                 jnp.float32) * 0.05
        w_down = jax.random.normal(jax.random.PRNGKey(25), (e, 64, h),
                                   jnp.float32) * 0.05
        out, state = ep_moe(
            _put(mesh8, x, P("x")), _put(mesh8, logits, P("x")),
            _put(mesh8, w_up, P("x")), _put(mesh8, w_down, P("x")), ctx,
            state=state,
        )
        ref = dense_moe_ref(x, logits, w_up, w_down, topk)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
        )


def test_fused_moe_tp_ag_group_gemm_race_free(mesh8):
    """VERDICT r5 #4: the single-kernel AG⊕GroupGEMM under the race
    detector. The risky construct is moe_tp-specific: the SMEM
    block→expert table (``be_ref[src, i]`` inside emit_pipeline index
    maps) steers every A-block fetch while ring DMAs land in the same
    workspace — a mis-indexed expert reads a slab mid-flight."""
    from triton_distributed_tpu.kernels import moe_utils as mu
    from triton_distributed_tpu.ops.moe_tp import (
        ag_group_gemm_fused,
        align_routing_sharded,
        create_ag_group_gemm_context,
    )

    E, TOPK, M, K, F = 16, 2, 64, 96, 256   # shapes unique to this module
    x = jax.random.normal(jax.random.PRNGKey(70), (M, K), jnp.float32)
    logits = jax.random.normal(jax.random.PRNGKey(71), (M, E))
    w_up = jax.random.normal(
        jax.random.PRNGKey(72), (E, K, F), jnp.float32
    ) * 0.05
    _, ids = mu.select_experts(logits, TOPK)
    ctx = create_ag_group_gemm_context(
        mesh8, "x", num_experts=E, topk=TOPK, block_m=8, dtype=jnp.float32
    )
    routing = align_routing_sharded(ctx, ids)
    y = np.asarray(ag_group_gemm_fused(
        _put(mesh8, x, P("x")), routing,
        _put(mesh8, w_up, P(None, None, "x")), ctx,
    ))
    tp, m_s, cap_s = 8, M // 8, routing.cap_s
    for s in range(tp):
        sti = np.asarray(routing.sti[s])
        ids_s = np.asarray(ids)[s * m_s:(s + 1) * m_s]
        xs = np.asarray(mu.gather_sorted(
            jnp.asarray(np.asarray(x)[s * m_s:(s + 1) * m_s]),
            jnp.asarray(sti), TOPK,
        ))
        flat = ids_s.reshape(-1)
        slab = y[s * cap_s:(s + 1) * cap_s]
        for r in range(0, cap_s, 7):
            if sti[r] < m_s * TOPK:
                expect = xs[r] @ np.asarray(w_up)[flat[sti[r]]]
                np.testing.assert_allclose(
                    slab[r], expect, atol=2e-5, rtol=2e-5
                )


def test_fused_moe_tp_reduce_rs_race_free(mesh8):
    """VERDICT r5 #4: the compute-into-the-ring GroupGEMM⊕Reduce-RS
    under the race detector, composed behind the fused AG side — the
    grouped pipeline's SMEM expert indexing feeds partials straight
    into ring slots a peer is concurrently folding."""
    from triton_distributed_tpu.kernels import moe_utils as mu
    from triton_distributed_tpu.ops.moe_tp import (
        ag_group_gemm_fused,
        align_routing_sharded,
        create_ag_group_gemm_context,
        moe_reduce_rs_fused,
    )

    E, TOPK, M, K, F, H = 16, 2, 64, 96, 256, 96
    x = jax.random.normal(jax.random.PRNGKey(80), (M, K), jnp.float32)
    logits = jax.random.normal(jax.random.PRNGKey(81), (M, E))
    w_up = jax.random.normal(
        jax.random.PRNGKey(82), (E, K, F), jnp.float32) * 0.05
    w_down = jax.random.normal(
        jax.random.PRNGKey(83), (E, F, H), jnp.float32) * 0.05
    weights, ids = mu.select_experts(logits, TOPK)
    ctx = create_ag_group_gemm_context(
        mesh8, "x", num_experts=E, topk=TOPK, block_m=8, dtype=jnp.float32
    )
    routing = align_routing_sharded(ctx, ids)
    wug = _put(mesh8, w_up, P(None, None, "x"))
    wdg = _put(mesh8, w_down, P(None, "x"))
    h = ag_group_gemm_fused(_put(mesh8, x, P("x")), routing, wug, ctx)
    h = jax.nn.silu(h.astype(jnp.float32)).astype(jnp.float32)
    out = moe_reduce_rs_fused(
        h, routing, _put(mesh8, weights, P("x")), wdg, ctx
    )
    ref = jnp.zeros((M, H))
    for t in range(TOPK):
        ht = jax.nn.silu(jnp.einsum("mk,mkf->mf", x, w_up[ids[:, t]]))
        ref += weights[:, t: t + 1] * jnp.einsum(
            "mf,mfh->mh", ht, w_down[ids[:, t]]
        )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_fused_moe_dispatch_race_free(mesh8):
    """Fused window-DMA dispatch + slot-regular combine under the race
    detector (the dynamic-offset windows are the risky part)."""
    from triton_distributed_tpu.ops import create_ep_moe_context, ep_moe

    e, topk, m_per, h = 16, 2, 8, 128
    x = jax.random.normal(jax.random.PRNGKey(20), (8 * m_per, h), jnp.float32)
    logits = jax.random.normal(jax.random.PRNGKey(21), (8 * m_per, e))
    w_up = jax.random.normal(jax.random.PRNGKey(22), (e, h, 64), jnp.float32) * 0.05
    w_down = jax.random.normal(jax.random.PRNGKey(23), (e, 64, h), jnp.float32) * 0.05
    ctx = create_ep_moe_context(
        mesh8, "x", num_experts=e, topk=topk, max_m=m_per * topk, hidden=h,
        dtype=jnp.float32, transport="fused", block_m=8, use_pallas_gemm=False,
    )
    out = ep_moe(
        _put(mesh8, x, P("x")), _put(mesh8, logits, P("x")),
        _put(mesh8, w_up, P("x")), _put(mesh8, w_down, P("x")), ctx,
    )
    from conftest import dense_moe_ref

    ref = dense_moe_ref(x, logits, w_up, w_down, topk)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)
