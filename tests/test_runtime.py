"""Runtime tests: bootstrap, symmetric buffers, topology classification."""

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu import runtime
from triton_distributed_tpu.runtime import (
    AllGatherMethod,
    auto_allgather_method,
    detect_topology,
)
from triton_distributed_tpu.runtime.topology import LinkKind


def test_initialize_distributed_single_host():
    ctx = runtime.initialize_distributed()
    assert ctx.world_size == 1
    assert ctx.num_devices == 8
    assert ctx.mesh.shape["x"] == 8



def test_detect_topology_cpu(mesh8):
    topo = detect_topology(mesh8)
    assert topo.link_kind == LinkKind.HOST
    assert topo.num_devices == 8


def test_auto_allgather_method(mesh8):
    topo = detect_topology(mesh8)
    small = auto_allgather_method(topo, 1024)
    big = auto_allgather_method(topo, 1 << 24)
    assert small == AllGatherMethod.LL_SMALL
    assert big == AllGatherMethod.RING_BIDIR
