"""Runtime tests: bootstrap, symmetric buffers, topology classification."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu import runtime
from triton_distributed_tpu.runtime import (
    AllGatherMethod,
    auto_allgather_method,
    detect_topology,
)
from triton_distributed_tpu.runtime.topology import LinkKind

#: tier-1 fast subset (ci/fast.sh): pure host-level runtime logic
pytestmark = pytest.mark.fast


def test_initialize_distributed_single_host():
    ctx = runtime.initialize_distributed()
    assert ctx.world_size == 1
    assert ctx.num_devices == 8
    assert ctx.mesh.shape["x"] == 8



def test_detect_topology_cpu(mesh8):
    topo = detect_topology(mesh8)
    assert topo.link_kind == LinkKind.HOST
    assert topo.num_devices == 8


def test_auto_allgather_method(mesh8):
    topo = detect_topology(mesh8)
    small = auto_allgather_method(topo, 1024)
    big = auto_allgather_method(topo, 1 << 24)
    assert small == AllGatherMethod.LL_SMALL
    assert big == AllGatherMethod.RING_BIDIR


class TestShardguardSelfcheck:
    """Pin the private jax/XLA surfaces shardguard parses (ADVICE r5):
    drift in `_kept_var_idx` or the HLO input_output_alias table must
    fail HERE with shardguard.selfcheck's diagnostic, not as spurious
    donation errors in a serving loop."""

    def test_selfcheck_passes_on_this_jax(self):
        from triton_distributed_tpu.runtime import shardguard

        shardguard.selfcheck()   # raises with a clear message on drift

    def test_alias_table_roundtrip(self):
        from triton_distributed_tpu.runtime import shardguard

        f = jax.jit(lambda s, x: s + x, donate_argnums=(0,))
        x = jnp.zeros((64, 64), jnp.float32)
        compiled = f.lower(x, jnp.ones((64, 64), jnp.float32)).compile()
        aliased = shardguard.input_output_aliased_params(compiled)
        assert 0 in aliased

    def test_kept_indices_track_unused_leaves(self):
        from triton_distributed_tpu.runtime import shardguard

        g = jax.jit(lambda used, unused: used * 2.0)
        x = jnp.zeros((8, 8), jnp.float32)
        compiled = g.lower(x, x).compile()
        kept = shardguard._kept_indices(compiled, 2)
        flat_sh = jax.tree_util.tree_leaves(
            compiled.input_shardings[0],
            is_leaf=lambda s: isinstance(s, jax.sharding.Sharding),
        )
        assert len(kept) == len(flat_sh)

    def test_assert_args_aliased_flags_dropped_donation(self):
        import pytest as _pytest

        from triton_distributed_tpu.runtime import shardguard

        f = jax.jit(lambda s, x: s + x)      # NOT donated
        x = jnp.zeros((64, 64), jnp.float32)
        y = jnp.ones((64, 64), jnp.float32)
        compiled = f.lower(x, y).compile()
        with _pytest.raises(AssertionError, match="NOT input/output-aliased"):
            shardguard.assert_args_aliased(compiled, (x, y), lambda a: a[0])
