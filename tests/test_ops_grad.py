"""Differentiable ops layer: values and gradients vs dense references.

The reference has no autograd through its kernels (inference library);
this is new TPU-framework surface, checked against jax.grad of the plain
dense computation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu import ops
from triton_distributed_tpu.utils import assert_allclose


def _rand(shape, dtype=jnp.float32, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype=dtype)


def _check_grads(fused_loss, dense_loss, args, atol=1e-3):
    val, grads = jax.value_and_grad(fused_loss, argnums=(0, 1))(*args)
    val_ref, grads_ref = jax.value_and_grad(dense_loss, argnums=(0, 1))(*args)
    assert_allclose(np.asarray(val), np.asarray(val_ref), atol=atol, rtol=atol)
    for g, gr in zip(grads, grads_ref):
        assert_allclose(np.asarray(g), np.asarray(gr), atol=atol, rtol=atol)


def test_ag_gemm_grad(mesh8):
    ctx = ops.create_ag_gemm_context(mesh8, "x")
    a = _rand((64, 32), seed=1)
    b = _rand((32, 128), seed=2)
    w = _rand((64, 128), seed=3)

    def fused(a, b):
        return jnp.sum(ops.ag_gemm(a, b, ctx) * w)

    def dense(a, b):
        return jnp.sum(jnp.dot(a, b) * w)

    _check_grads(fused, dense, (a, b))


def test_gemm_rs_grad(mesh8):
    ctx = ops.create_gemm_rs_context(mesh8, "x")
    a = _rand((64, 32), seed=4)
    b = _rand((32, 48), seed=5)
    w = _rand((64, 48), seed=6)

    def fused(a, b):
        return jnp.sum(ops.gemm_rs(a, b, ctx) * w)

    def dense(a, b):
        return jnp.sum(jnp.dot(a, b) * w)

    _check_grads(fused, dense, (a, b))


def test_tp_mlp_grad(mesh8):
    """Grad through the canonical TP MLP: AG-GEMM up-proj → GEMM-RS
    down-proj. The backward chain exercises both dual ops."""
    ag_ctx = ops.create_ag_gemm_context(mesh8, "x")
    rs_ctx = ops.create_gemm_rs_context(mesh8, "x")
    x = _rand((64, 32), seed=7)
    w1 = _rand((32, 64), seed=8)
    w2 = _rand((64, 32), seed=9)

    def fused(w1, w2):
        h = jax.nn.gelu(ops.ag_gemm(x, w1, ag_ctx))
        return jnp.mean(ops.gemm_rs(h, w2, rs_ctx) ** 2)

    def dense(w1, w2):
        h = jax.nn.gelu(jnp.dot(x, w1))
        return jnp.mean(jnp.dot(h, w2) ** 2)

    _check_grads(fused, dense, (w1, w2))


def test_ag_gemm_grad_no_saved_gather(mesh8):
    """save_gathered=False: dB re-gathers A in backward (the lower-memory
    residual mode) — must match the gather-free default numerically."""
    ctx = ops.create_ag_gemm_context(mesh8, "x", save_gathered=False)
    a = _rand((64, 32), seed=21)
    b = _rand((32, 128), seed=22)
    w = _rand((64, 128), seed=23)

    def fused(a, b):
        return jnp.sum(ops.ag_gemm(a, b, ctx) * w)

    def dense(a, b):
        return jnp.sum(jnp.dot(a, b) * w)

    _check_grads(fused, dense, (a, b))


def test_ag_gemm_dp_batch_axes(mesh2x4):
    """DP×TP: rows sharded (dp, tp) — sequence-parallel within each DP
    group; weight grads must psum over dp."""
    ctx = ops.create_ag_gemm_context(mesh2x4, "tp", batch_axes=("dp",))
    a = _rand((64, 32), seed=10)
    b = _rand((32, 128), seed=11)
    w = _rand((64, 128), seed=12)

    def fused(a, b):
        return jnp.sum(ops.ag_gemm(a, b, ctx) * w)

    def dense(a, b):
        return jnp.sum(jnp.dot(a, b) * w)

    _check_grads(fused, dense, (a, b))


def test_gemm_rs_dp_batch_axes(mesh2x4):
    ctx = ops.create_gemm_rs_context(mesh2x4, "tp", batch_axes=("dp",))
    a = _rand((64, 32), seed=13)
    b = _rand((32, 48), seed=14)
    w = _rand((64, 48), seed=15)

    def fused(a, b):
        return jnp.sum(ops.gemm_rs(a, b, ctx) * w)

    def dense(a, b):
        return jnp.sum(jnp.dot(a, b) * w)

    _check_grads(fused, dense, (a, b))
