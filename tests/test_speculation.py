"""Speculative decoding: drafters, the verify/accept row, and the
rejection-sampling identity.

The ISSUE-12 pin: every token stream the SpeculativeEngine produces is
BYTE-IDENTICAL to the non-speculative engine's — across chunked-prefill
contention, recompute-eviction mid-draft, tp=2 head sharding, and the
disaggregated ship cadence. The accept rule samples each position from
the verify row's logits with the request-keyed draw and accepts a draft
only on exact match, so wrong drafts can never perturb the stream (the
rejection-sampling identity under deterministic keyed draws); these
tests make that claim falsifiable everywhere scheduling could differ.

Also covered: drafter determinism (pure functions of the token history,
invariant under ``config.interp_key()`` perturbations), rollback page
accounting (rejected tails leak no pool pages), and the perf-model spec
terms the fleet router and `auto` placement price speculation with.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.serving import (
    DisaggregatedEngine,
    DraftModelDrafter,
    Drafter,
    EngineConfig,
    NGramDrafter,
    Request,
    ServingEngine,
    SpeculativeEngine,
    TreeDrafter,
    make_drafter,
    poisson_trace,
)

pytestmark = pytest.mark.fast

CFG = dict(
    vocab=128, n_layers=2, hidden=64, ffn=128,
    n_heads=4, n_kv_heads=2, head_dim=16,
    dtype=jnp.float32, param_dtype=jnp.float32,
)

ECFG = dict(slots=4, token_budget=48, chunk=16, page=8, npages=40)


@pytest.fixture(scope="module")
def mesh1():
    return Mesh(np.asarray(jax.devices()[:1]), ("tp",))


@pytest.fixture(scope="module")
def model_params(mesh1):
    model = Transformer(TransformerConfig(**CFG), mesh1, "tp", ())
    return model, model.init(jax.random.PRNGKey(0))


def _motif_trace(seed, n, mean_ia, len_lo, len_hi, new_lo, new_hi,
                 vocab=128):
    """Poisson arrivals with prompts rewritten into repeated 5-token
    motifs — the traffic prompt-lookup drafting feeds on. Fresh Request
    objects per call (engines mutate them in place)."""
    base = poisson_trace(seed, n, mean_ia, len_lo, len_hi, new_lo,
                         new_hi, vocab)
    rng = np.random.default_rng(seed + 1000)
    for r in base:
        ln = len(r.prompt)
        motif = rng.integers(0, vocab, (5,)).astype(np.int32)
        r.prompt = np.tile(motif, -(-ln // 5))[:ln]
    return base


def _req(prompt, max_new=4, rid=0):
    return Request(rid=rid, prompt=np.asarray(prompt, np.int32),
                   max_new=max_new, arrival=0.0)


class _WrongDrafter(Drafter):
    """Adversarial drafter: always proposes the SAME (usually wrong)
    token — maximal rejection pressure on the rollback path. Still a
    deterministic pure function of the history, so streams must stay
    token-exact no matter how much it drafts wrong."""

    name = "wrong"

    def draft(self, req, k):
        tok = (int(req.seq[-1]) + 1) % 128
        return np.full((k,), tok, np.int32)


class TestDrafters:
    def test_ngram_draft_is_pure_and_deterministic(self):
        d = NGramDrafter()
        req = _req([1, 2, 3, 9, 1, 2, 3])
        a = d.draft(req, 3)
        b = d.draft(req, 3)
        np.testing.assert_array_equal(a, b)
        # proposes the continuation of the earlier [1, 2, 3]
        np.testing.assert_array_equal(a, [9, 1, 2])

    def test_ngram_rightmost_match_wins(self):
        # [5, 7] occurs twice with different continuations; recency
        # (the rightmost earlier occurrence) must win the tie-break
        req = _req([5, 7, 1, 5, 7, 2, 5, 7])
        out = NGramDrafter().draft(req, 1)
        np.testing.assert_array_equal(out, [2])

    def test_ngram_no_match_degrades_to_empty(self):
        out = NGramDrafter().draft(_req([1, 2, 3, 4]), 4)
        assert out.shape == (0,)

    def test_ngram_invariant_under_interp_key_knobs(self):
        """Drafting is a pure function of the token history — the
        chaos/fleet knobs folded into config.interp_key() must not
        reach it (a drafter that varied with them would break the
        determinism the accept rule's token-exactness rests on)."""
        from triton_distributed_tpu.config import config, set_fleet_seed

        d = NGramDrafter()
        req = _req([4, 6, 4, 6, 4, 6, 8])
        base = d.draft(req, 3)
        old_delay = config.chaos_delay
        try:
            set_fleet_seed(1234)
            config.chaos_delay = 7
            np.testing.assert_array_equal(d.draft(req, 3), base)
        finally:
            set_fleet_seed(None)
            config.chaos_delay = old_delay

    def test_draft_model_shares_truncated_target_weights(
            self, model_params):
        """The draft checkpoint IS the target's: embedding/norm/head
        and the first ``depth`` blocks are parameter VIEWS, never
        copies — shared embeddings, truncated depth."""
        model, params = model_params
        d = DraftModelDrafter(model, params, depth=1)
        assert d.depth == 1
        assert d._params["embed"] is params["embed"]
        assert d._params["lm_head"] is params["lm_head"]
        assert len(d._params["blocks"]) == 1
        assert d._params["blocks"][0] is params["blocks"][0]
        # default depth: half the target's layer count
        assert DraftModelDrafter(model, params).depth == 1
        with pytest.raises(ValueError, match="draft depth"):
            DraftModelDrafter(model, params, depth=3)
        with pytest.raises(ValueError, match="draft depth"):
            DraftModelDrafter(model, params, depth=0)

    def test_draft_model_greedy_walk_is_padding_invariant(
            self, model_params):
        """Drafting k tokens = k greedy autoregressive steps of the
        truncated model. The drafter right-pads to its compile bucket;
        causal attention must make that padding invisible, so an
        unpadded reference forward produces the identical walk — and
        two calls on the same history agree (the purity the accept
        rule's token-exactness rests on)."""
        model, params = model_params
        d = DraftModelDrafter(model, params, depth=1)
        out = d.draft(_req([3, 5]), 3)
        assert out.shape == (3,)
        np.testing.assert_array_equal(d.draft(_req([3, 5]), 3), out)
        seq, want = [3, 5], []
        for _ in range(3):
            toks = np.asarray(seq, np.int32)[None, :]   # no padding
            logits = np.asarray(model.forward(d._params, toks))
            tok = int(np.argmax(logits[len(seq) - 1]))
            want.append(tok)
            seq.append(tok)
        np.testing.assert_array_equal(out, want)

    def test_draft_model_runs_on_quantized_checkpoints(
            self, model_params, mesh1):
        """int8 dense-weight checkpoints (dict lm_head) draft through
        the same truncated forward — valid in-vocab tokens, same walk
        on every call."""
        model, params = model_params
        qmodel = Transformer(
            TransformerConfig(**CFG, dense_weight_quant="int8"),
            mesh1, "tp", (),
        )
        qparams = qmodel.quantize_dense_weights(
            jax.tree.map(lambda x: x, params))
        assert isinstance(qparams["lm_head"], dict)
        dq = DraftModelDrafter(qmodel, qparams, depth=1)
        out = dq.draft(_req([3, 5, 7]), 4)
        assert out.shape == (4,)
        assert ((out >= 0) & (out < CFG["vocab"])).all()
        np.testing.assert_array_equal(
            dq.draft(_req([3, 5, 7]), 4), out)

    def test_make_drafter(self, model_params):
        model, params = model_params
        assert isinstance(make_drafter("ngram", max_ngram=2),
                          NGramDrafter)
        assert isinstance(
            make_drafter("draft_model", model, params),
            DraftModelDrafter)
        with pytest.raises(ValueError, match="needs model"):
            make_drafter("draft_model")
        with pytest.raises(ValueError, match="unknown drafter"):
            make_drafter("nope")


class TestRejectionSamplingIdentity:
    """Speculative streams byte-identical to the plain engine's."""

    def _streams(self, model, params, trace_fn, ecfg, **spec_kw):
        t_ref = trace_fn()
        ref = ServingEngine(model, params, EngineConfig(**ecfg))
        s_ref = ref.run(t_ref, max_steps=800)
        t_spec = trace_fn()
        eng = SpeculativeEngine(model, params, EngineConfig(**ecfg),
                                **spec_kw)
        s_spec = eng.run(t_spec, max_steps=800)
        assert s_ref.completed == s_spec.completed == len(t_ref)
        return t_ref, t_spec, s_spec, eng

    def test_token_exact_under_chunked_contention(self, model_params):
        """Verify rows interleaved with other requests' chunked
        prefill — the mixed-batch case the ragged kernel makes free."""
        model, params = model_params
        t_ref, t_spec, stats, _ = self._streams(
            model, params,
            lambda: _motif_trace(7, 6, 0.5, 8, 30, 8, 16),
            ECFG, spec_k=4, drafter=NGramDrafter(),
        )
        assert stats.spec_rows > 0
        assert stats.accepted_draft_tokens > 0, (
            "trace never exercised an accepted draft")
        for a, b in zip(t_ref, t_spec):
            assert a.generated == b.generated, a.rid

    def test_token_exact_with_eviction_mid_draft(self, model_params):
        """Pool far smaller than the load: recompute-evictions fire
        while drafts are in flight; evicted requests re-prefill
        prompt+generated and the streams still match."""
        model, params = model_params
        t_ref, t_spec, stats, _ = self._streams(
            model, params,
            lambda: _motif_trace(9, 8, 0.4, 8, 30, 8, 16),
            dict(ECFG, npages=14), spec_k=4, drafter=NGramDrafter(),
        )
        assert stats.evictions > 0, "config failed to force an eviction"
        assert stats.spec_rows > 0
        for a, b in zip(t_ref, t_spec):
            assert a.generated == b.generated, a.rid

    def test_token_exact_under_rejection_pressure(self, model_params):
        """An always-wrong drafter maximizes rollback traffic — every
        verify row rejects its whole tail — and the streams must be
        untouched (the identity does not depend on drafter quality)."""
        model, params = model_params
        t_ref, t_spec, stats, eng = self._streams(
            model, params,
            lambda: _motif_trace(11, 5, 0.6, 8, 24, 6, 10),
            ECFG, spec_k=3, drafter=_WrongDrafter(),
        )
        assert stats.spec_rows > 0
        assert stats.rolled_back_tokens > 0
        assert stats.accepted_draft_tokens == 0
        for a, b in zip(t_ref, t_spec):
            assert a.generated == b.generated, a.rid
        # rollback page accounting: with every slot drained, the
        # rejected tails' pages are all back in the pool
        assert all(r is None for r in eng.slot_req)
        assert eng.pool.available == eng.cfg.npages

    def test_token_exact_sampled_temperature(self, model_params):
        """temperature/top-k sampling: the keyed draws make acceptance
        rarer but the identity is unconditional."""
        model, params = model_params
        ecfg = dict(ECFG, temperature=0.7, top_k=40, seed=5)
        t_ref, t_spec, stats, _ = self._streams(
            model, params,
            lambda: _motif_trace(13, 5, 0.6, 8, 24, 6, 10),
            ecfg, spec_k=4, drafter=NGramDrafter(),
        )
        assert stats.spec_rows > 0
        for a, b in zip(t_ref, t_spec):
            assert a.generated == b.generated, a.rid

    def test_tp2_head_sharding_token_exact(self):
        """tp=2: the verify row's logits come off a head-sharded
        ragged step; the accept loop must see identical draws."""
        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs 2 devices")
        mesh2 = Mesh(np.asarray(devs[:2]), ("tp",))
        model = Transformer(TransformerConfig(**CFG), mesh2, "tp", ())
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            model.init(jax.random.PRNGKey(0)), model.shardings(),
        )
        t_ref = _motif_trace(7, 5, 0.5, 8, 30, 8, 14)
        ServingEngine(model, params, EngineConfig(**ECFG)).run(
            t_ref, max_steps=600)
        t_spec = _motif_trace(7, 5, 0.5, 8, 30, 8, 14)
        eng = SpeculativeEngine(
            model, params, EngineConfig(**ECFG), spec_k=4,
            drafter=NGramDrafter(),
        )
        stats = eng.run(t_spec, max_steps=600)
        assert stats.completed == 5 and stats.spec_rows > 0
        for a, b in zip(t_ref, t_spec):
            assert a.generated == b.generated, a.rid

    def test_run_is_deterministic(self, model_params):
        model, params = model_params
        outs = []
        for _ in range(2):
            trace = _motif_trace(3, 5, 0.6, 8, 24, 6, 10)
            eng = SpeculativeEngine(
                model, params, EngineConfig(**ECFG), spec_k=4,
                drafter=NGramDrafter(),
            )
            eng.run(trace, max_steps=600)
            outs.append([tuple(r.generated) for r in trace])
        assert outs[0] == outs[1]

    def test_max_new_is_exact(self, model_params):
        """A full-accept verify row near the emission target must not
        overshoot max_new — stream lengths match the plain engine."""
        model, params = model_params
        trace = _motif_trace(17, 4, 0.5, 10, 20, 3, 5)
        eng = SpeculativeEngine(
            model, params, EngineConfig(**ECFG), spec_k=4,
            drafter=NGramDrafter(),
        )
        eng.run(trace, max_steps=400)
        for r in trace:
            assert len(r.generated) == r.max_new, r.rid

    def test_spec_k_wider_than_chunk_rejected(self, model_params):
        model, params = model_params
        with pytest.raises(ValueError, match="chunk"):
            SpeculativeEngine(
                model, params,
                EngineConfig(slots=2, token_budget=32, chunk=4, page=8,
                             npages=16),
                spec_k=4,
            )
        with pytest.raises(ValueError, match="spec_k"):
            SpeculativeEngine(model, params, EngineConfig(**ECFG),
                              spec_k=0)


class TestTreeSpeculation:
    """spec_tree: a branchy draft tree packed into ONE verify row under
    the kernel's TREE topology — same request-keyed accept identity,
    sibling rescue paths linear draft-k cannot express."""

    def _streams(self, model, params, trace_fn, ecfg, **spec_kw):
        t_ref = trace_fn()
        ServingEngine(model, params, EngineConfig(**ecfg)).run(
            t_ref, max_steps=800)
        t_spec = trace_fn()
        eng = SpeculativeEngine(model, params, EngineConfig(**ecfg),
                                **spec_kw)
        stats = eng.run(t_spec, max_steps=800)
        assert stats.completed == len(t_ref)
        return t_ref, t_spec, stats, eng

    def test_token_exact_greedy(self, model_params):
        model, params = model_params
        t_ref, t_spec, stats, eng = self._streams(
            model, params,
            lambda: _motif_trace(7, 6, 0.5, 8, 30, 8, 16),
            ECFG, spec_tree=8, drafter=TreeDrafter(),
        )
        assert stats.spec_rows > 0
        assert stats.accepted_draft_tokens > 0
        for a, b in zip(t_ref, t_spec):
            assert a.generated == b.generated, a.rid
        # drained: no slot held, every page back in the pool
        assert all(r is None for r in eng.slot_req)
        assert eng.pool.available == eng.cfg.npages

    def test_token_exact_sampled_and_beats_linear(self, model_params):
        """The acceptance claim, pinned: on branchy sampled traffic
        (small top_k makes the self-history genuinely ambiguous) the
        tree's sibling branches rescue steps the linear draft loses —
        accepted tokens per verify row strictly above linear draft-k,
        streams byte-identical to the plain engine throughout."""
        model, params = model_params
        ecfg = dict(ECFG, temperature=1.0, top_k=4, seed=5)
        trace_fn = lambda: _motif_trace(13, 6, 0.5, 8, 30, 16, 24)
        t_ref, t_tree, tree, _ = self._streams(
            model, params, trace_fn, ecfg,
            spec_tree=8, drafter=TreeDrafter(branches=3, branch_len=2),
        )
        for a, b in zip(t_ref, t_tree):
            assert a.generated == b.generated, a.rid
        t_lin = trace_fn()
        lin = SpeculativeEngine(
            model, params, EngineConfig(**ecfg), spec_k=4,
            drafter=NGramDrafter(),
        ).run(t_lin, max_steps=800)
        for a, b in zip(t_ref, t_lin):
            assert a.generated == b.generated, a.rid
        tree_rate = tree.accepted_draft_tokens / max(tree.spec_rows, 1)
        lin_rate = lin.accepted_draft_tokens / max(lin.spec_rows, 1)
        assert tree_rate > lin_rate, (tree_rate, lin_rate)

    def test_token_exact_under_eviction(self, model_params):
        model, params = model_params
        t_ref, t_spec, stats, _ = self._streams(
            model, params,
            lambda: _motif_trace(9, 8, 0.4, 8, 30, 8, 16),
            dict(ECFG, npages=14), spec_tree=6, drafter=TreeDrafter(),
        )
        assert stats.evictions > 0, "config failed to force an eviction"
        for a, b in zip(t_ref, t_spec):
            assert a.generated == b.generated, a.rid

    def test_validation_and_factory(self, model_params):
        model, params = model_params
        with pytest.raises(ValueError, match="chunk"):
            SpeculativeEngine(
                model, params,
                EngineConfig(slots=2, token_budget=32, chunk=4, page=8,
                             npages=16),
                spec_tree=8,
            )
        with pytest.raises(ValueError, match="draft_tree"):
            SpeculativeEngine(model, params, EngineConfig(**ECFG),
                              spec_tree=4, drafter=NGramDrafter())
        assert isinstance(make_drafter("tree", branches=3), TreeDrafter)

    def test_tree_traffic_key_is_distinct(self, model_params):
        """Satellite: the grid-schedule traffic key carries the
        speculation signature — tree, linear, and plain engines must
        ledger under different keys for the retuner."""
        model, params = model_params
        plain = ServingEngine(model, params, EngineConfig(**ECFG))
        lin = SpeculativeEngine(model, params, EngineConfig(**ECFG),
                                spec_k=4)
        tree = SpeculativeEngine(model, params, EngineConfig(**ECFG),
                                 spec_tree=8, drafter=TreeDrafter())
        keys = {e._grid_key[-2:] for e in (plain, lin, tree)}
        assert keys == {(0, 0), (4, 0), (4, 8)}
        assert len({e._grid_key for e in (plain, lin, tree)}) == 3

    def test_trunk_is_linear_draft(self, model_params):
        """TreeDrafter's trunk IS the NGram linear draft — the tree can
        only add rescue branches, never lose the linear path."""
        model, params = model_params
        req = _req([1, 2, 3, 9, 1, 2, 3])
        lin = NGramDrafter().draft(req, 3)
        toks, parents = TreeDrafter().draft_tree(req, 6)
        trunk = []
        cur = -1
        for i, p in enumerate(parents):
            if p == cur:
                trunk.append(int(toks[i]))
                cur = i
        np.testing.assert_array_equal(trunk[:len(lin)], lin)
        assert all(p < i for i, p in enumerate(parents))


class TestSharedPrefix:
    """cfg.prefix_share: in-batch shared-prefix dedup — duplicate
    prefix pages folded onto one canonical page (PagePool refcounts),
    rows marked SHARED_PREFIX in the topology operand."""

    def _shared_trace(self, n=6, vocab=128):
        """Requests sharing a long common prompt prefix — every batch
        carries duplicate frozen prefix pages until dedup folds them."""
        rng = np.random.default_rng(21)
        prefix = rng.integers(0, vocab, (24,)).astype(np.int32)
        reqs = []
        for i in range(n):
            tail = rng.integers(0, vocab, (4,)).astype(np.int32)
            reqs.append(Request(
                rid=i, prompt=np.concatenate([prefix, tail]),
                max_new=6, arrival=0.1 * i,
            ))
        return reqs

    def test_dedup_token_exact_and_counted(self, model_params):
        model, params = model_params
        ecfg = dict(ECFG, slots=3, npages=64)
        t_ref = self._shared_trace()
        ServingEngine(model, params, EngineConfig(**ecfg)).run(
            t_ref, max_steps=800)
        t_dd = self._shared_trace()
        eng = ServingEngine(
            model, params,
            EngineConfig(**ecfg, prefix_cache=True, prefix_share=True),
        )
        stats = eng.run(t_dd, max_steps=800)
        assert stats.completed == len(t_ref)
        assert stats.shared_prefix_rows > 0
        assert stats.deduped_pages > 0
        for a, b in zip(t_ref, t_dd):
            assert a.generated == b.generated, a.rid
        # no leak: drained engine returns every page
        assert all(r is None for r in eng.slot_req)
        assert eng.pool.available == eng.cfg.npages

    def test_prefix_share_requires_prefix_cache(self, model_params):
        model, params = model_params
        with pytest.raises(ValueError, match="prefix_cache"):
            ServingEngine(model, params,
                          EngineConfig(**ECFG, prefix_share=True))


class TestSpeculativeDisaggregated:
    def test_disagg_ship_cadence_token_exact(self):
        """DisaggregatedEngine(spec_k=4): prefill KV ships on the DCN
        wire, the decode role verifies drafts — fewer, wider decode
        steps (the changed cadence) with streams still equal to the
        colocated PLAIN engine's."""
        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs 2 devices")
        mesh_p = Mesh(np.asarray(devs[:1]), ("tp",))
        mesh_d = Mesh(np.asarray(devs[1:2]), ("tp",))
        hybrid = Mesh(np.asarray(devs[:2]).reshape(2, 1), ("dcn", "tp"))
        cfg = TransformerConfig(**{**CFG, "kv_quant": "int8"})
        mp = Transformer(cfg, mesh_p, "tp", ())
        md = Transformer(cfg, mesh_d, "tp", ())
        params = mp.init(jax.random.PRNGKey(0))
        pp = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                          mp.shardings())
        pd = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                          md.shardings())
        ecfg = EngineConfig(**ECFG)
        t_ref = _motif_trace(9, 5, 0.7, 8, 30, 8, 14)
        ServingEngine(mp, pp, ecfg).run(t_ref, max_steps=600)
        t_d = _motif_trace(9, 5, 0.7, 8, 30, 8, 14)
        eng = DisaggregatedEngine(
            mp, pp, md, pd, ecfg, hybrid_mesh=hybrid, dcn_axis="dcn",
            transport="dcn", ship_delay_steps=1, spec_k=4,
            drafter=NGramDrafter(),
        )
        stats = eng.run(t_d, max_ticks=900)
        assert stats.completed == 5
        assert stats.ships > 0
        assert isinstance(eng.decode, SpeculativeEngine)
        assert stats.decode.spec_rows > 0
        assert stats.decode.accepted_draft_tokens > 0
        for a, b in zip(t_ref, t_d):
            assert a.generated == b.generated, a.rid


class TestSpecPerfModel:
    def test_expected_accepted_bounds_and_monotonicity(self):
        from triton_distributed_tpu.tune.perf_model import (
            expected_accepted_per_step,
        )

        assert expected_accepted_per_step(4, 0.0) == 1.0
        assert expected_accepted_per_step(4, 1.0) == 5.0
        prev = 0.0
        for p in (0.1, 0.3, 0.5, 0.7, 0.9):
            cur = expected_accepted_per_step(4, p)
            assert 1.0 < cur < 5.0 and cur > prev
            prev = cur

    def test_spec_step_costs_more_than_plain(self):
        from triton_distributed_tpu.tune.perf_model import (
            ragged_serving_step_ms,
            spec_step_ms,
        )

        kw = dict(page=32, hkv=2, g=4, d=128, hidden=1024)
        plain = ragged_serving_step_ms([512] * 8, [1] * 8, **kw)
        spec = spec_step_ms([512] * 8, spec_k=4, **kw)
        assert spec > plain
        # ...but far less than 5 plain steps — the speculation win
        assert spec < 5 * plain

    def test_placement_flips_under_speculation(self):
        """The priced ship-cadence change: traffic whose ship hides
        under a plain decode window is REFUSED once spec_k shrinks the
        window to max_new/accepted steps. decode_step_ms pins the
        window so the flip is deterministic across TpuSpec defaults."""
        from triton_distributed_tpu.tune.perf_model import (
            refuse_disaggregation,
        )

        cfg = TransformerConfig(**{**CFG, "kv_quant": "int8"})
        traffic = dict(prompt_len=4096, max_new=8, decode_step_ms=0.02)
        assert refuse_disaggregation(cfg, 32, traffic) is None
        why = refuse_disaggregation(
            cfg, 32,
            dict(traffic, spec_k=4, spec_acceptance=0.9),
        )
        assert why is not None and "spec_k=4" in why

    def test_replica_load_prices_measured_acceptance(self, model_params):
        """A speculative replica that measured >1 accepted/step must
        price CHEAPER per token than its plain twin at the same
        occupancy — the router term that keeps speculative replicas
        fully routed."""
        from triton_distributed_tpu.tune.perf_model import (
            replica_load_ms,
        )

        model, params = model_params
        trace = _motif_trace(7, 5, 0.5, 8, 30, 10, 16)
        eng = SpeculativeEngine(
            model, params, EngineConfig(**ECFG), spec_k=4,
            drafter=NGramDrafter(),
            on_complete=lambda r, s: False,   # park: keep slots resident
        )
        eng.run(trace, max_steps=600)
        assert eng.stats.accepted_tokens_per_step > 1.0
        plain = ServingEngine(
            model, params, EngineConfig(**ECFG),
            on_complete=lambda r, s: False,
        )
        plain.run(_motif_trace(7, 5, 0.5, 8, 30, 10, 16),
                  max_steps=600)
        assert replica_load_ms(eng) < replica_load_ms(plain)


# ------------------------------------------- adaptive draft-k (ISSUE-13)

class TestAdaptiveK:
    """Per-request AIMD draft budget: a rejection clamps the next draft
    to ``accepted + 1``, a fully-accepted row grows it back by one,
    always inside ``[1, spec_k]``. Pure bookkeeping over the verify
    outcome, so streams stay byte-exact and the k trajectory is
    seeded-deterministic."""

    def test_token_exact_and_bounded(self, model_params):
        model, params = model_params
        t_ref = _motif_trace(9, 5, 0.5, 8, 30, 8, 14)
        ServingEngine(model, params, EngineConfig(**ECFG)).run(
            t_ref, max_steps=600)
        t_ad = _motif_trace(9, 5, 0.5, 8, 30, 8, 14)
        eng = SpeculativeEngine(
            model, params, EngineConfig(**ECFG), spec_k=4,
            drafter=NGramDrafter(), adaptive_k=True)
        stats = eng.run(t_ad, max_steps=600)
        assert stats.completed == 5
        for a, b in zip(t_ref, t_ad):
            assert a.generated == b.generated, a.rid
        hist = stats.adaptive_k_histogram
        assert hist and sum(hist.values()) > 0
        # the budget never leaves [1, spec_k]
        assert all(1 <= k <= 4 for k in hist)

    def test_shrinks_under_rejection_pressure(self, model_params):
        """The always-wrong drafter drives every row to a rejection;
        the budget must collapse to 1 and stay there (each request's
        FIRST row still opens at spec_k)."""
        model, params = model_params
        trace = _motif_trace(5, 3, 0.5, 8, 20, 6, 8)
        eng = SpeculativeEngine(
            model, params, EngineConfig(**ECFG), spec_k=4,
            drafter=_WrongDrafter(), adaptive_k=True)
        stats = eng.run(trace, max_steps=600)
        assert stats.completed == 3
        hist = stats.adaptive_k_histogram
        assert hist.get(1, 0) > hist.get(4, 0)

    def test_histogram_and_streams_deterministic(self, model_params):
        model, params = model_params
        outs = []
        for _ in range(2):
            trace = _motif_trace(3, 5, 0.6, 8, 24, 6, 10)
            eng = SpeculativeEngine(
                model, params, EngineConfig(**ECFG), spec_k=4,
                drafter=NGramDrafter(), adaptive_k=True)
            stats = eng.run(trace, max_steps=600)
            outs.append((stats.adaptive_k_histogram,
                         [tuple(r.generated) for r in trace]))
        assert outs[0] == outs[1]

    def test_off_by_default_no_histogram(self, model_params):
        model, params = model_params
        trace = _motif_trace(11, 3, 0.5, 8, 20, 4, 6)
        eng = SpeculativeEngine(
            model, params, EngineConfig(**ECFG), spec_k=4,
            drafter=NGramDrafter())
        stats = eng.run(trace, max_steps=400)
        assert stats.adaptive_k_histogram == {}
