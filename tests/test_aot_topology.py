"""Multi-chip Mosaic compile validation — no multi-chip hardware needed.

VERDICT r2 #1: every cross-chip Pallas primitive had only ever run under
the CPU interpreter (real-chip runs degenerate to n=1, where no remote
DMA is issued). This suite closes that gap the way the reference closes
it with real 8×H800 runs (test/nvidia/test_ag_gemm.py, launch.sh): each
Pallas collective family is AOT-lowered AND fully compiled — XLA +
Mosaic, producing a real TPU executable — against an UNATTACHED v5e-8
topology (``jax.experimental.topologies``; libtpu provides the compiler,
no chips required). A kernel that would fail Mosaic lowering or the
Mosaic backend (layout/alignment/semaphore legality) on real 8-chip
silicon fails here.

What this does NOT prove: runtime behavior (deadlock freedom, data
races) — that remains the interpreter suite's job (tests/test_races.py,
chaos suite). Compile + simulate together are the strongest validation
available without multi-chip hardware.

Marked ``slow`` (round 6): constructing the unattached v5e topology
plus the full XLA+Mosaic compiles costs ~8 minutes of the tier-1
budget on the 1-core CI host (462 s of it in the module fixture alone
— VERDICT r5 noted the suite no longer fit 10 minutes). Run it
explicitly with ``pytest -m slow tests/test_aot_topology.py`` (nightly
and before any kernel-touching merge).
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.config import config, interp_key

pytestmark = pytest.mark.slow


def _make_topology_mesh():
    from jax.experimental import topologies

    topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x4")
    return topologies.make_mesh(topo, (8,), ("x",))


@pytest.fixture(scope="module")
def tmesh():
    """v5e-8 compile-only topology mesh. If the installed libtpu cannot
    construct one, the skip reason names the failing API (docs/PERF.md
    records the same contract)."""
    try:
        return _make_topology_mesh()
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(
            "jax.experimental.topologies.get_topology_desc('v5e:2x4') "
            f"unavailable: {type(e).__name__}: {e}"
        )


@pytest.fixture(autouse=True)
def _force_compile():
    """Pallas builds in this module must lower through Mosaic (not the
    interpreter) even though the test process is CPU-backed. Builders
    key their caches on interp_key(), so no stale-build leakage."""
    old = config.force_compile
    config.force_compile = True
    yield
    config.force_compile = old


def _assert_compiles(jitted, *args):
    """lower() must produce a Mosaic custom call; compile() must run the
    full XLA+Mosaic pipeline for the 8-chip topology."""
    lowered = jitted.lower(*args)
    text = lowered.as_text()
    assert "tpu_custom_call" in text, "no Mosaic kernel in lowering"
    compiled = lowered.compile()  # raises on any Mosaic backend error
    assert compiled is not None


def _sds(mesh, shape, dtype, *spec):
    return jax.ShapeDtypeStruct(
        shape, dtype, sharding=NamedSharding(mesh, P(*spec))
    )


class TestCollectiveFamilies:
    """One compile per kernel family, 8-chip v5e topology, bf16,
    Mosaic-aligned shapes (the strict divisor logic sees
    compiling_for_tpu()=True here, exactly as on hardware)."""

    def test_ring_1d_allgather(self, tmesh):
        from triton_distributed_tpu.kernels.allgather import _build_all_gather
        from triton_distributed_tpu.runtime import AllGatherMethod

        fn = _build_all_gather(
            tmesh, "x", AllGatherMethod.RING_1D, (1024, 256),
            jnp.dtype(jnp.bfloat16), 2, interp_key(),
        )
        _assert_compiles(fn, _sds(tmesh, (1024, 256), jnp.bfloat16, "x"))

    def test_ring_bidir_allgather(self, tmesh):
        from triton_distributed_tpu.kernels.allgather import _build_all_gather
        from triton_distributed_tpu.runtime import AllGatherMethod

        fn = _build_all_gather(
            tmesh, "x", AllGatherMethod.RING_BIDIR, (1024, 256),
            jnp.dtype(jnp.bfloat16), 2, interp_key(),
        )
        _assert_compiles(fn, _sds(tmesh, (1024, 256), jnp.bfloat16, "x"))

    def test_ll_push_allgather(self, tmesh):
        from triton_distributed_tpu.kernels.allgather import _build_all_gather
        from triton_distributed_tpu.runtime import AllGatherMethod

        fn = _build_all_gather(
            tmesh, "x", AllGatherMethod.LL_SMALL, (1024, 256),
            jnp.dtype(jnp.bfloat16), 2, interp_key(),
        )
        _assert_compiles(fn, _sds(tmesh, (1024, 256), jnp.bfloat16, "x"))

    def test_ll_persist_allgather(self, tmesh):
        from triton_distributed_tpu.kernels.allgather import _build_ll_persist

        fn = _build_ll_persist(
            tmesh, "x", 128, 256, jnp.dtype(jnp.bfloat16), 12, interp_key()
        )
        _assert_compiles(
            fn,
            _sds(tmesh, (1,), jnp.int32),
            _sds(tmesh, (1024, 256), jnp.bfloat16, "x"),
            _sds(tmesh, (8 * 2 * 1024, 256), jnp.bfloat16, "x"),
        )

    def test_dense_all_to_all(self, tmesh):
        from triton_distributed_tpu.kernels.all_to_all import _build_all_to_all

        fn = _build_all_to_all(
            tmesh, "x", (1024, 256), jnp.dtype(jnp.bfloat16), 4, interp_key()
        )
        _assert_compiles(fn, _sds(tmesh, (1024, 256), jnp.bfloat16, "x"))

    def test_ring_reduce_scatter_vmem(self, tmesh):
        from triton_distributed_tpu.kernels.reduce_scatter import (
            _build_reduce_scatter,
        )

        # stacked=True: (n, M, cols) per-device partials sharded on dim 0
        fn = _build_reduce_scatter(
            tmesh, "x", (1024, 256), jnp.dtype(jnp.bfloat16), True, 3,
            interp_key(),
        )
        _assert_compiles(fn, _sds(tmesh, (8, 1024, 256), jnp.bfloat16, "x"))

    def test_streaming_reduce_scatter_hbm(self, tmesh):
        from triton_distributed_tpu.kernels.reduce_scatter import (
            _build_rs_stream,
        )

        fn = _build_rs_stream(
            tmesh, "x", 1024, 512, jnp.dtype(jnp.bfloat16), False, 3,
            interp_key(),
        )
        _assert_compiles(fn, _sds(tmesh, (1024, 512), jnp.bfloat16))

    def test_fused_ag_gemm(self, tmesh):
        from triton_distributed_tpu.kernels.ag_gemm import _build_fused

        m, k, nn = 1024, 256, 2048   # per-shard (128, 256) @ (256, 256)
        fn = _build_fused(
            tmesh, "x", (), (m, k), (k, nn), jnp.dtype(jnp.bfloat16),
            jnp.dtype(jnp.bfloat16), 5, interp_key(), False,
        )
        _assert_compiles(
            fn,
            _sds(tmesh, (m, k), jnp.bfloat16, "x"),
            _sds(tmesh, (k, nn), jnp.bfloat16, None, "x"),
        )

    def test_fused_gemm_rs(self, tmesh):
        from triton_distributed_tpu.kernels.gemm_rs import _build_fused

        m, k, nn = 1024, 2048, 256   # per-shard (1024, 256) @ (256, 256)
        fn = _build_fused(
            tmesh, "x", (), (m, k), (k, nn), jnp.dtype(jnp.bfloat16),
            jnp.dtype(jnp.bfloat16), 6, interp_key(),
        )
        _assert_compiles(
            fn,
            _sds(tmesh, (m, k), jnp.bfloat16, None, "x"),
            _sds(tmesh, (k, nn), jnp.bfloat16, "x"),
        )

    def test_fused_ag_gemm_int8_wire(self, tmesh):
        """The quantized-wire AG ring (ISSUE 3): int8 payload slabs +
        scale-plane rail + in-kernel dequant pipeline must survive the
        full Mosaic backend for the 8-chip topology. (int8 is the
        in-kernel wire on this toolchain — Mosaic rejects f8 extf,
        lang.wire.inkernel_wire_ok; fp8 rides the XLA engines.)"""
        from triton_distributed_tpu.kernels.ag_gemm import _build_fused

        m, k, nn = 1024, 2048, 2048   # per-shard (128, 2048) slabs
        fn = _build_fused(
            tmesh, "x", (), (m, k), (k, nn), jnp.dtype(jnp.bfloat16),
            jnp.dtype(jnp.bfloat16), 5, interp_key(), False, None, "int8",
        )
        _assert_compiles(
            fn,
            _sds(tmesh, (m, k), jnp.bfloat16, "x"),
            _sds(tmesh, (k, nn), jnp.bfloat16, None, "x"),
        )

    def test_fused_gemm_rs_int8_wire(self, tmesh):
        """The quantized-wire reduce ring: per-hop quant pipeline +
        f32 dequant-accumulate + the scale rail, through Mosaic."""
        from triton_distributed_tpu.kernels.gemm_rs import _build_fused

        m, k, nn = 1024, 2048, 2048
        fn = _build_fused(
            tmesh, "x", (), (m, k), (k, nn), jnp.dtype(jnp.bfloat16),
            jnp.dtype(jnp.bfloat16), 6, interp_key(), None, "int8",
        )
        _assert_compiles(
            fn,
            _sds(tmesh, (m, k), jnp.bfloat16, None, "x"),
            _sds(tmesh, (k, nn), jnp.bfloat16, "x"),
        )

    def test_standalone_ag_ring_int8_wire(self, tmesh):
        from triton_distributed_tpu.kernels.allgather import (
            _build_all_gather,
        )
        from triton_distributed_tpu.runtime import AllGatherMethod

        fn = _build_all_gather(
            tmesh, "x", AllGatherMethod.RING_1D, (1024, 2048),
            jnp.dtype(jnp.bfloat16), 2, interp_key(), wire="int8",
        )
        _assert_compiles(fn, _sds(tmesh, (1024, 2048), jnp.bfloat16, "x"))

    def test_fp8_wire_on_fused_engine_raises_cleanly(self, tmesh):
        """Explicit fp8 on an in-kernel ring under real Mosaic must fail
        with lang.wire's diagnostic (a pinned wire is a contract), NOT a
        MosaicError mid-compile."""
        from triton_distributed_tpu.kernels.ag_gemm import (
            AGGemmMethod,
            resolve_ag_gemm_wire,
        )

        a = jax.ShapeDtypeStruct((1024, 2048), jnp.bfloat16)
        b = jax.ShapeDtypeStruct((2048, 2048), jnp.bfloat16)
        with pytest.raises(ValueError, match="in-kernel f8"):
            resolve_ag_gemm_wire(
                tmesh, "x", a, b, method=AGGemmMethod.PALLAS_FUSED,
                wire_dtype="fp8",
            )

    def test_fused_ag_group_gemm(self, tmesh):
        from triton_distributed_tpu.ops.moe_tp import (
            _build_ag_gg_fused,
            create_ag_group_gemm_context,
        )

        e, topk, cap_s, k, nl_local, block_m = 8, 2, 256, 256, 256, 64
        ctx = create_ag_group_gemm_context(
            tmesh, "x", num_experts=e, topk=topk, block_m=block_m,
            dtype=jnp.bfloat16,
        )
        fn = _build_ag_gg_fused(ctx, cap_s, k, nl_local)
        n = 8
        _assert_compiles(
            fn,
            _sds(tmesh, (n, cap_s // block_m), jnp.int32),
            _sds(tmesh, (n * cap_s, k), jnp.bfloat16, "x"),
            _sds(tmesh, (e, k, nl_local * n), jnp.bfloat16, None, None, "x"),
        )

    def test_fused_moe_reduce_rs(self, tmesh):
        from triton_distributed_tpu.ops.moe_tp import (
            _build_moe_rs_fused,
            create_ag_group_gemm_context,
        )

        e, topk, cap_s, fl_local, h, block_m = 8, 2, 256, 256, 256, 64
        ctx = create_ag_group_gemm_context(
            tmesh, "x", num_experts=e, topk=topk, block_m=block_m,
            dtype=jnp.bfloat16,
        )
        fn = _build_moe_rs_fused(ctx, cap_s, fl_local, h)
        n = 8
        _assert_compiles(
            fn,
            _sds(tmesh, (n, cap_s // block_m), jnp.int32),
            _sds(tmesh, (n * cap_s, fl_local * n), jnp.bfloat16, None, "x"),
            _sds(tmesh, (e, fl_local * n, h), jnp.bfloat16, None, "x", None),
        )

    def test_fused_moe_dispatch(self, tmesh):
        """Count-bounded chunked a2a, barrier mode (dispatch leg:
        in-kernel meta-count discovery drives traced recv loops)."""
        from triton_distributed_tpu.kernels import moe_all_to_all as ma
        from triton_distributed_tpu.kernels import moe_dispatch as md

        ctx = ma.create_all_to_all_context(
            tmesh, "x", max_m=256, hidden=512, experts_per_rank=2,
            dtype=jnp.bfloat16, quant="fp8",
        )
        call = md._build_chunked_a2a(
            *md._geom_args(ctx), False, 10, interp_key()
        )
        fn = jax.jit(
            jax.shard_map(
                call, mesh=tmesh,
                in_specs=(P("x"),) * 4 + (P("x"), P("x")),
                out_specs=(P("x"), P("x")),
                check_vma=False,
            )
        )
        mr = md.meta_rows(ctx)
        _assert_compiles(
            fn,
            _sds(tmesh, (8 * 1,), jnp.int32, "x"),
            _sds(tmesh, (8 * 8,), jnp.int32, "x"),
            _sds(tmesh, (8 * 8,), jnp.int32, "x"),
            _sds(tmesh, (8 * 8,), jnp.int32, "x"),
            _sds(tmesh, (8 * md.m_cap(ctx), ctx.hidden), ctx.wire_dtype, "x"),
            _sds(tmesh, (8 * 8 * mr, md.META_W), jnp.int32, "x"),
        )

    def test_fused_moe_dispatch_ll(self, tmesh):
        """Barrier-free LL variant: persistent aliased workspaces +
        per-parity semaphore rows through the Mosaic backend."""
        from triton_distributed_tpu.kernels import moe_all_to_all as ma
        from triton_distributed_tpu.kernels import moe_dispatch as md

        ctx = ma.create_all_to_all_context(
            tmesh, "x", max_m=256, hidden=512, experts_per_rank=2,
            dtype=jnp.bfloat16, quant="fp8",
        )
        call = md._build_chunked_a2a_ll(
            *md._geom_args(ctx), False, 7001, interp_key()
        )
        fn = jax.jit(
            jax.shard_map(
                call, mesh=tmesh,
                in_specs=(P("x"),) * 4 + (P("x"),) * 4,
                out_specs=(P("x"), P("x")),
                check_vma=False,
            )
        )
        mr = md.meta_rows(ctx)
        sp = md.slot_pad(ctx)
        _assert_compiles(
            fn,
            _sds(tmesh, (8 * 1,), jnp.int32, "x"),
            _sds(tmesh, (8 * 8,), jnp.int32, "x"),
            _sds(tmesh, (8 * 8,), jnp.int32, "x"),
            _sds(tmesh, (8 * 8,), jnp.int32, "x"),
            _sds(tmesh, (8 * md.m_cap(ctx), ctx.hidden), ctx.wire_dtype, "x"),
            _sds(tmesh, (8 * 8 * mr, md.META_W), jnp.int32, "x"),
            _sds(tmesh, (8 * 2 * 8 * sp, ctx.hidden), ctx.wire_dtype, "x"),
            _sds(tmesh, (8 * 2 * 8 * mr, md.META_W), jnp.int32, "x"),
        )

    def test_hier_ag_gemm_dcn_overlap(self, tmesh):
        """VERDICT r3 #5: the chunked hierarchical AG-GEMM's compiled
        schedule must fly a rail fetch (collective-permute) UNDER a
        Mosaic ring call — assert a custom-call sits between an async
        permute's start and done in the optimized module."""
        from triton_distributed_tpu.kernels.ag_gemm import _build_fused, _specs
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(
            platform="tpu", topology_name="v5e:2x4"
        )
        hmesh = topologies.make_mesh(topo, (4, 2), ("tp", "dcn"))
        m, k, nn = 1024, 256, 2048
        fn = _build_fused(
            hmesh, "tp", (), (m, k), (k, nn), jnp.dtype(jnp.bfloat16),
            jnp.dtype(jnp.bfloat16), 5, interp_key(), True, "dcn",
        )
        (a_spec, b_spec), _ = _specs("tp", (), "dcn")
        low = fn.lower(
            _sds(hmesh, (m, k), jnp.bfloat16, *a_spec),
            _sds(hmesh, (k, nn), jnp.bfloat16, *b_spec),
        )
        txt = low.compile().as_text()
        in_flight = False
        straddle = False
        for line in txt.splitlines():
            if "collective-permute-start" in line:
                in_flight = True
            elif "collective-permute-done" in line:
                in_flight = False
            elif "custom-call" in line and in_flight:
                straddle = True
        assert straddle, (
            "no Mosaic call scheduled inside a collective-permute "
            "start/done window — the DCN rail is not overlapping"
        )

    def test_hier_gemm_rs_dcn_overlap(self, tmesh):
        """VERDICT r4 #5: the CHUNKED hierarchical GEMM-RS (N split
        over column chunks, each chunk's DCN reduce ring expressed as
        ppermute hops) must fly a chunk's DCN transfer UNDER the next
        chunk's Mosaic ring — assert a custom-call sits between an
        async permute's start and done in the optimized v5e-8 module.
        (A sync psum_scatter leg — the r4 design — serializes here by
        construction; the chunked ppermute ring is what earns the
        async window.)"""
        from triton_distributed_tpu.kernels.gemm_rs import _build_fused, _specs
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(
            platform="tpu", topology_name="v5e:2x4"
        )
        hmesh = topologies.make_mesh(topo, (4, 2), ("tp", "dcn"))
        m, k, nn = 1024, 2048, 2048
        fn = _build_fused(
            hmesh, "tp", (), (m, k), (k, nn), jnp.dtype(jnp.bfloat16),
            jnp.dtype(jnp.bfloat16), 6, interp_key(), "dcn",
        )
        (a_spec, b_spec), _ = _specs("tp", (), "dcn")
        low = fn.lower(
            _sds(hmesh, (m, k), jnp.bfloat16, *a_spec),
            _sds(hmesh, (k, nn), jnp.bfloat16, *b_spec),
        )
        txt = low.compile().as_text()
        assert txt.count("custom-call") >= 2, "column chunking did not engage"
        in_flight = 0
        straddle = False
        for line in txt.splitlines():
            if "collective-permute-start" in line:
                in_flight += 1
            elif "collective-permute-done" in line:
                in_flight = max(0, in_flight - 1)
            elif "custom-call" in line and in_flight:
                straddle = True
        assert straddle, (
            "no Mosaic call scheduled inside a collective-permute "
            "start/done window — the chunked GEMM-RS DCN leg is not "
            "overlapping"
        )

    def test_ep_moe_decode_step_fused(self, tmesh):
        """The COMPOSED serving path (VERDICT r3 #4): a full
        Transformer.decode_step — SP flash-decode attention + EP-MoE
        block on the barrier-free fused transport with its LL state —
        lowered and compiled over the 8-chip topology. Closes the gap
        where the fused decode transport had only kernel-level compile
        coverage."""
        from triton_distributed_tpu.models import Transformer, TransformerConfig

        cfg = TransformerConfig(
            vocab=512, n_layers=1, hidden=256, ffn=256, n_heads=8,
            n_kv_heads=4, head_dim=32, moe="ep", moe_layers=(0,),
            num_experts=8, topk=2,
        )
        model = Transformer(cfg, tmesh, tp_axis="x")
        b, cap = 16, 256
        params_sds = jax.eval_shape(model.init, jax.random.PRNGKey(0))
        params_sds = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            params_sds, model.shardings(),
        )
        cache_sh = NamedSharding(tmesh, P(None, None, "x"))
        kv = jax.ShapeDtypeStruct(
            (b, cfg.n_kv_heads, cap, cfg.head_dim), jnp.bfloat16,
            sharding=cache_sh,
        )
        caches = [(kv, kv)]
        state_sds = model.init_decode_state(b, abstract=True)
        assert state_sds is not None and state_sds[0] is not None, (
            "force_compile must route decode onto the fused transport"
        )
        fn = jax.jit(model.decode_step)
        _assert_compiles(
            fn,
            params_sds,
            caches,
            _sds(tmesh, (b,), jnp.int32),
            _sds(tmesh, (b,), jnp.int32),
            state_sds,
        )

    def test_paged_flash_decode(self, tmesh):
        """Scalar-prefetch page-table index maps through real Mosaic."""
        import functools as ft

        from triton_distributed_tpu.kernels.flash_decode import (
            paged_gqa_fwd_batch_decode,
        )

        b, hq, hkv, d, page, pps, npages = 2, 16, 4, 128, 64, 4, 16
        fn = jax.jit(
            jax.shard_map(
                ft.partial(paged_gqa_fwd_batch_decode, interpret=False),
                mesh=tmesh, in_specs=(P(),) * 5, out_specs=(P(), P()),
                check_vma=False,
            )
        )
        _assert_compiles(
            fn,
            _sds(tmesh, (b, hq, d), jnp.bfloat16),
            _sds(tmesh, (npages, hkv, page, d), jnp.bfloat16),
            _sds(tmesh, (npages, hkv, page, d), jnp.bfloat16),
            _sds(tmesh, (b,), jnp.int32),
            _sds(tmesh, (b, pps), jnp.int32),
        )

    def test_flash_decode_q8(self, tmesh):
        """INT8 KV decode: the dynamic-trip-count kernel's quant mode —
        int8 payload DMAs + (B, Hkv, 1, S) scale-plane DMAs + in-softmax
        scale folds — through real Mosaic for the 8-chip topology."""
        import functools as ft

        from triton_distributed_tpu.kernels.flash_decode import (
            gqa_fwd_batch_decode_q8,
        )

        b, hq, hkv, d, s = 4, 16, 8, 128, 1024
        fn = jax.jit(
            jax.shard_map(
                ft.partial(
                    gqa_fwd_batch_decode_q8, interpret=False, block_k=512
                ),
                mesh=tmesh, in_specs=(P(),) * 6, out_specs=(P(), P()),
                check_vma=False,
            )
        )
        _assert_compiles(
            fn,
            _sds(tmesh, (b, hq, d), jnp.bfloat16),
            _sds(tmesh, (b, hkv, s, d), jnp.int8),
            _sds(tmesh, (b, hkv, s), jnp.float32),
            _sds(tmesh, (b, hkv, s, d), jnp.int8),
            _sds(tmesh, (b, hkv, s), jnp.float32),
            _sds(tmesh, (b,), jnp.int32),
        )

    def test_paged_flash_decode_q8(self, tmesh):
        """INT8 paged decode: table-driven int8 page windows + their
        scale windows through real Mosaic."""
        import functools as ft

        from triton_distributed_tpu.kernels.flash_decode import (
            paged_gqa_fwd_batch_decode_q8,
        )

        b, hq, hkv, d, page, pps, npages = 2, 16, 4, 128, 128, 4, 16
        fn = jax.jit(
            jax.shard_map(
                ft.partial(paged_gqa_fwd_batch_decode_q8, interpret=False),
                mesh=tmesh, in_specs=(P(),) * 7, out_specs=(P(), P()),
                check_vma=False,
            )
        )
        _assert_compiles(
            fn,
            _sds(tmesh, (b, hq, d), jnp.bfloat16),
            _sds(tmesh, (npages, hkv, page, d), jnp.int8),
            _sds(tmesh, (npages, hkv, page), jnp.float32),
            _sds(tmesh, (npages, hkv, page, d), jnp.int8),
            _sds(tmesh, (npages, hkv, page), jnp.float32),
            _sds(tmesh, (b,), jnp.int32),
            _sds(tmesh, (b, pps), jnp.int32),
        )

    def test_flash_decode_sp(self, tmesh):
        """SP decode: the per-device split-kv kernel + combine compiled
        over the sequence-sharded mesh (the serving hot path)."""
        from triton_distributed_tpu.layers.attention import (
            SpGQAFlashDecodeAttention,
        )

        b, hq, hkv, d, s_len = 2, 16, 4, 128, 2048
        layer = SpGQAFlashDecodeAttention(
            tmesh, "x", q_heads=hq, kv_heads=hkv, head_dim=d
        )
        fn = jax.jit(layer.__call__)
        _assert_compiles(
            fn,
            _sds(tmesh, (b, hq, d), jnp.bfloat16),
            _sds(tmesh, (b, hkv, s_len, d), jnp.bfloat16, None, None, "x"),
            _sds(tmesh, (b, hkv, s_len, d), jnp.bfloat16, None, None, "x"),
            _sds(tmesh, (b,), jnp.int32),
        )
