"""Test harness: 8 virtual CPU devices simulating a TPU slice.

The reference tests are torchrun multi-process scripts on real GPUs
(SURVEY.md §4). Here every test runs single-process on a virtual 8-device
CPU mesh; Pallas kernels execute under the TPU interpreter
(InterpretParams), which faithfully simulates remote DMA + semaphores.
Real-TPU execution of the same kernels is covered by bench.py and the
driver's dryrun.
"""

import faulthandler
import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

# importing config applies the jax API-drift compat shims (ensure_compat)
# BEFORE any fixture/test touches the shimmed names
from triton_distributed_tpu import config as _tdtpu_config  # noqa: E402

#: does this jax ship the TPU-simulation interpreter? Tests that need
#: faithful remote-DMA/semaphore simulation skip when it is absent
#: (collectives then only run through their XLA-native fallbacks).
HAS_TPU_SIM = _tdtpu_config.has_tpu_interpreter()

requires_tpu_sim = pytest.mark.skipif(
    not HAS_TPU_SIM,
    reason="jax lacks the Pallas TPU-simulation interpreter "
    "(pltpu.InterpretParams)",
)


#: Test modules whose every test exercises Pallas device semantics the
#: plain interpreter cannot provide (remote DMA, semaphores, the race
#: detector). Skipped wholesale when the TPU-simulation interpreter is
#: absent — the XLA-native degradation paths are covered elsewhere.
_SIM_REQUIRED_MODULES = frozenset({
    "test_lang_shmem", "test_races", "test_chaos", "test_ep_moe",
    "test_moe_tp", "test_ring_attention",
})

#: Individual tests known to WEDGE (not fail) without the simulator —
#: e.g. the LL-state decode scans hang in an XLA CPU collective
#: rendezvous on pre-interpreter jax. A wedge trips the per-test
#: faulthandler deadline, which hard-exits the whole suite, so these
#: are skipped up front.
_SIM_REQUIRED_KEYWORDS = ("ll_state", "fused_ll")


def pytest_collection_modifyitems(items):
    """Run the tuned-engine-selection tests LAST. They bench many
    interpreted kernels in rapid succession, which can leave the TPU
    interpreter's io_callback worker pool wedged on this 1-core host;
    an interpreted kernel running after them in the same process then
    deadlocks in the ordered-effects chain (observed as a hang in
    Token.block_until_ready). The full suite's alphabetical order
    already put test_tune last — this makes that load-bearing ordering
    explicit so subset runs are safe too.

    Also applies the no-TPU-simulator skips (see
    ``_SIM_REQUIRED_MODULES`` / ``_SIM_REQUIRED_KEYWORDS``)."""
    items.sort(key=lambda it: "TestTunedEngineSelection" in it.nodeid)
    if not HAS_TPU_SIM:
        skip = pytest.mark.skip(
            reason="requires the Pallas TPU-simulation interpreter "
            "(pltpu.InterpretParams), absent from this jax"
        )
        for it in items:
            if it.module.__name__ in _SIM_REQUIRED_MODULES or any(
                k in it.nodeid for k in _SIM_REQUIRED_KEYWORDS
            ):
                it.add_marker(skip)


@pytest.fixture(autouse=True)
def _fresh_interpreter_state():
    """Isolate tests: the TPU interpreter keeps global shared memory /
    semaphore state per process; stale state from a failed kernel must not
    leak into the next test. (On pre-interpreter jax the compat shim makes
    this a no-op — there is no global state to reset.)"""
    from jax.experimental.pallas import tpu as pltpu

    pltpu.reset_tpu_interpret_mode_state()
    yield


@pytest.fixture(autouse=True)
def _test_deadline():
    """Per-test wall-clock ceiling: a hung collective (wedged semaphore
    wait, starved io_callback pool) must fail the suite in seconds, not
    eat the full tier-1 budget. ``faulthandler.dump_traceback_later``
    fires from a watchdog thread even when the main thread is blocked
    inside a C++ wait (where ``signal.alarm`` would never be delivered):
    it dumps every thread's stack and hard-exits. Override the ceiling
    with ``TDTPU_TEST_TIMEOUT`` (seconds; 0 disables)."""
    ceiling = float(os.environ.get("TDTPU_TEST_TIMEOUT", "300"))
    if ceiling > 0:
        faulthandler.dump_traceback_later(ceiling, exit=True)
    try:
        yield
    finally:
        faulthandler.cancel_dump_traceback_later()


@pytest.fixture(scope="session")
def mesh8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return Mesh(np.asarray(devs), ("x",))


@pytest.fixture(scope="session")
def mesh2x4():
    devs = np.asarray(jax.devices()).reshape(2, 4)
    return Mesh(devs, ("dp", "tp"))


# ---------------------------------------------------------------- MoE helpers
# Shared by test_ep_moe / test_moe / test_chaos so the dense reference and
# the routed-data construction exist exactly once.

def dense_moe_ref(x, logits, w_up, w_down, topk, activation="silu"):
    """Per-token dense MoE reference: topk-weighted expert MLPs."""
    import jax
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels import moe_utils as mu

    weights, ids = mu.select_experts(logits, topk)
    act = jax.nn.silu if activation == "silu" else jax.nn.gelu
    out = jnp.zeros((x.shape[0], w_down.shape[-1]))
    for t in range(topk):
        h = act(jnp.einsum("mh,mhf->mf", x, w_up[ids[:, t]]))
        out += weights[:, t : t + 1] * jnp.einsum(
            "mf,mfh->mh", h, w_down[ids[:, t]]
        )
    return out


def moe_splits_data(n, m, num_experts, hidden, seed=0):
    """Random expert-sorted tokens + per-device splits (numpy)."""
    rng = np.random.default_rng(seed)
    assign = np.sort(rng.integers(0, num_experts, (n, m)), axis=1)
    splits = np.stack(
        [np.bincount(a, minlength=num_experts) for a in assign]
    ).astype(np.int32)
    toks = rng.standard_normal((n, m, hidden)).astype(np.float32)
    return toks, splits
