"""Test harness: 8 virtual CPU devices simulating a TPU slice.

The reference tests are torchrun multi-process scripts on real GPUs
(SURVEY.md §4). Here every test runs single-process on a virtual 8-device
CPU mesh; Pallas kernels execute under the TPU interpreter
(InterpretParams), which faithfully simulates remote DMA + semaphores.
Real-TPU execution of the same kernels is covered by bench.py and the
driver's dryrun.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402
from jax.sharding import Mesh  # noqa: E402


def pytest_collection_modifyitems(items):
    """Run the tuned-engine-selection tests LAST. They bench many
    interpreted kernels in rapid succession, which can leave the TPU
    interpreter's io_callback worker pool wedged on this 1-core host;
    an interpreted kernel running after them in the same process then
    deadlocks in the ordered-effects chain (observed as a hang in
    Token.block_until_ready). The full suite's alphabetical order
    already put test_tune last — this makes that load-bearing ordering
    explicit so subset runs are safe too."""
    items.sort(key=lambda it: "TestTunedEngineSelection" in it.nodeid)


@pytest.fixture(autouse=True)
def _fresh_interpreter_state():
    """Isolate tests: the TPU interpreter keeps global shared memory /
    semaphore state per process; stale state from a failed kernel must not
    leak into the next test."""
    from jax.experimental.pallas import tpu as pltpu

    pltpu.reset_tpu_interpret_mode_state()
    yield


@pytest.fixture(scope="session")
def mesh8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return Mesh(np.asarray(devs), ("x",))


@pytest.fixture(scope="session")
def mesh2x4():
    devs = np.asarray(jax.devices()).reshape(2, 4)
    return Mesh(devs, ("dp", "tp"))


# ---------------------------------------------------------------- MoE helpers
# Shared by test_ep_moe / test_moe / test_chaos so the dense reference and
# the routed-data construction exist exactly once.

def dense_moe_ref(x, logits, w_up, w_down, topk, activation="silu"):
    """Per-token dense MoE reference: topk-weighted expert MLPs."""
    import jax
    import jax.numpy as jnp

    from triton_distributed_tpu.kernels import moe_utils as mu

    weights, ids = mu.select_experts(logits, topk)
    act = jax.nn.silu if activation == "silu" else jax.nn.gelu
    out = jnp.zeros((x.shape[0], w_down.shape[-1]))
    for t in range(topk):
        h = act(jnp.einsum("mh,mhf->mf", x, w_up[ids[:, t]]))
        out += weights[:, t : t + 1] * jnp.einsum(
            "mf,mfh->mh", h, w_down[ids[:, t]]
        )
    return out


def moe_splits_data(n, m, num_experts, hidden, seed=0):
    """Random expert-sorted tokens + per-device splits (numpy)."""
    rng = np.random.default_rng(seed)
    assign = np.sort(rng.integers(0, num_experts, (n, m)), axis=1)
    splits = np.stack(
        [np.bincount(a, minlength=num_experts) for a in assign]
    ).astype(np.int32)
    toks = rng.standard_normal((n, m, hidden)).astype(np.float32)
    return toks, splits
