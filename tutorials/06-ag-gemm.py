"""Tutorial 06: fused AllGather-GEMM (the flagship TP overlap op).

≡ reference tutorial 07 / test_ag_gemm.py: the activation gather and
the matmul run as ONE engine — on TPU a shard-granular ring where each
step's MXU matmul overlaps the RDMA forwarding the next shard — instead
of allgather-then-dot.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu import ops

M, K, N = 256, 128, 512
ctx = ops.create_ag_gemm_context(mesh, "x")
a = jax.random.normal(jax.random.PRNGKey(0), (M, K), jnp.float32)
b = jax.random.normal(jax.random.PRNGKey(1), (K, N), jnp.float32)
ag = jax.device_put(a, NamedSharding(mesh, P("x")))
bg = jax.device_put(b, NamedSharding(mesh, P(None, "x")))
y = ops.ag_gemm(ag, bg, ctx)
np.testing.assert_allclose(np.asarray(y), np.asarray(a @ b), atol=2e-4, rtol=2e-4)
print("tutorial 06 OK: fused AG-GEMM == all_gather -> dot")
