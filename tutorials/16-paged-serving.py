"""Tutorial 16: paged serving — page pools, block tables, on-device
multi-step decode.

Production serving doesn't keep one contiguous KV slab per request —
it allocates fixed-size PAGES from a pool and addresses them through a
block table (the reference's block-table path is its default decode
entry, flash_decode.py:763-846). Round 5 makes that a first-class
model mode:

* ``Transformer.init_paged_cache(batch, capacity, page)`` — per-layer
  int8/bf16 page pools, rank-major over tp (rank r owns its sequence
  slice's pages), plus ONE (R, B, pages_per_slice) table of LOCAL page
  ids shared by every layer.
* ``Transformer.paginate_caches(caches, page)`` — the prefill→decode
  bridge: a contiguous prefill-filled cache converts to pools with one
  reshape per plane (pages of the dense identity allocation ARE the
  page-aligned rows; no gather).
* ``decode_step(..., block_table=table)`` — attention walks the table
  (scalar-prefetch index maps: the DMA engine fetches page[j] while
  page[j-1] computes) and ``paged_append_kv`` writes the new token
  through the table in place.
* ``generate(..., block_table=...)`` / ``generate_scan(...)`` — the
  serving loops run unchanged on pools; generate_scan folds the whole
  decode into ONE jitted lax.scan (one dispatch per SEQUENCE — behind
  a ~90 ms dispatch relay that is the difference between usable and
  not).
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import Transformer, TransformerConfig

cfg = TransformerConfig(
    vocab=128, n_layers=2, hidden=128, ffn=256,
    n_heads=8, n_kv_heads=4, head_dim=16,
    moe="ep", moe_layers=(1,), num_experts=8, topk=2,
    dtype=jnp.float32, param_dtype=jnp.float32,
)
model = Transformer(cfg, mesh, "x", ())
params = jax.tree.map(
    lambda p, s: jax.device_put(p, s),
    model.init(jax.random.PRNGKey(0)), model.shardings(),
)

B, PROMPT, STEPS, CAP, PAGE = 2, 16, 4, 64, 4  # 8 ranks × 2 pages × 4 rows

# ---- path A: contiguous prefill, then PAGINATE and decode from pools
prompt = jax.random.randint(jax.random.PRNGKey(1), (B, PROMPT), 0, cfg.vocab)
caches = model.init_cache(B, CAP)
last, caches, lens = model._prefill_jit(params, caches, prompt)
first = jnp.argmax(last, axis=-1).astype(jnp.int32)

pools, table = model.paginate_caches(caches, page=PAGE)
# the decode jits DONATE caches and lens (in-place update) — hand each
# serving path its own lens buffer (`+ 0`), the same discipline as any
# state shared across donating calls
toks_paged, pools, lens_p = model.generate(
    params, pools, lens + 0, first, STEPS, block_table=table
)

# contiguous twin from the same state → identical tokens
toks_flat, _, _ = model.generate(params, caches, lens + 0, first, STEPS)
np.testing.assert_array_equal(np.asarray(toks_paged), np.asarray(toks_flat))
print(f"paged generate == contiguous generate over {STEPS} steps")

# ---- path B: pool-native session (no contiguous stage at all), decoded
# by the ON-DEVICE multi-step entry (one jitted lax.scan)
pools2, table2 = model.init_paged_cache(B, CAP, page=PAGE)
toks_scan, pools2, lens2 = model.generate_scan(
    params, pools2, jnp.zeros((B,), jnp.int32), first, STEPS,
    block_table=table2,
)
toks_loop, _, _ = model.generate(
    params, model.init_paged_cache(B, CAP, page=PAGE)[0],
    jnp.zeros((B,), jnp.int32), first, STEPS, block_table=table2,
)
np.testing.assert_array_equal(np.asarray(toks_scan), np.asarray(toks_loop))
print(f"generate_scan (one program, {STEPS} steps) == per-step generate")
print("tutorial 16 OK")
