"""Tutorial 02: AllGather engines and auto-selection.

≡ reference tutorials 02/03 (intra-node AG + fast variants): the same
op runs as a neighbor ring (bandwidth), a bidirectional ring (half the
hops), or a single-shot full-mesh push for small messages (the
LL-protocol regime), and the entry picks by topology + message size.
"""

from _common import get_mesh

mesh = get_mesh()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
import jax

from triton_distributed_tpu.kernels import all_gather
from triton_distributed_tpu.runtime import AllGatherMethod

x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
xs = jax.device_put(x, NamedSharding(mesh, P("x")))

for method in (AllGatherMethod.RING_1D, AllGatherMethod.RING_BIDIR,
               AllGatherMethod.LL_SMALL, None):
    y = all_gather(xs, mesh, "x", method=method)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))
    print(f"  {method or 'auto'}: OK")

# the barrier-free LL protocol: a persistent double-buffered workspace
# replaces the entry barrier entirely (call it repeatedly — the parity
# double-buffering is the protocol)
for step in range(3):
    y = all_gather(xs, mesh, "x", method=AllGatherMethod.LL_PERSIST)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))
print("  ll_persist (barrier-free, 3 calls): OK")
print("tutorial 02 OK: all engines gather identically")
