"""Tutorial 13: the serving loop — one-pass prefill, then SP decode.

The reference leaves serving orchestration to the caller (its surface
is the SP decode layer, sp_flash_decode_layer.py); here the flagship
model completes the loop: ``prefill`` runs the forward stack once over
the whole prompt and fills the bhsd sequence-sharded KV caches, and
``generate`` continues with the distributed flash-decode kernel — one
forward pass replaces S decode steps.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import Transformer, TransformerConfig

cfg = TransformerConfig(
    vocab=128, n_layers=2, hidden=128, ffn=256,
    n_heads=8, n_kv_heads=4, head_dim=16,
    dtype=jnp.float32, param_dtype=jnp.float32,
)
model = Transformer(cfg, mesh, "x", ())
params = jax.tree.map(
    lambda p, s: jax.device_put(p, s),
    model.init(jax.random.PRNGKey(0)), model.shardings(),
)

B, PROMPT, STEPS, CAP = 2, 16, 4, 64
prompt = jax.random.randint(jax.random.PRNGKey(1), (B, PROMPT), 0, cfg.vocab)

# one forward pass processes the whole prompt and fills the caches
caches = model.init_cache(B, CAP)
last_logits, caches, lens = model._prefill_jit(params, caches, prompt)
assert np.asarray(lens).tolist() == [PROMPT] * B

# greedy continuation through the distributed flash-decode kernel
first = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
toks, caches, lens = model.generate(params, caches, lens, first, STEPS - 1)
completion = np.concatenate([np.asarray(first)[:, None], np.asarray(toks)], 1)
assert completion.shape == (B, STEPS)
assert np.asarray(lens).tolist() == [PROMPT + STEPS - 1] * B

# consistency: stepwise-decoding the prompt must land in the same state
caches_b = model.init_cache(B, CAP)
lens_b = jnp.zeros((B,), jnp.int32)
for t in range(PROMPT):
    logits_b, caches_b, lens_b = model._decode_jit(
        params, caches_b, lens_b, prompt[:, t]
    )
np.testing.assert_allclose(
    np.asarray(last_logits), np.asarray(logits_b), atol=2e-3, rtol=2e-3
)
print(f"prefill({PROMPT} tokens) + {STEPS}-token completion == stepwise decode")

# ---- the same serving loop with an EP-MoE model: decode routes every
# MoE block through the EP dispatch → sharded grouped expert MLP →
# combine machinery (expert weights stay sharded; the reference's
# EP-MoE inference headline, test_ep_moe_inference.py)
moe_cfg = TransformerConfig(
    vocab=128, n_layers=2, hidden=128, ffn=256,
    n_heads=8, n_kv_heads=4, head_dim=16,
    moe="ep", moe_layers=(0, 1), num_experts=8, topk=2,
    dtype=jnp.float32, param_dtype=jnp.float32,
)
moe_model = Transformer(moe_cfg, mesh, "x", ())
moe_params = jax.tree.map(
    lambda p, s: jax.device_put(p, s),
    moe_model.init(jax.random.PRNGKey(2)), moe_model.shardings(),
)
caches_m = moe_model.init_cache(B, CAP)
last_m, caches_m, lens_m = moe_model._prefill_jit(moe_params, caches_m, prompt)
first_m = jnp.argmax(last_m, axis=-1).astype(jnp.int32)
toks_m, caches_m, lens_m = moe_model.generate(
    moe_params, caches_m, lens_m, first_m, STEPS - 1
)
assert np.asarray(toks_m).shape == (B, STEPS - 1)
assert np.asarray(lens_m).tolist() == [PROMPT + STEPS - 1] * B
print(f"EP-MoE serving loop: prefill + {STEPS}-token completion through ep_moe")
print("tutorial 13 OK")
