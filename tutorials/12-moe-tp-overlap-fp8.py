"""Tutorial 12: overlapped MoE-TP, fp8 EP transport, hierarchical EP.

Round-2 flagships in one walk-through:

* the single-kernel overlapped MoE-TP pipeline (AG⊕GroupGEMM →
  GroupGEMM⊕Reduce-RS, kernels/moe_tp_fused.py) — per-shard expert-
  sorted token slabs ride the ring while arrived shards stream through
  grouped-GEMM pipelines (≡ reference allgather_group_gemm.py:420-498 +
  moe_reduce_rs.py:362-545);
* the fp8 wire format for EP dispatch/combine — tokens at 1 byte/elem
  with per-token scales packed in-slot (≡ the WITH_SCALE fp8 headline
  config, low_latency_all_to_all.py:43-107);
* the hierarchical DCN-aware EP exchange — same-local-rank rail leg
  over the slice axis + intra-slice Pallas leg (≡ ep_a2a.py:36-150).
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.ops import (
    create_ag_group_gemm_context,
    create_ep_moe_context,
    ep_moe,
    moe_tp_mlp_overlapped,
)

E, TOPK, M, K, F, H = 16, 2, 64, 128, 256, 128

x = jax.random.normal(jax.random.PRNGKey(0), (M, K), jnp.float32)
logits = jax.random.normal(jax.random.PRNGKey(1), (M, E))
w_up = jax.random.normal(jax.random.PRNGKey(2), (E, K, F), jnp.float32) * 0.05
w_down = jax.random.normal(jax.random.PRNGKey(3), (E, F, H), jnp.float32) * 0.05
weights, ids = mu.select_experts(logits, TOPK)

dense = jnp.zeros((M, H))
for t in range(TOPK):
    h = jax.nn.silu(jnp.einsum("mk,mkf->mf", x, w_up[ids[:, t]]))
    dense += weights[:, t : t + 1] * jnp.einsum("mf,mfh->mh", h, w_down[ids[:, t]])

# ---- 1. overlapped MoE-TP (tokens sharded, experts' columns sharded) ----
ctx = create_ag_group_gemm_context(
    mesh, "x", num_experts=E, topk=TOPK, block_m=8, dtype=jnp.float32
)
out = moe_tp_mlp_overlapped(
    jax.device_put(x, NamedSharding(mesh, P("x"))),
    jax.device_put(ids, NamedSharding(mesh, P("x"))),
    jax.device_put(weights, NamedSharding(mesh, P("x"))),
    jax.device_put(w_up, NamedSharding(mesh, P(None, None, "x"))),
    jax.device_put(w_down, NamedSharding(mesh, P(None, "x"))),
    ctx,
)
np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=2e-5, rtol=2e-5)
print("overlapped MoE-TP == dense MoE")

# ---- 2. EP with the fp8 wire format (+ per-token scales in-slot) --------
ep_ctx = create_ep_moe_context(
    mesh, "x", num_experts=E, topk=TOPK, max_m=(M // 8) * TOPK, hidden=K,
    dtype=jnp.float32, transport="pallas", block_m=8, quant="fp8",
)
w_up_ep = jax.random.normal(jax.random.PRNGKey(4), (E, K, F), jnp.float32) * 0.05
w_down_ep = jax.random.normal(jax.random.PRNGKey(5), (E, F, K), jnp.float32) * 0.05
dense_ep = jnp.zeros((M, K))
for t in range(TOPK):
    h = jax.nn.silu(jnp.einsum("mk,mkf->mf", x, w_up_ep[ids[:, t]]))
    dense_ep += weights[:, t : t + 1] * jnp.einsum("mf,mfk->mk", h, w_down_ep[ids[:, t]])
rows = NamedSharding(mesh, P("x"))
out_ep = ep_moe(
    jax.device_put(x, rows), jax.device_put(logits, rows),
    jax.device_put(w_up_ep, rows), jax.device_put(w_down_ep, rows), ep_ctx,
)
err = np.abs(np.asarray(out_ep) - np.asarray(dense_ep)).max()
scale = np.abs(np.asarray(dense_ep)).max()
assert err < 0.08 * scale, (err, scale)
print(f"fp8 EP dispatch/combine within quant tolerance ({err / scale:.1%} of scale)")

# ---- 3. hierarchical EP on a (dcn=2, ep=4) mesh -------------------------
devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
hmesh = Mesh(devs, ("dcn", "ep"))
hier_ctx = create_ep_moe_context(
    hmesh, "ep", dcn_axis="dcn", num_experts=E, topk=TOPK,
    max_m=(M // 8) * TOPK, hidden=K, dtype=jnp.float32,
    transport="pallas", block_m=8,
)
hrows = NamedSharding(hmesh, P(("dcn", "ep")))
out_h = ep_moe(
    jax.device_put(x, hrows), jax.device_put(logits, hrows),
    jax.device_put(w_up_ep, hrows), jax.device_put(w_down_ep, hrows), hier_ctx,
)
np.testing.assert_allclose(
    np.asarray(out_h), np.asarray(dense_ep), atol=2e-5, rtol=2e-5
)
print("hierarchical (rail-leg) EP == dense MoE")
print("tutorial 12 OK")
