"""Tutorial 14: barrier-free EP-MoE decode (the LL call_count protocol).

≡ the reference's low-latency AllToAll call protocol
(low_latency_all_to_all.py:97-118): persistent symmetric buffers +
call-count double buffering remove the per-call barrier — the latency
tax that dominates small decode-step exchanges. Here the same protocol
is a FUNCTIONAL CARRY: `create_ep_moe_state` allocates the persistent
double-buffered workspaces, `ep_moe(..., state=)` runs both a2a legs
barrier-free and returns the rolled state, and because the state is an
ordinary pytree the whole decode loop can live inside one jit.

Wire bytes are count-bounded (ceil(count/chunk)·chunk rows per peer —
the reference's exact per-expert ranges, :62-90), so the transport
moves what the router routed, not the worst case.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.ops import (
    create_ep_moe_context,
    create_ep_moe_state,
    ep_moe,
)

n = mesh.shape["x"]
E, topk, H, F, M = 2 * n, 2, 128, 256, 16

ctx = create_ep_moe_context(
    mesh, "x", num_experts=E, topk=topk, max_m=M * topk, hidden=H,
    dtype=jnp.float32, transport="fused",      # the chunked DMA kernels
    block_m=8, use_pallas_gemm=False,
)
state = create_ep_moe_state(ctx)               # persistent LL workspaces

rng = np.random.default_rng(0)
w_up = jnp.asarray(rng.standard_normal((E, H, F)) * 0.05, jnp.float32)
w_down = jnp.asarray(rng.standard_normal((E, F, H)) * 0.05, jnp.float32)
sh = NamedSharding(mesh, P("x"))
args_w = (jax.device_put(w_up, sh), jax.device_put(w_down, sh))


def dense_ref(x, logits):
    w, ids = mu.select_experts(logits, topk)
    out = jnp.zeros((x.shape[0], H))
    for t in range(topk):
        h = jax.nn.silu(jnp.einsum("mh,mhf->mf", x, w_up[ids[:, t]]))
        out += w[:, t:t + 1] * jnp.einsum("mf,mfh->mh", h, w_down[ids[:, t]])
    return out


# ---- decode-style loop: every call rolls the parity; NO barrier_all is
# issued by either a2a leg (compare tutorial 04's barrier'd transport)
for step in range(4):
    x = jnp.asarray(rng.standard_normal((n * M, H)), jnp.float32)
    logits = jnp.asarray(rng.standard_normal((n * M, E)), jnp.float32)
    out, state = ep_moe(
        jax.device_put(x, sh), jax.device_put(logits, sh), *args_w,
        ctx, state=state,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense_ref(x, logits)),
        atol=1e-5, rtol=1e-5,
    )
    print(f"step {step}: parity -> {int(np.asarray(state.parity)[0])}, "
          "output matches dense reference")

print("tutorial 14 OK: barrier-free LL EP-MoE, state as a functional carry")
print("(Transformer.decode_step threads the same state per MoE layer —")
print(" see models/transformer.py init_decode_state/generate)")
