"""Tutorial 09: train the flagship transformer on a dp x tp mesh.

Beyond the reference's scope (it ships kernels, not a trainer): every
projection runs through the fused overlap ops, the MoE block through
the EP a2a, gradients reduce over dp — one jitted program.
"""

from _common import get_mesh

mesh1d = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_distributed_tpu.models import Transformer, TransformerConfig

devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
mesh = Mesh(devs, ("dp", "tp"))
cfg = TransformerConfig(vocab=128, n_layers=2, hidden=128, ffn=256,
                        n_heads=8, n_kv_heads=4, head_dim=16,
                        moe="ep", moe_layers=(1,), num_experts=8, topk=2,
                        dtype=jnp.float32, param_dtype=jnp.float32)
model = Transformer(cfg, mesh, "tp", ("dp",))
params = jax.tree.map(lambda p, s: jax.device_put(p, s),
                      model.init(jax.random.PRNGKey(0)), model.shardings())
toks = jax.device_put(
    jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 128),
    NamedSharding(mesh, P("dp")))
step = jax.jit(model.train_step)
losses = []
for _ in range(3):
    loss, params = step(params, toks, toks)
    losses.append(float(loss))
print("losses:", [f"{l:.4f}" for l in losses])
assert losses[-1] < losses[0]
print("tutorial 09 OK: loss decreases under dp x tp training")
