"""Tutorial 11: long-context training attention — ring and Ulysses.

Beyond the reference's decode-only sequence parallelism: both standard
context-parallel schemes, differentiable end to end. Ring rotates KV
blocks around the mesh while partial attention folds into online-softmax
state; Ulysses re-shards seq->heads with one all-to-all and runs local
attention over the full sequence.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels import ring_attention, ulysses_attention
from triton_distributed_tpu.kernels.ring_attention import (
    dense_attention_reference,
)

B, S, Hq, Hkv, D = 2, 512, 8, 4, 64   # sequence 8x longer than one shard
q = jax.random.normal(jax.random.PRNGKey(0), (B, S, Hq, D), jnp.float32)
k = jax.random.normal(jax.random.PRNGKey(1), (B, S, Hkv, D), jnp.float32)
v = jax.random.normal(jax.random.PRNGKey(2), (B, S, Hkv, D), jnp.float32)
sh = NamedSharding(mesh, P(None, "x"))
qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))

ref = dense_attention_reference(q, k, v)
for name, fn in (("ring", ring_attention), ("ulysses", ulysses_attention)):
    out = fn(qs, ks, vs, mesh, "x")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)
    # gradients flow through the collectives
    g = jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v, mesh, "x") ** 2))(qs, ks, vs)
    assert np.isfinite(np.asarray(g).sum())
    print(f"  {name}: fwd == dense causal, grads finite")
print("tutorial 11 OK: context-parallel attention, trainable")
