"""Shared tutorial bootstrap: an 8-device virtual CPU mesh when no
multi-chip TPU slice is attached (the conftest env dance), real devices
otherwise. Every tutorial is a standalone script: `python tutorials/NN-*.py`.
"""

import os
import pathlib
import sys

# run from anywhere: the repo root is the package root
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def get_mesh(min_devices: int = 8):
    """An ``(min_devices,)`` mesh named "x". Default: a virtual CPU mesh
    (the demos run anywhere); TDTPU_LOCAL_DEVICES (the launch.sh knob)
    overrides the size, and TDTPU_TUTORIAL_TPU=1 runs on a real slice
    with enough chips instead."""
    import jax

    min_devices = int(os.environ.get("TDTPU_LOCAL_DEVICES", min_devices))
    if os.environ.get("TDTPU_TUTORIAL_TPU") != "1":
        try:
            # Must happen before any backend is touched.
            jax.config.update("jax_num_cpu_devices", min_devices)
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    import numpy as np
    from jax.sharding import Mesh

    from triton_distributed_tpu import runtime

    runtime.initialize_distributed()
    devs = jax.devices()
    assert len(devs) >= min_devices, (
        f"need {min_devices} devices, have {len(devs)}"
    )
    return Mesh(np.asarray(devs[:min_devices]), ("x",))
