"""Tutorial 04: MoE low-latency AllToAll (EP dispatch/combine).

≡ reference tutorial 04 (DeepEP-style a2a, low_latency_all_to_all.py):
tokens sorted by destination expert ride per-peer padded slots with
their counts packed in the same RDMA payload; the combine leg returns
processed tokens to their owners.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels import moe_all_to_all as ma

n, epr, H, M = mesh.shape["x"], 2, 128, 16
E = n * epr
ctx = ma.create_all_to_all_context(
    mesh, "x", max_m=M, hidden=H, experts_per_rank=epr, dtype=jnp.float32
)

rng = np.random.default_rng(0)
assign = np.sort(rng.integers(0, E, (n, M)), axis=1)
splits = np.stack([np.bincount(a, minlength=E) for a in assign]).astype(np.int32)
toks = rng.standard_normal((n, M, H)).astype(np.float32)

sh = NamedSharding(mesh, P("x"))
stage = jax.jit(jax.shard_map(
    lambda t, s: ma.pack_slots(ctx, *ma.dispatch_stage(ctx, t, s)),
    mesh=mesh, in_specs=(P("x"), P("x")), out_specs=P("x"), check_vma=False))
send = stage(jax.device_put(jnp.asarray(toks).reshape(n * M, H), sh),
             jax.device_put(jnp.asarray(splits).reshape(n * E), sh))
recv = ma.fast_all_to_all(ctx, send)              # dispatch: one RDMA per peer
back_in = jax.jit(jax.shard_map(
    lambda r: ma.combine_stage(ctx, ma.recv_tokens_view(ctx, r)[0]),
    mesh=mesh, in_specs=P("x"), out_specs=P("x"), check_vma=False))(recv)
comb = ma.fast_all_to_all(ctx, back_in)           # combine: the return leg
out = jax.jit(jax.shard_map(
    lambda c, s: ma.combine_unstage(ctx, ma.combine_unpack(ctx, c), s, M),
    mesh=mesh, in_specs=(P("x"), P("x")), out_specs=P("x"), check_vma=False))(
        comb, jax.device_put(jnp.asarray(splits).reshape(n * E), sh))
np.testing.assert_allclose(np.asarray(out).reshape(n, M, H), toks, rtol=1e-6)
print("tutorial 04 OK: dispatch/combine round-trip is exact")

# ---- the FUSED count-bounded dispatch (kernels/moe_dispatch): the
# transport kernel ships each peer ceil(count/chunk) chunk DMAs straight
# from the aligned expert-sorted payload — wire bytes track the true
# counts (≡ the reference's exact per-expert ranges,
# low_latency_all_to_all.py:62-90); this is the headline path (docs/PERF.md)
from triton_distributed_tpu.kernels import moe_dispatch as mdk

def fused_roundtrip(t_loc, se_loc, spl_loc):
    spl_loc = spl_loc.reshape(-1)
    T = t_loc.shape[0]
    counts, offs, offs_al, sendk = mdk.send_plan(ctx, spl_loc)
    peer, dest = mdk.assignment_dest(ctx, se_loc, offs, offs_al)
    payload, scales = mdk.stage_aligned(
        ctx, t_loc, jnp.arange(T, dtype=jnp.int32), dest, T
    )
    meta = mdk.meta_payload(ctx, spl_loc, scales, offs_al, sendk)
    rtok, rmeta = mdk.dispatch_device(ctx, payload, offs_al, sendk, meta)
    toks_in, rspl = mdk.recv_view(ctx, rtok, rmeta)
    # identity "expert compute", then the slot-regular return leg
    y_tok, y_meta = mdk.stage_return(ctx, toks_in)
    retk = -(-jnp.sum(rspl, axis=1) // mdk.chunk_rows(ctx))
    c_tok, c_meta = mdk.combine_device(ctx, y_tok, y_meta, retk, sendk)
    return mdk.combine_view(ctx, c_tok, c_meta, peer, dest, offs_al, T)

rt = jax.jit(jax.shard_map(
    fused_roundtrip, mesh=mesh, in_specs=(P("x"), P("x"), P("x")),
    out_specs=P("x"), check_vma=False))(
        jax.device_put(jnp.asarray(toks).reshape(n * M, H), sh),
        jax.device_put(jnp.asarray(assign.astype(np.int32)).reshape(-1), sh),
        jax.device_put(jnp.asarray(splits).reshape(n * E), sh))
np.testing.assert_allclose(np.asarray(rt).reshape(n, M, H), toks, rtol=1e-5)
print("tutorial 04 OK: fused chunked-DMA dispatch round-trip is exact")
