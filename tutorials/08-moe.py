"""Tutorial 08: MoE both ways — expert-parallel and tensor-parallel.

≡ reference test_ep_moe_inference.py (EP over the a2a) and
test_ag_moe.py / test_moe_reduce_rs.py (MoE TP): the same router +
expert weights, two distributions of work.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu import ops
from triton_distributed_tpu.kernels import moe_utils as mu

n = mesh.shape["x"]
E, topk, H, F, Mtok = 2 * n, 2, 128, 256, 16
x = jax.random.normal(jax.random.PRNGKey(0), (n * Mtok, H), jnp.float32)
logits = jax.random.normal(jax.random.PRNGKey(1), (n * Mtok, E))
w_up = jax.random.normal(jax.random.PRNGKey(2), (E, H, F), jnp.float32) * 0.05
w_down = jax.random.normal(jax.random.PRNGKey(3), (E, F, H), jnp.float32) * 0.05
weights, ids = mu.select_experts(logits, topk)
ref = jnp.zeros((n * Mtok, H))
for t in range(topk):
    h = jax.nn.silu(jnp.einsum("mh,mhf->mf", x, w_up[ids[:, t]]))
    ref += weights[:, t:t + 1] * jnp.einsum("mf,mfh->mh", h, w_down[ids[:, t]])

rows = NamedSharding(mesh, P("x"))
# --- EP: experts sharded over ranks, tokens dispatched to them
ep = ops.create_ep_moe_context(mesh, "x", num_experts=E, topk=topk,
                               max_m=Mtok * topk, hidden=H,
                               dtype=jnp.float32, block_m=8)
y_ep = ops.ep_moe(jax.device_put(x, rows), jax.device_put(logits, rows),
                  jax.device_put(w_up, rows), jax.device_put(w_down, rows), ep)
np.testing.assert_allclose(np.asarray(y_ep), np.asarray(ref), atol=1e-4)
print("  EP MoE OK")

# --- TP: every rank holds a column slice of every expert
from triton_distributed_tpu.layers import MoETPMLP
tp = ops.create_ag_group_gemm_context(mesh, "x", num_experts=E, topk=topk,
                                      block_m=8, dtype=jnp.float32)
y_tp = MoETPMLP(tp)(
    {"up": jax.device_put(w_up, NamedSharding(mesh, P(None, None, "x"))),
     "down": jax.device_put(w_down, NamedSharding(mesh, P(None, "x")))},
    jax.device_put(x, rows), ids, weights)
np.testing.assert_allclose(np.asarray(y_tp), np.asarray(ref), atol=1e-4)
print("  TP MoE OK")
print("tutorial 08 OK: EP and TP MoE agree with the dense reference")
