"""Tutorial 01: notify/wait producer-consumer over remote DMA.

≡ reference tutorials/01-distributed-notify-wait.py: the producer puts a
payload into its right neighbor's buffer and raises a signal; the
consumer waits on the signal before reading. On TPU the put is a Pallas
async remote copy whose receive semaphore fires after the payload lands,
so signal-after-data ordering is a hardware guarantee.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import lang


def kernel(x_ref, out_ref, scratch, send_sem, recv_sem, flag):
    me, n = lang.my_pe("x"), lang.n_pes("x")
    right = jax.lax.rem(me + 1, n)
    # producer: put payload into right neighbor's scratch, then notify it
    h = lang.putmem_signal_nbi_block(scratch, x_ref, send_sem, recv_sem, right)
    lang.quiet(h)
    lang.signal_op(flag, 1, pe=right)
    # consumer: wait for the notify and the payload, then use it
    lang.signal_wait_until(flag, 1)
    h.wait_recv()
    out_ref[:] = scratch[:] + 1000.0


call = lang.shmem_call(
    kernel,
    out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
    in_specs=lang.vmem_specs(1),
    scratch_shapes=[
        pltpu.VMEM((8, 128), jnp.float32),
        pltpu.SemaphoreType.DMA(()),
        pltpu.SemaphoreType.DMA(()),
        pltpu.SemaphoreType.REGULAR,
    ],
)
f = lang.on_mesh(mesh, in_specs=P("x"), out_specs=P("x"))(call)

x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
y = f(x)
np.testing.assert_allclose(np.asarray(y), np.roll(np.asarray(x), 8, 0) + 1000.0)
print("tutorial 01 OK: every device received its left neighbor's payload")
