"""Tutorial 07: fused GEMM-ReduceScatter (the dual overlap op).

≡ reference tutorial 08 / test_gemm_rs.py: the row-parallel matmul's
partial outputs feed the ring reduce-scatter as they complete.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu import ops

M, K, N = 256, 512, 128
ctx = ops.create_gemm_rs_context(mesh, "x")
a = jax.random.normal(jax.random.PRNGKey(0), (M, K), jnp.float32)
b = jax.random.normal(jax.random.PRNGKey(1), (K, N), jnp.float32)
ag = jax.device_put(a, NamedSharding(mesh, P(None, "x")))
bg = jax.device_put(b, NamedSharding(mesh, P("x", None)))
y = ops.gemm_rs(ag, bg, ctx)
np.testing.assert_allclose(np.asarray(y), np.asarray(a @ b), atol=2e-4, rtol=2e-4)
print("tutorial 07 OK: fused GEMM-RS == dot -> reduce_scatter")
