"""Tutorial 05: sequence-parallel distributed flash-decode.

≡ reference test_sp_decode_attn.py / sp_flash_decode_layer.py: the KV
cache is sharded over the sequence across devices; each device runs an
online-softmax decode over its shard, the (out, lse) partials are
all-gathered, and the blockwise-softmax merge renormalizes — the
ring-attention combine, done once over ranks.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.layers import SpGQAFlashDecodeAttention
from triton_distributed_tpu.kernels.flash_decode import gqa_fwd_batch_decode_xla

B, Hq, Hkv, D, S = 2, 8, 2, 128, 2048
# The layer's default cache layout is "bhsd" (B, Hkv, S, D) — each KV
# block is one contiguous DMA run (~97% of HBM speed-of-light on v5e).
# Callers holding reference-style (B, S, Hkv, D) caches pass
# kv_layout="bshd" instead.
layer = SpGQAFlashDecodeAttention(
    mesh, "x", q_heads=Hq, kv_heads=Hkv, head_dim=D, block_k=128
)
q = jax.random.normal(jax.random.PRNGKey(0), (B, Hq, D), jnp.float32)
k = jax.random.normal(jax.random.PRNGKey(1), (B, Hkv, S, D), jnp.float32)
v = jax.random.normal(jax.random.PRNGKey(2), (B, Hkv, S, D), jnp.float32)
lens = jnp.array([1800, 700], jnp.int32)   # ragged: shards may be empty

out = layer(q, k, v, lens)
ref, _ = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bhsd")
np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2, rtol=2e-2)
print("tutorial 05 OK: SP decode == dense attention over the full cache")

# ---- PAGED mode (the reference layer's block_table surface): each rank
# owns a page POOL of its sequence slice plus the table addressing it.
# TPU guidance: pages should be >=1024 rows at scale (docs/PERF.md);
# tiny here for the demo mesh.
R, PAGE, PPS = mesh.shape["x"], 16, S // (mesh.shape["x"] * 16)
npl = B * PPS                                  # pages per rank's pool
rng = np.random.default_rng(3)
perm = np.stack([rng.permutation(npl).reshape(B, PPS) for _ in range(R)])
table = jnp.asarray(perm.astype(np.int32))     # (R, B, pages_per_slice)

# scatter the contiguous caches into the per-rank pools (serving stacks
# write pages directly; here we derive them so the answers must match)
k_np = np.asarray(k).reshape(B, Hkv, R, PPS, PAGE, D)
v_np = np.asarray(v).reshape(B, Hkv, R, PPS, PAGE, D)
k_pool = np.zeros((R * npl, Hkv, PAGE, D), np.float32)
v_pool = np.zeros((R * npl, Hkv, PAGE, D), np.float32)
for r in range(R):
    for b in range(B):
        for j in range(PPS):
            pid = r * npl + perm[r, b, j]
            k_pool[pid] = k_np[b, :, r, j]
            v_pool[pid] = v_np[b, :, r, j]

out_paged = layer(
    q, jnp.asarray(k_pool), jnp.asarray(v_pool), lens,
    block_table=table,
)
np.testing.assert_allclose(
    np.asarray(out_paged), np.asarray(ref), atol=2e-2, rtol=2e-2
)
print("tutorial 05 OK: paged (block-table) SP decode == dense attention")
