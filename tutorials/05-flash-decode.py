"""Tutorial 05: sequence-parallel distributed flash-decode.

≡ reference test_sp_decode_attn.py / sp_flash_decode_layer.py: the KV
cache is sharded over the sequence across devices; each device runs an
online-softmax decode over its shard, the (out, lse) partials are
all-gathered, and the blockwise-softmax merge renormalizes — the
ring-attention combine, done once over ranks.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.layers import SpGQAFlashDecodeAttention
from triton_distributed_tpu.kernels.flash_decode import gqa_fwd_batch_decode_xla

B, Hq, Hkv, D, S = 2, 8, 2, 128, 2048
# The layer's default cache layout is "bhsd" (B, Hkv, S, D) — each KV
# block is one contiguous DMA run (~97% of HBM speed-of-light on v5e).
# Callers holding reference-style (B, S, Hkv, D) caches pass
# kv_layout="bshd" instead.
layer = SpGQAFlashDecodeAttention(
    mesh, "x", q_heads=Hq, kv_heads=Hkv, head_dim=D, block_k=128
)
q = jax.random.normal(jax.random.PRNGKey(0), (B, Hq, D), jnp.float32)
k = jax.random.normal(jax.random.PRNGKey(1), (B, Hkv, S, D), jnp.float32)
v = jax.random.normal(jax.random.PRNGKey(2), (B, Hkv, S, D), jnp.float32)
lens = jnp.array([1800, 700], jnp.int32)   # ragged: shards may be empty

out = layer(q, k, v, lens)
ref, _ = gqa_fwd_batch_decode_xla(q, k, v, lens, kv_layout="bhsd")
np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2, rtol=2e-2)
print("tutorial 05 OK: SP decode == dense attention over the full cache")
