"""Tutorial 15: the int8 serving stack — quantize every heavy plane.

Serving decode is HBM-bound three ways, and each plane gets its own
int8 treatment with exact scale folds (docs/PERF.md, round 4):

* KV cache (``kv_quant="int8"``): int8 values + one f32 scale per
  (batch, head, position) row; the flash-decode kernel folds K's
  per-column scale into the scores and V's into p before the PV dot,
  so no D-wide dequantization multiply ever runs. Half the cache HBM
  — 2× the context per chip — and 25–40% faster decode attention.
* Expert matrices (``moe_weight_quant="int8"``): per-(expert,
  out-channel) scales folded into the grouped-GEMM f32 epilogue
  (exact: dequantization is linear over the K reduction).
* Expert ACTIVATIONS too (``moe_act_quant="int8"``, W8A8): per-row
  int8 tokens into the MXU's native s8×s8 path at 2× the bf16 rate,
  rank-1 scale correction on the s32 accumulator.
* Dense projections (``dense_weight_quant="int8"``): the same
  epilogue-dequant kernel with E=1 and block_m=B (one M-block — the
  grid iterates m outermost, so more blocks would re-stream the
  weight tiles).

The reference quantizes only the tokens moving through the MoE wire
(fp8 WITH_SCALE, low_latency_all_to_all.py:82-90); the stationary
planes are TPU-first extensions. Measured all together at the serving
headline (B=128, hidden 7168, topk 8, v5e): 4.5 → 2.48 ms/step.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import Transformer, presets

# the DeepSeek serving preset ships all four planes on; the tiny()
# twin keeps the same quantization topology at CI size
cfg = presets.tiny(presets.deepseek_moe_16b())
assert cfg.kv_quant == "int8"
assert cfg.moe_weight_quant == "int8"
assert cfg.moe_act_quant == "int8"
assert cfg.dense_weight_quant == "int8"

model = Transformer(cfg, mesh, "x", ())
params = jax.tree.map(
    lambda p, s: jax.device_put(p, s),
    model.init(jax.random.PRNGKey(0)), model.shardings(),
)

# quantize AFTER init/load + device placement (the quantized leaves
# inherit the sharding of their sources)
params = model.quantize_moe_weights(params)
params = model.quantize_dense_weights(params)
assert params["blocks"][0]["wqkv"]["q"].dtype == jnp.int8
assert params["lm_head"]["q"].dtype == jnp.int8

B, PROMPT, STEPS, CAP = 4, 12, 4, 64
prompt = jax.random.randint(jax.random.PRNGKey(1), (B, PROMPT), 0, cfg.vocab)

# init_cache sees kv_quant and allocates {"q": int8, "scale": f32}
# dicts; prefill quantizes its K/V writes row-by-row
caches = model.init_cache(B, CAP)
assert caches[0][0]["q"].dtype == jnp.int8
last_logits, caches, lens = model._prefill_jit(params, caches, prompt)

first = jax.numpy.argmax(last_logits, axis=-1).astype(jnp.int32)
toks, caches, lens = model.generate(params, caches, lens, first, steps=STEPS)
print("int8-stack generation:", np.asarray(toks))

# the full-precision model (same weights pre-quantization) agrees to
# within int8 noise on the first decode logits
cfg_f = presets.tiny(presets.deepseek_moe_16b(), kv_quant=None,
                     moe_weight_quant=None, moe_act_quant=None,
                     dense_weight_quant=None)
model_f = Transformer(cfg_f, mesh, "x", ())
params_f = jax.tree.map(
    lambda p, s: jax.device_put(p, s),
    model_f.init(jax.random.PRNGKey(0)), model_f.shardings(),
)
caches_f = model_f.init_cache(B, CAP)
last_f, caches_f, lens_f = model_f._prefill_jit(params_f, caches_f, prompt)
err = np.abs(np.asarray(last_logits) - np.asarray(last_f)).max()
rel = err / np.abs(np.asarray(last_f)).max()
print(f"quantized vs full-precision prefill logits: rel err {rel:.4f}")
assert rel < 0.05, rel
print("OK")
