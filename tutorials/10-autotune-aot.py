"""Tutorial 10: autotune a kernel choice, then ship it AOT.

≡ reference autotuner.py (thunk-level contextual autotune) +
tools/compile_aot.py (artifact per signature point, dispatcher over
them).
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.tune import contextual_autotune, estimate_gemm_ms, detect_spec
from triton_distributed_tpu.tools import aot_compile_spaces
from triton_distributed_tpu.kernels import moe_utils as mu, group_gemm as gg

E, topk, M, K, N = 8, 2, 64, 128, 256
_, ids = mu.select_experts(jax.random.normal(jax.random.PRNGKey(0), (M, E)), topk)
x = jax.random.normal(jax.random.PRNGKey(1), (M, K), jnp.float32)
w = jax.random.normal(jax.random.PRNGKey(2), (E, K, N), jnp.float32) * 0.05


@contextual_autotune(configs=[{"block_m": 8}, {"block_m": 16}], log=False)
def moe_gemm(x, w, ids, *, block_m):
    sti, be, _ = mu.moe_align_block_size(ids, E, block_m)
    return gg.grouped_matmul(mu.gather_sorted(x, sti, topk), w, be,
                             block_m=block_m)


y = moe_gemm(x, w, ids)          # benches both configs, picks, caches
y2 = moe_gemm(x, w, ids)         # cache hit
print(f"  autotuned grouped GEMM -> {y.shape}")
print(f"  model check: 4k^3 GEMM SoL on {detect_spec().name} = "
      f"{estimate_gemm_ms(4096, 4096, 4096):.2f} ms")

lib = aot_compile_spaces(
    lambda a, b: a @ b,
    spaces=[(jnp.ones((64, 128)), jnp.ones((128, 64))),
            (jnp.ones((32, 128)), jnp.ones((128, 64)))],
    name="mm", cache_dir="/tmp/tdtpu_tutorial_aot")
out = lib(jnp.ones((32, 128)), jnp.ones((128, 64)))   # dispatches by shape
np.testing.assert_allclose(np.asarray(out), 128.0)
print("tutorial 10 OK: autotune picked a config; AOT library dispatches by shape")
