"""Tutorial 03: ring ReduceScatter.

≡ reference tutorial on reduce_scatter.py: per-device partial
contributions are summed around the ring and each device keeps its
shard. The `stacked` layout is the GEMM-partials case the overlap ops
feed.
"""

from _common import get_mesh

mesh = get_mesh()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels import reduce_scatter, reduce_scatter_xla

n = mesh.shape["x"]
parts = jnp.arange(n * 64 * 128, dtype=jnp.float32).reshape(n, 64, 128) / 1e3
xs = jax.device_put(parts, NamedSharding(mesh, P("x")))
y = reduce_scatter(xs, mesh, "x", stacked=True)
y_ref = reduce_scatter_xla(xs, mesh, "x", stacked=True)
np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), rtol=1e-5)
np.testing.assert_allclose(
    np.asarray(y), np.asarray(parts.sum(0)), rtol=1e-5
)
print("tutorial 03 OK: ring RS == psum_scatter == explicit sum")
