#!/usr/bin/env bash
# fast: the < 5-minute tier-1 subset (ROADMAP CI-budget item, closed
# round 7).
#
# Runs the `fast`-marked modules — the static analysis suite
# (shmemlint + the Mosaic-compat pre-flight, incl. the kv_ship.pages
# family + its SL008/SL009 fixtures), the fault engine, the host-level
# runtime/topology logic, the wire-layout/XLA-twin tests, the
# lang-layer slices, the tools, the continuous-batching serving suite
# (the ragged-kernel numerics + scheduler tests,
# tests/test_ragged_attention.py + tests/test_serving_engine.py with
# the prefix-cache/sampling satellites), the disaggregated
# prefill/decode transport suite (tests/test_kv_ship.py: wire-layout
# round trips, ship/eviction race pins, 2-role token-exactness) and
# the health/failover suite (tests/test_health.py: ledger state
# machine + determinism, mesh shrink, slice-death failover
# token-exactness, probation re-promotion) and the fleet router suite
# (tests/test_fleet.py: scoring/affinity/spill, ReplicaDeath failover,
# probe re-entry, chaos-site heartbeats, elastic grow/drain and the
# live KV-page-migration chaos soak), the multi-tenant suite
# (tests/test_multitenant.py: deadline routing, priority preemption,
# tier-priced retries, fair share, brownout shedding, replay
# determinism) and the training suite
# (tests/test_train.py: EF gradient-ring numerics + determinism, the
# dp×tp×cp train step vs the dense reference, backward wire duals,
# grad-ring chaos degradation/probation) — everything that answers
# "did I just break a protocol, a contract, or the host plumbing?"
# without paying for the big interpreted model suites. Use it as the
# inner-loop gate; the full tier-1 run remains the merge gate.
#
#   ci/fast.sh              # the subset
#   ci/fast.sh -x -k wire   # extra pytest args pass through
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'fast and not slow' \
  -p no:cacheprovider "$@"

# Bounded schedule-search smoke: enumerate + mutate one ring family,
# replay every candidate through shmemlint + the Mosaic pre-flight, and
# require that the oracle rejected at least one mutation (stable rule
# IDs) AND produced a lint-clean pick. Exits 2 if the gate is unwired.
JAX_PLATFORMS=cpu python -m triton_distributed_tpu.tune.schedule \
  --family ag_gemm.fused --mesh 8

# Same oracle over the ISSUE-14 gradient ring: the scale_rail=payload
# mutation must be rejected with a stable rule ID (SL009 — scales must
# ride the sideband rail, never the int8 payload) and the clean
# schedule must win.
JAX_PLATFORMS=cpu python -m triton_distributed_tpu.tune.schedule \
  --family grad_ring.stream_int8w --mesh 8

# Bounded GRID-schedule smoke (PR-15): the three grid families —
# ragged paged attention (block_q/n_bufs/pack_rows), kv_ship page
# coalescing, and the GEMM-RS int8-MXU epilogue — each enumerate their
# freedom product + mutations through the same oracle. Exits 2 unless
# at least one candidate was rejected with a stable rule ID (the
# over-wide block's SL008, the dropped/shared scale rail's SL009) AND
# a lint-clean pick landed. Mesh 8 here; the pytest suite pins mesh 4.
JAX_PLATFORMS=cpu python -m triton_distributed_tpu.tune.schedule \
  --family flash_decode.ragged_paged --mesh 8
JAX_PLATFORMS=cpu python -m triton_distributed_tpu.tune.schedule \
  --family kv_ship.pages --mesh 8
JAX_PLATFORMS=cpu python -m triton_distributed_tpu.tune.schedule \
  --family gemm_rs.mx_epilogue --mesh 8

# Degradation-target gate (the `bench.py --lint` check, standalone):
# every registered kernel family must name a degradation target that
# resolves to a real callable — a family without a declared fallback
# is a robustness hole, not a style nit.
JAX_PLATFORMS=cpu python - <<'EOF'
from triton_distributed_tpu.kernels.registry import (
    missing_degradation_targets,
)

gaps = missing_degradation_targets()
assert not gaps, f"families without a resolvable degradation target: {gaps}"
print(f"degradation targets: all families declare a resolvable fallback")
EOF

# Fleet failover smoke (ISSUE 11 acceptance): a 2-replica fleet on a
# short seeded trace with a mid-trace ReplicaDeath must finish with
# ZERO lost requests — every in-flight request on the dead replica
# drains back through the router onto the survivor.
JAX_PLATFORMS=cpu python - <<'EOF'
import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2").strip()

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.runtime import faults
from triton_distributed_tpu.serving import (
    EngineConfig, ServingEngine, ServingFleet, poisson_trace,
)

cfg = TransformerConfig(
    vocab=128, n_layers=2, hidden=64, ffn=128, n_heads=4, n_kv_heads=2,
    head_dim=16, dtype=jnp.float32, param_dtype=jnp.float32,
    kv_quant="int8")
ecfg = EngineConfig(slots=4, token_budget=48, chunk=16, page=8,
                    npages=32, prefix_cache=True, temperature=0.7,
                    top_k=40, seed=11)
devs = jax.devices()
engines = []
params = None
for k in range(2):
    mesh = Mesh(np.asarray(devs[k:k + 1]), ("tp",))
    model = Transformer(cfg, mesh, "tp", ())
    if params is None:
        params = model.init(jax.random.PRNGKey(0))
    p = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                     model.shardings())
    engines.append(ServingEngine(model, p, ecfg, use_pallas=False))

fleet = ServingFleet(engines, seed=1)
trace = poisson_trace(seed=9, n_requests=8, mean_interarrival=0.7,
                      len_lo=8, len_hi=30, max_new_lo=5, max_new_hi=8,
                      vocab=128)
# pin a session to the doomed replica so the step-6 death is
# guaranteed to catch in-flight work (the failover path, not a no-op)
for i, r in enumerate(trace):
    if i % 2:
        r.session = "s"
fleet.router.affinity["s"] = 1
plan = faults.parse_plan("seed=1; ReplicaDeath(replica=1, step=6)")
with faults.fault_plan(plan):
    stats = fleet.run(trace)
assert stats.lost_requests == 0, (
    f"fleet smoke lost {stats.lost_requests} requests: {stats}")
assert stats.deaths == [(1, 6)], stats.deaths
assert stats.failover_requeued >= 1, stats.failover_requeued
print(f"fleet smoke: {stats.completed}/{stats.submitted} completed, "
      f"0 lost across ReplicaDeath(replica=1, step=6), "
      f"requeued={stats.failover_requeued}")
EOF

# Speculative decoding smoke (ISSUE 12 acceptance): a short motif-heavy
# trace through the SpeculativeEngine (n-gram drafter) vs the plain
# engine — exits nonzero unless the streams are BYTE-IDENTICAL
# (token_mismatches == 0, the rejection-sampling identity) AND the
# drafter actually earned its keep (accepted_tokens_per_step > 1.0).
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.serving import (
    EngineConfig, NGramDrafter, ServingEngine, SpeculativeEngine,
    poisson_trace,
)

cfg = TransformerConfig(
    vocab=128, n_layers=2, hidden=64, ffn=128, n_heads=4, n_kv_heads=2,
    head_dim=16, dtype=jnp.float32, param_dtype=jnp.float32)
ecfg = EngineConfig(slots=4, token_budget=48, chunk=16, page=8,
                    npages=40)
mesh = Mesh(np.asarray(jax.devices()[:1]), ("tp",))
model = Transformer(cfg, mesh, "tp", ())
params = model.init(jax.random.PRNGKey(0))

def mk_trace():
    base = poisson_trace(seed=7, n_requests=6, mean_interarrival=0.5,
                         len_lo=8, len_hi=30, max_new_lo=8,
                         max_new_hi=16, vocab=128)
    rng = np.random.default_rng(1007)
    for r in base:
        ln = len(r.prompt)
        motif = rng.integers(0, 128, (5,)).astype(np.int32)
        r.prompt = np.tile(motif, -(-ln // 5))[:ln]
    return base

t_ref = mk_trace()
ServingEngine(model, params, ecfg, use_pallas=False).run(
    t_ref, max_steps=600)
t_spec = mk_trace()
eng = SpeculativeEngine(model, params, ecfg, spec_k=4,
                        drafter=NGramDrafter(), use_pallas=False)
stats = eng.run(t_spec, max_steps=600)
mismatches = sum(
    a.generated != b.generated for a, b in zip(t_ref, t_spec))
acc = stats.accepted_tokens_per_step
assert mismatches == 0, (
    f"speculative smoke: {mismatches} token-stream mismatches vs the "
    f"non-speculative engine")
assert acc > 1.0, (
    f"speculative smoke: accepted_tokens_per_step={acc:.3f} <= 1.0 "
    f"(spec_rows={stats.spec_rows}, drafted={stats.draft_tokens})")
print(f"speculative smoke: 0 mismatches across {stats.completed} "
      f"requests, accepted_tokens_per_step={acc:.2f} "
      f"(verify rows={stats.spec_rows}, "
      f"rolled_back={stats.rolled_back_tokens})")
EOF

# Tree-speculation smoke (ISSUE 18 acceptance): a BRANCHY sampled motif
# trace (small top_k makes the self-history ambiguous — the regime
# sibling rescue branches exist for) through spec_tree=8 (TreeDrafter)
# vs linear spec_k=4 vs the plain engine — exits nonzero unless the
# tree streams are byte-identical (token_mismatches == 0) AND the tree
# row lands at least the linear baseline's accepted tokens per verify
# step (strictly more, on this pinned recipe). Then the in-batch
# shared-prefix dedup smoke: requests sharing one long prompt prefix
# under cfg.prefix_share must fold duplicate prefix pages
# (deduped_pages > 0) while staying token-exact with no pool leak.
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.serving import (
    EngineConfig, NGramDrafter, Request, ServingEngine,
    SpeculativeEngine, TreeDrafter, poisson_trace,
)
from dataclasses import replace

cfg = TransformerConfig(
    vocab=128, n_layers=2, hidden=64, ffn=128, n_heads=4, n_kv_heads=2,
    head_dim=16, dtype=jnp.float32, param_dtype=jnp.float32)
ecfg = EngineConfig(slots=4, token_budget=48, chunk=16, page=8,
                    npages=40, temperature=1.0, top_k=4, seed=5)
mesh = Mesh(np.asarray(jax.devices()[:1]), ("tp",))
model = Transformer(cfg, mesh, "tp", ())
params = model.init(jax.random.PRNGKey(0))

def mk_trace():
    base = poisson_trace(seed=13, n_requests=6, mean_interarrival=0.5,
                         len_lo=8, len_hi=30, max_new_lo=16,
                         max_new_hi=24, vocab=128)
    rng = np.random.default_rng(1013)
    for r in base:
        ln = len(r.prompt)
        motif = rng.integers(0, 128, (5,)).astype(np.int32)
        r.prompt = np.tile(motif, -(-ln // 5))[:ln]
    return base

t_ref = mk_trace()
ServingEngine(model, params, ecfg, use_pallas=False).run(
    t_ref, max_steps=800)
t_tree = mk_trace()
eng = SpeculativeEngine(
    model, params, ecfg, spec_tree=8,
    drafter=TreeDrafter(branches=3, branch_len=2), use_pallas=False)
tree = eng.run(t_tree, max_steps=800)
t_lin = mk_trace()
lin = SpeculativeEngine(
    model, params, ecfg, spec_k=4, drafter=NGramDrafter(),
    use_pallas=False).run(t_lin, max_steps=800)
mismatches = sum(
    a.generated != b.generated for a, b in zip(t_ref, t_tree))
assert mismatches == 0, (
    f"tree smoke: {mismatches} token-stream mismatches vs the "
    f"non-speculative engine")
t_acc = tree.accepted_tokens_per_step
l_acc = lin.accepted_tokens_per_step
assert t_acc >= l_acc, (
    f"tree smoke: tree accepted/step {t_acc:.3f} below the linear "
    f"draft-k baseline {l_acc:.3f}")
assert eng.pool.available == ecfg.npages, "tree smoke: pool leak"
print(f"tree smoke: 0 mismatches across {tree.completed} requests, "
      f"tree accepted/step={t_acc:.3f} vs linear {l_acc:.3f} "
      f"(rolled_back={tree.rolled_back_tokens})")

rng = np.random.default_rng(21)
prefix = rng.integers(0, 128, (24,)).astype(np.int32)
def shared_trace():
    r2 = np.random.default_rng(22)
    return [Request(rid=i,
                    prompt=np.concatenate(
                        [prefix,
                         r2.integers(0, 128, (4,)).astype(np.int32)]),
                    max_new=6, arrival=0.1 * i)
            for i in range(6)]

dcfg = replace(ecfg, slots=3, npages=64)
t_base = shared_trace()
ServingEngine(model, params, dcfg, use_pallas=False).run(
    t_base, max_steps=800)
t_dd = shared_trace()
deng = ServingEngine(
    model, params, replace(dcfg, prefix_cache=True, prefix_share=True),
    use_pallas=False)
dd = deng.run(t_dd, max_steps=800)
mism = sum(a.generated != b.generated for a, b in zip(t_base, t_dd))
assert mism == 0, f"dedup smoke: {mism} token-stream mismatches"
assert dd.deduped_pages > 0, (
    f"dedup smoke: no pages deduped "
    f"(shared_prefix_rows={dd.shared_prefix_rows})")
assert deng.pool.available == dcfg.npages, "dedup smoke: pool leak"
print(f"dedup smoke: 0 mismatches across {dd.completed} requests, "
      f"deduped_pages={dd.deduped_pages} "
      f"shared_prefix_rows={dd.shared_prefix_rows}")
EOF

# Elastic fleet smoke (ISSUE 13 acceptance): a 1-replica fleet with one
# reserve engine scales UP under queue pressure (the grown replica must
# earn admission through the probation-probe path), then replica 0 is
# DRAINED onto the newcomer — exits nonzero unless lost_requests == 0,
# at least one autoscale grow landed, and at least one live KV-page
# migration was priced cheaper than re-prefilling the same pages.
JAX_PLATFORMS=cpu python - <<'EOF'
import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2").strip()

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_distributed_tpu import config
from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.runtime.health import HealthLedger, PeerState
from triton_distributed_tpu.serving import (
    AutoscalerConfig, EngineConfig, ServingEngine, ServingFleet,
)
from triton_distributed_tpu.serving.engine import Request

cfg = TransformerConfig(
    vocab=128, n_layers=2, hidden=64, ffn=128, n_heads=4, n_kv_heads=2,
    head_dim=16, dtype=jnp.float32, param_dtype=jnp.float32,
    kv_quant="int8")
ecfg = EngineConfig(slots=4, token_budget=48, chunk=16, page=8,
                    npages=32, prefix_cache=True, temperature=0.7,
                    top_k=40, seed=11)
devs = jax.devices()
models = []
params0 = None
for k in range(2):
    mesh = Mesh(np.asarray(devs[k % len(devs):k % len(devs) + 1]),
                ("tp",))
    model = Transformer(cfg, mesh, "tp", ())
    if params0 is None:
        params0 = model.init(jax.random.PRNGKey(0))
    p = jax.tree.map(lambda x, s: jax.device_put(x, s), params0,
                     model.shardings())
    models.append((model, p))

spare = lambda: ServingEngine(models[1][0], models[1][1], ecfg,
                              use_pallas=False)
ledger = HealthLedger(seed=0, probation_after=1, promote_after=1,
                      probe_interval=2)
fleet = ServingFleet(
    [ServingEngine(models[0][0], models[0][1], ecfg, use_pallas=False)],
    seed=3, health=ledger, reserve=[spare],
    autoscaler=AutoscalerConfig(slo_ms=0.0, window=2, cooldown=50,
                                max_replicas=2))

rng = np.random.default_rng(5)
trace = [Request(rid=i,
                 prompt=rng.integers(0, 128, (12,)).astype(np.int32),
                 max_new=5, arrival=i * 0.5)
         for i in range(18)]

prev = config.fleet_seed()
config.set_fleet_seed(fleet.seed)
drained = False
try:
    fleet.submit_trace(trace)
    for _ in range(500):
        if fleet.idle:
            break
        if (not drained and fleet.stats.grows
                and ledger.state("replica:1") is PeerState.HEALTHY
                and 1 in fleet.rotation()
                and fleet.replicas[0].held()):
            fleet.drain(0)
            drained = True
        fleet.tick()
finally:
    config.set_fleet_seed(prev)

stats = fleet.stats
assert stats.lost_requests == 0, (
    f"elastic smoke lost {stats.lost_requests} requests: {stats}")
assert stats.completed == len(trace), stats.completed
assert len(stats.grows) >= 1, f"no autoscale grow landed: {stats.grows}"
assert drained and len(stats.drains) == 1, (
    f"drain never completed: drained={drained} drains={stats.drains}")
assert stats.migrations >= 1, (
    f"drain finished without migrating any KV pages: {stats}")
assert stats.migrations_cheaper >= 1, (
    f"no migration was priced under re-prefill: "
    f"{stats.migration_priced}")
print(f"elastic smoke: {stats.completed}/{stats.submitted} completed, "
      f"0 lost across grow@{stats.grows[0][1]} + "
      f"drain{stats.drains[0]}, migrations={stats.migrations} "
      f"({stats.migrated_pages} pages, "
      f"{stats.migrations_cheaper} priced under re-prefill)")
EOF

# Training smoke (ISSUE 14 acceptance): a tiny dp2×tp2×cp2 step on the
# int8 EF gradient ring vs the single-device dense reference — exits
# nonzero unless the loss trajectories agree within tolerance, the ring
# actually moved fewer bytes than bf16 (ratio ~2×), and the three
# training families lint clean with declared degradation targets
# (train_gaps == 0, the `bench.py --lint` gate, standalone).
JAX_PLATFORMS=cpu python - <<'EOF'
import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

from triton_distributed_tpu.analysis.lint import lint_family
from triton_distributed_tpu.kernels.registry import (
    missing_degradation_targets,
)
from triton_distributed_tpu.train import (
    TRAIN_ENGINE_FAMILIES, TrainConfig, Trainer, train_step_reference,
)
from triton_distributed_tpu.train.step import init_opt_state, init_params

cfg = TrainConfig()  # dp2×tp2×cp2, wire=int8, ef=True
tr = Trainer(cfg)
params = init_params(cfg)
opt = init_opt_state(params)
delta = 0.0
loss = loss_ref = None
for k in range(5):
    tokens, targets = tr.make_batch(k)
    loss = tr.step(tokens, targets)["loss"]
    params, opt, loss_ref = train_step_reference(
        params, opt, tokens, targets, cfg)
    delta = max(delta, abs(float(loss) - float(loss_ref)))
assert delta < 0.05, (
    f"train smoke: wire-ring loss diverged from the dense reference "
    f"by {delta:.4f} (tol 0.05)")
rep = tr.wire_report()
assert rep["ratio"] > 1.9, (
    f"train smoke: int8 ring moved {rep['wire_bytes']}B vs "
    f"{rep['bf16_bytes']}B bf16 (ratio {rep['ratio']:.2f} <= 1.9)")
gaps = {f.name for f in missing_degradation_targets()}
for fam in TRAIN_ENGINE_FAMILIES:
    findings = lint_family(fam, n=8)
    assert findings == [], f"train smoke: {fam} lints dirty: {findings}"
    assert fam not in gaps, f"train smoke: {fam} has a degradation gap"
print(f"train smoke: 5 steps dp2×tp2×cp2 wire=int8, "
      f"max loss delta {delta:.4f} < 0.05 vs dense reference, "
      f"wire bytes ratio {rep['ratio']:.2f}x, "
      f"{len(TRAIN_ENGINE_FAMILIES)} families lint-clean with "
      f"declared fallbacks")
EOF

# Multi-tenant smoke (ISSUE 16 acceptance): a 2-replica fleet under a
# batch flood + an interactive trickle + a mid-flood ReplicaDeath,
# with the brownout controller armed and tier-priced admission —
# exits nonzero unless interactive p99 TTFT is no worse than the
# no-flood baseline (same death), every shed landed on
# background/batch only, and lost_requests == 0.
JAX_PLATFORMS=cpu python - <<'EOF'
import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2").strip()

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_distributed_tpu import config
from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.runtime import faults
from triton_distributed_tpu.serving import (
    BrownoutConfig, EngineConfig, Request, RouterConfig, ServingEngine,
    ServingFleet, TenantConfig,
)

cfg = TransformerConfig(
    vocab=128, n_layers=2, hidden=64, ffn=128, n_heads=4, n_kv_heads=2,
    head_dim=16, dtype=jnp.float32, param_dtype=jnp.float32,
    kv_quant="int8")
ecfg = EngineConfig(slots=4, token_budget=48, chunk=16, page=8,
                    npages=32, prefix_cache=True, temperature=0.7,
                    top_k=40, seed=11)
tenants = {
    "iact": TenantConfig(priority="interactive", slo_ms=0.05),
    "bat": TenantConfig(priority="batch"),
    "bg": TenantConfig(priority="background"),
}
devs = jax.devices()
models = []
params = None
for k in range(2):
    mesh = Mesh(np.asarray(devs[k:k + 1]), ("tp",))
    model = Transformer(cfg, mesh, "tp", ())
    if params is None:
        params = model.init(jax.random.PRNGKey(0))
    p = jax.tree.map(lambda x, s: jax.device_put(x, s), params,
                     model.shardings())
    models.append((model, p))


def build():
    return ServingFleet(
        [ServingEngine(m, p, ecfg, use_pallas=False)
         for m, p in models],
        seed=1, router=RouterConfig(queue_cap=3), tenants=tenants,
        brownout=BrownoutConfig(slo_ms=0.004, window=2, cooldown=3))


def trace(flood=True):
    rng = np.random.default_rng(5)
    out = []

    def mk(rid, arrival, tenant, plen):
        r = Request(rid=rid,
                    prompt=rng.integers(0, 128, (plen,)).astype(
                        np.int32),
                    max_new=5, arrival=arrival)
        r.tenant = tenant
        return r

    for i in range(4):
        out.append(mk(i, i * 3.0, "iact", 20))
    if flood:
        for i in range(24):
            out.append(mk(10 + i, 1.0 + i * 0.2, "bat", 24))
        for i in range(6):
            out.append(mk(50 + i, i * 1.5, "bg", 16))
    return out


def run(fleet, t):
    plan = faults.parse_plan("seed=1; ReplicaDeath(replica=1, step=8)")
    prev = config.fleet_seed()
    config.set_fleet_seed(fleet.seed)
    try:
        with faults.fault_plan(plan):
            fleet.submit_trace(t)
            for _ in range(800):
                if fleet.idle:
                    break
                fleet.tick()
    finally:
        config.set_fleet_seed(prev)
    return fleet.stats

base = build()
run(base, trace(flood=False))
assert base.stats.lost_requests == 0, base.stats
p99_free = base.per_tenant()["iact"]["p99_ttft_ticks"]

fleet = build()
stats = run(fleet, trace(flood=True))
p99_flood = fleet.per_tenant()["iact"]["p99_ttft_ticks"]
assert stats.lost_requests == 0, (
    f"multi-tenant smoke lost {stats.lost_requests} requests: {stats}")
assert (1, 8) in stats.deaths, stats.deaths
assert set(stats.sheds) <= {"background", "batch"}, stats.sheds
assert sum(stats.sheds.values()) >= 1, "flood never tripped brownout"
assert p99_flood <= p99_free, (
    f"multi-tenant smoke: interactive p99 degraded under the flood "
    f"({p99_flood} > {p99_free})")
leaked = sum(role.pool.held_pages
             for r in fleet._alive() for role in r._roles)
assert leaked == 0, f"multi-tenant smoke leaked {leaked} pool pages"
print(f"multi-tenant smoke: {stats.completed}/{stats.submitted} "
      f"completed, 0 lost across ReplicaDeath(replica=1, step=8), "
      f"interactive p99 {p99_flood} <= {p99_free} no-flood, "
      f"sheds={dict(stats.sheds)}, "
      f"preemptions={fleet.preemptions}")
EOF

# Contract-inference smoke (ISSUE 17 acceptance): derive the delivery
# contract of one family per twin class from the XLA twin + replay
# provenance at mesh 4 and diff it against the declaration — a drifted
# declaration (SL012) or a silently missing one (SL013) fails CI in
# seconds. The full-registry sweep at mesh 4 AND 8 lives in the pytest
# suite; this step keeps the fast path to one family per class:
# gather (ring AG), reduce (ring RS), permute (dense a2a), local
# (ragged paged attention).
JAX_PLATFORMS=cpu python - <<'EOF'
import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()

from triton_distributed_tpu.analysis import contract_infer
from triton_distributed_tpu.kernels.registry import families

fams = families()
drifted = []
for name in ("allgather.ring_1d", "reduce_scatter.ring",
             "all_to_all.dense", "flash_decode.ragged_paged"):
    res = contract_infer.infer_family(fams[name], 4)
    assert res.profile.executed, (
        f"{name}: twin not executed ({res.profile.detail})")
    if res.findings:
        drifted.append((name, [f.format() for f in res.findings]))
assert not drifted, f"contract inference drift: {drifted}"
print("contract inference: ring AG / ring RS / dense a2a / ragged "
      "local all agree with their declared contracts at mesh 4")
EOF

# Serving-protocol model-check smoke (ISSUE 19 acceptance): servlint's
# bounded exhaustive exploration over the production ProtocolOps seam
# must visit >= 1000 states with ZERO findings in <= 5 s, and every
# seeded mutated-ops fixture (SV001..SV007) must be caught — exit 2 —
# by exactly its rule.
JAX_PLATFORMS=cpu python - <<'EOF2'
import time

from triton_distributed_tpu.analysis import servlint

t0 = time.perf_counter()
findings, stats = servlint.lint_serving(max_states=2000)
dt = time.perf_counter() - t0
assert findings == [], (
    f"servlint smoke: production ops produced findings: "
    f"{[f.format() for f in findings]}")
assert stats["states"] >= 1000, (
    f"servlint smoke: only {stats['states']} states explored (< 1000)")
assert dt <= 5.0, (
    f"servlint smoke: exploration took {dt:.1f}s (> 5s budget)")
print(f"servlint smoke: {stats['states']} states / "
      f"{stats['transitions']} transitions clean in {dt:.2f}s")
EOF2
for rule in SV001 SV001cp SV002 SV003 SV004 SV005 SV006 SV007; do
  rc=0
  JAX_PLATFORMS=cpu python -m triton_distributed_tpu.analysis.lint \
    --serving-fixture "$rule" >/dev/null 2>&1 || rc=$?
  if [ "$rc" -ne 2 ]; then
    echo "servlint smoke: fixture $rule exited $rc (want 2)" >&2
    exit 1
  fi
done
echo "servlint smoke: all 8 seeded fixtures caught (exit 2 each)"

# Long-context smoke (ISSUE 20 acceptance): a request whose end-to-end
# KV need EXCEEDS one per-shard page pool must be ADMITTED on a cp=2
# engine (sharded page walk + cross-rank LSE-combine) and produce a
# token stream byte-identical to a single-pool oracle, with every page
# back in the pool after the drain — exits nonzero on any mismatch or
# leak.
JAX_PLATFORMS=cpu python - <<'EOF'
import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2").strip()

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from triton_distributed_tpu.models import Transformer, TransformerConfig
from triton_distributed_tpu.serving import (
    EngineConfig, Request, ServingEngine,
)
from triton_distributed_tpu.serving.state import CpPagePool

cfg = TransformerConfig(
    vocab=128, n_layers=2, hidden=64, ffn=128, n_heads=4, n_kv_heads=2,
    head_dim=16, dtype=jnp.float32, param_dtype=jnp.float32)
devs = jax.devices()
mesh_cp = Mesh(np.asarray(devs[:2]).reshape(1, 2), ("x", "cpx"))
mesh_1 = Mesh(np.asarray(devs[:1]), ("x",))


def run(mesh, cp_axis, npages):
    model = Transformer(cfg, mesh, tp_axis="x", cp_axis=cp_axis)
    params = model.init(jax.random.PRNGKey(0))
    ecfg = EngineConfig(slots=2, token_budget=16, chunk=8, page=4,
                        npages=npages, max_steps=600, temperature=0.0)
    eng = ServingEngine(model, params, ecfg, use_pallas=False)
    rng = np.random.default_rng(0)
    reqs = [Request(rid=0, prompt=rng.integers(1, 127, 30, np.int32),
                    max_new=10, arrival=0),
            Request(rid=1, prompt=rng.integers(1, 127, 7, np.int32),
                    max_new=6, arrival=0)]
    done = {}
    eng.on_complete = lambda req, slot: done.setdefault(
        req.rid, list(req.generated)) or True
    eng.run(reqs)
    return eng, done

# the long request needs 10 pages: > one 6-page shard pool, <= the
# 12-page cp=2 total — admission is the capability under test
eng_cp, done_cp = run(mesh_cp, "cpx", 6)
assert isinstance(eng_cp.pool, CpPagePool), type(eng_cp.pool)
_, done_1 = run(mesh_1, None, 12)
assert set(done_cp) == {0, 1} == set(done_1), (done_cp, done_1)
mism = sum(done_cp[r] != done_1[r] for r in done_cp)
assert mism == 0, (
    f"long-context smoke: {mism} token-stream mismatches vs the "
    f"single-pool oracle")
refs = int(np.asarray(eng_cp.pool.refs).sum())
assert refs == 0, f"long-context smoke: {refs} leaked page refs"
assert len(eng_cp.pool.free) + len(eng_cp.pool._reclaim) \
    == eng_cp.pool.npages, "long-context smoke: pool accounting leak"
print(f"long-context smoke: 10-page request admitted on cp=2 "
      f"(6-page shards), 0 mismatches across {len(done_cp)} requests, "
      f"0 leaked pages")
EOF
