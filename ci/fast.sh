#!/usr/bin/env bash
# fast: the < 5-minute tier-1 subset (ROADMAP CI-budget item, closed
# round 7).
#
# Runs the `fast`-marked modules — the static analysis suite
# (shmemlint + the Mosaic-compat pre-flight, incl. the kv_ship.pages
# family + its SL008/SL009 fixtures), the fault engine, the host-level
# runtime/topology logic, the wire-layout/XLA-twin tests, the
# lang-layer slices, the tools, the continuous-batching serving suite
# (the ragged-kernel numerics + scheduler tests,
# tests/test_ragged_attention.py + tests/test_serving_engine.py with
# the prefix-cache/sampling satellites), the disaggregated
# prefill/decode transport suite (tests/test_kv_ship.py: wire-layout
# round trips, ship/eviction race pins, 2-role token-exactness) and
# the health/failover suite (tests/test_health.py: ledger state
# machine + determinism, mesh shrink, slice-death failover
# token-exactness, probation re-promotion) — everything that answers
# "did I just break a protocol, a contract, or the host plumbing?"
# without paying for the big interpreted model suites. Use it as the
# inner-loop gate; the full tier-1 run remains the merge gate.
#
#   ci/fast.sh              # the subset
#   ci/fast.sh -x -k wire   # extra pytest args pass through
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'fast and not slow' \
  -p no:cacheprovider "$@"

# Bounded schedule-search smoke: enumerate + mutate one ring family,
# replay every candidate through shmemlint + the Mosaic pre-flight, and
# require that the oracle rejected at least one mutation (stable rule
# IDs) AND produced a lint-clean pick. Exits 2 if the gate is unwired.
JAX_PLATFORMS=cpu python -m triton_distributed_tpu.tune.schedule \
  --family ag_gemm.fused --mesh 8

# Degradation-target gate (the `bench.py --lint` check, standalone):
# every registered kernel family must name a degradation target that
# resolves to a real callable — a family without a declared fallback
# is a robustness hole, not a style nit.
JAX_PLATFORMS=cpu python - <<'EOF'
from triton_distributed_tpu.kernels.registry import (
    missing_degradation_targets,
)

gaps = missing_degradation_targets()
assert not gaps, f"families without a resolvable degradation target: {gaps}"
print(f"degradation targets: all families declare a resolvable fallback")
EOF
