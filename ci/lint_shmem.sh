#!/usr/bin/env bash
# lint-shmem: the fail-fast protocol gate of the tier-1 flow.
#
# Runs BEFORE the full test budget: a semaphore-protocol regression in a
# SHMEM kernel (missed wait, credit off-by-one, collective_id collision)
# fails here in seconds — statically, with rank/semaphore diagnostics —
# instead of surfacing as a hang the chaos suite's watchdog has to catch
# minutes later (or not at all on a jax without the TPU-simulation
# interpreter, where the dynamic race passes are skipped entirely).
#
# Three legs, mirroring the satellite contract in docs/ANALYSIS.md:
#   1. the `analysis`-marked pytest subset (rule fixtures + API surface);
#   2. the CLI over every registered kernel family on an 8-rank mesh —
#      protocol (SL001-007) AND data correctness (SL008-010: delivery
#      contracts incl. the kv_ship pairwise page-ship permute,
#      wire-rail consistency, stale-scale reads);
#   3. the Mosaic-compat pre-flight (MC001-004): each family's kernel
#      jaxpr, built for hardware, scanned for constructs this
#      toolchain's Mosaic rejects — seconds-fast compile-shaped
#      coverage now that the full AOT suite is slow-marked.
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q -m analysis \
  -p no:cacheprovider "$@"
JAX_PLATFORMS=cpu python -m triton_distributed_tpu.analysis.lint --mesh 8
JAX_PLATFORMS=cpu python -m triton_distributed_tpu.analysis.mosaic_compat \
  --mesh 8
