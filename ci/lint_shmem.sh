#!/usr/bin/env bash
# lint-shmem: the fail-fast protocol gate of the tier-1 flow.
#
# Runs BEFORE the full test budget: a semaphore-protocol regression in a
# SHMEM kernel (missed wait, credit off-by-one, collective_id collision)
# fails here in seconds — statically, with rank/semaphore diagnostics —
# instead of surfacing as a hang the chaos suite's watchdog has to catch
# minutes later (or not at all on a jax without the TPU-simulation
# interpreter, where the dynamic race passes are skipped entirely).
#
# Two legs, mirroring the satellite contract in docs/ANALYSIS.md:
#   1. the `analysis`-marked pytest subset (rule fixtures + API surface);
#   2. the CLI over every registered kernel family on an 8-rank mesh
#      (exits nonzero on any ERROR-severity finding).
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q -m analysis \
  -p no:cacheprovider "$@"
JAX_PLATFORMS=cpu python -m triton_distributed_tpu.analysis.lint --mesh 8
