#!/usr/bin/env bash
# nightly: the checks too slow for ci/fast.sh's inner loop but cheap
# enough to run unattended once a day.
#
# Today that is the EXHAUSTIVE serving-protocol model check (ISSUE 20
# satellite): `--serving-states 0` lifts the per-commit state cap so
# the bounded BFS walks the ENTIRE reachable graph of the abstract
# fleet — tractable because `_World.key()` canonicalizes page ids
# (states identical up to a shard-preserving page relabeling share one
# key), ~43k states / ~340k transitions in under a minute. The run
# must come back with the HONEST "exhaustive" label (and the --json
# `complete: true` field); a capped control run must come back
# "state-capped" — a labeling bug that reports a truncated exploration
# as exhaustive would quietly void the nightly's whole point.
#
#   ci/nightly.sh            # the nightly gate
set -euo pipefail
cd "$(dirname "$0")/.."

# 1. uncapped production exploration: clean AND labeled exhaustive
out=$(JAX_PLATFORMS=cpu python -m triton_distributed_tpu.analysis.lint \
  --serving --serving-states 0 2>&1)
echo "$out"
case "$out" in
  *"(exhaustive)"*" 0 error(s), 0 warning(s)"*) ;;
  *) echo "nightly: uncapped servlint run is not clean-and-exhaustive" >&2
     exit 1 ;;
esac

# 2. the same, through --json: header must carry complete=true
JAX_PLATFORMS=cpu python - <<'EOF'
import json
import subprocess
import sys

proc = subprocess.run(
    [sys.executable, "-m", "triton_distributed_tpu.analysis.lint",
     "--serving", "--serving-states", "0", "--json"],
    capture_output=True, text=True)
assert proc.returncode == 0, proc.stderr
header = json.loads(proc.stdout.splitlines()[0])
assert header["mode"] == "serving"
assert header["complete"] is True, header
assert header["states"] > 20_000, header
print(f"nightly: exhaustive servlint json complete=true at "
      f"{header['states']} states / {header['transitions']} "
      f"transitions")
EOF

# 3. honest-label control: a capped run must say so
out=$(JAX_PLATFORMS=cpu python -m triton_distributed_tpu.analysis.lint \
  --serving --serving-states 500 2>&1)
case "$out" in
  *"(state-capped)"*) echo "nightly: capped control labeled state-capped" ;;
  *) echo "nightly: capped control run did not label itself state-capped:" >&2
     echo "$out" >&2
     exit 1 ;;
esac

# 4. the cp-shard facet's clean half, also uncapped: the sharded pool
# (CpPagePool ownership routing) explored to completion
JAX_PLATFORMS=cpu python - <<'EOF'
from triton_distributed_tpu.analysis import servlint

findings, stats = servlint.lint_serving(servlint.CpProtocolOps(),
                                        max_states=0)
assert findings == [], [f.format() for f in findings]
assert stats["complete"] is True, stats
print(f"nightly: cp-facet exploration exhaustive and clean at "
      f"{stats['states']} states / {stats['transitions']} transitions")
EOF

echo "nightly: all gates passed"
