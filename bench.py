"""Driver benchmark: fused AG-GEMM throughput on the north-star TP shape.

Measures the flagship overlap op (BASELINE.md north-star: fused AG-GEMM on
Llama-7B TP shapes, reference tutorial 07 / test_ag_gemm.py) on whatever
devices are present — the one real TPU chip under the driver, or the
virtual CPU mesh during development.

Methodology (the round-1 numbers were dispatch-overhead artifacts):

* Every timing is an **in-jit ``lax.fori_loop``** whose carry chains each
  iteration's output back into the next iteration's input, timed as the
  *difference* between a high and a low iteration count — the ~90 ms
  axon-relay dispatch round-trip cancels out.
* The loop dependency folds ``jnp.sum(out)`` into the carry so XLA cannot
  narrow the benched computation to the part feeding one element (it
  will happily turn ``dot(a, b)[0, 0]`` into a dot-product).
* ``block_until_ready`` is a no-op over the axon relay; a host fetch of
  the scalar result is the reliable fence.
* Numbers are reported with ``device_kind`` and MFU / %-of-SOL against
  ``tune.perf_model.detect_spec()`` so they are explainable as
  %-of-speed-of-light.

Prints ONE JSON line on stdout:
  {"metric": "ag_gemm_tflops_per_chip", "value": N, "unit": "TFLOP/s",
   "vs_baseline": speedup_vs_unoverlapped, ...}

``vs_baseline`` compares the fused flagship engine against the
unoverlapped baseline (all_gather → dot, ≡ the reference's torch_ag_gemm
cuBLAS+NCCL baseline, test_ag_gemm.py) measured the same way on the same
hardware; the baseline's own TFLOPs ride along so both sides are visible.
Secondary metrics (gemm_rs, grouped-GEMM MFU, MoE a2a transport,
flash-decode HBM%) go to stderr, one JSON line each.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

# CPU dev-box runs (JAX_PLATFORMS=cpu) get the same virtual 8-device
# mesh the test harness uses (tests/conftest.py): the multi-rank rows —
# the 2×(n/2) disaggregated serving split, the DCN rails, the ring
# engines — then exercise their real cross-device paths instead of
# degenerating to n=1. Real-TPU runs are untouched.
if os.environ.get("JAX_PLATFORMS") == "cpu":
    _flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _make_runner(step, state, iters):
    """Jitted (state → scalar) fori_loop runner, compiled and warmed —
    the one timing-runner construction both bench_loop and bench_paired
    use (the double float() is compile + steady-state warm; the host
    fetch of the scalar is the only reliable fence over the relay)."""

    @jax.jit
    def run(state):
        def body(i, carry):
            return step(*carry)

        return jax.lax.fori_loop(0, iters, body, (state, jnp.float32(0)))[1]

    float(run(state))
    float(run(state))
    return run


def _make_donating_runner(step, state, iters, donate_idx):
    """Runner that DONATES ``state[donate_idx]`` — a persistent-
    workspace carry (e.g. the barrier-free LL MoE state, whose protocol
    requires the SAME physical buffers across invocations: skewed peers'
    in-flight DMAs target the persistent addresses). Each invocation
    consumes the donated tree and returns the final carry's version, so
    callers THREAD it: ``d, s = call(d)`` — the run/donate protocol of
    production decode (models/transformer._decode_jit_state). The float
    fetch is inside ``call`` (the fence, as in :func:`_make_runner`)."""
    state = tuple(state)
    rest = state[:donate_idx] + (None,) + state[donate_idx + 1:]

    @functools.partial(jax.jit, donate_argnums=(1,))
    def run(rest_in, dstate):
        full = rest_in[:donate_idx] + (dstate,) + rest_in[donate_idx + 1:]

        def body(i, carry):
            return step(*carry)

        fstate, s = jax.lax.fori_loop(
            0, iters, body, (full, jnp.float32(0))
        )
        return fstate[donate_idx], s

    def call(dstate):
        d, s = run(rest, dstate)
        return d, float(s)

    return call


def bench_loop(step, state, *, lo=4, hi=20, reps=5, donate_idx=None):
    """Time ``step`` (state, s) -> (state, s) via in-jit fori_loop deltas.

    Returns seconds per iteration. ``s`` is a f32 scalar the step must
    fold a full-output reduction into (the anti-DCE / anti-narrowing
    dependency); fetching it on the host is the execution fence.

    The chip behind the axon relay is time-shared, so a single (lo, hi)
    pair is noisy; each rep measures the pair back-to-back (slowly-varying
    interference hits both sides) and the median paired delta is used.
    Callers size (hi - lo) so the expected delta dwarfs relay jitter.

    ``donate_idx``: position in ``state`` of a persistent-workspace
    carry to donate-and-thread across every runner invocation (see
    :func:`_make_donating_runner`) — without it, re-invoking jitted
    programs with non-donated workspaces would break the LL persistent-
    buffer contract at n>1 (each invocation would get fresh placements
    while peers RDMA into the old addresses).
    """
    if donate_idx is not None:
        state = tuple(state)
        run_lo = _make_donating_runner(step, state, lo, donate_idx)
        run_hi = _make_donating_runner(step, state, hi, donate_idx)
        d = state[donate_idx]
        for r in (run_lo, run_lo, run_hi, run_hi):   # compile + steady warm
            d, _ = r(d)
        deltas = []
        for _ in range(reps):
            t0 = time.perf_counter()
            d, _ = run_lo(d)
            t1 = time.perf_counter()
            d, _ = run_hi(d)
            deltas.append((time.perf_counter() - t1) - (t1 - t0))
    else:
        run_lo = _make_runner(step, state, lo)
        run_hi = _make_runner(step, state, hi)
        deltas = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(run_lo(state))
            t1 = time.perf_counter()
            float(run_hi(state))
            deltas.append((time.perf_counter() - t1) - (t1 - t0))
    dt = float(np.median(deltas)) / (hi - lo)
    if dt <= 0:
        raise RuntimeError(
            f"bench_loop: non-positive median timing delta over {reps} reps "
            f"(lo={lo}, hi={hi}) — noise swamped the measurement; raise the "
            "iteration counts"
        )
    return dt


def perturb(a, s):
    """Tiny dynamic data dependency: keeps the loop carry live without
    changing values beyond an underflowing-to-zero epsilon."""
    return a + (s * jnp.float32(1e-30)).astype(a.dtype)


def bench_paired(step_a, step_b, state, *, lo=8, hi=40, reps=11):
    """Paired A-vs-B timing: per rep, A's and B's (lo, hi) fori_loop
    deltas run back-to-back IN SNAKE ORDER (A,B then B,A on alternating
    reps — a monotonic interference ramp hits whichever side runs later,
    so a fixed order would bias every pair's ratio the same way; the
    alternation makes the bias cancel across reps, the same fix
    autotuner._bench applies to config ranking). Returns (median t_a,
    median t_b, median of per-pair t_b/t_a ratios, (q25, q75) of the
    ratios)."""
    a_lo, a_hi = _make_runner(step_a, state, lo), _make_runner(step_a, state, hi)
    b_lo, b_hi = _make_runner(step_b, state, lo), _make_runner(step_b, state, hi)

    def delta(r_lo, r_hi):
        t0 = time.perf_counter()
        float(r_lo(state))
        t1 = time.perf_counter()
        float(r_hi(state))
        return ((time.perf_counter() - t1) - (t1 - t0)) / (hi - lo)

    ratios, tas, tbs = [], [], []
    for rep in range(reps):
        if rep % 2 == 0:
            ta = delta(a_lo, a_hi)
            tb = delta(b_lo, b_hi)
        else:
            tb = delta(b_lo, b_hi)
            ta = delta(a_lo, a_hi)
        if ta > 0 and tb > 0:
            ratios.append(tb / ta)
            tas.append(ta)
            tbs.append(tb)
    if not ratios:
        # every rep lost a side to noise (µs-scale CPU deltas): one
        # last-resort UNPAIRED attempt, reported as untrusted (NaN IQR
        # + stderr warning) — fabricated confidence would be worse than
        # aborting, and a still-negative delta does abort
        ta = delta(a_lo, a_hi)
        tb = delta(b_lo, b_hi)
        if ta <= 0 or tb <= 0:
            raise RuntimeError(
                "bench_paired: no positive paired deltas and the "
                "unpaired fallback is non-positive too — noise swamped "
                "the measurement; raise lo/hi"
            )
        print(
            json.dumps({
                "warning": "bench_paired fell back to a single UNPAIRED "
                "comparison (all paired reps lost a side to noise); "
                "ratio is order-biased and IQR is undefined",
            }),
            file=sys.stderr, flush=True,
        )
        return ta, tb, tb / ta, (float("nan"), float("nan"))
    tas, tbs, ratios = map(np.asarray, (tas, tbs, ratios))
    # outlier rejection: an interference burst on one side of a pair
    # collapses (or inflates) that delta and its ratio explodes — keep
    # pairs whose BOTH deltas sit within 2× of their medians, so the
    # reported IQR reflects the protocol, not the relay's worst burst
    ma, mb = np.median(tas), np.median(tbs)
    keep = (
        (tas > 0.5 * ma) & (tas < 2 * ma)
        & (tbs > 0.5 * mb) & (tbs < 2 * mb)
    )
    if keep.any():
        tas, tbs, ratios = tas[keep], tbs[keep], ratios[keep]
    return (
        float(np.median(tas)),
        float(np.median(tbs)),
        float(np.median(ratios)),
        (float(np.percentile(ratios, 25)), float(np.percentile(ratios, 75))),
    )


def _parse_args(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="triton_distributed_tpu driver benchmark"
    )
    ap.add_argument(
        "--lint", action="store_true",
        help="run shmemlint (protocol SL001-007, delivery/wire dataflow "
        "SL008-010) plus the Mosaic-compat pre-flight (MC001-004) over "
        "the benched kernel families BEFORE any timing; abort (exit 2) "
        "on errors so a broken protocol — or a kernel Mosaic would "
        "reject mid-run — fails in seconds instead of hanging the "
        "timed run",
    )
    ap.add_argument(
        "--infer-contracts", action="store_true",
        help="with --lint: additionally derive each family's delivery "
        "contract from its XLA twin (rank-tagged execution + replay "
        "provenance) and diff it against the declared one — SL012 on "
        "drift, SL013 on a family registered without a declaration "
        "(SL008 runs on the inferred contract there). Needs enough "
        "host devices to execute the twins; falls back to the static "
        "class table otherwise",
    )
    ap.add_argument(
        "--dryrun", action="store_true",
        help="hardware-free engine exercise: run ONLY the "
        "serving_continuous bench at interpreter-tiny shapes (whatever "
        "the platform) and exit — with --faults, the fault plan is "
        "active inside the ragged kernel and the scheduler's "
        "eviction/degradation behavior runs under it (the robustness "
        "follow-on: chaos-line replay without a TPU)",
    )
    ap.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="replay a nightly chaos line on real hardware: a "
        "(seed, faults) spec, e.g. \"seed=7; Delay(site=allgather, "
        "rank=2, cycles=50000)\" or the JSON twin (see "
        "runtime.faults.parse_plan). The plan is active for every "
        "benched collective.",
    )
    ap.add_argument(
        "--n-layers", type=int, default=None, metavar="L",
        help="serving benches: override the model depth (default 1). "
        "The serving-state donation path only shows its cost at depth "
        "> 1 — per-layer pool bytes are reported so the sweep is "
        "explainable (ISSUE-12 satellite / ISSUE-6 follow-on)",
    )
    ap.add_argument(
        "--tree", action="store_true",
        help="serving_speculative: the tree-speculation paired row — "
        "spec_tree verify trees (TreeDrafter sibling branches) vs "
        "linear draft-k on a branchy SAMPLED motif trace (token "
        "mismatches must be 0 and accepted/step strictly above "
        "linear), plus the in-batch shared-prefix dedup row "
        "(deduped pages > 0, token-exact; ISSUE-18)",
    )
    ap.add_argument(
        "--spec-k", type=int, default=None, metavar="K",
        help="serving_fleet: run SPECULATIVE replicas (draft-k K, "
        "ngram drafter) against a non-speculative fleet on the "
        "IDENTICAL trace — per-replica accepted-tokens/step and the "
        "goodput ratio are reported (ISSUE-13 satellite)",
    )
    ap.add_argument(
        "scenario", nargs="?", default=None,
        help="run ONLY this named scenario (currently: serving_fleet "
        "— the multi-replica router bench, --spec-k K for speculative "
        "replicas — serving_speculative — the draft-k speculative "
        "engine vs the plain engine, colocated AND disaggregated — or "
        "serving_elastic — autoscale grow from a reserve mesh, a "
        "mid-trace drain with live KV-page migration — or "
        "serving_multitenant — priority preemption, deadline routing "
        "and brownout shedding under a 4x batch flood — or "
        "serving_longcontext — context-parallel decode over the "
        "cp-sharded page pool: a request k× one pool shard served "
        "token-exact vs the single-slice oracle, the short-request "
        "goodput tax, and the priced long-context placement verdict "
        "(ISSUE-20); all compose "
        "with --dryrun and --faults, e.g. the ISSUE-16 acceptance "
        "line 'serving_multitenant --dryrun --faults \"seed=1; "
        "ReplicaDeath(replica=1, step=8)\"' — or train_step — the "
        "dp×tp×cp train step on the int8 EF gradient ring vs the "
        "single-device reference and the exact psum twin, ISSUE-14)",
    )
    return ap.parse_args(argv)


def _run_lint(infer_contracts: bool = False) -> None:
    """bench --lint: static protocol + dataflow + Mosaic-compat passes
    over the benched kernel set (exit 2 on errors — unchanged
    contract; the dataflow rules ride inside lint_all, the pre-flight
    is its own sweep). ``infer_contracts`` additionally diffs every
    declared delivery contract against the twin-inferred one (SL012 /
    SL013 ride inside the findings stream like any other rule)."""
    from triton_distributed_tpu.analysis import lint as shmemlint
    from triton_distributed_tpu.analysis import mosaic_compat
    from triton_distributed_tpu.analysis.findings import (
        Severity,
        rule_counts,
    )

    findings = shmemlint.lint_all(n=8, infer_contracts=infer_contracts)
    if infer_contracts:
        print(
            json.dumps({"lint_contract_inference": {
                "mesh": 8,
                "drift": sum(f.rule == "SL012" for f in findings),
                "undeclared": sum(f.rule == "SL013" for f in findings),
            }}),
            file=sys.stderr, flush=True,
        )
    mc, report = mosaic_compat.preflight_all(n=8)
    findings += mc
    for f in findings:
        print(json.dumps({"lint": f.to_json()}), file=sys.stderr, flush=True)
    # re-gate every persisted schedule-search winner: a cached schedule
    # is trusted by the op resolve paths with zero checks at load time,
    # so --lint is where a stale/corrupt entry gets caught
    from triton_distributed_tpu.tune import schedule as sched_lib

    for key, entry in sched_lib.stored_entries().items():
        fam = entry.get("family")
        try:
            # kind-aware rebuild: grid winners replay as GridSchedule
            # through the same gate as ring winners
            sched = sched_lib.schedule_from_entry(entry)
            if sched is None:
                raise ValueError(f"unparseable store entry {key!r}")
            extra = sched_lib.check_schedule(fam, sched, 8)
        except Exception as e:
            print(
                json.dumps({"lint_schedule_cache": key,
                            "error": f"{type(e).__name__}: {e}"[:200]}),
                file=sys.stderr, flush=True,
            )
            continue
        findings += extra
        print(
            json.dumps({"lint_schedule_cache": key,
                        "findings": [f.rule for f in extra]}),
            file=sys.stderr, flush=True,
        )

    # degradation-target gate: every registered family must declare a
    # resolvable XLA twin to fall onto (the health ledger's demotion
    # needs somewhere to go — an undeclared target is the silent-gap
    # class docs/ROBUSTNESS.md's matrix documents)
    from triton_distributed_tpu.kernels.registry import (
        missing_degradation_targets,
    )

    gaps = missing_degradation_targets()
    for fam, problem in gaps:
        print(
            json.dumps({"lint_degradation_gap":
                        {"family": fam, "problem": problem}}),
            file=sys.stderr, flush=True,
        )

    # fleet gate (ISSUE 11): every kernel family a fleet replica's
    # engines launch must be REGISTERED with a resolvable degradation
    # target — a replica whose engines cannot degrade is not a safe
    # failover destination, so the router's whole health story would
    # rest on an unverified fallback
    from triton_distributed_tpu.kernels import registry as _registry
    from triton_distributed_tpu.serving.fleet import (
        FLEET_ENGINE_FAMILIES,
    )

    fams = _registry.families()
    gap_names = {f for f, _ in gaps}
    fleet_gaps = []
    for fam in FLEET_ENGINE_FAMILIES:
        if fam not in fams:
            fleet_gaps.append((fam, "fleet replica family not registered"))
        elif fam in gap_names:
            fleet_gaps.append(
                (fam, "fleet replica family has a degradation gap"))
    for fam, problem in fleet_gaps:
        print(
            json.dumps({"lint_fleet_gap":
                        {"family": fam, "problem": problem}}),
            file=sys.stderr, flush=True,
        )

    # speculative gate (ISSUE 12): the kernel families the speculative
    # engine launches — by design the SAME ragged family as the plain
    # engine — must be registered with a resolvable degradation target,
    # so a speculative deployment degrades onto the XLA twin exactly
    # like a plain one (verify rows are ordinary ragged rows there too)
    from triton_distributed_tpu.serving.spec import SPEC_ENGINE_FAMILIES

    spec_gaps = []
    for fam in SPEC_ENGINE_FAMILIES:
        if fam not in fams:
            spec_gaps.append(
                (fam, "speculative engine family not registered"))
        elif fam in gap_names:
            spec_gaps.append(
                (fam, "speculative engine family has a degradation gap"))
    for fam, problem in spec_gaps:
        print(
            json.dumps({"lint_spec_gap":
                        {"family": fam, "problem": problem}}),
            file=sys.stderr, flush=True,
        )

    # migration gate (ISSUE 13): the fleet's replica→replica KV-page
    # migration rides the kv_ship wire families — they must stay
    # registered with a resolvable degradation target, or a drain's
    # migrate-or-finish path would rest on an unverified transport
    # (the fallback when the wire is refused is re-prefill, which is
    # exactly the degradation target story this gate keeps honest)
    from triton_distributed_tpu.serving.fleet import (
        MIGRATION_ENGINE_FAMILIES,
    )

    migration_gaps = []
    for fam in MIGRATION_ENGINE_FAMILIES:
        if fam not in fams:
            migration_gaps.append(
                (fam, "migration wire family not registered"))
        elif fam in gap_names:
            migration_gaps.append(
                (fam, "migration wire family has a degradation gap"))
    for fam, problem in migration_gaps:
        print(
            json.dumps({"lint_migration_gap":
                        {"family": fam, "problem": problem}}),
            file=sys.stderr, flush=True,
        )

    # training gate (ISSUE 14): the train step's collective families —
    # the CP attention rings and the quantized gradient ring — must be
    # registered with a resolvable degradation target, or the trainer's
    # ledger demotion (wire ring → exact psum twin) would rest on an
    # unverified fallback
    from triton_distributed_tpu.train import TRAIN_ENGINE_FAMILIES

    train_gaps = []
    for fam in TRAIN_ENGINE_FAMILIES:
        if fam not in fams:
            train_gaps.append(
                (fam, "training family not registered"))
        elif fam in gap_names:
            train_gaps.append(
                (fam, "training family has a degradation gap"))
    for fam, problem in train_gaps:
        print(
            json.dumps({"lint_train_gap":
                        {"family": fam, "problem": problem}}),
            file=sys.stderr, flush=True,
        )

    # serving-protocol gate (ISSUE 19): servlint's bounded model check
    # of the host-side serving/fleet protocol — page conservation,
    # transactional ships, request safety (SV001–SV007) — over the
    # production ProtocolOps seam. The same exit-2 contract: a protocol
    # counterexample refuses the timing run.
    from triton_distributed_tpu.analysis import servlint

    sv_findings, sv_stats = servlint.lint_serving(max_states=3000)
    findings += sv_findings
    for f in sv_findings:
        print(json.dumps({"lint": f.to_json()}), file=sys.stderr,
              flush=True)
    print(
        json.dumps({"metric": "servlint",
                    "states": sv_stats["states"],
                    "transitions": sv_stats["transitions"],
                    "complete": sv_stats["complete"],
                    "errors": sum(f.severity >= Severity.ERROR
                                  for f in sv_findings)}),
        file=sys.stderr, flush=True,
    )

    errs = (sum(f.severity >= Severity.ERROR for f in findings)
            + len(gaps) + len(fleet_gaps) + len(spec_gaps)
            + len(migration_gaps) + len(train_gaps))
    print(
        json.dumps({"metric": "shmemlint", "errors": errs,
                    "findings": len(findings),
                    "rule_counts": rule_counts(findings),
                    "degradation_gaps": len(gaps),
                    "fleet_gaps": len(fleet_gaps),
                    "spec_gaps": len(spec_gaps),
                    "migration_gaps": len(migration_gaps),
                    "train_gaps": len(train_gaps),
                    "mosaic_scanned": len(report["scanned"]),
                    "mosaic_refused": len(report["refused"])}),
        file=sys.stderr, flush=True,
    )
    if errs:
        print(
            json.dumps({
                "metric": "ag_gemm_tflops_per_chip", "value": 0.0,
                "unit": "TFLOP/s", "vs_baseline": 0.0,
                "error": f"shmemlint found {errs} protocol error(s); "
                "refusing to time broken kernels",
            }),
            flush=True,
        )
        sys.exit(2)


def main(argv=None) -> None:
    args = _parse_args(argv)
    if args.lint:
        _run_lint(infer_contracts=args.infer_contracts)
    if args.faults:
        from triton_distributed_tpu.runtime import faults as _rt_faults

        plan = _rt_faults.parse_plan(args.faults)
        _rt_faults.set_fault_plan(plan)
        print(
            json.dumps({"metric": "fault_replay", "plan": repr(plan)}),
            file=sys.stderr, flush=True,
        )

    if args.scenario is not None:
        from triton_distributed_tpu.tune.perf_model import detect_spec

        scenarios = {
            "serving_fleet": _bench_serving_fleet,
            "serving_speculative": _bench_serving_speculative,
            "serving_elastic": _bench_serving_elastic,
            "serving_multitenant": _bench_serving_multitenant,
            "serving_longcontext": _bench_serving_longcontext,
            "train_step": _bench_train_step,
        }
        bench_fn = scenarios.get(args.scenario)
        if bench_fn is None:
            print(json.dumps({"error":
                              f"unknown scenario {args.scenario!r}"}),
                  file=sys.stderr, flush=True)
            sys.exit(2)
        devs = jax.devices()
        mesh = Mesh(np.asarray(devs), ("x",))
        on_tpu = jax.default_backend() == "tpu"
        kw = {}
        if args.scenario == "serving_fleet" and args.spec_k:
            kw["spec_k"] = args.spec_k
        if args.scenario == "serving_speculative" and args.tree:
            kw["tree"] = True
        out = bench_fn(
            mesh, len(devs), on_tpu, detect_spec(),
            tiny=args.dryrun or not on_tpu, **kw,
        )
        out["faults"] = args.faults
        print(json.dumps(out), flush=True)
        return

    if args.dryrun:
        from triton_distributed_tpu.tune.perf_model import detect_spec

        devs = jax.devices()
        mesh = Mesh(np.asarray(devs), ("x",))
        on_tpu = jax.default_backend() == "tpu"
        out = _bench_serving_continuous(
            mesh, len(devs), on_tpu, detect_spec(), tiny=True,
            n_layers=args.n_layers,
        )
        out["faults"] = args.faults
        print(json.dumps(out), flush=True)
        # the disaggregated twin at the same interpreter shapes: the
        # split-role engine, the DCN wire rails and the perf-model
        # placement gate all run hardware-free too
        out2 = _bench_serving_disaggregated(
            mesh, len(devs), on_tpu, detect_spec(), tiny=True,
        )
        out2["faults"] = args.faults
        print(json.dumps(out2), flush=True)
        return

    from triton_distributed_tpu.kernels.ag_gemm import (
        _build_fused,
        _build_xla_naive,
    )
    from triton_distributed_tpu.tune.perf_model import (
        detect_spec,
        overlap_efficiency,
    )

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.asarray(devs), ("x",))
    on_tpu = jax.default_backend() == "tpu"
    spec = detect_spec()
    device_kind = getattr(devs[0], "device_kind", "cpu")

    # Llama-7B TP8 up-projection (reference test_ag_gemm defaults
    # 8192×8192×28672): each chip's work is the full gathered A against
    # its N/8 weight shard. On one chip we bench exactly that per-chip
    # work; off-TPU (CPU dev runs) shapes shrink to keep CI fast.
    tp = 8
    if on_tpu:
        m, k, n_shard = 8192, 8192, 28672 // tp
    else:
        m, k, n_shard = 256, 256, 512 // tp
    nn = n_shard * n  # global N for the n-device mesh
    dtype = jnp.bfloat16

    key = jax.random.PRNGKey(0)
    a = jax.device_put(
        jax.random.normal(key, (m, k), dtype), NamedSharding(mesh, P("x", None))
    )
    b = jax.device_put(
        jax.random.normal(key, (k, nn), dtype), NamedSharding(mesh, P(None, "x"))
    )

    fused = _build_fused(
        mesh, "x", (), (m, k), (k, nn), jnp.dtype(dtype), jnp.dtype(dtype), 5,
        False, False,  # return_gathered=False: the production default path
    )
    naive = _build_xla_naive(mesh, "x", (), jnp.dtype(dtype))

    def fused_step(state, s):
        a, b = state
        out, _ag = fused(a, b)
        s = s + jnp.sum(out.astype(jnp.float32))
        return (perturb(a, s), b), s

    def naive_step(state, s):
        a, b = state
        out = naive(a, b)
        s = s + jnp.sum(out.astype(jnp.float32))
        return (perturb(a, s), b), s

    lo, hi = (8, 40) if on_tpu else (1, 3)
    reps = 11 if on_tpu else 5  # CPU deltas are µs-scale; keep headroom
    # PAIRED protocol (r4 settle, docs/PERF.md): each rep measures the
    # fused and baseline lo/hi deltas back-to-back and vs_baseline is
    # the MEDIAN OF PER-PAIR RATIOS — slowly-varying chip interference
    # hits both sides of a pair, so the recorded ratio is stable where
    # two independent medians drift apart by the run spread (±2%).
    t_fused, t_naive, ratio_med, ratio_iqr = bench_paired(
        fused_step, naive_step, (a, b), lo=lo, hi=hi, reps=reps
    )

    flops = 2.0 * m * k * nn
    tflops_per_chip = flops / t_fused / n / 1e12
    tflops_naive = flops / t_naive / n / 1e12
    mfu = tflops_per_chip / spec.bf16_tflops
    if n > 1:
        # MEASURED overlap (VERDICT r2 #7): fused vs compute-only vs
        # comm-only on the same shapes, same methodology —
        # (t_comm + t_compute - t_fused) / t_comm is the fraction of the
        # comm time the fused engine actually hid.
        compute_only = jax.jit(
            jax.shard_map(
                lambda af, bl: jnp.dot(af, bl, preferred_element_type=jnp.float32).astype(dtype),
                mesh=mesh, in_specs=(P(None, None), P(None, "x")),
                out_specs=P(None, "x"), check_vma=False,
            )
        )
        comm_only = jax.jit(
            jax.shard_map(
                lambda al: jax.lax.all_gather(al, "x", tiled=True),
                mesh=mesh, in_specs=P("x", None), out_specs=P(None, None),
                check_vma=False,
            )
        )
        a_rep = jax.device_put(
            jax.random.normal(key, (m, k), dtype), NamedSharding(mesh, P(None, None))
        )

        def compute_step(state, s):
            af, bl = state
            out = compute_only(af, bl)
            s = s + jnp.sum(out.astype(jnp.float32))
            return (perturb(af, s), bl), s

        def comm_step(state, s):
            al = state
            out = comm_only(al)
            s = s + jnp.sum(out.astype(jnp.float32))
            return perturb(al, s), s

        t_compute = bench_loop(compute_step, (a_rep, b), lo=lo, hi=hi)
        t_comm = bench_loop(comm_step, a, lo=lo, hi=hi)
        # a comm leg within noise of zero cannot anchor the ratio — say
        # so instead of reporting a clamped artifact as "measured"
        if t_comm > 0.05 * t_fused:
            overlap = max(0.0, min(1.0, (t_comm + t_compute - t_fused) / t_comm))
            overlap_kind = "measured"
        else:
            overlap = 0.0
            overlap_kind = "comm_below_noise_floor"
    else:
        # n=1: no comm exists to measure — project the TP8 ring
        # analytically from the measured per-chip compute. Per ring step
        # the fused kernel hides ONE shard transfer (m/tp·k bytes,
        # unidirectional, one ICI link) under ONE shard matmul (1/tp of
        # the whole per-chip job).
        compute_step_ms = t_fused / tp * 1e3
        shard_bytes = (m // tp) * k * jnp.dtype(dtype).itemsize
        comm_step_ms = shard_bytes / (spec.ici_gbps * 1e9) * 1e3
        overlap = overlap_efficiency(compute_step_ms, comm_step_ms)
        overlap_kind = "projected_tp8"

    print(
        json.dumps(
            {
                "metric": "ag_gemm_tflops_per_chip",
                "value": round(tflops_per_chip, 2),
                "unit": "TFLOP/s",
                # fused vs unoverlapped AG→dot, median of PER-PAIR
                # ratios (paired protocol). At n=1 the baseline's gather
                # leg is free, so this isolates raw engine efficiency —
                # the settled ~2-3% streaming-pipeline overhead
                # (docs/PERF.md; the op entry short-circuits n=1 to the
                # XLA engine, so users never pay it); the overlap
                # advantage appears where there is comm to hide (n>1).
                "vs_baseline": round(ratio_med, 4),
                # NaN (the unpaired-fallback sentinel) is not valid
                # JSON — emit null so the headline line stays parseable
                "vs_baseline_iqr": [
                    None if np.isnan(v) else round(v, 4) for v in ratio_iqr
                ],
                "baseline_tflops_per_chip": round(tflops_naive, 2),
                "device_kind": device_kind,
                "n_chips": n,
                "mfu": round(mfu, 4),
                "overlap_pct": round(100 * overlap, 1),
                "overlap_kind": overlap_kind,
                "config": f"M={m} K={k} N={nn} bf16 fused-streaming",
            }
        ),
        flush=True,
    )

    for fn in (_bench_gemm_rs, _bench_wire_rings, _bench_schedule_search,
               _bench_group_gemm,
               _bench_moe_a2a, _bench_flash_decode,
               _bench_serving_moe_decode, _bench_serving_multilayer,
               _bench_serving_paged, _bench_generate_scan,
               _bench_serving_continuous, _bench_serving_disaggregated):
        try:
            print(json.dumps(fn(mesh, n, on_tpu, spec)), file=sys.stderr, flush=True)
        except Exception as e:
            print(
                json.dumps({"metric": fn.__name__, "error": f"{type(e).__name__}: {e}"[:300]}),
                file=sys.stderr,
                flush=True,
            )


def _bench_gemm_rs(mesh, n, on_tpu, spec):
    """North-star GEMM-RS (Llama-7B down-projection 8192×28672×8192 TP8):
    per-chip K shard against the full output."""
    from triton_distributed_tpu.kernels.gemm_rs import _build_fused

    tp = 8
    m, k_shard, nn = (8192, 28672 // tp, 8192) if on_tpu else (128, 64, 256)
    k = k_shard * n
    dtype = jnp.bfloat16
    a = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (m, k), dtype),
        NamedSharding(mesh, P(None, "x")),
    )
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (k, nn), dtype),
        NamedSharding(mesh, P("x", None)),
    )
    fused = _build_fused(
        mesh, "x", (), (m, k), (k, nn), jnp.dtype(dtype), jnp.dtype(dtype), 6, False
    )

    def step(state, s):
        a, b = state
        out = fused(a, b)
        s = s + jnp.sum(out.astype(jnp.float32))
        return (perturb(a, s), b), s

    lo, hi = (4, 16) if on_tpu else (1, 3)
    t = bench_loop(step, (a, b), lo=lo, hi=hi)
    tflops = 2.0 * m * k * nn / t / n / 1e12
    return {
        "metric": "gemm_rs_tflops_per_chip",
        "value": round(tflops, 2),
        "unit": "TFLOP/s",
        "mfu": round(tflops / spec.bf16_tflops, 4),
        "config": f"n={n} M={m} K={k} N={nn} bf16 fused-streaming",
    }


def _bench_wire_rings(mesh, n, on_tpu, spec):
    """Quantized-wire streaming rings on COMM-BOUND shapes (ISSUE 3):
    decode-side small-M AG-GEMM and GEMM-RS shards where the bf16 ring
    transfer, not the shard matmul, is the per-step critical path.
    Reports per-step wire bytes bf16 vs fp8 (the ≥1.8× acceptance
    check), projected overlap_pct for both wires from the perf model,
    the auto-selector's picks on the comm-bound AND the compute-bound
    north-star configs (must be fp8 resp. bf16), and measured accuracy
    deltas of the fp8/int8 wire vs the bf16-wire twin (XLA ring engines
    — byte-identical wire layout to the fused kernels, runnable at any
    n)."""
    from triton_distributed_tpu.kernels.ag_gemm import AGGemmMethod, ag_gemm
    from triton_distributed_tpu.kernels.gemm_rs import GemmRSMethod, gemm_rs
    from triton_distributed_tpu.lang import wire as wirelib
    from triton_distributed_tpu.tune.perf_model import (
        auto_wire_dtype,
        estimate_gemm_ms,
        overlap_efficiency,
        ring_wire_ms,
    )

    tp = 8
    # comm-bound: decode-scale M (batch rows), Llama-7B K, a small
    # per-shard N (qkv-head-scale projection) — the weight fetch no
    # longer hides the A-slab ring transfer, so the wire IS the
    # per-step critical path
    m_cb, k_cb, nl_cb = 1024, 8192, 512
    slab_cb = m_cb // tp
    # compute-bound: the north-star prefill shard
    m_ns, k_ns, nl_ns = 8192, 8192, 28672 // tp
    slab_ns = m_ns // tp

    from triton_distributed_tpu.tune.perf_model import (
        dequant_pass_ms,
        estimate_s8_gemm_ms,
        int8_mxu_step_ratio,
    )

    fmt = wirelib.make_wire_format("fp8", slab_cb, strict=False)
    bf16_bytes = slab_cb * k_cb * 2
    fp8_bytes = fmt.slab_bytes(slab_cb, k_cb)
    compute_cb = estimate_gemm_ms(slab_cb, k_cb, nl_cb, spec)
    out = {
        "metric": "wire_quantized_rings",
        "wire_reduction_fp8": round(bf16_bytes / fp8_bytes, 3),
        "wire_bytes_per_step": {"bf16": bf16_bytes, "fp8": fp8_bytes},
        "overlap_pct_bf16": round(
            100 * overlap_efficiency(compute_cb, ring_wire_ms(bf16_bytes, spec)), 1
        ),
        "overlap_pct_fp8": round(
            100 * overlap_efficiency(compute_cb, ring_wire_ms(fp8_bytes, spec)), 1
        ),
        "auto_pick_comm_bound": auto_wire_dtype(slab_cb, k_cb, nl_cb, 2, spec=spec),
        "auto_pick_north_star": auto_wire_dtype(slab_ns, k_ns, nl_ns, 2, spec=spec),
        # int8→MXU (round 8): the dequant-free consumer vs
        # dequant-then-matmul on the same int8 wire — the skipped
        # per-arrival pass plus the s8×s8 MXU rate, per ring step
        "auto_pick_comm_bound_wq_int8": auto_wire_dtype(
            slab_cb, k_cb, nl_cb, 2, spec=spec, consumer_wq="int8"
        ),
        "auto_pick_north_star_wq_int8": auto_wire_dtype(
            slab_ns, k_ns, nl_ns, 2, spec=spec, consumer_wq="int8"
        ),
        "int8_mxu_skipped_dequant_ms": round(
            dequant_pass_ms(slab_cb, k_cb, 2, spec), 5
        ),
        "int8_mxu_step_ms": round(
            estimate_s8_gemm_ms(slab_cb, k_cb, nl_cb, spec), 5
        ),
        "int8_mxu_vs_dequant_step_ratio": round(
            int8_mxu_step_ratio(slab_cb, k_cb, nl_cb, spec), 3
        ),
        "config": (
            f"comm-bound M={m_cb} K={k_cb} N/tp={nl_cb} tp={tp} "
            f"(slab {slab_cb}×{k_cb}) vs north-star M={m_ns}"
        ),
    }

    # measured accuracy deltas vs the bf16-wire twin (small shapes off
    # TPU; the wire layout is identical to the fused engines')
    if n == 1:
        # a 1-device mesh short-circuits the rings — no wire is crossed
        # and a 0.0 delta would be vacuous, not evidence
        out["accuracy"] = (
            "n=1: no wire crossed; pinned tolerances in tests/test_wire.py"
        )
        return out
    ma, ka, na = (512, 2048, 512) if not on_tpu else (1024, 8192, 512)
    a = jax.random.normal(jax.random.PRNGKey(21), (ma, ka), jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(22), (ka, na), jnp.bfloat16)
    ref = np.asarray(
        ag_gemm(a, b, mesh, "x", method=AGGemmMethod.XLA_RING), np.float32
    )
    scale = float(np.abs(ref).max()) or 1.0
    pair = {}
    for w in ("fp8", "int8", "int8-mxu"):
        got = np.asarray(
            ag_gemm(a, b, mesh, "x", method=AGGemmMethod.XLA_RING,
                    wire_dtype=w),
            np.float32,
        )
        pair[w] = got
        key = w.replace("-", "_")
        out[f"ag_{key}_rel_err"] = round(
            float(np.abs(got - ref).max()) / scale, 5
        )
    # the paired row the acceptance pins: epilogue-folded dequant vs
    # the dequant-then-matmul twin on the SAME int8 wire bytes (their
    # gap is pure weight-quantization error, bounded by ~1/127)
    out["ag_int8_mxu_vs_dequant_delta"] = round(
        float(np.abs(pair["int8-mxu"] - pair["int8"]).max()) / scale, 5
    )
    a2 = jax.random.normal(jax.random.PRNGKey(23), (ma, ka), jnp.bfloat16)
    b2 = jax.random.normal(jax.random.PRNGKey(24), (ka, na), jnp.bfloat16)
    ref2 = np.asarray(
        gemm_rs(a2, b2, mesh, "x", method=GemmRSMethod.XLA_RING), np.float32
    )
    scale2 = float(np.abs(ref2).max()) or 1.0
    for w in ("fp8", "int8"):
        got = np.asarray(
            gemm_rs(a2, b2, mesh, "x", method=GemmRSMethod.XLA_RING,
                    wire_dtype=w),
            np.float32,
        )
        out[f"rs_{w}_rel_err"] = round(
            float(np.abs(got - ref2).max()) / scale2, 5
        )

    # rs_ring_stream wire row (round 8): the standalone RS's
    # HBM-streaming engine now carries the quantized wire; off-TPU the
    # entry degrades to the byte-identical XLA twin, so this measures
    # the same per-hop quantize / f32 dequant-accumulate numerics the
    # streaming kernel ships on chip
    from triton_distributed_tpu.kernels.reduce_scatter import (
        reduce_scatter,
    )

    ys = jax.random.normal(
        jax.random.PRNGKey(27), (n, 32 * n, 2048), jnp.bfloat16
    )
    ref_s = np.asarray(ys, np.float32).sum(0)
    scale_s = float(np.abs(ref_s).max()) or 1.0
    got_s = np.asarray(
        reduce_scatter(ys, mesh, "x", stacked=True, wire_dtype="int8"),
        np.float32,
    )
    out["rs_stream_int8_rel_err"] = round(
        float(np.abs(got_s - ref_s).max()) / scale_s, 5
    )

    # DCN rail row (round 8): hierarchical ag_gemm at dcn_axis>1 — the
    # rail legs (the slowest transport) ship the quantized payload +
    # scale planes; measured against the raw-rail twin on a 2×(n/2)
    # mesh (the rail machinery is link-agnostic, so the numbers are the
    # DCN numerics even off a real multi-slice pod)
    if n >= 4 and n % 2 == 0:
        from jax.sharding import Mesh

        mesh2 = Mesh(
            np.asarray(mesh.devices).reshape(2, n // 2), ("rail", "x")
        )
        tp2, nd2 = n // 2, 2
        md, kd, nld = 32 * tp2 * nd2, 2048, 64 * tp2 * nd2
        ad = jax.random.normal(jax.random.PRNGKey(28), (md, kd), jnp.bfloat16)
        bd = jax.random.normal(jax.random.PRNGKey(29), (kd, nld), jnp.bfloat16)
        ref_d = np.asarray(
            ag_gemm(ad, bd, mesh2, "x", dcn_axis="rail",
                    method=AGGemmMethod.XLA_RING),
            np.float32,
        )
        got_d = np.asarray(
            ag_gemm(ad, bd, mesh2, "x", dcn_axis="rail",
                    method=AGGemmMethod.XLA_RING, wire_dtype="fp8"),
            np.float32,
        )
        out["dcn_rail_fp8_rel_err"] = round(
            float(np.abs(got_d - ref_d).max())
            / (float(np.abs(ref_d).max()) or 1.0),
            5,
        )
        m_dev = md // (tp2 * nd2)
        fmt_d = wirelib.make_wire_format("fp8", m_dev, strict=False)
        out["dcn_rail_wire_reduction"] = round(
            m_dev * kd * 2 / fmt_d.slab_bytes(m_dev, kd), 3
        )

    if on_tpu and n > 1:
        # real multi-chip: time the fused wire vs bf16 twin, paired.
        # int8 wire — the in-kernel wire this Mosaic can lower
        # (lang.wire.inkernel_wire_ok; fp8 extf is rejected)
        from triton_distributed_tpu.kernels.ag_gemm import _build_fused

        dtype = jnp.bfloat16
        av = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(25), (m_cb, k_cb), dtype),
            NamedSharding(mesh, P("x", None)),
        )
        bv = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(26), (k_cb, nl_cb * n), dtype),
            NamedSharding(mesh, P(None, "x")),
        )
        raw = _build_fused(
            mesh, "x", (), av.shape, bv.shape, jnp.dtype(dtype),
            jnp.dtype(dtype), 5, False, False,
        )
        comp = _build_fused(
            mesh, "x", (), av.shape, bv.shape, jnp.dtype(dtype),
            jnp.dtype(dtype), 5, False, False, None, "int8",
        )

        def mk(fn):
            def step(state, s):
                a, b = state
                o, _ = fn(a, b)
                s = s + jnp.sum(o.astype(jnp.float32))
                return (perturb(a, s), b), s
            return step

        t_raw, t_q, ratio, iqr = bench_paired(
            mk(raw), mk(comp), (av, bv), lo=8, hi=40, reps=11
        )
        out["fused_int8_vs_bf16_ratio"] = round(ratio, 4)
        out["fused_int8_vs_bf16_iqr"] = [round(v, 4) for v in iqr]
        # int8-mxu vs dequant-then-matmul, paired on the SAME wire: the
        # measured counterpart of int8_mxu_vs_dequant_step_ratio above
        mxc = _build_fused(
            mesh, "x", (), av.shape, bv.shape, jnp.dtype(dtype),
            jnp.dtype(dtype), 5, False, False, None, "int8-mxu",
        )
        _, _, ratio_mx, iqr_mx = bench_paired(
            mk(comp), mk(mxc), (av, bv), lo=8, hi=40, reps=11
        )
        out["fused_int8mxu_vs_int8_ratio"] = round(ratio_mx, 4)
        out["fused_int8mxu_vs_int8_iqr"] = [round(v, 4) for v in iqr_mx]
    return out


def _bench_schedule_search(mesh, n, on_tpu, spec):
    """Schedule-space search on the comm-bound config (the tentpole's
    paired row): enumerate ring schedules for the AG-GEMM family, gate
    every candidate through shmemlint+Mosaic (rejections carry rule
    IDs — at least one mutation MUST be rejected or the oracle is
    dead), price the survivors on the perf model, and report the
    searched winner against the canonical default. On TPU the top-k
    survivors are also timed end to end (fused engine, int8 wire);
    off-TPU the row is perf-model-only (``timed: 0``). The winner
    persists keyed by (family, shape, mesh, wire) — the second bench
    run reloads it with zero search cost (``cached: true``)."""
    from triton_distributed_tpu.kernels.ag_gemm import _build_fused
    from triton_distributed_tpu.tune import schedule as sched_lib
    from triton_distributed_tpu.tune.autotuner import search_ring_schedule

    tp = 8
    m_cb, k_cb, nl_cb = 1024, 8192, 512   # _bench_wire_rings' comm-bound
    slab_cb = m_cb // tp

    time_fn = None
    if on_tpu and n == tp:
        dtype = jnp.bfloat16
        av = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(30), (m_cb, k_cb), dtype),
            NamedSharding(mesh, P("x", None)),
        )
        bv = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(31), (k_cb, nl_cb * n), dtype),
            NamedSharding(mesh, P(None, "x")),
        )

        def time_fn(sched):
            wire = "int8-mxu" if sched.dequant == "epilogue" else "int8"
            fn = _build_fused(
                mesh, "x", (), av.shape, bv.shape, jnp.dtype(dtype),
                jnp.dtype(dtype), 5, False, False, None, wire, False, sched,
            )

            def step(state, s):
                a, b = state
                o, _ = fn(a, b)
                s = s + jnp.sum(o.astype(jnp.float32))
                return (perturb(a, s), b), s

            return bench_loop(step, (av, bv), lo=8, hi=40) * 1e3

    rep = search_ring_schedule(
        "ag_gemm.fused", rows=slab_cb, cols=k_cb, mesh_shape=(tp,),
        wire="int8", shape=(m_cb, k_cb), itemsize=2,
        dryrun=not on_tpu, top_k=2, time_fn=time_fn,
    )
    winner = sched_lib.RingSchedule.from_dict(rep["winner"])
    out = {
        "metric": "schedule_search",
        "family": rep["family"],
        "config": f"comm-bound M={m_cb} K={k_cb} N/tp={nl_cb} tp={tp}",
        "cached": rep["cached"],
        "candidates": rep["candidates"],
        "timed": rep.get("timed", 0),
        # the paired row: canonical default vs searched winner, same
        # perf model, same shapes — searched must be no worse
        "default": sched_lib.DEFAULT.to_dict(),
        "default_ms": round(rep["default_ms"], 5),
        "searched": rep["winner"],
        "searched_ms": round(rep["winner_ms"], 5),
        "searched_no_worse": rep["winner_ms"] <= rep["default_ms"] + 1e-9,
        "rejected": [
            {"schedule": s, "rules": rules} for s, rules in rep["rejected"]
        ],
        "winner_is_default": winner.is_default(),
    }
    return out


def _bench_group_gemm(mesh, n, on_tpu, spec):
    """Grouped-GEMM MFU proxy (the MoE expert-compute hot loop)."""
    from triton_distributed_tpu.kernels.group_gemm import grouped_matmul

    if on_tpu:
        e, m_per, h, f, block_m = 8, 1024, 4096, 2048, 512
    else:
        e, m_per, h, f, block_m = 4, 64, 128, 128, 64
    m_total = e * m_per
    x = jax.random.normal(jax.random.PRNGKey(3), (m_total, h), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(4), (e, h, f), jnp.bfloat16)
    block_expert = jnp.repeat(jnp.arange(e, dtype=jnp.int32), m_per // block_m)

    def step(state, s):
        x, w = state
        out = grouped_matmul(x, w, block_expert, block_m=block_m)
        s = s + jnp.sum(out.astype(jnp.float32))
        return (perturb(x, s), w), s

    lo, hi = (8, 80) if on_tpu else (1, 3)
    t = bench_loop(step, (x, w), lo=lo, hi=hi)
    tflops = 2.0 * m_total * h * f / t / 1e12
    return {
        "metric": "group_gemm_tflops",
        "value": round(tflops, 2),
        "unit": "TFLOP/s",
        "mfu": round(tflops / spec.bf16_tflops, 4),
        "config": f"experts={e} m/e={m_per} {h}x{f} bf16",
    }


def _bench_moe_a2a(mesh, n, on_tpu, spec):
    """MoE dispatch leg on the reference's headline config (128 tok/rank,
    topk 8, hidden 7168 — README.md:87), through the FUSED count-bounded
    chunked transport (kernels/moe_dispatch): one aligned staging pass
    over the true M·topk rows + per-peer chunked DMAs sized by the true
    counts (r4; the r3 windows shipped worst-case bytes). With one chip
    there is no wire to cross; what is measured (and labeled) is the
    full dispatch machinery — aligned staging, quantize/bitcast, the
    compiled chunked-DMA kernel, receive unpack."""
    from triton_distributed_tpu.kernels import moe_all_to_all as ma
    from triton_distributed_tpu.kernels import moe_dispatch as md

    epr, hidden, tok, topk = (8, 7168, 128, 8) if on_tpu else (2, 256, 16, 2)
    max_m = tok * topk
    # fp8 wire with in-row per-token scales — the reference's headline
    # config is fp8 WITH_SCALE (README.md:87)
    ctx = ma.create_all_to_all_context(
        mesh, "x", max_m=max_m, hidden=hidden,
        experts_per_rank=epr, dtype=jnp.bfloat16, quant="fp8",
    )
    rng = np.random.default_rng(5)
    sorted_e = np.sort(
        rng.integers(0, ctx.num_experts, (n, max_m)), axis=1
    ).astype(np.int32)
    splits_np = np.stack(
        [np.bincount(a, minlength=ctx.num_experts) for a in sorted_e]
    ).astype(np.int32)
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(5), (n * max_m, hidden), jnp.bfloat16),
        NamedSharding(mesh, P("x")),
    )
    se = jax.device_put(jnp.asarray(sorted_e).reshape(-1), NamedSharding(mesh, P("x")))
    splits = jax.device_put(jnp.asarray(splits_np), NamedSharding(mesh, P("x")))

    def device_leg(x_loc, se_loc, spl_loc):
        spl_loc = spl_loc.reshape(-1)
        counts, offs, offs_al, sendk = md.send_plan(ctx, spl_loc)
        peer, dest = md.assignment_dest(ctx, se_loc, offs, offs_al)
        payload, scales = md.stage_aligned(
            ctx, x_loc, jnp.arange(x_loc.shape[0], dtype=jnp.int32), dest,
            x_loc.shape[0],
        )
        meta = md.meta_payload(ctx, spl_loc, scales, offs_al, sendk)
        recv_tok, recv_meta = md.dispatch_device(
            ctx, payload, offs_al, sendk, meta
        )
        toks, rspl = md.recv_view(ctx, recv_tok, recv_meta)
        return toks.reshape(n * md.slot_pad(ctx), hidden)

    leg = jax.jit(
        jax.shard_map(
            device_leg, mesh=mesh, in_specs=(P("x"), P("x"), P("x")),
            out_specs=P("x"), check_vma=False,
        )
    )

    def device_stage_only(x_loc, se_loc, spl_loc):
        """The staging half alone (plan, gather, quantize, meta pack) —
        total − stage ≈ the transport kernel + receive unpack."""
        spl_loc = spl_loc.reshape(-1)
        counts, offs, offs_al, sendk = md.send_plan(ctx, spl_loc)
        peer, dest = md.assignment_dest(ctx, se_loc, offs, offs_al)
        payload, scales = md.stage_aligned(
            ctx, x_loc, jnp.arange(x_loc.shape[0], dtype=jnp.int32), dest,
            x_loc.shape[0],
        )
        meta = md.meta_payload(ctx, spl_loc, scales, offs_al, sendk)
        return (
            jnp.sum(payload.astype(jnp.float32), axis=1, keepdims=True)
            + jnp.sum(meta.astype(jnp.float32)).reshape(1, 1)
        )

    stage = jax.jit(
        jax.shard_map(
            device_stage_only, mesh=mesh, in_specs=(P("x"), P("x"), P("x")),
            out_specs=P("x"), check_vma=False,
        )
    )

    def step(state, s):
        x = state
        out = leg(x, se, splits)
        s = s + jnp.sum(out.astype(jnp.float32))
        return perturb(x, s), s

    def stage_step(state, s):
        x = state
        out = stage(x, se, splits)
        s = s + jnp.sum(out)
        return perturb(x, s), s

    lo, hi = (16, 400) if on_tpu else (1, 3)
    t = bench_loop(step, x, lo=lo, hi=hi)
    t_stage = bench_loop(stage_step, x, lo=lo, hi=hi)
    return {
        "metric": "moe_a2a_dispatch_latency",
        "value": round(t * 1e6, 1),
        "unit": "us",
        "stage_us": round(t_stage * 1e6, 1),
        "kernel_unpack_us": round((t - t_stage) * 1e6, 1),
        "config": (
            f"n={n} tok/rank={tok} topk={topk} hidden={hidden} fp8+scales "
            "fused-chunked-dma "
            + ("self-transport(no wire)" if n == 1 else "ring")
        ),
    }


# cross-metric scratch: the multi-layer serving bench reports its
# per-layer marginal against the 1-layer step measured just before it
_SHARED = {}


def _bench_serving_multilayer(mesh, n, on_tpu, spec):
    """Serving decode at MODEL depth (VERDICT r4 #3): n_layers=4 with
    alternating dense/MoE blocks (MoE at 1 and 3 — the DeepSeek shape:
    dense layer 0, MoE above, presets.deepseek_moe_16b), the per-layer
    ``EPMoEState`` list threaded at depth, same per-layer dims as the
    1-layer headline. Reports µs/layer marginal vs the 1-layer step —
    serving claims are per-model, and layer-list state threading +
    cross-layer XLA scheduling only show up at depth."""
    from triton_distributed_tpu.models import Transformer, TransformerConfig

    if on_tpu:
        b, s_cap, layers = 128, 2048, 4
        cfg = TransformerConfig(
            vocab=4096, n_layers=layers, hidden=7168, ffn=2048, n_heads=56,
            n_kv_heads=8, head_dim=128, moe="ep", moe_layers=(1, 3),
            num_experts=8, topk=8, param_dtype=jnp.bfloat16,
            moe_weight_quant="int8", moe_act_quant="int8", kv_quant="int8",
            dense_weight_quant="int8", dense_act_quant="int8",
        )
    else:
        b, s_cap, layers = 8, 256, 4
        cfg = TransformerConfig(
            vocab=512, n_layers=layers, hidden=256, ffn=128, n_heads=8,
            n_kv_heads=4, head_dim=32, moe="ep", moe_layers=(1, 3),
            num_experts=8, topk=2, param_dtype=jnp.bfloat16,
        )
    model = Transformer(cfg, mesh, tp_axis="x")
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, s),
        model.init(jax.random.PRNGKey(7)), model.shardings(),
    )
    params = model.quantize_moe_weights(params)
    params = model.quantize_dense_weights(params)
    caches = model.init_cache(b, s_cap)
    lens = jnp.asarray(
        np.random.default_rng(11).integers(s_cap // 8, 3 * s_cap // 4, (b,)),
        jnp.int32,
    )
    toks0 = jnp.zeros((b,), jnp.int32)
    moe_state = model.init_decode_state(b)

    def step(state, s):
        prm, caches, lens_, toks, mst = state
        if mst is None:
            logits, caches, lens_ = model.decode_step(prm, caches, lens_, toks)
        else:
            logits, caches, lens_, mst = model.decode_step(
                prm, caches, lens_, toks, mst
            )
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        s = s + jnp.sum(toks.astype(jnp.float32))
        return (prm, caches, lens_, toks, mst), s

    lo, hi = (4, 24) if on_tpu else (1, 3)
    t_step = bench_loop(
        step, (params, caches, lens, toks0, moe_state), lo=lo, hi=hi,
        donate_idx=4 if moe_state is not None else None,
    )
    out = {
        "metric": "serving_moe_decode_step_multilayer",
        "value": round(t_step * 1e6, 1),
        "unit": "us",
        "n_layers": layers,
        "tok_per_s": round(b / t_step, 0),
        "config": (
            f"n={n} B={b} hidden={cfg.hidden} layers={layers} "
            f"moe_layers={cfg.moe_layers} S={s_cap} lens~U[S/8,3S/4] "
            "dense0+alternating-MoE "
            + ("self-transport(no wire)" if n == 1 else "multi-chip")
        ),
    }
    t1 = _SHARED.get("serving_step_1l")
    if t1:
        # marginal cost of one ADDED layer vs the 1-layer measurement
        # (layer 0 here is dense — cheaper than the MoE headline layer —
        # so the honest comparison is per-MoE-layer: 2 MoE + 2 dense vs
        # 1 MoE; report both raw marginal and the extrapolation ratio)
        out["us_per_layer_marginal"] = round((t_step - t1) / (layers - 1) * 1e6, 1)
        out["vs_1l_extrapolation"] = round(t_step / (layers * t1), 3)
    return out


def _bench_generate_scan(mesh, n, on_tpu, spec):
    """On-device multi-step decode (VERDICT r4 #6): `generate_scan`
    folds the whole decode into ONE jitted lax.scan — this times it at
    the serving headline and reports per-step cost vs the single-step
    extrapolation. Methodology: wall-clock DELTA between steps=64 and
    steps=32 sequences (one dispatch each, host fetch as fence) — the
    ~90 ms relay dispatch round-trip cancels, exactly the artifact the
    scan entry exists to kill. Fresh caches per invocation keep the
    workload constant (caches/lens/state are donated); the LL state is
    threaded call to call."""
    import time as _time

    from triton_distributed_tpu.models import Transformer, TransformerConfig

    if on_tpu:
        b, s_cap = 128, 2048
        cfg = TransformerConfig(
            vocab=4096, n_layers=1, hidden=7168, ffn=2048, n_heads=56,
            n_kv_heads=8, head_dim=128, moe="ep", moe_layers=(0,),
            num_experts=8, topk=8, param_dtype=jnp.bfloat16,
            moe_weight_quant="int8", moe_act_quant="int8", kv_quant="int8",
            dense_weight_quant="int8", dense_act_quant="int8",
        )
        lo_steps, hi_steps, reps = 32, 64, 5
    else:
        b, s_cap = 8, 256
        cfg = TransformerConfig(
            vocab=512, n_layers=1, hidden=256, ffn=128, n_heads=8,
            n_kv_heads=4, head_dim=32, moe="ep", moe_layers=(0,),
            num_experts=8, topk=2, param_dtype=jnp.bfloat16,
        )
        lo_steps, hi_steps, reps = 2, 4, 2
    model = Transformer(cfg, mesh, tp_axis="x")
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, s),
        model.init(jax.random.PRNGKey(7)), model.shardings(),
    )
    params = model.quantize_moe_weights(params)
    params = model.quantize_dense_weights(params)
    lens = jnp.asarray(
        np.random.default_rng(11).integers(s_cap // 8, 3 * s_cap // 4, (b,)),
        jnp.int32,
    )
    toks0 = jnp.zeros((b,), jnp.int32)
    mst = model.init_decode_state(b)

    def run(steps, mst):
        caches = model.init_cache(b, s_cap)    # outside the timed window
        lens0 = lens + 0
        t0 = _time.perf_counter()
        out = model.generate_scan(
            params, caches, lens0, toks0, steps, moe_state=mst
        )
        np.asarray(out[0])                     # host fetch = the fence
        return _time.perf_counter() - t0, (out[3] if mst is not None else None)

    for s in (lo_steps, hi_steps, lo_steps, hi_steps):  # compile + warm
        _, mst = run(s, mst)
    deltas = []
    for _ in range(reps):
        t_lo, mst = run(lo_steps, mst)
        t_hi, mst = run(hi_steps, mst)
        deltas.append((t_hi - t_lo) / (hi_steps - lo_steps))
    t_step = float(np.median(deltas))
    if t_step <= 0:
        raise RuntimeError("generate_scan delta swamped by noise")
    out = {
        "metric": "generate_scan_step",
        "value": round(t_step * 1e6, 1),
        "unit": "us",
        "tok_per_s": round(b / t_step, 0),
        "steps": f"{lo_steps}->{hi_steps}",
        "config": (
            f"n={n} B={b} hidden={cfg.hidden} S={s_cap} one-program "
            "lax.scan decode (donated carries, LL state threaded)"
        ),
    }
    t1 = _SHARED.get("serving_step_1l")
    if t1:
        out["vs_single_step"] = round(t_step / t1, 3)
    return out


def _bench_serving_paged(mesh, n, on_tpu, spec):
    """The serving headline FROM PAGE POOLS (VERDICT r4 #7): same
    config as ``serving_moe_decode_step`` but the KV lives in int8 page
    pools behind a block table (page 1024 per the docs/PERF.md
    guidance) — the production serving mode (the reference's
    block-table path is its default decode entry,
    flash_decode.py:763-846). Proves the composition pool + dynamic
    trips + int8 + LL MoE at the headline shapes; expected within ~10%
    of the contiguous number."""
    from triton_distributed_tpu.models import Transformer, TransformerConfig

    if on_tpu:
        b, s_cap, page = 128, 2048, 1024
        cfg = TransformerConfig(
            vocab=4096, n_layers=1, hidden=7168, ffn=2048, n_heads=56,
            n_kv_heads=8, head_dim=128, moe="ep", moe_layers=(0,),
            num_experts=8, topk=8, param_dtype=jnp.bfloat16,
            moe_weight_quant="int8", moe_act_quant="int8", kv_quant="int8",
            dense_weight_quant="int8", dense_act_quant="int8",
        )
    else:
        b, s_cap, page = 8, 256, 32
        cfg = TransformerConfig(
            vocab=512, n_layers=1, hidden=256, ffn=128, n_heads=8,
            n_kv_heads=4, head_dim=32, moe="ep", moe_layers=(0,),
            num_experts=8, topk=2, param_dtype=jnp.bfloat16,
        )
    model = Transformer(cfg, mesh, tp_axis="x")
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, s),
        model.init(jax.random.PRNGKey(7)), model.shardings(),
    )
    params = model.quantize_moe_weights(params)
    params = model.quantize_dense_weights(params)
    caches, table = model.init_paged_cache(b, s_cap, page=page)
    lens = jnp.asarray(
        np.random.default_rng(11).integers(s_cap // 8, 3 * s_cap // 4, (b,)),
        jnp.int32,
    )
    toks0 = jnp.zeros((b,), jnp.int32)
    moe_state = model.init_decode_state(b)

    def step(state, s):
        prm, caches, lens_, toks, mst, table = state
        if mst is None:
            logits, caches, lens_ = model.decode_step(
                prm, caches, lens_, toks, block_table=table
            )
        else:
            logits, caches, lens_, mst = model.decode_step(
                prm, caches, lens_, toks, mst, block_table=table
            )
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        s = s + jnp.sum(toks.astype(jnp.float32))
        return (prm, caches, lens_, toks, mst, table), s

    lo, hi = (8, 64) if on_tpu else (1, 3)
    t_step = bench_loop(
        step, (params, caches, lens, toks0, moe_state, table), lo=lo, hi=hi,
        donate_idx=4 if moe_state is not None else None,
    )
    out = {
        "metric": "serving_moe_decode_step_paged",
        "value": round(t_step * 1e6, 1),
        "unit": "us",
        "tok_per_s": round(b / t_step, 0),
        "config": (
            f"n={n} B={b} hidden={cfg.hidden} page={page} S={s_cap} "
            "lens~U[S/8,3S/4] int8-KV page pools + block table "
            + ("self-transport(no wire)" if n == 1 else "multi-chip")
        ),
    }
    t1 = _SHARED.get("serving_step_1l")
    if t1:
        out["vs_contiguous"] = round(t_step / t1, 3)
    return out


def _serving_continuous_config(n, on_tpu, tiny=False, n_layers=None):
    """(model config, engine config, trace knobs) for the continuous
    bench. TPU: the serving headline model (hidden 7168, EP-MoE, every
    int8 knob) under the ISSUE-6 traffic shape — B≫128 requests,
    lengths ~U[S/8, 3S/4] against S=2048. Off-TPU (and --dryrun):
    interpreter-sized shapes, same shape of traffic. ``n_layers``
    overrides the model depth (the ``--n-layers`` donation sweep —
    depth > 1 exercises the per-layer serving-state donation path the
    default depth-1 bench never touches)."""
    import jax.numpy as jnp

    from triton_distributed_tpu.models import TransformerConfig
    from triton_distributed_tpu.serving import EngineConfig

    # KV heads shard over tp in the serving state — keep divisible
    n_kv = n if n > 4 else 4
    if on_tpu and not tiny:
        s_cap = 2048
        cfg = TransformerConfig(
            vocab=4096, n_layers=1, hidden=7168, ffn=2048, n_heads=7 * n_kv,
            n_kv_heads=n_kv, head_dim=128, moe="ep", moe_layers=(0,),
            num_experts=max(8, n), topk=8, param_dtype=jnp.bfloat16,
            moe_weight_quant="int8", moe_act_quant="int8", kv_quant="int8",
            dense_weight_quant="int8", dense_act_quant="int8",
        )
        ecfg = EngineConfig(
            slots=160, token_budget=512, chunk=256, page=1024,
            npages=352, max_steps=200_000,
        )
        trace_kw = dict(
            n_requests=256, mean_interarrival=0.25,
            len_lo=s_cap // 8, len_hi=3 * s_cap // 4,
            max_new_lo=16, max_new_hi=64, vocab=4096,
        )
    else:
        s_cap = 64
        cfg = TransformerConfig(
            vocab=256, n_layers=1, hidden=128, ffn=128, n_heads=2 * n_kv,
            n_kv_heads=n_kv, head_dim=32, moe="ep", moe_layers=(0,),
            num_experts=max(4, n), topk=2, param_dtype=jnp.bfloat16,
            dtype=jnp.float32,
        )
        ecfg = EngineConfig(
            slots=6, token_budget=48, chunk=16, page=8,
            npages=40, max_steps=5_000,
        )
        trace_kw = dict(
            n_requests=24, mean_interarrival=0.6,
            len_lo=s_cap // 8, len_hi=3 * s_cap // 4,
            max_new_lo=3, max_new_hi=8, vocab=256,
        )
    if n_layers is not None and n_layers != cfg.n_layers:
        from dataclasses import replace as _rep2

        # keep the MoE layer set valid at the new depth (drop layers
        # past it; added depth is dense — the donation path under test
        # is per-layer KV state, not expert count)
        moe_layers = tuple(l for l in cfg.moe_layers if l < n_layers)
        cfg = _rep2(cfg, n_layers=int(n_layers), moe_layers=moe_layers)
    return cfg, ecfg, trace_kw, s_cap


def _bench_serving_continuous(mesh, n, on_tpu, spec, tiny=False,
                              n_layers=None):
    """CONTINUOUS-BATCHING serving on the ragged paged-attention kernel
    (ISSUE 6 tentpole acceptance): a seeded Poisson arrival trace with
    ~U[S/8, 3S/4] prompt lengths drives the ServingEngine — admission/
    eviction over the page pool, chunked prefill interleaved into
    decode batches, one ragged mixed kernel launch per step — and the
    same trace is then served by the FIXED-BATCH paged baseline (FCFS
    rectangles of `slots` requests through prefill + generate_scan over
    the paged decode path). Reports sustained tok/s, p50/p99 step time
    and GOODPUT (completed requests' generated tokens per wall second)
    for both; ``goodput_vs_fixed_batch`` > 1 is the acceptance."""
    import time as _time

    import jax

    from triton_distributed_tpu.models import Transformer
    from triton_distributed_tpu.serving import ServingEngine, poisson_trace
    from triton_distributed_tpu.tune.perf_model import (
        ragged_serving_step_ms,
    )

    cfg, ecfg, trace_kw, s_cap = _serving_continuous_config(
        n, on_tpu, tiny, n_layers=n_layers
    )
    model = Transformer(cfg, mesh, tp_axis="x")
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, s),
        model.init(jax.random.PRNGKey(7)), model.shardings(),
    )
    params = model.quantize_moe_weights(params)
    params = model.quantize_dense_weights(params)

    def fresh_trace():
        return poisson_trace(seed=11, **trace_kw)

    # ---- continuous engine (run twice; first run pays the compiles)
    for _warm in (False, True):
        trace = fresh_trace()
        eng = ServingEngine(model, params, ecfg)
        stats = eng.run(trace)
    assert stats.completed == trace_kw["n_requests"], (
        stats.completed, stats.deferrals)
    # per-layer KV pool footprint: at depth > 1 the engine carries one
    # (k_pool, v_pool) pair PER LAYER, all donated through the jitted
    # step — the `--n-layers` sweep's reported quantity
    per_layer_pool_bytes = sum(
        int(x.nbytes) for x in jax.tree.leaves(eng.state.layers[0])
    )
    # donation precondition at depth: every layer's pool leaves carry
    # their own buffers (the step jit donates the whole ServingState —
    # a buffer shared across layers would alias the in-place appends).
    # Verified at ANY depth, but only depth > 1 exercises it.
    _leaves = jax.tree.leaves(eng.state.layers)
    try:
        _ptrs = {
            x.addressable_shards[0].data.unsafe_buffer_pointer()
            for x in _leaves
        }
        donation_distinct = len(_ptrs) == len(_leaves)
    except Exception:
        donation_distinct = None

    # ---- traffic-tuned grid schedules: the run's shape ledger feeds a
    # dryrun schedule search per hot key (oracle-gated, perf-model
    # priced); winners persist in the store and the REBUILT engine
    # resolves them with zero search cost on its build path
    from triton_distributed_tpu.tune import traffic as traffic_lib

    wire_key = "int8" if cfg.kv_quant is not None else None
    tune_reports = traffic_lib.retune_hot_shapes(
        stats, mesh_shape=(model.tp,), wire=wire_key, dryrun=True,
    )
    tuned_vs_default = [
        {
            "key": str(rep.get("key", "")),
            "default_ms": round(rep["default_ms"], 4),
            "tuned_ms": round(rep["winner_ms"], 4),
            "winner": rep["winner"],
            "cached": rep["cached"],
        }
        for rep in tune_reports if "error" not in rep
    ]
    eng_tuned = ServingEngine(model, params, ecfg)
    resolved_schedule = eng_tuned.grid_schedule.to_dict()

    # ---- fixed-batch paged baseline on the SAME trace: FCFS
    # rectangles of `slots` requests, padded prompts, every row decoded
    # until the batch's LAST row finishes (the stragglers the engine
    # does not wait for)
    b = ecfg.slots
    page = ecfg.page
    r_ranks = mesh.shape["x"]
    cap_align = r_ranks * page             # paged capacity granularity

    def run_baseline():
        trace = fresh_trace()
        total_useful = 0
        t0 = _time.perf_counter()
        for i in range(0, len(trace), b):
            batch = trace[i:i + b]
            bb = len(batch)
            maxlen = max(len(r.prompt) for r in batch)
            steps = max(r.max_new for r in batch)
            # ONE rectangle for all batches (a per-batch capacity
            # would recompile prefill/scan per batch — charge the
            # rectangle its true cost, not compile time)
            cap = -(-(s_cap + trace_kw["max_new_hi"] + 1)
                    // cap_align) * cap_align
            toks = np.zeros((bb, s_cap), np.int32)
            lens = np.zeros((bb,), np.int32)
            for j, r in enumerate(batch):
                toks[j, :len(r.prompt)] = r.prompt
                lens[j] = len(r.prompt)
            del maxlen
            caches = model.init_cache(bb, cap)
            last, caches, klens = model._prefill_jit(
                params, caches, jnp.asarray(toks), jnp.asarray(lens)
            )
            first = jnp.argmax(last, axis=-1).astype(jnp.int32)
            pcaches, table = model.paginate_caches(caches, page=page)
            out = model.generate_scan(
                params, pcaches, klens, first, int(steps) - 1,
                block_table=table,
            )
            np.asarray(out[0])             # fence
            total_useful += sum(r.max_new for r in batch)
        return total_useful / (_time.perf_counter() - t0)

    run_baseline()                          # compile warm
    base_goodput = run_baseline()

    # model term: a representative steady step (every slot decoding at
    # the mean trace length)
    mean_len = (trace_kw["len_lo"] + trace_kw["len_hi"]) // 2
    from triton_distributed_tpu.tune.perf_model import (
        measured_page_issue_ms,
    )

    model_ms = ragged_serving_step_ms(
        [mean_len] * ecfg.slots, [1] * ecfg.slots, page=page,
        hkv=cfg.n_kv_heads // n, g=cfg.n_heads // cfg.n_kv_heads,
        d=cfg.head_dim, hidden=cfg.hidden, n_layers=cfg.n_layers,
        spec=spec, quant=cfg.kv_quant is not None,
        # the backend's MEASURED per-page issue cost (ROADMAP
        # follow-on): off-TPU the interpreter pays milliseconds per
        # page, not the v5e's 0.17 µs — the model term should track
        # the machine the measurement next to it ran on
        issue_ms=measured_page_issue_ms(),
    )
    ratio = (stats.goodput_tok_per_s / base_goodput
             if base_goodput > 0 else float("inf"))
    return {
        "metric": "serving_continuous",
        "value": round(stats.goodput_tok_per_s, 1),
        "unit": "tok/s goodput",
        "sustained_tok_per_s": round(stats.sustained_tok_per_s, 1),
        "p50_step_ms": round(stats.p50_step_ms, 2),
        "p99_step_ms": round(stats.p99_step_ms, 2),
        "steps": len(stats.step_times),
        "completed": stats.completed,
        "evictions": stats.evictions,
        "deferrals": stats.deferrals,
        "degraded_to_xla": stats.degraded,
        "fixed_batch_goodput": round(base_goodput, 1),
        "goodput_vs_fixed_batch": round(ratio, 3),
        "model_steady_step_ms": round(model_ms, 3),
        "n_layers": cfg.n_layers,
        "per_layer_pool_bytes": per_layer_pool_bytes,
        "pool_bytes_total": per_layer_pool_bytes * cfg.n_layers,
        "donation_distinct_buffers": donation_distinct,
        "tuned_vs_default": tuned_vs_default,
        "tuned_strictly_better": sum(
            1 for r in tuned_vs_default
            if r["tuned_ms"] < r["default_ms"]
        ),
        "resolved_grid_schedule": resolved_schedule,
        "config": (
            f"n={n} slots={ecfg.slots} budget={ecfg.token_budget} "
            f"chunk={ecfg.chunk} page={page} npages={ecfg.npages} "
            f"requests={trace_kw['n_requests']} "
            f"lens~U[{trace_kw['len_lo']},{trace_kw['len_hi']}] "
            f"poisson(seed=11) hidden={cfg.hidden} "
            f"kvq={cfg.kv_quant} "
            + ("tiny-dryrun" if tiny or not on_tpu else "headline")
        ),
    }


def _bench_serving_longcontext(mesh, n, on_tpu, spec, tiny=False):
    """LONG-CONTEXT serving (ISSUE 20 tentpole acceptance): a tp×cp
    mesh replica whose page-table walk is context-parallel — each cp
    rank walks only its own pool shard and the per-rank (out, lse)
    partials merge through the LSE-combine contract — serves a request
    whose KV need is a MULTIPLE of one pool shard (inadmissible on any
    cp-free replica of the same per-slice pool), token-exact against a
    single-slice oracle engine given one pool of the combined size.
    The paired row: (a) the capacity ratio the cp axis bought with
    ``token_mismatches == 0``, (b) short-request goodput on the SAME
    cp engine vs the cp-free engine (the hop tax short traffic pays),
    (c) the PRICED placement verdict — what the fleet router tells a
    cp-free replica refusing the long request, and the modeled
    cp-vs-flat step cost crossover behind it."""
    import jax

    from triton_distributed_tpu.models import Transformer, TransformerConfig
    from triton_distributed_tpu.serving import (
        EngineConfig,
        Request,
        ServingEngine,
        poisson_trace,
    )
    from triton_distributed_tpu.tune.perf_model import (
        cp_decode_step_ms,
        ragged_serving_step_ms,
        refuse_long_context,
    )

    devs = jax.devices()
    if len(devs) < 2:
        return {"metric": "serving_longcontext",
                "error": "needs >= 2 devices for a cp=2 axis"}
    cp = 2
    tp = 2 if len(devs) >= 4 else 1
    mesh_cp = Mesh(
        np.asarray(devs[:tp * cp]).reshape(tp, cp), ("x", "cp"))
    mesh_flat = Mesh(np.asarray(devs[:tp]), ("x",))

    import jax.numpy as jnp

    n_kv = max(tp, 2)
    cfg = TransformerConfig(
        vocab=256, n_layers=2, hidden=128, ffn=128, n_heads=2 * n_kv,
        n_kv_heads=n_kv, head_dim=32, dtype=jnp.float32,
    )
    # one pool shard: 8 pages of 8 tokens. The long request needs
    # ~12 pages — inadmissible on one shard, admitted under cp=2.
    npages_shard, page = 8, 8
    ecfg = EngineConfig(slots=4, token_budget=32, chunk=16, page=page,
                        npages=npages_shard, max_steps=5_000,
                        temperature=0.0)
    ecfg_oracle = EngineConfig(
        slots=4, token_budget=32, chunk=16, page=page,
        npages=cp * npages_shard, max_steps=5_000, temperature=0.0)

    def build(m, cp_axis, use_pallas):
        model = Transformer(cfg, m, tp_axis="x", cp_axis=cp_axis)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            model.init(jax.random.PRNGKey(7)), model.shardings(),
        )
        return model, params, use_pallas

    model_cp, params_cp, _ = build(mesh_cp, "cp", False)
    model_fl, params_fl, _ = build(mesh_flat, None, False)
    use_pallas = bool(on_tpu)

    # ---- (a) capacity: long requests k× one pool shard, cp vs oracle
    rng = np.random.default_rng(23)
    long_prompt = rng.integers(1, 255, size=84).astype(np.int32)
    short_prompts = [rng.integers(1, 255, size=12).astype(np.int32)
                     for _ in range(3)]

    def long_trace():
        reqs = [Request(rid=0, prompt=long_prompt.copy(), max_new=10,
                        arrival=0)]
        reqs += [Request(rid=i + 1, prompt=p.copy(), max_new=4,
                         arrival=0) for i, p in enumerate(short_prompts)]
        return reqs

    t_cp = long_trace()
    eng_cp = ServingEngine(model_cp, params_cp, ecfg,
                           use_pallas=use_pallas)
    stats_cp = eng_cp.run(t_cp)
    t_or = long_trace()
    eng_or = ServingEngine(model_fl, params_fl, ecfg_oracle,
                           use_pallas=use_pallas)
    eng_or.run(t_or)
    streams_cp = {r.rid: list(r.generated) for r in t_cp}
    streams_or = {r.rid: list(r.generated) for r in t_or}
    mismatches = sum(
        1 for rid in streams_or
        for a, b in zip(streams_cp.get(rid, []), streams_or[rid])
        if a != b
    ) + sum(
        1 for rid in streams_or
        if len(streams_cp.get(rid, [])) != len(streams_or[rid])
    )
    need_pages = -(-(len(long_prompt) + 10) // page)
    leaked = int(np.asarray(eng_cp.pool.refs).sum())

    # ---- (b) short-request goodput: cp engine vs cp-free engine on
    # an identical short-only Poisson trace (both warmed once)
    trace_kw = dict(n_requests=12, mean_interarrival=0.6, len_lo=8,
                    len_hi=40, max_new_lo=3, max_new_hi=6, vocab=256)

    def short_goodput(model, params, cfg_e):
        for _warm in (False, True):
            eng = ServingEngine(model, params, cfg_e,
                                use_pallas=use_pallas)
            st = eng.run(poisson_trace(seed=11, **trace_kw))
        return st

    st_cp = short_goodput(model_cp, params_cp, ecfg)
    st_fl = short_goodput(model_fl, params_fl, ecfg)
    ratio = (st_cp.goodput_tok_per_s / st_fl.goodput_tok_per_s
             if st_fl.goodput_tok_per_s > 0 else float("inf"))

    # ---- (c) priced placement verdict: what a cp-free replica of one
    # pool shard says when refusing the long request, and the modeled
    # cp-vs-flat step-cost pair behind the router's choice
    verdict = refuse_long_context(
        cfg, page, need_pages,
        pool_pages=npages_shard,
        pages_per_seq=min(npages_shard, 1024),
        cp=1, spec=spec,
    )
    kv = need_pages * page
    hkv = cfg.n_kv_heads // tp
    g = cfg.n_heads // cfg.n_kv_heads
    cp_ms = cp_decode_step_ms(
        kv, cp=cp, page=page, hkv=hkv, g=g, d=cfg.head_dim,
        hidden=cfg.hidden, n_layers=cfg.n_layers, spec=spec,
        quant=cfg.kv_quant is not None)
    flat_ms = ragged_serving_step_ms(
        [kv], [1], page=page, hkv=hkv, g=g, d=cfg.head_dim,
        hidden=cfg.hidden, n_layers=cfg.n_layers, spec=spec,
        quant=cfg.kv_quant is not None)
    return {
        "metric": "serving_longcontext",
        "value": round(need_pages / npages_shard, 3),
        "unit": "x one-pool capacity served",
        "token_mismatches": int(mismatches),
        "leaked_pages": leaked,
        "long_request_pages": need_pages,
        "pool_pages_per_shard": npages_shard,
        "cp": cp,
        "tp": tp,
        "completed_long": stats_cp.completed,
        "evictions": stats_cp.evictions,
        "short_goodput_cp_tok_per_s": round(
            st_cp.goodput_tok_per_s, 1),
        "short_goodput_flat_tok_per_s": round(
            st_fl.goodput_tok_per_s, 1),
        "short_goodput_ratio": round(ratio, 3),
        "placement_verdict": verdict,
        "model_cp_step_ms": round(cp_ms, 4),
        "model_flat_step_ms": round(flat_ms, 4),
        "config": (
            f"tp={tp} cp={cp} slots={ecfg.slots} page={page} "
            f"npages/shard={npages_shard} long={len(long_prompt)}+10 "
            f"hidden={cfg.hidden} "
            + ("tiny-dryrun" if tiny or not on_tpu else "headline")
        ),
    }


def _bench_serving_disaggregated(mesh, n, on_tpu, spec, tiny=False):
    """DISAGGREGATED prefill/decode (ISSUE 7 tentpole acceptance): the
    PR-6 Poisson trace served by a two-role topology on a 2×(n/2)
    hybrid mesh — a prefill slice runs chunked prefill, each finished
    request's int8 KV pages ship slice→slice on the quantized DCN wire
    (payload + per-row scale planes, the pool's native bytes), landing
    in the decode slice's pool overlapped with its decode steps — vs
    the COLOCATED PR-6 engine on the same n/2-chip slice serving the
    same trace. The number disaggregation must win is DECODE p99 step
    time: colocated decode steps carry interleaved prefill chunks (the
    contention), the decode role's steps never do. Both engines run the
    satellite temperature/top-k sampler (request-keyed draws — the two
    topologies still produce identical token streams, asserted here)."""
    import jax

    from triton_distributed_tpu.models import Transformer
    from triton_distributed_tpu.serving import (
        DisaggregatedEngine,
        ServingEngine,
        poisson_trace,
    )
    from triton_distributed_tpu.tune.perf_model import (
        kv_ship_ms,
        measured_page_issue_ms,
        refuse_disaggregation,
    )

    devs = jax.devices()
    if len(devs) < 2:
        return {"metric": "serving_disaggregated",
                "error": "needs >= 2 devices for a 2x(n/2) role split"}
    half = len(devs) // 2
    mesh_p = Mesh(np.asarray(devs[:half]), ("x",))
    mesh_d = Mesh(np.asarray(devs[half:2 * half]), ("x",))
    hybrid = Mesh(
        np.asarray(devs[:2 * half]).reshape(2, half), ("dcn", "x")
    )

    cfg, ecfg, trace_kw, s_cap = _serving_continuous_config(
        half, on_tpu, tiny
    )
    from dataclasses import replace as _rep

    if not on_tpu or tiny:
        # the CONTENDED shape of the comparison: prefill chunks much
        # wider than a decode batch (budget ≫ 8·slots), prompts many
        # chunks long, arrivals dense enough that colocated decode
        # steps almost always carry a prefill chunk. The decode role's
        # engine auto-narrows to an 8·slots packed width, so its steps
        # never pay the prefill-sized rectangle — the width gap that
        # IS the interference, visible even on the dev box where the
        # XLA-twin step cost is rectangle-shaped.
        s_cap = 256
        # int8 KV pools even at interpreter shapes: the ship's payload
        # is then the pool's native int8 bytes + per-row scale planes —
        # the quantized wire (and its compression) under test
        cfg = _rep(cfg, kv_quant="int8")
        ecfg = _rep(
            ecfg, slots=6, token_budget=256, chunk=128, page=8,
            npages=192,
        )
        trace_kw = dict(
            n_requests=24, mean_interarrival=0.8,
            len_lo=64, len_hi=192, max_new_lo=4, max_new_hi=10,
            vocab=trace_kw["vocab"],
        )
    ecfg = _rep(ecfg, temperature=0.7, top_k=40, seed=11)

    def build(mesh_role):
        model = Transformer(cfg, mesh_role, tp_axis="x")
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            model.init(jax.random.PRNGKey(7)), model.shardings(),
        )
        params = model.quantize_moe_weights(params)
        params = model.quantize_dense_weights(params)
        return model, params

    model_p, params_p = build(mesh_p)
    model_d, params_d = build(mesh_d)

    def fresh_trace():
        return poisson_trace(seed=11, **trace_kw)

    import os as _os

    from triton_distributed_tpu.runtime import faults as _rt_faults
    from triton_distributed_tpu.runtime import watchdog as _rt_watchdog

    wd_trips = []

    def _guarded(run_fn):
        """Under --faults, arm the collective watchdog around the run
        (the serving_step / kv_ship host heartbeats are live) so a
        stalled ship or step TRIPS — the trip feeds the health ledger
        and releases the stall gates — instead of wedging the bench.
        Trips are reported, not fatal: the run's recovery behavior is
        the thing under test."""
        if _rt_faults.active_plan() is None:
            return run_fn()
        # generous default: the first guarded run pays jit compiles,
        # which can take seconds on the dev box — only a real stall
        # (or a wedged slice) should out-wait this
        deadline = float(_os.environ.get("TDTPU_BENCH_WATCHDOG", "10.0"))
        box = {}
        try:
            with _rt_watchdog.collective_watchdog(deadline=deadline):
                box["stats"] = run_fn()
        except _rt_watchdog.WatchdogTimeout as e:
            wd_trips.append(str(e).splitlines()[0])
        finally:
            _rt_watchdog.clear_trip()
        return box.get("stats")

    # ---- colocated baseline on the SAME n/2-chip slice (run twice;
    # the first run pays the compiles). Under a SliceDeath fault plan
    # this engine is untouched (no slice roles), so its token streams
    # stay the fault-free reference the failover must reproduce.
    for _warm in (False, True):
        trace_c = fresh_trace()
        eng_c = ServingEngine(model_p, params_p, ecfg)
        stats_c = _guarded(lambda: eng_c.run(trace_c))
    assert stats_c is not None and (
        stats_c.completed == trace_kw["n_requests"]
    ), (stats_c and stats_c.completed, wd_trips)

    # ---- disaggregated, KV on the quantized DCN wire
    for _warm in (False, True):
        trace_d = fresh_trace()
        eng = DisaggregatedEngine(
            model_p, params_p, model_d, params_d, ecfg,
            hybrid_mesh=hybrid, dcn_axis="dcn", transport="dcn",
            ship_delay_steps=1,
        )
        stats = _guarded(lambda: eng.run(trace_d))
    assert stats is not None and (
        stats.completed == trace_kw["n_requests"]
    ), (stats and stats.completed, len(eng._ready), len(eng._inflight),
        wd_trips)
    # token-exactness across topologies (int8 KV pages shipped
    # verbatim + request-keyed sampling): the split changes WHERE work
    # runs, never what it computes
    mismatches = sum(
        a.generated != b.generated for a, b in zip(trace_c, trace_d)
    )

    mean_len = (trace_kw["len_lo"] + trace_kw["len_hi"]) // 2
    pages_per_req = -(-mean_len // ecfg.page)
    hkv_l = cfg.n_kv_heads // half
    ship_model_ms = kv_ship_ms(
        pages_per_req, ecfg.page, hkv_l, cfg.head_dim, cfg.n_layers,
        cfg.kv_quant is not None, spec,
    )
    refusal = refuse_disaggregation(
        cfg, ecfg.page,
        {"prompt_len": mean_len,
         "max_new": (trace_kw["max_new_lo"] + trace_kw["max_new_hi"]) // 2},
        spec,
    )
    # the measured per-page issue cost (ROADMAP follow-on): steady-state
    # decode walks ~ceil(len/page) pages per active row, so the decode
    # role's p50 step over its typical row count prices one page walk
    steady_rows = max(
        1, min(ecfg.slots, int(np.median(
            [t for t in stats.decode.step_tokens if t > 0] or [1]
        )))
    )
    measured_issue = (
        stats.decode.p50_step_ms / (steady_rows * pages_per_req)
        if pages_per_req else 0.0
    )

    p99_c = stats_c.decode_p99_step_ms
    p99_d = stats.decode_p99_step_ms
    return {
        "metric": "serving_disaggregated",
        "value": round(p99_d, 2),
        "unit": "ms decode p99",
        "colocated_decode_p99_ms": round(p99_c, 2),
        "decode_p99_vs_colocated": round(p99_d / p99_c, 3) if p99_c else None,
        "decode_p99_improved": bool(p99_d < p99_c),
        "goodput_tok_per_s": round(stats.goodput_tok_per_s, 1),
        "colocated_goodput": round(stats_c.goodput_tok_per_s, 1),
        "goodput_vs_colocated": round(
            stats.goodput_tok_per_s / stats_c.goodput_tok_per_s, 3
        ) if stats_c.goodput_tok_per_s else None,
        "ships": stats.ships,
        "ship_p50_ms": round(float(np.median(stats.ship_ms)), 2)
        if stats.ship_ms else 0.0,
        "shipped_wire_bytes": stats.shipped_wire_bytes,
        "wire_compression_vs_raw": round(stats.wire_compression, 3),
        "degraded_transport": stats.degraded_transport,
        "final_transport": eng.transport,
        "ship_retries": stats.ship_retries,
        "transport_repromotions": stats.transport_repromotions,
        "kernel_repromotions": (
            stats.prefill.repromotions + stats.decode.repromotions
        ),
        # failover outcome (ISSUE 10): under a SliceDeath plan the
        # colocated run above is the fault-free token reference, so
        # token_mismatches_vs_colocated == 0 IS the token-exactness
        # acceptance; lost_requests must be 0
        "failover": stats.failover,
        "lost_requests": trace_kw["n_requests"] - stats.completed,
        "watchdog_trips": wd_trips,
        "health": eng.health.snapshot(),
        "token_mismatches_vs_colocated": mismatches,
        "prefill_evictions": stats.prefill.evictions,
        "decode_evictions": stats.decode.evictions,
        "kv_ship_model_ms_per_req": round(ship_model_ms, 4),
        "auto_placement": ("refused: " + refusal) if refusal else "accepted",
        "measured_page_issue_ms": round(measured_issue, 4),
        "model_page_issue_ms": measured_page_issue_ms(),
        "config": (
            f"2x{half} hybrid mesh, slots={ecfg.slots} "
            f"budget={ecfg.token_budget} chunk={ecfg.chunk} "
            f"page={ecfg.page} npages={ecfg.npages} "
            f"requests={trace_kw['n_requests']} "
            f"lens~U[{trace_kw['len_lo']},{trace_kw['len_hi']}] "
            f"temp=0.7 top_k=40 kvq={cfg.kv_quant} "
            + ("tiny-dryrun" if tiny or not on_tpu else "headline")
        ),
    }


def _bench_serving_speculative_tree(mesh, n, on_tpu, spec, tiny=False):
    """The --tree paired row (ISSUE-18 acceptance): tree speculation
    (spec_tree verify trees under the kernel's TREE topology, the
    TreeDrafter's trunk + sibling branches) against linear draft-k on
    a BRANCHY SAMPLED motif trace — small top_k temperature sampling
    makes the prompt self-history genuinely ambiguous, the regime
    where sibling rescue branches accept tokens the single linear
    draft loses. Both engines must reproduce the plain engine's
    streams byte-identically; the tree row must land strictly more
    accepted tokens per verify step. Rides the pinned small recipe
    (the acceptance comparison is about scheduling, not FLOPs) so the
    row is deterministic on CPU and TPU alike. Also emits the
    in-batch shared-prefix dedup paired row: requests sharing a long
    prompt prefix served with ``prefix_share`` fold their duplicate
    frozen prefix pages onto one canonical page (deduped pages > 0,
    token-exact, goodput no worse)."""
    import jax
    from dataclasses import replace as _sp_rep

    from triton_distributed_tpu.models import Transformer, TransformerConfig
    from triton_distributed_tpu.serving import (
        EngineConfig,
        NGramDrafter,
        Request,
        ServingEngine,
        SpeculativeEngine,
        TreeDrafter,
        poisson_trace,
    )
    from triton_distributed_tpu.tune.perf_model import (
        DEFAULT_SPEC_ACCEPTANCE,
        expected_accepted_per_step,
        expected_accepted_per_step_tree,
    )

    cfg = TransformerConfig(
        vocab=128, n_layers=2, hidden=64, ffn=128, n_heads=4,
        n_kv_heads=2, head_dim=16, dtype=jnp.float32,
        param_dtype=jnp.float32,
    )
    mesh1 = Mesh(np.asarray(jax.devices()[:1]), ("x",))
    model = Transformer(cfg, mesh1, tp_axis="x")
    params = model.init(jax.random.PRNGKey(0))
    ecfg = EngineConfig(slots=4, token_budget=48, chunk=16, page=8,
                        npages=40, temperature=1.0, top_k=4, seed=5)
    spec_tree, spec_k = 8, 4

    def branchy_trace():
        base = poisson_trace(13, 6, 0.5, 8, 30, 16, 24, 128)
        rng = np.random.default_rng(13 + 1000)
        for r in base:
            ln = len(r.prompt)
            motif = rng.integers(0, 128, (5,)).astype(np.int32)
            r.prompt = np.tile(motif, -(-ln // 5))[:ln]
        return base

    t_ref = branchy_trace()
    stats_ref = ServingEngine(model, params, ecfg).run(
        t_ref, max_steps=800)
    t_tree = branchy_trace()
    stats_tree = SpeculativeEngine(
        model, params, ecfg, spec_tree=spec_tree,
        drafter=TreeDrafter(branches=3, branch_len=2),
    ).run(t_tree, max_steps=800)
    t_lin = branchy_trace()
    stats_lin = SpeculativeEngine(
        model, params, ecfg, spec_k=spec_k, drafter=NGramDrafter(),
    ).run(t_lin, max_steps=800)
    assert (stats_ref.completed == stats_tree.completed
            == stats_lin.completed == len(t_ref))
    mism_tree = sum(
        a.generated != b.generated for a, b in zip(t_ref, t_tree))
    mism_lin = sum(
        a.generated != b.generated for a, b in zip(t_ref, t_lin))
    tree_acc = stats_tree.accepted_tokens_per_step
    lin_acc = stats_lin.accepted_tokens_per_step

    # ---- shared-prefix dedup paired row: one long common prefix
    def shared_trace():
        rng = np.random.default_rng(21)
        prefix = rng.integers(0, 128, (24,)).astype(np.int32)
        return [
            Request(rid=i,
                    prompt=np.concatenate(
                        [prefix,
                         rng.integers(0, 128, (4,)).astype(np.int32)]),
                    max_new=6, arrival=0.1 * i)
            for i in range(6)
        ]

    dcfg = _sp_rep(ecfg, slots=3, npages=64)
    for _warm in (False, True):            # warm run pays the compiles
        t_base = shared_trace()
        stats_base = ServingEngine(model, params, dcfg).run(
            t_base, max_steps=800)
    for _warm in (False, True):
        t_dd = shared_trace()
        stats_dd = ServingEngine(
            model, params,
            _sp_rep(dcfg, prefix_cache=True, prefix_share=True),
        ).run(t_dd, max_steps=800)
    assert stats_base.completed == stats_dd.completed == len(t_base)
    mism_dd = sum(
        a.generated != b.generated for a, b in zip(t_base, t_dd))

    return {
        "metric": "serving_speculative_tree",
        "value": round(tree_acc, 3),
        "unit": "accepted tok/verify-step",
        "accepted_tokens_per_step": round(tree_acc, 3),
        "linear_accepted_tokens_per_step": round(lin_acc, 3),
        "tree_beats_linear": bool(tree_acc > lin_acc),
        "token_mismatches_vs_nonspeculative": mism_tree,
        "linear_token_mismatches_vs_nonspeculative": mism_lin,
        "spec_rows": stats_tree.spec_rows,
        "draft_tokens": stats_tree.draft_tokens,
        "rolled_back_tokens": stats_tree.rolled_back_tokens,
        "steps": len(stats_tree.step_times),
        "steps_linear": len(stats_lin.step_times),
        "steps_nonspeculative": len(stats_ref.step_times),
        "model_accepted_per_step_linear_prior": round(
            expected_accepted_per_step(spec_k, DEFAULT_SPEC_ACCEPTANCE),
            3),
        "model_accepted_per_step_tree_prior": round(
            expected_accepted_per_step_tree(
                spec_tree, DEFAULT_SPEC_ACCEPTANCE, branches=3), 3),
        # the shared-prefix dedup row
        "shared_prefix_rows": stats_dd.shared_prefix_rows,
        "deduped_pages": stats_dd.deduped_pages,
        "dedup_token_mismatches": mism_dd,
        # scheduler-level goodput (generated tokens per STEP): the
        # deterministic "no worse" pin — dedup changes page aliasing,
        # never the step count or the streams. Wall-clock goodput rides
        # alongside; at interpreter-tiny shapes it sees the host-side
        # table rewrite but not the KV reads dedup saves, so it is
        # reported, not gated on.
        "dedup_goodput_ratio": round(
            (stats_dd.generated_tokens / len(stats_dd.step_times))
            / (stats_base.generated_tokens / len(stats_base.step_times)),
            3),
        "dedup_wallclock_goodput_ratio": round(
            stats_dd.goodput_tok_per_s / stats_base.goodput_tok_per_s, 3
        ) if stats_base.goodput_tok_per_s else None,
        "config": (
            f"spec_tree={spec_tree} TreeDrafter(branches=3, "
            f"branch_len=2) vs spec_k={spec_k} ngram, top_k=4 "
            f"temperature=1.0 branchy motif trace; dedup: 6 requests "
            f"sharing a 24-token prefix, page=8 "
            + ("tiny-dryrun" if tiny or not on_tpu else "headline")
        ),
    }


def _bench_serving_speculative(mesh, n, on_tpu, spec, tiny=False,
                               tree=False):
    if tree:
        return _bench_serving_speculative_tree(mesh, n, on_tpu, spec,
                                               tiny=tiny)
    """SPECULATIVE decoding (ISSUE 12 tentpole acceptance): the PR-6
    Poisson trace with MOTIF-HEAVY prompts (repeated 5-token motifs —
    the traffic shape prompt-lookup speculation exists for) served
    three ways: (1) the plain colocated engine — the token-stream
    reference; (2) the colocated SpeculativeEngine (n-gram drafter,
    spec_k=4) — must reproduce the reference streams byte-identically
    while emitting >1 accepted token per verify row; (3) the
    disaggregated engine with a speculative decode role — same streams
    again, with KV still shipping on the quantized DCN wire at the
    CHANGED cadence (fewer, wider decode steps). Reports the decode
    p50/p99 deltas speculation buys and the perf-model rows that price
    the cadence change for placement (`spec_step_ms`, the truncated-
    geometric accepted/step prior, and `refuse_disaggregation` with
    and without `spec_k` in the traffic dict)."""
    import jax

    from triton_distributed_tpu.models import Transformer
    from triton_distributed_tpu.serving import (
        DisaggregatedEngine,
        NGramDrafter,
        ServingEngine,
        SpeculativeEngine,
        poisson_trace,
    )
    from triton_distributed_tpu.tune.perf_model import (
        DEFAULT_SPEC_ACCEPTANCE,
        expected_accepted_per_step,
        measured_page_issue_ms,
        ragged_serving_step_ms,
        refuse_disaggregation,
        spec_step_ms,
    )

    devs = jax.devices()
    if len(devs) < 2:
        return {"metric": "serving_speculative",
                "error": "needs >= 2 devices for the disaggregated leg"}
    half = len(devs) // 2
    mesh_p = Mesh(np.asarray(devs[:half]), ("x",))
    mesh_d = Mesh(np.asarray(devs[half:2 * half]), ("x",))
    hybrid = Mesh(
        np.asarray(devs[:2 * half]).reshape(2, half), ("dcn", "x")
    )

    cfg, ecfg, trace_kw, s_cap = _serving_continuous_config(
        half, on_tpu, tiny
    )
    from dataclasses import replace as _rep

    # GREEDY decode: at temperature 0 every engine argmaxes the same
    # logits, so acceptance is purely "did the drafter guess the
    # model's next token" — the honest accepted/step for prompt-lookup
    ecfg = _rep(ecfg, temperature=0.0, seed=11)
    if not on_tpu or tiny:
        # decode-heavy traffic: long generation tails (greedy decode on
        # a tiny model settles into repetitive continuations — the
        # regime prompt-lookup drafting feeds on) and pool headroom for
        # the provisional draft pages
        trace_kw = dict(
            trace_kw, len_lo=8, len_hi=32,
            max_new_lo=16, max_new_hi=32,
        )
        ecfg = _rep(ecfg, npages=64)
    spec_k = 4

    def build(mesh_role):
        model = Transformer(cfg, mesh_role, tp_axis="x")
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            model.init(jax.random.PRNGKey(7)), model.shardings(),
        )
        params = model.quantize_moe_weights(params)
        params = model.quantize_dense_weights(params)
        return model, params

    model_p, params_p = build(mesh_p)
    model_d, params_d = build(mesh_d)

    def fresh_trace():
        """The Poisson arrivals/max_new, with every prompt rewritten
        into a repeated 5-token motif (fresh Request objects per call —
        engines mutate them in place). Deterministic."""
        base = poisson_trace(seed=11, **trace_kw)
        rng = np.random.default_rng(29)
        for r in base:
            ln = len(r.prompt)
            motif = rng.integers(
                0, trace_kw["vocab"], (5,)).astype(np.int32)
            r.prompt = np.tile(motif, -(-ln // 5))[:ln]
        return base

    # ---- (1) plain colocated reference (warm run pays compiles)
    for _warm in (False, True):
        trace_ref = fresh_trace()
        eng_ref = ServingEngine(model_p, params_p, ecfg)
        stats_ref = eng_ref.run(trace_ref)
    assert stats_ref.completed == trace_kw["n_requests"], (
        stats_ref.completed, stats_ref.deferrals)

    # ---- (2) colocated speculative, n-gram drafter
    for _warm in (False, True):
        trace_s = fresh_trace()
        eng_s = SpeculativeEngine(
            model_p, params_p, ecfg, spec_k=spec_k,
            drafter=NGramDrafter(),
        )
        stats_s = eng_s.run(trace_s)
    assert stats_s.completed == trace_kw["n_requests"], (
        stats_s.completed, stats_s.deferrals)
    mism_coloc = sum(
        a.generated != b.generated for a, b in zip(trace_ref, trace_s)
    )

    # ---- (3) disaggregated with a speculative decode role
    for _warm in (False, True):
        trace_d = fresh_trace()
        eng_d = DisaggregatedEngine(
            model_p, params_p, model_d, params_d, ecfg,
            hybrid_mesh=hybrid, dcn_axis="dcn", transport="dcn",
            ship_delay_steps=1, spec_k=spec_k, drafter=NGramDrafter(),
        )
        stats_d = eng_d.run(trace_d)
    assert stats_d.completed == trace_kw["n_requests"], (
        stats_d.completed, len(eng_d._ready), len(eng_d._inflight))
    mism_disagg = sum(
        a.generated != b.generated for a, b in zip(trace_ref, trace_d)
    )

    # ---- perf-model: the priced ship-cadence change. Speculation
    # widens each decode row to q=1+k and shrinks the decode window to
    # max_new/accepted steps — the rows placement reasons with.
    mean_len = (trace_kw["len_lo"] + trace_kw["len_hi"]) // 2
    hkv_l = max(1, cfg.n_kv_heads // half)
    g = cfg.n_heads // cfg.n_kv_heads
    plain_ms = ragged_serving_step_ms(
        [mean_len] * ecfg.slots, [1] * ecfg.slots, page=ecfg.page,
        hkv=hkv_l, g=g, d=cfg.head_dim, hidden=cfg.hidden,
        n_layers=cfg.n_layers, spec=spec,
        quant=cfg.kv_quant is not None,
        issue_ms=measured_page_issue_ms(),
    )
    spec_ms = spec_step_ms(
        [mean_len] * ecfg.slots, spec_k=spec_k, page=ecfg.page,
        hkv=hkv_l, g=g, d=cfg.head_dim, hidden=cfg.hidden,
        n_layers=cfg.n_layers, spec=spec,
        quant=cfg.kv_quant is not None,
        issue_ms=measured_page_issue_ms(),
    )
    prior_acc = expected_accepted_per_step(
        spec_k, DEFAULT_SPEC_ACCEPTANCE
    )
    measured_acc = stats_s.accepted_tokens_per_step
    traffic = {
        "prompt_len": mean_len,
        "max_new": (trace_kw["max_new_lo"]
                    + trace_kw["max_new_hi"]) // 2,
    }
    refusal_plain = refuse_disaggregation(cfg, ecfg.page, traffic, spec)
    p_meas = (min(1.0, stats_s.draft_acceptance_rate)
              if stats_s.draft_tokens else DEFAULT_SPEC_ACCEPTANCE)
    refusal_spec = refuse_disaggregation(
        cfg, ecfg.page,
        dict(traffic, spec_k=spec_k, spec_acceptance=p_meas),
        spec,
    )

    p50_ref, p99_ref = (stats_ref.decode_p50_step_ms,
                        stats_ref.decode_p99_step_ms)
    p50_s, p99_s = (stats_s.decode_p50_step_ms,
                    stats_s.decode_p99_step_ms)
    return {
        "metric": "serving_speculative",
        "value": round(measured_acc, 3),
        "unit": "accepted tok/verify-step",
        "accepted_tokens_per_step": round(measured_acc, 3),
        "draft_acceptance_rate": round(
            stats_s.draft_acceptance_rate, 3),
        "token_mismatches_vs_nonspeculative": mism_coloc,
        "token_mismatches_disaggregated": mism_disagg,
        "spec_rows": stats_s.spec_rows,
        "draft_tokens": stats_s.draft_tokens,
        "rolled_back_tokens": stats_s.rolled_back_tokens,
        "steps": len(stats_s.step_times),
        "steps_nonspeculative": len(stats_ref.step_times),
        "decode_p50_step_ms": round(p50_s, 2),
        "decode_p99_step_ms": round(p99_s, 2),
        "decode_p50_delta_ms": round(p50_s - p50_ref, 2),
        "decode_p99_delta_ms": round(p99_s - p99_ref, 2),
        "goodput_tok_per_s": round(stats_s.goodput_tok_per_s, 1),
        "goodput_vs_nonspeculative": round(
            stats_s.goodput_tok_per_s / stats_ref.goodput_tok_per_s, 3
        ) if stats_ref.goodput_tok_per_s else None,
        "disagg_accepted_tokens_per_step": round(
            stats_d.decode.accepted_tokens_per_step, 3),
        "disagg_ships": stats_d.ships,
        "disagg_decode_p99_ms": round(stats_d.decode_p99_step_ms, 2),
        # the priced cadence change: ms per EMITTED token, before and
        # after speculation — what replica_load_ms and auto placement
        # now reason with
        "model_plain_step_ms": round(plain_ms, 4),
        "model_spec_step_ms": round(spec_ms, 4),
        "model_accepted_per_step_prior": round(prior_acc, 3),
        "model_ms_per_token_plain": round(plain_ms, 4),
        "model_ms_per_token_spec": round(
            spec_ms / max(measured_acc, 1.0), 4),
        "auto_placement_plain": (
            ("refused: " + refusal_plain) if refusal_plain
            else "accepted"),
        "auto_placement_spec": (
            ("refused: " + refusal_spec) if refusal_spec
            else "accepted"),
        "config": (
            f"2x{half} hybrid mesh, spec_k={spec_k} ngram drafter "
            f"slots={ecfg.slots} budget={ecfg.token_budget} "
            f"chunk={ecfg.chunk} page={ecfg.page} "
            f"npages={ecfg.npages} requests={trace_kw['n_requests']} "
            f"motif-prompts lens~U[{trace_kw['len_lo']},"
            f"{trace_kw['len_hi']}] greedy "
            + ("tiny-dryrun" if tiny or not on_tpu else "headline")
        ),
    }


def _fleet_trace(trace_kw, page):
    """The serving_fleet traffic: the seeded Poisson base PLUS two
    session bursts, each sharing its OWN 10-page prompt prefix — a
    leader arrives early and its followers arrive after the leader's
    prefill has published the prefix pages. A cache-aware router lands
    every follower on resident pages (one prefill per session);
    round-robin scatters each session across replicas and pays the
    prefill once per replica. Deterministic; fresh Request objects per
    call (engines mutate them in place)."""
    from triton_distributed_tpu.serving import poisson_trace
    from triton_distributed_tpu.serving.engine import Request

    base = poisson_trace(seed=13, **trace_kw)
    rng = np.random.default_rng(17)
    out = list(base)
    rid = len(base)
    for s in range(2):
        prefix = rng.integers(
            0, trace_kw["vocab"], (10 * page,)).astype(np.int32)
        # leader at 1.0/2.0 (prefilled well before the acceptance
        # plan's step-8 death); followers straddle the death
        arrivals = [1.0 + s] + [8.0 + s + 1.5 * j for j in range(5)]
        for a in arrivals:
            tail = rng.integers(
                0, trace_kw["vocab"], (int(rng.integers(4, 12)),)
            ).astype(np.int32)
            req = Request(
                rid=rid,
                prompt=np.concatenate([prefix, tail]),
                max_new=int(rng.integers(trace_kw["max_new_lo"],
                                         trace_kw["max_new_hi"])),
                arrival=a,
            )
            req.session = f"burst-{s}"
            out.append(req)
            rid += 1
    return out


def _bench_serving_fleet(mesh, n, on_tpu, spec, tiny=False,
                         spec_k=None):
    """FLEET serving (ISSUE 11 tentpole acceptance): 3 engine replicas,
    each on its own mesh slice carved by ``carve_replica_meshes``,
    behind the scored ``FleetRouter`` (prefix overlap × health × load
    estimate, session affinity, spill) vs a ROUND-ROBIN baseline on
    the same Poisson + shared-prefix-burst trace. Under a --faults
    ``ReplicaDeath`` plan the dead replica's in-flight requests drain
    back through the router onto the survivor: ``lost_requests`` must
    be 0 and the token streams byte-identical to the fault-free
    reference run (request-keyed sampling — placement cannot change
    tokens).

    ``--spec-k K`` (ISSUE 13 satellite) swaps every replica for a
    :class:`SpeculativeEngine` at draft budget K (ngram drafter, motif
    prompts so prompt-lookup drafting has something to accept): the
    NON-speculative scored fleet becomes the reference run, so the
    token oracle simultaneously proves fleet-level speculative
    token-exactness, and the output adds per-replica accepted
    tokens/step plus the spec-vs-plain goodput ratio on the identical
    trace."""
    import os as _os

    import jax

    from triton_distributed_tpu.models import Transformer
    from triton_distributed_tpu.runtime import faults as _rt_faults
    from triton_distributed_tpu.runtime import watchdog as _rt_watchdog
    from triton_distributed_tpu.runtime.topology import (
        carve_replica_meshes,
    )
    from triton_distributed_tpu.serving import (
        NGramDrafter,
        ServingEngine,
        SpeculativeEngine,
    )
    from triton_distributed_tpu.serving.fleet import (
        RouterConfig,
        ServingFleet,
    )

    devs = jax.devices()
    # 3 replicas: the acceptance plan kills replica 1 mid-trace, and
    # with TWO survivors the router keeps being a router afterwards —
    # a 2-replica fleet degenerates to "route everything to the lone
    # survivor" where every policy is equal
    n_replicas = 3
    meshes = carve_replica_meshes(n_replicas, devs)
    w = int(meshes[0].devices.size)
    cfg, ecfg, trace_kw, s_cap = _serving_continuous_config(
        w, on_tpu, tiny
    )
    from dataclasses import replace as _rep

    if not on_tpu or tiny:
        # small enough for the CI smoke, big enough that the burst's
        # shared prefix (10 pages, ~5 prefill chunks) dominates the
        # routing decision
        trace_kw = dict(
            n_requests=12, mean_interarrival=1.0,
            len_lo=8, len_hi=40,
            # spec fleets need decode room for the drafter to earn
            # accepts; the plain fleet headline keeps short tails
            max_new_lo=8 if spec_k else 3,
            max_new_hi=14 if spec_k else 7,
            vocab=trace_kw["vocab"],
        )
        ecfg = _rep(ecfg, slots=4, token_budget=48, chunk=16, page=8,
                    npages=64)
    ecfg = _rep(ecfg, prefix_cache=True, temperature=0.7, top_k=40,
                seed=11)

    models = []
    for m in meshes:
        model = Transformer(cfg, m, tp_axis="x")
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            model.init(jax.random.PRNGKey(7)), model.shardings(),
        )
        params = model.quantize_moe_weights(params)
        params = model.quantize_dense_weights(params)
        models.append((model, params))

    def fresh_trace():
        out = _fleet_trace(trace_kw, ecfg.page)
        if spec_k:
            # motif prompts: prompt-lookup drafting needs repeats to
            # accept — without them the spec fleet degenerates to a
            # k=0 fleet and the ratio measures only verify overhead
            rng = np.random.default_rng(23)
            for r in out:
                ln = len(r.prompt)
                motif = rng.integers(
                    0, trace_kw["vocab"], (5,)).astype(np.int32)
                r.prompt = np.tile(motif, -(-ln // 5))[:ln]
        return out

    n_total = len(fresh_trace())

    def build_fleet(policy, k=None):
        if k:
            engines = [SpeculativeEngine(model, params, ecfg,
                                         spec_k=k,
                                         drafter=NGramDrafter())
                       for model, params in models]
        else:
            engines = [ServingEngine(model, params, ecfg)
                       for model, params in models]
        return ServingFleet(
            engines, seed=1, router=RouterConfig(policy=policy),
            meshes=meshes,
        )

    wd_trips = []

    def _guarded(run_fn):
        # same contract as the disaggregated bench: under --faults the
        # collective watchdog is armed so a stalled router_dispatch /
        # serving_step trips into the ledger instead of wedging
        if _rt_faults.active_plan() is None:
            return run_fn()
        deadline = float(_os.environ.get("TDTPU_BENCH_WATCHDOG", "10.0"))
        box = {}
        try:
            with _rt_watchdog.collective_watchdog(deadline=deadline):
                box["out"] = run_fn()
        except _rt_watchdog.WatchdogTimeout as e:
            wd_trips.append(str(e).splitlines()[0])
        finally:
            _rt_watchdog.clear_trip()
        return box.get("out")

    # ---- fault-free reference (the token oracle; run twice — the
    # first run pays every jit compile for both replica models)
    plan = _rt_faults.active_plan()
    _rt_faults.set_fault_plan(None)
    try:
        for _warm in (False, True):
            ref_fleet = build_fleet("scored")
            ref_fleet.run(fresh_trace())
    finally:
        _rt_faults.set_fault_plan(plan)
    ref_tokens = ref_fleet.token_streams()
    assert ref_fleet.stats.lost_requests == 0, ref_fleet.stats

    # ---- the routed fleet under the active plan (the headline run;
    # with --spec-k these replicas are SPECULATIVE and the non-spec
    # reference above doubles as the goodput baseline)
    fleet = build_fleet("scored", k=spec_k)
    stats = _guarded(lambda: fleet.run(fresh_trace()))
    assert stats is not None, wd_trips

    # ---- round-robin baseline under the SAME plan
    rr = build_fleet("round_robin", k=spec_k)
    rr_stats = _guarded(lambda: rr.run(fresh_trace()))
    assert rr_stats is not None, wd_trips

    tokens = fleet.token_streams()
    mismatches = sum(
        1 for rid, t in ref_tokens.items() if tokens.get(rid) != t
    )

    def hit_rate(fl):
        total_pages = sum(
            len(rec["req"].prompt) // ecfg.page
            for rec in fl.stats.records.values())
        return fl.prefix_hits / total_pages if total_pages else 0.0

    goodput = fleet.goodput_tok_per_s
    rr_goodput = rr.goodput_tok_per_s
    out = {
        "metric": "serving_fleet",
        "value": round(goodput, 1),
        "unit": "tok/s fleet goodput (modeled wall)",
        "rr_goodput": round(rr_goodput, 1),
        "goodput_vs_round_robin": round(goodput / rr_goodput, 3)
        if rr_goodput else None,
        "ticks": fleet.ticks,
        "rr_ticks": rr.ticks,
        "p99_ttft_ticks": round(stats.p99_ttft_ticks, 2),
        "p99_tpot_ticks": round(stats.p99_tpot_ticks, 2),
        "rr_p99_ttft_ticks": round(rr_stats.p99_ttft_ticks, 2),
        "prefix_hit_rate": round(hit_rate(fleet), 3),
        "rr_prefix_hit_rate": round(hit_rate(rr), 3),
        "completed": stats.completed,
        "lost_requests": stats.lost_requests,
        "rr_lost_requests": rr_stats.lost_requests,
        "token_mismatches_vs_fault_free": mismatches,
        "deaths": stats.deaths,
        "failover_requeued": stats.failover_requeued,
        "failover_re_prefill_tokens": stats.failover_re_prefill_tokens,
        "routed": {str(k): v for k, v in sorted(stats.routed.items())},
        "spills": stats.spills,
        "affinity_hits": stats.affinity_hits,
        "probes": stats.probes,
        "rotation": list(fleet.rotation()),
        "watchdog_trips": wd_trips,
        "health": fleet.health.snapshot(),
        "config": (
            f"replicas={n_replicas}x{w} slots={ecfg.slots} "
            f"budget={ecfg.token_budget} chunk={ecfg.chunk} "
            f"page={ecfg.page} npages={ecfg.npages} "
            f"requests={n_total} temp=0.7 top_k=40 "
            f"prefix_cache=on fleet_seed=1 "
            + (f"spec_k={spec_k} ngram-drafter " if spec_k else "")
            + ("tiny-dryrun" if tiny or not on_tpu else "headline")
        ),
    }
    if spec_k:
        nonspec_goodput = ref_fleet.goodput_tok_per_s
        out.update({
            "spec_k": spec_k,
            # per-replica accepted tokens per verify step — the spec
            # win the router's load term prices replicas by
            "accepted_tokens_per_step": {
                str(r.index): round(
                    r.engine.stats.accepted_tokens_per_step, 3)
                for r in fleet.replicas},
            "spec_rows": {
                str(r.index): r.engine.stats.spec_rows
                for r in fleet.replicas},
            "nonspec_goodput": round(nonspec_goodput, 1),
            "goodput_vs_nonspec": round(goodput / nonspec_goodput, 3)
            if nonspec_goodput else None,
        })
    return out


def _bench_serving_elastic(mesh, n, on_tpu, spec, tiny=False):
    """ELASTIC fleet (ISSUE 13 tentpole acceptance): 2 active replicas
    plus one RESERVE slice carved by ``carve_replica_meshes(...,
    reserve=1)``, a seeded :class:`FleetAutoscaler` that spawns from
    the reserve under sustained priced pressure (the newcomer earns
    admission through the PR-10 probation-probe path), then a planned
    ``drain`` of replica 0 once the newcomer is HEALTHY — its resident
    rows MIGRATE their committed KV pages over the kv_ship wire when
    ``perf_model.migrate_vs_reprefill_ms`` prices the wire under the
    recompute. Composes with the --faults acceptance plan
    ``ReplicaDeath(replica=1, step=N)``: the death, the grow and the
    drain all land in one run, and still lost_requests == 0 with every
    stream byte-identical to the fault-free reference. The whole
    grow/drain/migrate event log is replayed twice under the same
    fleet seed and must come back identical."""
    import os as _os

    import jax

    from triton_distributed_tpu import config as _config
    from triton_distributed_tpu.models import Transformer
    from triton_distributed_tpu.runtime import faults as _rt_faults
    from triton_distributed_tpu.runtime import watchdog as _rt_watchdog
    from triton_distributed_tpu.runtime.health import (
        HealthLedger,
        PeerState,
    )
    from triton_distributed_tpu.runtime.topology import (
        carve_replica_meshes,
    )
    from triton_distributed_tpu.serving import ServingEngine
    from triton_distributed_tpu.serving.fleet import (
        AutoscalerConfig,
        RouterConfig,
        ServingFleet,
    )

    devs = jax.devices()
    n_active = 2
    active_meshes, spare_meshes = carve_replica_meshes(
        n_active, devs, reserve=1)
    w = int(active_meshes[0].devices.size)
    cfg, ecfg, trace_kw, s_cap = _serving_continuous_config(
        w, on_tpu, tiny
    )
    from dataclasses import replace as _rep

    if not on_tpu or tiny:
        trace_kw = dict(
            n_requests=14, mean_interarrival=0.6,
            len_lo=8, len_hi=40, max_new_lo=4, max_new_hi=8,
            vocab=trace_kw["vocab"],
        )
        ecfg = _rep(ecfg, slots=4, token_budget=48, chunk=16, page=8,
                    npages=64)
    ecfg = _rep(ecfg, prefix_cache=True, temperature=0.7, top_k=40,
                seed=11)

    models = []
    for m in list(active_meshes) + list(spare_meshes):
        model = Transformer(cfg, m, tp_axis="x")
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            model.init(jax.random.PRNGKey(7)), model.shardings(),
        )
        params = model.quantize_moe_weights(params)
        params = model.quantize_dense_weights(params)
        models.append((model, params))

    def fresh_trace():
        return _fleet_trace(trace_kw, ecfg.page)

    n_total = len(fresh_trace())
    grown_peer = f"replica:{n_active}"

    def build_fleet(elastic=True):
        engines = [ServingEngine(model, params, ecfg)
                   for model, params in models[:n_active]]
        spare_model, spare_params = models[n_active]
        if not elastic:
            return ServingFleet(
                engines, seed=1, router=RouterConfig(),
                meshes=list(active_meshes))
        return ServingFleet(
            engines, seed=1,
            router=RouterConfig(queue_cap=4),
            # fast probation so the grown replica earns admission
            # within the trace (the PR-10 knobs, not a blind add)
            health=HealthLedger(seed=1, probation_after=1,
                                promote_after=1, probe_interval=2),
            meshes=list(active_meshes),
            reserve=[(lambda: ServingEngine(spare_model, spare_params,
                                            ecfg),
                      spare_meshes[0])],
            autoscaler=AutoscalerConfig(slo_ms=0.0, window=2,
                                        cooldown=50, max_replicas=3),
        )

    def drive(fleet, max_ticks=2000):
        """fleet.run plus the drain trigger: once the grown replica is
        HEALTHY, replica 0 is drained — the planned-retirement half of
        the elastic story, with the autoscaler's grow and the fault
        plan's death composing around it."""
        fleet.submit_trace(fresh_trace())
        prev = _config.fleet_seed()
        _config.set_fleet_seed(fleet.seed)
        drained = False
        try:
            for _ in range(max_ticks):
                if fleet.idle:
                    break
                if (not drained and fleet.stats.grows
                        and fleet.health.state(grown_peer)
                        is PeerState.HEALTHY):
                    fleet.drain(0)
                    drained = True
                fleet.tick()
        finally:
            _config.set_fleet_seed(prev)
        return fleet.stats

    wd_trips = []

    def _guarded(run_fn):
        if _rt_faults.active_plan() is None:
            return run_fn()
        deadline = float(_os.environ.get("TDTPU_BENCH_WATCHDOG", "10.0"))
        box = {}
        try:
            with _rt_watchdog.collective_watchdog(deadline=deadline):
                box["out"] = run_fn()
        except _rt_watchdog.WatchdogTimeout as e:
            wd_trips.append(str(e).splitlines()[0])
        finally:
            _rt_watchdog.clear_trip()
        return box.get("out")

    # ---- fault-free static reference (the token oracle; run twice —
    # the first run pays every jit compile for the replica models)
    plan = _rt_faults.active_plan()
    _rt_faults.set_fault_plan(None)
    try:
        for _warm in (False, True):
            ref_fleet = build_fleet(elastic=False)
            ref_fleet.run(fresh_trace())
    finally:
        _rt_faults.set_fault_plan(plan)
    ref_tokens = ref_fleet.token_streams()
    assert ref_fleet.stats.lost_requests == 0, ref_fleet.stats

    # ---- the elastic run under the active plan (grow + drain +
    # migrate + whatever the plan throws at it)
    fleet = build_fleet()
    stats = _guarded(lambda: drive(fleet))
    assert stats is not None, wd_trips

    # ---- replay determinism: the same fleet seed and trace must
    # produce the byte-identical grow/drain/migration event log
    fleet2 = build_fleet()
    stats2 = _guarded(lambda: drive(fleet2))
    assert stats2 is not None, wd_trips
    events_deterministic = list(stats.events) == list(stats2.events)

    tokens = fleet.token_streams()
    mismatches = sum(
        1 for rid, t in ref_tokens.items() if tokens.get(rid) != t
    )
    goodput = fleet.goodput_tok_per_s
    priced = [(round(wms, 6), round(rms, 6))
              for wms, rms in stats.migration_priced]
    return {
        "metric": "serving_elastic",
        "value": round(goodput, 1),
        "unit": "tok/s fleet goodput (modeled wall)",
        "ticks": fleet.ticks,
        "completed": stats.completed,
        "lost_requests": stats.lost_requests,
        "token_mismatches_vs_fault_free": mismatches,
        "grows": stats.grows,
        "drains": stats.drains,
        "drain_requeued": stats.drain_requeued,
        "migrations": stats.migrations,
        "migrations_cheaper_than_reprefill": stats.migrations_cheaper,
        "migrated_pages": stats.migrated_pages,
        "migration_wire_bytes": stats.migration_wire_bytes,
        "migration_priced_ms": priced[:8],
        "migration_refusals": stats.migration_refusals,
        "migration_failures": stats.migration_failures,
        "deaths": stats.deaths,
        "failover_requeued": stats.failover_requeued,
        "admission_rejections": stats.admission_rejections,
        "probes": stats.probes,
        "routed": {str(k): v for k, v in sorted(stats.routed.items())},
        "rotation": list(fleet.rotation()),
        "event_log": [list(e) for e in stats.events[:24]],
        "event_log_deterministic": events_deterministic,
        "watchdog_trips": wd_trips,
        "health": fleet.health.snapshot(),
        "config": (
            f"active={n_active}x{w} reserve=1x{w} slots={ecfg.slots} "
            f"budget={ecfg.token_budget} chunk={ecfg.chunk} "
            f"page={ecfg.page} npages={ecfg.npages} "
            f"requests={n_total} queue_cap=4 slo_ms=0.0 window=2 "
            f"temp=0.7 top_k=40 prefix_cache=on fleet_seed=1 "
            + ("tiny-dryrun" if tiny or not on_tpu else "headline")
        ),
    }


def _bench_serving_multitenant(mesh, n, on_tpu, spec, tiny=False):
    """MULTI-TENANT fleet (ISSUE 16 tentpole acceptance): 3 replicas,
    an interactive trickle under a 4x BATCH FLOOD plus a background
    drip, per-tenant :class:`TenantConfig` (tight interactive SLO →
    the router's deadline-slack term is live), the seeded
    :class:`BrownoutController` armed and ``queue_cap`` admission
    counting tier-visible depth. Four runs:

    1. fault-free SINGLE-TENANT oracle over the identical trace — the
       token-exactness reference (sampling is request-keyed, so
       preemption/shed/retry may reorder WHEN a token appears, never
       WHICH token);
    2. flood-free interactive-only run under the SAME fault plan —
       the p99 baseline the brownout + preemption must protect;
    3. the headline multi-tenant run under the plan (the acceptance
       line adds ``--faults "seed=1; ReplicaDeath(replica=1,
       step=8)"``): interactive p99 no worse than (2), every shed on
       background/batch with background shed strictly first,
       preemptions > 0, zero pool-page leaks on live replicas, zero
       lost requests;
    4. a same-seed replay of (3) — the event log (placement,
       preemption, shed, brownout transition, retune) must come back
       byte-identical (the PR-13 replay contract extended to the
       multi-tenant events)."""
    import os as _os

    import jax

    from triton_distributed_tpu import config as _config
    from triton_distributed_tpu.models import Transformer
    from triton_distributed_tpu.runtime import faults as _rt_faults
    from triton_distributed_tpu.runtime import watchdog as _rt_watchdog
    from triton_distributed_tpu.runtime.topology import (
        carve_replica_meshes,
    )
    from triton_distributed_tpu.serving import (
        BrownoutConfig,
        Request,
        ServingEngine,
        TenantConfig,
    )
    from triton_distributed_tpu.serving.fleet import (
        RouterConfig,
        ServingFleet,
    )

    devs = jax.devices()
    n_replicas = 3
    meshes = carve_replica_meshes(n_replicas, devs)
    w = int(meshes[0].devices.size)
    cfg, ecfg, _trace_kw, _s_cap = _serving_continuous_config(
        w, on_tpu, tiny
    )
    from dataclasses import replace as _rep

    if not on_tpu or tiny:
        ecfg = _rep(ecfg, slots=4, token_budget=48, chunk=16, page=8,
                    npages=64)
    ecfg = _rep(ecfg, prefix_cache=True, temperature=0.7, top_k=40,
                seed=11)
    # SLOs scale with the perf model's step cost: interpreter-tiny
    # models step in ~microseconds of MODEL time, headline in ms
    slo_iact = 0.05 if (tiny or not on_tpu) else 50.0
    slo_brownout = 0.004 if (tiny or not on_tpu) else 4.0
    tenants = {
        "iact": TenantConfig(priority="interactive", slo_ms=slo_iact),
        "bat": TenantConfig(priority="batch"),
        "bg": TenantConfig(priority="background"),
    }

    models = []
    for m in meshes:
        model = Transformer(cfg, m, tp_axis="x")
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            model.init(jax.random.PRNGKey(7)), model.shardings(),
        )
        params = model.quantize_moe_weights(params)
        params = model.quantize_dense_weights(params)
        models.append((model, params))

    import numpy as _np

    n_iact, n_bat, n_bg = 6, 24, 6       # the 4x batch flood

    def fresh_trace(only_interactive=False):
        out, rid = [], 0

        def mk(rid, arrival, tenant, plen):
            rng = _np.random.default_rng(5000 + rid)
            prompt = rng.integers(
                0, cfg.vocab, (plen,)).astype(_np.int32)
            r = Request(rid=rid, prompt=prompt, max_new=5,
                        arrival=arrival)
            r.tenant = tenant
            return r

        for i in range(n_iact):
            out.append(mk(rid, i * 3.0, "iact", 20)); rid += 1
        for i in range(n_bat):
            r = mk(rid, 1.0 + i * 0.2, "bat", 24); rid += 1
            if not only_interactive:
                out.append(r)
        for i in range(n_bg):
            r = mk(rid, i * 1.5, "bg", 16); rid += 1
            if not only_interactive:
                out.append(r)
        return out

    def build_fleet(multitenant=True):
        engines = [ServingEngine(model, params, ecfg)
                   for model, params in models]
        if not multitenant:
            return ServingFleet(engines, seed=1,
                                router=RouterConfig(),
                                meshes=list(meshes))
        return ServingFleet(
            engines, seed=1,
            router=RouterConfig(queue_cap=3),
            meshes=list(meshes),
            tenants=tenants,
            brownout=BrownoutConfig(slo_ms=slo_brownout, window=2,
                                    cooldown=3),
        )

    def drive(fleet, trace, max_ticks=2000):
        fleet.submit_trace(trace)
        prev = _config.fleet_seed()
        _config.set_fleet_seed(fleet.seed)
        try:
            for _ in range(max_ticks):
                if fleet.idle:
                    break
                fleet.tick()
        finally:
            _config.set_fleet_seed(prev)
        return fleet.stats

    wd_trips = []

    def _guarded(run_fn):
        if _rt_faults.active_plan() is None:
            return run_fn()
        deadline = float(_os.environ.get("TDTPU_BENCH_WATCHDOG",
                                         "10.0"))
        box = {}
        try:
            with _rt_watchdog.collective_watchdog(deadline=deadline):
                box["out"] = run_fn()
        except _rt_watchdog.WatchdogTimeout as e:
            wd_trips.append(str(e).splitlines()[0])
        finally:
            _rt_watchdog.clear_trip()
        return box.get("out")

    # ---- (1) fault-free single-tenant oracle (run twice — the first
    # pays every jit compile for the replica models)
    plan = _rt_faults.active_plan()
    _rt_faults.set_fault_plan(None)
    try:
        for _warm in (False, True):
            oracle = build_fleet(multitenant=False)
            drive(oracle, fresh_trace())
    finally:
        _rt_faults.set_fault_plan(plan)
    ref_tokens = oracle.token_streams()
    assert oracle.stats.lost_requests == 0, oracle.stats

    # ---- (2) flood-free interactive-only baseline, SAME fault plan:
    # the p99 the flood must not degrade
    base = build_fleet()
    base_stats = _guarded(
        lambda: drive(base, fresh_trace(only_interactive=True)))
    assert base_stats is not None, wd_trips
    p99_free = base.per_tenant()["iact"]["p99_ttft_ticks"]

    # ---- (3) the headline multi-tenant flood under the plan
    fleet = build_fleet()
    stats = _guarded(lambda: drive(fleet, fresh_trace()))
    assert stats is not None, wd_trips

    # ---- (4) same-seed replay: byte-identical event log
    fleet2 = build_fleet()
    stats2 = _guarded(lambda: drive(fleet2, fresh_trace()))
    assert stats2 is not None, wd_trips
    events_deterministic = list(stats.events) == list(stats2.events)

    per_tenant = fleet.per_tenant()
    p99_flood = per_tenant["iact"]["p99_ttft_ticks"]
    tokens = fleet.token_streams()
    mismatches = sum(
        1 for rid, t in ref_tokens.items() if tokens.get(rid) != t
    )
    shed_tiers = [e[3].split("tier=")[1].split()[0]
                  for e in stats.events if e[0] == "shed"]
    bg_shed_first = ("batch" not in shed_tiers
                     or "background" in
                     shed_tiers[:shed_tiers.index("batch")])
    leaked = sum(role.pool.held_pages
                 for r in fleet._alive() for role in r._roles)

    # the acceptance pins — loud here, and ci/fast.sh re-derives them
    # from the JSON so the smoke exits nonzero on any regression
    assert stats.lost_requests == 0, stats
    assert mismatches == 0, (
        f"{mismatches} admitted streams diverged from the fault-free "
        "single-tenant oracle")
    assert set(shed_tiers) <= {"background", "batch"}, shed_tiers
    assert bg_shed_first, shed_tiers
    assert fleet.preemptions > 0, "flood never forced a preemption"
    assert leaked == 0, f"{leaked} pool pages leaked on live replicas"
    assert p99_flood <= p99_free, (
        f"interactive p99 degraded under flood: "
        f"{p99_flood} > {p99_free}")

    return {
        "metric": "serving_multitenant",
        "value": round(fleet.goodput_tok_per_s, 1),
        "unit": "tok/s fleet goodput (modeled wall)",
        "ticks": fleet.ticks,
        "completed": stats.completed,
        "lost_requests": stats.lost_requests,
        "token_mismatches_vs_single_tenant_oracle": mismatches,
        "interactive_p99_ttft_ticks_flood": p99_flood,
        "interactive_p99_ttft_ticks_flood_free": p99_free,
        "preemptions": fleet.preemptions,
        "tenant_preemptions": fleet.tenant_preemptions(),
        "sheds_by_tier": dict(stats.sheds),
        "background_shed_before_batch": bg_shed_first,
        "brownout_transitions": [
            e[3] for e in stats.events if e[0] == "brownout"],
        "pool_pages_leaked": leaked,
        "deaths": stats.deaths,
        "failover_requeued": stats.failover_requeued,
        "admission_rejections": stats.admission_rejections,
        "per_tenant": per_tenant,
        "routed": {str(k): v for k, v in sorted(stats.routed.items())},
        "event_log": [list(e) for e in stats.events[:24]],
        "event_log_deterministic": events_deterministic,
        "watchdog_trips": wd_trips,
        "config": (
            f"replicas={n_replicas}x{w} slots={ecfg.slots} "
            f"budget={ecfg.token_budget} chunk={ecfg.chunk} "
            f"page={ecfg.page} npages={ecfg.npages} "
            f"trace={n_iact}iact+{n_bat}bat+{n_bg}bg queue_cap=3 "
            f"slo_iact={slo_iact} brownout_slo={slo_brownout} "
            f"window=2 cooldown=3 temp=0.7 top_k=40 fleet_seed=1 "
            + ("tiny-dryrun" if tiny or not on_tpu else "headline")
        ),
    }


def _bench_serving_moe_decode(mesh, n, on_tpu, spec):
    """One FULL EP-MoE serving decode step on the chip (VERDICT r3 #3:
    the workload every MoE transport improvement serves — the
    reference's test_ep_moe_inference.py scenario). DeepSeek-ish
    per-chip scale: B=128 last tokens, hidden 7168, topk 8 over the 8
    locally-owned experts, GQA flash-decode attention over a 2048-token
    cache, greedy argmax feeding the next step. The EP-MoE block rides
    the fused chunked transport BARRIER-FREE (LL state threaded through
    the loop carry). n=1: dispatch is self-transport (no wire) — what
    is measured is the full per-chip serving step.

    ``moe_block_us`` re-times the MoE block alone (routing + staging +
    fused a2a + grouped expert MLP + combine) at the same shapes;
    ``attn_rest_us`` is the difference (attention + projections + LM
    head)."""
    from triton_distributed_tpu.models import Transformer, TransformerConfig
    from triton_distributed_tpu.ops import create_ep_moe_state, ep_moe

    if on_tpu:
        b, s_cap = 128, 2048
        cfg = TransformerConfig(
            vocab=4096, n_layers=1, hidden=7168, ffn=2048, n_heads=56,
            n_kv_heads=8, head_dim=128, moe="ep", moe_layers=(0,),
            num_experts=8, topk=8, param_dtype=jnp.bfloat16,
            # serving weight path: int8 expert matrices (per-out-channel
            # scales, grouped-GEMM epilogue dequant) — the decode GEMMs
            # are weight-HBM-bound, so this is the production default
            # (presets.deepseek_moe_16b); measured 1.88 -> 1.55 ms on
            # the MoE block (docs/PERF.md)
            moe_weight_quant="int8",
            # W8A8 expert GEMMs: s8×s8 MXU at 2× the bf16 rate
            moe_act_quant="int8",
            # int8 KV cache: halves the attention DMA bytes + the cache
            # HBM (production default, presets.deepseek_moe_16b)
            kv_quant="int8",
            # int8 dense projections (wqkv/wo/lm_head): same
            # weight-HBM-bound argument as the expert matrices; W8A8
            # on the projections (lm_head stays W8A16)
            dense_weight_quant="int8",
            dense_act_quant="int8",
        )
    else:
        b, s_cap = 8, 256
        cfg = TransformerConfig(
            vocab=512, n_layers=1, hidden=256, ffn=128, n_heads=8,
            n_kv_heads=4, head_dim=32, moe="ep", moe_layers=(0,),
            num_experts=8, topk=2, param_dtype=jnp.bfloat16,
        )
    model = Transformer(cfg, mesh, tp_axis="x")
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, s),
        model.init(jax.random.PRNGKey(7)), model.shardings(),
    )
    params = model.quantize_moe_weights(params)
    params = model.quantize_dense_weights(params)
    caches = model.init_cache(b, s_cap)
    # MIXED conversation lengths (a serving batch, not a lockstep one):
    # uniform [S/8, 3S/4] so the longest row + the timing loop's appends
    # stay inside capacity. The decode attention kernel walks
    # ceil(len/block_k) blocks PER ROW (dynamic trip counts), so KV
    # reads track the true lengths — a capacity-walk kernel would read
    # S for every row.
    lens = jnp.asarray(
        np.random.default_rng(11).integers(s_cap // 8, 3 * s_cap // 4, (b,)),
        jnp.int32,
    )
    toks0 = jnp.zeros((b,), jnp.int32)
    # LL state rides UNCONDITIONALLY (r4 weak #3 closed): bench_loop's
    # donate_idx threads the workspaces across runner invocations, so
    # the persistent-buffer contract holds at any n — the bench times
    # the same barrier-free path production decode runs
    # (_decode_jit_state's donate protocol)
    moe_state = model.init_decode_state(b)

    # params ride the CARRY, not the closure: closed-over device arrays
    # are embedded in the lowered module as literal constants, and ~1 GB
    # of weights blows the axon relay's compile-request size limit
    # (observed HTTP 413); as loop-invariant carry entries they lower as
    # parameters and XLA hoists them.
    def step(state, s):
        prm, caches, lens, toks, mst = state
        if mst is None:
            logits, caches, lens = model.decode_step(prm, caches, lens, toks)
        else:
            logits, caches, lens, mst = model.decode_step(
                prm, caches, lens, toks, mst
            )
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        s = s + jnp.sum(toks.astype(jnp.float32))
        return (prm, caches, lens, toks, mst), s

    lo, hi = (8, 64) if on_tpu else (1, 3)
    t_step = bench_loop(
        step, (params, caches, lens, toks0, moe_state), lo=lo, hi=hi,
        donate_idx=4 if moe_state is not None else None,
    )
    _SHARED["serving_step_1l"] = t_step

    # MoE block alone at the same shapes (own LL state)
    blk = params["blocks"][0]
    ctx = model._moe_ep_ctx(-(-b // model.token_shards), inference=True)
    mst2 = create_ep_moe_state(ctx) if ctx.transport == "fused" else None
    x0 = jax.random.normal(jax.random.PRNGKey(8), (b, cfg.hidden), cfg.dtype)
    # quantized expert dicts pass through; plain arrays cast
    w_up, w_down = (
        w if isinstance(w, dict) else w.astype(cfg.dtype)
        for w in (blk["moe_up"], blk["moe_down"])
    )

    def moe_step(state, s):
        x, router, up, down, mst = state
        logits_r = x.astype(jnp.float32) @ router
        if mst is None:
            y = ep_moe(x, logits_r, up, down, ctx)
        else:
            y, mst = ep_moe(x, logits_r, up, down, ctx, state=mst)
        s = s + jnp.sum(y.astype(jnp.float32))
        return (perturb(x, s), router, up, down, mst), s

    lo2, hi2 = (16, 128) if on_tpu else (1, 3)
    t_moe = bench_loop(
        moe_step, (x0, blk["router"], w_up, w_down, mst2), lo=lo2, hi=hi2,
        donate_idx=4 if mst2 is not None else None,
    )

    return {
        "metric": "serving_moe_decode_step",
        "value": round(t_step * 1e6, 1),
        "unit": "us",
        "moe_block_us": round(t_moe * 1e6, 1),
        "attn_rest_us": round((t_step - t_moe) * 1e6, 1),
        "tok_per_s": round(b / t_step, 0),
        "transport": ctx.transport + ("+ll" if mst2 is not None else ""),
        "config": (
            f"n={n} B={b} hidden={cfg.hidden} topk={cfg.topk} "
            f"experts/chip={cfg.num_experts} ffn={cfg.ffn} S={s_cap} "
            f"lens~U[S/8,3S/4] wq={cfg.moe_weight_quant} "
            f"aq={cfg.moe_act_quant} kvq={cfg.kv_quant} "
            "1-layer EP-MoE decode "
            + ("self-transport(no wire)" if n == 1 else "multi-chip")
        ),
    }


def _bench_flash_decode(mesh, n, on_tpu, spec):
    from triton_distributed_tpu.kernels.flash_decode import gqa_fwd_batch_decode

    b, hq, hkv, d, s_len = (4, 32, 8, 128, 8192) if on_tpu else (2, 8, 2, 128, 1024)
    q = jax.random.normal(jax.random.PRNGKey(0), (b, hq, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, hkv, s_len, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, hkv, s_len, d), jnp.bfloat16)
    lens = jnp.full((b,), s_len, jnp.int32)

    def step(state, s):
        q, k, v = state
        out, _lse = gqa_fwd_batch_decode(
            q, k, v, lens, kv_layout="bhsd", block_k=4096 if on_tpu else 256
        )
        s = s + jnp.sum(out.astype(jnp.float32))
        return (perturb(q, s), k, v), s

    lo, hi = (16, 300) if on_tpu else (1, 3)
    t = bench_loop(step, (q, k, v), lo=lo, hi=hi)
    kv_bytes = 2 * b * s_len * hkv * d * 2
    gbps = kv_bytes / t / 1e9

    # int8 KV twin at the same shape (half the cache bytes; scales fold
    # in-softmax — kernels/flash_decode.py q8 mode)
    from triton_distributed_tpu.kernels.flash_decode import (
        gqa_fwd_batch_decode_q8,
        quantize_kv,
    )

    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)

    def step_q8(state, s):
        q, kq, ks, vq, vs = state
        out, _ = gqa_fwd_batch_decode_q8(
            q, kq, ks, vq, vs, lens, block_k=4096 if on_tpu else 256
        )
        s = s + jnp.sum(out.astype(jnp.float32))
        return (perturb(q, s), kq, ks, vq, vs), s

    t_q8 = bench_loop(step_q8, (q, kq, ks, vq, vs), lo=lo, hi=hi)
    return {
        "metric": "flash_decode_step",
        "value": round(t * 1e6, 1),
        "unit": "us",
        "kv_gbps": round(gbps, 1),
        "hbm_pct": round(100 * gbps / spec.hbm_gbps, 1),
        "int8_kv_us": round(t_q8 * 1e6, 1),
        "config": f"B={b} Hq={hq} Hkv={hkv} D={d} S={s_len} bf16 (+int8-KV twin)",
    }


def _bench_train_step(mesh, n, on_tpu, spec, tiny=False):
    """TRAINING (ISSUE 14 acceptance): the dp2×tp2×cp2 train step on
    the int8 EF gradient ring — CP ring attention over "cp", Megatron
    MLP over "tp", the wire-quantized dp all-reduce — vs the
    single-device dense reference and the exact psum twin. One row
    reports: the ring's wire bytes vs the bf16 baseline (~2× down),
    the final-loss delta against its pinned tolerance, and the EF
    link-aggregate error strictly below the no-EF control."""
    import numpy as _np

    from jax.sharding import Mesh as _Mesh, PartitionSpec as _P

    from triton_distributed_tpu import train
    from triton_distributed_tpu.train import grad_wire, step as _stepmod

    steps = 5 if tiny else 20
    cfg = train.TrainConfig()
    trainer = train.Trainer(cfg)
    batches = [trainer.make_batch(k) for k in range(steps)]
    t0 = time.perf_counter()
    dist = [trainer.step(tok, tgt)["loss"] for tok, tgt in batches]
    dt = time.perf_counter() - t0

    params = _stepmod.init_params(cfg)
    opt = _stepmod.init_opt_state(params)
    ref = []
    for tok, tgt in batches:
        params, opt, loss = train.train_step_reference(
            params, opt, tok, tgt, cfg)
        ref.append(float(loss))
    loss_tol = 0.05
    delta = abs(dist[-1] - ref[-1])

    # EF vs the no-EF control on the metric EF bounds: the
    # link-aggregate (stripe-summed) reduce-scatter error (see
    # train/grad_wire.py — per-element error is the SR noise floor
    # either way)
    nring, srows, cols = 4, 8, 128
    ring_mesh = _Mesh(_np.asarray(jax.devices()[:nring]), ("x",))

    def agg_err(ef):
        errs = []
        for seed in (0, 1, 2):
            rng = _np.random.RandomState(seed)
            x = rng.standard_normal(
                (nring * nring * srows, cols)).astype(_np.float32)
            exact = x.reshape(nring, nring * srows, cols).sum(axis=0)
            fn = jax.shard_map(
                lambda v: grad_wire.ef_ring_reduce_scatter(
                    v, "x", n=nring, wire="int8", seed=seed + 7, ef=ef),
                mesh=ring_mesh, in_specs=_P("x", None),
                out_specs=_P("x", None), check_vma=False,
            )
            err = _np.asarray(jax.jit(fn)(x)) - exact
            errs.append(
                float(_np.abs(
                    err.reshape(nring, srows, cols).sum(axis=0)).mean()))
        return float(_np.mean(errs))

    ef_err, ctl_err = agg_err(True), agg_err(False)
    wires = trainer.wire_report()
    ok = (delta < loss_tol and ef_err < ctl_err
          and wires["ratio"] > 1.9)
    return {
        "metric": "train_step",
        "value": round(dt / steps * 1e3, 2),
        "unit": "ms/step",
        "config": (f"dp{cfg.dp}×tp{cfg.tp}×cp{cfg.cp} "
                   f"attn={cfg.attn} wire={trainer.wire} "
                   f"microbatches={cfg.microbatches}"),
        "steps": steps,
        "final_loss": round(dist[-1], 6),
        "final_loss_ref": round(ref[-1], 6),
        "final_loss_delta": round(delta, 6),
        "loss_tol": loss_tol,
        "grad_ring_bytes": wires["wire_bytes"],
        "grad_ring_bf16_bytes": wires["bf16_bytes"],
        "grad_ring_byte_ratio": round(wires["ratio"], 3),
        "ef_agg_err": round(ef_err, 6),
        "no_ef_agg_err": round(ctl_err, 6),
        "ef_below_control": ef_err < ctl_err,
        "ok": ok,
    }


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never leave the driver without a JSON line
        print(
            json.dumps(
                {
                    "metric": "ag_gemm_tflops_per_chip",
                    "value": 0.0,
                    "unit": "TFLOP/s",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            )
        )
        sys.exit(0)
