"""Driver benchmark: fused AG-GEMM throughput on the north-star TP shape.

Measures the flagship overlap op (BASELINE.md north-star: fused AG-GEMM on
Llama-7B TP shapes, reference tutorial 07 / test_ag_gemm.py) on whatever
devices are present — the one real TPU chip under the driver, or the
virtual CPU mesh during development.

Prints ONE JSON line:
  {"metric": "ag_gemm_tflops_per_chip", "value": N, "unit": "TFLOP/s",
   "vs_baseline": speedup_vs_unoverlapped}

``vs_baseline`` is the speedup of our best engine over the unoverlapped
baseline (all_gather → dot, ≡ the reference's torch_ag_gemm cuBLAS+NCCL
baseline, test_ag_gemm.py) on the same hardware — the quantity the
reference's perf charts report (README.md:181-182).
"""

from __future__ import annotations

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _sync(out):
    # block_until_ready is a no-op over the axon relay; a host read of one
    # element is the reliable device fence.
    leaf = jax.tree.leaves(out)[0]
    np.asarray(jax.device_get(leaf.ravel()[0]))


def _bench(fn, *args, iters=32, warmup=3):
    import time

    out = None
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters


def main() -> None:
    from triton_distributed_tpu.kernels.ag_gemm import (
        AGGemmMethod,
        _build_fused,
        _build_xla_naive,
        _build_xla_ring,
        _fused_fits,
    )

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.asarray(devs), ("x",))

    # Llama-7B TP up-projection shape (reference test_ag_gemm defaults,
    # 8192 x 8192 x 28672), scaled down off-TPU to keep CI fast.
    on_tpu = jax.default_backend() == "tpu"
    m, k, nn = (8192, 8192, 28672) if on_tpu else (512, 512, 1024)
    dtype = jnp.bfloat16

    key = jax.random.PRNGKey(0)
    a = jax.device_put(
        jax.random.normal(key, (m, k), dtype), NamedSharding(mesh, P("x", None))
    )
    b = jax.device_put(
        jax.random.normal(key, (k, nn), dtype), NamedSharding(mesh, P(None, "x"))
    )

    if n == 1:
        # Single chip: no gather leg — both engines are the same MXU matmul.
        fn = jax.jit(lambda a, b: jnp.dot(a, b, preferred_element_type=jnp.float32).astype(dtype))
        t_best = t_naive = _bench(fn, a, b)
    else:
        t_naive = _bench(_build_xla_naive(mesh, "x", (), dtype), a, b)
        candidates = [_build_xla_ring(mesh, "x", (), dtype)]
        if _fused_fits(n, m, k, nn // n, a.dtype.itemsize):
            candidates.append(
                _build_fused(mesh, "x", (), a.shape, b.shape, a.dtype, dtype, 5, False)
            )
        t_best = min(min(_bench(c, a, b) for c in candidates), t_naive)

    tflops_per_chip = 2.0 * m * k * nn / t_best / n / 1e12
    # headline FIRST: a hang in a secondary bench must not starve the
    # driver of the already-computed metric
    print(
        json.dumps(
            {
                "metric": "ag_gemm_tflops_per_chip",
                "value": round(tflops_per_chip, 2),
                "unit": "TFLOP/s",
                "vs_baseline": round(t_naive / t_best, 4),
            }
        ),
        flush=True,
    )

    # Secondary metrics (stderr — the driver consumes exactly one stdout
    # line): MoE a2a dispatch latency on the reference's headline config
    # (128 tok/rank, topk 8, hidden 7168 — README.md:87, 137 µs on 32
    # GPUs) and distributed flash-decode step time.
    for fn in (_bench_moe_a2a, _bench_flash_decode):
        try:
            print(json.dumps(fn(mesh, n, on_tpu)), file=sys.stderr)
        except Exception as e:
            print(json.dumps({"metric": fn.__name__, "error": str(e)[:200]}),
                  file=sys.stderr)


def _bench_moe_a2a(mesh, n, on_tpu):
    from triton_distributed_tpu.kernels import moe_all_to_all as ma

    epr, hidden, tok, topk = (8, 7168, 128, 8) if on_tpu else (2, 256, 16, 2)
    max_m = tok * topk
    ctx = ma.create_all_to_all_context(
        mesh, "x", max_m=max_m, hidden=hidden,
        experts_per_rank=epr, dtype=jnp.bfloat16,
    )
    rows = NamedSharding(mesh, P("x"))
    send = jax.device_put(
        jnp.zeros((n * n * ctx.slot_rows, ctx.ints_per_row), jnp.int32), rows
    )
    t = _bench(lambda s: ma.fast_all_to_all(ctx, s), send, iters=64)
    return {
        "metric": "moe_a2a_dispatch_latency", "value": round(t * 1e6, 1),
        "unit": "us",
        "config": f"n={n} tok/rank={tok} topk={topk} hidden={hidden} bf16",
    }


def _bench_flash_decode(mesh, n, on_tpu):
    from triton_distributed_tpu.kernels.flash_decode import gqa_fwd_batch_decode

    b, hq, hkv, d, s = (4, 32, 8, 128, 8192) if on_tpu else (2, 8, 2, 128, 1024)
    q = jax.random.normal(jax.random.PRNGKey(0), (b, hq, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d), jnp.bfloat16)
    lens = jnp.full((b,), s, jnp.int32)
    t = _bench(
        lambda *a: gqa_fwd_batch_decode(*a, block_k=512 if on_tpu else 256),
        q, k, v, lens, iters=16,
    )
    kv_bytes = 2 * b * s * hkv * d * 2
    return {
        "metric": "flash_decode_step", "value": round(t * 1e6, 1),
        "unit": "us", "kv_gbps": round(kv_bytes / t / 1e9, 1),
        "config": f"B={b} Hq={hq} Hkv={hkv} D={d} S={s} bf16",
    }


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never leave the driver without a JSON line
        print(
            json.dumps(
                {
                    "metric": "ag_gemm_tflops_per_chip",
                    "value": 0.0,
                    "unit": "TFLOP/s",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            )
        )
        sys.exit(0)
