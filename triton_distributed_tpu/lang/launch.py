"""Launch helpers: wrap Pallas SHMEM kernels for execution on a mesh.

The reference launches distributed Triton kernels on torch streams after
NVSHMEM module init (patches/triton/python/triton/compiler/compiler.py:
414-425). The TPU equivalent is: ``pl.pallas_call`` (with a collective_id
and the platform-appropriate interpret mode) wrapped in ``jax.shard_map``
over the target mesh. These helpers cut that boilerplate.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.config import interpret_params


@dataclass
class LaunchSpec:
    """Static facts about one ``shmem_call`` construction — the
    shmemlint capture (:mod:`triton_distributed_tpu.analysis`): the
    kernel callable plus everything the abstract evaluator needs to
    materialize refs (out shapes, memory spaces, scratch incl.
    semaphores) and the checker passes need for hygiene/VMEM rules
    (collective_id, vmem_limit_bytes). Input SHAPES are a call-time
    property pallas never sees at build time; the kernel registry
    supplies them alongside the captured spec."""

    name: str
    kernel: object
    out_shape: object
    in_specs: object
    out_specs: object
    scratch_shapes: tuple
    collective_id: object
    vmem_limit_bytes: int | None
    grid: object = None
    # full grid_spec object (PrefetchScalarGridSpec kernels): the
    # Mosaic pre-flight re-invokes pallas_call with it so scalar-
    # prefetch families trace exactly as production builds them
    grid_spec: object = None


#: most recent LaunchSpec per kernel name. Builders are lru-cached, so
#: the analyzer busts their caches (a fresh token in an unused key arg)
#: to guarantee the spec it reads back was built from ITS shapes.
_LAUNCH_SPECS: dict = {}


def captured_launch(name: str) -> LaunchSpec | None:
    return _LAUNCH_SPECS.get(name)


def shmem_call(
    kernel,
    *,
    out_shape,
    in_specs=None,
    out_specs=None,
    grid=None,
    grid_spec=None,
    scratch_shapes=(),
    collective_id=0,
    cost_estimate=None,
    vmem_limit_bytes=None,
    interpret=None,
    input_output_aliases=None,
    name=None,
    dimension_semantics=None,
):
    """``pl.pallas_call`` preconfigured for SHMEM-style distributed kernels:
    side-effecting, collective, interpreted off-TPU.

    ``dimension_semantics``: per-grid-dim tuple of "parallel"/"arbitrary".
    Kernels whose correctness depends on SEQUENTIAL grid execution (e.g.
    cross-step scratch carries, DMA slot rotation) must pin every dim
    "arbitrary" — a future parallel/Megacore default would silently
    corrupt them.
    """
    # collective_id=None → a purely local kernel (no barrier semaphore);
    # Mosaic requires it unset in that case.
    compiler_params = pltpu.CompilerParams(
        has_side_effects=True,
        collective_id=collective_id,
        vmem_limit_bytes=vmem_limit_bytes,
        dimension_semantics=dimension_semantics,
    )
    kwargs = {}
    if grid_spec is not None:
        kwargs["grid_spec"] = grid_spec
    else:
        if in_specs is not None:
            kwargs["in_specs"] = in_specs
        if out_specs is None and grid is None:
            # default: whole-array blocks resident in VMEM (never ANY — the
            # interpreter can't service remote DMA waits on ANY-space bufs)
            out_specs = jax.tree.map(
                lambda _: pl.BlockSpec(memory_space=pltpu.VMEM), out_shape
            )
        if out_specs is not None:
            kwargs["out_specs"] = out_specs
        if grid is not None:
            kwargs["grid"] = grid
    if cost_estimate is not None:
        kwargs["cost_estimate"] = cost_estimate
    if input_output_aliases is not None:
        kwargs["input_output_aliases"] = input_output_aliases
    if name is not None:
        kwargs["name"] = name
        # grid_spec kernels carry their scratch inside the spec object —
        # surface it so the analyzer materializes the same refs
        cap_scratch = tuple(scratch_shapes) or tuple(
            getattr(grid_spec, "scratch_shapes", ()) or ()
        )
        _LAUNCH_SPECS[name] = LaunchSpec(
            name=name,
            kernel=kernel,
            out_shape=out_shape,
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=cap_scratch,
            collective_id=collective_id,
            vmem_limit_bytes=vmem_limit_bytes,
            grid=grid,
            grid_spec=grid_spec,
        )
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        scratch_shapes=list(scratch_shapes),
        compiler_params=compiler_params,
        interpret=interpret_params() if interpret is None else interpret,
        **kwargs,
    )


def vmem_specs(n: int):
    """n whole-array VMEM BlockSpecs (the common case for SHMEM kernels)."""
    return [pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(n)]


def maybe_instrument(call, *, axis, site, collective_id, n, step=None):
    """Wrap a per-device collective callable (used inside shard_map) with
    the robustness host hooks — the ``shmem_call`` side of the collective
    watchdog (:mod:`triton_distributed_tpu.runtime.watchdog`):

    * an ENTRY heartbeat callback per rank (registers the launch with the
      armed watchdog and holds the fault plan's single-peer stall gates),
      data-tied to the kernel's operands via ``optimization_barrier`` so
      XLA cannot start the collective before the heartbeat fires;
    * an EXIT heartbeat data-tied to the kernel's outputs, so the
      watchdog can tell *which ranks* are still inside a wedged launch.

    ``axis=None`` selects HOST mode for plain-Python call sites with no
    mapped axis (the serving step jit, the kv_ship transports): the
    heartbeats run synchronously around ``call`` on the calling thread as
    rank 0, with ``step`` forwarded so step-bound (transient) stalls can
    match. Host mode re-evaluates arming per call, so it never caches a
    wrapped/unwrapped decision.

    Returns ``call`` untouched when neither a watchdog is armed nor the
    active fault plan stalls this site — the wrapped/unwrapped decision
    is part of the trace-cache key (``config.interp_key`` folds in
    ``faults.trace_key``), so builders cache correctly across arming.
    """
    from triton_distributed_tpu.runtime import faults, watchdog

    plan = faults.active_plan()
    stalls = plan is not None and plan.stalled_ranks(site, step)
    if not (watchdog.armed() or stalls):
        return call

    if axis is None:
        def host_body(*args, **kwargs):
            wd = watchdog.current()
            if wd is not None:
                wd.on_enter(site, collective_id, n, 0, step=step)
            else:
                faults.stall_wait(site, 0, step)
            try:
                return call(*args, **kwargs)
            finally:
                if wd is not None:
                    wd.on_exit(site, collective_id, n, 0)

        return host_body

    import jax.numpy as jnp
    from jax.experimental import io_callback

    enter_cb = functools.partial(watchdog._hb_enter, site, collective_id, n)
    exit_cb = functools.partial(watchdog._hb_exit, site, collective_id, n)
    hb = jax.ShapeDtypeStruct((), jnp.int32)

    def body(*args):
        me = jax.lax.axis_index(axis)
        gate = io_callback(enter_cb, hb, me)
        args = tuple(
            jax.lax.optimization_barrier((a, gate))[0] for a in args
        )
        out = call(*args)
        leaves = jax.tree.leaves(out)
        dep = leaves[0].reshape(-1)[:1] if leaves else jnp.zeros((1,))
        io_callback(exit_cb, hb, me, dep)
        return out

    return body


def on_mesh(mesh, in_specs, out_specs, axis_names=None, jit=True):
    """Decorator: run ``fn`` SPMD on ``mesh`` via shard_map (+jit)."""

    def wrap(fn):
        mapped = jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
        if jit:
            mapped = jax.jit(mapped)
        return functools.wraps(fn)(mapped)

    return wrap
