"""SHMEM-style device primitives over Pallas TPU remote DMA + semaphores.

Mapping from the reference's device API (libshmem_device.py:28-335 and the
distributed dialect ops, dialect/include/Dialect/Distributed/IR/
DistributedOps.td:45-190) onto TPU hardware mechanisms:

==============================  =============================================
reference (NVSHMEM)             here (Pallas TPU)
==============================  =============================================
``my_pe()/n_pes()``             ``lax.axis_index/axis_size`` inside shard_map
``putmem_nbi_block``            ``make_async_remote_copy(...).start()``
``putmem_signal_nbi_block``     same — the *recv* DMA semaphore IS the
                                signal: TPU RDMA increments it only after
                                the payload has landed, so the NVSHMEM
                                "data then flag" ordering is a hardware
                                guarantee here, no LL-packing needed.
``signal_op(SET/ADD)``          ``semaphore_signal`` (ADD). TPU semaphores
                                have no SET; counters are cumulative and
                                callers wait on cumulative values
                                (call_count patterns still work: wait for
                                +1 per round instead of ==round).
``signal_wait_until(CMP_EQ,v)`` ``semaphore_wait(sem, v)`` — consuming wait
                                (decrements by v after the wait). This is
                                the TPU idiom; kernels are written for
                                consume semantics.
``fence()/quiet()``             DMA-handle ``wait_send()`` — completion of
                                outstanding puts is per-handle, made
                                explicit by :func:`quiet`.
``barrier_all``                 signal-all-peers + wait(n-1) on the global
                                barrier semaphore (needs a ``collective_id``).
``symm_at(ptr, rank)``          not needed: remote DMA takes a logical
                                device id directly; peers are addressed by
                                (ref, device_id), see runtime.flat_device_id.
==============================  =============================================

All functions here must be called from inside a Pallas kernel body that is
itself invoked under ``shard_map`` (see :mod:`triton_distributed_tpu.lang.launch`).
"""

from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as pltpu


def _arec():
    """The active shmemlint recorder, or None (the overwhelmingly common
    case: no symbolic execution in progress). Primitives with host-level
    control flow that cannot run outside a mesh context (axis_index,
    fori_loop) branch on this; everything else is intercepted by the
    evaluator's patched Pallas environment
    (:mod:`triton_distributed_tpu.analysis.abstract`)."""
    from triton_distributed_tpu.analysis import events

    return events.active_recorder()

# Signal-op / compare constants, mirroring NVSHMEM_SIGNAL_* / NVSHMEM_CMP_*
# (libshmem_device.py constants section).
SIGNAL_SET = "set"   # emulated — see module docstring
SIGNAL_ADD = "add"
CMP_EQ = "eq"
CMP_GE = "ge"


def my_pe(axis) -> jax.Array:
    """This device's index along mesh axis(es) ``axis`` (≡ nvshmem_my_pe)."""
    rec = _arec()
    if rec is not None:
        return rec.me
    return jax.lax.axis_index(axis)


def pe_flat(axis, idx, mesh_axes=None):
    """Translate index ``idx`` along ``axis`` into the flat LOGICAL device id
    Pallas wants, keeping this device's coordinates on all other axes.

    ``mesh_axes`` is the full ordered tuple of mesh axis names; ``None``
    means a 1D mesh where ``idx`` already is the flat id. Every cross-device
    primitive here takes flat ids — forgetting this on a multi-axis mesh
    makes RDMA target devices on the wrong mesh row (deadlock/corruption).
    """
    if mesh_axes is None or tuple(mesh_axes) == (axis,):
        return idx
    if _arec() is not None:
        raise NotImplementedError(
            "shmemlint analyzes kernels on an abstract 1D mesh; "
            f"multi-axis pe_flat over {mesh_axes} is not modeled"
        )
    from triton_distributed_tpu.runtime.topology import flat_device_id

    return flat_device_id(tuple(mesh_axes), axis, idx)


def n_pes(axis) -> jax.Array:
    """Number of devices along ``axis`` (≡ nvshmem_n_pes)."""
    rec = _arec()
    if rec is not None:
        return rec.n
    return jax.lax.axis_size(axis)


def remote_copy(src_ref, dst_ref, send_sem, recv_sem, pe):
    """Build (don't start) an async remote copy descriptor to device ``pe``.

    ``pe`` is a flat logical device id (use runtime.flat_device_id for
    multi-axis meshes, or the axis index directly on a 1D mesh).
    """
    return pltpu.make_async_remote_copy(
        src_ref=src_ref,
        dst_ref=dst_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=pe,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )


def putmem_nbi_block(dst_ref, src_ref, send_sem, recv_sem, pe):
    """Non-blocking put of ``src_ref`` into ``dst_ref`` on device ``pe``.

    Returns the DMA handle; pair with :func:`quiet` (sender) and
    ``handle.wait_recv()`` or :func:`signal_wait_until` semantics on the
    receiver (≡ libshmem_device.putmem_nbi_block).
    """
    h = remote_copy(src_ref, dst_ref, send_sem, recv_sem, pe)
    h.start()
    return h


def putmem_signal_nbi_block(dst_ref, src_ref, send_sem, recv_sem, pe):
    """Put + arrival signal (≡ putmem_signal_nbi_block, 6-variant family).

    On TPU the receive semaphore is incremented after payload arrival, so a
    single RDMA is both the data movement and the ordered signal.
    """
    return putmem_nbi_block(dst_ref, src_ref, send_sem, recv_sem, pe)


def signal_op(sem, inc=1, pe=None, *, site=None, me=None, n=None):
    """Increment a (possibly remote) regular semaphore
    (≡ libshmem_device.signal_op with NVSHMEM_SIGNAL_ADD, and the dialect's
    ``distributed.notify``, DistributedOps.td:151-164).

    ``site``/``me``/``n`` are fault-engine coordinates (see
    :mod:`triton_distributed_tpu.runtime.faults`): when an active
    :class:`FaultPlan` carries drop/dup signal faults matching ``site``,
    the matching rank's increment is suppressed or doubled — modelling a
    lost or replayed notification. Call sites that pass no coordinates
    are not hookable (plan signal faults skip them).
    """
    rec = _arec()
    if rec is not None:
        from triton_distributed_tpu.analysis import events

        rec.emit(events.SignalEvent(
            key=sem.key,
            target=rec.me if pe is None else int(pe),
            inc=int(inc),
            site=site,
        ))
        return
    from triton_distributed_tpu.runtime import faults

    if faults.inject_signal(sem, inc, pe, site, me, n):
        return
    if pe is None:
        pltpu.semaphore_signal(sem, inc=inc)
    else:
        pltpu.semaphore_signal(
            sem, inc=inc, device_id=pe, device_id_type=pltpu.DeviceIdType.LOGICAL
        )


def signal_wait_until(sem, value):
    """Wait until ``sem`` reaches ``value`` then consume it
    (≡ signal_wait_until(CMP_EQ) and the dialect's ``distributed.wait``,
    DistributedOps.td:45-77; consuming semantics are the TPU idiom)."""
    pltpu.semaphore_wait(sem, value)


def fence():
    """Ordering fence between puts to the same peer.

    TPU RDMA to a given destination is delivered in issue order per
    (src, dst) pair and the recv semaphore fires post-arrival, so the
    reference's fence (libshmem_device.fence) is a no-op here. Kept for
    API parity (and recorded for the shmemlint ordering passes).
    """
    rec = _arec()
    if rec is not None:
        from triton_distributed_tpu.analysis import events

        rec.emit(events.FenceEvent())
    return None


def quiet(*handles):
    """Block until all given put handles have drained locally
    (≡ libshmem_device.quiet). Sender-side completion only."""
    for h in handles:
        h.wait_send()


def barrier_all(axis, mesh_axes=None):
    """Grid-wide barrier across all devices along ``axis``
    (≡ libshmem_device.barrier_all / barrier_all_block;
    reference common_ops.py:62-130's barrier_all family).

    Requires the enclosing pallas_call to set a ``collective_id`` in its
    CompilerParams (the global barrier semaphore is keyed by it).
    """
    rec = _arec()
    if rec is not None:
        from triton_distributed_tpu.analysis import events

        rec.emit(events.BarrierEvent(collective_id=rec.info.collective_id))
    barrier_sem_wait_all(pltpu.get_barrier_semaphore(), axis, mesh_axes)


def neighbor_barrier(axis, left, right, *, site=None, me=None, n=None):
    """Ring-neighbor barrier on the global barrier semaphore: no RDMA into
    a peer that hasn't entered the kernel yet. ``left``/``right`` are flat
    logical device ids (already pe_flat-translated). ``site``/``me``/``n``
    expose the two outgoing credits to the fault engine's signal faults
    (see :func:`signal_op`)."""
    rec = _arec()
    if rec is not None:
        from triton_distributed_tpu.analysis import events

        rec.emit(events.BarrierEvent(collective_id=rec.info.collective_id))
    sem = pltpu.get_barrier_semaphore()
    signal_op(sem, 1, pe=left, site=site, me=me, n=n)
    signal_op(sem, 1, pe=right, site=site, me=me, n=n)
    pltpu.semaphore_wait(sem, 2)


def barrier_sem_wait_all(sem, axis, mesh_axes=None):
    """Signal every peer on a user regular semaphore and wait for all."""
    rec = _arec()
    if rec is not None:
        # symbolic execution: concrete rank loop (axis_index/fori_loop
        # have no meaning outside a mesh trace); events flow through the
        # hooked signal_op / the evaluator's patched semaphore_wait
        for i in range(rec.n - 1):
            signal_op(sem, 1, pe=pe_flat(axis, (rec.me + i + 1) % rec.n,
                                         mesh_axes))
        signal_wait_until(sem, rec.n - 1)
        return
    n = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)

    def body(i, _):
        peer = pe_flat(axis, jax.lax.rem(me + i + 1, n), mesh_axes)
        pltpu.semaphore_signal(
            sem, inc=1, device_id=peer, device_id_type=pltpu.DeviceIdType.LOGICAL
        )
        return 0

    jax.lax.fori_loop(0, n - 1, body, 0)
    pltpu.semaphore_wait(sem, n - 1)
