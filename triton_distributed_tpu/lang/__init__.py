"""Device-side language layer: SHMEM-like primitives for Pallas kernels.

TPU-native re-creation of the reference's device language (L3):
``triton_dist.language`` (``dl.wait/notify/symm_at/rank``; reference
python/triton_dist/language.py:57-112) and ``libshmem_device``
(reference patches/triton/python/triton/language/extra/
libshmem_device.py:28-335). Function names track the reference so its
tutorials/kernels map one-to-one.
"""

from triton_distributed_tpu.lang.shmem import (
    CMP_EQ,
    CMP_GE,
    SIGNAL_ADD,
    SIGNAL_SET,
    barrier_all,
    barrier_sem_wait_all,
    fence,
    my_pe,
    neighbor_barrier,
    n_pes,
    pe_flat,
    putmem_nbi_block,
    putmem_signal_nbi_block,
    quiet,
    remote_copy,
    signal_op,
    signal_wait_until,
)
from triton_distributed_tpu.lang.launch import (
    maybe_instrument,
    on_mesh,
    shmem_call,
    vmem_specs,
)
from triton_distributed_tpu.lang import wire  # noqa: F401  (lang.wire — pack/unpack+scales)

__all__ = [
    "my_pe",
    "n_pes",
    "pe_flat",
    "remote_copy",
    "putmem_nbi_block",
    "putmem_signal_nbi_block",
    "signal_op",
    "signal_wait_until",
    "fence",
    "quiet",
    "barrier_all",
    "barrier_sem_wait_all",
    "neighbor_barrier",
    "SIGNAL_SET",
    "SIGNAL_ADD",
    "CMP_EQ",
    "CMP_GE",
    "shmem_call",
    "maybe_instrument",
    "on_mesh",
    "vmem_specs",
]
