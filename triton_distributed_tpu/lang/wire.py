"""Wire quantization for the streaming rings: fp8/int8 payloads with
per-chunk scales.

The MoE A2A transport already proved out fp8+scales wire compression
(kernels/moe_all_to_all.py, BENCH_r05 "fp8+scales fused-chunked-dma");
this module generalizes the idea to the AG/RS streaming rings so the
fused TP engines (ag_gemm, gemm_rs, the moe_tp_fused pair) and the
standalone ring collectives can move 1-byte slabs on comm-bound shapes
— DeepEP-style low-latency transports in the reference compress their
dispatch payloads for exactly this reason (arXiv:2504.19442).

Layout contract (shared by the Pallas rings and their XLA twins, so
both ship byte-identical wire formats):

* payload: the (rows, cols) slab cast to the wire dtype — fp8 (e4m3)
  or int8, 1 byte/element;
* scales: ONE f32 scale per CHUNK of ``chunk_rows`` consecutive rows
  (symmetric quantization, scale = chunk amax / QMAX), shipped as a
  (rows // chunk_rows, 128) f32 plane with the scale replicated across
  the 128 lanes — the lane replication makes the plane a legal Mosaic
  block operand ((1, 128) blocks, the flash-decode scale-plane idiom)
  and costs 512 B per chunk, negligible against chunk_rows·cols wire
  bytes at ring-slab scale.

Semantics:

* AG-side rings quantize ONCE at the source and forward the quantized
  bytes unchanged; receivers dequantize to the compute dtype before
  the MXU consumes the shard (each rank's OWN shard is consumed exact
  — it never crosses the wire).
* RS-side rings must re-quantize at every hop (each hop's payload is a
  new partial sum); the receive side dequantizes and accumulates in
  f32 before casting back, so the reduction error stays bounded by
  (n-1) independent per-hop roundings rather than compounding through
  the accumulator.

The value-level transforms are gradient-opaque (quantize rounds), so
the forward ops treat the wire knob as a transport option, mirroring
the MoE transport. Gradient RINGS ride the wire too — via the seeded
stochastic-rounding twin :func:`quantize_slab_sr` plus the per-hop
error feedback in ``train.grad_wire``, which together keep the
accumulated backward error bounded instead of compounding.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

#: accepted wire_dtype spellings. None and "bf16" both mean "raw wire"
#: (ship the compute dtype, today's behavior); "int8-mxu" ships the
#: int8 payload AND ends the wire at the MXU — the consumer runs an
#: s8×s8→s32 matmul on the arriving slab and folds the chunk scale into
#: the f32 accumulator epilogue (no per-arrival dequant pass); "auto"
#: defers to the perf-model / autotuner selection at the op entry.
WIRE_DTYPES = (None, "bf16", "fp8", "int8", "int8-mxu", "auto")

_QMAX = {"fp8": 448.0, "int8": 127.0}
_WDT = {"fp8": jnp.float8_e4m3fn, "int8": jnp.int8}

#: lane width of the scale planes (one f32 scale replicated per lane).
SCALE_LANES = 128


def _lint_recorder():
    """The active shmemlint recorder, or None (the overwhelmingly
    common case). The wire transforms are hookable the same way the
    ``lang.shmem`` primitives are: under symbolic execution they emit
    Quant/Dequant events carrying their ref regions (the provenance
    edges the SL008–SL010 data-correctness passes replay) instead of
    running the value-level pipelines."""
    from triton_distributed_tpu.analysis import events

    return events.active_recorder()


def paired_scale_ok(q_rows: int, s_shape: tuple) -> bool:
    """THE wire layout contract, exported for the static checker: a
    payload slab of ``q_rows`` rows pairs with an ``(s_rows,
    SCALE_LANES)`` f32 scale plane whose rows evenly chunk the payload
    (chunk_rows = q_rows / s_rows). shmemlint's SL009 validates every
    payload/scale RDMA pair against this instead of re-deriving layout
    from kernel internals."""
    if len(s_shape) != 2:
        return False
    s_rows, s_cols = s_shape
    return (
        s_cols == SCALE_LANES and s_rows > 0 and q_rows > 0
        and q_rows % s_rows == 0
    )


def normalize_wire(wire_dtype) -> str | None:
    """Canonical wire spelling: None for raw bf16 wire, 'fp8'/'int8'
    for compressed, 'int8-mxu' for the epilogue-dequant consumer wire,
    'auto' passed through for the selectors."""
    if wire_dtype in (None, "bf16"):
        return None
    if wire_dtype in ("fp8", "int8", "int8-mxu", "auto"):
        return wire_dtype
    raise ValueError(
        f"wire_dtype must be one of {WIRE_DTYPES}, got {wire_dtype!r}"
    )


def wire_payload(wire: str | None) -> str | None:
    """The PAYLOAD format a wire spelling puts on the rails. 'int8-mxu'
    ships byte-identical rails to 'int8' — the difference is entirely on
    the consumer side (epilogue-folded dequant instead of a dequant
    pass) — so ops with no MXU consumer (standalone AG/RS rings, the
    DCN rail legs, which dequantize before any compute) carry it as a
    plain int8 wire."""
    return "int8" if wire == "int8-mxu" else wire


@dataclass(frozen=True)
class WireFormat:
    """Static geometry of one quantized ring wire.

    ``quant``: 'fp8' | 'int8'; ``chunk_rows``: rows per f32 scale
    (must divide the slab rows it is used with).
    """

    quant: str
    chunk_rows: int

    @property
    def wire_dtype(self):
        return jnp.dtype(_WDT[self.quant])

    @property
    def qmax(self) -> float:
        return _QMAX[self.quant]

    def chunks(self, rows: int) -> int:
        assert rows % self.chunk_rows == 0, (rows, self.chunk_rows)
        return rows // self.chunk_rows

    def scale_shape(self, rows: int) -> tuple:
        return (self.chunks(rows), SCALE_LANES)

    def slab_bytes(self, rows: int, cols: int) -> int:
        """Wire bytes of one (rows, cols) slab: payload + scale plane."""
        return rows * cols * self.wire_dtype.itemsize \
            + self.chunks(rows) * SCALE_LANES * 4


def pick_chunk_rows(rows: int, strict: bool, target: int = 64) -> int | None:
    """Scale-chunk granularity for a slab of ``rows`` rows: the largest
    divisor ≤ ``target`` that keeps an interior (chunk_rows, bn) wire
    block Mosaic-lowerable (int8 sublane granule 32), or the whole slab
    as a single chunk. None only for pathological strict shapes."""
    from triton_distributed_tpu.kernels.ag_gemm import _divisor_block

    return _divisor_block(rows, min(target, rows), 32, strict)


def make_wire_format(quant: str, rows: int, *, strict: bool = False,
                     chunk_rows: int | None = None) -> WireFormat | None:
    """WireFormat for a slab of ``rows`` rows, or None when no legal
    chunking exists (callers then stay on the bf16 wire)."""
    cr = chunk_rows or pick_chunk_rows(rows, strict)
    if cr is None or rows % cr:
        return None
    return WireFormat(quant=wire_payload(quant), chunk_rows=cr)


# ------------------------------------------------------- XLA-side helpers

def quantize_slab(x, fmt: WireFormat):
    """(rows, cols) → (wire-dtype payload, (chunks, 128) f32 scales).

    Symmetric per-chunk quantization (scale = chunk amax / QMAX) — the
    per-token scales of the MoE wire (moe_all_to_all.quantize_rows),
    coarsened to ring-chunk granularity."""
    rows, cols = x.shape
    ch = fmt.chunks(rows)
    xf = x.astype(jnp.float32).reshape(ch, fmt.chunk_rows * cols)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-12) / fmt.qmax
    q = xf / scale[:, None]
    if fmt.quant == "int8":
        q = jnp.clip(jnp.round(q), -127, 127)
    q = q.reshape(rows, cols).astype(fmt.wire_dtype)
    scales = jnp.broadcast_to(scale[:, None], (ch, SCALE_LANES))
    return q, scales.astype(jnp.float32)


def dequantize_slab(q, scales, fmt: WireFormat, out_dtype):
    """Inverse of :func:`quantize_slab` back to ``out_dtype``."""
    rows, cols = q.shape
    ch = fmt.chunks(rows)
    y = q.astype(jnp.float32).reshape(ch, fmt.chunk_rows * cols)
    y = y * scales[:, :1]
    return y.reshape(rows, cols).astype(out_dtype)


def quantize_slab_sr(x, fmt: WireFormat, key):
    """:func:`quantize_slab` with SEEDED STOCHASTIC ROUNDING — the
    gradient-ring quantizer (``train.grad_wire`` and the quantized
    backward duals of ``ops.overlap``).

    Same scale convention as the deterministic twin (symmetric
    per-chunk, scale = amax / QMAX clamped at 1e-12), but the int8 grid
    rounds ``floor(y + u)`` with ``u ~ U[0, 1)`` drawn from ``key`` —
    unbiased per element (``E[q·s] = x``), so the ring's reduction
    error averages out instead of accumulating a systematic
    round-to-nearest bias across hops. The fp8 grid is non-uniform (no
    uniform-offset SR exists for it), so fp8 keeps round-to-nearest and
    the grad ring's error feedback carries the bias instead.

    Deterministic under a fixed ``key``: same seed, same bits — the
    trainer derives keys from ``config.interp_key()``-stable seeds so a
    replayed step requantizes identically."""
    import jax

    rows, cols = x.shape
    ch = fmt.chunks(rows)
    xf = x.astype(jnp.float32).reshape(ch, fmt.chunk_rows * cols)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-12) / fmt.qmax
    q = xf / scale[:, None]
    if fmt.quant == "int8":
        u = jax.random.uniform(key, q.shape, dtype=jnp.float32)
        q = jnp.clip(jnp.floor(q + u), -127, 127)
    q = q.reshape(rows, cols).astype(fmt.wire_dtype)
    scales = jnp.broadcast_to(scale[:, None], (ch, SCALE_LANES))
    return q, scales.astype(jnp.float32)


# -------------------------------------------------- in-kernel pipelines
#
# HBM-streaming twins of the helpers above, for the fused engines whose
# slabs never fit VMEM whole. Blocks stream through VMEM double-buffered
# (the ew_add_pipeline idiom); the scale plane rides as (1, 128) blocks.

def _wire_cols_block(cols: int, itemsize: int) -> int | None:
    """Column block of the dequant pipelines. Pinned to the scale
    plane's lane width: the inner multiply is then a (chunk_rows, 128)
    payload block against the (1, 128) scale block — a plain sublane
    broadcast, the flash-decode scale-fold idiom. A scalar extraction
    (``s_ref[0, 0]``) instead lowers to a ``vector.shape_cast 1x1 →
    scalar`` this Mosaic rejects (caught by the AOT suite)."""
    from triton_distributed_tpu.config import compiling_for_tpu
    from triton_distributed_tpu.kernels.ag_gemm import _divisor_block

    del itemsize
    if cols % SCALE_LANES == 0:
        return SCALE_LANES
    return _divisor_block(cols, SCALE_LANES, 128, compiling_for_tpu())


def quant_pipeline(rows: int, cols: int, fmt: WireFormat):
    """Streaming quantizer over HBM refs: callable(src, q, s).

    Two passes (both tiled emit_pipelines): the scale pass reduces each
    (chunk_rows, cols) chunk to its lane-replicated (1, 128) scale row
    via keepdims reductions + a lane broadcast — never materializing a
    scalar, because Mosaic rejects the ``vector<1x1> → scalar``
    shape_cast that ``jnp.max(x)`` / ``s_ref[0, 0]`` would emit (AOT
    suite finding) — and the quantize pass divides (chunk_rows, 128)
    payload blocks by the (1, 128) scale row (sublane broadcast, the
    flash-decode scale-fold idiom). Costs one extra read of the source
    slab; the wire, not HBM, is the bottleneck where this engages."""
    from jax.experimental.pallas import tpu as pltpu
    from jax.experimental import pallas as pl

    ch = fmt.chunks(rows)
    qmax = fmt.qmax
    bn = _wire_cols_block(cols, 1)

    def scale_inner(src_ref, s_ref):
        x = jnp.abs(src_ref[...].astype(jnp.float32))
        row = jnp.max(x, axis=1, keepdims=True)         # (cr, 1)  lanes
        chunk = jnp.max(row, axis=0, keepdims=True)     # (1, 1) sublanes
        s_ref[...] = jnp.broadcast_to(
            jnp.maximum(chunk, 1e-12) / qmax, (1, SCALE_LANES)
        ).astype(jnp.float32)

    def quant_inner(src_ref, s_ref, q_ref):
        y = src_ref[...].astype(jnp.float32) / s_ref[:, :bn]
        if fmt.quant == "int8":
            y = jnp.clip(jnp.round(y), -127, 127)
        q_ref[...] = y.astype(q_ref.dtype)

    scale_pipe = pltpu.emit_pipeline(
        scale_inner,
        grid=(ch,),
        in_specs=[pl.BlockSpec((fmt.chunk_rows, cols), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((1, SCALE_LANES), lambda i: (i, 0))],
    )
    quant_pipe = pltpu.emit_pipeline(
        quant_inner,
        grid=(ch, cols // bn),
        in_specs=[
            pl.BlockSpec((fmt.chunk_rows, bn), lambda i, j: (i, j)),
            pl.BlockSpec((1, SCALE_LANES), lambda i, j: (i, 0)),
        ],
        out_specs=[pl.BlockSpec((fmt.chunk_rows, bn), lambda i, j: (i, j))],
    )

    def run(src_hbm, q_hbm, s_hbm):
        rec = _lint_recorder()
        if rec is not None:
            from triton_distributed_tpu.analysis import events as ev

            rec.emit(ev.QuantEvent(
                src_region=src_hbm.region(), q_region=q_hbm.region(),
                s_region=s_hbm.region(), chunk_rows=fmt.chunk_rows,
            ))
            return
        scale_pipe(src_hbm, s_hbm)
        quant_pipe(src_hbm, s_hbm, q_hbm)

    return run


def dequant_pipeline(rows: int, cols: int, fmt: WireFormat):
    """Streaming dequantizer over HBM refs: (q, scales) → dst."""
    from jax.experimental.pallas import tpu as pltpu
    from jax.experimental import pallas as pl

    ch = fmt.chunks(rows)
    bn = _wire_cols_block(cols, fmt.wire_dtype.itemsize)

    def inner(q_ref, s_ref, o_ref):
        # (cr, bn) · (1, bn) — sublane broadcast (the scale is lane-
        # replicated across the plane, so any bn ≤ 128 window is valid)
        o_ref[...] = (
            q_ref[...].astype(jnp.float32) * s_ref[:, :bn]
        ).astype(o_ref.dtype)

    pipe = pltpu.emit_pipeline(
        inner,
        grid=(ch, cols // bn),
        in_specs=[
            pl.BlockSpec((fmt.chunk_rows, bn), lambda i, j: (i, j)),
            pl.BlockSpec((1, SCALE_LANES), lambda i, j: (i, 0)),
        ],
        out_specs=[pl.BlockSpec((fmt.chunk_rows, bn), lambda i, j: (i, j))],
    )

    def run(q_hbm, s_hbm, dst_hbm):
        rec = _lint_recorder()
        if rec is not None:
            from triton_distributed_tpu.analysis import events as ev

            rec.emit(ev.DequantEvent(
                q_region=q_hbm.region(), s_region=s_hbm.region(),
                dst_region=dst_hbm.region(),
            ))
            return
        pipe(q_hbm, s_hbm, dst_hbm)

    return run


def dequant_add_pipeline(rows: int, cols: int, fmt: WireFormat):
    """Streaming fused dequant-accumulate: dst = a + dequant(q, s).

    The RS-ring fold with a quantized wire: the add runs in f32 (the
    dequantized operand never round-trips through the wire dtype), so
    per-hop error is one rounding, not a compounding cast chain."""
    from jax.experimental.pallas import tpu as pltpu
    from jax.experimental import pallas as pl

    ch = fmt.chunks(rows)
    bn = _wire_cols_block(cols, fmt.wire_dtype.itemsize)

    def inner(a_ref, q_ref, s_ref, o_ref):
        o_ref[...] = (
            a_ref[...].astype(jnp.float32)
            + q_ref[...].astype(jnp.float32) * s_ref[:, :bn]
        ).astype(o_ref.dtype)

    spec = pl.BlockSpec((fmt.chunk_rows, bn), lambda i, j: (i, j))
    pipe = pltpu.emit_pipeline(
        inner,
        grid=(ch, cols // bn),
        in_specs=[
            spec,
            pl.BlockSpec((fmt.chunk_rows, bn), lambda i, j: (i, j)),
            pl.BlockSpec((1, SCALE_LANES), lambda i, j: (i, 0)),
        ],
        out_specs=[pl.BlockSpec((fmt.chunk_rows, bn), lambda i, j: (i, j))],
    )

    def run(a_hbm, q_hbm, s_hbm, dst_hbm):
        rec = _lint_recorder()
        if rec is not None:
            from triton_distributed_tpu.analysis import events as ev

            rec.emit(ev.DequantEvent(
                q_region=q_hbm.region(), s_region=s_hbm.region(),
                dst_region=dst_hbm.region(), add_region=a_hbm.region(),
            ))
            return
        pipe(a_hbm, q_hbm, s_hbm, dst_hbm)

    return run


def dequant_add_requant_pipeline(rows: int, cols: int, fmt: WireFormat):
    """Fused RS-ring fold + wire requantize:
    ``dst = a + dequant(q, s)`` AND ``(wq, ws) = quant(dst)`` with the
    wire scale taken off the fold accumulator — the reduce ring's next
    hop must ship the ACCUMULATED partial, so a producer-quantized wire
    (gemm_rs int8-MXU) re-quantizes here, in the fold pass itself,
    instead of a separate ``quant_pipeline`` read-back over HBM (the
    fold writes dst + the scale row in one pass; only the payload
    quantize re-reads dst — one slab read saved per hop)."""
    from jax.experimental.pallas import tpu as pltpu
    from jax.experimental import pallas as pl

    ch = fmt.chunks(rows)
    qmax = fmt.qmax
    bn = _wire_cols_block(cols, fmt.wire_dtype.itemsize)

    def fold_inner(a_ref, q_ref, s_ref, o_ref, ws_ref):
        # (1, 1) scale window → sublane+lane broadcast over the full
        # chunk (the scale row is lane-replicated; mm_q8_rs_pipeline's
        # ``as_ref[:, :1]`` idiom)
        t = (a_ref[...].astype(jnp.float32)
             + q_ref[...].astype(jnp.float32) * s_ref[:, :1])
        o_ref[...] = t.astype(o_ref.dtype)
        row = jnp.max(jnp.abs(t), axis=1, keepdims=True)
        chunk = jnp.max(row, axis=0, keepdims=True)
        ws_ref[...] = jnp.broadcast_to(
            jnp.maximum(chunk, 1e-12) / qmax, (1, SCALE_LANES)
        ).astype(jnp.float32)

    fold_pipe = pltpu.emit_pipeline(
        fold_inner,
        grid=(ch,),
        in_specs=[
            pl.BlockSpec((fmt.chunk_rows, cols), lambda i: (i, 0)),
            pl.BlockSpec((fmt.chunk_rows, cols), lambda i: (i, 0)),
            pl.BlockSpec((1, SCALE_LANES), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((fmt.chunk_rows, cols), lambda i: (i, 0)),
            pl.BlockSpec((1, SCALE_LANES), lambda i: (i, 0)),
        ],
    )

    def quant_inner(src_ref, s_ref, q_ref):
        y = src_ref[...].astype(jnp.float32) / s_ref[:, :bn]
        if fmt.quant == "int8":
            y = jnp.clip(jnp.round(y), -127, 127)
        q_ref[...] = y.astype(q_ref.dtype)

    quant_pipe = pltpu.emit_pipeline(
        quant_inner,
        grid=(ch, cols // bn),
        in_specs=[
            pl.BlockSpec((fmt.chunk_rows, bn), lambda i, j: (i, j)),
            pl.BlockSpec((1, SCALE_LANES), lambda i, j: (i, 0)),
        ],
        out_specs=[pl.BlockSpec((fmt.chunk_rows, bn), lambda i, j: (i, j))],
    )

    def run(a_hbm, q_hbm, s_hbm, dst_hbm, wq_hbm, ws_hbm):
        rec = _lint_recorder()
        if rec is not None:
            from triton_distributed_tpu.analysis import events as ev

            rec.emit(ev.DequantEvent(
                q_region=q_hbm.region(), s_region=s_hbm.region(),
                dst_region=dst_hbm.region(), add_region=a_hbm.region(),
            ))
            rec.emit(ev.QuantEvent(
                src_region=dst_hbm.region(), q_region=wq_hbm.region(),
                s_region=ws_hbm.region(), chunk_rows=fmt.chunk_rows,
            ))
            return
        fold_pipe(a_hbm, q_hbm, s_hbm, dst_hbm, ws_hbm)
        quant_pipe(dst_hbm, ws_hbm, wq_hbm)

    return run


# ------------------------------------------------- VMEM-resident helpers
#
# The standalone ring kernels (allgather._ring_ag_kernel_w,
# reduce_scatter._ring_rs_kernel_w) keep whole slabs VMEM-resident and
# (de)quantize with direct ref arithmetic rather than streamed
# pipelines. Routing that arithmetic through these helpers keeps ONE
# implementation of the per-row wire math and gives shmemlint the same
# Quant/Dequant provenance events the pipelines emit.

def quant_rows_into(q_ref, s_ref, src_ref, quant: str):
    """Per-row symmetric quantization (chunk_rows=1) of a VMEM slab:
    ``q = src / scale``, ``s`` the lane-replicated f32 scale plane."""
    rec = _lint_recorder()
    if rec is not None:
        from triton_distributed_tpu.analysis import events as ev

        rec.emit(ev.QuantEvent(
            src_region=src_ref.region(), q_region=q_ref.region(),
            s_region=s_ref.region(), chunk_rows=1,
        ))
        return
    af = src_ref[...].astype(jnp.float32)
    amax = jnp.max(jnp.abs(af), axis=1, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / _QMAX[quant]
    q = af / scale
    if quant == "int8":
        q = jnp.clip(jnp.round(q), -127, 127)
    q_ref[...] = q.astype(q_ref.dtype)
    s_ref[...] = jnp.broadcast_to(
        scale, (af.shape[0], SCALE_LANES)
    ).astype(jnp.float32)


def dequant_rows_into(dst_ref, q_ref, s_ref):
    """Per-row dequant of a VMEM slab: ``dst = q · s[:, :1]`` (the
    scale is lane-replicated, column 0 suffices)."""
    from jax.experimental import pallas as pl

    rec = _lint_recorder()
    if rec is not None:
        from triton_distributed_tpu.analysis import events as ev

        rec.emit(ev.DequantEvent(
            q_region=q_ref.region(), s_region=s_ref.region(),
            dst_region=dst_ref.region(),
        ))
        return
    sc = s_ref[:, pl.ds(0, 1)]
    dst_ref[...] = (
        q_ref[...].astype(jnp.float32) * sc
    ).astype(dst_ref.dtype)


def dequant_add_rows_into(dst_ref, q_ref, s_ref, add_ref):
    """Fused per-row dequant-accumulate: ``dst = add + q · s[:, :1]``
    in f32 (the RS-ring fold — one rounding per hop)."""
    from jax.experimental import pallas as pl

    rec = _lint_recorder()
    if rec is not None:
        from triton_distributed_tpu.analysis import events as ev

        rec.emit(ev.DequantEvent(
            q_region=q_ref.region(), s_region=s_ref.region(),
            dst_region=dst_ref.region(), add_region=add_ref.region(),
        ))
        return
    sc = s_ref[:, pl.ds(0, 1)]
    dst_ref[...] = (
        q_ref[...].astype(jnp.float32) * sc
        + add_ref[...].astype(jnp.float32)
    ).astype(dst_ref.dtype)


def inkernel_wire_ok(quant: str) -> bool:
    """Can a PALLAS ring dequantize/quantize this wire dtype in-kernel
    on the current toolchain?

    The 2024-12 Mosaic backend rejects fp8 float extensions ("Only
    16-bit to 32-bit extensions supported": ``arith.extf f8E4M3FN →
    f32`` — caught by tests/test_aot_topology.py), while int8 ↔ f32
    widening/narrowing lowers fine (the int8-KV decode kernels run on
    chip, round 5). So in-kernel wires are int8-only when compiling
    for real Mosaic; fp8 stays available on the XLA engines (XLA
    handles f8 natively) and under the interpreter. Set
    ``TDTPU_WIRE_FP8_INKERNEL=1`` on a newer toolchain whose Mosaic
    gained the f8 casts."""
    import os

    from triton_distributed_tpu.config import compiling_for_tpu

    if quant != "fp8":
        return True
    if os.environ.get("TDTPU_WIRE_FP8_INKERNEL") == "1":
        return True
    return not compiling_for_tpu()


def require_inkernel(quant: str, engine: str) -> None:
    """Raise the canonical diagnostic when an EXPLICIT wire format needs
    in-kernel casts the current Mosaic lacks (pinned = contract)."""
    if not inkernel_wire_ok(quant):
        raise ValueError(
            f"{engine}: wire_dtype='fp8' requires in-kernel f8 casts this "
            "Mosaic backend lacks ('Only 16-bit to 32-bit extensions "
            "supported'); use wire_dtype='int8', an XLA engine (which "
            "carries fp8 natively), or TDTPU_WIRE_FP8_INKERNEL=1 on a "
            "newer toolchain"
        )


def inkernel_s8_dot_ok() -> bool:
    """Can a PALLAS kernel on the current toolchain feed int8 operands
    straight into the MXU (``dot_general`` s8×s8 → s32)?

    This Mosaic backend lowers the native s8×s8→s32 path fine — the
    W8A8 grouped GEMM (kernels/group_gemm._ggemm_q8a_kernel) runs it on
    chip at ~2× the bf16 rate (round 5, docs/PERF.md) — so the default
    is True. ``TDTPU_WIRE_INT8_MXU=0`` force-disables the epilogue-
    dequant consumers on a toolchain whose Mosaic regresses (the
    mosaic_compat pre-flight's MC004 scan then also catches the
    rejected accumulator form at build time)."""
    import os

    return os.environ.get("TDTPU_WIRE_INT8_MXU") != "0"


def require_mxu(engine: str) -> None:
    """Raise the canonical clean-refusal diagnostic when an EXPLICIT
    'int8-mxu' wire is pinned but in-kernel s8 MXU consumption is
    disabled for this toolchain (pinned = contract; the mosaic_compat
    pre-flight treats this refusal as a pass, mirroring the fp8
    handling)."""
    if not inkernel_s8_dot_ok():
        raise ValueError(
            f"{engine}: wire_dtype='int8-mxu' requires in-kernel s8 "
            "MXU dots, disabled for this toolchain "
            "(TDTPU_WIRE_INT8_MXU=0); use wire_dtype='int8' "
            "(dequant-then-matmul) or the bf16 wire"
        )


def quantize_cols(b):
    """(K, N) matmul weight → ((K, N) int8, (1, N) f32 scales):
    symmetric per-out-channel weight quantization for the int8-MXU
    consumers (the stationary-operand half of the s8×s8 product; the
    moving half is the per-chunk wire quantization). Same convention as
    ``kernels.group_gemm.quantize_grouped_weights`` with E=1, kept 2-D
    so the (1, bn) scale block is a legal Mosaic operand."""
    bf = b.astype(jnp.float32)
    amax = jnp.max(jnp.abs(bf), axis=0, keepdims=True)        # (1, N)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(bf / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def epilogue_consume(q_hbm, s_hbm, out_hbm):
    """Record (under an active shmemlint recorder) that a quantized
    payload slab is consumed by an MXU pipeline whose ACCUMULATOR
    EPILOGUE folds the paired scale plane — the provenance edge that
    lets the dataflow pass treat the slab as dequantized-on-consume
    (SL008) while still checking the scale pairing (SL009/SL010).
    Returns True when an event was emitted (the caller then skips its
    value-level pipeline). ``s_hbm=None`` records a consume WITHOUT the
    scale fold — the scale-fold-omitted bug SL009 pins."""
    rec = _lint_recorder()
    if rec is None:
        return False
    from triton_distributed_tpu.analysis import events as ev

    rec.emit(ev.DequantEvent(
        q_region=q_hbm.region(),
        s_region=None if s_hbm is None else s_hbm.region(),
        dst_region=None if out_hbm is None else out_hbm.region(),
        epilogue=True,
    ))
    return True


def wire_blockable(rows: int, cols: int, quant: str, strict: bool) -> bool:
    """Can a (rows, cols) slab carry this wire format at all? (legal
    chunking + lowerable column blocks + the scale overhead actually
    saves bytes — tiny-cols slabs where the 512 B/chunk plane eats the
    compression are rejected rather than silently shipped larger)."""
    fmt = make_wire_format(wire_payload(quant), rows, strict=strict)
    if fmt is None or _wire_cols_block(cols, 1) is None:
        return False
    return fmt.slab_bytes(rows, cols) < rows * cols * 2  # vs bf16 wire
