"""The dp×tp×cp train step: CP ring attention, Megatron TP, and the
wire-quantized dp gradient ring under one shard_map.

The reference repo trains with torch.distributed and leaves the
backward pass on raw NCCL; here the full serving-side stack — wire
formats, watchdog instrumentation, HealthLedger degradation — extends
to training:

* **cp** shards the sequence; attention runs the
  :func:`~triton_distributed_tpu.kernels.ring_attention.ring_attention_device`
  body (or the Ulysses a2a body) over the ``"cp"`` axis inside the
  step's own shard_map.
* **tp** shards the MLP Megatron-style. The f-operator (identity
  forward, psum-over-tp backward) is spelled explicitly with a
  ``custom_vjp``: after backward, every tp rank holds the FULL input
  cotangent, so replicated-parameter gradients come out tp-replicated
  and the gradient sync never reduces over ``"tp"`` (doing so would
  double-count the attention path — the classic mixed
  replicated/sharded transpose trap).
* **dp** syncs gradients on the quantized ring
  (:func:`~triton_distributed_tpu.train.grad_wire.grad_tree_allreduce`):
  flatten the grad tree to one slab, EF+SR int8/fp8 reduce-scatter,
  quantize-once all-gather. ``wire=None`` is the exact ``psum`` twin —
  the degradation target the HealthLedger demotes to.

Gradient reductions are therefore: exact ``psum`` over ``"cp"``
(distinct tokens per cp rank), the wire ring over ``"dp"``, nothing
over ``"tp"``.

Degradation follows the serving-engine idiom: the jitted step runs
under a host-mode ``maybe_instrument`` heartbeat at site
``"grad_ring"`` — an armed watchdog that trips on a wedged step
broadcasts ``site:grad_ring`` FATAL into live ledgers, the next step
demotes to the XLA psum twin, and the ledger's probation schedule
re-promotes through clean probes.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_distributed_tpu.kernels.ring_attention import (
    dense_attention_reference,
    ring_attention_device,
    ulysses_attention_device,
)
from triton_distributed_tpu.train import grad_wire

#: The registry families this subsystem owns — bench.py's ``--lint``
#: gate requires each to be registered with a delivery contract, a
#: degradation target, and zero lint findings (``train_gaps == 0``).
TRAIN_ENGINE_FAMILIES = (
    "cp.ring_attention",
    "cp.ulysses",
    "grad_ring.stream_int8w",
)

_SITE = "grad_ring"
_PEER = "site:grad_ring"          # the ledger key a watchdog trip lands on


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Static train-step configuration (hashable: it keys the jit
    cache). The defaults are the dryrun geometry — a tiny transformer
    block on the dp2×tp2×cp2 virtual mesh."""

    vocab: int = 64
    d_model: int = 32
    n_heads: int = 4
    d_ff: int = 64
    seq: int = 16
    batch: int = 8
    dp: int = 2
    tp: int = 2
    cp: int = 2
    microbatches: int = 2
    attn: str = "ring"            # "ring" | "ulysses"
    #: the dp gradient ring's wire: None/'bf16' = exact psum,
    #: 'fp8'/'int8' = pinned (raises if the slab admits no legal
    #: chunking), 'auto' = demote silently to the exact wire
    wire_dtype: object = "int8"
    ef: bool = True               # error feedback on the ring
    lr: float = 1e-2
    beta1: float = 0.9
    beta2: float = 0.999
    adam_eps: float = 1e-8
    seed: int = 0

    def __post_init__(self):
        from triton_distributed_tpu.lang import wire as wirelib

        wirelib.normalize_wire(self.wire_dtype)   # loud on junk
        if self.d_model % self.n_heads:
            raise ValueError(f"d_model {self.d_model} % n_heads "
                             f"{self.n_heads} != 0")
        if self.seq % self.cp:
            raise ValueError(f"seq {self.seq} % cp {self.cp} != 0")
        if self.batch % self.dp:
            raise ValueError(f"batch {self.batch} % dp {self.dp} != 0")
        if (self.batch // self.dp) % self.microbatches:
            raise ValueError(
                f"per-dp batch {self.batch // self.dp} % microbatches "
                f"{self.microbatches} != 0")
        if self.d_ff % self.tp:
            raise ValueError(f"d_ff {self.d_ff} % tp {self.tp} != 0")
        if self.attn not in ("ring", "ulysses"):
            raise ValueError(f"attn must be 'ring'|'ulysses', "
                             f"got {self.attn!r}")
        if self.attn == "ulysses" and self.n_heads % self.cp:
            raise ValueError(f"ulysses needs n_heads {self.n_heads} % "
                             f"cp {self.cp} == 0")


def default_train_mesh(cfg: TrainConfig) -> Mesh:
    """The (dp, tp, cp) mesh over the first dp·tp·cp local devices."""
    need = cfg.dp * cfg.tp * cfg.cp
    devs = jax.devices()
    if len(devs) < need:
        raise ValueError(
            f"TrainConfig wants dp×tp×cp = {need} devices, "
            f"have {len(devs)}")
    return Mesh(np.asarray(devs[:need]).reshape(cfg.dp, cfg.tp, cfg.cp),
                ("dp", "tp", "cp"))


# ------------------------------------------------------------- model


def init_params(cfg: TrainConfig) -> dict:
    """The tiny one-block transformer's parameters, f32, unplaced.
    ``w1``/``w2`` are the Megatron-sharded pair (cols/rows over tp);
    everything else is replicated."""
    ks = jax.random.split(jax.random.PRNGKey(cfg.seed), 8)
    d, ff, v = cfg.d_model, cfg.d_ff, cfg.vocab

    def init(k, shape, scale):
        return jax.random.normal(k, shape, jnp.float32) * scale

    return {
        "embed": init(ks[0], (v, d), 1.0),
        "wq": init(ks[1], (d, d), d ** -0.5),
        "wk": init(ks[2], (d, d), d ** -0.5),
        "wv": init(ks[3], (d, d), d ** -0.5),
        "wo": init(ks[4], (d, d), d ** -0.5),
        "w1": init(ks[5], (d, ff), d ** -0.5),
        "w2": init(ks[6], (ff, d), ff ** -0.5),
        "head": init(ks[7], (d, v), d ** -0.5),
    }


def init_opt_state(params: dict) -> dict:
    """Adam state: step count + f32 first/second moments (same tree
    structure and shardings as the parameters — donated every step)."""
    return {
        "t": jnp.zeros((), jnp.int32),
        "m": jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32),
                          params),
        "v": jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32),
                          params),
    }


def _param_specs(cfg: TrainConfig) -> dict:
    return {
        k: (P(None, "tp") if k == "w1"
            else P("tp", None) if k == "w2" else P())
        for k in ("embed", "wq", "wk", "wv", "wo", "w1", "w2", "head")
    }


def _megatron_f(axis: str):
    """Megatron's f-operator: identity forward, psum-over-tp backward.
    Placed on the MLP INPUT so the input cotangent — partial per tp
    rank (each rank backprops only its own w1/w2 shard's path) — is
    summed to the full dx before it reaches the replicated attention/
    embedding parameters. Their grads then come out tp-REPLICATED, and
    the gradient sync must not reduce over tp at all."""

    @jax.custom_vjp
    def f(x):
        return x

    f.defvjp(lambda x: (x, None),
             lambda _, g: (jax.lax.psum(g, axis),))
    return f


def _token_xent_sum(logits, targets):
    """Summed (not meaned) next-token cross-entropy in f32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.sum(ll)


def _forward_device(cfg: TrainConfig, params, tokens):
    """Per-device forward (inside shard_map): tokens (b_loc, s_loc) →
    logits (b_loc, s_loc, vocab). Attention over ``"cp"``, Megatron
    MLP over ``"tp"``."""
    x = params["embed"][tokens]                        # (b, s, d)
    b, s, d = x.shape
    h, dh = cfg.n_heads, d // cfg.n_heads

    def heads(w):
        return (x @ w).reshape(b, s, h, dh)

    attn = (ring_attention_device if cfg.attn == "ring"
            else ulysses_attention_device)
    o = attn(heads(params["wq"]), heads(params["wk"]),
             heads(params["wv"]), "cp", causal=True)
    x = x + o.reshape(b, s, d) @ params["wo"]
    xf = _megatron_f("tp")(x)
    mlp = jax.lax.psum(
        jax.nn.gelu(xf @ params["w1"]) @ params["w2"], "tp")
    x = x + mlp
    return x @ params["head"]


def _adam(cfg: TrainConfig, params, grads, opt):
    t = opt["t"] + 1
    b1, b2 = cfg.beta1, cfg.beta2
    m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                     opt["m"], grads)
    v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g,
                     opt["v"], grads)
    tf = t.astype(jnp.float32)
    c1 = 1.0 - b1 ** tf
    c2 = 1.0 - b2 ** tf

    def upd(p, m_, v_):
        step = cfg.lr * (m_ / c1) / (jnp.sqrt(v_ / c2) + cfg.adam_eps)
        return (p - step).astype(p.dtype)

    return jax.tree.map(upd, params, m, v), {"t": t, "m": m, "v": v}


def _device_step(cfg: TrainConfig, wire, base_seed,
                 params, opt, tokens, targets):
    """One per-device train step (the shard_map body): microbatched
    loss+grad, cp psum, dp wire ring, Adam. Returns the global mean
    loss replicated on every rank."""
    n_total = cfg.batch * cfg.seq
    mb = tokens.shape[0] // cfg.microbatches

    def loss_fn(p, tok, tgt):
        return _token_xent_sum(_forward_device(cfg, p, tok),
                               tgt) / n_total

    grads, loss_sum = None, jnp.float32(0)
    for i in range(cfg.microbatches):
        sl = slice(i * mb, (i + 1) * mb)
        li, gi = jax.value_and_grad(loss_fn)(
            params, tokens[sl], targets[sl])
        loss_sum = loss_sum + li
        grads = gi if grads is None else jax.tree.map(jnp.add, grads, gi)

    # cp ranks hold distinct tokens: exact psum. tp needs NO reduction
    # (the Megatron f-operator already made grads tp-replicated).
    grads = jax.tree.map(lambda g: jax.lax.psum(g, "cp"), grads)
    if cfg.dp > 1:
        if wire is None:
            grads = jax.tree.map(lambda g: jax.lax.psum(g, "dp"), grads)
        else:
            # SR seed varies per step (fold the Adam step count) so the
            # rounding noise is independent across steps
            grads = grad_wire.grad_tree_allreduce(
                grads, "dp", n=cfg.dp, wire=wire,
                seed=base_seed + opt["t"], ef=cfg.ef)
    loss = jax.lax.psum(loss_sum, ("dp", "cp"))
    params, opt = _adam(cfg, params, grads, opt)
    return params, opt, loss


@functools.lru_cache(maxsize=32)
def _train_step_fn(cfg: TrainConfig, mesh: Mesh, wire, base_seed: int):
    """The jitted distributed step, cached per (cfg, mesh, wire). The
    ``wire=None`` entry is the XLA psum twin the ledger demotes to.
    Params and optimizer state are donated."""
    pspec = _param_specs(cfg)
    ospec = {"t": P(), "m": pspec, "v": pspec}
    data = P("dp", "cp")
    body = functools.partial(_device_step, cfg, wire, base_seed)
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(pspec, ospec, data, data),
        out_specs=(pspec, ospec, P()),
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=(0, 1))


# ---------------------------------------------------------- reference


@functools.lru_cache(maxsize=8)
def _reference_fn(cfg: TrainConfig):
    """Single-device dense twin of the distributed step: dense
    attention over the full sequence, unsharded MLP, exact f32 grads,
    same microbatch accumulation and Adam. The loss-delta pins in
    tests/bench compare against this."""

    def loss_fn(p, tok, tgt):
        x = p["embed"][tok]
        b, s, d = x.shape
        h, dh = cfg.n_heads, d // cfg.n_heads

        def heads(w):
            return (x @ w).reshape(b, s, h, dh)

        o = dense_attention_reference(
            heads(p["wq"]), heads(p["wk"]), heads(p["wv"]),
            causal=True)
        x = x + o.reshape(b, s, d) @ p["wo"]
        x = x + jax.nn.gelu(x @ p["w1"]) @ p["w2"]
        return _token_xent_sum(x @ p["head"],
                               tgt) / (cfg.batch * cfg.seq)

    def body(params, opt, tokens, targets):
        mb = tokens.shape[0] // cfg.microbatches
        grads, loss_sum = None, jnp.float32(0)
        for i in range(cfg.microbatches):
            sl = slice(i * mb, (i + 1) * mb)
            li, gi = jax.value_and_grad(loss_fn)(
                params, tokens[sl], targets[sl])
            loss_sum = loss_sum + li
            grads = gi if grads is None \
                else jax.tree.map(jnp.add, grads, gi)
        params, opt = _adam(cfg, params, grads, opt)
        return params, opt, loss_sum

    return jax.jit(body)


def train_step_reference(params, opt_state, tokens, targets,
                         cfg: TrainConfig):
    """One single-device reference step → (params, opt_state, loss)."""
    return _reference_fn(cfg)(
        params, opt_state,
        jnp.asarray(tokens, jnp.int32), jnp.asarray(targets, jnp.int32))


# ------------------------------------------------------------ trainer


class Trainer:
    """Stateful dp×tp×cp trainer with ledger-driven wire degradation.

    Owns placed params + Adam state and a step counter. Every step runs
    the jitted distributed step under a host-mode watchdog heartbeat at
    site ``"grad_ring"``; a trip (or a recorded kernel error) demotes
    the dp gradient sync from the quantized ring to the exact XLA psum
    twin, and the HealthLedger's probation schedule re-promotes it
    through clean probes — the serving engine's degradation contract,
    applied to training.
    """

    def __init__(self, cfg: TrainConfig, mesh: Mesh | None = None,
                 health=None):
        from triton_distributed_tpu.runtime.health import HealthLedger

        self.cfg = cfg
        self.mesh = mesh if mesh is not None else default_train_mesh(cfg)
        for ax in ("dp", "tp", "cp"):
            if self.mesh.shape.get(ax) != getattr(cfg, ax):
                raise ValueError(
                    f"mesh axis {ax!r} is {self.mesh.shape.get(ax)}, "
                    f"TrainConfig wants {getattr(cfg, ax)}")
        self.health = health if health is not None \
            else HealthLedger(seed=cfg.seed)

        pspec = _param_specs(cfg)
        params = init_params(cfg)
        self.params = {
            k: jax.device_put(v, NamedSharding(self.mesh, pspec[k]))
            for k, v in params.items()
        }
        opt = init_opt_state(self.params)
        put = lambda tree: {
            k: jax.device_put(v, NamedSharding(self.mesh, pspec[k]))
            for k, v in tree.items()
        }
        self.opt_state = {
            "t": jax.device_put(opt["t"],
                                NamedSharding(self.mesh, P())),
            "m": put(opt["m"]),
            "v": put(opt["v"]),
        }

        # the grad slab's geometry decides wire eligibility up front —
        # a pinned-but-illegal wire refuses HERE, not mid-training
        total = sum(int(np.prod(v.shape)) for v in params.values())
        rows = -(-total // 128)
        rows += (-rows) % cfg.dp
        self.slab_rows = rows
        self.wire = grad_wire.resolve_grad_wire(
            cfg.wire_dtype, rows, 128, cfg.dp)
        self.base_seed = grad_wire.derive_seed(cfg.seed, "train.dp_ring")

        self.use_wire = self.wire is not None
        self.degraded = False
        self.repromotions = 0
        self.step_count = 0

    # -- data ---------------------------------------------------------

    def make_batch(self, step: int):
        """Deterministic synthetic LM batch for step ``step``:
        (tokens, targets) of shape (batch, seq) int32, targets the
        next token (sequence rolled left)."""
        rng = np.random.RandomState(
            (self.cfg.seed * 100003 + step) % (2 ** 31 - 1))
        tokens = rng.randint(
            0, self.cfg.vocab,
            size=(self.cfg.batch, self.cfg.seq)).astype(np.int32)
        return tokens, np.roll(tokens, -1, axis=1).astype(np.int32)

    # -- stepping -----------------------------------------------------

    def _run(self, tokens, targets) -> np.ndarray:
        from triton_distributed_tpu.lang.launch import maybe_instrument

        wire = self.wire if self.use_wire else None
        fn = _train_step_fn(self.cfg, self.mesh, wire, self.base_seed)
        # host-mode heartbeat: an armed watchdog sees a wedged step, a
        # fault-plan Stall(site="grad_ring") gates here
        step_fn = maybe_instrument(
            fn, axis=None, site=_SITE,
            collective_id=(_SITE, _PEER), n=1, step=self.step_count)
        sh = NamedSharding(self.mesh, P("dp", "cp"))
        tok = jax.device_put(jnp.asarray(tokens, jnp.int32), sh)
        tgt = jax.device_put(jnp.asarray(targets, jnp.int32), sh)
        self.params, self.opt_state, loss = step_fn(
            self.params, self.opt_state, tok, tgt)
        return np.asarray(loss)          # host fetch = the fence

    def step(self, tokens=None, targets=None) -> dict:
        """One train step (synthesizing a batch when none is given).
        Returns a small report: loss, the wire actually used, and the
        degradation flags."""
        from triton_distributed_tpu.runtime.health import PeerState

        if tokens is None:
            tokens, targets = self.make_batch(self.step_count)
        wire_avail = self.wire is not None
        if self.use_wire \
                and self.health.state(_PEER) is PeerState.UNHEALTHY:
            # the ledger condemned the ring out-of-band (a watchdog
            # trip's broadcast): demote before launching
            self.use_wire = False
            self.degraded = True
        probing = (wire_avail and not self.use_wire
                   and self.health.probe_due(_PEER, self.step_count))
        if probing:
            self.use_wire = True
        try:
            loss = self._run(tokens, targets)
        except Exception:
            if not self.use_wire:
                raise
            # degradation: retry the SAME step on the exact psum twin.
            # A probe failure drops straight back to UNHEALTHY; a first
            # failure is fatal (kernel_error) so re-entry to the ring
            # only ever happens through clean probes.
            if probing:
                self.health.probe_result(_PEER, False,
                                         step=self.step_count)
            else:
                self.health.record("kernel_error", _PEER,
                                   step=self.step_count)
            self.use_wire = False
            self.degraded = True
            loss = self._run(tokens, targets)
        else:
            if probing:
                st = self.health.probe_result(_PEER, True,
                                              step=self.step_count)
                if st is PeerState.HEALTHY:
                    # enough clean probes: stay on the ring
                    self.degraded = False
                    self.repromotions += 1
                else:
                    self.use_wire = False   # keep earning probes
            elif self.degraded and not self.use_wire:
                # clean degraded steps earn PROBATION (and clear a
                # non-fatal SUSPECT straight back to HEALTHY)
                st = self.health.observe_clean(_PEER,
                                               step=self.step_count)
                if st is PeerState.HEALTHY:
                    self.use_wire = wire_avail
                    self.degraded = False
                    self.repromotions += 1
        report = {
            "step": self.step_count,
            "loss": float(loss),
            "wire": self.wire if self.use_wire else None,
            "degraded": self.degraded,
            "probing": probing,
        }
        self.step_count += 1
        return report

    def run(self, steps: int) -> list:
        """Run ``steps`` synthetic-batch steps, returning the reports."""
        return [self.step() for _ in range(steps)]

    # -- reporting ----------------------------------------------------

    def wire_report(self) -> dict:
        """Analytic per-step dp-ring wire bytes (one rank): the bf16
        baseline vs the resolved wire, and their ratio."""
        bf16 = grad_wire.ring_wire_bytes(
            self.slab_rows, 128, self.cfg.dp, None)
        wired = grad_wire.ring_wire_bytes(
            self.slab_rows, 128, self.cfg.dp, self.wire)
        return {
            "slab_rows": self.slab_rows,
            "bf16_bytes": bf16,
            "wire_bytes": wired,
            "ratio": (bf16 / wired) if wired else math.nan,
        }
