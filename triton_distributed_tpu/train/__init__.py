"""Training subsystem: wire-quantized gradient rings + dp×tp×cp step.

The reference leaves training parallelism to torch.distributed; here
the wire/lint/schedule/health stack extends to the backward pass:

* :mod:`~triton_distributed_tpu.train.grad_wire` — error-feedback +
  seeded stochastic-rounding quantized gradient rings (the backward
  duals' wire, ``OverlapContext(bwd_wire_dtype=...)``) and the dp
  gradient all-reduce.
* :mod:`~triton_distributed_tpu.train.step` — the dp×tp×cp train step
  (ring-attention CP, Megatron TP, quantized dp grad ring, Adam,
  gradient accumulation) with HealthLedger degradation/probation on
  the grad ring.
"""

from triton_distributed_tpu.train.grad_wire import (
    GRAD_RING_COLLECTIVE_ID,
    derive_seed,
    ef_ag_gemm,
    ef_gemm_rs,
    ef_ring_reduce_scatter,
    grad_allreduce_device,
    grad_allreduce_xla,
    grad_tree_allreduce,
    quantized_allgather,
    resolve_grad_wire,
    ring_wire_bytes,
    tree_slab,
)
from triton_distributed_tpu.train.step import (
    TRAIN_ENGINE_FAMILIES,
    TrainConfig,
    Trainer,
    train_step_reference,
)

__all__ = [
    "GRAD_RING_COLLECTIVE_ID",
    "TRAIN_ENGINE_FAMILIES",
    "TrainConfig",
    "Trainer",
    "derive_seed",
    "ef_ag_gemm",
    "ef_gemm_rs",
    "ef_ring_reduce_scatter",
    "grad_allreduce_device",
    "grad_allreduce_xla",
    "grad_tree_allreduce",
    "quantized_allgather",
    "resolve_grad_wire",
    "ring_wire_bytes",
    "train_step_reference",
    "tree_slab",
]
