"""Wire-quantized gradient rings with per-hop error feedback.

The forward/serving path compresses its rings through ``lang.wire``
(fp8/int8 payload + scale rails); the backward duals used to be pinned
bf16 ("gradient rings stay exact", PR 3). This module is the training
half of the wire story: XLA ``ppermute`` rings that ship 1-byte
gradient payloads with two numerics guards the forward wire never
needed —

* **Seeded stochastic rounding** (``lang.wire.quantize_slab_sr``): the
  int8 grid rounds ``floor(y + u)``, ``u ~ U[0,1)`` from a key derived
  deterministically from ``(seed, config.interp_key(), rank, hop)`` —
  unbiased per element, bit-identical under the same seed, so a
  replayed step requantizes exactly.
* **Per-hop error feedback**: the reduce ring quantizes a NEW partial
  sum every hop, so plain rounding injects up to n-1 independent
  errors per link. Here each rank carries the f32 residual
  ``outgoing - dequant(quant(outgoing))`` and folds it into the NEXT
  hop's outgoing slab before quantizing — so the total a rank ships
  down its link TELESCOPES: ``sum_t dequant(q_t) = sum_t outgoing_t -
  resid_last``, exact up to ONE final residual instead of n-1
  accumulated roundings. What this bounds is the aggregate
  (stripe-summed) gradient error — the gradient mass a link delivers —
  which stays O(1) in hop count where the no-EF control grows with
  n-1. The PER-ELEMENT error is dominated by the unbiased SR noise
  either way (EF cannot beat independent-noise variance inside a
  single reduction — its job is killing the accumulated drift). The
  property tests in tests/test_train.py pin exactly this split:
  aggregate error strictly below the no-EF control and sublinear in
  hops; per-element error bounded vs the bf16 reference.

The ring layout mirrors :func:`~triton_distributed_tpu.kernels.
reduce_scatter.reduce_scatter_xla`'s wire branch (per-hop quantize →
``ppermute`` payload+scales → f32 dequant-accumulate), with COMPACT
(ch, 1) scale columns on the wire — both ends are ours, so the lane-
replicated (ch, 128) plane the Pallas rails need would be 128× wasted
ppermute bytes here. The all-gather half quantizes ONCE at the source
and forwards verbatim (one rounding total, no feedback needed), and
every rank — owners included — consumes the DEQUANTIZED bytes, so the
replicated optimizer states stay bit-identical across data-parallel
ranks after the sync.

The Pallas twin of this ring (lint/preflight evidence, RingSchedule
threading, SL008/SL009 coverage) is ``kernels.cp_ring``'s
``grad_ring.stream_int8w`` family; production training steps off-TPU
run these XLA rings, degrading to :func:`grad_allreduce_xla` (plain
``psum``) when the grad-ring site is condemned by the health ledger.
"""

from __future__ import annotations

import functools
import zlib

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.lang import wire as wirelib

_SITE = "grad_ring"

#: collective id of the dp gradient ring (the cp_ring lint family's id).
GRAD_RING_COLLECTIVE_ID = 17


# ------------------------------------------------------------- resolve

def resolve_grad_wire(wire_dtype, rows: int, cols: int,
                      n: int) -> str | None:
    """The wire format a gradient ring will ACTUALLY ship for an
    (rows, cols) per-rank f32 slab reduced over ``n`` ranks — the
    ``resolve_*_wire`` contract of the forward ops applied to the
    backward:

    * ``None``/``'bf16'`` → None (raw wire, today's exact rings);
    * ``'auto'`` → 'int8' when the slab admits the ring layout and the
      compressed bytes actually win, else a SILENT demotion to None;
    * a pinned ``'fp8'``/``'int8'`` that cannot be carried RAISES — a
      pinned wire format is a contract, not a hint.

    'int8-mxu' demotes to its 'int8' payload (these rings dequantize
    before any MXU sees the bytes, like the DCN rail legs)."""
    w = wirelib.normalize_wire(wire_dtype)
    if w is None:
        return None
    if n <= 1:
        return None if w == "auto" else wirelib.wire_payload(w)
    eligible = (
        rows % n == 0
        and rows // n >= 1
        # payload + compact scale column must beat the bf16 wire
        and (rows // n) * cols * 1 + (rows // n) * 4
        < (rows // n) * cols * 2
    )
    if w == "auto":
        return "int8" if eligible else None
    payload = wirelib.wire_payload(w)
    if not eligible:
        raise ValueError(
            f"grad ring wire_dtype={w!r}: slab ({rows}, {cols}) over "
            f"n={n} admits no legal wire chunking (a pinned wire format "
            "is a contract); use wire_dtype='auto' or the bf16 wire"
        )
    return payload


def _fmt(wire: str) -> wirelib.WireFormat:
    # per-ROW scales (chunk_rows=1): the KV-pool / VMEM-ring granularity,
    # robust for the arbitrary stripe heights a flattened grad slab has
    return wirelib.WireFormat(quant=wirelib.wire_payload(wire),
                              chunk_rows=1)


def derive_seed(seed: int, *tags) -> int:
    """A 31-bit seed folding the caller's ``seed``, the config/fault
    trace identity (``config.interp_key()`` — re-arming the watchdog or
    changing the fault plan re-derives, exactly like the kernel build
    caches), and any extra ``tags``. Concrete host-side int, so it can
    key the jitted-builder caches."""
    from triton_distributed_tpu.config import interp_key

    return zlib.crc32(repr((int(seed), interp_key(), tags)).encode()) \
        & 0x7FFFFFFF


# ----------------------------------------------------- device-level rings

def _sr_quant_compact(x, fmt, key):
    """quantize_slab_sr → (payload, COMPACT (ch, 1) f32 scale column) —
    the XLA-ring wire (both ends ours; the 128-lane replication is a
    Pallas blocking requirement, not a numerics one)."""
    q, sc = wirelib.quantize_slab_sr(x, fmt, key)
    return q, sc[:, :1]


def _dequant_compact(q, sc1, fmt):
    rows, cols = q.shape
    ch = fmt.chunks(rows)
    y = q.astype(jnp.float32).reshape(ch, fmt.chunk_rows * cols) * sc1
    return y.reshape(rows, cols)


def ef_ring_reduce_scatter(x, axis, *, n, wire, seed, ef=True):
    """Quantized ring reduce-scatter with error feedback, callable
    inside shard_map over ``axis``.

    ``x``: (n·srows, cols) f32 — stripe ``i`` is this rank's partial
    contribution to the stripe rank ``i`` will own. Returns the fully
    reduced (srows, cols) f32 stripe owned by this rank. ``wire`` must
    be a concrete 'fp8'/'int8' (resolve first); ``ef=False`` is the
    no-feedback control the property tests compare against (see the
    module docstring for what EF does and does not bound: the shipped
    aggregate telescopes to one residual; per-element noise is the
    unbiased SR floor either way)."""
    me = jax.lax.axis_index(axis)
    rows, cols = x.shape
    srows = rows // n
    fmt = _fmt(wire)
    base = jax.random.fold_in(jax.random.PRNGKey(seed), me)
    perm = [(i, (i - 1) % n) for i in range(n)]

    def stripe(i):
        return jax.lax.dynamic_slice_in_dim(x, i * srows, srows).astype(
            jnp.float32
        )

    def hop(h, carry):
        acc, resid = carry
        outgoing = acc + resid
        q, sc1 = _sr_quant_compact(
            outgoing, fmt, jax.random.fold_in(base, h)
        )
        sent = _dequant_compact(q, sc1, fmt)
        resid_next = jnp.where(ef, outgoing - sent, 0.0)
        q = jax.lax.ppermute(q, axis, perm=perm)
        sc1 = jax.lax.ppermute(sc1, axis, perm=perm)
        arrived = _dequant_compact(q, sc1, fmt)
        nxt = jax.lax.rem(me + 2 + h, n)
        return arrived + stripe(nxt), resid_next

    acc0 = stripe(jax.lax.rem(me + 1, n))
    acc, _ = jax.lax.fori_loop(
        0, n - 1, hop, (acc0, jnp.zeros_like(acc0))
    )
    return acc


def quantized_allgather(x, axis, *, n, wire, seed):
    """Quantize-once ring all-gather, callable inside shard_map:
    (srows, cols) f32 per-rank stripe → (n·srows, cols) f32 with every
    stripe dequantized from the SAME shipped bytes on every rank —
    owners included, so replicated consumers (optimizer state) stay
    bit-identical across ranks. One rounding per element total; no
    error feedback needed on the AG side."""
    me = jax.lax.axis_index(axis)
    fmt = _fmt(wire)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), me)
    q, sc1 = _sr_quant_compact(x.astype(jnp.float32), fmt, key)
    q_all = jax.lax.all_gather(q, axis, tiled=True)
    s_all = jax.lax.all_gather(sc1, axis, tiled=True)
    return _dequant_compact(q_all, s_all, fmt)


def grad_allreduce_device(g, axis, *, n, wire, seed, ef=True):
    """Quantized-ring gradient all-reduce (RS + AG halves), callable
    inside shard_map: (rows, cols) f32 per-rank partials → the
    (rows, cols) f32 sum, identical bits on every rank. ``wire=None``
    falls back to the exact ``psum`` (the bf16/raw wire)."""
    if wire is None or n <= 1:
        return jax.lax.psum(g.astype(jnp.float32), axis)
    red = ef_ring_reduce_scatter(
        g, axis, n=n, wire=wire, seed=seed, ef=ef
    )
    return quantized_allgather(
        red, axis, n=n, wire=wire, seed=seed + 1
    )


def tree_slab(grads, n, cols: int = 128):
    """Flatten a gradient pytree into one ring-reducible (rows, cols)
    f32 slab, rows padded to a multiple of ``n``. Returns
    (slab, unflatten) — ``unflatten(slab)`` restores the pytree with
    the original leaf shapes/dtypes."""
    leaves, treedef = jax.tree.flatten(grads)
    sizes = [int(l.size) for l in leaves]
    flat = jnp.concatenate(
        [l.reshape(-1).astype(jnp.float32) for l in leaves]
    )
    total = int(flat.size)
    rows = -(-total // cols)               # ceil
    rows += (-rows) % n
    slab = jnp.pad(flat, (0, rows * cols - total)).reshape(rows, cols)

    def unflatten(s):
        out_flat = s.reshape(-1)[:total]
        outs, off = [], 0
        for leaf, size in zip(leaves, sizes):
            outs.append(
                out_flat[off:off + size].reshape(leaf.shape).astype(
                    leaf.dtype
                )
            )
            off += size
        return jax.tree.unflatten(treedef, outs)

    return slab, unflatten


def grad_tree_allreduce(grads, axis, *, n, wire, seed, ef=True):
    """Pytree all-reduce over the dp axis on the quantized gradient
    ring (device-level): flatten → :func:`grad_allreduce_device` →
    unflatten. The trainer's data-parallel gradient sync."""
    slab, unflatten = tree_slab(grads, n)
    return unflatten(
        grad_allreduce_device(
            slab, axis, n=n, wire=wire, seed=seed, ef=ef
        )
    )


# -------------------------------------------------- host-level dual engines

@functools.lru_cache(maxsize=128)
def _ef_gemm_rs_fn(mesh, axis, batch_axes, out_dtype, wire, seed, ef,
                   ikey=None):
    from triton_distributed_tpu import lang

    ba = tuple(batch_axes)
    n = mesh.shape[axis]

    def body(a_loc, b_loc):
        part = jnp.dot(
            a_loc, b_loc, preferred_element_type=jnp.float32
        )
        red = ef_ring_reduce_scatter(
            part, axis, n=n, wire=wire, seed=seed, ef=ef
        )
        return red.astype(out_dtype)

    body = lang.maybe_instrument(
        body, axis=axis, site=_SITE,
        collective_id=GRAD_RING_COLLECTIVE_ID, n=n,
    )
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(ba if ba else None, axis), P(axis, None)),
        out_specs=P(ba + (axis,) if ba else axis, None),
        check_vma=False,
    )
    return jax.jit(fn)


def ef_gemm_rs(a, b, mesh, axis, *, batch_axes=(), out_dtype=None,
               wire, seed=0, ef=True):
    """GEMM → error-feedback quantized reduce-scatter ring: the
    backward dual engine of ``ag_gemm`` when ``bwd_wire_dtype``
    resolves (dA = GEMM-RS(dC, Bᵀ) on the 1-byte wire). Layout contract
    of ``kernels.gemm_rs``: ``a`` (M, K) rows batch-sharded / cols
    ``axis``-sharded, ``b`` (K, N) rows ``axis``-sharded, out (M, N)
    rows sharded (*batch_axes, axis). ``wire`` must already be resolved
    ('fp8'/'int8')."""
    out_dtype = jnp.dtype(out_dtype or a.dtype)
    return _ef_gemm_rs_fn(
        mesh, axis, tuple(batch_axes), out_dtype, str(wire), int(seed),
        bool(ef), _ikey(),
    )(a, b)


@functools.lru_cache(maxsize=128)
def _ef_ag_gemm_fn(mesh, axis, batch_axes, out_dtype, wire, seed,
                   return_gathered, ikey=None):
    from triton_distributed_tpu import lang

    ba = tuple(batch_axes)
    n = mesh.shape[axis]

    def body(a_loc, b_loc):
        a_full = quantized_allgather(
            a_loc, axis, n=n, wire=wire, seed=seed
        ).astype(a_loc.dtype)
        out = jnp.dot(
            a_full, b_loc, preferred_element_type=jnp.float32
        ).astype(out_dtype)
        if return_gathered:
            return out, a_full
        return out

    body = lang.maybe_instrument(
        body, axis=axis, site=_SITE,
        collective_id=GRAD_RING_COLLECTIVE_ID + 1, n=n,
    )
    out_specs = (P(ba if ba else None, axis), P(ba if ba else None, None)) \
        if return_gathered else P(ba if ba else None, axis)
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(ba + (axis,) if ba else axis, None), P(None, axis)),
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)


def ef_ag_gemm(a, b, mesh, axis, *, batch_axes=(), out_dtype=None,
               wire, seed=0, return_gathered=False):
    """Quantized-allgather → GEMM: the backward dual engine of
    ``gemm_rs`` when ``bwd_wire_dtype`` resolves (dA = AG-GEMM(dC, Bᵀ)
    with dC gathered on the 1-byte wire; ``return_gathered`` hands the
    DEQUANTIZED gathered dC back for the weight gradient, exactly like
    the fused engine's free by-product). Layout contract of
    ``kernels.ag_gemm``."""
    out_dtype = jnp.dtype(out_dtype or a.dtype)
    return _ef_ag_gemm_fn(
        mesh, axis, tuple(batch_axes), out_dtype, str(wire), int(seed),
        bool(return_gathered), _ikey(),
    )(a, b)


def _ikey():
    from triton_distributed_tpu.config import interp_key

    return interp_key()


# ------------------------------------------------------- twin + accounting

def grad_allreduce_xla(g, mesh, axis: str = "x"):
    """Plain ``psum`` all-reduce — the grad ring's degradation target
    (exact, no wire, nothing to deadlock): what a training step runs
    after the health ledger condemns ``site:grad_ring``, until
    probation re-promotes the quantized ring."""
    fn = jax.shard_map(
        lambda x: jax.lax.psum(x, axis), mesh=mesh,
        in_specs=P(), out_specs=P(), check_vma=False,
    )
    return jax.jit(fn)(g)


def ring_wire_bytes(rows: int, cols: int, n: int,
                    wire: str | None) -> int:
    """Analytic wire bytes ONE rank ships for one (rows, cols) slab
    all-reduce on the ring (RS n-1 hops + AG n-1 forwarded stripes):
    the bench row's byte accounting. Compact (ch, 1) scale columns
    (chunk_rows=1 → one f32 per row)."""
    srows = max(rows // max(n, 1), 1)
    hops = 2 * (n - 1)
    if wire in (None, "bf16"):
        return hops * srows * cols * 2
    return hops * (srows * cols * 1 + srows * 4)
