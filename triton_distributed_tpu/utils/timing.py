"""Timing helpers (≡ reference utils.perf_func CUDA-event timing, utils.py:186-198)."""

from __future__ import annotations

import time

import jax
import numpy as np


def device_fence(out):
    """Force completion of everything queued before ``out``.

    ``jax.block_until_ready`` is not a reliable fence behind remote-relay
    backends (observed: it returns before execution on the axon tunnel);
    a host fetch of one element per addressable shard is — execution is
    in-order per device, and every device holding a piece of any output
    leaf gets fetched from, so no device's queue escapes the fence.
    """
    jax.block_until_ready(out)  # correct where it works (CPU, direct TPU)
    for leaf in jax.tree.leaves(out):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for sh in shards:
                if sh.data.size:
                    # single-element slice, NOT ravel(): ravel would copy
                    # the whole shard on-device inside the timed window
                    np.asarray(jax.device_get(sh.data[(0,) * sh.data.ndim]))
        elif hasattr(leaf, "ravel") and getattr(leaf, "size", 0):
            np.asarray(jax.device_get(leaf[(0,) * leaf.ndim]))


def perf_func(fn, *args, iters: int = 10, warmup: int = 3):
    """Return (last_output, mean_ms). Fences device completion each phase.

    XLA has no user-visible event API like CUDA events; wall-clock around
    a device fence on pre-compiled functions is the TPU-standard
    measurement. The per-fetch relay round-trip is a *constant* offset
    amortized over ``iters`` — it shifts every measured config equally,
    so rankings (the autotuner's consumer) survive; absolute numbers for
    reporting should come from bench.py's in-jit loop methodology.
    """
    out = None
    for _ in range(warmup):
        out = fn(*args)
    device_fence(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    device_fence(out)
    t1 = time.perf_counter()
    return out, (t1 - t0) * 1e3 / iters
