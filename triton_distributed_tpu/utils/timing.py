"""Timing helpers (≡ reference utils.perf_func CUDA-event timing, utils.py:186-198)."""

from __future__ import annotations

import time

import jax


def perf_func(fn, *args, iters: int = 10, warmup: int = 3):
    """Return (last_output, mean_ms). Blocks on device completion each call.

    XLA has no user-visible event API like CUDA events; wall-clock around
    ``block_until_ready`` on pre-compiled functions is the TPU-standard
    measurement (dispatch overhead is amortized over ``iters``).
    """
    out = None
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    t1 = time.perf_counter()
    return out, (t1 - t0) * 1e3 / iters
