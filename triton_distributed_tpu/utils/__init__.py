"""Utilities: printing, timing, correctness checks, chaos testing.

Reference equivalent: python/triton_dist/utils.py (dist_print :201,
perf_func :186, assert_allclose :789, chaos-delay allgather.py:72-77).
"""

from triton_distributed_tpu.utils.debug import dist_print
from triton_distributed_tpu.utils.testing import assert_allclose, chaos_delay
from triton_distributed_tpu.utils.timing import perf_func

__all__ = ["dist_print", "perf_func", "assert_allclose", "chaos_delay"]
