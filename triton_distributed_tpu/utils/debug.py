"""Rank-aware printing (≡ reference utils.dist_print, utils.py:201-230)."""

from __future__ import annotations

import sys

import jax


def dist_print(*args, ranks=None, prefix=True, flush=True, file=None, **kwargs):
    """Print only on the given process ranks (default: rank 0).

    ``ranks=None`` → rank 0 only; ``ranks="all"`` → every rank, prefixed.
    """
    rank = jax.process_index()
    if ranks is None:
        allowed = {0}
    elif ranks == "all":
        allowed = set(range(jax.process_count()))
    else:
        allowed = set(ranks)
    if rank not in allowed:
        return
    out = file or sys.stdout
    if prefix and (ranks == "all" or len(allowed) > 1):
        print(f"[rank {rank}]", *args, flush=flush, file=out, **kwargs)
    else:
        print(*args, flush=flush, file=out, **kwargs)
