"""Correctness helpers: allclose with diff dump, chaos delay.

Reference: ``assert_allclose`` with mismatch dump (utils.py:789-820) and the
``for_correctness`` random comm-stream sleep that widens race windows
(allgather.py:72-77,118-121). On TPU the chaos delay is a Pallas in-kernel
delay; the CPU interpreter additionally offers a true race detector
(config.detect_races → InterpretParams(detect_races=True)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.config import config


def assert_allclose(actual, expected, atol=1e-3, rtol=1e-3, verbose=True):
    actual = np.asarray(actual)
    expected = np.asarray(expected)
    if actual.shape != expected.shape:
        raise AssertionError(f"shape mismatch: {actual.shape} vs {expected.shape}")
    close = np.isclose(actual, expected, atol=atol, rtol=rtol)
    if close.all():
        return
    bad = np.argwhere(~close)
    diff = np.abs(actual.astype(np.float64) - expected.astype(np.float64))
    msg = [
        f"allclose failed: {bad.shape[0]}/{actual.size} mismatched "
        f"(atol={atol}, rtol={rtol})",
        f"max |diff| = {diff.max()} at {np.unravel_index(diff.argmax(), diff.shape)}",
    ]
    if verbose:
        for idx in bad[:10]:
            t = tuple(idx)
            msg.append(f"  at {t}: actual={actual[t]} expected={expected[t]}")
    raise AssertionError("\n".join(msg))


def chaos_delay(cycles: int = 100_000, enable: bool | None = None, *,
                site: str | None = None, step: int | None = None,
                me=None, n: int | None = None):
    """In-kernel delay to widen race windows (call inside a Pallas kernel).

    ≡ the reference's ``torch.cuda._sleep`` injection (allgather.py:72-77).

    Two regimes:

    * An active :class:`~triton_distributed_tpu.runtime.faults.FaultPlan`
      owns this hook point: seeded per-(rank, step) delays are injected
      from the plan (``site``/``step``/``me``/``n`` give the plan its
      coordinates; ``me`` is the traced rank index, ``n`` the static
      ring size). The global ``config.chaos_delay`` boolean is ignored.
    * No plan: legacy behaviour — a fixed ``cycles`` delay when
      ``config.chaos_delay`` (or ``enable``) is set, identically on
      every rank.
    """
    from jax.experimental import pallas as pl

    from triton_distributed_tpu.runtime import faults

    if faults.inject_delay(site, step, me, n, cycles):
        return
    on = config.chaos_delay if enable is None else enable
    if on:
        pl.delay(cycles)
