"""triton_distributed_tpu — a TPU-native distributed kernel framework.

A brand-new framework with the capability surface of Triton-distributed
(ByteDance Seed), re-designed idiomatically for TPU on JAX/XLA/Pallas:

- ``runtime``  — bootstrap, mesh/topology discovery, symmetric buffers
                 (the reference's ``pynvshmem`` + ``utils.initialize_distributed``,
                 reference: python/triton_dist/utils.py:91-111).
- ``lang``     — SHMEM-like device-side primitives usable inside Pallas
                 kernels: put/put-with-signal, signal ops, waits, barriers
                 (reference: patches/triton/python/triton/language/extra/
                 libshmem_device.py:28-335).
- ``kernels``  — overlapping collective/compute kernels: AllGather,
                 ReduceScatter, AllToAll, AG-GEMM, GEMM-RS, grouped-GEMM MoE,
                 distributed flash-decode (reference:
                 python/triton_dist/kernels/nvidia/).
- ``layers``   — NN-module-level wrappers (reference:
                 python/triton_dist/layers/nvidia/).
- ``models``   — flagship model definitions exercising the layers.
- ``ops``      — stable functional entry points (ag_gemm, gemm_rs, ...);
                 the TP/EP/SP/DP sharding plans live here and in
                 ``runtime`` (mesh construction).
- ``tune``     — distributed-consensus autotuner (reference:
                 python/triton_dist/autotuner.py).
- ``tools``    — AOT compile and profiling tools.
- ``utils``    — dist_print, timing, allclose, chaos-delay testing helpers.
"""

from triton_distributed_tpu.version import __version__

__all__ = [
    "__version__",
    "config",
    "runtime",
    "lang",
    "kernels",
    "layers",
    "models",
    "ops",
    "tune",
    "tools",
    "utils",
]


def __getattr__(name):
    if name in __all__ and name != "__version__":
        import importlib

        return importlib.import_module(f"triton_distributed_tpu.{name}")
    raise AttributeError(f"module 'triton_distributed_tpu' has no attribute {name!r}")
