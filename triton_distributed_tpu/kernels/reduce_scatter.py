"""ReduceScatter engines.

Reference: python/triton_dist/kernels/nvidia/reduce_scatter.py — 2D
scatter+ring_reduce pipeline with dedicated streams (:46-181, :692-861)
and 1D ring variants (:287-523).

TPU re-design: a reduce ring over ICI. At step s each device sends its
partial accumulation of shard ``(me+1+s)`` to its *left* neighbor while
receiving the partial of shard ``(me+2+s)`` from the right, adding its own
contribution; after n-1 steps device ``me`` holds the fully-reduced shard
``me``. The add runs on the VPU between DMAs — compute/comm overlap within
the kernel replaces the reference's multi-stream orchestration.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import lang
from triton_distributed_tpu.config import fused_vmem_budget, interp_key
from triton_distributed_tpu.lang import wire as wirelib
from triton_distributed_tpu.runtime import ring_neighbors
from triton_distributed_tpu.utils.testing import chaos_delay


def ring_reduce_core(
    n, axis, mesh_axes, make_partial, out_ref, acc_ref, recv_ref, send_sem, recv_sem, ack_sem
):
    """Reduce ring with explicit flow control, parametrized over the
    per-destination contribution producer.

    ``make_partial(dst)`` returns this device's contribution to destination
    shard ``dst``; it is invoked *between* a slot DMA's start and wait, so a
    compute-heavy producer (e.g. the GEMM-RS matmul) overlaps the transfer.

    The receive buffer is double-buffered and the consumer acks its sender
    (my *right* neighbor, since data flows leftward) after folding a slot
    into the accumulator; a sender re-uses a slot only after the ack for
    its previous use. Without the ack, a fast sender two steps ahead could
    overwrite a slot the receiver hasn't consumed (semaphore credits alone
    don't stop that — they count arrivals, not consumption)."""
    me = lang.my_pe(axis)
    left, right = ring_neighbors(me, n)
    left, right = lang.pe_flat(axis, left, mesh_axes), lang.pe_flat(axis, right, mesh_axes)

    lang.neighbor_barrier(axis, left, right, site="reduce_scatter", me=me, n=n)

    # acc starts as my contribution to shard (me+1), the first one I forward.
    acc_ref[:] = make_partial(jax.lax.rem(me + 1, n))

    for s in range(n - 1):
        chaos_delay(site="reduce_scatter", step=s, me=me, n=n)
        if s >= 2:
            # left must have consumed my slot (s-2) before I rewrite it
            pltpu.semaphore_wait(ack_sem, 1)
        dma = lang.remote_copy(
            acc_ref,
            recv_ref.at[s % 2],
            send_sem.at[s % 2],
            recv_sem.at[s % 2],
            left,
        )
        dma.start()
        # produce my contribution to the next destination while the
        # accumulator is in flight
        nxt = jax.lax.rem(me + 2 + s, n)
        partial = make_partial(nxt)
        dma.wait()  # send drained (acc reusable) + my slot s%2 arrival landed
        # received: partial sum of shard (me+2+s) accumulated so far by the
        # ring to my right; fold in my own contribution.
        acc_ref[:] = recv_ref[s % 2] + partial
        # tell my sender (right neighbor) this slot is free again
        lang.signal_op(ack_sem, 1, pe=right)

    out_ref[:] = acc_ref[:]
    # drain leftover acks: n-1 received, max(n-3, 0) consumed in-loop
    pltpu.semaphore_wait(ack_sem, min(2, n - 1))


def _ring_rs_kernel(n, axis, mesh_axes, x_ref, out_ref, acc_ref, recv_ref, send_sem, recv_sem, ack_sem):
    m = out_ref.shape[0]
    ring_reduce_core(
        n,
        axis,
        mesh_axes,
        lambda dst: x_ref[pl.ds(dst * m, m)],
        out_ref,
        acc_ref,
        recv_ref,
        send_sem,
        recv_sem,
        ack_sem,
    )


def _ring_rs_kernel_w(
    n, axis, mesh_axes, quant,
    x_ref, out_ref,
    acc_ref, qbuf_ref, sbuf_ref, recvq_ref, recvs_ref,
    send_sem, recv_sem, s_send_sem, s_recv_sem, ack_sem,
):
    """Quantized-wire twin of :func:`_ring_rs_kernel` (VMEM-resident):
    each hop's partial accumulation is quantized per ROW (lang.wire,
    chunk_rows=1) into the 1-byte ``qbuf`` + f32 scale plane and both
    rails flow leftward; the receive side dequant-accumulates in f32.
    Same ack-credit flow control as ring_reduce_core (a sender may not
    rewrite a recv slot its receiver hasn't folded)."""
    me = lang.my_pe(axis)
    m = out_ref.shape[0]
    left, right = ring_neighbors(me, n)
    left = lang.pe_flat(axis, left, mesh_axes)
    right = lang.pe_flat(axis, right, mesh_axes)

    lang.neighbor_barrier(axis, left, right, site="reduce_scatter", me=me, n=n)
    acc_ref[:] = x_ref[pl.ds(jax.lax.rem(me + 1, n) * m, m)]

    for s in range(n - 1):
        chaos_delay(site="reduce_scatter", step=s, me=me, n=n)
        if s >= 2:
            pltpu.semaphore_wait(ack_sem, 1)
        # per-row symmetric quantization of the outgoing partial
        wirelib.quant_rows_into(qbuf_ref, sbuf_ref, acc_ref, quant)
        dma_q = lang.remote_copy(
            qbuf_ref, recvq_ref.at[s % 2],
            send_sem.at[s % 2], recv_sem.at[s % 2], left,
        )
        dma_s = lang.remote_copy(
            sbuf_ref, recvs_ref.at[s % 2],
            s_send_sem.at[s % 2], s_recv_sem.at[s % 2], left,
        )
        dma_q.start()
        dma_s.start()
        nxt = jax.lax.rem(me + 2 + s, n)
        dma_q.wait()   # send drained (qbuf reusable) + arrival landed
        dma_s.wait()
        wirelib.dequant_add_rows_into(
            acc_ref, recvq_ref.at[s % 2], recvs_ref.at[s % 2],
            x_ref.at[pl.ds(nxt * m, m)],
        )
        lang.signal_op(ack_sem, 1, pe=right)

    out_ref[:] = acc_ref[:]
    pltpu.semaphore_wait(ack_sem, min(2, n - 1))


def _rs_stream_kernel(
    n, axis, mesh_axes, schedule, x_hbm, out_hbm, w0, w1, r0, r1,
    copy_sem, send_sem, recv_sem, ack_sem,
):
    """HBM-streaming reduce ring: each destination's contribution is
    DMA'd straight from the HBM input into the ring slabs (no
    whole-payload VMEM residency — RS at activation-scale payloads); the
    fold-in add streams tiles through VMEM. Protocol: kernels/ring.py."""
    from triton_distributed_tpu.kernels.gemm_rs import ew_add_pipeline
    from triton_distributed_tpu.kernels.ring import reduce_ring

    m = out_hbm.shape[0]

    def partial_into(dst, dst_ref):
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(dst * m, m)], dst_ref, copy_sem
        )
        cp.start()
        cp.wait()

    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1), (r0, r1),
        send_sem, recv_sem, ack_sem, partial_into,
        ew_add_pipeline(m, out_hbm.shape[1], out_hbm.dtype.itemsize),
        schedule=schedule,
    )


def _rs_stream_kernel3(
    n, axis, mesh_axes, schedule, x_hbm, out_hbm, w0, w1, w2, r0, r1, r2,
    copy_sem, send_sem, recv_sem, ack_sem,
):
    """Triple-buffered twin of :func:`_rs_stream_kernel` (schedule depth
    3): identical protocol with one extra in-flight slot of slack — the
    ack credit arrives at ``s >= 3`` instead of ``s >= 2``."""
    from triton_distributed_tpu.kernels.gemm_rs import ew_add_pipeline
    from triton_distributed_tpu.kernels.ring import reduce_ring

    m = out_hbm.shape[0]

    def partial_into(dst, dst_ref):
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(dst * m, m)], dst_ref, copy_sem
        )
        cp.start()
        cp.wait()

    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1, w2), (r0, r1, r2),
        send_sem, recv_sem, ack_sem, partial_into,
        ew_add_pipeline(m, out_hbm.shape[1], out_hbm.dtype.itemsize),
        schedule=schedule,
    )


def _rs_stream_kernel_w(
    n, axis, mesh_axes, fmt, schedule,
    x_hbm, out_hbm, w0, w1,
    wq0, wq1, ws0, ws1, rq0, rq1, rs0, rs1,
    copy_sem, send_sem, recv_sem, ack_sem, s_send_sem, s_recv_sem,
):
    """Quantized-wire twin of :func:`_rs_stream_kernel` — the last bf16
    leg of the standalone RS family (ROADMAP PR-3 follow-on): the
    HBM-streaming reduce ring now ships each hop's partial as a 1-byte
    payload + per-chunk f32 scale plane (the fused gemm_rs wire
    kernel's exact shape: per-hop quant_pipeline into the wq/ws rails,
    f32 dequant-accumulate on receive — one bounded rounding per hop).
    The bf16 recv slabs are gone; arrivals land in the 1-byte rq slabs."""
    from triton_distributed_tpu.kernels.ring import RSWireRefs, reduce_ring

    m = out_hbm.shape[0]
    cols = out_hbm.shape[1]

    def partial_into(dst, dst_ref):
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(dst * m, m)], dst_ref, copy_sem
        )
        cp.start()
        cp.wait()

    wire = RSWireRefs(
        fmt=fmt, wq=(wq0, wq1), ws=(ws0, ws1), rq=(rq0, rq1), rs=(rs0, rs1),
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        quantize=wirelib.quant_pipeline(m, cols, fmt),
        dequant_add=wirelib.dequant_add_pipeline(m, cols, fmt),
    )
    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1), (None, None),
        send_sem, recv_sem, ack_sem, partial_into, None, wire=wire,
        schedule=schedule,
    )


def _rs_stream_kernel_w3(
    n, axis, mesh_axes, fmt, schedule,
    x_hbm, out_hbm, w0, w1, w2,
    wq0, wq1, wq2, ws0, ws1, ws2, rq0, rq1, rq2, rs0, rs1, rs2,
    copy_sem, send_sem, recv_sem, ack_sem, s_send_sem, s_recv_sem,
):
    """Triple-buffered twin of :func:`_rs_stream_kernel_w` (schedule
    depth 3): every wire rail grows a third slot."""
    from triton_distributed_tpu.kernels.ring import RSWireRefs, reduce_ring

    m = out_hbm.shape[0]
    cols = out_hbm.shape[1]

    def partial_into(dst, dst_ref):
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(dst * m, m)], dst_ref, copy_sem
        )
        cp.start()
        cp.wait()

    wire = RSWireRefs(
        fmt=fmt, wq=(wq0, wq1, wq2), ws=(ws0, ws1, ws2),
        rq=(rq0, rq1, rq2), rs=(rs0, rs1, rs2),
        s_send_sem=s_send_sem, s_recv_sem=s_recv_sem,
        quantize=wirelib.quant_pipeline(m, cols, fmt),
        dequant_add=wirelib.dequant_add_pipeline(m, cols, fmt),
    )
    reduce_ring(
        n, axis, mesh_axes, out_hbm, (w0, w1, w2), (None, None, None),
        send_sem, recv_sem, ack_sem, partial_into, None, wire=wire,
        schedule=schedule,
    )


@functools.lru_cache(maxsize=256)
def _build_rs_stream_w(mesh, axis, rows, cols, dtype, stacked,
                       collective_id, ikey, wire, schedule=None):
    """Quantized-wire HBM-streaming reduce ring (2-D payloads, per-chunk
    scales — the lang.wire streaming layout of the fused gemm_rs wire)."""
    from triton_distributed_tpu.config import compiling_for_tpu

    wirelib.require_inkernel(wire, "reduce_scatter")
    n = mesh.shape[axis]
    m_local = rows // n
    d = 2 if schedule is None else int(schedule.depth)
    fmt = wirelib.make_wire_format(wire, m_local, strict=compiling_for_tpu())
    assert fmt is not None, (wire, m_local)   # gated by the entry
    slab = jax.ShapeDtypeStruct((m_local, cols), dtype)
    qslab = jax.ShapeDtypeStruct((m_local, cols), fmt.wire_dtype)
    sslab = jax.ShapeDtypeStruct(
        (fmt.chunks(m_local), wirelib.SCALE_LANES), jnp.float32
    )
    kernel = _rs_stream_kernel_w if d == 2 else _rs_stream_kernel_w3
    call = lang.shmem_call(
        functools.partial(
            kernel, n, axis, mesh.axis_names, fmt, schedule
        ),
        # out + bf16 work slots + quantized work/scale + recv/scale slots
        # (HBM workspaces ride as ANY outputs — Mosaic has no HBM scratch)
        out_shape=[slab] + [slab] * d
                  + [qslab] * d + [sslab] * d
                  + [qslab] * d + [sslab] * d,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (1 + 5 * d),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((d,)),
            pltpu.SemaphoreType.DMA((d,)),
            pltpu.SemaphoreType.REGULAR,
            pltpu.SemaphoreType.DMA((d,)),   # scale rail
            pltpu.SemaphoreType.DMA((d,)),
        ],
        collective_id=collective_id,
        name=f"rs_ring_stream_{wire}w",
    )
    call = lang.maybe_instrument(
        call, axis=axis, site="reduce_scatter", collective_id=collective_id,
        n=n,
    )
    body = (lambda s: call(s[0])[0]) if stacked else (lambda s: call(s)[0])
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=P(axis) if stacked else P(None),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def _build_rs_stream(mesh, axis, rows, cols, dtype, stacked, collective_id,
                     ikey, schedule=None):
    n = mesh.shape[axis]
    d = 2 if schedule is None else int(schedule.depth)
    slab = jax.ShapeDtypeStruct((rows // n, cols), dtype)
    kernel = _rs_stream_kernel if d == 2 else _rs_stream_kernel3
    call = lang.shmem_call(
        functools.partial(kernel, n, axis, mesh.axis_names, schedule),
        # ring slabs ride as extra ANY outputs (Mosaic has no HBM scratch)
        out_shape=[slab] * (1 + 2 * d),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (1 + 2 * d),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((d,)),
            pltpu.SemaphoreType.DMA((d,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        collective_id=collective_id,
        name="rs_ring_stream",
    )
    body = (lambda s: call(s[0])[0]) if stacked else (lambda s: call(s)[0])
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=P(axis) if stacked else P(None),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(fn)


def _vmem_ring_fits(n, local_shape, itemsize) -> bool:
    """The VMEM ring keeps the whole per-device contribution + acc + two
    recv slots resident; prefer it for small payloads (lower latency),
    stream through HBM otherwise."""
    slab = int(np.prod(local_shape)) * itemsize
    return (n + 3) * slab <= fused_vmem_budget() // 2


def _streamable(m_local: int, cols: int, itemsize: int) -> bool:
    """The streaming engine's fold-in add needs a TPU-lowerable divisor
    blocking of the (m_local, cols) slab (≡ gemm_rs's pick_mm_blocks
    guard); shapes without one must stay on the VMEM ring rather than
    crash at Mosaic trace time."""
    from triton_distributed_tpu.config import compiling_for_tpu
    from triton_distributed_tpu.kernels.ag_gemm import _divisor_block

    strict = compiling_for_tpu()
    return (
        _divisor_block(m_local, 512, 8 * (4 // itemsize), strict) is not None
        and _divisor_block(cols, 2048, 128, strict) is not None
    )


def _resolve_rs_wire(wire_dtype, rows, cols, n, itemsize):
    """The wire :func:`reduce_scatter` will actually ship: None unless
    the payload reshapes to 2-D columns wide enough that the per-row
    scale plane saves bytes. 'auto' uses the standalone-ring byte
    threshold (a reduce ring is pure comm, like a gather). 'int8-mxu'
    carries its int8 payload — a reduce ring accumulates, it has no MXU
    consumer to fold scales into."""
    w = wirelib.wire_payload(wirelib.normalize_wire(wire_dtype))
    if w is None:
        return None
    eligible = rows % n == 0 and cols * itemsize > cols + wirelib.SCALE_LANES * 4
    if w == "auto":
        if not eligible:
            return None
        from triton_distributed_tpu.runtime.topology import (
            auto_allgather_wire,
        )

        return auto_allgather_wire((rows // n) * cols * itemsize)
    if not eligible:
        raise ValueError(
            f"reduce_scatter wire_dtype={w!r} needs a 2-D-reshapeable "
            f"payload with cols·itemsize > cols + "
            f"{wirelib.SCALE_LANES * 4} (a pinned wire format is a "
            f"contract); got rows={rows} cols={cols} itemsize={itemsize}"
        )
    return w


def reduce_scatter(
    x, mesh, axis: str = "x", *, stacked: bool = False, collective_id: int = 3,
    wire_dtype=None, schedule=None,
):
    """ReduceScatter: sums per-device (M, ...) contributions and scatters the
    row-shards along ``axis``.

    ``stacked=False``: ``x`` is a replicated (M, ...) array (every device
    contributes the same values). ``stacked=True``: ``x`` is (n, M, ...)
    sharded on dim 0 — device i contributes slice ``x[i]`` (the normal case,
    e.g. partial GEMM outputs).

    Two engines by payload size: the VMEM-resident ring (low latency) and
    the HBM-streaming ring (no VMEM cap — activation-scale payloads;
    trailing dims ride as a free 2D view of the contiguous array).

    ``wire_dtype``: quantized ring wire ('fp8'/'int8' — per-hop
    quantized partials with f32 scales, f32 dequant-accumulate; 'auto'
    — compressed above the standalone-ring byte threshold). Carried by
    the VMEM ring (per-row scales), the HBM-streaming engine (per-chunk
    scales via the fused gemm_rs wire pipelines — round 8) and the XLA
    twin; only payloads too ragged to stream fall back to the bf16
    wire.

    ``schedule``: an explicit :class:`~triton_distributed_tpu.tune.schedule.
    RingSchedule` for the HBM-streaming engines (``None`` loads any
    persisted searched winner, falling back to the canonical default).
    The VMEM rings ignore it — they have no streaming schedule to vary.

    Host entry ≡ reference ``reduce_scatter_2d_op`` (reduce_scatter.py:863).
    """
    from triton_distributed_tpu.config import pallas_collectives_available

    n = mesh.shape[axis]
    full_shape = x.shape[1:] if stacked else x.shape
    rows = full_shape[0]
    cols = int(np.prod(full_shape[1:], dtype=np.int64)) if len(full_shape) > 1 else 1
    if not pallas_collectives_available():
        # off-TPU without the TPU-simulation interpreter: degrade to the
        # XLA-native twin (which carries the wire too)
        if n == 1:
            return x[0] if stacked else x
        return reduce_scatter_xla(
            x, mesh, axis, stacked=stacked,
            wire_dtype=_resolve_rs_wire(
                wire_dtype, rows, cols, n, x.dtype.itemsize
            ),
        )
    if n == 1:
        return x[0] if stacked else x
    assert full_shape[0] % n == 0, f"dim0 {full_shape[0]} not divisible by {n}"
    local_shape = (full_shape[0] // n,) + tuple(full_shape[1:])
    wire = _resolve_rs_wire(wire_dtype, rows, cols, n, x.dtype.itemsize)
    from triton_distributed_tpu.tune.schedule import resolve_schedule

    sched = resolve_schedule(
        "reduce_scatter.stream", (rows, cols), (n,), wire, schedule
    )
    if wire == "fp8" and not wirelib.inkernel_wire_ok("fp8"):
        # the Pallas VMEM ring dequantizes in-kernel; this Mosaic lacks
        # the f8 casts — explicit fp8 raises, auto stays exact
        if wirelib.normalize_wire(wire_dtype) == "fp8":
            wirelib.require_inkernel("fp8", "reduce_scatter")
        wire = None
    if wire is not None:
        # the wire ring is VMEM-resident; its working set is ~half the
        # bf16 ring's (1-byte recv slots), so the same fit gate applies
        if _vmem_ring_fits(n, local_shape, x.dtype.itemsize):
            x2d = x.reshape(((n,) if stacked else ()) + (rows, cols))
            fn = _build_reduce_scatter_w(
                mesh, axis, (rows, cols), x.dtype, stacked, collective_id,
                interp_key(), wire,
            )
            return fn(x2d).reshape(full_shape)
        from triton_distributed_tpu.config import compiling_for_tpu

        if _streamable(rows // n, cols, x.dtype.itemsize) and \
                wirelib.wire_blockable(
                    rows // n, cols, wire, compiling_for_tpu()
                ):
            # activation-scale payloads: the HBM-streaming wire ring
            # (per-hop quant pipelines + scale rail, the fused gemm_rs
            # wire shape — the last bf16 leg of the standalone RS)
            x2d = x.reshape(((n,) if stacked else ()) + (rows, cols))
            fn = _build_rs_stream_w(
                mesh, axis, rows, cols, x.dtype, stacked, collective_id,
                interp_key(), wire, sched,
            )
            return fn(x2d).reshape(full_shape)
        _warn_rs_wire_once()
        wire = None
    if not _vmem_ring_fits(n, local_shape, x.dtype.itemsize) and _streamable(
        rows // n, cols, x.dtype.itemsize
    ):
        x2d = x.reshape(((n,) if stacked else ()) + (rows, cols))
        fn = _build_rs_stream(
            mesh, axis, rows, cols, x.dtype, stacked, collective_id,
            interp_key(), sched,
        )
        return fn(x2d).reshape(full_shape)
    fn = _build_reduce_scatter(
        mesh, axis, tuple(full_shape), x.dtype, stacked, collective_id,
        interp_key(),
    )
    return fn(x)


_rs_wire_warned = [False]


def _warn_rs_wire_once():
    if not _rs_wire_warned[0]:
        _rs_wire_warned[0] = True
        import logging

        logging.getLogger(__name__).warning(
            "reduce_scatter: payload exceeds the VMEM ring and admits "
            "no streaming wire blocking; shipping the bf16 wire"
        )


@functools.lru_cache(maxsize=256)
def _build_reduce_scatter_w(mesh, axis, full_shape, dtype, stacked,
                            collective_id, chaos, wire):
    """Quantized-wire VMEM reduce ring (2-D payloads; per-row scales)."""
    wirelib.require_inkernel(wire, "reduce_scatter")
    n = mesh.shape[axis]
    m_local = full_shape[0] // n
    cols = full_shape[1]
    wdt = jnp.dtype(
        jnp.float8_e4m3fn if wire == "fp8" else jnp.int8
    )
    call = lang.shmem_call(
        functools.partial(_ring_rs_kernel_w, n, axis, mesh.axis_names, wire),
        out_shape=jax.ShapeDtypeStruct((m_local, cols), dtype),
        in_specs=lang.vmem_specs(1),
        scratch_shapes=[
            pltpu.VMEM((m_local, cols), dtype),                   # acc
            pltpu.VMEM((m_local, cols), wdt),                     # qbuf
            pltpu.VMEM((m_local, wirelib.SCALE_LANES), jnp.float32),
            pltpu.VMEM((2, m_local, cols), wdt),                  # recv q
            pltpu.VMEM((2, m_local, wirelib.SCALE_LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),                        # scale rail
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        collective_id=collective_id,
        name=f"rs_ring_{wire}w",
    )
    call = lang.maybe_instrument(
        call, axis=axis, site="reduce_scatter", collective_id=collective_id,
        n=n,
    )
    body = (lambda s: call(s[0])) if stacked else call
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=P(axis) if stacked else P(None),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def _build_reduce_scatter(mesh, axis, full_shape, dtype, stacked, collective_id, chaos):
    n = mesh.shape[axis]
    m_local = full_shape[0] // n
    local_shape = (m_local,) + tuple(full_shape[1:])

    call = lang.shmem_call(
        functools.partial(_ring_rs_kernel, n, axis, mesh.axis_names),
        out_shape=jax.ShapeDtypeStruct(local_shape, dtype),
        in_specs=lang.vmem_specs(1),
        scratch_shapes=[
            pltpu.VMEM(local_shape, dtype),
            pltpu.VMEM((2,) + local_shape, dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        collective_id=collective_id,
        name="rs_ring",
    )
    call = lang.maybe_instrument(
        call, axis=axis, site="reduce_scatter", collective_id=collective_id,
        n=n,
    )
    body = (lambda s: call(s[0])) if stacked else call
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=P(axis) if stacked else P(None),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(fn)


def reduce_scatter_xla(x, mesh, axis: str = "x", *, stacked: bool = False,
                       wire_dtype=None):
    """lax.psum_scatter reference implementation (correctness baseline).

    ``wire_dtype`` ('fp8'/'int8'): a manual ppermute reduce ring whose
    hops carry per-row-quantized partials (lang.wire, chunk_rows=1) —
    the numerics twin of the Pallas wire ring, and a genuine byte saver
    on DCN where psum_scatter cannot compress."""
    wire = wirelib.normalize_wire(wire_dtype)
    assert wire != "auto", "resolve 'auto' at the reduce_scatter entry"
    n = mesh.shape[axis]
    full_shape = x.shape[1:] if stacked else x.shape
    rows = full_shape[0]
    cols = int(np.prod(full_shape[1:], dtype=np.int64)) if len(full_shape) > 1 else 1
    if wire is not None:
        fmt = wirelib.WireFormat(quant=wire, chunk_rows=1)
        m_local = rows // n

        def body(s):
            s = s[0] if stacked else s
            s2 = s.reshape(rows, cols)
            me = jax.lax.axis_index(axis)
            perm = [(i, (i - 1) % n) for i in range(n)]

            def stripe(i):
                return jax.lax.dynamic_slice(
                    s2, (i * m_local, 0), (m_local, cols)
                )

            def step(h, acc):
                q, sc = wirelib.quantize_slab(acc, fmt)
                q = jax.lax.ppermute(q, axis, perm=perm)
                sc = jax.lax.ppermute(sc, axis, perm=perm)
                arrived = wirelib.dequantize_slab(q, sc, fmt, jnp.float32)
                nxt = jax.lax.rem(me + 2 + h, n)
                return (arrived + stripe(nxt).astype(jnp.float32)).astype(
                    s.dtype
                )

            acc = stripe(jax.lax.rem(me + 1, n))
            acc = jax.lax.fori_loop(0, n - 1, step, acc)
            return acc.reshape((m_local,) + tuple(full_shape[1:]))
    else:
        def body(s):
            s = s[0] if stacked else s
            return jax.lax.psum_scatter(
                s, axis, scatter_dimension=0, tiled=True
            )

    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=P(axis) if stacked else P(None),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(fn)(x)
